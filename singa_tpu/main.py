"""CLI entry point — the reference `singa` binary's flag surface.

Reference: /root/reference/src/main.cc:13-18 — flags -procsID, -hostfile,
-cluster_conf, -model_conf.  The reference forks Server/Worker
personalities by process id (main.cc:49-55); on TPU there is no
parameter-server personality (gradient aggregation is a compiled psum),
so every process is a worker and -procsID/-hostfile map to
jax.distributed process coordinates for multi-host runs.

Usage:
    python -m singa_tpu.main -model_conf examples/mnist/conv.conf \
        -cluster_conf examples/mnist/cluster.conf [-procsID 0] [-hostfile h]

Serving (the inference tier, singa_tpu/serve/):
    python -m singa_tpu.main serve -model_conf lm.conf \
        --workspace ws [--port 8000] [--serve_spec 'buckets=4x16/8x32,...']
follows the trainer's checkpoints in the workspace (hot-reload) and
serves /generate, /predict, /stats, /metrics, /healthz over stdlib
HTTP.  With `cb=on` in the serve spec, /generate runs continuous
batching over a paged KV cache and streams tokens as produced when
the request body carries `"stream": true` (docs/SERVING.md).
`serve --fleet N` runs N pinned engine workers behind a
health-driven router with canary rollout/auto-rollback;
`serve --fleet_hostfile h` adopts already-running `serve --pinned`
processes as the fleet.

Closed-loop pipeline (docs/PIPELINE.md):
    python -m singa_tpu.main pipeline -model_conf lm.conf \
        --workspace ws --synthetic [--fleet 2] [--smoke 50]
runs the supervised trainer AND the serving fleet concurrently against
one workspace: every health-blessed checkpoint is canaried and
promoted to traffic within bounded lag, and a DIVERGED step is never
served by more than the canary.  All subcommands take `--obs on
[--obs_spec ...]` for the unified telemetry layer
(docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import argparse
import os
import sys

from . import obs
from .config import load_cluster_config, load_model_config
from .core.trainer import Trainer


def make_argparser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="singa_tpu",
        description="TPU-native SINGA-capability training runtime")
    # single-dash long flags, gflags style (main.cc:13-18)
    ap.add_argument("-model_conf", "--model_conf", required=True)
    ap.add_argument("-cluster_conf", "--cluster_conf", default=None)
    ap.add_argument("-procsID", "--procsID", type=int, default=0)
    ap.add_argument("-hostfile", "--hostfile", default=None)
    ap.add_argument("-v", type=int, default=0, help="verbosity (glog style)")
    # TPU-native extras
    ap.add_argument("--synthetic", action="store_true",
                    help="use a synthetic learnable dataset (no egress env)")
    ap.add_argument("--steps", type=int, default=None,
                    help="override ModelProto.train_steps")
    ap.add_argument("--batchsize", type=int, default=0,
                    help="override every data layer's batchsize")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--resume", action="store_true",
                    help="resume from latest checkpoint in the workspace")
    ap.add_argument("--max-restarts", "--max_restarts", type=int,
                    dest="max_restarts", default=0,
                    help="supervise the run: on a step/pipeline failure "
                         "restore the latest valid checkpoint, replay "
                         "data, and retry with backoff up to N times "
                         "(0 = unsupervised; see docs/FAULT_TOLERANCE.md)")
    ap.add_argument("--fault_spec", default=None,
                    help="deterministic fault injection: comma-separated "
                         "site@visit[:kind] entries, e.g. "
                         "'step.train@7:preempt,ckpt.save@1:torn' "
                         "(sites/kinds in singa_tpu/utils/faults.py)")
    ap.add_argument("--health", choices=("on", "off"), default="on",
                    help="numeric-health sentinel: device-side "
                         "loss/grad-norm/update-ratio probes fused into "
                         "the train step, host-side OK/SPIKE/NONFINITE/"
                         "DIVERGED classification, checkpoint verdicts, "
                         "and (under --max-restarts) divergence rescue "
                         "(see docs/FAULT_TOLERANCE.md)")
    ap.add_argument("--health_spec", default=None,
                    help="health thresholds + rescue policy: comma-"
                         "separated key=value entries over the "
                         "HealthSpec fields, e.g. 'grad_norm_max=1e4,"
                         "spike_mad=8,patience=3,blame_batches=1,"
                         "lr_backoff=0.5' "
                         "(singa_tpu/utils/health.py)")
    ap.add_argument("--workspace", default=None,
                    help="override ClusterProto.workspace")
    ap.add_argument("--scan_chunk", type=int, default=0,
                    help="run up to N steps per device dispatch (fused "
                         "lax.scan inner loop; cadence events still fire "
                         "at their exact steps)")
    ap.add_argument("--feeder", choices=("auto", "on", "off"),
                    default="auto",
                    help="overlapped host/device feed pipeline for the "
                         "chunked loop: a background thread stages the "
                         "next chunk (stack + sharded device_put) while "
                         "the current one trains (auto = on when "
                         "scan_chunk > 1 unless SINGA_TPU_FEEDER=0; "
                         "see docs/PERFORMANCE.md)")
    ap.add_argument("--feeder_depth", "--feeder-depth", type=int,
                    dest="feeder_depth", default=0,
                    help="staged chunks the feeder may run ahead "
                         "(0 = SINGA_TPU_FEEDER_DEPTH or 2)")
    ap.add_argument("--phase_profile", action="store_true",
                    help="measure the device fwd/bwd/update split once "
                         "(profiler trace) and report it at every "
                         "display interval (worker.h:91-114 parity)")
    _add_obs_flags(ap)
    return ap


def _add_obs_flags(ap: argparse.ArgumentParser) -> None:
    ap.add_argument("--obs", choices=("on", "off"), default="off",
                    help="unified telemetry: span tracing (Chrome "
                         "trace JSON, Perfetto-loadable), a metrics "
                         "registry, and a structured JSONL event log "
                         "(see docs/OBSERVABILITY.md); artifacts "
                         "default under <workspace>/obs/")
    ap.add_argument("--obs_spec", default=None,
                    help="telemetry config: comma-separated key=value "
                         "over the ObsSpec fields, e.g. "
                         "'trace=/tmp/t.json,events=/tmp/e.jsonl,"
                         "metrics_period_s=5,max_spans=100000,"
                         "trace_ring=65536,max_events_mb=64,"
                         "process=worker-0,sample=tail,"
                         "sample_slow_ms=250,flightrec=/tmp/fr' "
                         "(singa_tpu/obs/__init__.py)")


def _obs_enable(args, workspace=None) -> bool:
    """Arm the process-global telemetry session from --obs/--obs_spec.
    Bare `--obs on` defaults both artifacts under `<workspace>/obs/`
    (`./obs/` without a workspace).  Returns True when a session was
    installed — the caller owns the matching `obs.disable()`."""
    if getattr(args, "obs", "off") != "on":
        if getattr(args, "obs_spec", None):
            obs.get_logger("main")("warning: --obs_spec given with "
                                   "--obs off; telemetry stays "
                                   "disabled")
        return False
    spec = obs.ObsSpec.parse(getattr(args, "obs_spec", None))
    base = os.path.join(workspace or ".", "obs")
    if not spec.trace:
        spec.trace = os.path.join(base, "trace.json")
    if not spec.events:
        spec.events = os.path.join(base, "events.jsonl")
    if not spec.flightrec:
        # post-mortem flight recorder armed by default: triggered
        # dumps land next to the other obs artifacts
        spec.flightrec = os.path.join(base, "flightrec")
    obs.enable(spec)
    return True


def make_serve_argparser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="singa_tpu serve",
        description="TPU-native inference serving tier "
                    "(docs/SERVING.md): micro-batched request "
                    "scheduler over compiled bucket programs, with "
                    "checkpoint hot-reload")
    ap.add_argument("-model_conf", "--model_conf", required=True)
    ap.add_argument("--workspace", default=None,
                    help="checkpoint workspace to serve from and "
                         "hot-reload against (the trainer's "
                         "--workspace); omit to serve fresh-init "
                         "params (smoke/dev only)")
    ap.add_argument("--serve_spec", default=None,
                    help="serving config: comma-separated key=value "
                         "over the ServeSpec fields, buckets as "
                         "BxP '/' entries, e.g. 'buckets=1x16/4x32,"
                         "max_new_tokens=32,eos_id=2,"
                         "batch_window_s=0.005'; cb=on enables "
                         "continuous batching over the paged KV cache "
                         "(cb_slots, cb_block_len, cb_blocks, "
                         "cb_prompt_cap) with streaming POST "
                         "/generate (singa_tpu/serve/engine.py, "
                         "docs/SERVING.md)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8000,
                    help="HTTP port (0 = ephemeral)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--smoke", type=int, default=0, metavar="N",
                    help="serve N synthetic in-process requests, "
                         "print the stats snapshot as JSON, and exit "
                         "(no HTTP listener)")
    ap.add_argument("--fleet", type=int, default=0, metavar="N",
                    help="serving fleet: spawn N in-process pinned "
                         "engine workers behind a health-driven "
                         "router with canary rollout/auto-rollback "
                         "(docs/SERVING.md)")
    ap.add_argument("--fleet_hostfile", default=None,
                    help="adopt an already-running fleet instead of "
                         "spawning one: one engine host[:port] per "
                         "line (each a `serve --pinned` process); "
                         "mutually exclusive with --fleet")
    ap.add_argument("--fleet_spec", default=None,
                    help="router config: comma-separated key=value "
                         "over the RouterSpec fields, e.g. "
                         "'probe_period_s=0.25,quarantine_after=2,"
                         "readmit_base_s=0.25' "
                         "(singa_tpu/serve/router.py)")
    ap.add_argument("--rollout_spec", default=None,
                    help="rollout config: comma-separated key=value "
                         "over the RolloutSpec fields, e.g. "
                         "'window_s=2,min_requests=10,p95_ratio=3' "
                         "(singa_tpu/serve/fleet.py)")
    ap.add_argument("--autoscale_spec", default=None,
                    help="enable the SLO-driven autoscaler over the "
                         "fleet: comma-separated key=value over the "
                         "AutoScaleSpec fields, e.g. 'slo_p95_ms=200,"
                         "max_shed_rate=0.02,min_engines=1,"
                         "max_engines=4,cooldown_s=5,window_s=10' "
                         "(singa_tpu/serve/autoscale.py; needs "
                         "--fleet, not --fleet_hostfile)")
    ap.add_argument("--tenant_spec", default=None,
                    help="multi-tenant QoS envelopes: ';'-separated "
                         "tenants, each 'name,key=value,...' over the "
                         "TenantSpec fields, e.g. 'a,queue_frac=0.25,"
                         "budget_floor=4;b,queue_frac=0.5' — quotas, "
                         "retry-budget floors, and brownout overrides "
                         "enforced per tenant at admission "
                         "(singa_tpu/serve/tenancy.py, "
                         "docs/SERVING.md); unnamed clients ride the "
                         "unquota'd `default` tenant")
    ap.add_argument("--pinned", action="store_true",
                    help="run this engine as a fleet member: never "
                         "self-reload; only the rollout controller's "
                         "POST /admin/reload moves the served params")
    ap.add_argument("--standby", action="store_true",
                    help="start the fleet router as a warm STANDBY "
                         "over the same --workspace as the primary: "
                         "engines load and warm but the data plane "
                         "stays 503 until POST /admin/promote claims "
                         "the next epoch, fences the primary's "
                         "session WAL, and replays it "
                         "(docs/SERVING.md, control-plane "
                         "durability; needs --fleet/--fleet_hostfile)")
    ap.add_argument("--wire", action="store_true",
                    help="start the zero-copy binary framed listener "
                         "beside the HTTP frontend (ephemeral port "
                         "unless --wire_port): /healthz advertises "
                         "it and transport=auto fleet routers "
                         "upgrade this engine's data plane to it, "
                         "falling back to HTTP on any wire failure "
                         "(singa_tpu/serve/wire.py, docs/SERVING.md)")
    ap.add_argument("--wire_port", type=int, default=0,
                    help="binary transport port (0 = ephemeral; "
                         "implies --wire when nonzero)")
    ap.add_argument("--transport", default="auto",
                    choices=("auto", "http"),
                    help="fleet data plane for adopted (hostfile) "
                         "engines: auto = negotiate binary per "
                         "engine via /healthz wire_port with "
                         "automatic HTTP fallback; http = pin the "
                         "debug surface (singa_tpu/serve/wire.py)")
    ap.add_argument("--fault_spec", default=None,
                    help="deterministic fault injection over the "
                         "serve.* and fleet.* sites "
                         "(singa_tpu/utils/faults.py)")
    _add_obs_flags(ap)
    return ap


def serve_main(argv) -> int:
    """The `serve` subcommand: build the inference net from the model
    config, load the latest healthy checkpoint, and serve."""
    import json as _json

    args = make_serve_argparser().parse_args(argv)
    if args.fleet and args.fleet_hostfile:
        print("error: --fleet and --fleet_hostfile are mutually "
              "exclusive (spawn a fleet OR adopt one)",
              file=sys.stderr)
        return 2
    if args.standby and not (args.fleet or args.fleet_hostfile):
        print("error: --standby is a fleet-router mode (needs "
              "--fleet or --fleet_hostfile)", file=sys.stderr)
        return 2
    from .utils.faults import FaultSchedule, inject
    schedule = (FaultSchedule.parse(args.fault_spec, seed=args.seed)
                if args.fault_spec else None)
    log = obs.get_logger("serve")
    obs_on = _obs_enable(args, args.workspace)
    try:
        model = load_model_config(args.model_conf)
        from .data import discover_input_shapes
        input_shapes = discover_input_shapes(model, force_synthetic=True)
        trainer = Trainer(model, input_shapes, log_fn=lambda s: None)
        # the inference net: test phase when the config defines one,
        # else the train net (same params either way)
        net = trainer.test_net or trainer.train_net

        import jax

        from .serve import InferenceEngine, InferenceServer, ServeSpec
        spec = (ServeSpec.parse(args.serve_spec) if args.serve_spec
                else ServeSpec())
        # fresh-init fallback so a checkpoint-less workspace still
        # serves (engine.load prefers any restorable healthy snapshot)
        fallback = net.init_params(jax.random.PRNGKey(args.seed))
        if args.fleet or args.fleet_hostfile:
            return _fleet_main(args, net, spec, fallback, schedule,
                               log)
        engine = InferenceEngine(net, spec, workspace=args.workspace,
                                 params=fallback, log_fn=log,
                                 pinned=args.pinned)
        reg = obs.registry()
        if reg is not None:
            engine.stats.register_into(reg)
        from .serve import TenantRegistry
        tenancy = (TenantRegistry.parse(args.tenant_spec)
                   if args.tenant_spec else None)

        with inject(schedule):
            if schedule is not None:
                log(f"fault injection active: {args.fault_spec} "
                    f"(seed {args.seed})")
            server = InferenceServer(engine, host=args.host,
                                     port=args.port,
                                     http=(args.smoke == 0),
                                     tenancy=tenancy, log_fn=log,
                                     wire_on=(args.smoke == 0
                                              and (args.wire
                                                   or args.wire_port
                                                   > 0)),
                                     wire_port=args.wire_port)
            server.start()
            if engine.params_step < 0:
                log("warning: serving fresh-init params (no "
                    "restorable checkpoint in the workspace)")
            try:
                if args.smoke > 0:
                    import numpy as np
                    rng = np.random.default_rng(args.seed)
                    vocab = _serve_vocab(net)
                    cap = (spec.cb_max_prompt_len if spec.cb_on
                           else spec.max_prompt_len)
                    for i in range(args.smoke):
                        plen = int(rng.integers(1, cap + 1))
                        prompt = rng.integers(0, vocab,
                                              plen).astype("int32")
                        out = server.generate(prompt)
                        shape = (f"finish {out['finish']}"
                                 if "finish" in out
                                 else f"bucket {out.get('bucket')}")
                        log(f"smoke {i}: plen={plen} -> "
                            f"{len(out['tokens'])} tokens "
                            f"(step {out['step']}, {shape})")
                    print(_json.dumps(server.snapshot()))
                    return 0
                import time
                while True:
                    time.sleep(3600)
            except KeyboardInterrupt:
                log("serve: shutting down")
                print(_json.dumps(server.snapshot()))
                return 0
            finally:
                server.stop()
    finally:
        if obs_on:
            obs.disable()


def _fleet_main(args, net, spec, fallback, schedule, log) -> int:
    """The fleet branch of `serve`: N pinned engine workers behind a
    `Router` + `RolloutController`, fronted by `FleetServer` (or
    driven in-process under --smoke)."""
    import json as _json

    from .serve import (AutoScaler, AutoScaleSpec, EngineFleet,
                        FleetServer, RolloutSpec, RouterSpec,
                        TenantRegistry)
    from .utils.faults import inject

    router_spec = RouterSpec.parse(args.fleet_spec)
    rollout_spec = RolloutSpec.parse(args.rollout_spec)
    autoscale_spec = (AutoScaleSpec.parse(args.autoscale_spec)
                      if args.autoscale_spec is not None else None)
    tenancy = (TenantRegistry.parse(args.tenant_spec)
               if args.tenant_spec else None)
    if args.pinned:
        log("warning: --pinned is a member flag; the fleet's workers "
            "are always pinned — ignoring")
    with inject(schedule):
        if schedule is not None:
            log(f"fault injection active: {args.fault_spec} "
                f"(seed {args.seed})")
        if args.fleet_hostfile:
            fleet = EngineFleet.from_hostfile(
                args.fleet_hostfile, workspace=args.workspace,
                router_spec=router_spec, rollout_spec=rollout_spec,
                tenancy=tenancy, standby=args.standby, log_fn=log,
                transport=args.transport)
        else:
            fleet = EngineFleet.local(
                net, spec, args.fleet, workspace=args.workspace,
                params=fallback, router_spec=router_spec,
                rollout_spec=rollout_spec, tenancy=tenancy,
                standby=args.standby, log_fn=log)
        scaler = None
        if autoscale_spec is not None and args.standby:
            log("warning: --autoscale_spec ignored on a standby "
                "router (no traffic signal to scale on until "
                "promote)")
        elif autoscale_spec is not None:
            if not fleet.can_grow():
                log("warning: --autoscale_spec on an adopted "
                    "(hostfile) fleet can only scale DOWN — spawning "
                    "remote workers is deployment's job")
            scaler = AutoScaler(fleet, spec=autoscale_spec, log_fn=log)
            # cooldown/streak survive a router restart: without this a
            # crash forgets the flap damping and can oscillate
            fleet.add_state_provider("autoscale", scaler.export_state,
                                     scaler.restore_state)
        reg = obs.registry()
        if reg is not None:
            fleet.router.stats.register_into(reg)
            if scaler is not None:
                scaler.register_into(reg)
        fleet.start()
        if scaler is not None:
            scaler.start()
        try:
            if args.smoke > 0:
                import numpy as np
                rng = np.random.default_rng(args.seed)
                vocab = _serve_vocab(net)
                for i in range(args.smoke):
                    plen = int(rng.integers(1, spec.max_prompt_len + 1))
                    prompt = rng.integers(0, vocab,
                                          plen).astype("int32")
                    out = fleet.generate(prompt)
                    log(f"smoke {i}: plen={plen} -> "
                        f"{len(out['tokens'])} tokens on "
                        f"{out['engine']} (step {out['step']})")
                snap = fleet.snapshot()
                if scaler is not None:
                    snap["autoscale"] = scaler.snapshot()
                print(_json.dumps(snap))
                return 0
            front = FleetServer(fleet, host=args.host, port=args.port,
                                log_fn=log)
            front.start()
            try:
                import time
                while True:
                    time.sleep(3600)
            except KeyboardInterrupt:
                log("fleet: shutting down")
                print(_json.dumps(fleet.snapshot()))
                return 0
            finally:
                front.stop()
        finally:
            if scaler is not None:
                scaler.stop()
            fleet.stop()


def _serve_vocab(net) -> int:
    for layer in net.layers.values():
        for attr in ("vocab_size", "vocab"):
            v = getattr(layer, attr, None)
            if isinstance(v, int) and v > 1:
                return v
    return 256


def make_pipeline_argparser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="singa_tpu pipeline",
        description="closed-loop train-and-serve (docs/PIPELINE.md): "
                    "a supervised trainer and a serving fleet run "
                    "concurrently against ONE workspace — every "
                    "health-blessed checkpoint is canaried and "
                    "promoted to traffic within bounded lag, and a "
                    "DIVERGED step is never served by more than the "
                    "canary")
    ap.add_argument("-model_conf", "--model_conf", required=True)
    ap.add_argument("--workspace", required=True,
                    help="the shared checkpoint workspace — the "
                         "trainer publishes into it, the fleet "
                         "promotes out of it")
    ap.add_argument("--steps", type=int, default=None,
                    help="override ModelProto.train_steps")
    ap.add_argument("--batchsize", type=int, default=0,
                    help="override every data layer's batchsize")
    ap.add_argument("--synthetic", action="store_true",
                    help="use a synthetic learnable dataset")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--resume", action="store_true",
                    help="resume training from the workspace's latest "
                         "healthy checkpoint")
    ap.add_argument("--max-restarts", "--max_restarts", type=int,
                    dest="max_restarts", default=3,
                    help="trainer supervision budget (pipeline mode "
                         "is always supervised; default 3)")
    ap.add_argument("--scan_chunk", type=int, default=0)
    ap.add_argument("--health", choices=("on", "off"), default="on",
                    help="numeric-health sentinel on the trainer — "
                         "checkpoint verdicts are what bless a step "
                         "for promotion (docs/FAULT_TOLERANCE.md)")
    ap.add_argument("--health_spec", default=None)
    ap.add_argument("--fault_spec", default=None,
                    help="deterministic fault injection across BOTH "
                         "halves (train + serve sites, plus "
                         "pipeline.publish; singa_tpu/utils/faults.py)")
    ap.add_argument("--serve_spec", default=None,
                    help="ServeSpec for the fleet's engines")
    ap.add_argument("--fleet", type=int, default=2, metavar="N",
                    help="serving fleet size (default 2: one canary, "
                         "one stable)")
    ap.add_argument("--fleet_spec", default=None,
                    help="RouterSpec key=value entries")
    ap.add_argument("--rollout_spec", default=None,
                    help="RolloutSpec key=value entries (poll_s "
                         "bounds the fingerprint-poll half of the "
                         "blessed-to-served lag)")
    ap.add_argument("--pipeline_spec", default=None,
                    help="PipelineSpec key=value entries, e.g. "
                         "'lag_alarm_s=10,join_s=600' "
                         "(singa_tpu/core/pipeline.py)")
    ap.add_argument("--autoscale_spec", default=None,
                    help="enable the SLO-driven autoscaler over the "
                         "pipeline's fleet (AutoScaleSpec key=value "
                         "entries; the blessed-to-served lag joins "
                         "its pressure signals)")
    ap.add_argument("--smoke", type=int, default=0, metavar="N",
                    help="drive >= N in-process client requests while "
                         "training runs, wait for the loop to drain "
                         "(blessed == served), print the pipeline "
                         "snapshot as JSON, and exit (no HTTP)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8000,
                    help="FleetServer HTTP port (0 = ephemeral)")
    _add_obs_flags(ap)
    return ap


def pipeline_main(argv) -> int:
    """The `pipeline` subcommand: trainer + fleet, one workspace, the
    `PipelineController` owning the seam."""
    import json as _json
    import time as _time

    args = make_pipeline_argparser().parse_args(argv)
    from .utils.faults import FaultSchedule, inject
    schedule = (FaultSchedule.parse(args.fault_spec, seed=args.seed)
                if args.fault_spec else None)
    log = obs.get_logger("pipeline")
    obs_on = _obs_enable(args, args.workspace)
    try:
        model = load_model_config(args.model_conf)
        if args.steps is not None:
            model.train_steps = args.steps
        from .data import discover_input_shapes, resolve_data_source
        if args.batchsize:
            for layer in (model.neuralnet.layer
                          if model.neuralnet else []):
                if layer.data_param:
                    layer.data_param.batchsize = args.batchsize
                if layer.seqdata_param:
                    layer.seqdata_param.batchsize = args.batchsize
        input_shapes = discover_input_shapes(
            model, force_synthetic=args.synthetic)

        from .utils.health import HealthMonitor, HealthSpec
        health_spec = HealthSpec.parse(args.health_spec)
        health = (HealthMonitor(health_spec,
                                log_fn=obs.get_logger("health"))
                  if args.health == "on" else None)
        if health is None:
            log("warning: --health off means every checkpoint "
                "publishes unclassified — only the canary gate "
                "stands between a diverged step and traffic")

        trainer = Trainer(model, input_shapes, health=health)
        reg = obs.registry()
        if reg is not None:
            trainer.timer.register_into(reg)
            if health is not None:
                health.register_into(reg)

        from .core.pipeline import PipelineController, PipelineSpec
        from .core.supervisor import Supervisor, TrainingAborted
        sup = Supervisor(trainer, args.workspace,
                         max_restarts=max(args.max_restarts, 1),
                         max_divergences=health_spec.max_divergences,
                         blame_batches=health_spec.blame_batches,
                         lr_backoff=health_spec.lr_backoff,
                         log=obs.get_logger("supervisor"))

        train_layer = next(
            (l for l in model.neuralnet.layer
             if l.type in ("kShardData", "kLMDBData", "kSequenceData")
             and "kTrain" not in l.exclude),
            None)
        if train_layer is None:
            bs = 64
        elif train_layer.type == "kSequenceData":
            bs = (train_layer.seqdata_param.batchsize
                  if train_layer.seqdata_param else 64)
        else:
            bs = train_layer.data_param.batchsize

        def make_train_iter():
            it, _ = resolve_data_source(
                model, bs, seed=args.seed,
                force_synthetic=args.synthetic,
                sample_shapes=input_shapes)
            return it

        import jax

        from .serve import (AutoScaleSpec, EngineFleet, FleetServer,
                            RolloutSpec, RouterSpec, ServeSpec)
        spec = (ServeSpec.parse(args.serve_spec) if args.serve_spec
                else ServeSpec())
        net = trainer.test_net or trainer.train_net
        fallback = net.init_params(jax.random.PRNGKey(args.seed))
        fleet = EngineFleet.local(
            net, spec, args.fleet, workspace=args.workspace,
            params=fallback, router_spec=RouterSpec.parse(args.fleet_spec),
            rollout_spec=RolloutSpec.parse(args.rollout_spec),
            log_fn=obs.get_logger("fleet"))
        ctl = PipelineController(
            sup, fleet, args.workspace,
            spec=PipelineSpec.parse(args.pipeline_spec),
            autoscale_spec=(AutoScaleSpec.parse(args.autoscale_spec)
                            if args.autoscale_spec is not None
                            else None),
            log_fn=log)
        if reg is not None:
            fleet.router.stats.register_into(reg)
            ctl.register_into(reg)

        with inject(schedule):
            if schedule is not None:
                log(f"fault injection active: {args.fault_spec} "
                    f"(seed {args.seed})")
            ctl.start(make_train_iter, seed=args.seed,
                      scan_chunk=args.scan_chunk, resume=args.resume)
            try:
                if args.smoke > 0:
                    rc = _pipeline_smoke(ctl, net, args, log)
                    print(_json.dumps(ctl.snapshot()))
                    return rc
                front = FleetServer(fleet, host=args.host,
                                    port=args.port, log_fn=log)
                ctl.register_into(front.metrics)
                front.start()
                try:
                    while not ctl.wait(timeout=1.0):
                        pass
                    if isinstance(ctl.train_error, TrainingAborted):
                        log(f"error: {ctl.train_error}")
                    log("pipeline: training finished; fleet keeps "
                        "serving (Ctrl-C to stop)")
                    while True:
                        _time.sleep(3600)
                except KeyboardInterrupt:
                    log("pipeline: shutting down")
                    print(_json.dumps(ctl.snapshot()))
                    return 0
                finally:
                    front.stop()
            finally:
                ctl.stop()
    finally:
        if obs_on:
            obs.disable()


def _pipeline_smoke(ctl, net, args, log) -> int:
    """In-process client loop for `pipeline --smoke N`: keep requests
    flowing while training runs, then wait for the loop to drain
    (every blessed step promoted).  Exit 0 only when training
    finished, no client request failed, and blessed == served."""
    import time as _time

    import numpy as np

    rng = np.random.default_rng(args.seed)
    vocab = _serve_vocab(net)
    sent = failed = 0
    drain_deadline = None
    while True:
        train_done = not ctl.train_running()
        lag = ctl.lag()
        if train_done and drain_deadline is None:
            # bounded drain: give the rollout a few alarm windows to
            # promote the tail, then report whatever lag remains
            drain_deadline = _time.monotonic() + \
                3 * float(ctl.spec.lag_alarm_s)
        drained = lag["lag_steps"] == 0
        if train_done and sent >= args.smoke and \
                (drained or ctl.train_error is not None
                 or _time.monotonic() >= drain_deadline):
            break
        plen = int(rng.integers(1, 9))
        prompt = rng.integers(0, vocab, plen).astype("int32")
        try:
            out = ctl.generate(prompt)
            sent += 1
            if sent % 25 == 0 or sent == 1:
                log(f"smoke {sent}: step {out['step']} on "
                    f"{out['engine']} (blessed "
                    f"{lag['blessed_step']}, served "
                    f"{lag['served_step']})")
        except Exception as e:  # noqa: BLE001 — a failure is the verdict
            failed += 1
            log(f"warning: smoke request failed "
                f"({type(e).__name__}: {e})")
            _time.sleep(0.05)
    lag = ctl.lag()
    ok = (ctl.train_error is None and failed == 0
          and lag["lag_steps"] == 0)
    log(f"pipeline smoke: {sent} requests ({failed} failed), "
        f"blessed {lag['blessed_step']} served {lag['served_step']}"
        + ("" if ctl.train_error is None
           else f", training FAILED: {ctl.train_error!r}"))
    return 0 if ok else 1


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "serve":
        return serve_main(argv[1:])
    if argv and argv[0] == "pipeline":
        return pipeline_main(argv[1:])
    args = make_argparser().parse_args(argv)
    from .utils.faults import FaultSchedule, inject
    schedule = (FaultSchedule.parse(args.fault_spec, seed=args.seed)
                if args.fault_spec else None)
    obs_on = _obs_enable(args, args.workspace)
    try:
        if schedule is not None:
            obs.get_logger("main")(
                f"fault injection active: {args.fault_spec} "
                f"(seed {args.seed})")
        with inject(schedule):
            return _run(args)
    finally:
        if obs_on:
            obs.disable()


def _run(args) -> int:
    log = obs.get_logger("main")
    model = load_model_config(args.model_conf)
    cluster = (load_cluster_config(args.cluster_conf)
               if args.cluster_conf else None)

    # Multi-host bootstrap BEFORE any jax device query: -procsID/-hostfile
    # are the reference's launch coordinates (run.sh:20-37); here they
    # seed jax.distributed so jax.devices() spans every host.
    if args.hostfile:
        from .parallel.bootstrap import DEFAULT_PORT, distributed_init
        port = cluster.start_port if cluster else DEFAULT_PORT
        if distributed_init(args.procsID, args.hostfile, port=port):
            log(f"jax.distributed initialized: process {args.procsID}")
    if args.steps is not None:
        model.train_steps = args.steps

    # data-layer discovery: real sources are peeked for their true
    # record geometry, synthetic mode infers it from the parser configs
    # (the reference's Setup-reads-a-record contract, layer.cc:388-392)
    from .data import discover_input_shapes
    if args.batchsize:
        for layer in (model.neuralnet.layer if model.neuralnet else []):
            if layer.data_param:
                layer.data_param.batchsize = args.batchsize
            if layer.seqdata_param:
                layer.seqdata_param.batchsize = args.batchsize
    input_shapes = discover_input_shapes(
        model, force_synthetic=args.synthetic)

    # Mesh from the cluster config: engages DP/TP/SP/EP shardings when
    # more than one device is visible (ClusterProto topology → Mesh,
    # the reference's Cluster singleton role, cluster.h:20-121).
    import jax
    mesh = None
    if cluster is not None and len(jax.devices()) > 1:
        from .parallel import mesh_from_cluster
        ptype = model.neuralnet.partition_type if model.neuralnet else "kNone"
        mesh = mesh_from_cluster(cluster, ptype)
        log(f"mesh: {dict(mesh.shape)} over {len(jax.devices())} devices")

    # worker-group topology (cluster.h:49-60): nworkers/nprocs_per_group
    # data-parallel groups; with the async consistency tier active each
    # group is a replica against the shared center (ReplicaSet below)
    ngroups = 1
    if cluster is not None and not cluster.synchronous:
        ngroups = max(cluster.nworkers
                      // max(cluster.nprocs_per_group, 1), 1)

    # numeric-health sentinel: probes compile into the train step only
    # when armed; --health off restores the exact pre-health program
    from .utils.health import HealthMonitor, HealthSpec
    health_spec = HealthSpec.parse(args.health_spec)
    health = (HealthMonitor(health_spec,
                            log_fn=obs.get_logger("health"))
              if args.health == "on" else None)
    if args.health == "off" and args.health_spec:
        log("warning: --health_spec given with --health off; the "
            "monitor is disabled and the spec only configures the "
            "supervisor's divergence policy")

    trainer = Trainer(model, input_shapes, mesh=mesh,
                      n_micro=(cluster.pipeline_microbatches
                               if cluster else 0),
                      ngroups=ngroups, health=health)
    trainer.phase_profile = args.phase_profile
    # additive metric collectors (no-op without --obs on): the per-phase
    # timer and the health-verdict tallies feed the periodic dump
    reg = obs.registry()
    if reg is not None:
        trainer.timer.register_into(reg)
        if health is not None:
            health.register_into(reg)

    from .parallel.elastic import async_active
    async_multi = ngroups > 1 and async_active(model.updater)

    workspace = args.workspace or (cluster.workspace if cluster else None)
    # an explicit --workspace is a request to checkpoint: default to a
    # final snapshot when the config doesn't set a cadence
    if args.workspace and model.checkpoint_frequency == 0:
        model.checkpoint_frequency = max(model.train_steps, 1)
    train_layer = next(
        (l for l in model.neuralnet.layer
         if l.type in ("kShardData", "kLMDBData", "kSequenceData")
         and "kTrain" not in l.exclude),
        None)
    if train_layer is None:
        bs = 64
    elif train_layer.type == "kSequenceData":
        bs = (train_layer.seqdata_param.batchsize
              if train_layer.seqdata_param else 64)
    else:
        bs = train_layer.data_param.batchsize

    # Data source: shard files if the configured path exists locally,
    # else the synthetic source (reference configs point at dead hosts).
    from .data import resolve_data_source

    if async_multi:
        # multi-group async tier: each group trains its own replica and
        # exchanges with the shared center at the UpdaterProto cadence.
        # Branches BEFORE single-group state (init/sharding/prefetch)
        # is built — none of it is used on this path.
        from .parallel.elastic import ReplicaSet
        for flag, what in ((args.resume, "--resume"),
                           (workspace, "checkpointing (workspace)"),
                           (mesh is not None, "mesh sharding")):
            if flag:
                log(f"warning: {what} is not supported on the "
                    f"multi-group async simulation path; ignoring")
        log(f"async replica groups: {ngroups} x "
            f"{model.updater.param_type}")
        # ClusterProto.bandwidth/nservers drive the runtime SyncConfig
        # (param_manager.cc:85-93): after warmup the RandomSync sample
        # ratio adapts to the configured pipe
        rs = ReplicaSet(trainer, ngroups, seed=args.seed,
                        bandwidth_mb_s=(cluster.bandwidth
                                        if cluster else 0.0),
                        nservers=(cluster.nservers or 1
                                  if cluster else 1))
        # same task (seed), a distinct sample stream per replica
        iters = [resolve_data_source(
                     model, bs, seed=args.seed,
                     stream_seed=args.seed + 1000 * (g + 1),
                     force_synthetic=args.synthetic,
                     sample_shapes=input_shapes)[0]
                 for g in range(ngroups)]
        center, history = rs.run(iters, model.train_steps,
                                 seed=args.seed)
        last = history[0][-1] if history and history[0] else {}
        log(f"training done (center of {ngroups} replicas)" +
            (": " + ", ".join(f"{k} : {v:.6f}"
                              for k, v in sorted(last.items()))
             if last else ""))
        test_factory = resolve_data_source(
            model, bs, seed=args.seed,
            force_synthetic=args.synthetic,
            sample_shapes=input_shapes)[1]
        if trainer.test_step is not None and test_factory is not None \
                and center is not None and model.test_steps > 0:
            avg = trainer.evaluate(center, test_factory(),
                                   model.test_steps, trainer.test_step)
            log("center test: " + ", ".join(
                f"{k} : {v:.6f}" for k, v in sorted(avg.items())))
        return 0

    # Batch placement (sharded device_put under the mesh) is the
    # trainer's job now — _batch_place/_chunk_place inside run() and
    # evaluate() — so iterators stay HOST-side and the feed pipeline
    # can stage them into reusable buffers without a device round-trip.
    def make_train_iter():
        it, _ = resolve_data_source(
            model, bs, seed=args.seed, force_synthetic=args.synthetic,
            sample_shapes=input_shapes)
        return it

    _, test_factory = resolve_data_source(
        model, bs, seed=args.seed, force_synthetic=args.synthetic,
        sample_shapes=input_shapes)

    if args.resume and not workspace:
        log("warning: --resume given but no workspace configured "
            "(set --workspace or ClusterProto.workspace); "
            "starting from scratch")

    # auto → None: Trainer.run resolves via SINGA_TPU_FEEDER (default on
    # for chunked loops)
    feeder_flag = {"auto": None, "on": True, "off": False}[args.feeder]
    if args.feeder == "on" and args.scan_chunk <= 1:
        log("warning: --feeder on has no effect without "
            "--scan_chunk > 1 (the feeder stages whole scan chunks)")

    if args.max_restarts > 0:
        # supervised runtime: restore-the-last-valid-snapshot + replay
        # on failure, the recovery loop the reference left as a TODO
        # (Worker::Resume, worker.cc:65-67)
        from .core.supervisor import Supervisor, TrainingAborted
        sup = Supervisor(trainer, workspace,
                         max_restarts=args.max_restarts,
                         max_divergences=health_spec.max_divergences,
                         blame_batches=health_spec.blame_batches,
                         lr_backoff=health_spec.lr_backoff,
                         log=obs.get_logger("supervisor"))
        try:
            params, opt_state, history = sup.run(
                make_train_iter, test_iter_factory=test_factory,
                seed=args.seed, scan_chunk=args.scan_chunk,
                resume=args.resume, feeder=feeder_flag,
                feeder_depth=args.feeder_depth)
        except TrainingAborted as e:
            log(f"error: {e}")
            return 1
    else:
        params, opt_state = trainer.init(seed=args.seed)
        if mesh is not None:
            from .parallel import shard_opt_state, shard_params
            params = shard_params(mesh, trainer.train_net, params)
            opt_state = shard_opt_state(mesh, trainer.train_net,
                                        opt_state)
        start_step = 0
        if args.resume and workspace:
            params, opt_state, start_step = trainer.resume(
                params, opt_state, workspace)
            if start_step > 0:
                log(f"resumed from step {start_step}")
            else:
                log(f"no checkpoint found in {workspace}; "
                    "starting from scratch")
        params, opt_state, history = trainer.run(
            params, opt_state, make_train_iter(),
            test_iter_factory=test_factory,
            seed=args.seed, start_step=start_step, workspace=workspace,
            scan_chunk=args.scan_chunk, feeder=feeder_flag,
            feeder_depth=args.feeder_depth)
    final = trainer.perf.to_string()
    log("training done" + (f": {final}" if final else
                           f" at step {model.train_steps}"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
