"""Net-structure and training-curve visualization — the role of the
reference's script/graph.py (net JSON → graph image) and script/draw.py
(performance-log → curves).

The net builder already emits the reference's node-link JSON
(graph.cc:4-59 format, NeuralNet.to_json / Graph.to_json); this module
turns that into Graphviz dot text (renderable anywhere) and plots metric
curves from either Trainer history dicts or training log text.

Usage:
  python -m singa_tpu.tools.viz dot  <net.json> [out.dot]
  python -m singa_tpu.tools.viz plot <train.log> [out.png]
"""

from __future__ import annotations

import json
import re
import sys
from typing import Dict, List, Optional


def json_to_dot(net_json: str, name: str = "net") -> str:
    """Node-link JSON → Graphviz dot.  Data layers get box shapes, loss
    layers doubleoctagons, everything else ellipses."""
    doc = json.loads(net_json)
    nodes = doc.get("nodes", [])
    links = doc.get("links", [])
    lines = [f'digraph "{name}" {{', "  rankdir=TB;"]
    for nd in nodes:
        nid = nd["id"]
        typ = nd.get("type", "")
        shape = ("box" if "Data" in typ or "Image" in typ or typ == "kLabel"
                 else "doubleoctagon" if "Loss" in typ else "ellipse")
        label = nid if not typ else f"{nid}\\n{typ}"
        lines.append(f'  "{nid}" [shape={shape}, label="{label}"];')
    for ln in links:
        s = nodes[ln["source"]]["id"]
        d = nodes[ln["target"]]["id"]
        lines.append(f'  "{s}" -> "{d}";')
    lines.append("}")
    return "\n".join(lines) + "\n"


# "step-120: loss : 0.523411, precision : 0.843750" (Performance.to_string)
_LOG_RE = re.compile(r"step-(\d+)(?: (validation|test))?: (.*)")


def parse_training_log(text: str) -> Dict[str, Dict[str, List]]:
    """Parse Trainer log lines into {series: {"step": [...], metric:
    [...]}} with series ∈ {train, test, validation}."""
    out: Dict[str, Dict[str, List]] = {}
    for line in text.splitlines():
        m = _LOG_RE.match(line.strip())
        if not m:
            continue
        step, phase, rest = int(m.group(1)), m.group(2) or "train", m.group(3)
        series = out.setdefault(phase, {"step": []})
        series["step"].append(step)
        for part in rest.split(","):
            if ":" not in part:
                continue
            k, v = part.split(":", 1)
            try:
                series.setdefault(k.strip(), []).append(float(v))
            except ValueError:
                pass
    return out


def plot_training_log(text: str, out_path: str) -> List[str]:
    """Render loss/metric curves from a training log (draw.py role).
    Returns the metric names plotted."""
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    series = parse_training_log(text)
    metrics = sorted({k for s in series.values() for k in s if k != "step"})
    if not metrics:
        raise ValueError("no Performance lines found in log")
    fig, axes = plt.subplots(1, len(metrics),
                             figsize=(5 * len(metrics), 3.6))
    if len(metrics) == 1:
        axes = [axes]
    for ax, metric in zip(axes, metrics):
        for phase, s in sorted(series.items()):
            if metric in s:
                n = min(len(s["step"]), len(s[metric]))
                ax.plot(s["step"][:n], s[metric][:n], label=phase)
        ax.set_xlabel("step")
        ax.set_ylabel(metric)
        ax.legend()
    fig.tight_layout()
    fig.savefig(out_path, dpi=110)
    plt.close(fig)
    return metrics


def main(argv: Optional[List[str]] = None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) >= 2 and argv[0] == "dot":
        with open(argv[1]) as f:
            dot = json_to_dot(f.read())
        if len(argv) > 2:
            with open(argv[2], "w") as f:
                f.write(dot)
            print(f"wrote {argv[2]}")
        else:
            print(dot)
    elif len(argv) >= 2 and argv[0] == "plot":
        out = argv[2] if len(argv) > 2 else "training.png"
        with open(argv[1]) as f:
            metrics = plot_training_log(f.read(), out)
        print(f"plotted {metrics} to {out}")
    else:
        print(__doc__)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
