"""Emit the example .conf files from the programmatic model zoo.

The reference ships hand-written text-proto configs under
examples/mnist/ (mlp.conf, conv.conf — reference examples/mnist/); here
the same configs are *generated* from `singa_tpu.models.vision` so the
zoo and the on-disk examples can never drift.  Run after changing the
zoo:

    python -m singa_tpu.tools.export_examples [--outdir examples]
"""

from __future__ import annotations

import argparse
import os

from singa_tpu.config import model_config_to_text
from singa_tpu.models import rbm, vision


EXAMPLES = {
    "mnist/mlp.conf": lambda: vision.mlp_mnist(),
    "mnist/conv.conf": lambda: vision.lenet_mnist(),
    "mnist/rbm.conf": lambda: rbm.rbm_mnist(),
    "cifar10/quick.conf": lambda: vision.alexnet_cifar10(),
    "cifar10/alexnet.conf": lambda: vision.alexnet_cifar10_full(),
    "imagenet/alexnet.conf": lambda: vision.alexnet_imagenet(),
}


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--outdir", default="examples")
    args = ap.parse_args(argv)
    for rel, build in EXAMPLES.items():
        path = os.path.join(args.outdir, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(model_config_to_text(build()))
        print(f"wrote {path}")


if __name__ == "__main__":
    main()
