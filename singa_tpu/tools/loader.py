"""Data loader tool — the reference's `loader` binary
(/root/reference/tools/data_loader/data_loader.cc).

Modes (same surface):
  create: convert MNIST idx files or a CIFAR-10 binary folder into a
          Shard of Record protos (data_loader.cc:112-145)
  split:  re-partition a shard into N sub-shards (Split/SplitN,
          data_loader.cc:43-94)

Usage:
  python -m singa_tpu.tools.loader create mnist  <images.idx> <labels.idx> <out_folder>
  python -m singa_tpu.tools.loader create cifar10 <data_batch.bin...> <out_folder>
  python -m singa_tpu.tools.loader split <in_folder> <out_prefix> <n>
"""

from __future__ import annotations

import os
import struct
import sys
from typing import Iterator, List, Tuple

import numpy as np

from ..data.records import Record, SingleLabelImageRecord
from ..data.shard import Shard


def read_mnist_idx(images_path: str, labels_path: str
                   ) -> Iterator[Tuple[np.ndarray, int]]:
    """Parse the MNIST idx format (big-endian headers)."""
    with open(labels_path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        if magic != 2049:
            raise ValueError(f"{labels_path}: bad idx label magic {magic}")
        labels = np.frombuffer(f.read(n), np.uint8)
    with open(images_path, "rb") as f:
        magic, n2, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != 2051:
            raise ValueError(f"{images_path}: bad idx image magic {magic}")
        if n2 != n:
            raise ValueError(f"image/label count mismatch: {n2} vs {n}")
        for i in range(n):
            img = np.frombuffer(f.read(rows * cols), np.uint8)
            yield img.reshape(rows, cols), int(labels[i])


def read_cifar10_bins(paths: List[str]) -> Iterator[Tuple[np.ndarray, int]]:
    """CIFAR-10 binary batches: rows of [label u8][3072 pixel u8]."""
    for path in paths:
        with open(path, "rb") as f:
            while True:
                row = f.read(3073)
                if len(row) < 3073:
                    break
                yield (np.frombuffer(row[1:], np.uint8).reshape(3, 32, 32),
                       row[0])


def create_shard(source: Iterator[Tuple[np.ndarray, int]], out_folder: str,
                 append: bool = True) -> int:
    """Write (image, label) pairs as Record tuples. Appending is
    restartable: duplicate keys are skipped (data_loader.cc:122-143)."""
    os.makedirs(out_folder, exist_ok=True)
    mode = Shard.KAPPEND if append else Shard.KCREATE
    n = 0
    with Shard(out_folder, mode) as sh:
        for i, (img, label) in enumerate(source):
            rec = Record(image=SingleLabelImageRecord(
                shape=list(img.shape), label=label, pixel=img.tobytes()))
            if sh.insert(f"{i:08d}", rec.encode()):
                n += 1
    return n


def split_shard(in_folder: str, out_prefix: str, n: int) -> List[int]:
    """Round-robin split into n sub-shards (SplitN semantics)."""
    outs = []
    counts = []
    for i in range(n):
        folder = f"{out_prefix}{i}"
        os.makedirs(folder, exist_ok=True)
        outs.append(Shard(folder, Shard.KCREATE))
        counts.append(0)
    with Shard(in_folder, Shard.KREAD) as src:
        for i, (key, val) in enumerate(src):
            outs[i % n].insert(key, val)
            counts[i % n] += 1
    for sh in outs:
        sh.close()
    return counts


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if not argv:
        print(__doc__)
        return 2
    cmd = argv[0]
    if cmd == "create" and len(argv) >= 2 and argv[1] == "mnist":
        images, labels, out = argv[2:5]
        n = create_shard(read_mnist_idx(images, labels), out)
        print(f"wrote {n} records to {out}")
    elif cmd == "create" and len(argv) >= 2 and argv[1] == "cifar10":
        *bins, out = argv[2:]
        n = create_shard(read_cifar10_bins(bins), out)
        print(f"wrote {n} records to {out}")
    elif cmd == "split":
        in_folder, out_prefix, n = argv[1], argv[2], int(argv[3])
        counts = split_shard(in_folder, out_prefix, n)
        print(f"split into {counts}")
    else:
        print(__doc__)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
