"""Data loader tool — the reference's `loader` binary
(/root/reference/tools/data_loader/data_loader.cc).

Modes (same surface):
  create: convert MNIST idx files, a CIFAR-10 binary folder, or an
          ImageNet-style image folder + list file into a Shard of
          Record protos (data_loader.cc:112-145; ImageNetSource
          data_source.h:63-148: cv2 resize, CHW uint8)
  split:  re-partition a shard into N sub-shards (Split/SplitN,
          data_loader.cc:43-94)
  partition: per-worker dataset placement for multi-host training —
          script/load_data.py's partition(): group-sliced, replicated
          or split inside each group, one proc{i}/ shard per worker
  mean:   compute the per-pixel float mean of a shard and write it as a
          single Record (the reference's mean.binaryproto role)
  convert-lmdb: walk a caffe LMDB environment of Datum values
          (layer.cc:237-328's data source) and rewrite it as a Shard
          of Record protos, so the native batch decoder applies

Usage:
  python -m singa_tpu.tools.loader create mnist  <images.idx> <labels.idx> <out_folder>
  python -m singa_tpu.tools.loader create cifar10 <data_batch.bin...> <out_folder>
  python -m singa_tpu.tools.loader create imagefolder <img_dir> <list_file> <out_folder> [size]
  python -m singa_tpu.tools.loader split <in_folder> <out_prefix> <n>
  python -m singa_tpu.tools.loader partition <in_folder> <out_root> <nworkers> [group_size] [--replicate] [--shuffle[=seed]]
  python -m singa_tpu.tools.loader mean <shard_folder> <out_file>
  python -m singa_tpu.tools.loader convert-lmdb <lmdb_env> <out_folder>
"""

from __future__ import annotations

import os
import struct
import sys
from typing import Iterator, List, Tuple

import numpy as np

from ..data.records import Record, SingleLabelImageRecord
from ..data.shard import Shard


def read_mnist_idx(images_path: str, labels_path: str
                   ) -> Iterator[Tuple[np.ndarray, int]]:
    """Parse the MNIST idx format (big-endian headers)."""
    with open(labels_path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        if magic != 2049:
            raise ValueError(f"{labels_path}: bad idx label magic {magic}")
        labels = np.frombuffer(f.read(n), np.uint8)
    with open(images_path, "rb") as f:
        magic, n2, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != 2051:
            raise ValueError(f"{images_path}: bad idx image magic {magic}")
        if n2 != n:
            raise ValueError(f"image/label count mismatch: {n2} vs {n}")
        for i in range(n):
            img = np.frombuffer(f.read(rows * cols), np.uint8)
            yield img.reshape(rows, cols), int(labels[i])


def read_cifar10_bins(paths: List[str]) -> Iterator[Tuple[np.ndarray, int]]:
    """CIFAR-10 binary batches: rows of [label u8][3072 pixel u8]."""
    for path in paths:
        with open(path, "rb") as f:
            while True:
                row = f.read(3073)
                if len(row) < 3073:
                    break
                yield (np.frombuffer(row[1:], np.uint8).reshape(3, 32, 32),
                       row[0])


def read_image_folder(img_dir: str, list_path: str, size: int = 256
                      ) -> Iterator[Tuple[np.ndarray, int]]:
    """ImageNet-style source (data_source.h:63-148): a list file of
    `relative_path label` lines; each image is decoded + resized to
    (size, size) with OpenCV and stored CHW uint8 (BGR channel order,
    matching what the reference's cv-based loader wrote)."""
    import cv2
    with open(list_path) as f:
        for line in f:
            parts = line.split()
            if not parts:
                continue
            name = parts[0]
            label = int(parts[1]) if len(parts) > 1 else 0
            img = cv2.imread(os.path.join(img_dir, name))
            if img is None:
                print(f"warning: unreadable image {name!r}, skipped",
                      file=sys.stderr)
                continue
            img = cv2.resize(img, (size, size))
            yield img.transpose(2, 0, 1), label


def compute_mean(shard_folder: str, out_path: str) -> np.ndarray:
    """Per-pixel float mean over every record of a shard, written as one
    Record with `data` floats (the mean.binaryproto role; consumed as
    the `mean` entry of the input batch for kRGBImage)."""
    total = None
    count = 0
    with Shard(shard_folder, Shard.KREAD) as src:
        for _, val in src:
            rec = Record.decode(val).image
            arr = rec.pixels_array().astype(np.float64)
            total = arr if total is None else total + arr
            count += 1
    if not count:
        raise ValueError(f"{shard_folder}: empty shard")
    mean = (total / count).astype(np.float32)
    out = Record(image=SingleLabelImageRecord(
        shape=list(mean.shape), data=[float(x) for x in mean.ravel()]))
    with open(out_path, "wb") as f:
        f.write(out.encode())
    return mean


def create_shard(source: Iterator[Tuple[np.ndarray, int]], out_folder: str,
                 append: bool = True) -> int:
    """Write (image, label) pairs as Record tuples. Appending is
    restartable: duplicate keys are skipped (data_loader.cc:122-143)."""
    os.makedirs(out_folder, exist_ok=True)
    mode = Shard.KAPPEND if append else Shard.KCREATE
    n = 0
    with Shard(out_folder, mode) as sh:
        for i, (img, label) in enumerate(source):
            rec = Record(image=SingleLabelImageRecord(
                shape=list(img.shape), label=label, pixel=img.tobytes()))
            if sh.insert(f"{i:08d}", rec.encode()):
                n += 1
    return n


def convert_lmdb(lmdb_env: str, out_folder: str) -> int:
    """caffe LMDB → Shard: walk the env in key order, convert each
    Datum to a Record (same keys), and insert into a fresh shard."""
    from ..data.lmdb_reader import iter_lmdb
    from ..data.records import Datum, record_from_datum

    os.makedirs(out_folder, exist_ok=True)
    n = 0
    with Shard(out_folder, Shard.KCREATE) as sh:
        for key, raw in iter_lmdb(lmdb_env):
            rec = record_from_datum(Datum.decode(raw))
            if sh.insert(key, rec.encode()):
                n += 1
    return n


def split_shard(in_folder: str, out_prefix: str, n: int) -> List[int]:
    """Round-robin split into n sub-shards (SplitN semantics)."""
    outs = []
    counts = []
    for i in range(n):
        folder = f"{out_prefix}{i}"
        os.makedirs(folder, exist_ok=True)
        outs.append(Shard(folder, Shard.KCREATE))
        counts.append(0)
    with Shard(in_folder, Shard.KREAD) as src:
        for i, (key, val) in enumerate(src):
            outs[i % n].insert(key, val)
            counts[i % n] += 1
    for sh in outs:
        sh.close()
    return counts


def partition_shard(in_folder: str, out_root: str, nworkers: int,
                    group_size: int = 1, replicate: bool = False,
                    shuffle_seed: int | None = None) -> List[int]:
    """Per-worker dataset placement — script/load_data.py's partition()
    as a shard operation (the reference slices a record-id list per
    worker group, then either replicates the slice inside the group or
    splits it per worker, and scps each list to its host).

    Writes `out_root/proc{i}/` for i in [0, nworkers): worker i (process
    i in the -procsID/-hostfile launch) gets group g = i // group_size's
    contiguous slice of the source records — the whole slice when
    `replicate` (every group member sees the group's data; intra-group
    parallelism splits the batch, not the dataset), else its contiguous
    sub-slice.  Placement on the actual hosts is one rsync of proc{i}/
    per host (the ssh/scp loop has no meaning in this zero-egress
    image).  Returns per-worker record counts."""
    if nworkers <= 0 or group_size <= 0 or nworkers % group_size:
        raise ValueError(f"nworkers {nworkers} must be a positive "
                         f"multiple of group_size {group_size}")
    with Shard(in_folder, Shard.KREAD) as src:
        records = list(src)
    if shuffle_seed is not None:
        np.random.default_rng(shuffle_seed).shuffle(records)
    ngroups = nworkers // group_size
    per_group = len(records) // ngroups
    counts = []
    for i in range(nworkers):
        g, k = divmod(i, group_size)
        # the last group absorbs the remainder (the reference's integer
        # division silently DROPPED the tail; records are too expensive
        # to lose on purpose)
        g_end = (g + 1) * per_group if g < ngroups - 1 else len(records)
        grp = records[g * per_group:g_end]
        if replicate:
            mine = grp
        else:
            per_w = len(grp) // group_size
            w_end = ((k + 1) * per_w if k < group_size - 1 else len(grp))
            mine = grp[k * per_w:w_end]
        folder = os.path.join(out_root, f"proc{i}")
        os.makedirs(folder, exist_ok=True)
        with Shard(folder, Shard.KCREATE) as out:
            for key, val in mine:
                out.insert(key, val)
        counts.append(len(mine))
    return counts


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if not argv:
        print(__doc__)
        return 2
    cmd = argv[0]
    if cmd == "create" and len(argv) >= 2 and argv[1] == "mnist":
        images, labels, out = argv[2:5]
        n = create_shard(read_mnist_idx(images, labels), out)
        print(f"wrote {n} records to {out}")
    elif cmd == "create" and len(argv) >= 2 and argv[1] == "cifar10":
        *bins, out = argv[2:]
        n = create_shard(read_cifar10_bins(bins), out)
        print(f"wrote {n} records to {out}")
    elif cmd == "create" and len(argv) >= 2 and argv[1] == "imagefolder":
        img_dir, list_file, out = argv[2:5]
        size = int(argv[5]) if len(argv) > 5 else 256
        n = create_shard(read_image_folder(img_dir, list_file, size), out)
        print(f"wrote {n} records to {out}")
    elif cmd == "convert-lmdb":
        env, out = argv[1], argv[2]
        n = convert_lmdb(env, out)
        print(f"converted {n} LMDB records to {out}")
    elif cmd == "split":
        in_folder, out_prefix, n = argv[1], argv[2], int(argv[3])
        counts = split_shard(in_folder, out_prefix, n)
        print(f"split into {counts}")
    elif cmd == "partition":
        flags = [a for a in argv[1:] if a.startswith("--")]
        pos = [a for a in argv[1:] if not a.startswith("--")]
        in_folder, out_root, nworkers = pos[0], pos[1], int(pos[2])
        gsize = int(pos[3]) if len(pos) > 3 else 1
        seed = None
        for f in flags:
            if f.startswith("--shuffle"):
                seed = int(f.split("=")[1]) if "=" in f else 0
        counts = partition_shard(in_folder, out_root, nworkers, gsize,
                                 replicate="--replicate" in flags,
                                 shuffle_seed=seed)
        print(f"partitioned into {counts} (proc0..proc{nworkers - 1} "
              f"under {out_root})")
    elif cmd == "mean":
        shard_folder, out_path = argv[1], argv[2]
        mean = compute_mean(shard_folder, out_path)
        print(f"wrote mean {mean.shape} to {out_path}")
    else:
        print(__doc__)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
