"""North-star gate 1: train examples/mnist/conv.conf to >=99% test
accuracy and record time-to-99 (BASELINE.md tracked metric).

The reference's convergence configs train on real MNIST shards
(examples/mnist/conv.conf:1-21; accuracy printed by the Performance
blob, worker.cc:376-386).  This environment has zero egress and no
local MNIST, so the run uses the learnable synthetic source
(singa_tpu.data.synthetic): fixed per-class templates, a *held-out
test stream* (same templates, independent noise/labels — the model
must generalize, not memoize batches), and a noise level set so the
net starts at chance and has to learn.

Writes CONVERGENCE.json at the repo root; bench.py folds its numbers
into the judged stdout line.  Two wall-clocks are reported:
`time_to_99_seconds` from the start of run() (includes XLA compiles —
what a user experiences) and `train_time_to_99_seconds` counting every
train chunk and eval at warm-execution speed (programs pre-compiled
before timing starts).

Usage: python -m singa_tpu.tools.convergence_run [--target 0.99]
       [--max-steps 10000] [--out CONVERGENCE.json] [--noise-std 96]
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np


def run(conf: str, target: float, max_steps: int, out: str,
        noise_std: float, chunk: int, test_batches: int,
        log=print) -> dict:
    t_start = time.time()
    import jax

    from ..config import load_model_config
    from ..core.trainer import Trainer
    from ..data.synthetic import synthetic_image_batches

    cfg = load_model_config(conf)
    batch = next(l.data_param.batchsize for l in cfg.neuralnet.layer
                 if l.data_param)
    trainer = Trainer(cfg, {"data": {"pixel": (28, 28), "label": ()}},
                      log_fn=log)
    params, opt_state = trainer.init(seed=0)

    train_iter = synthetic_image_batches(batch, seed=7, stream_seed=100,
                                         noise_std=noise_std)
    # held-out split: same templates (seed), independent stream
    test_set = []
    test_iter = synthetic_image_batches(1000, seed=7, stream_seed=200,
                                        noise_std=noise_std)
    for _ in range(test_batches):
        test_set.append(next(test_iter))

    def test_accuracy(p):
        accs = [float(trainer.test_step(p, b)["precision"])
                for b in test_set]
        return float(np.mean(accs))

    rng = jax.random.PRNGKey(1)
    step = 0
    train_s = 0.0
    result = None
    acc0 = test_accuracy(params)   # also compiles test_step
    log(f"step-0 test accuracy {acc0:.4f} (chance ~0.10)")
    # pre-compile the scan program so every timed chunk below is warm
    # execution (train_time_to_99_seconds counts ALL train steps + all
    # evals, excluding only XLA compilation)
    warm = [next(train_iter) for _ in range(chunk)]
    warm_stacked = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *warm)
    # through the trainer's AOT cache: CompileWatch times the compile,
    # CostWatch harvests it, and profile_phases-style consumers reuse
    # the same executable instead of compiling their own
    trainer.compiled_scan(params, opt_state, warm_stacked, 0, rng,
                          chunk, True)
    while step < max_steps:
        n = min(chunk, max_steps - step)
        batches = ([next(train_iter) for _ in range(n)]
                   if step or n != chunk else warm)
        stacked = (jax.tree_util.tree_map(lambda *xs: np.stack(xs),
                                          *batches)
                   if step or n != chunk else warm_stacked)
        t0 = time.perf_counter()
        params, opt_state, _ = trainer.train_steps(
            params, opt_state, stacked, step, rng, n, True)
        jax.block_until_ready(
            jax.tree_util.tree_leaves(params)[0])
        train_s += time.perf_counter() - t0
        step += n
        t0 = time.perf_counter()
        acc = test_accuracy(params)
        train_s += time.perf_counter() - t0
        log(f"step-{step} test accuracy {acc:.4f}")
        if acc >= target and result is None:
            result = {
                "mnist_test_accuracy": round(acc, 4),
                "steps_to_99": step,
                "time_to_99_seconds": round(time.time() - t_start, 2),
                "train_time_to_99_seconds": round(train_s, 2),
            }
            break
    final = {
        "conf": os.path.relpath(conf),
        "target": target,
        "data": f"synthetic-learnable(noise_std={noise_std}, "
                f"held-out stream)",
        "batchsize": batch,
        "test_samples": 1000 * test_batches,
        "device": str(jax.devices()[0]),
        "reached": result is not None,
        **(result or {"mnist_test_accuracy": round(acc, 4),
                      "steps_run": step}),
    }
    with open(out, "w") as f:
        json.dump(final, f, indent=1)
    log(json.dumps(final))
    return final


def main():
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    ap = argparse.ArgumentParser()
    ap.add_argument("--conf",
                    default=os.path.join(repo, "examples/mnist/conv.conf"))
    ap.add_argument("--target", type=float, default=0.99)
    ap.add_argument("--max-steps", type=int, default=10000)
    ap.add_argument("--out",
                    default=os.path.join(repo, "CONVERGENCE.json"))
    ap.add_argument("--noise-std", type=float, default=96.0)
    ap.add_argument("--chunk", type=int, default=100)
    ap.add_argument("--test-batches", type=int, default=10)
    a = ap.parse_args()
    run(a.conf, a.target, a.max_steps, a.out, a.noise_std, a.chunk,
        a.test_batches)


if __name__ == "__main__":
    main()
