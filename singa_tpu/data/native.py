"""ctypes binding for the native shard store (native/shard_store.cc).

The hot data path runs in C++ (like the reference's shard reader,
shard.cc); Python falls back to the pure implementation in
singa_tpu.data.shard when the shared library hasn't been built.
Build with `make -C native`.
"""

from __future__ import annotations

import ctypes
import os
from typing import Iterator, Optional, Tuple

_LIB_PATH = os.path.join(os.path.dirname(__file__), "..", "..",
                         "native", "libsinga_native.so")
_lib = None
_lib_failed = False


def load_library() -> Optional[ctypes.CDLL]:
    global _lib, _lib_failed
    if _lib is not None:
        return _lib
    if _lib_failed:
        return None
    path = os.path.abspath(_LIB_PATH)
    if not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError as e:
        # a built .so that cannot load (ABI/runtime mismatch, e.g. an
        # older libstdc++ than the build host's) must degrade to the
        # pure-Python codec, not crash every batch decode
        _lib_failed = True
        import sys
        print(f"warning: native shard library unusable ({e}); "
              f"falling back to the Python codec", file=sys.stderr)
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.shard_open_read.restype = ctypes.c_void_p
    lib.shard_open_read.argtypes = [ctypes.c_char_p]
    lib.shard_next.restype = ctypes.c_int
    lib.shard_next.argtypes = [ctypes.c_void_p, ctypes.POINTER(u8p),
                               ctypes.POINTER(ctypes.c_uint64),
                               ctypes.POINTER(u8p),
                               ctypes.POINTER(ctypes.c_uint64)]
    lib.shard_seek_first.argtypes = [ctypes.c_void_p]
    lib.shard_count.restype = ctypes.c_long
    lib.shard_count.argtypes = [ctypes.c_void_p]
    lib.shard_close_read.argtypes = [ctypes.c_void_p]
    lib.shard_open_write.restype = ctypes.c_void_p
    lib.shard_open_write.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.shard_insert.restype = ctypes.c_int
    lib.shard_insert.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_uint64, ctypes.c_char_p,
                                 ctypes.c_uint64]
    lib.shard_flush.argtypes = [ctypes.c_void_p]
    lib.shard_close_write.argtypes = [ctypes.c_void_p]
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.record_probe.restype = ctypes.c_int
    lib.record_probe.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int),
        u64p, ctypes.POINTER(ctypes.c_int32)]
    lib.record_batch_decode.restype = ctypes.c_long
    lib.record_batch_decode.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), u64p, ctypes.c_long,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_int32)]
    _lib = lib
    return lib


def available() -> bool:
    return load_library() is not None


def decode_image_batch(vals):
    """Decode a list of serialized Record protos into (pixels, labels)
    via the C++ walker (native/record_codec.cc) — one memcpy per record.

    Returns (uint8 ndarray (n, *shape), int32 ndarray (n,)), or None
    when the library isn't built or the records aren't uniform uint8
    pixel images (caller falls back to the Python codec).
    """
    import numpy as np
    lib = load_library()
    if lib is None or not vals:
        return None
    shape = (ctypes.c_int64 * 4)()
    ndim = ctypes.c_int()
    plen = ctypes.c_uint64()
    label = ctypes.c_int32()
    if lib.record_probe(vals[0], len(vals[0]), shape, ctypes.byref(ndim),
                        ctypes.byref(plen), ctypes.byref(label)) != 0:
        return None
    dims = tuple(shape[i] for i in range(ndim.value))
    if not dims or plen.value != int(np.prod(dims)):
        return None   # float-data or shapeless record: Python path
    n = len(vals)
    # per-record pointers into the bytes objects (held alive by `vals`) —
    # no concatenation copy of the batch payload
    recs = (ctypes.c_char_p * n)(*vals)
    lens = (ctypes.c_uint64 * n)(*(len(v) for v in vals))
    pixels = np.empty((n,) + dims, np.uint8)
    labels = np.empty((n,), np.int32)
    got = lib.record_batch_decode(
        recs, lens, n, shape, ndim.value,
        pixels.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        plen.value, labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    if got != n:
        return None
    return pixels, labels


class NativeShardReader:
    """Iterates (key, val) tuples via the C++ reader."""

    def __init__(self, folder: str):
        lib = load_library()
        if lib is None:
            raise RuntimeError("native shard library not built "
                               "(run `make -C native`)")
        self._lib = lib
        path = os.path.join(folder, "shard.dat").encode()
        self._h = lib.shard_open_read(path)
        if not self._h:
            raise IOError(f"cannot open shard at {folder!r}")

    def __iter__(self) -> Iterator[Tuple[bytes, bytes]]:
        self._lib.shard_seek_first(self._h)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        key_p, val_p = u8p(), u8p()
        klen, vlen = ctypes.c_uint64(), ctypes.c_uint64()
        while self._lib.shard_next(self._h, ctypes.byref(key_p),
                                   ctypes.byref(klen), ctypes.byref(val_p),
                                   ctypes.byref(vlen)):
            yield (ctypes.string_at(key_p, klen.value),
                   ctypes.string_at(val_p, vlen.value))

    def count(self) -> int:
        return self._lib.shard_count(self._h)

    def close(self) -> None:
        if self._h:
            self._lib.shard_close_read(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class NativeShardWriter:
    def __init__(self, folder: str, append: bool = False):
        lib = load_library()
        if lib is None:
            raise RuntimeError("native shard library not built")
        self._lib = lib
        path = os.path.join(folder, "shard.dat").encode()
        self._h = lib.shard_open_write(path, 1 if append else 0)
        if not self._h:
            raise IOError(f"cannot open shard for write at {folder!r}")

    def insert(self, key: bytes | str, val: bytes) -> bool:
        if isinstance(key, str):
            key = key.encode()
        return bool(self._lib.shard_insert(self._h, key, len(key),
                                           val, len(val)))

    def flush(self) -> None:
        self._lib.shard_flush(self._h)

    def close(self) -> None:
        if self._h:
            self._lib.shard_flush(self._h)
            self._lib.shard_close_write(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
