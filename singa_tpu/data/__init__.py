"""Data subsystem: Shard store, record codecs, loaders, prefetch."""

from __future__ import annotations

from typing import Callable, Iterator, Tuple

from .discovery import discover_input_shapes
from .records import Datum, Record, SingleLabelImageRecord
from .shard import Shard, ShardError
from .feed import ChunkStager, DeviceFeeder, FeedChunk, FeedError
from .pipeline import (PipelineStats, PrefetchError, Prefetcher, prefetch,
                       shard_batches)
from .synthetic import synthetic_image_batches


def resolve_data_source(model_cfg, batchsize: int, seed: int = 0,
                        force_synthetic: bool = False,
                        stream_seed: int | None = None,
                        sample_shapes: dict | None = None
                        ) -> Tuple[Iterator, Callable[[], Iterator]]:
    """Pick (train_iter, test_iter_factory) for a model config: shard
    folders from DataProto.path when they exist locally, else synthetic.

    `seed` fixes the synthetic task (class templates / LM transition
    table); `stream_seed` varies only the sample stream — async replica
    groups pass a different stream_seed per replica so they train
    different data of the SAME task (a different `seed` would hand each
    replica an unrelated task and make their center average garbage).

    `sample_shapes` (data-layer name → field → per-sample shape, as
    discovery.discover_input_shapes returns) sizes the synthetic source
    so it matches the geometry the net was built for — RGB nets get
    (3, S, S) records, not MNIST's (28, 28).  Omitted, it is derived by
    the same discovery the Trainer path uses, so a caller can never get
    batches shaped differently from the net it built."""
    if sample_shapes is None:
        from .discovery import discover_input_shapes as _discover
        sample_shapes = _discover(model_cfg,
                                  force_synthetic=force_synthetic)
    # one stats object per resolved source: train iterator and every
    # test-factory iterator share the quarantine tally, and the
    # returned Prefetcher exposes it as `.stats`
    stats = PipelineStats()
    train_path = test_path = None
    train_name = test_name = "data"
    layers = model_cfg.neuralnet.layer if model_cfg.neuralnet else []

    # token-sequence models (kSequenceData): synthetic Markov LM data
    for layer in layers:
        if layer.type == "kSequenceData" and layer.seqdata_param:
            from ..models.transformer import synthetic_token_batches
            p = layer.seqdata_param
            # the transition table is keyed by table_seed (fixed), so
            # different seeds here already share one "language"
            mk = lambda s: synthetic_token_batches(  # noqa: E731
                batchsize, p.seq_len, p.vocab_size, seed=s,
                data_layer=layer.name, table_seed=1234 + seed)
            return (prefetch(mk(stream_seed if stream_seed is not None
                                else seed), stats=stats),
                    (lambda: mk(seed + 7919)))

    # the SAME existence predicates discovery uses to size the net —
    # the two must never diverge or served batches mismatch the net
    from .discovery import lmdb_source_exists, shard_source_exists

    def shard_ok(p):
        return not force_synthetic and shard_source_exists(p)

    def lmdb_ok(p):
        return not force_synthetic and lmdb_source_exists(p)

    train_skip = 0
    train_lmdb = test_lmdb = False
    for layer in layers:
        if layer.type in ("kShardData", "kLMDBData") and layer.data_param:
            is_lmdb = layer.type == "kLMDBData"
            if is_lmdb and not force_synthetic \
                    and not lmdb_ok(layer.data_param.path):
                import sys as _sys
                print(f"warning: kLMDBData layer {layer.name!r} "
                      f"path {layer.data_param.path!r} not found; "
                      f"using the synthetic source", file=_sys.stderr)
            if "kTrain" not in layer.exclude:
                train_path, train_name = layer.data_param.path, layer.name
                train_skip = layer.data_param.random_skip
                train_lmdb = is_lmdb
            else:
                test_path, test_name = layer.data_param.path, layer.name
                test_lmdb = is_lmdb

    def _warn_identical_streams(kind: str) -> None:
        # stream decorrelation on real sources rides
        # DataProto.random_skip (layer.cc:646-673): each stream_seed
        # draws a different initial skip; record order is otherwise
        # fixed.  Warn when a caller asks for distinct streams but the
        # config gives no skip budget.
        if stream_seed is not None and not train_skip:
            import sys as _sys
            print(f"warning: distinct data streams requested "
                  f"(stream_seed) but DataProto.random_skip is 0 — "
                  f"{kind} replicas will read identical record order",
                  file=_sys.stderr)

    from .pipeline import lmdb_batches
    if train_lmdb and lmdb_ok(train_path):
        _warn_identical_streams("LMDB")
        train_iter = prefetch(lmdb_batches(
            train_path, batchsize, train_name,
            seed=(stream_seed if stream_seed is not None else seed),
            random_skip=train_skip, stats=stats), stats=stats)
    elif shard_ok(train_path):
        _warn_identical_streams("shard")
        train_iter = prefetch(
            shard_batches(train_path, batchsize, train_name,
                          seed=(stream_seed if stream_seed is not None
                                else seed),
                          random_skip=train_skip, stats=stats),
            stats=stats)
    else:
        # train/test must share the class templates (`seed`) and differ
        # only in the sample stream — templates keyed by different
        # seeds are unrelated tasks and make test accuracy pure noise
        train_iter = prefetch(synthetic_image_batches(
            batchsize, data_layer=train_name, seed=seed,
            image_shape=_pixel_shape(sample_shapes, train_name),
            stream_seed=(stream_seed if stream_seed is not None
                         else seed + 101)), stats=stats)
    if test_lmdb and lmdb_ok(test_path):
        test_factory = lambda: lmdb_batches(
            test_path, batchsize, test_name, loop=False, stats=stats)
    elif shard_ok(test_path):
        test_factory = lambda: shard_batches(
            test_path, batchsize, test_name, loop=False, stats=stats)
    else:
        test_factory = lambda: synthetic_image_batches(
            batchsize, data_layer=test_name, seed=seed,
            image_shape=_pixel_shape(sample_shapes, test_name),
            stream_seed=seed + 202)
    return train_iter, test_factory


def _pixel_shape(sample_shapes: dict | None, layer_name: str):
    if sample_shapes and layer_name in sample_shapes:
        return tuple(sample_shapes[layer_name].get("pixel", (28, 28)))
    return (28, 28)
