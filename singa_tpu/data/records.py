"""Record codecs — wire-compatible with the reference's data protos.

Reference schema: /root/reference/src/proto/model.proto:279-305 —
  Record{ type=1 (enum, kSingleLabelImage=0), image=2 (message) }
  SingleLabelImageRecord{ shape=1 (repeated int32), label=2 (int32),
                          pixel=3 (bytes), data=4 (repeated float) }
  Datum{ channels=1, height=2, width=3, data=4 (bytes), label=5,
         float_data=6 (repeated float), encoded=7 (bool) }   (caffe LMDB)

Hand-rolled protobuf wire codec (varints + length-delimited fields) so
shards written by the reference `loader` binary decode here byte-for-byte
and shards written here feed the reference — without generated code.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np

# -- protobuf wire primitives ------------------------------------------------

_WT_VARINT, _WT_64, _WT_LEN, _WT_32 = 0, 1, 2, 5


def _enc_varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _dec_varint(buf: bytes, i: int) -> Tuple[int, int]:
    shift = 0
    result = 0
    while True:
        b = buf[i]
        i += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, i
        shift += 7


def _tag(fieldnum: int, wt: int) -> bytes:
    return _enc_varint((fieldnum << 3) | wt)


def _iter_fields(buf: bytes):
    i = 0
    n = len(buf)
    while i < n:
        key, i = _dec_varint(buf, i)
        fieldnum, wt = key >> 3, key & 7
        if wt == _WT_VARINT:
            v, i = _dec_varint(buf, i)
        elif wt == _WT_LEN:
            ln, i = _dec_varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == _WT_32:
            v = buf[i:i + 4]
            i += 4
        elif wt == _WT_64:
            v = buf[i:i + 8]
            i += 8
        else:
            raise ValueError(f"bad wire type {wt}")
        yield fieldnum, wt, v


# -- messages ----------------------------------------------------------------


@dataclass
class SingleLabelImageRecord:
    shape: List[int] = field(default_factory=list)
    label: int = 0
    pixel: bytes = b""
    data: List[float] = field(default_factory=list)

    def encode(self) -> bytes:
        out = bytearray()
        for s in self.shape:
            out += _tag(1, _WT_VARINT) + _enc_varint(s)
        if self.label:
            out += _tag(2, _WT_VARINT) + _enc_varint(self.label)
        if self.pixel:
            out += _tag(3, _WT_LEN) + _enc_varint(len(self.pixel)) + self.pixel
        for f in self.data:
            out += _tag(4, _WT_32) + struct.pack("<f", f)
        return bytes(out)

    @classmethod
    def decode(cls, buf: bytes) -> "SingleLabelImageRecord":
        rec = cls()
        for fn, wt, v in _iter_fields(buf):
            if fn == 1:
                if wt == _WT_LEN:   # packed repeated
                    i = 0
                    while i < len(v):
                        x, i = _dec_varint(v, i)
                        rec.shape.append(x)
                else:
                    rec.shape.append(v)
            elif fn == 2:
                rec.label = v
            elif fn == 3:
                rec.pixel = bytes(v)
            elif fn == 4:
                if wt == _WT_LEN:   # packed repeated float
                    rec.data.extend(
                        struct.unpack(f"<{len(v) // 4}f", v))
                else:
                    rec.data.append(struct.unpack("<f", v)[0])
        return rec

    def pixels_array(self) -> np.ndarray:
        if self.pixel:
            arr = np.frombuffer(self.pixel, np.uint8)
        else:
            arr = np.asarray(self.data, np.float32)
        return arr.reshape(self.shape) if self.shape else arr


@dataclass
class Record:
    KSINGLE_LABEL_IMAGE = 0
    type: int = KSINGLE_LABEL_IMAGE
    image: Optional[SingleLabelImageRecord] = None

    def encode(self) -> bytes:
        out = bytearray()
        # type has default 0 — the reference always writes image
        if self.type:
            out += _tag(1, _WT_VARINT) + _enc_varint(self.type)
        if self.image is not None:
            body = self.image.encode()
            out += _tag(2, _WT_LEN) + _enc_varint(len(body)) + body
        return bytes(out)

    @classmethod
    def decode(cls, buf: bytes) -> "Record":
        rec = cls()
        for fn, wt, v in _iter_fields(buf):
            if fn == 1:
                rec.type = v
            elif fn == 2:
                rec.image = SingleLabelImageRecord.decode(v)
        return rec


def record_has_image(buf: bytes) -> bool:
    """Whether a serialized Record carries an image submessage — a
    tag-walk only (no submessage parse), cheap enough for the input
    pipeline to filter image-less records before batching.

    Raises ValueError on an unparseable buffer: a torn/corrupt record
    must fail loudly (the shard store already truncates torn tails at
    open, shard.cc:175-206 semantics), not be silently dropped as if it
    were merely image-less."""
    try:
        for fn, wt, _ in _iter_fields(buf):
            if fn == 2 and wt == _WT_LEN:
                return True
    except (ValueError, IndexError) as e:
        raise ValueError(
            f"corrupt Record buffer ({len(buf)} bytes): {e}") from e
    return False


def record_from_datum(d: "Datum") -> "Record":
    """caffe Datum → Record, the conversion the reference's LMDB parse
    loop performs implicitly (layer.cc:285-316: Datum fields copied
    into the blob the same way Record fields are)."""
    if d.encoded:
        raise ValueError(
            "encoded (JPEG/PNG) Datum values are not supported — "
            "re-export the LMDB with convert_imageset's raw mode, or "
            "decode to raw pixels before conversion (no image codec "
            "exists in this environment)")
    img = SingleLabelImageRecord(
        shape=[d.channels, d.height, d.width], label=d.label,
        pixel=d.data, data=list(d.float_data) if not d.data else [])
    return Record(image=img)


@dataclass
class Datum:
    """caffe's LMDB record (model.proto:288-299)."""
    channels: int = 0
    height: int = 0
    width: int = 0
    data: bytes = b""
    label: int = 0
    float_data: List[float] = field(default_factory=list)
    encoded: bool = False

    def encode(self) -> bytes:
        out = bytearray()
        for fn, v in ((1, self.channels), (2, self.height), (3, self.width)):
            if v:
                out += _tag(fn, _WT_VARINT) + _enc_varint(v)
        if self.data:
            out += _tag(4, _WT_LEN) + _enc_varint(len(self.data)) + self.data
        if self.label:
            out += _tag(5, _WT_VARINT) + _enc_varint(self.label)
        for f in self.float_data:
            out += _tag(6, _WT_32) + struct.pack("<f", f)
        if self.encoded:
            out += _tag(7, _WT_VARINT) + _enc_varint(1)
        return bytes(out)

    @classmethod
    def decode(cls, buf: bytes) -> "Datum":
        d = cls()
        for fn, wt, v in _iter_fields(buf):
            if fn == 1:
                d.channels = v
            elif fn == 2:
                d.height = v
            elif fn == 3:
                d.width = v
            elif fn == 4:
                d.data = bytes(v)
            elif fn == 5:
                d.label = v
            elif fn == 6:
                if wt == _WT_LEN:
                    d.float_data.extend(struct.unpack(f"<{len(v) // 4}f", v))
                else:
                    d.float_data.append(struct.unpack("<f", v)[0])
            elif fn == 7:
                d.encoded = bool(v)
        return d
