"""Synthetic data sources (this environment has zero egress, so real
dataset downloads are impossible; shard files can be built offline with
singa_tpu.data.shard tools when data exists locally).

Provides deterministic, learnable synthetic classification batches shaped
like the reference's MNIST/CIFAR records so training loops and benchmarks
exercise the identical compute path.
"""

from __future__ import annotations

from typing import Dict, Iterator, Optional, Tuple

import numpy as np


def synthetic_image_batches(
        batchsize: int,
        image_shape: Tuple[int, ...] = (28, 28),
        nclass: int = 10,
        data_layer: str = "data",
        seed: int = 0,
        learnable: bool = True,
        dtype=np.uint8,
        stream_seed: Optional[int] = None,
        noise_std: float = 64.0) -> Iterator[Dict]:
    """Infinite iterator of {data_layer: {"pixel": u8, "label": i32}}.

    When `learnable`, each class k has a fixed random template and samples
    are noisy copies — so accuracy above chance proves learning end to end.

    `seed` fixes the class templates.  `stream_seed` fixes the
    label/noise stream independently; when omitted, the stream simply
    continues the template RNG (the original behavior — note this is
    NOT the same stream as an explicit stream_seed=seed, which
    re-seeds from scratch).  A held-out test split is the SAME
    templates with a different stream_seed (train/test
    generalization, not memorization of identical batches).
    `noise_std` sets the per-pixel gaussian corruption (higher =
    harder task).  Pick stream_seed != seed so the stream does not
    replay the bit sequence that generated the templates.
    """
    rng = np.random.default_rng(seed)
    templates = rng.integers(0, 256, (nclass,) + tuple(image_shape))
    stream = (rng if stream_seed is None
              else np.random.default_rng(stream_seed))
    while True:
        labels = stream.integers(0, nclass, (batchsize,))
        if learnable:
            noise = stream.normal(0, noise_std,
                                  (batchsize,) + tuple(image_shape))
            pixel = np.clip(templates[labels] + noise, 0, 255)
        else:
            pixel = stream.integers(0, 256,
                                    (batchsize,) + tuple(image_shape))
        yield {data_layer: {
            "pixel": pixel.astype(dtype),
            "label": labels.astype(np.int32),
        }}
