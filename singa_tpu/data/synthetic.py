"""Synthetic data sources (this environment has zero egress, so real
dataset downloads are impossible; shard files can be built offline with
singa_tpu.data.shard tools when data exists locally).

Provides deterministic, learnable synthetic classification batches shaped
like the reference's MNIST/CIFAR records so training loops and benchmarks
exercise the identical compute path.
"""

from __future__ import annotations

from typing import Dict, Iterator, Optional, Tuple

import numpy as np


def synthetic_image_batches(
        batchsize: int,
        image_shape: Tuple[int, ...] = (28, 28),
        nclass: int = 10,
        data_layer: str = "data",
        seed: int = 0,
        learnable: bool = True,
        dtype=np.uint8) -> Iterator[Dict]:
    """Infinite iterator of {data_layer: {"pixel": u8, "label": i32}}.

    When `learnable`, each class k has a fixed random template and samples
    are noisy copies — so accuracy above chance proves learning end to end.
    """
    rng = np.random.default_rng(seed)
    templates = rng.integers(0, 256, (nclass,) + tuple(image_shape))
    while True:
        labels = rng.integers(0, nclass, (batchsize,))
        if learnable:
            noise = rng.normal(0, 64, (batchsize,) + tuple(image_shape))
            pixel = np.clip(templates[labels] + noise, 0, 255)
        else:
            pixel = rng.integers(0, 256, (batchsize,) + tuple(image_shape))
        yield {data_layer: {
            "pixel": pixel.astype(dtype),
            "label": labels.astype(np.int32),
        }}
