"""Shard record store — binary-compatible with the reference's format.

Reference: /root/reference/include/utils/shard.h:33-142,
src/utils/shard.cc.  A shard folder holds `shard.dat`: a sequence of
tuples `[size_t keylen][key bytes][size_t vallen][val bytes]` (size_t =
8-byte little-endian).  Properties preserved:

- duplicate keys are rejected on insert (shard.cc:49-52 `keys_` set)
- kAppend rescans the file and truncates a torn tail from a crashed
  writer before appending (shard.cc:175-206 PrepareForAppend)
- buffered writes flushed explicitly (shard.cc:70-74)

A shard written by the reference's `loader` binary is readable here and
vice versa.
"""

from __future__ import annotations

import os
import struct
from typing import Iterator, Optional, Tuple

_SZ = struct.Struct("<Q")   # size_t on x86-64


class ShardError(IOError):
    pass


class Shard:
    KREAD, KCREATE, KAPPEND = "r", "w", "a"

    def __init__(self, folder: str, mode: str, capacity: int = 100 * 1024 * 1024):
        if not os.path.isdir(folder):
            raise ShardError(f"Cannot open shard folder {folder}")
        self.path = os.path.join(folder, "shard.dat")
        self.mode = mode
        self.capacity = capacity
        self._keys = set()
        self._buf = bytearray()
        self._closed = False
        if mode == self.KREAD:
            self._f = open(self.path, "rb")
        elif mode == self.KCREATE:
            self._f = open(self.path, "wb")
        elif mode == self.KAPPEND:
            last_ok = self._prepare_for_append()
            self._f = open(self.path, "r+b")
            self._f.truncate(last_ok)
            self._f.seek(last_ok)
        else:
            raise ShardError(f"bad mode {mode!r}")

    # -- write path --------------------------------------------------------
    def insert(self, key: bytes | str, val: bytes) -> bool:
        if self._closed:
            # writing to a dead handle would raise a bare ValueError at
            # the next capacity flush — or worse, buffer silently until
            # then; fail at the call site instead
            raise ShardError(f"insert on closed shard {self.path}")
        if isinstance(key, str):
            key = key.encode()
        if key in self._keys or len(val) == 0:
            return False
        self._keys.add(key)
        rec = _SZ.pack(len(key)) + key + _SZ.pack(len(val)) + val
        if len(self._buf) + len(rec) > self.capacity:
            self._f.write(self._buf)
            self._buf.clear()
        self._buf += rec
        return True

    def flush(self) -> None:
        self._f.write(self._buf)
        self._f.flush()
        self._buf.clear()

    # -- read path ---------------------------------------------------------
    def seek_to_first(self) -> None:
        self._f.seek(0)

    def next(self) -> Optional[Tuple[bytes, bytes]]:
        """Next (key, val) or None at EOF / torn tail."""
        hdr = self._f.read(8)
        if len(hdr) < 8:
            return None
        klen = _SZ.unpack(hdr)[0]
        key = self._f.read(klen)
        hdr = self._f.read(8)
        if len(key) < klen or len(hdr) < 8:
            return None
        vlen = _SZ.unpack(hdr)[0]
        val = self._f.read(vlen)
        if len(val) < vlen:
            return None
        return key, val

    def __iter__(self) -> Iterator[Tuple[bytes, bytes]]:
        self.seek_to_first()
        while True:
            kv = self.next()
            if kv is None:
                return
            yield kv

    def count(self) -> int:
        """Number of complete tuples (shard.cc:124-141 Count)."""
        pos = self._f.tell()
        n = sum(1 for _ in self)
        self._f.seek(pos)
        return n

    def close(self) -> None:
        if self._closed:
            return
        try:
            if self.mode != self.KREAD:
                self.flush()
        finally:
            # mark closed BEFORE the handle close so a flush failure
            # still retires the shard (no further inserts can land in a
            # half-flushed buffer) and close() stays idempotent
            self._closed = True
            self._f.close()

    @property
    def closed(self) -> bool:
        return self._closed

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        try:
            self.close()
        except Exception:
            # the body's exception is the one the caller must see; a
            # flush failure on the way out must not mask it (it is
            # ordinarily a symptom of the same underlying I/O error)
            if exc_type is None:
                raise
        return False

    # -- crash recovery ----------------------------------------------------
    def _prepare_for_append(self) -> int:
        """Scan for the end of the last complete tuple, registering keys
        for dedup (shard.cc:175-206)."""
        if not os.path.exists(self.path):
            open(self.path, "wb").close()
            return 0
        last_ok = 0
        with open(self.path, "rb") as f:
            while True:
                hdr = f.read(8)
                if len(hdr) < 8:
                    break
                klen = _SZ.unpack(hdr)[0]
                key = f.read(klen)
                hdr2 = f.read(8)
                if len(key) < klen or len(hdr2) < 8:
                    break
                vlen = _SZ.unpack(hdr2)[0]
                val = f.read(vlen)
                if len(val) < vlen:
                    break
                self._keys.add(key)
                last_ok = f.tell()
        return last_ok
