"""Host input pipeline: shard reading + background prefetch.

The reference overlaps I/O and compute with a per-executor prefetch
thread and a double-buffered ParserLayer handoff (worker.cc:127-177,
base_layer.h:510-560).  Here a background thread keeps a bounded queue
of ready batches ahead of the device; normalization happens *on device*
inside the jitted step, so host work is pure file I/O + batching.

Failure semantics (the hardening tier — see docs/FAULT_TOLERANCE.md):
a producer-thread exception is re-raised on the consumer side; a
producer that dies without signaling raises PrefetchError instead of
hanging the trainer (liveness is polled, never assumed); corrupt
records are quarantined — skipped and counted per pass in a shared
PipelineStats — rather than silently dropped or fatally raised.  The
`data.decode` / `data.prefetch` fault-injection sites (utils.faults)
make all three paths testable.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterator, List, Optional

import numpy as np

from ..utils.faults import CorruptRecord, maybe_fault
from .records import Record, record_has_image
from .shard import Shard


class PrefetchError(RuntimeError):
    """The prefetch producer died or stalled; the batch stream is
    broken (distinct from StopIteration = clean end of data)."""


class ProducerDied(Exception):
    """Internal signal from `poll_queue`: the producer thread exited
    without a sentinel reaching the consumer.  Callers translate it
    into their own terminal error (PrefetchError / FeedError) after
    checking for a captured producer exception."""


def poll_queue(q: queue.Queue, thread: threading.Thread, poll: float,
               stall: Optional[float], what: str = "prefetch"):
    """Blocking `q.get` with producer-liveness checks — the shared
    consumer side of every bounded producer/consumer handoff in the
    data plane (Prefetcher at batch granularity, data.feed.DeviceFeeder
    at chunk granularity).  Returns the next item; raises ProducerDied
    when the producer thread is gone and the queue is empty (with a
    drain-race re-check, since the sentinel may land between the
    timeout and the liveness probe), or PrefetchError after `stall`
    seconds without an item from a live-but-stuck producer."""
    deadline = (time.monotonic() + stall if stall is not None else None)
    while True:
        try:
            return q.get(timeout=poll)
        except queue.Empty:
            if not thread.is_alive():
                try:
                    return q.get_nowait()
                except queue.Empty:
                    raise ProducerDied
            if deadline is not None and time.monotonic() > deadline:
                raise PrefetchError(
                    f"{what} stalled: no item for {stall:.1f}s "
                    f"(producer alive but stuck — slow or hung "
                    f"source)")


@dataclass
class PipelineStats:
    """Shared counters between a batch source, its Prefetcher, and the
    consumer (trainer/supervisor) — chiefly the quarantine tally of
    corrupt records skipped instead of crashing the run."""
    quarantined: int = 0        # total corrupt records skipped
    quarantined_pass: int = 0   # within the current read pass
    passes: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False)

    def quarantine(self, n: int = 1) -> None:
        with self._lock:
            self.quarantined += n
            self.quarantined_pass += n

    def end_pass(self) -> int:
        """Close the current pass; returns (and resets) its quarantine
        count so sources can log once per pass."""
        with self._lock:
            n = self.quarantined_pass
            self.quarantined_pass = 0
            self.passes += 1
            return n

    def register_into(self, registry,
                      prefix: str = "singa_data") -> None:
        """Register these counters into an `obs.MetricsRegistry` as a
        pull-time collector — additive; existing semantics untouched."""
        from ..obs.metrics import Sample

        def collect():
            with self._lock:
                q, p = self.quarantined, self.passes
            return [
                Sample(f"{prefix}_quarantined_total", "counter",
                       "corrupt records skipped instead of crashing",
                       float(q)),
                Sample(f"{prefix}_passes_total", "counter",
                       "completed read passes over the source",
                       float(p)),
            ]

        registry.register_collector(collect)


def _decode_batch(vals: List[bytes], data_layer: str) -> Dict:
    """Decode a batch of serialized records — native C++ batch decoder
    when built (one memcpy per record), Python codec otherwise.  Callers
    filter image-less records before batching (record_has_image), so
    every val here contributes one batch row."""
    from . import native
    fast = native.decode_image_batch(vals)
    if fast is not None:
        pixels, labels = fast
        return {data_layer: {"pixel": pixels, "label": labels}}
    pixels, labels = [], []
    for val in vals:
        rec = Record.decode(val)
        pixels.append(rec.image.pixels_array())
        labels.append(rec.image.label)
    return {data_layer: {"pixel": np.stack(pixels),
                         "label": np.asarray(labels, np.int32)}}


def _quarantine_pass_report(source: str, stats: PipelineStats) -> None:
    n = stats.end_pass()
    if n:
        import sys
        print(f"warning: quarantined {n} corrupt record(s) in one pass "
              f"over {source} ({stats.quarantined} total)",
              file=sys.stderr)


def lmdb_batches(path: str, batchsize: int, data_layer: str = "data",
                 loop: bool = True, random_skip: int = 0,
                 seed: int = 0,
                 stats: Optional[PipelineStats] = None) -> Iterator[Dict]:
    """Batches straight from an LMDB environment of caffe Datum values
    (kLMDBData semantics, layer.cc:237-328): B-tree key order, Datum →
    Record conversion, same random_skip contract as shard_batches.
    For production throughput convert once with
    `tools/loader.py convert-lmdb` (shards get the native batch
    decoder); this path exists so reference configs pointing at an
    LMDB env train unchanged."""
    from .lmdb_reader import iter_lmdb
    from .records import Datum, record_from_datum

    stats = stats if stats is not None else PipelineStats()
    rng = np.random.default_rng(seed)
    # [0, random_skip-1], the reference's rand() % random_skip_
    # contract (layer.cc:651-653)
    skip = rng.integers(0, random_skip) if random_skip else 0
    # partial batches CARRY across epoch boundaries in loop mode (an
    # env smaller than the batch still fills batches over several
    # passes instead of silently dropping its records every epoch)
    vals: List[bytes] = []
    warned = [False]
    while True:
        usable = skipped = seen = 0
        for _, raw in iter_lmdb(path):
            seen += 1
            if skip > 0:
                skip -= 1
                skipped += 1
                continue
            try:
                maybe_fault("data.decode")
                d = Datum.decode(raw)
            except (ValueError, IndexError, CorruptRecord):
                # a single rotten Datum must not kill a million-record
                # pass; quarantine it (counted, reported per pass)
                stats.quarantine()
                continue
            # NOT quarantined: a *valid* Datum this build cannot use
            # (e.g. JPEG-encoded) is a config error and fails loud
            rec = record_from_datum(d)
            if rec.image is None or not (rec.image.pixel
                                         or rec.image.data):
                continue
            usable += 1
            vals.append(rec.encode())
            if len(vals) == batchsize:
                yield _decode_batch(vals, data_layer)
                vals = []
        _quarantine_pass_report(f"LMDB environment {path!r}", stats)
        _pass_end_guard(f"LMDB environment {path!r}", loop, usable,
                        skipped, seen, warned)
        if not loop:
            if vals:
                yield _decode_batch(vals, data_layer)
            return


def _pass_end_guard(source: str, loop: bool, usable: int, skipped: int,
                    seen: int, warned_skip: List[bool]) -> None:
    """Shared loop-mode sanity for a completed read pass (lmdb_batches
    and shard_batches both): a pass with records but no skips and no
    usable rows means an empty/imageless source — raise instead of
    spinning hot forever; a pass consumed ENTIRELY by random_skip is
    legal (the leftover skip carries) but a skip that large is almost
    always a config mistake, so warn ONCE about the silent extra
    passes.  A mixed pass (some skips, rest imageless) neither warns
    nor raises yet — once the skip budget exhausts, a later pass hits
    the raise with the accurate message."""
    if not loop:
        return
    if not usable and not skipped:
        raise ValueError(
            f"{source} contains no usable image records")
    if not usable and skipped == seen and seen and not warned_skip[0]:
        warned_skip[0] = True
        import sys
        print(f"warning: random_skip consumed an entire pass over "
              f"{source} ({skipped} records) — a skip larger than the "
              f"dataset costs a full extra scan per multiple before "
              f"the first batch", file=sys.stderr)


def shard_batches(folder: str, batchsize: int, data_layer: str = "data",
                  loop: bool = True, random_skip: int = 0,
                  seed: int = 0,
                  stats: Optional[PipelineStats] = None) -> Iterator[Dict]:
    """Batches from a shard folder of Record tuples, in file order
    (ShardData semantics, layer.cc:646-673 incl. random_skip).  Records
    whose bytes fail the tag-walk (torn mid-file writes the append-scan
    cannot truncate) are quarantined into `stats`, not raised — the
    shard's own torn-TAIL recovery already ran at open."""
    stats = stats if stats is not None else PipelineStats()
    rng = np.random.default_rng(seed)
    # [0, random_skip-1], the reference's rand() % random_skip_
    # contract (layer.cc:651-653)
    skip = rng.integers(0, random_skip) if random_skip else 0
    # partial batches carry across epoch boundaries in loop mode (a
    # shard smaller than the batch still fills batches over passes)
    vals: List[bytes] = []
    warned = [False]
    while True:
        shard = Shard(folder, Shard.KREAD)
        usable = skipped = seen = 0
        try:
            for i, (_, val) in enumerate(shard):
                seen += 1
                if skip > 0:
                    skip -= 1
                    skipped += 1
                    continue
                try:
                    maybe_fault("data.decode")
                    has_image = record_has_image(val)
                except (ValueError, CorruptRecord):
                    stats.quarantine()
                    continue
                if not has_image:
                    continue   # type-only records contribute no batch row
                usable += 1
                vals.append(val)
                if len(vals) == batchsize:
                    yield _decode_batch(vals, data_layer)
                    vals = []
        finally:
            # an abandoned generator (consumer dropped mid-pass) must
            # not leak the file handle
            shard.close()
        _quarantine_pass_report(f"shard folder {folder!r}", stats)
        _pass_end_guard(f"shard folder {folder!r}", loop, usable,
                        skipped, seen, warned)
        if not loop:
            if vals:  # final partial batch
                yield _decode_batch(vals, data_layer)
            return


class Prefetcher:
    """Bounded background prefetch (the reference's prefetch thread,
    worker.cc:163-177, generalized to a queue depth).

    Failure contract:
    - an exception in the producer thread is re-raised on the consumer
      side (a corrupt source must not look like a clean end of data);
    - the consumer polls with a timeout and checks producer liveness,
      so a producer that died without signaling raises PrefetchError
      instead of hanging the trainer forever; `stall_timeout` bounds
      the wait on a live-but-stuck producer (None = unbounded);
    - `close()` (also driven by `__del__` and iterator drop) stops the
      producer and drains the queue so the daemon thread exits instead
      of blocking on a full queue for the life of the process;
    - an injected CorruptRecord at the `data.decode` site is
      quarantined into `stats` (the batch stream continues, in order).
    """

    _END = object()

    def __init__(self, it: Iterator, depth: int = 2,
                 poll_timeout: float = 0.5,
                 stall_timeout: Optional[float] = None,
                 stats: Optional[PipelineStats] = None):
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._it = it
        self._err: Optional[BaseException] = None
        self._done = False
        self._poll = max(poll_timeout, 0.01)
        self._stall = stall_timeout
        self.stats = stats if stats is not None else PipelineStats()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _put(self, item) -> bool:
        """Blocking put that still honors close(): gives up when the
        consumer asked us to stop (the queue may be full forever)."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=self._poll)
                return True
            except queue.Full:
                continue
        return False

    def _run(self):
        try:
            while not self._stop.is_set():
                try:
                    maybe_fault("data.decode")
                except CorruptRecord:
                    # the bad record is consumed and counted; the next
                    # good one takes its slot, order preserved
                    self.stats.quarantine()
                    continue
                try:
                    item = next(self._it)
                except StopIteration:
                    break
                if not self._put(item):
                    return   # closed: no sentinel needed, nobody reads
        except BaseException as e:  # re-raised on the consumer thread —
            self._err = e           # a corrupt source must not look like
        finally:                    # a clean end of data
            self._put(self._END)

    def __iter__(self):
        return self

    def __next__(self):
        if self._done:           # terminal: never block on the dead queue
            if self._err is not None:
                raise self._err
            raise StopIteration
        maybe_fault("data.prefetch")
        try:
            item = poll_queue(self._q, self._thread, self._poll,
                              self._stall, what="prefetch")
        except ProducerDied:
            self._done = True
            if self._err is not None:
                raise self._err
            raise PrefetchError(
                "prefetch producer thread died without "
                "signaling end of data")
        if item is self._END:
            self._done = True
            return self.__next__()
        return item

    def close(self) -> None:
        """Stop the producer and release its thread.  Safe to call
        multiple times and from __del__."""
        self._stop.set()
        # unblock a producer waiting on a full queue
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        t = getattr(self, "_thread", None)
        if t is not None and t.is_alive():
            t.join(timeout=2.0)

    def __del__(self):  # pragma: no cover — GC timing
        try:
            self.close()
        except Exception:
            pass


def prefetch(it: Iterator, depth: int = 2,
             stats: Optional[PipelineStats] = None,
             stall_timeout: Optional[float] = None) -> Prefetcher:
    return Prefetcher(it, depth, stats=stats, stall_timeout=stall_timeout)
