"""Host input pipeline: shard reading + background prefetch.

The reference overlaps I/O and compute with a per-executor prefetch
thread and a double-buffered ParserLayer handoff (worker.cc:127-177,
base_layer.h:510-560).  Here a background thread keeps a bounded queue
of ready batches ahead of the device; normalization happens *on device*
inside the jitted step, so host work is pure file I/O + batching.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Dict, Iterator, List, Optional

import numpy as np

from .records import Record, record_has_image
from .shard import Shard


def _decode_batch(vals: List[bytes], data_layer: str) -> Dict:
    """Decode a batch of serialized records — native C++ batch decoder
    when built (one memcpy per record), Python codec otherwise.  Callers
    filter image-less records before batching (record_has_image), so
    every val here contributes one batch row."""
    from . import native
    fast = native.decode_image_batch(vals)
    if fast is not None:
        pixels, labels = fast
        return {data_layer: {"pixel": pixels, "label": labels}}
    pixels, labels = [], []
    for val in vals:
        rec = Record.decode(val)
        pixels.append(rec.image.pixels_array())
        labels.append(rec.image.label)
    return {data_layer: {"pixel": np.stack(pixels),
                         "label": np.asarray(labels, np.int32)}}


def lmdb_batches(path: str, batchsize: int, data_layer: str = "data",
                 loop: bool = True, random_skip: int = 0,
                 seed: int = 0) -> Iterator[Dict]:
    """Batches straight from an LMDB environment of caffe Datum values
    (kLMDBData semantics, layer.cc:237-328): B-tree key order, Datum →
    Record conversion, same random_skip contract as shard_batches.
    For production throughput convert once with
    `tools/loader.py convert-lmdb` (shards get the native batch
    decoder); this path exists so reference configs pointing at an
    LMDB env train unchanged."""
    from .lmdb_reader import iter_lmdb
    from .records import Datum, record_from_datum

    rng = np.random.default_rng(seed)
    # [0, random_skip-1], the reference's rand() % random_skip_
    # contract (layer.cc:651-653)
    skip = rng.integers(0, random_skip) if random_skip else 0
    # partial batches CARRY across epoch boundaries in loop mode (an
    # env smaller than the batch still fills batches over several
    # passes instead of silently dropping its records every epoch)
    vals: List[bytes] = []
    warned = [False]
    while True:
        usable = skipped = seen = 0
        for _, raw in iter_lmdb(path):
            seen += 1
            if skip > 0:
                skip -= 1
                skipped += 1
                continue
            rec = record_from_datum(Datum.decode(raw))
            if rec.image is None or not (rec.image.pixel
                                         or rec.image.data):
                continue
            usable += 1
            vals.append(rec.encode())
            if len(vals) == batchsize:
                yield _decode_batch(vals, data_layer)
                vals = []
        _pass_end_guard(f"LMDB environment {path!r}", loop, usable,
                        skipped, seen, warned)
        if not loop:
            if vals:
                yield _decode_batch(vals, data_layer)
            return


def _pass_end_guard(source: str, loop: bool, usable: int, skipped: int,
                    seen: int, warned_skip: List[bool]) -> None:
    """Shared loop-mode sanity for a completed read pass (lmdb_batches
    and shard_batches both): a pass with records but no skips and no
    usable rows means an empty/imageless source — raise instead of
    spinning hot forever; a pass consumed ENTIRELY by random_skip is
    legal (the leftover skip carries) but a skip that large is almost
    always a config mistake, so warn ONCE about the silent extra
    passes.  A mixed pass (some skips, rest imageless) neither warns
    nor raises yet — once the skip budget exhausts, a later pass hits
    the raise with the accurate message."""
    if not loop:
        return
    if not usable and not skipped:
        raise ValueError(
            f"{source} contains no usable image records")
    if not usable and skipped == seen and seen and not warned_skip[0]:
        warned_skip[0] = True
        import sys
        print(f"warning: random_skip consumed an entire pass over "
              f"{source} ({skipped} records) — a skip larger than the "
              f"dataset costs a full extra scan per multiple before "
              f"the first batch", file=sys.stderr)


def shard_batches(folder: str, batchsize: int, data_layer: str = "data",
                  loop: bool = True, random_skip: int = 0,
                  seed: int = 0) -> Iterator[Dict]:
    """Batches from a shard folder of Record tuples, in file order
    (ShardData semantics, layer.cc:646-673 incl. random_skip)."""
    rng = np.random.default_rng(seed)
    # [0, random_skip-1], the reference's rand() % random_skip_
    # contract (layer.cc:651-653)
    skip = rng.integers(0, random_skip) if random_skip else 0
    # partial batches carry across epoch boundaries in loop mode (a
    # shard smaller than the batch still fills batches over passes)
    vals: List[bytes] = []
    warned = [False]
    while True:
        shard = Shard(folder, Shard.KREAD)
        usable = skipped = seen = 0
        for i, (_, val) in enumerate(shard):
            seen += 1
            if skip > 0:
                skip -= 1
                skipped += 1
                continue
            if not record_has_image(val):
                continue   # type-only records contribute no batch row
            usable += 1
            vals.append(val)
            if len(vals) == batchsize:
                yield _decode_batch(vals, data_layer)
                vals = []
        shard.close()
        _pass_end_guard(f"shard folder {folder!r}", loop, usable,
                        skipped, seen, warned)
        if not loop:
            if vals:  # final partial batch
                yield _decode_batch(vals, data_layer)
            return


class Prefetcher:
    """Bounded background prefetch (the reference's prefetch thread,
    worker.cc:163-177, generalized to a queue depth)."""

    _END = object()

    def __init__(self, it: Iterator, depth: int = 2):
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._it = it
        self._err: Optional[BaseException] = None
        self._done = False
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        try:
            for item in self._it:
                self._q.put(item)
        except BaseException as e:  # re-raised on the consumer thread —
            self._err = e           # a corrupt record must not look like
        finally:                    # a clean end of data
            self._q.put(self._END)

    def __iter__(self):
        return self

    def __next__(self):
        if self._done:           # terminal: never block on the dead queue
            if self._err is not None:
                raise self._err
            raise StopIteration
        item = self._q.get()
        if item is self._END:
            self._done = True
            return self.__next__()
        return item


def prefetch(it: Iterator, depth: int = 2) -> Iterator:
    return Prefetcher(it, depth)
