"""Input-shape discovery for data layers.

The reference learns record geometry from the data itself: the data/parser
layers read the first record during Setup and size their blobs from its
shape (layer.cc:388-392 MnistImageLayer reads a sample record;
layer.cc:576-585 RGBImageLayer sizes from `sample.shape()` or the mean
record).  Same contract here: when the configured source exists locally,
peek its first usable record; when it does not (the zero-egress synthetic
path), infer the geometry the parser expects from the net itself —
kMnistImage parses 28x28 grayscale records, kRGBImage parses (3, S, S)
records whose S the crop geometry implies.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Tuple


def shard_source_exists(path: Optional[str]) -> bool:
    """Whether a shard folder is a live local source — the single
    predicate both shape discovery and data serving use, so the net is
    always built for the geometry that will actually be served."""
    return bool(path) and os.path.isfile(os.path.join(path, "shard.dat"))


def lmdb_source_exists(path: Optional[str]) -> bool:
    return bool(path) and (os.path.isfile(path) or os.path.isfile(
        os.path.join(path, "data.mdb")))


def _peek_shard(path: str) -> Optional[Tuple[int, ...]]:
    """Shape of the first usable image record in a shard folder."""
    from .records import Record, record_has_image
    from .shard import Shard

    shard = Shard(path, Shard.KREAD)
    try:
        for _, val in shard:
            if not record_has_image(val):
                continue
            rec = Record.decode(val)
            if rec.image and rec.image.shape:
                return tuple(rec.image.shape)
    finally:
        shard.close()
    return None


def _peek_lmdb(path: str) -> Optional[Tuple[int, ...]]:
    """Shape of the first usable Datum in an LMDB environment."""
    from .lmdb_reader import iter_lmdb
    from .records import Datum, record_from_datum

    for _, raw in iter_lmdb(path):
        rec = record_from_datum(Datum.decode(raw))
        if rec.image and rec.image.shape and (rec.image.pixel
                                              or rec.image.data):
            return tuple(rec.image.shape)
    return None


def _infer_from_parsers(layers, data_name: str) -> Tuple[int, ...]:
    """Record geometry implied by the parsers consuming a data layer.

    kMnistImage → (28, 28): the MNIST record layout the parser's
    normalization contract assumes (layer.cc:380-473).  kRGBImage →
    (3, S, S): when the parser crops, the record must be at least
    cropsize — use the classic dataset margins (CIFAR crops 28 from
    32-pixel records, ILSVRC crops 227 from 256), giving the random-crop
    path real freedom; uncropped RGB defaults to CIFAR's 32.  A data
    layer with no image parser (e.g. feeding kRBM via kMnistImage
    upstream or raw) falls back to MNIST geometry.
    """
    for layer in layers:
        if data_name not in (layer.srclayers or []):
            continue
        if layer.type == "kMnistImage":
            return (28, 28)
        if layer.type == "kRGBImage":
            p = layer.rgbimage_param
            cs = p.cropsize if p else 0
            if not cs:
                return (3, 32, 32)
            margin = 29 if cs >= 100 else 4
            return (3, cs + margin, cs + margin)
    return (28, 28)


def discover_input_shapes(model_cfg, force_synthetic: bool = False
                          ) -> Dict[str, Dict[str, tuple]]:
    """Per-data-layer sample shapes for NeuralNet construction.

    Returns {data_layer_name: {"pixel": shape, "label": ()}} for every
    kShardData/kLMDBData layer and {"input"/"target"} for kSequenceData.
    Real sources win (the record IS the schema); synthetic inference is
    the fallback, so a conf pointing at a live shard trains at the
    shard's true geometry even if it differs from the dataset's classic
    one.
    """
    shapes: Dict[str, Dict[str, tuple]] = {}
    layers = model_cfg.neuralnet.layer if model_cfg.neuralnet else []
    for layer in layers:
        if layer.type in ("kShardData", "kLMDBData"):
            pix = None
            path = layer.data_param.path if layer.data_param else None
            live = (not force_synthetic and
                    (shard_source_exists(path)
                     if layer.type == "kShardData"
                     else lmdb_source_exists(path)))
            if live:
                # a live source will be SERVED (resolve_data_source
                # uses the same predicates) — a peek failure must fail
                # loudly here, not guess a geometry the real records
                # won't match at an opaque jit shape error later.
                # Reader errors (LMDBFormatError, ShardError, corrupt
                # Record ValueError) propagate unchanged: they carry
                # the fail-loud contract's specific diagnosis.
                pix = (_peek_shard(path)
                       if layer.type == "kShardData"
                       else _peek_lmdb(path))
                if pix is None:
                    raise ValueError(
                        f"data layer {layer.name!r}: source {path!r} "
                        f"contains no usable image records")
            else:
                pix = _infer_from_parsers(layers, layer.name)
            shapes.setdefault(layer.name, {"pixel": tuple(pix),
                                           "label": ()})
        elif layer.type == "kSequenceData" and layer.seqdata_param:
            s = layer.seqdata_param.seq_len
            shapes.setdefault(layer.name, {"input": (s,),
                                           "target": (s,)})
    return shapes
