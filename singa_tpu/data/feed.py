"""Overlapped host→device feed stage: async chunk staging for the
fused train loop.

The reference hides data cost by running prefetch threads beside the
Executor compute (worker.cc:98-106, 163-177); our fused lax.scan train
loop removed per-step dispatch but left the HOST serial — Trainer.run
pulled and stacked every chunk on the critical path, then blocked on
`jax.device_get(metrics)` before touching the next batch.  This module
is the missing pipeline stage between `Prefetcher` (batch-granular,
pure host I/O) and the train loop (chunk-granular, device-resident):

    source → Prefetcher → DeviceFeeder → train_steps scan
             (batches)    (staged, device-placed chunks)

`ChunkStager` stacks a list of host batch trees into REUSABLE numpy
staging buffers (no per-chunk allocation) and places the stacked chunk
on device — under a mesh, with the batch-dim `NamedSharding` the
compiled step expects (parallel.partition.place_chunk), so the input
lands pre-sharded instead of on the default device.  `DeviceFeeder`
runs a stager on a background thread over a deterministic chunk *plan*
(the exact (start_step, length) sequence the train loop will consume,
cut at the same cadence boundaries), keeping `depth` staged chunks
ahead: chunk k+1 is already on device while chunk k's scan runs.

Failure contract (mirrors Prefetcher, docs/FAULT_TOLERANCE.md): a
producer-thread exception re-raises on `get()` — including injected
faults at the new `feed.stage` site, so the Supervisor's
restore-and-replay covers the async path; a producer that dies without
signaling raises `FeedError` instead of hanging; `close()` stops the
thread WITHOUT closing the upstream iterator (its owner — e.g. the
Supervisor, which rebuilds and fast-forwards it on restart — manages
that lifetime).  Determinism: the feeder consumes exactly one batch
per step in order, so the Supervisor's fast-forward-by-step contract
is unchanged.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterable, Iterator, List, NamedTuple, Optional, Tuple

import numpy as np

from .. import obs
from ..utils.faults import maybe_fault
from .pipeline import PrefetchError, ProducerDied, poll_queue


class FeedError(PrefetchError):
    """The feed producer died, stalled, or delivered a chunk that does
    not match the consumer's plan (distinct from StopIteration = the
    plan — or the upstream data — ran out cleanly)."""


class FeedChunk(NamedTuple):
    """One staged chunk: `batches` carries a leading `length` step axis
    on every leaf and is already device-placed (sharded under a mesh)."""
    start: int
    length: int
    batches: Any


#: XLA host-buffer zero-copy needs this alignment; staging buffers are
#: allocated to deliberately MISS it (see staging_buffer).
_XLA_HOST_ALIGN = 64


def staging_buffer(shape: Tuple[int, ...], dtype) -> np.ndarray:
    """An uninitialized array whose data pointer is itemsize-aligned
    but deliberately NOT 64-byte aligned.

    Why: XLA's CPU client zero-copy ALIASES a sufficiently aligned host
    numpy buffer on device_put (verified on this runtime: aliasing iff
    addr % 64 == 0, and np.empty hits that alignment at the
    allocator's whim) — so a reused staging buffer would silently
    corrupt a previously "placed" chunk that an in-flight scan still
    reads.  A misaligned source forces the copy path on every backend,
    which is exactly what buffer reuse needs; the byte offset costs
    nothing measurable on the staging memcpy."""
    dt = np.dtype(dtype)
    nbytes = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
    # offset ≡ itemsize (mod 64): aligned for numpy element access,
    # misaligned for XLA's zero-copy check (itemsize < 64 always here)
    want = dt.itemsize % _XLA_HOST_ALIGN or _XLA_HOST_ALIGN
    raw = np.empty(nbytes + 2 * _XLA_HOST_ALIGN, np.uint8)
    off = (want - raw.ctypes.data) % _XLA_HOST_ALIGN
    buf = raw[off:off + nbytes].view(dt).reshape(shape)
    assert buf.ctypes.data % _XLA_HOST_ALIGN != 0 or nbytes == 0
    return buf


class ChunkStager:
    """Stacks host batches into reusable staging buffers and places the
    chunk on device.

    `place(stacked_tree)` does the device placement — pass the
    trainer's sharded helper so batches land with the batch-dim
    NamedSharding; defaults to a plain `jax.device_put`.  `capacity`
    pre-sizes the leading axis (the loop's scan_chunk); shorter chunks
    reuse a view of the same buffers, so steady state allocates
    nothing per chunk.

    Buffer-reuse safety, in two layers.  (1) Buffers come from
    `staging_buffer` (deliberately misaligned, so no zero-copy path can
    alias them — the placed chunk is always a COPY).  (2) A buffer set
    is only overwritten after the transfer staged FROM IT has
    completed.  With `rotate=1` (the synchronous loop) that means
    blocking right after `place` — the stream is idle there, so the
    block is just the transfer.  The DeviceFeeder passes `rotate =
    depth + 2` buffer sets instead: the chunk is handed over
    IMMEDIATELY after `place`, and the block moves to the next visit of
    the same set, a full rotation later — by which point the consumer
    has long dispatched (and the drain ring synced past) that chunk.
    Without the rotation, a single-stream runtime (CPU PJRT enqueues
    host-to-device copies behind queued computations) would stall the
    producer a whole chunk-compute per stage.
    """

    def __init__(self, place: Optional[Callable[[Any], Any]] = None,
                 capacity: int = 0, rotate: int = 1):
        self._place = place
        self._capacity = max(int(capacity), 0)
        self._rotate = max(int(rotate), 1)
        self._sets: List[Optional[List[np.ndarray]]] = \
            [None] * self._rotate
        self._inflight: List[Any] = [None] * self._rotate
        self._turn = 0
        self._treedef = None

    def _alloc(self, rows: List[Any], n: int) -> List[np.ndarray]:
        import jax
        cap = max(self._capacity, n)
        return [
            staging_buffer((cap,) + np.shape(leaf),
                           # canonicalize like jnp.asarray so the staged
                           # chunk is bit-identical to the old jnp.stack
                           # path (float64 host leaves become float32
                           # under the default x64-disabled config)
                           jax.dtypes.canonicalize_dtype(
                               np.asarray(leaf).dtype))
            for leaf in rows]

    def stage(self, batches: List[Any]) -> Any:
        """Stack `batches` (a list of pytrees with identical structure)
        along a new leading axis and place the result on device."""
        import jax

        fault = maybe_fault("feed.stage")
        if fault == "torn":
            # torn has no meaning for an in-memory stage (nothing is
            # half-written anywhere durable); treat as a no-op
            fault = None
        n = len(batches)
        if n == 0:
            raise ValueError("cannot stage an empty chunk")
        flat0, treedef = jax.tree_util.tree_flatten(batches[0])
        rows = [flat0] + [treedef.flatten_up_to(b) for b in batches[1:]]
        if self._treedef is None or treedef != self._treedef:
            self._treedef = treedef
            self._sets = [None] * self._rotate
            self._inflight = [None] * self._rotate
        i = self._turn
        self._turn = (i + 1) % self._rotate
        bufs = self._sets[i]
        if (bufs is None or n > bufs[0].shape[0]
                or any(b.shape[1:] != np.shape(l)
                       for b, l in zip(bufs, flat0))):
            bufs = self._sets[i] = self._alloc(flat0, n)
            self._inflight[i] = None
        if self._inflight[i] is not None:
            # the transfer staged from this set a rotation ago must be
            # done before its buffers are overwritten
            jax.block_until_ready(self._inflight[i])
            self._inflight[i] = None
        for j, buf in enumerate(bufs):
            for k, row in enumerate(rows):
                # same-kind cast copy into the staging row (device_get
                # happens here implicitly if a caller hands us device
                # arrays — supported, just not the fast path)
                np.copyto(buf[k], np.asarray(row[j]))
        stacked = jax.tree_util.tree_unflatten(
            treedef, [buf[:n] for buf in bufs])
        placed = (self._place(stacked) if self._place is not None
                  else jax.device_put(stacked))
        if self._rotate == 1:
            # synchronous caller: safe (and cheap — idle stream) to
            # wait for the transfer here
            jax.block_until_ready(placed)
        else:
            self._inflight[i] = placed
        return placed


class DeviceFeeder:
    """Background staging thread: stages chunks of an iterator per a
    deterministic `plan` and hands them over a bounded queue.

    `plan` is an iterable of (start_step, length) descriptors — the
    SAME sequence the consumer computes (Trainer._chunk_plan), so the
    feeder's pre-pulls line up exactly with the loop's cadence cuts and
    with the Supervisor's one-batch-per-step fast-forward.  `get()`
    blocks for the next chunk with producer-liveness polling; after the
    plan is exhausted it raises StopIteration.

    `pull_seconds` / `stage_seconds` accumulate producer-thread time
    split between waiting on the upstream iterator and stack+device_put
    work — the trainer samples `stage_seconds` for the step report's
    `stage` phase (off the critical path by construction; the consumer
    only ever blocks in `get`, reported as `wait`).
    """

    _END = object()

    def __init__(self, it: Iterator, plan: Iterable[Tuple[int, int]],
                 place: Optional[Callable[[Any], Any]] = None,
                 depth: int = 2, capacity: int = 0,
                 poll_timeout: float = 0.5,
                 stall_timeout: Optional[float] = None):
        self._it = it
        self._plan = iter(plan)
        # depth+2 rotating buffer sets: <= depth chunks queued, one in
        # the consumer's hands, one being staged — the set revisited
        # next has always been handed over, so staging never blocks on
        # a live transfer (see ChunkStager)
        self._stager = ChunkStager(place, capacity=capacity,
                                   rotate=max(depth, 1) + 2)
        self._q: queue.Queue = queue.Queue(maxsize=max(depth, 1))
        self._err: Optional[BaseException] = None
        self._done = False
        self._poll = max(poll_timeout, 0.01)
        self._stall = stall_timeout
        self.pull_seconds = 0.0
        self.stage_seconds = 0.0
        self.chunks_staged = 0
        # the consumer constructs the feeder inside its recovery span
        # (Supervisor attempt); capture that correlation id HERE so
        # producer-thread spans carry it — thread-local span stacks
        # don't cross the staging thread
        self._corr = obs.current_corr()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    # -- producer ----------------------------------------------------------
    def _put(self, item) -> bool:
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=self._poll)
                return True
            except queue.Full:
                continue
        return False

    def _run(self):
        try:
            for start, n in self._plan:
                if self._stop.is_set():
                    return
                t0 = time.perf_counter()
                with obs.span("feeder.pull", corr=self._corr,
                              start=start, steps=n):
                    batches = []
                    for _ in range(n):
                        batches.append(next(self._it))
                t1 = time.perf_counter()
                with obs.span("feeder.stage", corr=self._corr,
                              start=start, steps=n):
                    placed = self._stager.stage(batches)
                t2 = time.perf_counter()
                self.pull_seconds += t1 - t0
                self.stage_seconds += t2 - t1
                self.chunks_staged += 1
                if not self._put(FeedChunk(start, n, placed)):
                    return   # closed: nobody reads, no sentinel needed
        except BaseException as e:    # re-raised on the consumer thread
            self._err = e             # (incl. injected feed.stage faults
        finally:                      # and upstream StopIteration)
            self._put(self._END)

    # -- consumer ----------------------------------------------------------
    def get(self) -> FeedChunk:
        """Next staged chunk; blocks with liveness polling.  Raises the
        producer's error, StopIteration after a clean end of plan, or
        FeedError for a dead/stalled producer."""
        if self._done:
            if self._err is not None:
                raise self._err
            raise StopIteration
        try:
            item = poll_queue(self._q, self._thread, self._poll,
                              self._stall, what="feed")
        except ProducerDied:
            self._done = True
            if self._err is not None:
                raise self._err
            raise FeedError("feed producer thread died without "
                            "signaling end of plan")
        if item is self._END:
            self._done = True
            return self.get()
        return item

    def close(self) -> None:
        """Stop the producer and release its thread.  Idempotent; does
        NOT close the upstream iterator."""
        self._stop.set()
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        t = getattr(self, "_thread", None)
        if t is not None and t.is_alive():
            t.join(timeout=2.0)

    def __del__(self):  # pragma: no cover — GC timing
        try:
            self.close()
        except Exception:
            pass
