"""Minimal read-only LMDB environment walker.

The reference's kLMDBData layer walks a live caffe LMDB cursor
(layer.cc:237-328: mdb_env_open + mdb_cursor_get(MDB_NEXT) over Datum
values).  No liblmdb binding exists in this environment, so this module
reads the on-disk format directly: pick the live meta page (higher
txnid), then walk the main DB's B-tree in key order, following
overflow-page chains for large values (a 3KB caffe Datum overflows a
4KB page, so this path is the common case, not an edge).

Format facts (LMDB 0.9.x data format, version 1, little-endian,
64-bit writer — caffe's deployment target):
  * page header (16 bytes): pgno u64, pad u16, flags u16, lower u16,
    upper u16; for overflow pages the lower/upper union is a u32 page
    count.
  * meta page: header + { magic u32 = 0xBEEFC0DE, version u32,
    address u64, mapsize u64, dbs[2] of 48 bytes each (free DB, main
    DB), last_pg u64, txnid u64 }.
  * MDB_db (48 bytes): pad u32, flags u16, depth u16, branch_pages
    u64, leaf_pages u64, overflow_pages u64, entries u64, root u64.
  * branch/leaf pages: u16 node offsets (from page start) at +16,
    count = (lower - 16) / 2, sorted by key.
  * node: lo u16, hi u16, flags u16, ksize u16, key bytes, then for
    leaves data of size lo | hi << 16 (or, with flag F_BIGDATA, a u64
    overflow pgno); for branches the child pgno is
    lo | hi << 16 | flags << 32.

Unsupported (fail-loud): DUPSORT sub-databases (F_DUPDATA/F_SUBDATA
nodes, P_LEAF2 pages) — caffe image DBs are plain key->value.
"""

from __future__ import annotations

import os
import struct
from typing import Iterator, Tuple

MAGIC = 0xBEEFC0DE
P_BRANCH, P_LEAF, P_OVERFLOW, P_META, P_LEAF2 = 0x01, 0x02, 0x04, 0x08, 0x20
F_BIGDATA, F_SUBDATA, F_DUPDATA = 0x01, 0x02, 0x04
_INVALID_PGNO = 0xFFFFFFFFFFFFFFFF
_PAGE_SIZES = (4096, 8192, 16384, 32768, 65536, 512, 1024, 2048)


class LMDBFormatError(IOError):
    pass


def _data_path(path: str) -> str:
    if os.path.isdir(path):
        return os.path.join(path, "data.mdb")
    return path


def _page_hdr(buf: bytes, off: int):
    pgno, _, flags, lower, upper = struct.unpack_from("<QHHHH", buf, off)
    return pgno, flags, lower, upper


def _parse_meta(buf: bytes, off: int):
    """(txnid, depth, root) of the main DB from the meta at page `off`;
    None if the magic/version doesn't match."""
    magic, version = struct.unpack_from("<II", buf, off + 16)
    if magic != MAGIC or version not in (1, 999):
        return None
    main_db = off + 16 + 24 + 48          # dbs[1]
    flags, depth = struct.unpack_from("<HH", buf, main_db + 4)
    entries, root = struct.unpack_from("<QQ", buf, main_db + 32)
    (txnid,) = struct.unpack_from("<Q", buf, off + 16 + 128)
    return txnid, depth, root, entries, flags


def _detect_page_size(buf: bytes) -> int:
    # ps + 152 covers every field _parse_meta unpacks (txnid at
    # off+16+128, 8 bytes) — a file truncated inside the meta page must
    # surface as LMDBFormatError, not a raw struct.error
    for ps in _PAGE_SIZES:
        if len(buf) >= ps + 152 and _parse_meta(buf, ps) is not None:
            return ps
    raise LMDBFormatError("no LMDB meta page found at any standard "
                          "page size (is this really an LMDB file?)")


def _overflow_data(buf: bytes, pgno: int, ps: int, size: int) -> bytes:
    off = pgno * ps
    _, flags, _, _ = _page_hdr(buf, off)
    if not flags & P_OVERFLOW:
        raise LMDBFormatError(
            f"page {pgno} should be an overflow page (flags {flags:#x})")
    return bytes(buf[off + 16: off + 16 + size])


def _walk(buf: bytes, pgno: int, ps: int
          ) -> Iterator[Tuple[bytes, bytes]]:
    off = pgno * ps
    _, flags, lower, upper = _page_hdr(buf, off)
    if flags & P_LEAF2:
        raise LMDBFormatError("P_LEAF2 (DUPFIXED) pages are not "
                              "supported")
    nkeys = (lower - 16) >> 1
    ptrs = struct.unpack_from(f"<{nkeys}H", buf, off + 16)
    if flags & P_LEAF:
        for p in ptrs:
            node = off + p
            lo, hi, nflags, ksize = struct.unpack_from("<HHHH", buf, node)
            if nflags & (F_SUBDATA | F_DUPDATA):
                raise LMDBFormatError("DUPSORT sub-databases are not "
                                      "supported")
            key = bytes(buf[node + 8: node + 8 + ksize])
            dsize = lo | (hi << 16)
            dstart = node + 8 + ksize
            if nflags & F_BIGDATA:
                (opgno,) = struct.unpack_from("<Q", buf, dstart)
                yield key, _overflow_data(buf, opgno, ps, dsize)
            else:
                yield key, bytes(buf[dstart: dstart + dsize])
    elif flags & P_BRANCH:
        for p in ptrs:
            node = off + p
            lo, hi, nflags, _ = struct.unpack_from("<HHHH", buf, node)
            child = lo | (hi << 16) | (nflags << 32)
            yield from _walk(buf, child, ps)
    else:
        raise LMDBFormatError(f"page {pgno}: unexpected flags "
                              f"{flags:#x} in tree walk")


def iter_lmdb(path: str) -> Iterator[Tuple[bytes, bytes]]:
    """(key, value) pairs of the main DB in key order.  The file is
    mmapped, not slurped — real caffe envs run to tens of GB and the
    walk only touches live pages."""
    import mmap

    fp = _data_path(path)
    with open(fp, "rb") as f:
        size = os.fstat(f.fileno()).st_size
        if size < 512:
            raise LMDBFormatError(f"{fp}: too small to be an LMDB "
                                  f"environment ({size} bytes)")
        buf = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        try:
            ps = _detect_page_size(buf)
            metas = [m for m in (_parse_meta(buf, 0),
                                 _parse_meta(buf, ps))
                     if m is not None]
            if not metas:
                raise LMDBFormatError(f"{fp}: no valid meta page")
            txnid, depth, root, entries, flags = max(metas)
            if flags & 0x04:     # MDB_DUPSORT on the main DB
                raise LMDBFormatError("DUPSORT main DB is not "
                                      "supported")
            if root != _INVALID_PGNO and entries:
                yield from _walk(buf, root, ps)
        finally:
            buf.close()


def lmdb_entry_count(path: str) -> int:
    """md_entries of the live meta (no tree walk)."""
    fp = _data_path(path)
    with open(fp, "rb") as f:
        buf = f.read(128 * 1024)
    ps = _detect_page_size(buf)
    metas = [m for m in (_parse_meta(buf, 0), _parse_meta(buf, ps))
             if m is not None]
    return max(metas)[3] if metas else 0
