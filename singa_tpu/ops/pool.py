"""Pooling with the reference's caffe-style ceil-mode geometry.

Reference: layer.cc:476-540 — pooled = ceil((h - k)/s) + 1; AVE divides
by k*k regardless of window clipping; MAX backward routes gradient to
max positions (mshadow `unpool<red::maximum>`).  On TPU this is one
`lax.reduce_window` (XLA lowers to a fused windowed reduction); the
backward comes from autodiff, which reproduces unpool semantics.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
from jax import lax


def pooled_size(size: int, kernel: int, stride: int) -> int:
    """layer.cc:497-500: ceil((size - kernel)/stride) + 1."""
    return int(math.ceil((size - kernel) / stride)) + 1


def _ceil_pad(size: int, kernel: int, stride: int) -> int:
    out = pooled_size(size, kernel, stride)
    return max(0, (out - 1) * stride + kernel - size)


def _window(kernel, stride, ph, pw, layout):
    if layout == "NHWC":
        return ((1, kernel, kernel, 1), (1, stride, stride, 1),
                ((0, 0), (0, ph), (0, pw), (0, 0)))
    return ((1, 1, kernel, kernel), (1, 1, stride, stride),
            ((0, 0), (0, 0), (0, ph), (0, pw)))


def _spatial(x, layout):
    return (x.shape[2], x.shape[3]) if layout == "NCHW" else (
        x.shape[1], x.shape[2])


def max_pool2d(x: jnp.ndarray, kernel: int, stride: int,
               layout: str = "NCHW") -> jnp.ndarray:
    """Ceil-mode max pool; x (N, C, H, W) or (N, H, W, C) per layout."""
    h, w = _spatial(x, layout)
    ph, pw = _ceil_pad(h, kernel, stride), _ceil_pad(w, kernel, stride)
    dims, strides, pad = _window(kernel, stride, ph, pw, layout)
    # NOTE: init must be a weak-typed Python scalar — an Array init value
    # defeats reduce_window's autodiff rule.
    return lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, pad)


def avg_pool2d(x: jnp.ndarray, kernel: int, stride: int,
               layout: str = "NCHW") -> jnp.ndarray:
    """Ceil-mode average pool dividing by k*k always (layer.cc:513-515)."""
    h, w = _spatial(x, layout)
    ph, pw = _ceil_pad(h, kernel, stride), _ceil_pad(w, kernel, stride)
    dims, strides, pad = _window(kernel, stride, ph, pw, layout)
    s = lax.reduce_window(x.astype(jnp.float32), 0.0, lax.add, dims, strides,
                          pad)
    return (s * (1.0 / (kernel * kernel))).astype(x.dtype)
