"""Pooling with the reference's caffe-style ceil-mode geometry.

Reference: layer.cc:476-540 — pooled = ceil((h - k)/s) + 1; AVE divides
by k*k regardless of window clipping; MAX backward routes gradient to
max positions (mshadow `unpool<red::maximum>`).  On TPU this is one
`lax.reduce_window` (XLA lowers to a fused windowed reduction).

MAX backward: autodiff's select-and-scatter everywhere.  An
equality-mask vjp (`_max_pool_nhwc`, kept below as the exact-parity
form of mshadow's `unpool<red::maximum>`, tensor_expr_ext.h:148-163 —
tied positions each receive the window's full gradient) was measured
on chip in both tap-loop and phase-decomposed forms and LOST badly
(187-198ms vs 132ms AlexNet step): under XLA's batch-in-lanes
activation layouts, the strided/padded spatial shuffles it needs cost
far more than the 7.8ms the fused select-and-scatter takes.  It stays
available for semantics tests (ties), not for speed.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax


def pooled_size(size: int, kernel: int, stride: int) -> int:
    """layer.cc:497-500: ceil((size - kernel)/stride) + 1."""
    return int(math.ceil((size - kernel) / stride)) + 1


def _ceil_pad(size: int, kernel: int, stride: int) -> int:
    out = pooled_size(size, kernel, stride)
    return max(0, (out - 1) * stride + kernel - size)


def _window(kernel, stride, ph, pw, layout):
    if layout == "NHWC":
        return ((1, kernel, kernel, 1), (1, stride, stride, 1),
                ((0, 0), (0, ph), (0, pw), (0, 0)))
    return ((1, 1, kernel, kernel), (1, 1, stride, stride),
            ((0, 0), (0, 0), (0, ph), (0, pw)))


def _spatial(x, layout):
    return (x.shape[2], x.shape[3]) if layout == "NCHW" else (
        x.shape[1], x.shape[2])


def max_pool2d(x: jnp.ndarray, kernel: int, stride: int,
               layout: str = "NCHW") -> jnp.ndarray:
    """Ceil-mode max pool; x (N, C, H, W) or (N, H, W, C) per layout."""
    h, w = _spatial(x, layout)
    ph, pw = _ceil_pad(h, kernel, stride), _ceil_pad(w, kernel, stride)
    dims, strides, pad = _window(kernel, stride, ph, pw, layout)
    # NOTE: init must be a weak-typed Python scalar — an Array init value
    # defeats reduce_window's autodiff rule.
    return lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, pad)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _max_pool_nhwc(x, kernel, stride):
    return _max_pool_nhwc_fwd(x, kernel, stride)[0]


def _max_pool_nhwc_fwd(x, kernel, stride):
    h, w = x.shape[1], x.shape[2]
    ph, pw = _ceil_pad(h, kernel, stride), _ceil_pad(w, kernel, stride)
    dims, strides, pad = _window(kernel, stride, ph, pw, "NHWC")
    y = lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, pad)
    return y, (x, y)


def _max_pool_nhwc_bwd(kernel, stride, res, g):
    x, y = res
    n, h, w, c = x.shape
    oh_full, ow_full = y.shape[1], y.shape[2]
    zero = jnp.zeros((), g.dtype)
    yx = y.astype(x.dtype)
    if h % stride == 0 and w % stride == 0:
        # Phase decomposition: input position (pi + s·m) is covered only
        # by taps ki ≡ pi (mod s), so each of the s² input phases sums
        # ⌈k/s⌉² zero-padded output-space terms — all shapes output-
        # sized, no strided scatters — and one stack/reshape interleaves
        # the phases back.
        hp, wp = h // stride, w // stride
        rows = []
        for pi in range(stride):
            cols = []
            for pj in range(stride):
                xp = x[:, pi::stride, pj::stride, :]
                acc = jnp.zeros((n, hp, wp, c), g.dtype)
                for di in range((kernel - pi + stride - 1) // stride):
                    ki = pi + di * stride
                    oh = min(oh_full, (h - 1 - ki) // stride + 1)
                    for dj in range((kernel - pj + stride - 1) // stride):
                        kj = pj + dj * stride
                        ow = min(ow_full, (w - 1 - kj) // stride + 1)
                        hit = (xp[:, di:di + oh, dj:dj + ow, :]
                               == yx[:, :oh, :ow, :])
                        t = jnp.where(hit, g[:, :oh, :ow, :], zero)
                        acc = acc + jnp.pad(
                            t, ((0, 0), (di, hp - di - oh),
                                (dj, wp - dj - ow), (0, 0)))
                cols.append(acc)
            rows.append(jnp.stack(cols, axis=3))       # (N,hp,wp,s,C)
        dx = jnp.stack(rows, axis=2)                   # (N,hp,s,wp,s,C)
        return (dx.reshape(n, h, w, c),)
    dx = jnp.zeros_like(x)
    for ki in range(kernel):
        # windows whose tap ki lands inside the unpadded input
        oh = min(oh_full, (h - 1 - ki) // stride + 1)
        hi = ki + (oh - 1) * stride + 1
        for kj in range(kernel):
            ow = min(ow_full, (w - 1 - kj) // stride + 1)
            wj = kj + (ow - 1) * stride + 1
            sl = (slice(None), slice(ki, hi, stride),
                  slice(kj, wj, stride), slice(None))
            hit = x[sl] == yx[:, :oh, :ow, :]
            dx = dx.at[sl].add(
                jnp.where(hit, g[:, :oh, :ow, :], zero))
    return (dx,)


_max_pool_nhwc.defvjp(_max_pool_nhwc_fwd, _max_pool_nhwc_bwd)


def avg_pool2d(x: jnp.ndarray, kernel: int, stride: int,
               layout: str = "NCHW") -> jnp.ndarray:
    """Ceil-mode average pool dividing by k*k always (layer.cc:513-515)."""
    h, w = _spatial(x, layout)
    ph, pw = _ceil_pad(h, kernel, stride), _ceil_pad(w, kernel, stride)
    dims, strides, pad = _window(kernel, stride, ph, pw, layout)
    s = lax.reduce_window(x.astype(jnp.float32), 0.0, lax.add, dims, strides,
                          pad)
    return (s * (1.0 / (kernel * kernel))).astype(x.dtype)
