"""Pooling with the reference's caffe-style ceil-mode geometry.

Reference: layer.cc:476-540 — pooled = ceil((h - k)/s) + 1; AVE divides
by k*k regardless of window clipping; MAX backward routes gradient to
max positions (mshadow `unpool<red::maximum>`).  On TPU this is one
`lax.reduce_window` (XLA lowers to a fused windowed reduction); the
backward comes from autodiff, which reproduces unpool semantics.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
from jax import lax


def pooled_size(size: int, kernel: int, stride: int) -> int:
    """layer.cc:497-500: ceil((size - kernel)/stride) + 1."""
    return int(math.ceil((size - kernel) / stride)) + 1


def _ceil_pad(size: int, kernel: int, stride: int) -> int:
    out = pooled_size(size, kernel, stride)
    return max(0, (out - 1) * stride + kernel - size)


def max_pool2d(x: jnp.ndarray, kernel: int, stride: int) -> jnp.ndarray:
    """x: (N, C, H, W). Ceil-mode max pool."""
    n, c, h, w = x.shape
    ph, pw = _ceil_pad(h, kernel, stride), _ceil_pad(w, kernel, stride)
    return lax.reduce_window(
        x, -jnp.inf, lax.max,
        window_dimensions=(1, 1, kernel, kernel),
        window_strides=(1, 1, stride, stride),
        padding=((0, 0), (0, 0), (0, ph), (0, pw)))


def avg_pool2d(x: jnp.ndarray, kernel: int, stride: int) -> jnp.ndarray:
    """Ceil-mode average pool dividing by k*k always (layer.cc:513-515)."""
    n, c, h, w = x.shape
    ph, pw = _ceil_pad(h, kernel, stride), _ceil_pad(w, kernel, stride)
    s = lax.reduce_window(
        x, 0.0, lax.add,
        window_dimensions=(1, 1, kernel, kernel),
        window_strides=(1, 1, stride, stride),
        padding=((0, 0), (0, 0), (0, ph), (0, pw)))
    return s * (1.0 / (kernel * kernel))
