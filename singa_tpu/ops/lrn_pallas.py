"""Pallas LRN kernels in XLA's own batch-in-lanes activation layout.

Profiling the AlexNet step (see ops/lrn.py for the op's semantics,
reference layer.cc:331-378) showed the jnp band-matmul LRN costs
~29ms/step of the 133ms total: XLA lays conv activations out
batch-in-lanes — bf16[N,H,W,C]{0,3,2,1}, i.e. the *batch* dim rides the
128-wide lane axis — and its fused band-dot emitter spends most of the
time on elementwise VPU passes around the windowed reduction.

These kernels adopt that layout instead of fighting it.  The logical
view (N,H,W,C) → transpose(1,2,3,0) → reshape (H·W, C, N) linearizes
identically to the {0,3,2,1} physical layout, so the boundary
transposes are layout no-ops (bitcasts), not copies — this is the
difference from an earlier (N·H·W, C)-view kernel attempt that lost to
relayout copies.  Blocks are (hw_blk, C, n_blk): N on lanes, C on
sublanes.  The channel-window sum runs on the MXU as per-row band
matmuls band(C,C) @ sq(C,n) with f32 accumulation (bf16 operands —
same arithmetic as the jnp path's bf16 band dot); elementwise work is
kept to the minimum pass count, since the VPU is the bottleneck at
these activation sizes.  An earlier variant that did the window sum
with sublane shifts + f32 casts measured 13ms on norm1 alone —
slower than XLA — and was replaced by this MXU form.

The whole forward (relu → window sum → n^-β → scale) is one HBM pass
(read x, write y); the backward reads x and g and writes da in one
pass, recomputing the window sums in-register — the same closed form
as the jnp custom_vjp (ops/lrn.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl


def _np_band(c: int, local_size: int) -> np.ndarray:
    idx = np.arange(c)
    return (np.abs(idx[:, None] - idx[None, :])
            <= local_size // 2).astype(np.float32)


def _band_dot(band, t):
    """s[h] = band @ t[h] for a (hw, C, n) block — unrolled per-row MXU
    matmuls with f32 accumulation."""
    rows = [jax.lax.dot_general(band, t[h], (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
            for h in range(t.shape[0])]
    return jnp.stack(rows)


def _p_of_n(n, beta: float):
    if beta == 0.75:
        r = jax.lax.rsqrt(n)
        return r * jnp.sqrt(r)
    return n ** -beta


def _fwd_kernel(x_ref, b_ref, y_ref, *, coef, knorm, beta, relu):
    x = x_ref[...]
    a = jnp.maximum(x, jnp.zeros((), x.dtype)) if relu else x
    s = _band_dot(b_ref[...], a * a)
    p = _p_of_n(s * coef + knorm, beta)
    y_ref[...] = (a.astype(jnp.float32) * p).astype(y_ref.dtype)


def _bwd_kernel(x_ref, g_ref, b_ref, dx_ref, *, coef, knorm, beta, relu):
    x = x_ref[...]
    g = g_ref[...]
    band = b_ref[...]
    a = jnp.maximum(x, jnp.zeros((), x.dtype)) if relu else x
    s = _band_dot(band, a * a)
    n = s * coef + knorm
    p = _p_of_n(n, beta)
    t = ((g * a).astype(jnp.float32) * (p / n)).astype(x.dtype)
    u = _band_dot(band, t)
    da = (g.astype(jnp.float32) * p
          - (2.0 * beta * coef) * a.astype(jnp.float32) * u)
    if relu:
        # Mosaic rejects bf16 comparisons; compare in f32.
        da = jnp.where(x.astype(jnp.float32) > 0, da, 0.0)
    dx_ref[...] = da.astype(dx_ref.dtype)


def _hw_block(hw: int, c: int, target: int = 1024) -> int:
    """Largest divisor of hw with block rows (hw_blk·C) near `target` —
    keeps f32 intermediates comfortably in VMEM across C sizes."""
    best = 1
    for d in range(1, hw + 1):
        if hw % d == 0 and d * c <= target:
            best = d
    return best


def eligible(x, layout: str = "NHWC") -> bool:
    """Whether the Pallas path applies: NHWC batch-in-lanes blocks need
    N a lane multiple and C a sublane multiple."""
    if layout != "NHWC" or x.ndim != 4:
        return False
    n, _, _, c = x.shape
    return (n % 128 == 0 and c % 8 == 0
            and x.dtype in (jnp.float32, jnp.bfloat16))


def _call(kernel, args, band, out_dtype, hw, c, n, n_blk, interpret,
          hw_blk=None, parallel=True):
    if n % n_blk:
        n_blk = 128   # eligible() guarantees n % 128 == 0
    if hw_blk is None:
        hw_blk = _hw_block(hw, c)
    grid = (hw // hw_blk, n // n_blk)
    spec = pl.BlockSpec((hw_blk, c, n_blk), lambda i, j: (i, 0, j))
    bspec = pl.BlockSpec((c, c), lambda i, j: (0, 0))
    from jax.experimental.pallas import tpu as pltpu
    params = (pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel")) if parallel
        and not interpret else None)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[spec] * len(args) + [bspec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((hw, c, n), out_dtype),
        compiler_params=params,
        interpret=interpret,
    )(*args, band)


def _to_lanes(x):
    """(N, H, W, C) → (H·W, C, N): a pure relabeling of the {0,3,2,1}
    batch-in-lanes physical layout (no data movement)."""
    n, h, w, c = x.shape
    return x.transpose(1, 2, 3, 0).reshape(h * w, c, n)


def _from_lanes(y, n, h, w, c):
    return y.reshape(h, w, c, n).transpose(3, 0, 1, 2)


def lrn_fwd_pallas(x, local_size: int, alpha: float, beta: float,
                   knorm: float, relu: bool, interpret: bool = False,
                   n_blk: int = 256, hw_blk=None, parallel: bool = True):
    if not eligible(x):
        raise ValueError(f"lrn_pallas needs N%128==0 and C%8==0; got "
                         f"{x.shape} {x.dtype}")
    n, h, w, c = x.shape
    band = jnp.asarray(_np_band(c, local_size), x.dtype)
    kern = functools.partial(
        _fwd_kernel, coef=alpha / local_size, knorm=knorm, beta=beta,
        relu=relu)
    y = _call(kern, [_to_lanes(x)], band, x.dtype, h * w, c, n,
              min(n, n_blk), interpret, hw_blk, parallel)
    return _from_lanes(y, n, h, w, c)


def lrn_bwd_pallas(x, g, local_size: int, alpha: float, beta: float,
                   knorm: float, relu: bool, interpret: bool = False,
                   n_blk: int = 256, hw_blk=None, parallel: bool = True):
    if not eligible(x):
        raise ValueError(f"lrn_pallas needs N%128==0 and C%8==0; got "
                         f"{x.shape} {x.dtype}")
    n, h, w, c = x.shape
    band = jnp.asarray(_np_band(c, local_size), x.dtype)
    kern = functools.partial(
        _bwd_kernel, coef=alpha / local_size, knorm=knorm, beta=beta,
        relu=relu)
    dx = _call(kern, [_to_lanes(x), _to_lanes(g)], band, x.dtype,
               h * w, c, n, min(n, n_blk), interpret, hw_blk, parallel)
    return _from_lanes(dx, n, h, w, c)
