"""Pallas fused LM-head forward: projection + online softmax stats.

The chunked XLA head (ops/loss.py chunked_lm_xent) is bwd-near-optimal
but its FORWARD materializes the f32 logits chunk in HBM (512MB at
N=8k, V=32k) and re-reads it for logsumexp, the label gather, and the
top-1 argmax — ~2.7ms of pure logits traffic per step on the bench
stack.  This kernel computes the three per-token statistics the loss
needs — lse, label logit, argmax hit — in ONE pass over vocab blocks
with the logits block living only in VMEM, flash-attention style
(online max/sum-exp rescaling; argmax with top_k's lowest-index-wins
tie break).

Backward stays the XLA chunked path via custom_vjp, with the saved lse
as a residual (so the backward skips the lse recompute the checkpoint
form needed): p = exp(logits - lse); dh = (p - onehot) @ w;
dw = (p - onehot)^T @ h — dots XLA already runs at ~80-87% of peak.

Weight layout is (V, E) — the embedding-table layout tied heads share —
and the projection contracts E on the last dim of both operands, so no
transposed copy of the table ever materializes.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _fwd_kernel(h_ref, w_ref, lbl_ref, lse_ref, ll_ref, hit_ref,
                m_ref, d_ref, amax_ref, ll_acc_ref, *, bv, nv):
    vb = pl.program_id(1)

    @pl.when(vb == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        d_ref[...] = jnp.zeros_like(d_ref)
        amax_ref[...] = jnp.zeros_like(amax_ref)
        ll_acc_ref[...] = jnp.zeros_like(ll_acc_ref)

    h = h_ref[...]                       # (bn, E) compute dtype
    w = w_ref[...]                       # (bv, E)
    logits = lax.dot_general(h, w, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    bn = logits.shape[0]
    col = vb * bv + lax.broadcasted_iota(jnp.int32, (bn, bv), 1)
    lbl = lbl_ref[...]                   # (bn, 1) int32

    # online logsumexp
    bmax = jnp.max(logits, axis=1, keepdims=True)          # (bn, 1)
    m_old = m_ref[...]
    m_new = jnp.maximum(m_old, bmax)
    bsum = jnp.sum(jnp.exp(logits - m_new), axis=1, keepdims=True)
    d_ref[...] = d_ref[...] * jnp.exp(m_old - m_new) + bsum
    # argmax with lowest-index-wins ties: strictly-greater update, and
    # within the block the first max column wins via iota tie-break
    in_block_max = logits == bmax
    bidx = jnp.min(jnp.where(in_block_max, col, jnp.int32(2 ** 30)),
                   axis=1, keepdims=True)
    take = bmax > m_old
    amax_ref[...] = jnp.where(take, bidx, amax_ref[...])
    m_ref[...] = m_new
    # label logit (exact f32 value from this block when the label
    # falls in it; zero contribution otherwise)
    ll_acc_ref[...] = ll_acc_ref[...] + jnp.sum(
        jnp.where(col == lbl, logits, 0.0), axis=1, keepdims=True)

    @pl.when(vb == nv - 1)
    def _done():
        lse_ref[...] = m_ref[...] + jnp.log(d_ref[...])
        ll_ref[...] = ll_acc_ref[...]
        hit_ref[...] = (amax_ref[...] == lbl).astype(jnp.float32)


def _head_stats_pallas(h, w_vE, labels, bn: int, bv: int,
                       interpret: bool):
    """(lse, ll, hit) per token: one fused pass, logits VMEM-only."""
    n, e = h.shape
    v = w_vE.shape[0]
    grid = (n // bn, v // bv)
    lbl2 = labels.astype(jnp.int32).reshape(n, 1)
    out_spec = pl.BlockSpec((bn, 1), lambda i, j: (i, 0))
    params = (None if interpret else pltpu.CompilerParams(
        dimension_semantics=("parallel", "arbitrary")))
    lse, ll, hit = pl.pallas_call(
        functools.partial(_fwd_kernel, bv=bv, nv=v // bv),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bn, e), lambda i, j: (i, 0)),
            pl.BlockSpec((bv, e), lambda i, j: (j, 0)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
        ],
        out_specs=[out_spec, out_spec, out_spec],
        out_shape=[jax.ShapeDtypeStruct((n, 1), jnp.float32)] * 3,
        scratch_shapes=[pltpu.VMEM((bn, 1), jnp.float32)] * 2
        + [pltpu.VMEM((bn, 1), jnp.int32),
           pltpu.VMEM((bn, 1), jnp.float32)],
        compiler_params=params,
        interpret=interpret,
    )(h, w_vE, lbl2)
    return lse[:, 0], ll[:, 0], hit[:, 0]


def eligible(h, w_vE, bn: int = 512, bv: int = 2048) -> bool:
    n, e = h.shape
    v = w_vE.shape[0]
    return (n % bn == 0 and v % bv == 0 and e % 128 == 0
            and h.dtype == w_vE.dtype
            and h.dtype in (jnp.bfloat16, jnp.float32))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def fused_lm_xent(h, w_vE, labels, scale: float = 1.0,
                  chunk_size: int = 4096, bn: int = 512, bv: int = 2048,
                  interpret: bool = False):
    """(loss, precision) for an LM head with (V, E) weight — fused
    Pallas forward, chunked XLA backward.  Top-1 precision only (the
    kernel tracks argmax; topk>1 callers use chunked_lm_xent)."""
    return _fused_fwd(h, w_vE, labels, scale, chunk_size, bn, bv,
                      interpret)[0]


def _fused_fwd(h, w_vE, labels, scale, chunk_size, bn, bv, interpret):
    n = h.shape[0]
    lse, ll, hit = _head_stats_pallas(h, w_vE, labels, bn, bv, interpret)
    loss = scale * jnp.sum(lse - ll) / n
    prec = scale * jnp.sum(hit) / n
    return (loss, prec), (h, w_vE, labels, lse)


def _fused_bwd(scale, chunk_size, bn, bv, interpret, res, g):
    from .loss import _largest_divisor_leq

    h, w_vE, labels, lse = res
    dloss, _ = g                       # precision is metric-only
    n, e = h.shape
    c = _largest_divisor_leq(n, chunk_size)
    nchunk = n // c
    hb = h.reshape(nchunk, c, e)
    lb = labels.astype(jnp.int32).reshape(nchunk, c)
    sb = lse.reshape(nchunk, c)
    coef = (dloss * scale / n).astype(jnp.float32)

    def step(dw, xs):
        hc, lc, lsec = xs
        logits = lax.dot_general(hc, w_vE, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        p = jnp.exp(logits - lsec[:, None])
        onehot = (lax.broadcasted_iota(jnp.int32, p.shape, 1)
                  == lc[:, None])
        dl = ((p - onehot.astype(jnp.float32)) * coef).astype(h.dtype)
        dh_c = lax.dot_general(dl, w_vE, (((1,), (0,)), ((), ())))
        dw = dw + lax.dot_general(dl, hc, (((0,), (0,)), ((), ())))
        return dw, dh_c

    dw0 = jnp.zeros(w_vE.shape, jnp.float32)
    dw, dh = lax.scan(step, dw0, (hb, lb, sb))
    return (dh.reshape(n, e).astype(h.dtype), dw.astype(w_vE.dtype),
            None)


fused_lm_xent.defvjp(_fused_fwd, _fused_bwd)
