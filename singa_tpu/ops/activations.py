"""Elementwise activations with reference numerics.

Reference: /root/reference/include/mshadow/cxxnet_op.h:14-113.  The
reference computes gradients from the layer *output* (e.g. tanh_grad(y) =
1 - y**2); those formulas are the exact derivatives of the forward
functions, so `jax.grad` through these plain definitions reproduces the
reference backward pass.

ReLU carries an explicit custom_vjp with the same output-side gradient
the reference uses (relu_grad(y) = 1[y > 0], cxxnet_op.h:26-30): under
plain autodiff XLA saved the forward's pred mask for the backward and
chose to *bitpack* it (u32 reduce over a spatial dim + shift/or, then
an unpack in every consumer) — the pack/unpack fusions cost ~10% of the
AlexNet/CIFAR-10 train step at batch 2048 on v5e.  Deriving the mask
from the output y (which downstream layers keep alive anyway) stores
nothing extra and emits a plain compare+select backward.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# scaled-tanh constants, cxxnet_op.h:77-81 (LeCun's 1.7159 * tanh(2x/3))
STANH_OUTER = 1.7159047
STANH_INNER = 0.66666667


@jax.custom_vjp
def _relu_from_output(x):
    return jnp.maximum(x, 0.0)


def _relu_fwd(x):
    y = jnp.maximum(x, 0.0)
    return y, y


def _relu_bwd(y, g):
    # Output-side gradient, exactly the reference's relu_grad(y) = y > 0
    # (cxxnet_op.h:26-30).  Differs from input-side autodiff only at
    # x == 0, where the true derivative is undefined anyway.
    return (jnp.where(y > 0, g, jnp.zeros((), g.dtype)),)


_relu_from_output.defvjp(_relu_fwd, _relu_bwd)


def relu(x, negative_slope: float = 0.0):
    """cxxnet_op.h:26-30; ReLUProto.negative_slope (leaky) model.proto:268-275."""
    if negative_slope:
        return jnp.where(x > 0, x, negative_slope * x)
    return _relu_from_output(x)


def sigmoid(x):
    return jax.nn.sigmoid(x)


def tanh(x):
    return jnp.tanh(x)


def stanh(x, outer_scale: float = STANH_OUTER, inner_scale: float = STANH_INNER):
    """Scaled tanh A*tanh(B*x). Defaults are the reference's hard-coded
    constants (cxxnet_op.h:77-81); TanhProto outer/inner_scale override."""
    return outer_scale * jnp.tanh(inner_scale * x)


def softplus(x):
    """cxxnet_op.h:48-52 log(1+exp(x)), numerically stabilized."""
    return jax.nn.softplus(x)


def bnll(x):
    """Binomial negative log-likelihood, cxxnet_op.h:58-62 (caffe BNLL):
    x>0 ? x + log(1+exp(-x)) : log(1+exp(x)) — the stable softplus."""
    return jax.nn.softplus(x)


def square(x):
    """cxxnet_op.h:71-75."""
    return x * x


def threshold(a, b):
    """Bernoulli mask: 1.0 where a < b else 0.0 (cxxnet_op.h:96-101).
    The reference applies it to uniform samples to build dropout masks
    (layer.cc:137-141)."""
    return jnp.where(a < b, 1.0, 0.0).astype(jnp.result_type(a))


def power(a, b):
    """Elementwise a**b (cxxnet_op.h:103-108)."""
    return jnp.power(a, b)


def sqrtop(a, b):
    """sqrt(a + b) — the AdaDelta/RMS denominator helper
    (cxxnet_op.h:109-113)."""
    return jnp.sqrt(a + b)
