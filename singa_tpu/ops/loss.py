"""Softmax cross-entropy loss + top-k precision metric.

Reference: layer.cc:702-765 (SoftmaxLossLayer) —
  forward: prob = softmax(logits); loss = scale * mean(-log prob[label]);
           precision = scale * mean(label in top-k(prob))
  backward: gsrc = (prob - onehot(label)) * scale / batch
The loss here is written in the numerically-stable logsumexp form whose
exact gradient is the reference's backward formula, so one `jax.grad`
reproduces it.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def softmax_cross_entropy(logits: jnp.ndarray, labels: jnp.ndarray,
                          scale: float = 1.0) -> jnp.ndarray:
    """logits: (B, D) float; labels: (B,) int. Returns scalar mean NLL*scale.
    Computed in f32 regardless of the logits dtype (bf16 nets included)."""
    logits = logits.reshape(logits.shape[0], -1).astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    label_logit = jnp.take_along_axis(
        logits, labels.astype(jnp.int32)[:, None], axis=-1)[:, 0]
    return scale * jnp.mean(lse - label_logit)


def topk_precision(logits: jnp.ndarray, labels: jnp.ndarray, topk: int = 1,
                   scale: float = 1.0) -> jnp.ndarray:
    """Fraction of rows whose true label is in the top-k logits."""
    logits = logits.reshape(logits.shape[0], -1)
    _, idx = jax.lax.top_k(logits, topk)
    hit = jnp.any(idx == labels.astype(jnp.int32)[:, None], axis=-1)
    return scale * jnp.mean(hit.astype(jnp.float32))


def softmax_loss_metrics(logits: jnp.ndarray, labels: jnp.ndarray,
                         topk: int = 1, scale: float = 1.0
                         ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(loss, precision) — the reference's metric_ blob layout
    (layer.cc:749-751: metric[0]=loss, metric[1]=precision)."""
    return (softmax_cross_entropy(logits, labels, scale),
            topk_precision(logits, labels, topk, scale))


def _largest_divisor_leq(n: int, target: int) -> int:
    for c in range(min(target, n), 0, -1):
        if n % c == 0:
            return c
    return n


def chunked_lm_xent(h: jnp.ndarray, w: jnp.ndarray, labels: jnp.ndarray,
                    chunk_size: int = 4096, topk: int = 1,
                    scale: float = 1.0,
                    w_is_vE: bool = False) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fused LM-head projection + softmax-xent + top-k precision that
    never materializes the (N, V) logits.

    h: (N, E) token activations; w: (E, V) head weight — or, with
    `w_is_vE`, the (V, E) embedding-table layout used by tied heads:
    the projection then contracts E on the LAST dim of both operands
    (dot_general), so no transposed copy of the table is ever
    materialized (the `w.T` form cost ~1-2 ms/step extra on the 32k-
    vocab bench stack, worse with an f32 master table since the
    transpose materialized in f32).  labels: (N,).
    Tokens are processed in chunks inside a lax.scan with jax.checkpoint:
    each chunk's logits exist only in the fused projection+logsumexp
    kernel and are recomputed in the backward — O(chunk·V) live memory
    instead of O(N·V).  At LM shapes (N=B·S~8k, V=32k, fp32) that is the
    difference between ~1 GB of logits traffic per step and ~0.5 GB
    *total* HBM churn.  Numerics match softmax_loss_metrics exactly.
    """
    n, e = h.shape
    c = _largest_divisor_leq(n, chunk_size)
    nchunk = n // c
    hb = h.reshape(nchunk, c, e)
    lb = labels.astype(jnp.int32).reshape(nchunk, c)

    @jax.checkpoint
    def chunk_stats(hc, lc):
        if w_is_vE:
            logits = jax.lax.dot_general(
                hc, w, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
        else:
            logits = jnp.dot(hc, w, preferred_element_type=jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        ll = jnp.take_along_axis(logits, lc[:, None], axis=-1)[:, 0]
        if topk == 1:
            # top-1 via argmax: lax.top_k is a sort-based custom call
            # costing ~7ms/step at V=32k on the bench stack.  argmax
            # keeps top_k's tie-break exactly (lowest index wins), so
            # degenerate rows don't inflate the metric the way a
            # "label logit >= row max" compare would.
            hits = jnp.argmax(logits, axis=-1) == lc
        else:
            _, idx = jax.lax.top_k(logits, topk)
            hits = jnp.any(idx == lc[:, None], axis=-1)
        return jnp.sum(lse - ll), jnp.sum(hits.astype(jnp.float32))

    def step(carry, xs):
        nll, hits = carry
        hc, lc = xs
        d_nll, d_hits = chunk_stats(hc, lc)
        return (nll + d_nll, hits + d_hits), None

    (nll, hits), _ = jax.lax.scan(
        step, (jnp.float32(0.0), jnp.float32(0.0)), (hb, lb))
    return scale * nll / n, scale * hits / n
