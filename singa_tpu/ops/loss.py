"""Softmax cross-entropy loss + top-k precision metric.

Reference: layer.cc:702-765 (SoftmaxLossLayer) —
  forward: prob = softmax(logits); loss = scale * mean(-log prob[label]);
           precision = scale * mean(label in top-k(prob))
  backward: gsrc = (prob - onehot(label)) * scale / batch
The loss here is written in the numerically-stable logsumexp form whose
exact gradient is the reference's backward formula, so one `jax.grad`
reproduces it.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def softmax_cross_entropy(logits: jnp.ndarray, labels: jnp.ndarray,
                          scale: float = 1.0) -> jnp.ndarray:
    """logits: (B, D) float; labels: (B,) int. Returns scalar mean NLL*scale."""
    logits = logits.reshape(logits.shape[0], -1)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    label_logit = jnp.take_along_axis(
        logits, labels.astype(jnp.int32)[:, None], axis=-1)[:, 0]
    return scale * jnp.mean(lse - label_logit)


def topk_precision(logits: jnp.ndarray, labels: jnp.ndarray, topk: int = 1,
                   scale: float = 1.0) -> jnp.ndarray:
    """Fraction of rows whose true label is in the top-k logits."""
    logits = logits.reshape(logits.shape[0], -1)
    _, idx = jax.lax.top_k(logits, topk)
    hit = jnp.any(idx == labels.astype(jnp.int32)[:, None], axis=-1)
    return scale * jnp.mean(hit.astype(jnp.float32))


def softmax_loss_metrics(logits: jnp.ndarray, labels: jnp.ndarray,
                         topk: int = 1, scale: float = 1.0
                         ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(loss, precision) — the reference's metric_ blob layout
    (layer.cc:749-751: metric[0]=loss, metric[1]=precision)."""
    return (softmax_cross_entropy(logits, labels, scale),
            topk_precision(logits, labels, topk, scale))
