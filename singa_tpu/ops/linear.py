"""Inner-product (fully-connected) op.

Reference: layer.cc:162-213 — weight (vdim, hdim), y = x @ W + bias
(bias broadcast over batch via repmat).  On TPU this is a single gemm on
the MXU; grads (x^T g, sum_rows g, g W^T — layer.cc:199-211) come from
autodiff and lower to the same two gemms.
"""

from __future__ import annotations

import jax.numpy as jnp


def linear(x: jnp.ndarray, weight: jnp.ndarray, bias=None) -> jnp.ndarray:
    """x: (B, ...) flattened to (B, vdim); weight: (vdim, hdim).  The gemm
    runs in x's dtype (bf16 under mixed precision) with f32 MXU
    accumulation; output returns to x's dtype."""
    x = x.reshape(x.shape[0], -1)
    y = jnp.dot(x, weight.astype(x.dtype),
                preferred_element_type=jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(x.dtype)
