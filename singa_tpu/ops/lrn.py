"""Local response normalization (cross-channel), reference numerics.

Reference: layer.cc:331-378 —
    norm = chpool_sum(x^2, lsize) * (alpha/lsize) + knorm
    y    = x * norm^(-beta)
where chpool sums x^2 over a channel window of lsize centered at each
channel (zero-padded).  Backward is derived by autodiff; the reference's
hand-written gradient (layer.cc:366-377) is the exact derivative of this
forward, so the numerics match.

On TPU (NHWC path): the channel-window sum is a banded-matrix matmul on
the MXU — see `lrn` — because a lane-axis reduce_window costs
activation-sized relayout passes.  The NCHW path keeps reduce_window
and serves as the golden-test oracle.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def _band(c: int, local_size: int) -> jnp.ndarray:
    """(C, C) 0/1 banded matrix: band[i, j] = |i - j| <= local_size//2."""
    idx = jnp.arange(c)
    return (jnp.abs(idx[:, None] - idx[None, :])
            <= local_size // 2).astype(jnp.float32)


def lrn(x: jnp.ndarray, local_size: int = 5, alpha: float = 1.0,
        beta: float = 0.75, knorm: float = 1.0,
        layout: str = "NCHW") -> jnp.ndarray:
    """Cross-channel LRN; x (N, C, H, W) or (N, H, W, C) per layout.

    NHWC path: the channel-window sum is a matmul against a (C, C)
    banded 0/1 matrix — it rides the (otherwise idle) MXU instead of a
    lane-axis reduce_window, which on TPU costs activation-sized
    relayout passes.  Its autodiff backward is the transposed banded
    matmul, equally cheap."""
    half = local_size // 2
    if layout == "NHWC":
        # window sum in x's dtype (bf16 under mixed precision: halves the
        # HBM traffic of the sq/norm tensors; the MXU still accumulates
        # the ≤local_size bf16 squares in f32, and the result only
        # normalizes — ~0.4% relative error is inconsequential there)
        sq = jnp.square(x)
        norm = jnp.dot(sq, _band(x.shape[-1], local_size).astype(x.dtype),
                       preferred_element_type=jnp.float32)
    else:
        sq = jnp.square(x.astype(jnp.float32))
        dims = (1, local_size, 1, 1)
        pad = ((0, 0), (half, half), (0, 0), (0, 0))
        norm = lax.reduce_window(sq, 0.0, lax.add, dims, (1, 1, 1, 1), pad)
    norm = norm * (alpha / local_size) + knorm
    if beta == 0.75:
        # norm^-3/4 == rsqrt(norm)*sqrt(rsqrt(norm)): sqrt/rsqrt are
        # single VPU ops, vs pow = exp∘log transcendentals which
        # measured as expensive as the windowed sum itself.
        r = lax.rsqrt(norm)
        return (x.astype(jnp.float32) * (r * jnp.sqrt(r))).astype(x.dtype)
    return (x.astype(jnp.float32) * (norm ** -beta)).astype(x.dtype)
