"""Local response normalization (cross-channel), reference numerics.

Reference: layer.cc:331-378 —
    norm = chpool_sum(x^2, lsize) * (alpha/lsize) + knorm
    y    = x * norm^(-beta)
where chpool sums x^2 over a channel window of lsize centered at each
channel (zero-padded).  Backward is derived by autodiff; the reference's
hand-written gradient (layer.cc:366-377) is the exact derivative of this
forward, so the numerics match.

On TPU: a windowed sum over the channel axis — one `lax.reduce_window`
that XLA fuses with the surrounding elementwise ops.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def lrn(x: jnp.ndarray, local_size: int = 5, alpha: float = 1.0,
        beta: float = 0.75, knorm: float = 1.0) -> jnp.ndarray:
    """x: (N, C, H, W) cross-channel LRN."""
    half = local_size // 2
    sq = x * x
    norm = lax.reduce_window(
        sq, 0.0, lax.add,
        window_dimensions=(1, local_size, 1, 1),
        window_strides=(1, 1, 1, 1),
        padding=((0, 0), (half, half), (0, 0), (0, 0)))
    norm = norm * (alpha / local_size) + knorm
    if beta == 0.75:
        # norm^-3/4 == rsqrt(norm)*sqrt(rsqrt(norm)): sqrt/rsqrt are
        # single VPU ops, vs pow = exp∘log transcendentals which
        # measured as expensive as the windowed sum itself.
        r = lax.rsqrt(norm)
        return x * (r * jnp.sqrt(r))
    return x * (norm ** -beta)
