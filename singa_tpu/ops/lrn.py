"""Local response normalization (cross-channel), reference numerics.

Reference: layer.cc:331-378 —
    norm = chpool_sum(x^2, lsize) * (alpha/lsize) + knorm
    y    = x * norm^(-beta)
where chpool sums x^2 over a channel window of lsize centered at each
channel (zero-padded).  The reference's hand-written gradient
(layer.cc:366-377) is the exact derivative of this forward, so the
numerics match.

On TPU (NHWC path): the channel-window sum is a banded-matrix matmul on
the MXU — a lane-axis reduce_window costs activation-sized relayout
passes, and a lane-shift add chain measured ~12% slower end-to-end on
the AlexNet stack.  The whole chain runs in the compute dtype.  Under
bf16 that rounds the window sum, norm, and n^-β to ~0.4% relative —
the same order as the unavoidable final bf16 rounding of y = x·n^-β
itself, so the achievable accuracy is output-resolution-bound either
way (in the caffe-alpha regime n = 1 + O(1e-4), bf16 rounds n^-β to
exactly 1 — but so does the bf16 cast of y = x·(1 - O(1e-4))).  An
f32 norm/pow chain measured 1.7-3ms/step slower at batch 2048 (f32
intermediates/residuals cost real HBM) for accuracy the output dtype
then discards.  The f32 NCHW oracle below is exact, and the golden
tests compare the two paths in f32, where they agree to 1e-6.

The backward is a hand-written custom_vjp (the same closed form the
reference derives): letting XLA autodiff through the band matmul under
jax.checkpoint generated bitpacked-relu-mask + f32-recompute fusion
soup that cost ~10% of the whole AlexNet train step.  The residual is
x alone; the backward recomputes the window sum with a second band
matmul — MXU time is cheaper here than writing and re-reading an
activation-sized s tensor through HBM.

`relu=True` fuses the reference's conv→relu→lrn chain: ReLU is applied
in-register before the window sum and its mask folds into the
backward, so the relu activation and its separate backward pass never
touch HBM (the net marks these chains — see NeuralNet._fuse_relu_lrn).
A hand-written Pallas kernel for this chain was tried and measured
*slower* (43.7 vs 36.3 ms/step): XLA lays conv activations out
batch-in-lanes here, and the (N·H·W, C) view a row-blocked kernel
needs forces full relayout copies at the kernel boundary.  The jnp
form lets XLA keep its layouts and fuse around the custom_vjp.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def _band(c: int, local_size: int, dtype) -> jnp.ndarray:
    """(C, C) 0/1 banded matrix: band[i, j] = |i - j| <= local_size//2."""
    idx = jnp.arange(c)
    return (jnp.abs(idx[:, None] - idx[None, :])
            <= local_size // 2).astype(dtype)


def _pow_neg_beta(n: jnp.ndarray, beta: float) -> jnp.ndarray:
    if beta == 0.75:
        # norm^-3/4 == rsqrt(norm)*sqrt(rsqrt(norm)): sqrt/rsqrt are
        # single VPU ops, vs pow = exp∘log transcendentals which
        # measured as expensive as the windowed sum itself.
        r = lax.rsqrt(n)
        return r * jnp.sqrt(r)
    return n ** -beta


def _window_sum(a: jnp.ndarray, local_size: int) -> jnp.ndarray:
    """Channel-window sum of a² in a's dtype.  No preferred_element_type:
    the TPU MXU accumulates bf16 products in f32 internally anyway, and
    requesting an f32 dot *output* forces a separate f32 tile write +
    convert pass (measured +2ms/step on the AlexNet stack).  On backends
    that accumulate bf16 partials in bf16 the extra rounding stays within
    the ~0.4% relative tolerance documented in the module docstring."""
    sq = jnp.square(a)
    return jnp.dot(sq, _band(a.shape[-1], local_size, a.dtype))


def _p_of_s(s: jnp.ndarray, local_size: int, alpha: float, beta: float,
            knorm: float):
    """(n, n^-β) in the compute dtype from the window sum."""
    n = s * jnp.asarray(alpha / local_size, s.dtype) + jnp.asarray(
        knorm, s.dtype)
    return n, _pow_neg_beta(n, beta)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5, 6))
def _lrn_nhwc(x, local_size, alpha, beta, knorm, relu, impl="jnp"):
    return _lrn_nhwc_fwd(x, local_size, alpha, beta, knorm, relu, impl)[0]


def _lrn_nhwc_fwd(x, local_size, alpha, beta, knorm, relu, impl="jnp"):
    if impl != "jnp":
        from .lrn_pallas import lrn_fwd_pallas
        return lrn_fwd_pallas(x, local_size, alpha, beta, knorm, relu,
                              interpret=impl == "interpret"), x
    a = jnp.maximum(x, jnp.zeros((), x.dtype)) if relu else x
    s = _window_sum(a, local_size)
    _, p = _p_of_s(s, local_size, alpha, beta, knorm)
    # Residual is x alone: spilling n for the backward was measured
    # time-neutral on chip (the recompute dot fuses into the backward's
    # band-dot emitter nearly free), so the lean-memory form wins.
    return a * p, x


def _lrn_nhwc_bwd(local_size, alpha, beta, knorm, relu, impl, res, g):
    # d/da of y_i = a_i·n_i^-β with n = k + (α/L)·B(a²):
    #   da = g·n^-β − 2β(α/L)·a·Bᵀ(g·a·n^{-β-1})
    # (B symmetric, so Bᵀ = B); matches the reference's closed form
    # (layer.cc:366-377).  With relu fused, a = max(x, 0) is recomputed
    # from the residual x (register op) and da is masked by x > 0.
    x = res
    if impl != "jnp":
        from .lrn_pallas import lrn_bwd_pallas
        return (lrn_bwd_pallas(x, g, local_size, alpha, beta, knorm, relu,
                               interpret=impl == "interpret"),)
    a = jnp.maximum(x, jnp.zeros((), x.dtype)) if relu else x
    s = _window_sum(a, local_size)
    n, p = _p_of_s(s, local_size, alpha, beta, knorm)
    t = g * a * (p / n)                     # g·a·n^{-β-1}
    u = jnp.dot(t, _band(x.shape[-1], local_size, x.dtype))
    da = g * p - jnp.asarray(
        2 * beta * alpha / local_size, x.dtype) * a * u
    if relu:
        # NOTE: XLA hoists this predicate into the forward as a
        # bitpacked mask tensor; an arithmetic `da * sign(a)` form that
        # avoids the hoist was A/B-measured on chip and is ~1%
        # SLOWER — the packed-mask read beats the extra VPU pass.
        da = jnp.where(x > 0, da, jnp.zeros((), da.dtype))
    return (da,)


_lrn_nhwc.defvjp(_lrn_nhwc_fwd, _lrn_nhwc_bwd)


def _impl_for(x) -> str:
    """Kernel selection for the NHWC path.  A Pallas batch-in-lanes
    kernel (ops/lrn_pallas.py) was measured AND REJECTED on chip: the
    channel-window sum needs ~12 VPU passes over the activation when
    done with sublane shifts (13ms fwd on norm1 vs XLA's 6.4ms fused
    band-dot, which rides the MXU 5-tap conv emitter), so the jnp band
    matmul is the production path; the kernel stays as the
    interpret-mode oracle for the closed-form backward
    (tests/test_ops.py) and for future re-measurement."""
    return "jnp"


def lrn(x: jnp.ndarray, local_size: int = 5, alpha: float = 1.0,
        beta: float = 0.75, knorm: float = 1.0,
        layout: str = "NCHW") -> jnp.ndarray:
    """Cross-channel LRN; x (N, C, H, W) or (N, H, W, C) per layout."""
    if layout == "NHWC":
        return _lrn_nhwc(x, local_size, alpha, beta, knorm, False,
                         _impl_for(x))
    half = local_size // 2
    sq = jnp.square(x.astype(jnp.float32))
    dims = (1, local_size, 1, 1)
    pad = ((0, 0), (half, half), (0, 0), (0, 0))
    norm = lax.reduce_window(sq, 0.0, lax.add, dims, (1, 1, 1, 1), pad)
    norm = norm * (alpha / local_size) + knorm
    return (x.astype(jnp.float32) * _pow_neg_beta(norm, beta)).astype(x.dtype)


def relu_lrn(x: jnp.ndarray, local_size: int = 5, alpha: float = 1.0,
             beta: float = 0.75, knorm: float = 1.0, relu: bool = False,
             layout: str = "NHWC") -> jnp.ndarray:
    """(optionally ReLU, then) cross-channel LRN — the fused form the
    net builder selects for conv→relu→lrn chains (NHWC only)."""
    if layout == "NHWC":
        return _lrn_nhwc(x, local_size, alpha, beta, knorm, relu,
                         _impl_for(x))
    a = jnp.maximum(x, jnp.zeros((), x.dtype)) if relu else x
    return lrn(a, local_size, alpha, beta, knorm, layout)
