"""Attention ops: Pallas flash attention + pure-jnp reference.

New capability (the reference predates attention; SURVEY.md §5
"long-context"): blockwise attention with online softmax so the S×S
score matrix never materializes in HBM — the TPU memory-hierarchy-aware
formulation (HBM→VMEM streaming, MXU matmuls per tile).

`flash_attention` / `flash_attention_packed` run Pallas kernels on TPU
(interpreter mode elsewhere and in tests).  The backward pass is the
hand-written dq/dkv kernel pair: tilewise recompute of the probabilities
from (q, k, lse), every matmul on the MXU, no S×S materialization.
`blockwise_attention` is kept as the autodiff-able memory-profile
oracle of the same math (lax.scan + checkpoint over KV blocks).

Also here: rotary position embeddings (RoPE) and GQA head expansion
used by the transformer model family.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

# Tuned flash block geometry.  (512, 512) won the S=1024 sweep
# (BASELINE.md "Explored and rejected": 1024-blocks crash the packed
# compile, strided 1024 ties but pays transposes); the long-S rows come
# from tools/longctx_sweep.py.  `set_flash_blocks` pins an override for
# in-process A/B sweeps.
_FLASH_BLOCK_OVERRIDE: Optional[tuple] = None

# Causal kernels CAN compile two compute bodies: fully-visible blocks
# (no mask select) and diagonal-partial ones.  Measured on v5e at
# S=4096 (tools/longctx_sweep.py, in-process A/B): the split is a wash
# at 512x512 (-0.3%, noise) and a 55% REGRESSION at 512x1024 (536 vs
# 347 ms/step) — the duplicated body defeats Mosaic's pipelining — so
# it stays off; kept A/B-able for future geometries.
MASK_SPLIT = False


def set_flash_blocks(override: Optional[tuple]) -> None:
    """Override (block_q, block_k) globally (None = tuned table).
    Takes effect on the next trace — re-jit after changing."""
    global _FLASH_BLOCK_OVERRIDE
    _FLASH_BLOCK_OVERRIDE = override


def flash_blocks(seq_len: int) -> tuple:
    """Tuned (block_q, block_k) for a sequence length.

    v5e, in-process in-net A/B (tools/longctx_sweep.py, round 4):
    bk=1024 wins at every S >= 1024 — the fatter KV block halves the
    per-block VPU overhead passes (rescale/max bookkeeping) per score —
    by +1.1% (S=1024), +10% (S=4096), +12% (S=8192) over 512x512.
    bq=1024+, bk=2048 crash the Mosaic compile at any scoped-vmem
    budget; bq=256 loses 3-13% everywhere."""
    if _FLASH_BLOCK_OVERRIDE is not None:
        return _FLASH_BLOCK_OVERRIDE
    if seq_len >= 1024:
        return (512, 1024)
    return (512, 512)


# ---------------------------------------------------------------------------
# reference attention (oracle + backward path)


def attention_reference(q, k, v, causal: bool = True,
                        q_offset: int = 0, kv_offset: int = 0):
    """q: (B, H, Sq, D), k/v: (B, H, Sk, D). Offsets give the absolute
    positions of the local q/kv chunks (used by ring attention)."""
    d = q.shape[-1]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    scores = scores / math.sqrt(d)
    if causal:
        qpos = jnp.arange(q.shape[2]) + q_offset
        kpos = jnp.arange(k.shape[2]) + kv_offset
        mask = qpos[:, None] >= kpos[None, :]
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)


# ---------------------------------------------------------------------------
# Pallas flash kernel


def _on_tpu() -> bool:
    """True when the default device is TPU hardware.  Checks device_kind
    as well as platform because tunneled TPU backends (e.g. the `axon`
    platform) report a platform name that isn't "tpu" while still
    compiling Pallas TPU kernels."""
    try:
        dev = jax.devices()[0]
    except Exception:
        return False
    return ("tpu" in getattr(dev, "platform", "").lower()
            or "TPU" in getattr(dev, "device_kind", ""))


def _fit_block(s: int, want: int) -> int:
    """Largest block <= `want` dividing s.  The kernels need blocks of
    at least a (8, 128) TPU tile row count; a seq len that only admits
    smaller blocks (odd / non-multiple-of-128 S) would otherwise
    surface as an obscure Mosaic tiling error, so fail loudly here and
    point callers at the dense fallback."""
    c = min(want, s)
    while s % c:
        c //= 2
    if c % 8:
        raise ValueError(
            f"flash attention needs a block size that is a multiple of "
            f"8 dividing seq_len={s} (got best fit {c}); pad the "
            f"sequence to a multiple of 128 or use "
            f"attention_reference (the dense fallback)")
    return c


def _causal_mask_block(iq, ik, block_q, block_k):
    qpos = iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    kpos = ik * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return qpos >= kpos


def _flash_forward(q, k, v, causal: bool, block_q: int, block_k: int,
                   interpret: bool):
    """Strided (B, H, S, D) flash forward.  (B·H, S, D) IS the packed
    layout with one head per row, so this is the packed kernel with
    num_heads=1 — one online-softmax implementation serves both entry
    points.  Returns (out, lse (B, H, S, 1))."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    out, lse = _packed_forward(
        q.reshape(b * h, sq, d), k.reshape(b * h, sk, d),
        v.reshape(b * h, sk, d), 1, causal, block_q, block_k, interpret)
    return out.reshape(b, h, sq, d), lse.reshape(b, h, sq, 1)


def _flash_backward(q, k, v, out, lse, do, causal, block_q, block_k,
                    interpret):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    dq, dk, dv = _packed_backward(
        q.reshape(b * h, sq, d), k.reshape(b * h, sk, d),
        v.reshape(b * h, sk, d), out.reshape(b * h, sq, d),
        lse.reshape(b * h, sq, 1), do.reshape(b * h, sq, d),
        1, causal, block_q, block_k, interpret)
    return (dq.reshape(q.shape), dk.reshape(k.shape),
            dv.reshape(v.shape))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = True, block_q: int = 512,
                    block_k: int = 512, interpret: Optional[bool] = None):
    """FlashAttention. q/k/v: (B, H, S, D).  On non-TPU backends (or with
    interpret=True) the Pallas kernels run interpreted.  Backward is the
    hand-written dq/dkv Pallas kernel pair (_flash_backward) — tilewise
    recompute from (q, k, lse), every matmul on the MXU."""
    if interpret is None:
        interpret = not _on_tpu()
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret)[0]


def chunk_attention(q, k, v, causal: bool, q_off, kv_off):
    """Partial attention of a Q chunk vs a KV chunk with absolute-position
    causal masking.  Returns (normalized out, lse) — the mergeable form
    shared by the blockwise backward here and ring attention
    (singa_tpu.parallel.sequence)."""
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) / math.sqrt(d)
    if causal:
        qpos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 2) + q_off
        kpos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 3) + kv_off
        s = jnp.where(qpos >= kpos, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    m_safe = jnp.maximum(m, NEG_INF / 2)   # guard fully-masked rows
    p = jnp.exp(s - m_safe)
    p = jnp.where(s <= NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    out = out / jnp.maximum(l, 1e-30)
    lse = jnp.where(l > 0, m_safe + jnp.log(jnp.maximum(l, 1e-30)), NEG_INF)
    return out, lse


def merge_attention(out1, lse1, out2, lse2):
    """Merge two partial (normalized, lse) attention results."""
    lse = jnp.logaddexp(jnp.maximum(lse1, NEG_INF),
                        jnp.maximum(lse2, NEG_INF))
    return out1 * jnp.exp(lse1 - lse) + out2 * jnp.exp(lse2 - lse), lse


def chunk_attention_blockwise(q, k, v, causal: bool, q_off, kv_off,
                              block_k: int = 512):
    """chunk_attention with flash-style memory: the KV chunk is scanned
    in `block_k` sub-blocks with online log-sum-exp merging and
    jax.checkpoint per sub-block, so peak memory is O(Sq·block_k)
    instead of O(Sq·Sk).  Same (normalized out, lse) contract and same
    autodiff path as chunk_attention — ring attention
    (singa_tpu.parallel.sequence) calls this for its local step so the
    per-rotation score matrix never materializes at full chunk size."""
    b, h, sk, d = k.shape
    if sk <= block_k or sk % block_k:
        return chunk_attention(q, k, v, causal, q_off, kv_off)
    nb = sk // block_k
    kb = k.reshape(b, h, nb, block_k, d).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(b, h, nb, block_k, d).transpose(2, 0, 1, 3, 4)

    @jax.checkpoint
    def sub(q, kc, vc, off):
        return chunk_attention(q, kc, vc, causal, q_off, off)

    def step(carry, blk):
        out, lse = carry
        kc, vc, i = blk
        o_new, l_new = sub(q, kc, vc, kv_off + i * block_k)
        return merge_attention(out, lse, o_new, l_new), None

    out0 = jnp.zeros(q.shape, jnp.float32)
    lse0 = jnp.full(q.shape[:3] + (1,), NEG_INF, jnp.float32)
    (out, lse), _ = jax.lax.scan(step, (out0, lse0),
                                 (kb, vb, jnp.arange(nb)))
    return out, lse


def blockwise_attention(q, k, v, causal: bool = True, block_k: int = 512):
    """O(S·block_k)-memory attention: lax.scan over KV chunks with
    jax.checkpoint per chunk, merging partials in log-sum-exp space.
    Kept as the autodiff-able oracle of the flash memory profile (the
    production backward is the hand-written dq/dkv kernel pair)."""
    b, h, sk, d = k.shape
    bk = min(block_k, sk)
    if sk % bk:
        return attention_reference(q, k, v, causal)
    nkv = sk // bk

    kb = k.reshape(b, h, nkv, bk, d).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(b, h, nkv, bk, d).transpose(2, 0, 1, 3, 4)

    @jax.checkpoint
    def chunk(q, kc, vc, kv_off):
        return chunk_attention(q, kc, vc, causal, 0, kv_off)

    def step(carry, blk):
        out, lse = carry
        kc, vc, i = blk
        o_new, lse_new = chunk(q, kc, vc, i * bk)
        return merge_attention(out, lse, o_new, lse_new), None

    out0 = jnp.zeros(q.shape, jnp.float32)
    lse0 = jnp.full(q.shape[:3] + (1,), NEG_INF, jnp.float32)
    (out, _), _ = jax.lax.scan(step, (out0, lse0),
                               (kb, vb, jnp.arange(nkv)))
    return out.astype(q.dtype)


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    if interpret is None:
        interpret = not _on_tpu()
    out, lse = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    if interpret is None:
        interpret = not _on_tpu()
    return _flash_backward(q, k, v, out, lse, g, causal, block_q,
                           block_k, interpret)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# RoPE + GQA helpers


def _rope_angles(positions: jnp.ndarray, d: int, theta: float):
    """(cos, sin) each (S, D/2) — shared by both rope layouts."""
    freqs = theta ** (-jnp.arange(0, d // 2, dtype=jnp.float32) / (d // 2))
    angles = positions.astype(jnp.float32)[:, None] * freqs[None, :]
    return jnp.cos(angles), jnp.sin(angles)


def _rotate_halves(x, cos, sin):
    d = x.shape[-1]
    x1, x2 = x[..., : d // 2], x[..., d // 2:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin],
        axis=-1).astype(x.dtype)


def rope(x: jnp.ndarray, positions: jnp.ndarray,
         theta: float = 10000.0) -> jnp.ndarray:
    """Rotary embeddings. x: (B, H, S, D) with even D; positions: (S,)."""
    cos, sin = _rope_angles(positions, x.shape[-1], theta)
    return _rotate_halves(x, cos, sin)


def expand_kv_heads(kv: jnp.ndarray, num_heads: int) -> jnp.ndarray:
    """GQA: repeat kv heads to match q heads. kv: (B, Hkv, S, D)."""
    hkv = kv.shape[1]
    if hkv == num_heads:
        return kv
    assert num_heads % hkv == 0
    return jnp.repeat(kv, num_heads // hkv, axis=1)


# ---------------------------------------------------------------------------
# packed-layout flash attention: (B, S, H·D) in, (B, S, H·D) out


def _packed_params(interpret):
    return (None if interpret
            else pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")))


LOG2E = 1.4426950408889634


def _packed_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref,
                       acc_ref, *, heads, kv_heads, causal, scale, bq,
                       bk):
    """All-heads blocks: refs are (1, bq|bk, H·D); the head loop runs
    in-kernel over D-column slices (Mosaic rejects last-dim blocks
    narrower than a lane tile, so per-head blocks of D=64 are not an
    option — the full H·D width equals the array dim, which is).

    VPU economy (the co-bottleneck at D=64, where exp work per score is
    within ~2x of MXU work): scores live in the base-2 domain — the
    softmax scale and log2(e) fold into the q load (one mult per q
    element instead of per score, exp → native exp2) — and causal
    blocks split into fully-visible (no mask select at all; the vast
    majority at long S) vs diagonal-partial (masked).  m/l trackers are
    base-2; the stored lse converts back to natural once at finalize."""
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)
    d = q_ref.shape[-1] // heads
    grp = heads // kv_heads   # GQA: q heads per kv head

    @pl.when(ik == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def compute(masked):
        mask = (_causal_mask_block(iq, ik, bq, bk) if masked else None)
        for h in range(heads):
            sl = slice(h * d, (h + 1) * d)
            slk = slice((h // grp) * d, (h // grp + 1) * d)
            # operands stay in their input dtype: bf16 x bf16 -> f32
            # runs the MXU at full rate (an f32 upcast halves it); the
            # base-2 scale folds into q in that dtype, flash-standard
            q = q_ref[0, :, sl] * jnp.asarray(scale * LOG2E,
                                              q_ref.dtype)
            k = k_ref[0, :, slk]
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            if mask is not None:
                s = jnp.where(mask, s, NEG_INF)
            m_prev = m_ref[:, h:h + 1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp2(s - m_new)
            alpha = jnp.exp2(m_prev - m_new)
            l_ref[:, h:h + 1] = (l_ref[:, h:h + 1] * alpha
                                 + jnp.sum(p, axis=1, keepdims=True))
            acc_ref[:, sl] = acc_ref[:, sl] * alpha + jax.lax.dot_general(
                p.astype(v_ref.dtype), v_ref[0, :, slk],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_ref[:, h:h + 1] = m_new

    if causal and MASK_SPLIT:
        # fully-visible blocks (max kpos <= min qpos) skip the mask
        full = (ik + 1) * bk - 1 <= iq * bq

        @pl.when(full)
        def _():
            compute(False)

        @pl.when(jnp.logical_not(full) & (ik * bk <= (iq + 1) * bq - 1))
        def _():
            compute(True)
    elif causal:
        @pl.when(ik * bk <= (iq + 1) * bq - 1)
        def _():
            compute(True)
    else:
        compute(False)

    @pl.when(ik == nk - 1)
    def _finalize():
        l_safe = jnp.maximum(l_ref[...], 1e-30)
        # natural-log lse: m is base-2, l is linear
        lse_ref[0] = m_ref[...] * (1.0 / LOG2E) + jnp.log(l_safe)
        for h in range(heads):
            sl = slice(h * d, (h + 1) * d)
            o_ref[0, :, sl] = (acc_ref[:, sl]
                               / l_safe[:, h:h + 1]).astype(o_ref.dtype)


def _packed_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref,
                      dq_ref, acc_ref, *, heads, kv_heads, causal,
                      scale, bq, bk):
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)
    d = q_ref.shape[-1] // heads
    grp = heads // kv_heads

    @pl.when(ik == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def compute(masked):
        mask = (_causal_mask_block(iq, ik, bq, bk) if masked else None)
        for h in range(heads):
            sl = slice(h * d, (h + 1) * d)
            slk = slice((h // grp) * d, (h // grp + 1) * d)
            # operands stay in their input dtype: bf16 x bf16 -> f32
            # runs the MXU at full rate (an f32 upcast halves it); the
            # base-2 scale folds into q in that dtype, flash-standard
            q = q_ref[0, :, sl] * jnp.asarray(scale * LOG2E,
                                              q_ref.dtype)
            k = k_ref[0, :, slk]
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            if mask is not None:
                s = jnp.where(mask, s, NEG_INF)
            p = jnp.exp2(s - lse_ref[0, :, h:h + 1] * LOG2E)
            dp = jax.lax.dot_general(
                do_ref[0, :, sl], v_ref[0, :, slk],
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            ds = p * (dp - dl_ref[0, :, h:h + 1])
            acc_ref[:, sl] = acc_ref[:, sl] + jax.lax.dot_general(
                ds.astype(k_ref.dtype), k_ref[0, :, slk],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    if causal and MASK_SPLIT:
        full = (ik + 1) * bk - 1 <= iq * bq

        @pl.when(full)
        def _():
            compute(False)

        @pl.when(jnp.logical_not(full) & (ik * bk <= (iq + 1) * bq - 1))
        def _():
            compute(True)
    elif causal:
        @pl.when(ik * bk <= (iq + 1) * bq - 1)
        def _():
            compute(True)
    else:
        compute(False)

    @pl.when(ik == nk - 1)
    def _done():
        dq_ref[0] = (acc_ref[...] * scale).astype(dq_ref.dtype)


def _packed_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref,
                       dk_ref, dv_ref, dk_acc, dv_acc, *, heads,
                       kv_heads, causal, scale, bq, bk):
    ik = pl.program_id(1)
    iq = pl.program_id(2)
    nq = pl.num_programs(2)
    d = q_ref.shape[-1] // heads
    grp = heads // kv_heads

    @pl.when(iq == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def compute(masked):
        mask = (_causal_mask_block(iq, ik, bq, bk) if masked else None)
        for h in range(heads):
            sl = slice(h * d, (h + 1) * d)
            # GQA: every q head in a group accumulates into its shared
            # kv slice (the sequential in-kernel adds ARE the head-sum)
            slk = slice((h // grp) * d, (h // grp + 1) * d)
            # operands stay in their input dtype: bf16 x bf16 -> f32
            # runs the MXU at full rate (an f32 upcast halves it); the
            # base-2 scale folds into q in that dtype, flash-standard
            q = q_ref[0, :, sl] * jnp.asarray(scale * LOG2E,
                                              q_ref.dtype)
            k = k_ref[0, :, slk]
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            if mask is not None:
                s = jnp.where(mask, s, NEG_INF)
            p = jnp.exp2(s - lse_ref[0, :, h:h + 1] * LOG2E)
            dv_acc[:, slk] = dv_acc[:, slk] + jax.lax.dot_general(
                p.astype(do_ref.dtype), do_ref[0, :, sl],
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(
                do_ref[0, :, sl], v_ref[0, :, slk],
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            ds = p * (dp - dl_ref[0, :, h:h + 1])
            dk_acc[:, slk] = dk_acc[:, slk] + jax.lax.dot_general(
                ds.astype(q_ref.dtype), q_ref[0, :, sl],
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    if causal and MASK_SPLIT:
        full = (ik + 1) * bk - 1 <= iq * bq

        @pl.when(full)
        def _():
            compute(False)

        @pl.when(jnp.logical_not(full) & (ik * bk <= (iq + 1) * bq - 1))
        def _():
            compute(True)
    elif causal:
        @pl.when(ik * bk <= (iq + 1) * bq - 1)
        def _():
            compute(True)
    else:
        compute(False)

    @pl.when(iq == nq - 1)
    def _done():
        dk_ref[0] = (dk_acc[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _packed_forward(q, k, v, num_heads, causal, block_q, block_k,
                    interpret, num_kv_heads=None):
    b, sq, hd = q.shape
    sk = k.shape[1]
    d = hd // num_heads
    kv_heads = num_kv_heads or num_heads
    hd_kv = kv_heads * d
    assert k.shape[-1] == hd_kv, (k.shape, kv_heads, d)
    bq, bk = _fit_block(sq, block_q), _fit_block(sk, block_k)
    assert sq % bq == 0 and sk % bk == 0
    scale = 1.0 / math.sqrt(d)
    q_spec = pl.BlockSpec((1, bq, hd), lambda b_, iq, ik: (b_, iq, 0))
    k_spec = pl.BlockSpec((1, bk, hd_kv), lambda b_, iq, ik: (b_, ik, 0))
    out, lse = pl.pallas_call(
        functools.partial(_packed_fwd_kernel, heads=num_heads,
                          kv_heads=kv_heads, causal=causal, scale=scale,
                          bq=bq, bk=bk),
        grid=(b, sq // bq, sk // bk),
        in_specs=[q_spec, k_spec, k_spec],
        out_specs=[
            q_spec,
            pl.BlockSpec((1, bq, num_heads),
                         lambda b_, iq, ik: (b_, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, sq, hd), q.dtype),
            jax.ShapeDtypeStruct((b, sq, num_heads), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, num_heads), jnp.float32),
            pltpu.VMEM((bq, num_heads), jnp.float32),
            pltpu.VMEM((bq, hd), jnp.float32),
        ],
        compiler_params=_packed_params(interpret),
        interpret=interpret,
    )(q, k, v)
    return out, lse


def _packed_backward(q, k, v, out, lse, do, num_heads, causal, block_q,
                     block_k, interpret, num_kv_heads=None, dlse=None):
    b, sq, hd = q.shape
    sk = k.shape[1]
    d = hd // num_heads
    kv_heads = num_kv_heads or num_heads
    hd_kv = kv_heads * d
    bq, bk = _fit_block(sq, block_q), _fit_block(sk, block_k)
    scale = 1.0 / math.sqrt(d)
    # delta[b, s, h] = rowsum(do·out) within head h; when the lse
    # output is live (ring merging differentiates through it), its
    # cotangent joins here: ds = p·(dp − (rowsum(do·out) − dlse))
    delta = jnp.sum(
        (do.astype(jnp.float32) * out.astype(jnp.float32))
        .reshape(b, sq, num_heads, d), axis=-1)
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)
    dor = do.astype(q.dtype)

    q_spec = pl.BlockSpec((1, bq, hd), lambda b_, iq, ik: (b_, iq, 0))
    k_spec = pl.BlockSpec((1, bk, hd_kv), lambda b_, iq, ik: (b_, ik, 0))
    r_spec = pl.BlockSpec((1, bq, num_heads),
                          lambda b_, iq, ik: (b_, iq, 0))
    dq = pl.pallas_call(
        functools.partial(_packed_dq_kernel, heads=num_heads,
                          kv_heads=kv_heads, causal=causal, scale=scale,
                          bq=bq, bk=bk),
        grid=(b, sq // bq, sk // bk),
        in_specs=[q_spec, k_spec, k_spec, q_spec, r_spec, r_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b, sq, hd), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, hd), jnp.float32)],
        compiler_params=_packed_params(interpret),
        interpret=interpret,
    )(q, k, v, dor, lse, delta)

    q_spec2 = pl.BlockSpec((1, bq, hd), lambda b_, ik, iq: (b_, iq, 0))
    k_spec2 = pl.BlockSpec((1, bk, hd_kv), lambda b_, ik, iq: (b_, ik, 0))
    r_spec2 = pl.BlockSpec((1, bq, num_heads),
                           lambda b_, ik, iq: (b_, iq, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_packed_dkv_kernel, heads=num_heads,
                          kv_heads=kv_heads, causal=causal, scale=scale,
                          bq=bq, bk=bk),
        grid=(b, sk // bk, sq // bq),
        in_specs=[q_spec2, k_spec2, k_spec2, q_spec2, r_spec2, r_spec2],
        out_specs=[k_spec2, k_spec2],
        out_shape=[jax.ShapeDtypeStruct((b, sk, hd_kv), k.dtype),
                   jax.ShapeDtypeStruct((b, sk, hd_kv), v.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, hd_kv), jnp.float32),
                        pltpu.VMEM((bk, hd_kv), jnp.float32)],
        compiler_params=_packed_params(interpret),
        interpret=interpret,
    )(q, k, v, dor, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def flash_attention_packed(q, k, v, num_heads: int, causal: bool = True,
                           block_q: int = 512, block_k: int = 512,
                           interpret: Optional[bool] = None,
                           num_kv_heads: Optional[int] = None):
    """FlashAttention on the packed projection layout: q (B, S, H·D),
    k/v (B, S, Hkv·D) — exactly what the qkv projections emit — with an
    in-kernel head loop over D-column slices.  No (B,S,H,D)→(B,H,S,D)
    transposes anywhere: on the 12-head S=1024 bench stack those
    relayout copies cost ~5ms/step.  GQA runs natively (round 4): each
    q head reads its group's kv slice in-kernel and the dkv kernel's
    sequential per-head adds ARE the group sum — no expand_kv_heads
    materialization, no strided fallback."""
    if interpret is None:
        interpret = not _on_tpu()
    return _packed_forward(q, k, v, num_heads, causal, block_q, block_k,
                           interpret, num_kv_heads)[0]


def _packed_vjp_fwd(q, k, v, num_heads, causal, block_q, block_k,
                    interpret, num_kv_heads=None):
    if interpret is None:
        interpret = not _on_tpu()
    out, lse = _packed_forward(q, k, v, num_heads, causal, block_q,
                               block_k, interpret, num_kv_heads)
    return out, (q, k, v, out, lse)


def _packed_vjp_bwd(num_heads, causal, block_q, block_k, interpret,
                    num_kv_heads, res, g):
    q, k, v, out, lse = res
    if interpret is None:
        interpret = not _on_tpu()
    return _packed_backward(q, k, v, out, lse, g, num_heads, causal,
                            block_q, block_k, interpret, num_kv_heads)


flash_attention_packed.defvjp(_packed_vjp_fwd, _packed_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def flash_attention_packed_lse(q, k, v, num_heads: int,
                               causal: bool = True, block_q: int = 512,
                               block_k: int = 512,
                               interpret: Optional[bool] = None,
                               num_kv_heads: Optional[int] = None):
    """flash_attention_packed that ALSO returns the natural log-sum-exp
    (B, S, H) — the mergeable (normalized out, lse) pair ring attention
    needs for its per-rotation partials.  Differentiable in both
    outputs: the backward folds the lse cotangent into the delta term
    (ds = p·(dp − (rowsum(do·out) − dlse)))."""
    if interpret is None:
        interpret = not _on_tpu()
    return _packed_forward(q, k, v, num_heads, causal, block_q, block_k,
                           interpret, num_kv_heads)


def _packed_lse_vjp_fwd(q, k, v, num_heads, causal, block_q, block_k,
                        interpret, num_kv_heads=None):
    if interpret is None:
        interpret = not _on_tpu()
    out, lse = _packed_forward(q, k, v, num_heads, causal, block_q,
                               block_k, interpret, num_kv_heads)
    return (out, lse), (q, k, v, out, lse)


def _packed_lse_vjp_bwd(num_heads, causal, block_q, block_k, interpret,
                        num_kv_heads, res, g):
    q, k, v, out, lse = res
    do, dlse = g
    if interpret is None:
        interpret = not _on_tpu()
    return _packed_backward(q, k, v, out, lse, do, num_heads, causal,
                            block_q, block_k, interpret, num_kv_heads,
                            dlse=dlse)


flash_attention_packed_lse.defvjp(_packed_lse_vjp_fwd,
                                  _packed_lse_vjp_bwd)


def flash_chunk(q, k, v, causal: bool,
                interpret: Optional[bool] = None,
                block_q: int = 512, block_k: int = 512):
    """Ring-attention local step on the Pallas kernels: strided
    (B, H, Sq, D) × (B, H, Sk, D) → (normalized out f32, natural lse
    (B, H, Sq, 1)) — the same mergeable contract as chunk_attention.
    Only legal for equal q/kv offsets (the diagonal rotation) or
    causal=False (fully-visible rotations); the ring driver picks the
    case per rotation."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    out, lse = flash_attention_packed_lse(
        q.reshape(b * h, sq, d), k.reshape(b * h, sk, d),
        v.reshape(b * h, sk, d), 1, causal, block_q, block_k, interpret)
    return (out.reshape(b, h, sq, d).astype(jnp.float32),
            lse.reshape(b, h, sq, 1))


def flash_chunk_legal(sq: int, sk: int, d: int) -> bool:
    """Whether flash_chunk's kernels can tile these local chunk shapes
    ((8, 128)-tile-able blocks; see _fit_block)."""
    def ok(n):
        c = min(512, n)
        while n % c:
            c //= 2
        return c % 8 == 0
    return sq >= 8 and sk >= 8 and d % 8 == 0 and ok(sq) and ok(sk)


def rope_packed(x: jnp.ndarray, positions: jnp.ndarray, num_heads: int,
                theta: float = 10000.0) -> jnp.ndarray:
    """RoPE on the packed (B, S, H·D) layout: per-head rotation applied
    through a free trailing-dim split/merge (no transposes)."""
    b, s, hd = x.shape
    d = hd // num_heads
    cos, sin = _rope_angles(positions, d, theta)
    out = _rotate_halves(x.reshape(b, s, num_heads, d),
                         cos[None, :, None, :], sin[None, :, None, :])
    return out.reshape(b, s, hd)
