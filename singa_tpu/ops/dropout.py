"""Dropout, reference numerics (layer.cc:126-160).

mask = 1[uniform < pkeep] / pkeep; y = x * mask.  Same mask reused by
the backward pass — which is exactly what autodiff through the masked
multiply produces.  RNG is an explicit JAX key (the reference seeds a
global mt19937 from the clock; here determinism is first-class).

TPU note: the keep test compares raw threefry bits against a uint32
threshold instead of materializing floats — `jax.random.uniform`'s
bits→float path measured ~10x the cost of `jax.random.bits` on v5e,
and P(bits < round(pkeep·2³²)) equals pkeep to within 2⁻³², far below
the mask's statistical noise.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def dropout(x: jnp.ndarray, rate: float, rng: jax.Array,
            train: bool = True) -> jnp.ndarray:
    if not train or rate <= 0.0:
        return x
    pkeep = 1.0 - rate
    thresh = np.uint32(min(round(pkeep * 2.0 ** 32), 2 ** 32 - 1))
    bits = jax.random.bits(rng, x.shape, dtype=jnp.uint32)
    mask = (bits < thresh).astype(x.dtype) / jnp.asarray(pkeep, x.dtype)
    return x * mask
