"""Dropout, reference numerics (layer.cc:126-160).

mask = 1[uniform < pkeep] / pkeep; y = x * mask.  Same mask reused by
the backward pass — which is exactly what autodiff through the masked
multiply produces.  RNG is an explicit JAX key (the reference seeds a
global mt19937 from the clock; here determinism is first-class).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def dropout(x: jnp.ndarray, rate: float, rng: jax.Array,
            train: bool = True) -> jnp.ndarray:
    if not train or rate <= 0.0:
        return x
    pkeep = 1.0 - rate
    mask = (jax.random.uniform(rng, x.shape) < pkeep).astype(x.dtype) / pkeep
    return x * mask
