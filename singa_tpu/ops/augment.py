"""On-device image augmentation — the elastic-distortion surface of the
reference's MNIST parser.

Reference: MnistProto (model.proto:211-225) declares kernel/sigma/alpha
(elastic displacement field), beta (rotation/shear, degrees), gamma
(scaling, percent), resize and elastic_freq — but the implementation in
layer.cc:380-473 is commented out.  Here the full Simard-2003-style
pipeline is real and runs *inside the jitted step* (the reference would
have done it per-pixel on the host): random displacement fields smoothed
by a Gaussian kernel, composed with a random rotation+scaling affine map,
sampled bilinearly.  Everything is vectorized over the batch, so the
augmentation cost is a few elementwise kernels and one gather on TPU.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def gaussian_kernel(size: int, sigma: float) -> jnp.ndarray:
    """Normalized (size, size) Gaussian filter."""
    r = jnp.arange(size, dtype=jnp.float32) - (size - 1) / 2.0
    g = jnp.exp(-(r ** 2) / (2.0 * max(sigma, 1e-6) ** 2))
    k = jnp.outer(g, g)
    return k / jnp.sum(k)


def _blur(field: jnp.ndarray, kernel: jnp.ndarray) -> jnp.ndarray:
    """Depthwise SAME blur of a (B, H, W) field."""
    b, h, w = field.shape
    k = kernel.shape[0]
    out = jax.lax.conv_general_dilated(
        field[:, None], kernel[None, None],
        window_strides=(1, 1),
        padding=[(k // 2, (k - 1) // 2), (k // 2, (k - 1) // 2)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    return out[:, 0]


def elastic_deform(x: jnp.ndarray, key: jax.Array, *, kernel: int = 0,
                   sigma: float = 0.0, alpha: float = 0.0,
                   beta: float = 0.0, gamma: float = 0.0) -> jnp.ndarray:
    """Random elastic + affine deformation of a batch of images.

    x: (B, H, W) float.  Per image: displacement field = Gaussian-blurred
    uniform(-1,1) noise scaled by `alpha` pixels (when kernel>0); affine =
    rotation by U(-beta, beta) degrees and axis scaling by
    U(1-gamma/100, 1+gamma/100), about the image center.  Bilinear
    sampling with edge clamping.  All parameters zero → identity.
    """
    b, h, w = x.shape
    k_rot, k_sc, k_dx, k_dy = jax.random.split(key, 4)

    yy, xx = jnp.meshgrid(jnp.arange(h, dtype=jnp.float32),
                          jnp.arange(w, dtype=jnp.float32), indexing="ij")
    cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
    yc, xc = yy - cy, xx - cx                       # centered grid (H, W)

    # inverse affine per image: rotate by -theta, scale by 1/s
    theta = (jax.random.uniform(k_rot, (b,), minval=-beta, maxval=beta)
             * math.pi / 180.0)
    scale = 1.0 + jax.random.uniform(k_sc, (b, 2), minval=-gamma,
                                     maxval=gamma) / 100.0
    cos, sin = jnp.cos(theta), jnp.sin(theta)
    # source coords = R(-theta) @ (grid / scale)
    gy = yc[None] / scale[:, 0, None, None]
    gx = xc[None] / scale[:, 1, None, None]
    src_y = cos[:, None, None] * gy + sin[:, None, None] * gx
    src_x = -sin[:, None, None] * gy + cos[:, None, None] * gx

    if kernel > 0 and alpha > 0:
        kern = gaussian_kernel(kernel, sigma)
        dy = _blur(jax.random.uniform(k_dy, (b, h, w), minval=-1.0,
                                      maxval=1.0), kern) * alpha
        dx = _blur(jax.random.uniform(k_dx, (b, h, w), minval=-1.0,
                                      maxval=1.0), kern) * alpha
        src_y = src_y + dy
        src_x = src_x + dx

    coords_y = jnp.clip(src_y + cy, 0.0, h - 1)
    coords_x = jnp.clip(src_x + cx, 0.0, w - 1)

    def sample(img, cy_, cx_):
        return jax.scipy.ndimage.map_coordinates(
            img, [cy_, cx_], order=1, mode="nearest")

    return jax.vmap(sample)(x, coords_y, coords_x)
