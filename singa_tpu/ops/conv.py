"""2-D convolution, TPU-native.

The reference (layer.cc:63-123) lowers conv to a per-batch-item
im2col (`unpack_patch2col`) followed by a gemm against a weight of shape
(num_filters, C*k*k).  On TPU the idiomatic form is a single
`lax.conv_general_dilated` which XLA tiles directly onto the MXU — one
fused op for the whole batch, with the backward passes derived by
autodiff (XLA emits the transposed/grad convs).

We keep the reference's *weight layout* (num_filters, C*k*k) as the
stored parameter so partition semantics (ParamProto.partition_dim) and
checkpoints line up with the config surface; it is reshaped to OIHW at
trace time (free at compile time).

`im2col` is also provided as a reference oracle for golden tests.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
from jax import lax


def conv_out_size(size: int, kernel: int, stride: int, pad: int) -> int:
    """Reference formula layer.cc:37-38: (h + 2p - k)/s + 1 (floor)."""
    return (size + 2 * pad - kernel) // stride + 1


def conv2d(x: jnp.ndarray, weight: jnp.ndarray, bias=None, *,
           kernel: int, stride: int = 1, pad: int = 0,
           channels: int | None = None,
           layout: str = "NCHW") -> jnp.ndarray:
    """weight: (num_filters, C*k*k) reference layout, either x layout.

    layout "NCHW": x (N, C, H, W) → (N, F, H', W') — the reference's
    convention, kept for the golden-test oracles.  layout "NHWC":
    x (N, H, W, C) → (N, H', W', F) — channels-minor, the layout the
    layer zoo runs in (channels land on the 128-wide lane axis, so XLA
    tiles the conv onto the MXU without inserting transposes; measured
    ~16% faster end-to-end than NCHW on the AlexNet stack).
    """
    if channels is None:
        channels = x.shape[1] if layout == "NCHW" else x.shape[-1]
    num_filters = weight.shape[0]
    wk = weight.reshape(num_filters, channels, kernel, kernel)
    if layout == "NHWC":
        wk = wk.transpose(2, 3, 1, 0)  # HWIO
        dn = ("NHWC", "HWIO", "NHWC")
    else:
        dn = ("NCHW", "OIHW", "NCHW")
    # No explicit preferred_element_type: the MXU accumulates bf16
    # products in f32 internally either way, and a f32-valued conv output
    # would make the backward's transposed conv mix dtypes (unsupported).
    out = lax.conv_general_dilated(
        x, wk.astype(x.dtype),
        window_strides=(stride, stride),
        padding=[(pad, pad), (pad, pad)],
        dimension_numbers=dn,
    )
    if bias is not None:
        shape = ((1, num_filters, 1, 1) if layout == "NCHW"
                 else (1, 1, 1, num_filters))
        out = out + bias.astype(out.dtype).reshape(shape)
    return out


def im2col(img: jnp.ndarray, kernel: int, stride: int) -> jnp.ndarray:
    """`unpack_patch2col` oracle (tensor_expr_ext.h:38-73 semantics).

    img: (C, H, W) → (C*k*k, H'*W') where row index = c*k*k + ki*k + kj
    (channel-major, then kernel row, then kernel col) matching the
    reference's col layout so weight @ col reproduces conv.
    """
    c, h, w = img.shape
    oh = (h - kernel) // stride + 1
    ow = (w - kernel) // stride + 1
    patches = []
    for ci in range(c):
        for ki in range(kernel):
            for kj in range(kernel):
                sub = lax.slice(img, (ci, ki, kj),
                                (ci + 1, ki + (oh - 1) * stride + 1,
                                 kj + (ow - 1) * stride + 1),
                                (1, stride, stride))
                patches.append(sub.reshape(-1))
    return jnp.stack(patches)
