"""Mixture-of-Experts FFN with top-k routing — the expert-parallel op.

New capability (no MoE in the reference).  Sort-based dispatch: the T·k
(token, expert) assignments are sorted by expert id, ranked within each
expert's run, capacity-clipped, and scattered into a dense
(n_exp, capacity, E) expert batch; expert FFNs run batched over the
leading expert dim and results scatter-add back per token.  Memory is
O(T·k·E + n_exp·capacity·E) — linear in tokens, never the
O(T·n_exp·capacity) one-hot dispatch tensor of naive GShard.

Under expert parallelism the expert-stacked weights (and the expert
batch) shard over the mesh's "expert" axis; XLA lowers the scatter/
gather across that axis to all-to-alls over ICI.

Router aux loss follows Switch Transformer (mean fraction × mean prob
per expert, scaled by n_experts).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp


def moe_ffn(x: jnp.ndarray, params: Dict[str, jnp.ndarray], k: int = 2,
            capacity_factor: float = 1.25,
            ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """x: (B, S, E).  params: router (E, n_exp); w1 (n_exp, E, F),
    b1 (n_exp, F); w2 (n_exp, F, E), b2 (n_exp, E).

    Returns (out (B, S, E), router aux loss).
    """
    b, s, e = x.shape
    n_exp = params["router"].shape[1]
    t = b * s
    tokens = x.reshape(t, e)
    capacity = max(int(capacity_factor * (t * k) / n_exp), 1)

    logits = jnp.dot(tokens.astype(jnp.float32),
                     params["router"].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)                    # (T, n_exp)
    gate_vals, gate_idx = jax.lax.top_k(probs, k)              # (T, k)

    # flatten assignments; row-major keeps rank-0 choices first per token
    flat_exp = gate_idx.reshape(t * k)
    flat_gate = gate_vals.reshape(t * k)
    flat_tok = jnp.arange(t * k, dtype=jnp.int32) // k

    # sort by expert (stable → earlier tokens keep queue priority)
    order = jnp.argsort(flat_exp, stable=True)
    sorted_exp = flat_exp[order]
    # rank within each expert's contiguous run
    onehot = (sorted_exp[:, None] ==
              jnp.arange(n_exp, dtype=sorted_exp.dtype)[None, :])
    rank = jnp.take_along_axis(
        jnp.cumsum(onehot.astype(jnp.int32), axis=0) - 1,
        sorted_exp[:, None].astype(jnp.int32), axis=1)[:, 0]
    keep = rank < capacity
    # dropped assignments write to a trash slot past the expert batch
    slot = jnp.where(keep, sorted_exp * capacity + rank, n_exp * capacity)

    tok_sorted = tokens[flat_tok[order]]                       # (T*k, E)
    buf = jnp.zeros((n_exp * capacity + 1, e), x.dtype)
    buf = buf.at[slot].set(tok_sorted)
    exp_in = buf[:-1].reshape(n_exp, capacity, e)

    h = jnp.einsum("ecd,edf->ecf", exp_in, params["w1"],
                   preferred_element_type=jnp.float32)
    h = jax.nn.silu(h + params["b1"][:, None, :])
    out = jnp.einsum("ecf,efd->ecd", h.astype(x.dtype), params["w2"],
                     preferred_element_type=jnp.float32)
    out = out + params["b2"][:, None, :]

    out_flat = jnp.concatenate(
        [out.reshape(n_exp * capacity, e), jnp.zeros((1, e), out.dtype)])
    out_sorted = out_flat[slot] * flat_gate[order][:, None]
    y = jnp.zeros((t, e), jnp.float32).at[flat_tok[order]].add(
        out_sorted.astype(jnp.float32))

    # Switch aux loss over rank-0 assignments
    frac_tokens = jnp.mean(
        jax.nn.one_hot(gate_idx[:, 0], n_exp, dtype=jnp.float32), axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    aux = n_exp * jnp.sum(frac_tokens * frac_probs)
    return y.reshape(b, s, e).astype(x.dtype), aux
