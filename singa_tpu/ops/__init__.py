from .activations import (relu, sigmoid, tanh, stanh, softplus, bnll,
                          square, threshold, power, sqrtop)
from .conv import conv2d, im2col, conv_out_size
from .pool import max_pool2d, avg_pool2d, pooled_size
from .lrn import lrn, relu_lrn
from .loss import softmax_cross_entropy, topk_precision, softmax_loss_metrics
from .dropout import dropout
from .linear import linear
