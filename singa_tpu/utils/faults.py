"""Deterministic fault injection for the training runtime.

The reference designed failure recovery but never shipped it
(Worker::Resume is an empty TODO, worker.cc:65-67) — partly because a
recovery path you cannot trigger on demand is a recovery path you never
test.  This module makes every failure mode reproducible on CPU: a
seeded `FaultSchedule` fires exceptions (or simulated preemptions, or
silent data corruption) at named *sites* instrumented throughout the
runtime, so tests and `scripts/fault_smoke.sh` can kill a run at step k,
tear a checkpoint, or corrupt one record and assert the supervisor
recovers to the exact uninterrupted trajectory.

Sites (each `maybe_fault(site)` call is one *visit*; visits are counted
per site across the whole process, including replayed steps after a
restart — so a one-shot fault never re-fires during recovery):

    data.decode    one record decoded (Prefetcher producer / shard read)
    data.prefetch  one batch handed to the consumer (Prefetcher.__next__)
    feed.stage     one chunk staged (ChunkStager.stage: stack +
                   device_put — fires on the DeviceFeeder producer
                   thread in the overlapped loop, inline otherwise)
    ckpt.save      one checkpoint save (before finalize)
    ckpt.restore   one checkpoint restore attempt
    sync.elastic   one cross-slice center exchange (elastic/randomsync)
    sync.delta     one replica contribution handed to a center exchange
                   (ElasticController.maybe_sync /
                   DistributedReplicaSet._sync — the silent kinds
                   poison the delta so validation/quarantine paths are
                   testable)
    step.train     one training-loop iteration (Trainer.run / run_cd)
    step.grad      one training step's gradients (Trainer.run consults
                   per step; the silent kinds poison the compiled
                   step's grads so numeric-health detection is
                   testable on CPU)
    serve.admit    one request admitted to the serving queue
                   (MicroBatcher.submit — an error sheds the request
                   with a Backoff retry hint instead of crashing)
    serve.batch    one micro-batch dispatched to the inference engine
                   (MicroBatcher dispatch loop — an error fails that
                   batch's requests; the server stays up)
    serve.reload   one checkpoint hot-reload attempt
                   (InferenceEngine.poll_reload / reload_to — an error
                   degrades to keep-serving-old-params, counted in
                   ServeStats; on a fleet canary it turns the rollout
                   into a counted refusal)
    fleet.dispatch one routed request attempt (Router.route — an error
                   is charged to the chosen engine exactly like a real
                   engine failure: the request retries on another
                   engine and the engine earns a strike)
    serve.hedge    one hedged dispatch fired (Router — an error abandons
                   that hedge attempt only: the primary dispatch is
                   untouched and the request's outcome is whatever the
                   primary returns, so a broken hedge path can never
                   make tail latency worse than no hedging)
    engine.stall   one compiled-program invocation (run_batch /
                   run_cb_prefill / run_cb_decode).  The silent "stall"
                   kind latches `ServeSpec.stall_fault_s` of host-side
                   sleep onto THAT engine's every subsequent program
                   call — the deterministic slow-replica lever the
                   hedging bench uses to prove a straggler cannot own
                   p99.  An "error" kind fails that one call (the
                   batch/step failure story above)
    fleet.rollout  one rollout-controller tick (RolloutController —
                   an error mid-canary aborts the rollout safely:
                   the canary is rolled back to the pinned step and
                   the fleet never promotes)
    pipeline.publish
                   one checkpoint publication in the closed train-and-
                   serve loop (PipelineController._on_publish — an
                   error degrades to a counted `publish_faults`: the
                   blessed step is still recorded and the rollout
                   controller still notices the fingerprint change on
                   its own poll, so a lost publish notification never
                   loses a promotion)
    scale.decide   one autoscaler control tick (AutoScaler.tick — an
                   error skips that tick's decision, counted in
                   `decide_faults` and evented `scale.abort`; a
                   faulted tick never spawns and NEVER retires an
                   engine, so fault injection can't shrink a fleet)
    obs.emit       one telemetry record written (a span recorded, an
                   event-log line appended, a trace exported — every
                   obs write path swallows the fault into a drop
                   counter, proving telemetry failure never takes
                   down training or serving)
    obs.flush      the observability session teardown (trace export,
                   final metrics dump, event-log close — a faulted
                   flush is itself a flight-recorder trigger:
                   `flightrec-obs_flush_fault-*.json` preserves the
                   window the lost export would have covered)
    serve.resume   one mid-stream failover resume attempt
                   (Router._failover_leg — an error abandons the
                   resume and the stream degrades to the pre-failover
                   terminal error: the client sees exactly the old
                   mid-stream RuntimeError, never a hang and never a
                   duplicated token)
    wire.frame     one outbound binary-transport frame (serve/wire.py
                   send path — an "error" kind DROPS the frame and
                   fails the connection, a "corrupt" kind flips bytes
                   so the receiver counts `wire_malformed_total` and
                   closes, a silent "torn" kind writes half the frame
                   then fails the sender.  All three degrade to a
                   counted reconnect or a per-request failure the
                   Router's retry/failover machinery absorbs — never
                   a hang, never an undetected bad payload)

Fault kinds:

    error    raise FaultError (a generic failure at the site)
    preempt  raise Preemption (the job is killed; a Supervisor treats it
             exactly like a SIGTERM'd process that restarts)
    corrupt  raise CorruptRecord (data sites: the record is bad; the
             pipeline quarantines it and continues)
    torn     no exception — maybe_fault returns "torn" and the SITE
             decides how to honor it (ckpt.save writes a truncated
             snapshot: a save that "succeeded" but left garbage on disk)
    nan      no exception — the site poisons the value with NaNs (a
             silent numeric failure: grads at step.grad, the exchanged
             delta at sync.delta) and training continues until the
             health tier notices
    spike    no exception — the site scales the value by a large factor
             (an exploding-gradient / corrupted-delta event that stays
             finite)
    stall    no exception — the site latches an injected latency onto
             itself (engine.stall: every later compiled call on that
             engine sleeps `stall_fault_s`; the slow replica that drags
             fleet p99 without ever failing a health probe)

Instrumented code calls `maybe_fault(site)` — a no-op returning None
unless a schedule is active via `inject(schedule)`.  Overhead when
inactive is one global read.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List, Optional

SITES = ("data.decode", "data.prefetch", "feed.stage", "ckpt.save",
         "ckpt.restore", "sync.elastic", "sync.delta", "step.train",
         "step.grad", "serve.admit", "serve.batch", "serve.reload",
         "serve.hedge", "engine.stall", "fleet.dispatch",
         "fleet.rollout", "pipeline.publish", "scale.decide",
         "obs.emit", "serve.resume", "obs.flush", "router.wal",
         "router.recover", "wire.frame")

KINDS = ("error", "preempt", "corrupt", "torn", "nan", "spike",
         "stall")

#: kinds that do not raise: maybe_fault returns the kind string and the
#: instrumented SITE decides how to honor it (tear a snapshot, poison a
#: gradient or sync delta, latch a latency stall)
SILENT_KINDS = ("torn", "nan", "spike", "stall")


class FaultError(RuntimeError):
    """A generic injected failure at a site."""


class Preemption(FaultError):
    """A simulated preemption: the run is killed at this point.  The
    Supervisor treats it like any crash — restore + replay — but keeps
    it distinct in the failure log (preemptions are expected on
    preemptible TPU slices; repeated *errors* are a bug)."""


class CorruptRecord(FaultError):
    """An injected bad data record; the pipeline quarantines it (skips
    and counts) instead of failing the run."""


_KIND_EXC = {"error": FaultError, "preempt": Preemption,
             "corrupt": CorruptRecord}


@dataclass
class FaultSpec:
    """Fire `kind` at the `at`-th visit (0-based) of `site`, once."""
    site: str
    at: int
    kind: str = "error"
    fired: bool = False

    def __post_init__(self):
        if self.site not in SITES:
            raise ValueError(f"unknown fault site {self.site!r}; "
                             f"sites are {SITES}")
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; "
                             f"kinds are {KINDS}")


@dataclass
class FiredFault:
    site: str
    visit: int
    kind: str
    time: float


class FaultSchedule:
    """Deterministic per-site fault plan: one-shot `FaultSpec`s plus
    optional seeded per-visit probabilities (`rates`, site -> p) for
    chaos runs.  Thread-safe — the prefetch producer thread and the
    training loop consult the same schedule."""

    def __init__(self, specs: Optional[List[FaultSpec]] = None,
                 rates: Optional[Dict[str, float]] = None,
                 rate_kind: str = "error", seed: int = 0):
        import numpy as np
        self.specs = list(specs or [])
        self.rates = dict(rates or {})
        for site in self.rates:
            if site not in SITES:
                raise ValueError(f"unknown fault site {site!r}")
        if rate_kind not in KINDS:
            raise ValueError(f"unknown fault kind {rate_kind!r}")
        self.rate_kind = rate_kind
        self._rng = np.random.default_rng(seed)
        self._visits: Dict[str, int] = {}
        self.fired: List[FiredFault] = []
        self._lock = threading.Lock()

    @classmethod
    def parse(cls, spec: str, seed: int = 0) -> "FaultSchedule":
        """Parse a CLI spec: comma/semicolon-separated `site@visit:kind`
        entries, e.g. `"step.train@7:preempt,ckpt.save@1:torn"`.  The
        kind defaults to `error`."""
        specs = []
        for part in spec.replace(";", ",").split(","):
            part = part.strip()
            if not part:
                continue
            try:
                site, rest = part.split("@", 1)
                at, _, kind = rest.partition(":")
                specs.append(FaultSpec(site=site.strip(), at=int(at),
                                       kind=(kind.strip() or "error")))
            except ValueError as e:
                raise ValueError(
                    f"bad fault spec entry {part!r} (want "
                    f"site@visit[:kind]): {e}") from e
        return cls(specs, seed=seed)

    def visits(self, site: str) -> int:
        with self._lock:
            return self._visits.get(site, 0)

    def visit(self, site: str) -> Optional[str]:
        """Record one visit to `site`; raise / return the scheduled
        fault if any.  Returns the kind string for the non-raising
        (silent) kinds — "torn", "nan", "spike" — None otherwise."""
        with self._lock:
            n = self._visits.get(site, 0)
            self._visits[site] = n + 1
            kind = None
            for s in self.specs:
                if s.site == site and s.at == n and not s.fired:
                    s.fired = True
                    kind = s.kind
                    break
            if kind is None and site in self.rates:
                if self._rng.random() < self.rates[site]:
                    kind = self.rate_kind
            if kind is None:
                return None
            self.fired.append(FiredFault(site, n, kind, time.time()))
        if kind in SILENT_KINDS:
            return kind
        raise _KIND_EXC[kind](f"injected {kind} at {site} (visit {n})")


# -- process-wide activation ----------------------------------------------
_ACTIVE: Optional[FaultSchedule] = None


def active() -> Optional[FaultSchedule]:
    return _ACTIVE


def maybe_fault(site: str) -> Optional[str]:
    """Consult the active schedule at an instrumented site.  No-op
    (None) when no schedule is installed."""
    sch = _ACTIVE
    return sch.visit(site) if sch is not None else None


@contextmanager
def inject(schedule: Optional[FaultSchedule]):
    """Activate `schedule` for the dynamic extent of the block.  Nesting
    replaces (and restores) the outer schedule; None is a no-op."""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = schedule
    try:
        yield schedule
    finally:
        _ACTIVE = prev


# -- retry/backoff ---------------------------------------------------------
@dataclass
class Backoff:
    """Exponential backoff with seeded jitter — deterministic delays in
    tests, decorrelated retries in a fleet (every worker hashing its
    coordinates into `seed` avoids a retry stampede after a shared
    outage).  delay(k) = min(cap, base * 2^k) * (1 + jitter*u),
    u ~ U[0,1) from the seeded stream."""
    base: float = 0.5
    cap: float = 30.0
    jitter: float = 0.25
    seed: int = 0
    _rng: object = field(default=None, repr=False)

    def delay(self, attempt: int) -> float:
        import numpy as np
        if self._rng is None:
            self._rng = np.random.default_rng(self.seed)
        d = min(self.cap, self.base * (2.0 ** max(attempt, 0)))
        return d * (1.0 + self.jitter * float(self._rng.random()))

    def sleep(self, attempt: int) -> float:
        d = self.delay(attempt)
        if d > 0:
            time.sleep(d)
        return d


def retry_call(fn, attempts: int, backoff: Backoff, log=None,
               what: str = "operation"):
    """Run `fn()` with up to `attempts` total tries, sleeping the
    backoff between failures.  Preemptions are never retried here — they
    mean the whole process is going away, so they propagate to the
    supervisor immediately.  Returns fn()'s value, or raises the last
    failure after the budget is spent."""
    last: Optional[BaseException] = None
    for k in range(max(attempts, 1)):
        try:
            return fn()
        except Preemption:
            raise
        except Exception as e:  # noqa: BLE001 — retry any site failure
            last = e
            if log is not None:
                log(f"warning: {what} failed (attempt {k + 1}/"
                    f"{attempts}): {e}")
            if k + 1 < attempts:
                backoff.sleep(k)
    raise last
