"""Checkpoint / resume — finishing what the reference designed but never
implemented (Worker::Resume is an empty TODO, worker.cc:65-67;
Blob::ToProto/FromProto commented out, blob.cc:300-320; ModelProto.step
"last snapshot step", model.proto:34-35; kPretrained init,
model.proto:78-79).

Backed by orbax (the TPU-native checkpoint format: sharded-array aware,
atomic renames).  A checkpoint holds {params, opt_state, step} — the
same state triple the reference intended to snapshot (Param data_ +
history_ + step).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

try:
    import orbax.checkpoint as ocp
    _HAVE_ORBAX = True
except Exception:  # pragma: no cover
    _HAVE_ORBAX = False

# Parameter-layout generation.  Bump when a change re-orders elements
# inside a stored parameter without changing its shape (such restores
# would silently load permuted weights).  History:
#   1 — NCHW vision stack (InnerProduct vdim ordered (C, H, W))
#   2 — NHWC vision stack (vdim ordered (H, W, C), commit dd2e3aa)
LAYOUT_VERSION = 2


class LayoutMismatchError(RuntimeError):
    pass


class CheckpointManager:
    """Save/restore the training state triple under `workspace/checkpoints`
    (the reference's ClusterProto.workspace layout, cluster.proto:10-12)."""

    def __init__(self, workspace: str, max_to_keep: int = 3):
        self.dir = os.path.abspath(os.path.join(workspace, "checkpoints"))
        os.makedirs(self.dir, exist_ok=True)
        if _HAVE_ORBAX:
            self._mgr = ocp.CheckpointManager(
                self.dir,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=max_to_keep, create=True))
        else:
            self._mgr = None

    def _version_path(self) -> str:
        return os.path.join(self.dir, "LAYOUT_VERSION")

    def _write_version(self) -> None:
        with open(self._version_path(), "w") as f:
            f.write(str(LAYOUT_VERSION))

    def _check_version(self) -> None:
        """Refuse to restore checkpoints written under a different
        parameter layout: shapes match but element order does not
        (e.g. the v1→v2 NCHW→NHWC InnerProduct vdim reorder), so a
        silent restore would load permuted weights."""
        path = self._version_path()
        if not os.path.exists(path):
            got = 1   # pre-versioning checkpoints are the v1 layout
        else:
            with open(path) as f:
                got = int(f.read().strip() or 1)
        if got != LAYOUT_VERSION:
            raise LayoutMismatchError(
                f"checkpoint layout version {got} != current "
                f"{LAYOUT_VERSION}: parameters were stored with a "
                f"different element order (see LAYOUT_VERSION history "
                f"in singa_tpu/utils/checkpoint.py); re-train or "
                f"convert the checkpoint")

    def save(self, step: int, params: Dict[str, Any],
             opt_state: Dict[str, Any]) -> None:
        if self.latest_step() is not None:
            # never mix layouts in one directory: saving v-current into
            # a workspace still holding older-layout checkpoints would
            # retroactively bless them (the marker is per-directory)
            self._check_version()
        state = {"params": params, "opt_state": opt_state,
                 "step": np.asarray(step)}
        if self._mgr is not None:
            self._mgr.save(step, args=ocp.args.StandardSave(state))
            self._mgr.wait_until_finished()
        else:  # numpy fallback
            path = os.path.join(self.dir, f"step_{step}.npz")
            flat = _flatten("", state)
            np.savez(path, **{k: np.asarray(v) for k, v in flat.items()})
        # stamp only after a successful save: a failed save must not
        # mark the directory as holding current-layout checkpoints
        self._write_version()

    def latest_step(self) -> Optional[int]:
        if self._mgr is not None:
            return self._mgr.latest_step()
        steps = [int(f[5:-4]) for f in os.listdir(self.dir)
                 if f.startswith("step_") and f.endswith(".npz")]
        return max(steps) if steps else None

    def restore(self, step: Optional[int] = None,
                template: Optional[Dict[str, Any]] = None
                ) -> Optional[Tuple[Dict, Dict, int]]:
        """Returns (params, opt_state, step) or None if no checkpoint."""
        step = step if step is not None else self.latest_step()
        if step is None:
            return None
        self._check_version()
        if self._mgr is not None:
            if template is not None:
                target = {"params": template["params"],
                          "opt_state": template["opt_state"],
                          "step": np.asarray(0)}
                state = self._mgr.restore(
                    step, args=ocp.args.StandardRestore(target))
            else:
                state = self._mgr.restore(step)
            return state["params"], state["opt_state"], int(state["step"])
        path = os.path.join(self.dir, f"step_{step}.npz")
        data = np.load(path)
        state = _unflatten(dict(data.items()))
        return state["params"], state["opt_state"], int(state["step"])


def _flatten(prefix: str, tree) -> Dict[str, Any]:
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(f"{prefix}{k}|", v))
    else:
        out[prefix.rstrip("|")] = tree
    return out


def _unflatten(flat: Dict[str, Any]):
    root: Dict[str, Any] = {}
    for key, v in flat.items():
        parts = key.split("|")
        d = root
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = v
    return root


def load_pretrained(workspace: str, params: Dict[str, Any],
                    opt_state: Dict[str, Any]
                    ) -> Tuple[Dict[str, Any], Dict[str, Any], int]:
    """kPretrained init (param.cc model.proto:78-79): overwrite
    freshly-initialized params with the latest checkpoint, keeping any
    params absent from the snapshot (e.g. a new head)."""
    mgr = CheckpointManager(workspace)
    restored = mgr.restore(template={"params": params,
                                     "opt_state": opt_state})
    if restored is None:
        return params, opt_state, 0
    rp, ro, step = restored
    merged = {**params, **{k: v for k, v in rp.items() if k in params}}
    return merged, ro, step
