"""Checkpoint / resume — finishing what the reference designed but never
implemented (Worker::Resume is an empty TODO, worker.cc:65-67;
Blob::ToProto/FromProto commented out, blob.cc:300-320; ModelProto.step
"last snapshot step", model.proto:34-35; kPretrained init,
model.proto:78-79).

Backed by orbax (the TPU-native checkpoint format: sharded-array aware,
atomic renames).  A checkpoint holds {params, opt_state, step} — the
same state triple the reference intended to snapshot (Param data_ +
history_ + step).

Hardening (the failure-recovery tier the reference never shipped):
the no-orbax fallback writes tmp-file + atomic rename and records a
sha256 per snapshot in a checksummed MANIFEST.json (itself written
atomically); `restore` verifies the requested snapshot and *walks back*
to the previous good one past any corrupt/partial/unreadable snapshot
instead of crashing the resume — on both the orbax and fallback paths.
Save/restore consult the `ckpt.save` / `ckpt.restore` fault-injection
sites (utils.faults), so torn writes and restore failures are testable
on CPU.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from . import faults
from .. import obs

try:
    import orbax.checkpoint as ocp
    _HAVE_ORBAX = True
except Exception:  # pragma: no cover
    _HAVE_ORBAX = False

# Parameter-layout generation.  Bump when a change re-orders elements
# inside a stored parameter without changing its shape (such restores
# would silently load permuted weights).  History:
#   1 — NCHW vision stack (InnerProduct vdim ordered (C, H, W))
#   2 — NHWC vision stack (vdim ordered (H, W, C), commit dd2e3aa)
LAYOUT_VERSION = 2

_MANIFEST = "MANIFEST.json"


class LayoutMismatchError(RuntimeError):
    pass


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _atomic_write(path: str, data: bytes) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _tear(path: str) -> None:
    """Simulate a torn write (fault kind "torn"): truncate the snapshot
    to half — a save that returned success but left garbage on disk
    (lost page cache, dying disk).  On a directory (orbax layout) the
    largest file inside is torn."""
    if os.path.isdir(path):
        files = [os.path.join(r, f) for r, _, fs in os.walk(path)
                 for f in fs]
        if not files:
            return
        path = max(files, key=os.path.getsize)
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size // 2)


class CheckpointManager:
    """Save/restore the training state triple under `workspace/checkpoints`
    (the reference's ClusterProto.workspace layout, cluster.proto:10-12)."""

    def __init__(self, workspace: str, max_to_keep: int = 3,
                 log_fn=print):
        self.dir = os.path.abspath(os.path.join(workspace, "checkpoints"))
        self.log = log_fn
        os.makedirs(self.dir, exist_ok=True)
        # writer-concurrent polling state (fingerprint): the last token
        # this manager handed out, the last manifest stat whose content
        # parsed clean, and how many polls hit a mid-write/torn read
        # and degraded to "no change"
        self._last_fp: tuple = ((), None)
        self._man_checked: Optional[tuple] = None
        self._last_steps: List[int] = []
        self.torn_polls = 0
        if _HAVE_ORBAX:
            self._mgr = ocp.CheckpointManager(
                self.dir,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=max_to_keep, create=True))
        else:
            self._mgr = None

    def _version_path(self) -> str:
        return os.path.join(self.dir, "LAYOUT_VERSION")

    def _write_version(self) -> None:
        with open(self._version_path(), "w") as f:
            f.write(str(LAYOUT_VERSION))

    def _check_version(self) -> None:
        """Refuse to restore checkpoints written under a different
        parameter layout: shapes match but element order does not
        (e.g. the v1→v2 NCHW→NHWC InnerProduct vdim reorder), so a
        silent restore would load permuted weights."""
        path = self._version_path()
        if not os.path.exists(path):
            got = 1   # pre-versioning checkpoints are the v1 layout
        else:
            with open(path) as f:
                got = int(f.read().strip() or 1)
        if got != LAYOUT_VERSION:
            raise LayoutMismatchError(
                f"checkpoint layout version {got} != current "
                f"{LAYOUT_VERSION}: parameters were stored with a "
                f"different element order (see LAYOUT_VERSION history "
                f"in singa_tpu/utils/checkpoint.py); re-train or "
                f"convert the checkpoint")

    # -- manifest (no-orbax fallback) --------------------------------------
    def _manifest_path(self) -> str:
        return os.path.join(self.dir, _MANIFEST)

    def _read_manifest(self) -> Dict[str, Any]:
        try:
            with open(self._manifest_path()) as f:
                return json.load(f)
        except FileNotFoundError:
            return {}
        except (json.JSONDecodeError, OSError) as e:
            # a corrupt manifest must not take every snapshot with it:
            # entries degrade to "legacy" (load-verified only)
            self.log(f"warning: checkpoint manifest unreadable ({e}); "
                     f"verifying snapshots by load only")
            return {}

    def _manifest_record(self, step: int, path: str,
                         health: Optional[Dict[str, Any]] = None) -> None:
        man = self._read_manifest()
        entry: Dict[str, Any] = {
            "step": step,
            "size": os.path.getsize(path),
            "sha256": _sha256_file(path),
        }
        if health is not None:
            entry["health"] = health
        man[os.path.basename(path)] = entry
        _atomic_write(self._manifest_path(),
                      json.dumps(man, indent=1, sort_keys=True).encode())

    def _health_key(self, step: int) -> str:
        """Manifest key carrying a snapshot's health record: the npz
        file name on the fallback path, the bare step on orbax (whose
        snapshot is a directory orbax owns — the manifest only rides
        along as verdict metadata there)."""
        return f"step_{step}.npz" if self._mgr is None else str(step)

    def health_verdict(self, step: int) -> Optional[str]:
        """The health verdict recorded at save time ("ok" / "spike" /
        "diverged" / "nonfinite"), or None for snapshots saved without
        a monitor (legacy/pre-health checkpoints — treated as ok by the
        skip_unhealthy walk-back, matching pre-manifest snapshots being
        load-verified only)."""
        entry = self._read_manifest().get(self._health_key(step))
        if not isinstance(entry, dict):
            return None
        health = entry.get("health")
        return health.get("verdict") if isinstance(health, dict) else None

    def _verify_fallback(self, step: int) -> Optional[str]:
        """Path of a checksum-clean snapshot for `step`, else None
        (missing / size or sha mismatch).  Snapshots with no manifest
        entry (pre-manifest checkpoints) pass here and are verified by
        the np.load in restore."""
        path = os.path.join(self.dir, f"step_{step}.npz")
        if not os.path.exists(path):
            return None
        entry = self._read_manifest().get(os.path.basename(path))
        if entry is not None:
            if (os.path.getsize(path) != entry.get("size")
                    or _sha256_file(path) != entry.get("sha256")):
                return None
        return path

    def save(self, step: int, params: Dict[str, Any],
             opt_state: Dict[str, Any],
             health: Optional[Dict[str, Any]] = None) -> None:
        """Snapshot the state triple.  `health` (from
        HealthMonitor.snapshot_health) is recorded in MANIFEST.json so
        `restore(skip_unhealthy=True)` can walk back past snapshots
        taken in a numerically suspect window."""
        with obs.span("ckpt.save", step=step,
                      verdict=(health or {}).get("verdict")):
            self._save(step, params, opt_state, health)

    def _save(self, step: int, params: Dict[str, Any],
              opt_state: Dict[str, Any],
              health: Optional[Dict[str, Any]] = None) -> None:
        if self.latest_step() is not None:
            # never mix layouts in one directory: saving v-current into
            # a workspace still holding older-layout checkpoints would
            # retroactively bless them (the marker is per-directory)
            self._check_version()
        act = faults.maybe_fault("ckpt.save")
        state = {"params": params, "opt_state": opt_state,
                 "step": np.asarray(step)}
        if self._mgr is not None:
            stepdir = os.path.join(self.dir, str(step))
            if os.path.isdir(stepdir) and not self._finalized(step):
                # the wreck of a previous writer killed mid-save of
                # this very step (a resumed trainer replays through its
                # death step).  Orbax treats the existing directory as
                # "step already saved" and silently skips the write —
                # the snapshot would be LOST while the manifest records
                # a verdict for it — so clear the wreck first.
                self.log(f"warning: clearing unfinalized checkpoint "
                         f"directory for step {step} (previous writer "
                         f"died mid-save); re-saving")
                shutil.rmtree(stepdir, ignore_errors=True)
                self._mgr.reload()
            self._mgr.save(step, args=ocp.args.StandardSave(state))
            self._mgr.wait_until_finished()
            if act == "torn":
                _tear(os.path.join(self.dir, str(step)))
                return   # crash before the version stamp
            if health is not None:
                man = self._read_manifest()
                man[self._health_key(step)] = {"step": step,
                                               "health": health}
                _atomic_write(self._manifest_path(),
                              json.dumps(man, indent=1,
                                         sort_keys=True).encode())
        else:
            path = os.path.join(self.dir, f"step_{step}.npz")
            flat = _flatten("", state)
            arrays = {k: np.asarray(v) for k, v in flat.items()}
            # tmp + atomic rename: a crash mid-write leaves a *.tmp the
            # reader never lists, not a torn step_N.npz that a resume
            # would trip over (the reference's shard store solved the
            # same problem by truncating torn tails, shard.cc:175-206)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                np.savez(f, **arrays)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            if act == "torn":
                # the rename "succeeded" but the data pages never hit
                # the platter; no manifest entry either (crash before)
                _tear(path)
                return
            self._manifest_record(step, path, health=health)
        # stamp only after a successful save: a failed save must not
        # mark the directory as holding current-layout checkpoints
        self._write_version()

    def _finalized(self, step: int) -> bool:
        """Whether an orbax step directory finished its save: orbax
        writes `_CHECKPOINT_METADATA` last, so a directory without it
        is a save in flight — or the wreck of a writer that died
        mid-save (a real SIGKILL, not the injected `torn` kind)."""
        return os.path.isfile(os.path.join(self.dir, str(step),
                                           "_CHECKPOINT_METADATA"))

    def available_steps(self) -> List[int]:
        """All *finalized* snapshot steps present on disk, ascending
        (readable or not — restore decides validity).

        Writer-concurrent contract (same as `fingerprint`): never
        raises against a live writer.  A directory listing caught
        mid-save/mid-rename returns the previous good listing (counted
        in `torn_polls`), and an orbax step directory whose save never
        finished — in flight right now, or orphaned by a writer killed
        mid-save — is not listed, so a serving poll neither reloads a
        half-written step nor crashes walking it."""
        try:
            if self._mgr is not None:
                # orbax caches the step list per manager instance;
                # refresh from disk so a reader sees saves made by
                # OTHER managers (the serving tier polls the trainer's
                # workspace)
                self._mgr.reload()
                steps = sorted(int(s) for s in self._mgr.all_steps()
                               if self._finalized(int(s)))
            else:
                steps = sorted(int(f[5:-4])
                               for f in os.listdir(self.dir)
                               if f.startswith("step_")
                               and f.endswith(".npz"))
        except Exception:  # noqa: BLE001 — any torn/mid-write listing
            self.torn_polls += 1
            return list(self._last_steps)
        self._last_steps = steps
        return steps

    def latest_step(self) -> Optional[int]:
        steps = self.available_steps()
        return steps[-1] if steps else None

    def fingerprint(self) -> tuple:
        """Cheap change token for hot-reload polling (serve tier): the
        set of snapshot steps on disk plus the MANIFEST.json stat
        (mtime_ns, size).  A new save — or a re-save carrying a new
        health verdict — changes it; comparing tokens costs two
        directory stats (plus one manifest parse per *change*), so a
        server can poll every second without touching snapshot data.

        Writer-concurrent contract: this NEVER raises.  A reader racing
        a live writer — a step list read mid-save, a MANIFEST.json
        caught mid-rename or half-written — surfaces as "no change"
        (the previous token is returned and `torn_polls` counts the
        degrade), so a poll loop retries on its next tick instead of
        crashing or reloading a torn step.  The parse check matters:
        a torn manifest loses every health verdict, so acting on its
        stat alone could hot-reload a DIVERGED snapshot as if it were
        blessed."""
        try:
            steps = tuple(self.available_steps())
            try:
                st = os.stat(self._manifest_path())
                man = (st.st_mtime_ns, st.st_size)
            except FileNotFoundError:
                man = None
            if man is not None and man != self._man_checked:
                # the stat moved: prove the content is whole before
                # handing out a token that would trigger reloads
                with open(self._manifest_path()) as f:
                    json.load(f)
                self._man_checked = man
        except Exception:  # noqa: BLE001 — any torn/mid-write read
            self.torn_polls += 1
            return self._last_fp
        self._last_fp = (steps, man)
        return self._last_fp

    def restore(self, step: Optional[int] = None,
                template: Optional[Dict[str, Any]] = None,
                skip_unhealthy: bool = False
                ) -> Optional[Tuple[Dict, Dict, int]]:
        """Returns (params, opt_state, step) or None if no checkpoint.

        A corrupt/partial/unreadable snapshot at the requested (or
        latest) step does not fail the resume: it is logged and skipped,
        and the next older snapshot is tried — the previous *good*
        checkpoint wins (TrainingAborted only when none is loadable).

        With `skip_unhealthy`, snapshots whose recorded health verdict
        is not "ok" (see `save`'s `health` record) are skipped the same
        way: the restore walks back to the last *numerically good*
        snapshot, not just the last readable one — the rollback the
        Supervisor's divergence rescue relies on.  Snapshots with no
        health record (saved without a monitor) count as ok."""
        with obs.span("ckpt.restore",
                      skip_unhealthy=skip_unhealthy) as sp:
            out = self._restore(step, template, skip_unhealthy)
            if out is not None:
                sp.set(step=out[2])
            return out

    def _restore(self, step: Optional[int],
                 template: Optional[Dict[str, Any]],
                 skip_unhealthy: bool
                 ) -> Optional[Tuple[Dict, Dict, int]]:
        steps = self.available_steps()
        if step is not None:
            steps = [s for s in steps if s <= step]
        if not steps:
            return None
        self._check_version()
        faults.maybe_fault("ckpt.restore")
        for s in reversed(steps):
            if skip_unhealthy:
                verdict = self.health_verdict(s)
                if verdict is not None and verdict != "ok":
                    self.log(f"warning: checkpoint step {s} has health "
                             f"verdict {verdict!r}; skipping to the "
                             f"previous snapshot")
                    continue
            try:
                out = self._restore_one(s, template)
            except LayoutMismatchError:
                raise
            except Exception as e:  # noqa: BLE001 — any torn snapshot
                self.log(f"warning: checkpoint step {s} is corrupt or "
                         f"partial ({type(e).__name__}: {e}); skipping "
                         f"to the previous snapshot")
                continue
            if out is not None:
                return out
        self.log(f"warning: no restorable checkpoint among steps "
                 f"{steps} in {self.dir}")
        return None

    def _restore_one(self, step: int,
                     template: Optional[Dict[str, Any]]
                     ) -> Optional[Tuple[Dict, Dict, int]]:
        if self._mgr is not None:
            if template is not None:
                target = {"params": template["params"],
                          "opt_state": template["opt_state"],
                          "step": np.asarray(0)}
                state = self._mgr.restore(
                    step, args=ocp.args.StandardRestore(target))
            else:
                # templateless restore (serving tier: the engine knows
                # params only, not the optimizer tree) — orbax rebuilds
                # the saved topology; safe here because save() always
                # writes the same {params, opt_state, step} triple.
                # orbax warns about exactly this on every call, which
                # would spam the serving reload poll — mute it.
                import logging
                absl_log = logging.getLogger("absl")
                prev = absl_log.level
                absl_log.setLevel(logging.ERROR)
                try:
                    state = self._mgr.restore(
                        step, args=ocp.args.StandardRestore())
                finally:
                    absl_log.setLevel(prev)
            return state["params"], state["opt_state"], int(state["step"])
        path = self._verify_fallback(step)
        if path is None:
            raise IOError(f"snapshot step_{step}.npz missing or "
                          f"checksum mismatch vs manifest")
        data = np.load(path)
        state = _unflatten({k: data[k] for k in data.files})
        return state["params"], state["opt_state"], int(state["step"])


def _flatten(prefix: str, tree) -> Dict[str, Any]:
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(f"{prefix}{k}|", v))
    else:
        out[prefix.rstrip("|")] = tree
    return out


def _unflatten(flat: Dict[str, Any]):
    root: Dict[str, Any] = {}
    for key, v in flat.items():
        parts = key.split("|")
        d = root
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = v
    return root


def load_pretrained(workspace: str, params: Dict[str, Any],
                    opt_state: Dict[str, Any]
                    ) -> Tuple[Dict[str, Any], Dict[str, Any], int]:
    """kPretrained init (param.cc model.proto:78-79): overwrite
    freshly-initialized params with the latest checkpoint, keeping any
    params absent from the snapshot (e.g. a new head)."""
    mgr = CheckpointManager(workspace)
    restored = mgr.restore(template={"params": params,
                                     "opt_state": opt_state})
    if restored is None:
        return params, opt_state, 0
    rp, ro, step = restored
    merged = {**params, **{k: v for k, v in rp.items() if k in params}}
    return merged, ro, step
