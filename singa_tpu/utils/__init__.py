from .checkpoint import CheckpointManager, load_pretrained
from .faults import (Backoff, CorruptRecord, FaultError, FaultSchedule,
                     FaultSpec, Preemption, inject, maybe_fault)
from .profiler import trace, StepTimer, flops_of
