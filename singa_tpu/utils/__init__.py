from .checkpoint import CheckpointManager, load_pretrained
from .profiler import trace, StepTimer, flops_of
