from .checkpoint import CheckpointManager, load_pretrained
from .faults import (Backoff, CorruptRecord, FaultError, FaultSchedule,
                     FaultSpec, Preemption, inject, maybe_fault)
from .health import (HealthMonitor, HealthSpec, NumericDivergence,
                     delta_health, health_probes)
from .profiler import trace, StepTimer, flops_of
