"""Numeric-health sentinel: silent-failure detection for the training
runtime.

PR 1's Supervisor recovers from *loud* failures (exceptions,
preemptions, torn writes) but a NaN loss, an exploding gradient, or a
corrupted replica delta trains on happily, gets checkpointed, and then
restore-latest faithfully resumes the divergence.  This module is the
guardrail tier:

- **Device-side probes** (`health_probes`) — global gradient norm,
  post-update parameter norm, and the update ratio ||Δp||/||p|| —
  computed INSIDE the compiled train step (a few fused reductions, no
  extra dispatch) and returned through the ordinary metrics dict, so
  they ride the deferred metrics ring and cost zero additional host
  syncs on the hot path (docs/PERFORMANCE.md).
- **Host-side classification** (`HealthMonitor`) — rolling median/MAD
  windows over loss and grad norm plus EWMA trackers, consulted as the
  ring drains: each step is classified OK / SPIKE / NONFINITE /
  DIVERGED.  Only OK values enter the windows, so a poisoned regime
  never normalizes itself.
- **Structured failure** (`NumericDivergence`) — raised by the trainer
  when a verdict is fatal; carries (step, metric, value, threshold) so
  the Supervisor can roll back *past* the divergence (checkpoint
  verdicts are recorded in MANIFEST.json; `restore(skip_unhealthy=True)`
  walks back to the last numerically good snapshot) and apply a rescue
  policy (blame-batch skip, one-shot LR backoff).
- **Sync validation** (`delta_health`) — finite/norm check for a
  replica's contribution before it touches the elastic center
  (parallel/elastic.py rejects poisoned deltas as skipped rounds and
  quarantines repeat offenders).

Verdict lifecycle: probe (device) → classify (host, at ring drain) →
quarantine (refuse checkpoint / reject sync) → rescue (Supervisor
rollback + policy).  Every path is deterministically testable on CPU
via the `nan`/`spike` fault kinds at the `step.grad` and `sync.delta`
sites (utils.faults).
"""

from __future__ import annotations

import math
from collections import deque
from dataclasses import dataclass, field, fields
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

# verdict statuses, ordered benign -> fatal
OK = "ok"
SPIKE = "spike"
DIVERGED = "diverged"
NONFINITE = "nonfinite"
_SEVERITY = {OK: 0, SPIKE: 1, DIVERGED: 2, NONFINITE: 3}
FATAL = (DIVERGED, NONFINITE)

#: gradient scale applied by the "spike" fault kind (utils.faults) —
#: big enough that any sane MAD window flags it, small enough that the
#: poisoned step stays finite in float32 (the point of `spike` vs `nan`)
SPIKE_SCALE = 1e3

#: metric keys the compiled step contributes (health_probes) — namespaced
#: so they coexist with model metrics in the deferred ring / Performance
GRAD_NORM = "health/grad_norm"
PARAM_NORM = "health/param_norm"
UPDATE_RATIO = "health/update_ratio"


class NumericDivergence(RuntimeError):
    """Training state is numerically poisoned: a probe went non-finite
    or a hard/rolling threshold was breached past patience.  Structured
    so the Supervisor's rescue policy can reason about it."""

    def __init__(self, step: int, metric: Optional[str],
                 value: Optional[float], threshold: Optional[float],
                 status: str = DIVERGED):
        self.step = int(step)
        self.metric = metric
        self.value = value
        self.threshold = threshold
        self.status = status
        thr = (f" (threshold {threshold:.6g})"
               if threshold is not None else "")
        val = f"={value:.6g}" if value is not None else ""
        super().__init__(f"numeric divergence at step {step}: "
                         f"{status} {metric or 'metrics'}{val}{thr}")


@dataclass
class HealthSpec:
    """Thresholds for the monitor plus the Supervisor's rescue policy
    (one spec so `--health_spec` configures the whole tier).

    A cap of 0 disables that check.  `spike_mad` is the MAD-multiple
    deviation from the rolling median that flags a SPIKE; `patience`
    consecutive SPIKEs escalate to DIVERGED."""
    grad_norm_max: float = 1e6      # hard cap -> DIVERGED
    loss_max: float = 0.0           # hard cap on loss (0 = off)
    update_ratio_max: float = 10.0  # hard cap on ||Δp||/||p||
    param_drift_max: float = 0.0    # param_norm vs its EWMA (0 = off)
    spike_mad: float = 10.0         # MAD multiples -> SPIKE
    window: int = 64                # rolling window length
    warmup: int = 8                 # OK observations before MAD tests
    patience: int = 3               # consecutive SPIKEs -> DIVERGED
    ewma_alpha: float = 0.1
    # rescue policy (consumed by the Supervisor via main.py)
    max_divergences: int = 2        # divergence restart budget
    blame_batches: int = 0          # batches skipped at the crash step
    lr_backoff: float = 0.0         # one-shot LR scale on rescue (0=off)

    _INT = ("window", "warmup", "patience", "max_divergences",
            "blame_batches")

    @classmethod
    def parse(cls, spec: Optional[str]) -> "HealthSpec":
        """CLI grammar: comma/semicolon-separated `key=value` entries,
        e.g. `"grad_norm_max=1e4,spike_mad=8,patience=3,lr_backoff=0.5"`.
        Keys are the HealthSpec field names."""
        out = cls()
        if not spec:
            return out
        known = {f.name for f in fields(cls) if not f.name.startswith("_")}
        for part in spec.replace(";", ",").split(","):
            part = part.strip()
            if not part:
                continue
            key, sep, val = part.partition("=")
            key = key.strip()
            if not sep or key not in known:
                raise ValueError(
                    f"bad health spec entry {part!r} (want key=value "
                    f"with key in {sorted(known)})")
            try:
                setattr(out, key, int(val) if key in cls._INT
                        else float(val))
            except ValueError as e:
                raise ValueError(
                    f"bad health spec value for {key!r}: {val!r}") from e
        return out


@dataclass
class Verdict:
    """One step's classification."""
    step: int
    status: str
    metric: Optional[str] = None
    value: Optional[float] = None
    threshold: Optional[float] = None

    @property
    def fatal(self) -> bool:
        return self.status in FATAL

    def to_error(self) -> NumericDivergence:
        return NumericDivergence(self.step, self.metric, self.value,
                                 self.threshold, status=self.status)


# -- device-side probes -----------------------------------------------------
def _sqsum(tree) -> jnp.ndarray:
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.asarray(0.0, jnp.float32)
    return sum(jnp.sum(jnp.square(x.astype(jnp.float32)))
               for x in leaves)


def health_probes(grads, params, new_params) -> Dict[str, jnp.ndarray]:
    """Device-side numeric probes for one train step, traced INSIDE the
    compiled program: global grad L2 norm, post-update param norm, and
    update ratio ||new - old|| / (||new|| + eps).  Returned as ordinary
    metric scalars so they stay device-resident in the deferred ring
    and reach the host only at drain boundaries."""
    gn = jnp.sqrt(_sqsum(grads))
    pn = jnp.sqrt(_sqsum(new_params))
    old = jax.tree_util.tree_leaves(params)
    new = jax.tree_util.tree_leaves(new_params)
    un = jnp.sqrt(sum(
        jnp.sum(jnp.square((a - b).astype(jnp.float32)))
        for a, b in zip(new, old)) if old else jnp.asarray(0.0))
    return {GRAD_NORM: gn, PARAM_NORM: pn,
            UPDATE_RATIO: un / (pn + 1e-12)}


def _delta_stats(tree, ref):
    """(norm, all_finite) of (tree - ref), one fused reduction."""
    t = jax.tree_util.tree_leaves(tree)
    r = jax.tree_util.tree_leaves(ref)
    sq = jnp.asarray(0.0, jnp.float32)
    finite = jnp.asarray(True)
    for a, b in zip(t, r):
        d = (a - b).astype(jnp.float32)
        sq = sq + jnp.sum(jnp.square(d))
        finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(d)))
    return jnp.sqrt(sq), finite


_delta_stats_jit = jax.jit(_delta_stats)


def delta_health(tree, ref=None, max_norm: float = 0.0
                 ) -> tuple[bool, float]:
    """Validate a sync contribution before it touches the center:
    returns (ok, delta_norm).  `ref` defaults to zeros (plain
    finiteness check); `max_norm > 0` additionally caps the delta
    norm.  One small jitted reduction — sync rounds are infrequent, so
    the host sync here is off the hot path."""
    if ref is None:
        ref = jax.tree_util.tree_map(jnp.zeros_like, tree)
    norm, finite = _delta_stats_jit(tree, ref)
    norm = float(norm)
    ok = bool(finite) and math.isfinite(norm)
    if ok and max_norm and max_norm > 0:
        ok = norm <= max_norm
    return ok, norm


# -- host-side monitor ------------------------------------------------------
class HealthMonitor:
    """Classify each step's probe metrics as the deferred ring drains.

    `observe(step, metrics)` returns a `Verdict`; the trainer raises
    `verdict.to_error()` on fatal ones.  `snapshot_health()` /
    `mark_snapshot()` bracket checkpoint saves: the recorded verdict is
    the WORST status since the previous snapshot, so a save taken in a
    suspect window is marked and `restore(skip_unhealthy=True)` can
    walk past it."""

    def __init__(self, spec: Optional[HealthSpec] = None, log_fn=print):
        self.spec = spec or HealthSpec()
        self.log = log_fn
        self.reset()

    def reset(self) -> None:
        """Forget all rolling state (Supervisor calls this per attempt:
        statistics from a poisoned run must not pollute the retry)."""
        w = max(self.spec.window, 4)
        self._windows = {"loss": deque(maxlen=w),
                         "grad_norm": deque(maxlen=w)}
        self._ewma: Dict[str, float] = {}
        self._spike_run = 0
        self.counts: Dict[str, int] = {OK: 0, SPIKE: 0, DIVERGED: 0,
                                       NONFINITE: 0}
        self.last_verdict: Optional[Verdict] = None
        self._since_snapshot = OK
        self._last_vals: Dict[str, float] = {}

    def register_into(self, registry,
                      prefix: str = "singa_health") -> None:
        """Register the verdict tallies into an `obs.MetricsRegistry`
        as a pull-time collector — additive; classification semantics
        and the monitor's own API are untouched.  (Counts reset per
        Supervisor attempt, exactly like `self.counts` always has.)"""
        from ..obs.metrics import Sample

        def collect():
            return [Sample(f"{prefix}_verdict_{status}_total",
                           "counter",
                           f"steps classified {status.upper()} "
                           f"(current attempt)", float(n))
                    for status, n in sorted(self.counts.items())]

        registry.register_collector(collect)

    # -- classification ----------------------------------------------------
    @staticmethod
    def _extract(metrics: Dict[str, Any]) -> Dict[str, float]:
        vals = {}
        for name, key in (("loss", "loss"), ("grad_norm", GRAD_NORM),
                          ("param_norm", PARAM_NORM),
                          ("update_ratio", UPDATE_RATIO)):
            if key in metrics:
                try:
                    vals[name] = float(metrics[key])
                except (TypeError, ValueError):  # pragma: no cover
                    continue
        return vals

    def _mad_spike(self, name: str, v: float):
        """(deviation, threshold) when `v` is a MAD-outlier vs the
        rolling window, else None."""
        win = self._windows[name]
        if len(win) < max(self.spec.warmup, 2):
            return None
        vals = sorted(win)
        n = len(vals)
        med = (vals[n // 2] if n % 2 else
               0.5 * (vals[n // 2 - 1] + vals[n // 2]))
        mad = sorted(abs(x - med) for x in vals)[n // 2]
        # floor the scale: a perfectly flat window (synthetic data,
        # converged loss) must not turn float jitter into spikes
        scale = max(mad, 1e-3 * abs(med), 1e-8)
        thr = self.spec.spike_mad * scale
        dev = abs(v - med)
        return (dev, med + thr if v >= med else med - thr) \
            if dev > thr else None

    def observe(self, step: int, metrics: Dict[str, Any]) -> Verdict:
        vals = self._extract(metrics)
        self._last_vals = dict(vals)
        status, metric, value, threshold = OK, None, None, None

        for name, v in vals.items():
            if not math.isfinite(v):
                status, metric, value = NONFINITE, name, v
                break
        if status == OK:
            for name, cap in (("grad_norm", self.spec.grad_norm_max),
                              ("loss", self.spec.loss_max),
                              ("update_ratio",
                               self.spec.update_ratio_max)):
                if cap and cap > 0 and name in vals and vals[name] > cap:
                    status, metric, value, threshold = \
                        DIVERGED, name, vals[name], cap
                    break
        if status == OK and self.spec.param_drift_max > 0:
            pn, ew = vals.get("param_norm"), self._ewma.get("param_norm")
            if (pn is not None and ew is not None and ew > 0
                    and pn > self.spec.param_drift_max * ew):
                status, metric, value = SPIKE, "param_norm", pn
                threshold = self.spec.param_drift_max * ew
        if status == OK:
            for name in ("grad_norm", "loss"):
                v = vals.get(name)
                hit = self._mad_spike(name, v) if v is not None else None
                if hit is not None:
                    status, metric, value, threshold = \
                        SPIKE, name, v, hit[1]
                    break

        if status == SPIKE:
            self._spike_run += 1
            if (self.spec.patience > 0
                    and self._spike_run >= self.spec.patience):
                status = DIVERGED
        elif status == OK:
            self._spike_run = 0
            for name in ("grad_norm", "loss"):
                if name in vals:
                    self._windows[name].append(vals[name])
            a = self.spec.ewma_alpha
            for name in ("param_norm", "update_ratio"):
                if name in vals:
                    prev = self._ewma.get(name)
                    self._ewma[name] = (vals[name] if prev is None
                                        else (1 - a) * prev
                                        + a * vals[name])

        verdict = Verdict(step, status, metric, value, threshold)
        self.last_verdict = verdict
        self.counts[status] += 1
        if _SEVERITY[status] > _SEVERITY[self._since_snapshot]:
            self._since_snapshot = status
        if status == SPIKE:
            self.log(f"warning: health SPIKE at step {step}: "
                     f"{metric}={value:.6g} vs rolling threshold "
                     f"{threshold:.6g} "
                     f"({self._spike_run}/{self.spec.patience} toward "
                     f"divergence)")
        elif verdict.fatal:
            self.log(f"health: {status.upper()} at step {step}: "
                     f"{metric}={value!r}"
                     + (f" (threshold {threshold:.6g})"
                        if threshold is not None else ""))
        return verdict

    # -- checkpoint bracket -------------------------------------------------
    def snapshot_health(self) -> Dict[str, Any]:
        """Verdict record for the snapshot about to be saved: the worst
        status since the last snapshot plus the final probe values —
        written into the checkpoint MANIFEST so `skip_unhealthy`
        restores can walk past suspect snapshots."""
        rec: Dict[str, Any] = {"verdict": self._since_snapshot}
        for name in ("loss", "grad_norm"):
            if name in self._last_vals:
                v = self._last_vals[name]
                rec[name] = v if math.isfinite(v) else repr(v)
        return rec

    def ok_to_save(self) -> bool:
        """False when the state that would be snapshotted is known
        poisoned — the trainer refuses the save outright (a SPIKE
        window still saves, but marked, so walk-back can skip it)."""
        return self._since_snapshot not in FATAL

    def mark_snapshot(self) -> None:
        self._since_snapshot = OK
