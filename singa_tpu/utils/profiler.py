"""Profiling — the reference's TimerInfo phase report (worker.h:91-114)
plus TPU-native jax.profiler traces.

The reference accumulates tForward_/tBackward_/tSyncData_/tSyncParam_
around each phase and prints "% of step per phase".  Under XLA the
fwd/bwd/update are one fused program, so the phase split comes from the
profiler trace instead; the host-visible split (data wait vs device
step) is kept in trainer.TimerInfo with the same report format.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Iterator, Optional

import jax


def hard_sync(tree) -> None:
    """Force completion of all pending device work feeding `tree`.

    `jax.block_until_ready` is the documented barrier, but experimental
    transport backends (e.g. the tunneled `axon` platform) can return
    from it before execution finishes, which silently corrupts wall-clock
    timing (we observed impossible >200% MFU).  Fetching bytes to the
    host cannot complete early, so timing code must use this instead.
    """
    import numpy as np
    leaves = jax.tree_util.tree_leaves(tree)
    for leaf in leaves:
        if hasattr(leaf, "addressable_shards") or hasattr(leaf, "device"):
            np.asarray(jax.device_get(leaf.ravel()[:1] if leaf.ndim else leaf))
            break


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    """Capture a jax.profiler trace viewable in TensorBoard/XProf."""
    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Wall-clock step timing with compile-step exclusion."""

    def __init__(self, skip_first: int = 1):
        self.skip = skip_first
        self.times = []
        self._t0: Optional[float] = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        if self.skip > 0:
            self.skip -= 1
        else:
            self.times.append(dt)

    def mean(self) -> float:
        return sum(self.times) / max(len(self.times), 1)

    def steps_per_sec(self) -> float:
        m = self.mean()
        return 1.0 / m if m else 0.0


def flops_of(fn, *args) -> Optional[float]:
    """Analytical FLOP estimate of a jitted function via XLA cost
    analysis — used for MFU reporting in bench.py."""
    try:
        lowered = jax.jit(fn).lower(*args)
        cost = lowered.compile().cost_analysis()
        if isinstance(cost, list):
            cost = cost[0]
        return float(cost.get("flops", 0.0)) if cost else None
    except Exception:
        return None
