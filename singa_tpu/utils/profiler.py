"""Profiling — the reference's TimerInfo phase report (worker.h:91-114)
plus TPU-native jax.profiler traces.

The reference accumulates tForward_/tBackward_/tSyncData_/tSyncParam_
around each phase and prints "% of step per phase".  Under XLA the
fwd/bwd/update are one fused program, so the phase split comes from the
profiler trace instead; the host-visible split (data wait vs device
step) is kept in trainer.TimerInfo with the same report format.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Iterator, Optional

import jax


def hard_sync(tree) -> None:
    """Force completion of all pending device work feeding `tree`.

    `jax.block_until_ready` is the documented barrier, but experimental
    transport backends (e.g. the tunneled `axon` platform) can return
    from it before execution finishes, which silently corrupts wall-clock
    timing (we observed impossible >200% MFU).  Fetching bytes to the
    host cannot complete early, so timing code must use this instead.
    """
    import numpy as np
    leaves = jax.tree_util.tree_leaves(tree)
    for leaf in leaves:
        if hasattr(leaf, "addressable_shards") or hasattr(leaf, "device"):
            np.asarray(jax.device_get(leaf.ravel()[:1] if leaf.ndim else leaf))
            break


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    """Capture a jax.profiler trace viewable in TensorBoard/XProf."""
    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Wall-clock step timing with compile-step exclusion."""

    def __init__(self, skip_first: int = 1):
        self.skip = skip_first
        self.times = []
        self._t0: Optional[float] = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        if self.skip > 0:
            self.skip -= 1
        else:
            self.times.append(dt)

    def mean(self) -> float:
        return sum(self.times) / max(len(self.times), 1)

    def steps_per_sec(self) -> float:
        m = self.mean()
        return 1.0 / m if m else 0.0


def hlo_attribution(compiled_text: str) -> dict:
    """HLO instruction name → "op_name  [file:line]" tag from the
    compiled module's metadata (the mapping tools/profile_step.py
    prints next to each hot op)."""
    import re

    attr = {}
    for m in re.finditer(
            r"%?([\w.\-]+) = [^\n]*metadata={([^}]*)}", compiled_text):
        name, meta = m.group(1), m.group(2)
        op = re.search(r'op_name="([^"]*)"', meta)
        src = re.search(r'source_file="([^"]*)"', meta)
        line = re.search(r"source_line=(\d+)", meta)
        tag = op.group(1) if op else ""
        if src:
            tag += (f"  [{os.path.basename(src.group(1))}:"
                    f"{line.group(1) if line else '?'}]")
        if tag:
            attr[name] = tag
    return attr


def parse_trace_ops(outdir: str):
    """Per-op device time from the newest profiler trace under `outdir`:
    returns (Counter op-name → microseconds, total_us).  Device pids
    cover TPU and the CPU backend (tests)."""
    import collections
    import glob
    import gzip
    import json

    paths = glob.glob(os.path.join(
        outdir, "plugins/profile/*/*.trace.json.gz"))
    if not paths:
        raise FileNotFoundError(f"no profiler trace under {outdir}")
    with gzip.open(max(paths, key=os.path.getmtime), "rt") as f:
        events = json.load(f)["traceEvents"]
    pid_names = {e["pid"]: e["args"]["name"] for e in events
                 if e.get("ph") == "M" and e.get("name") == "process_name"
                 and "args" in e}
    dev_pids = {p for p, n in pid_names.items()
                if "TPU" in n or "/device" in n.lower()
                or "cpu" in n.lower()}
    per_op = collections.Counter()
    for e in events:
        if e.get("ph") == "X" and e.get("pid") in dev_pids:
            per_op[e.get("name", "?")] += e.get("dur", 0)
    return per_op, sum(per_op.values())


def classify_phase(tag: str) -> str:
    """fwd / bwd / update from an HLO attribution tag.

    The jaxpr path in op_name marks reverse-mode ops with transpose(
    (value_and_grad's backward); updater ops carry updater.py source.
    An XLA fusion spanning phases keeps one representative metadata —
    the shares are a per-fusion attribution, not an exact split (the
    reference's per-phase timers had the same blur from async queues,
    worker.h:91-114)."""
    if "updater.py" in tag:
        return "update"
    if "transpose(" in tag:
        return "bwd"
    return "fwd"


def phase_shares(outdir: str, compiled_text: str) -> dict:
    """{"fwd": f, "bwd": b, "update": u, "coverage": c} — phase
    fractions of ATTRIBUTED device time plus the attributed/total
    coverage ratio, from a captured trace + the compiled module text.
    Coverage travels with the shares so the report can qualify them:
    a fusion spanning phases keeps one representative metadata (see
    classify_phase), and at small-model scale that blur can swallow a
    whole phase — "update 0%" with 70% coverage is attribution loss,
    not a free optimizer."""
    per_op, total = parse_trace_ops(outdir)
    attr = hlo_attribution(compiled_text)
    shares = {"fwd": 0.0, "bwd": 0.0, "update": 0.0}
    attributed = 0
    for name, us in per_op.items():
        tag = attr.get(name.split("(")[0])
        if tag is None:
            continue
        attributed += us
        shares[classify_phase(tag)] += us
    denom = attributed or total or 1
    out = {k: v / denom for k, v in shares.items()}
    out["coverage"] = attributed / (total or 1)
    return out


def flops_of(fn, *args) -> Optional[float]:
    """Analytical FLOP estimate of a jitted function via XLA cost
    analysis — used for MFU reporting in bench.py."""
    try:
        lowered = jax.jit(fn).lower(*args)
        cost = lowered.compile().cost_analysis()
        if isinstance(cost, list):
            cost = cost[0]
        return float(cost.get("flops", 0.0)) if cost else None
    except Exception:
        return None
