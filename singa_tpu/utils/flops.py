"""FLOPs accounting and MFU (model FLOPs utilization).

The reference reports only wall-clock phase percentages (TimerInfo,
worker.h:91-114).  On TPU the north-star metric is MFU — achieved
model FLOPs/s over the chip's peak (BASELINE.md: AlexNet/CIFAR-10 at
>=50% MFU) — so this module adds two FLOPs sources:

  * `compiled_flops(jitted, *args)` — XLA's own cost analysis of the
    compiled program (exact for what actually runs, includes fusion).
  * `net_forward_flops(net)` — analytic MXU-op count (2·MACs) walked
    over the net's conv/linear layers; the test oracle for the above
    and a device-independent estimate.

MFU convention: model FLOPs (matmul/conv only, 2·MACs; backward
counted as 2x forward, so train step = 3x forward) divided by
(step_time · peak_flops).  Peak table is bf16 MXU peak per chip.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

# bf16 MXU peak FLOP/s per jax Device, keyed by device_kind.  v2/v3
# expose one device per core (chip peaks are 45/123 TFLOP/s over 2
# cores); v4+ expose one device per chip.
PEAK_FLOPS: Dict[str, float] = {
    "TPU v2": 22.5e12, "TPU v3": 61.5e12,
    "TPU v4": 275e12, "TPU v4 lite": 137e12,
    "TPU v5 lite": 197e12, "TPU v5e": 197e12, "TPU v5": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12, "TPU v6e": 918e12,
}


def peak_flops(device=None) -> Optional[float]:
    """Per-chip bf16 peak for `device` (default: jax.devices()[0]);
    None when unknown (e.g. the CPU test platform)."""
    if device is None:
        import jax
        device = jax.devices()[0]
    return PEAK_FLOPS.get(getattr(device, "device_kind", ""))


def cost_metrics(compiled) -> Dict[str, float]:
    """Harvest XLA's cost analysis from an ALREADY-COMPILED executable
    (`jit(...).lower(...).compile()` result).  Never lowers or
    compiles anything — reading the cost model off a cached executable
    is free, which is what lets CostWatch run against every warm
    program without perturbing the compile counters it also watches.

    Returns {} when the backend reports nothing; otherwise a dict with
    whatever of `flops` / `bytes accessed` / `utilization` keys the
    cost model provides (older jax wraps the dict in a list)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 — diagnostics, never a failure
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return {}
    return {k: float(v) for k, v in ca.items()
            if isinstance(v, (int, float))}


def compiled_flops(jitted, *args, **kwargs) -> Optional[float]:
    """FLOPs of the compiled XLA program for `jitted(*args)`.

    `jitted` is either a jax.jit-wrapped callable (lowered and
    compiled here, at compile cost) or an already-compiled executable
    from `jit(...).lower(...).compile()` — the latter is preferred
    when one is at hand: harvesting from the cached object never
    triggers a duplicate compile.  Returns None when the backend's
    cost model does not report flops.
    """
    if hasattr(jitted, "cost_analysis"):   # Compiled (or Lowered):
        compiled = jitted                  # reuse, don't recompile
    else:
        compiled = jitted.lower(*args, **kwargs).compile()
    flops = cost_metrics(compiled).get("flops")
    return float(flops) if flops and flops > 0 else None


def mfu(model_flops: float, step_seconds: float,
        device=None) -> Optional[float]:
    """model_flops per step / (step_seconds · peak). None when peak
    unknown."""
    peak = peak_flops(device)
    if not peak or step_seconds <= 0:
        return None
    return model_flops / (step_seconds * peak)


# -- analytic per-layer counts (forward, 2·MACs convention) ----------------

def _conv_flops(layer) -> int:
    n, h, w, c_out = layer.out_shape  # NHWC
    return 2 * n * c_out * h * w * layer.kernel ** 2 * layer.channels


def _linear_flops(layer) -> int:
    n, out = layer.out_shape
    vdim, hdim = layer.param_specs[0].shape  # weight (vdim, hdim)
    return 2 * n * vdim * hdim


def _attention_flops(layer) -> int:
    b, s, e = layer.out_shape
    hd = layer.heads * layer.head_dim
    kvd = layer.kv_heads * layer.head_dim
    proj = 2 * b * s * e * (hd + 2 * kvd + hd)        # wq wk wv wo
    scores = 4 * b * layer.heads * s * s * layer.head_dim   # qk + pv
    if layer.causal:
        # standard causal-half accounting convention (only ~half the
        # score matrix is live).  NOTE this is a convention, not a
        # kernel fact: the dense fallback computes the full S^2 and
        # flash diagonal blocks are full tiles, so MFU comparability
        # across paths is approximate.
        scores //= 2
    return proj + scores


def _ffn_flops(layer) -> int:
    b, s, e = layer.out_shape
    f = layer.param_specs[0].shape[1]                 # w1 (E, F)
    mats = 3 if getattr(layer, "gated", False) else 2
    return 2 * b * s * e * f * mats


def _moe_flops(layer) -> int:
    b, s, e = layer.out_shape
    f = layer.param_specs[1].shape[2]                 # w1 (n_exp, E, F)
    router = 2 * b * s * e * layer.n_exp
    # each token runs k experts' (E→F→E) MLP (capacity overflow drops
    # are data-dependent; count the routed budget)
    return router + 2 * b * s * layer.k * 2 * e * f


def _lm_head_flops(layer) -> int:
    if layer.cfg.type == "kLMHeadLoss":
        b, s, e, v = layer.flops_shape
    else:
        b, s, v = layer.out_shape
        e = layer.param_specs[0].shape[0]       # w (E, V), tied or not
    return 2 * b * s * e * v


def layer_forward_flops(layer) -> int:
    """Matmul/conv FLOPs of one layer's forward; 0 for non-MXU layers
    (elementwise/pool/LRN/norm are bandwidth-, not FLOP-, dominated)."""
    t = layer.cfg.type
    if t == "kConvolution":
        return _conv_flops(layer)
    if t == "kInnerProduct":
        return _linear_flops(layer)
    if t == "kAttention":
        return _attention_flops(layer)
    if t == "kFeedForward":
        return _ffn_flops(layer)
    if t == "kMoE":
        return _moe_flops(layer)
    if t in ("kLMHead", "kLMHeadLoss"):
        return _lm_head_flops(layer)
    return 0


def net_forward_flops(net) -> int:
    """Analytic forward model-FLOPs of a built NeuralNet."""
    return sum(layer_forward_flops(net.layers[name]) for name in net.topo)


def net_train_flops(net) -> int:
    """Train-step model FLOPs: backward re-does each matmul twice
    (d-input + d-weight), so 3x forward — the standard convention.

    NOTE the convention counts 3x for the FIRST trainable layer too,
    whose input gradient XLA never computes (its input is data).  On
    the AlexNet bench stack that is conv1's dgrad, ~2% of total train
    FLOPs — i.e. the convention-free MFU is ~0.51 when the reported
    one is ~0.52.  Kept because every published MFU number (PaLM-style
    6ND etc.) uses the same uniform-3x convention and comparability
    matters more than the 2%."""
    return 3 * net_forward_flops(net)
