"""Protobuf text-format parser (pure Python, no generated code).

Parses the reference's text-format config files (e.g.
/root/reference/examples/mnist/mlp.conf, conv.conf — schema at
/root/reference/src/proto/model.proto, cluster.proto) into plain nested
dicts.  Every field value is accumulated into a list; the schema layer
(`singa_tpu.config.schema`) decides which fields are singular vs repeated.

Grammar handled (the subset protobuf text-format actually uses here):

    message   := field*
    field     := IDENT ':' scalar | IDENT ':'? '{' message '}'
    scalar    := NUMBER | STRING | IDENT        (IDENT covers enums + bools)
    comments  := '#' .. end-of-line
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Tuple

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<comment>\#[^\n]*)
  | (?P<string>"(?:[^"\\]|\\.)*"|'(?:[^'\\]|\\.)*')
  | (?P<punct>[{}:])
  | (?P<number>[-+]?(?:\.\d+|\d+\.?\d*)(?:[eE][-+]?\d+)?)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_.]*)
    """,
    re.VERBOSE,
)


class TextProtoError(ValueError):
    pass


def _tokenize(text: str) -> List[Tuple[str, str, int]]:
    tokens = []
    pos = 0
    line = 1
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if not m:
            raise TextProtoError(
                f"line {line}: unexpected character {text[pos]!r}")
        kind = m.lastgroup
        value = m.group()
        line += value.count("\n")
        if kind not in ("ws", "comment"):
            tokens.append((kind, value, line))
        pos = m.end()
    return tokens


def _unquote(s: str) -> str:
    body = s[1:-1]

    def sub(m):
        e = m.group(1)
        if e.startswith("x"):
            return chr(int(e[1:], 16))
        if e[0] in "01234567":
            return chr(int(e, 8))   # protoc emits octal \NNN escapes
        return {"n": "\n", "t": "\t", "r": "\r"}.get(e, e)

    return re.sub(r"\\([0-7]{1,3}|x[0-9a-fA-F]{1,2}|.)", sub, body)


def _escape(v: str) -> str:
    """Protobuf text-format string escaping: backslash, quote, the
    common control characters, and \\xNN for other non-printables —
    so dump() output always re-tokenizes (the tokenizer's string
    pattern cannot cross a raw newline)."""
    out = []
    for ch in v:
        if ch == "\\":
            out.append("\\\\")
        elif ch == '"':
            out.append('\\"')
        elif ch == "\n":
            out.append("\\n")
        elif ch == "\t":
            out.append("\\t")
        elif ch == "\r":
            out.append("\\r")
        elif ord(ch) < 0x20:
            out.append(f"\\x{ord(ch):02x}")
        else:
            out.append(ch)
    return "".join(out)


def _coerce_scalar(kind: str, value: str) -> Any:
    if kind == "string":
        return _unquote(value)
    if kind == "number":
        try:
            return int(value)
        except ValueError:
            return float(value)
    # ident: bool literals or enum symbol (kept as string)
    if value == "true":
        return True
    if value == "false":
        return False
    return value


class _Parser:
    def __init__(self, tokens):
        self.tokens = tokens
        self.i = 0

    def peek(self):
        return self.tokens[self.i] if self.i < len(self.tokens) else None

    def next(self):
        tok = self.peek()
        if tok is None:
            raise TextProtoError("unexpected end of input")
        self.i += 1
        return tok

    def parse_message(self, toplevel: bool = False) -> Dict[str, List[Any]]:
        msg: Dict[str, List[Any]] = {}
        while True:
            tok = self.peek()
            if tok is None:
                if not toplevel:
                    raise TextProtoError("unexpected end of input, missing '}'")
                return msg
            kind, value, line = tok
            if kind == "punct" and value == "}":
                if toplevel:
                    raise TextProtoError(f"line {line}: stray '}}'")
                return msg
            if kind != "ident":
                raise TextProtoError(
                    f"line {line}: expected field name, got {value!r}")
            self.next()
            name = value
            tok = self.peek()
            if tok is None:
                raise TextProtoError(f"line {line}: dangling field {name!r}")
            kind, value, line = tok
            if kind == "punct" and value == ":":
                self.next()
                tok = self.peek()
                kind, value, line = tok if tok else (None, None, line)
            if kind == "punct" and value == "{":
                self.next()
                field_value: Any = self.parse_message()
                ktok = self.next()
                if ktok[1] != "}":
                    raise TextProtoError(
                        f"line {ktok[2]}: expected '}}', got {ktok[1]!r}")
            elif kind in ("string", "number", "ident"):
                self.next()
                field_value = _coerce_scalar(kind, value)
            else:
                raise TextProtoError(
                    f"line {line}: bad value for field {name!r}: {value!r}")
            msg.setdefault(name, []).append(field_value)


def parse(text: str) -> Dict[str, List[Any]]:
    """Parse protobuf text format into {field: [values...]} nested dicts."""
    return _Parser(_tokenize(text)).parse_message(toplevel=True)


def parse_file(path: str) -> Dict[str, List[Any]]:
    with open(path, "r") as f:
        return parse(f.read())


def dump(msg: Dict[str, Any], indent: int = 0) -> str:
    """Serialize a {field: [values...]} dict back to text format."""
    out = []
    pad = "  " * indent
    for name, values in msg.items():
        if not isinstance(values, list):
            values = [values]
        for v in values:
            if isinstance(v, dict):
                out.append(f"{pad}{name} {{")
                out.append(dump(v, indent + 1))
                out.append(f"{pad}}}")
            elif isinstance(v, bool):
                out.append(f"{pad}{name}: {'true' if v else 'false'}")
            elif isinstance(v, str) and not re.fullmatch(r"[A-Za-z_][A-Za-z0-9_]*", v):
                out.append(f'{pad}{name}: "{_escape(v)}"')
            elif isinstance(v, str):
                # enum symbol — unquoted only if it looks like one that the
                # schema declares; plain strings (e.g. layer type "kReLU")
                # round-trip fine either way, quote to be safe.
                out.append(f'{pad}{name}: "{v}"')
            else:
                out.append(f"{pad}{name}: {v}")
    return "\n".join(out)
