from .schema import (
    ModelConfig, NetConfig, LayerConfig, ParamConfig, UpdaterConfig,
    ClusterConfig, ConfigError, load_model_config, load_cluster_config,
    model_config_from_text, model_config_from_dict,
    model_config_to_text, config_to_dict,
)
from . import textproto
