from .schema import (
    ModelConfig, NetConfig, LayerConfig, ParamConfig, UpdaterConfig,
    ClusterConfig, ConfigError, load_model_config, load_cluster_config,
    model_config_from_text, model_config_from_dict,
)
from . import textproto
