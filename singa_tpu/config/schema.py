"""Config schema: dataclasses mirroring the reference proto surface.

Field names, enum symbols and defaults follow the reference schema
(/root/reference/src/proto/model.proto, cluster.proto) so that the
reference's text-format configs (examples/mnist/*.conf) load unchanged.
Enums are kept as their text symbols (e.g. "kSGD", "MAX", "kTrain").

Extra TPU-native fields (mesh axes, precision, modern-parallelism knobs)
are additive and default-off, so reference configs parse with identical
semantics.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from . import textproto

# ---------------------------------------------------------------------------
# enum symbol sets (validation only — values stay strings)

PHASES = ("kTrain", "kValidation", "kTest")
PARTITION_TYPES = ("kDataPartition", "kLayerPartition", "kNone")
CONNECTION_TYPES = ("kOneToOne", "kOneToAll")
INIT_METHODS = (
    "kConstant", "kGaussain", "kUniform", "kPretrained",
    "kGaussainSqrtFanIn", "kUniformSqrtFanIn", "kUniformSqrtFanInOut",
    # TPU-native additions
    "kXavier", "kMSRA",
)
UPDATER_TYPES = ("kAdaGrad", "kAdaDelta", "kNesterov", "kSGD", "kRMSProp",
                 # TPU-native addition
                 "kAdam")
LR_CHANGE_METHODS = ("kFixed", "kInverse_t", "kInverse", "kExponential",
                     "kLinear", "kStep",
                     # TPU-native additions
                     "kCosine", "kWarmupCosine")
GRAD_CALC_ALGS = ("kBackPropagation", "kContrastiveDivergence")
POOL_METHODS = ("MAX", "AVE")
LRN_NORM_REGIONS = ("ACROSS_CHANNELS", "WITHIN_CHANNEL")


class ConfigError(ValueError):
    pass


def _build(cls, raw: Dict[str, List[Any]], path: str):
    """Instantiate dataclass `cls` from a parsed textproto dict."""
    kwargs = {}
    fields = {f.name: f for f in dataclasses.fields(cls)}
    for name, values in raw.items():
        if name not in fields:
            raise ConfigError(f"{path}: unknown field '{name}' for {cls.__name__}")
        f = fields[name]
        ftype = f.metadata.get("msg")
        repeated = f.metadata.get("repeated", False)
        if ftype is not None:
            for v in values:
                if not isinstance(v, dict):
                    raise ConfigError(
                        f"{path}: field '{name}' expects a "
                        f"{ftype.__name__} message block, got scalar {v!r}")
            conv = [_build(ftype, v, f"{path}.{name}") for v in values]
        else:
            conv = values
        if repeated:
            kwargs[name] = conv
        else:
            if len(conv) > 1:
                raise ConfigError(f"{path}: field '{name}' given {len(conv)} times")
            kwargs[name] = conv[0]
    return cls(**kwargs)


def _msg(cls, repeated=False):
    if repeated:
        return field(default_factory=list, metadata={"msg": cls, "repeated": True})
    return field(default=None, metadata={"msg": cls})


def _rep():
    return field(default_factory=list, metadata={"repeated": True})


# ---------------------------------------------------------------------------
# per-layer hyper-parameter messages (model.proto:160-275)


@dataclass
class ConvolutionConfig:
    num_filters: int = 0
    bias_term: bool = True
    pad: int = 0
    stride: int = 1
    kernel: int = 0


@dataclass
class ConcateConfig:
    concate_dimension: int = 0
    concate_num: int = 0


@dataclass
class DataConfig:
    source: str = ""
    path: str = ""
    batchsize: int = 0
    random_skip: int = 0


@dataclass
class DropoutConfig:
    dropout_ratio: float = 0.5


@dataclass
class InnerProductConfig:
    num_output: int = 0
    bias_term: bool = True


@dataclass
class LRNConfig:
    local_size: int = 5
    alpha: float = 1.0
    beta: float = 0.75
    norm_region: str = "ACROSS_CHANNELS"
    knorm: float = 1.0

    def __post_init__(self):
        if self.norm_region not in LRN_NORM_REGIONS:
            raise ConfigError(f"bad norm_region {self.norm_region!r}")


@dataclass
class MnistConfig:
    kernel: int = 0
    sigma: float = 0.0
    alpha: float = 0.0
    beta: float = 0.0
    gamma: float = 0.0
    resize: int = 0
    elastic_freq: int = 0
    norm_a: float = 1.0
    norm_b: float = 0.0


@dataclass
class PoolingConfig:
    pool: str = "MAX"
    kernel: int = 0
    pad: int = 0
    stride: int = 1

    def __post_init__(self):
        if self.pool not in POOL_METHODS:
            raise ConfigError(f"bad pool method {self.pool!r}")


@dataclass
class SliceConfig:
    slice_dimension: int = 0
    slice_num: int = 0


@dataclass
class SplitConfig:
    num_splits: int = 1


@dataclass
class ReLUConfig:
    negative_slope: float = 0.0


@dataclass
class RGBImageConfig:
    scale: float = 1.0
    cropsize: int = 0
    mirror: bool = False
    meanfile: str = ""   # path to mean record (AlexNet-style mean subtract)


@dataclass
class SoftmaxLossConfig:
    topk: int = 1
    scale: float = 1.0


@dataclass
class TanhConfig:
    outer_scale: float = 1.0
    inner_scale: float = 1.0


# --- TPU-native layer configs (modern model families; additive) -----------


@dataclass
class AttentionConfig:
    num_heads: int = 8
    head_dim: int = 64
    causal: bool = True
    # sequence-parallel strategy: "none" | "ring" | "ulysses"
    seq_parallel: str = "none"
    rope: bool = True
    rope_theta: float = 10000.0
    window: int = 0          # sliding-window size, 0 = full
    num_kv_heads: int = 0    # 0 => = num_heads (MHA); else GQA/MQA


@dataclass
class MoEConfig:
    num_experts: int = 8
    experts_per_token: int = 2
    expert_hidden: int = 0
    capacity_factor: float = 1.25
    router_aux_coef: float = 0.01


@dataclass
class EmbedConfig:
    vocab_size: int = 0
    embed_dim: int = 0
    # token-chunk size of the fused kLMHeadLoss layer (0 = default 4096)
    loss_chunk: int = 0


@dataclass
class RMSNormConfig:
    epsilon: float = 1e-6


@dataclass
class RBMConfig:
    num_hidden: int = 0
    cd_k: int = 1
    persistent: bool = False


@dataclass
class FFNConfig:
    hidden_dim: int = 0
    activation: str = "silu"     # silu | gelu | relu
    gated: bool = True           # SwiGLU-style gating


@dataclass
class SequenceDataConfig:
    batchsize: int = 0
    seq_len: int = 0
    vocab_size: int = 0


# ---------------------------------------------------------------------------
# ParamProto (model.proto:54-106)


@dataclass
class ParamConfig:
    name: str = ""
    id: int = -1
    shape: List[int] = _rep()
    split_threshold: int = 5000000
    partition_dim: int = -1
    init_method: str = "kConstant"
    value: float = 1.0
    low: float = -1.0
    high: float = 1.0
    mean: float = 0.0
    std: float = 1.0
    learning_rate_multiplier: float = 1.0
    weight_decay_multiplier: float = 1.0

    def __post_init__(self):
        if self.init_method not in INIT_METHODS:
            raise ConfigError(f"bad init_method {self.init_method!r}")


# ---------------------------------------------------------------------------
# LayerProto (model.proto:124-159)


@dataclass
class LayerConfig:
    name: str = ""
    type: str = ""
    srclayers: List[str] = _rep()
    locationid: int = 0
    partitionid: int = 0
    partition_type: Optional[str] = None
    share_ary: List[str] = _rep()
    param: List[ParamConfig] = _msg(ParamConfig, repeated=True)
    share_param: List[str] = _rep()
    exclude: List[str] = _rep()

    convolution_param: Optional[ConvolutionConfig] = _msg(ConvolutionConfig)
    concate_param: Optional[ConcateConfig] = _msg(ConcateConfig)
    data_param: Optional[DataConfig] = _msg(DataConfig)
    dropout_param: Optional[DropoutConfig] = _msg(DropoutConfig)
    inner_product_param: Optional[InnerProductConfig] = _msg(InnerProductConfig)
    lrn_param: Optional[LRNConfig] = _msg(LRNConfig)
    mnist_param: Optional[MnistConfig] = _msg(MnistConfig)
    pooling_param: Optional[PoolingConfig] = _msg(PoolingConfig)
    slice_param: Optional[SliceConfig] = _msg(SliceConfig)
    split_param: Optional[SplitConfig] = _msg(SplitConfig)
    relu_param: Optional[ReLUConfig] = _msg(ReLUConfig)
    rgbimage_param: Optional[RGBImageConfig] = _msg(RGBImageConfig)
    softmaxloss_param: Optional[SoftmaxLossConfig] = _msg(SoftmaxLossConfig)
    tanh_param: Optional[TanhConfig] = _msg(TanhConfig)
    # TPU-native additions
    attention_param: Optional[AttentionConfig] = _msg(AttentionConfig)
    moe_param: Optional[MoEConfig] = _msg(MoEConfig)
    embed_param: Optional[EmbedConfig] = _msg(EmbedConfig)
    rmsnorm_param: Optional[RMSNormConfig] = _msg(RMSNormConfig)
    rbm_param: Optional[RBMConfig] = _msg(RBMConfig)
    ffn_param: Optional[FFNConfig] = _msg(FFNConfig)
    seqdata_param: Optional[SequenceDataConfig] = _msg(SequenceDataConfig)

    def __post_init__(self):
        for ph in self.exclude:
            if ph not in PHASES:
                raise ConfigError(f"layer {self.name!r}: bad phase {ph!r}")
        if self.partition_type is not None and \
                self.partition_type not in PARTITION_TYPES:
            raise ConfigError(
                f"layer {self.name!r}: bad partition_type {self.partition_type!r}")


# ---------------------------------------------------------------------------
# NetProto / UpdaterProto / ModelProto


@dataclass
class NetConfig:
    layer: List[LayerConfig] = _msg(LayerConfig, repeated=True)
    partition_type: str = "kNone"

    def __post_init__(self):
        if self.partition_type not in PARTITION_TYPES:
            raise ConfigError(f"bad net partition_type {self.partition_type!r}")


@dataclass
class UpdaterConfig:
    type: str = "kAdaGrad"
    hogwild: bool = True
    momentum: float = 0.0
    weight_decay: float = 0.0
    gamma: float = 1.0
    pow: float = 0.0
    delta: float = 1e-7
    rho: float = 0.9
    base_learning_rate: float = 0.0
    final_learning_rate: float = 0.0
    learning_rate_change_frequency: int = 0
    learning_rate_change_method: str = "kFixed"
    sync_frequency: int = 1
    warmup_steps: int = 10
    moving_rate: float = 0.0
    param_type: str = "Elastic"
    # TPU-native additions (Adam betas; kWarmupCosine schedule)
    beta1: float = 0.9
    beta2: float = 0.999

    def __post_init__(self):
        if self.type not in UPDATER_TYPES:
            raise ConfigError(f"bad updater type {self.type!r}")
        if self.learning_rate_change_method not in LR_CHANGE_METHODS:
            raise ConfigError(
                f"bad learning_rate_change_method "
                f"{self.learning_rate_change_method!r}")


@dataclass
class ModelConfig:
    name: str = ""
    train_folder: str = "train"
    test_folder: str = "test"
    validation_folder: str = "validation"
    display_after_steps: int = 0
    display_frequency: int = 0
    validation_after_steps: int = 0
    validation_frequency: int = 0
    test_after_steps: int = 0
    test_frequency: int = 0
    prefetch: bool = True
    train_steps: int = 0
    validation_steps: int = 0
    test_steps: int = 0
    step: int = 0
    updater: Optional[UpdaterConfig] = _msg(UpdaterConfig)
    alg: str = "kBackPropagation"
    neuralnet: Optional[NetConfig] = _msg(NetConfig)
    debug: bool = False
    # TPU-native additions
    precision: str = "float32"        # compute dtype: float32 | bfloat16
    checkpoint_frequency: int = 0
    checkpoint_after_steps: int = 0
    # Raised scoped-VMEM compiler budget for conv-family step programs
    # (see Trainer._compiler_options): "auto" applies it when the net's
    # widest conv has >= 96 filters (the raised budget HANGS LeNet-scale
    # compiles, which is why auto exists), "on" forces it, "off"
    # disables it.  The SINGA_TPU_SCOPED_VMEM env var (same values)
    # overrides this field.
    scoped_vmem: str = "auto"         # auto | on | off

    def __post_init__(self):
        if self.alg not in GRAD_CALC_ALGS:
            raise ConfigError(f"bad alg {self.alg!r}")
        if self.scoped_vmem not in ("auto", "on", "off"):
            raise ConfigError(
                f"scoped_vmem must be auto|on|off, got "
                f"{self.scoped_vmem!r}")


# ---------------------------------------------------------------------------
# ClusterProto (cluster.proto) — plus TPU mesh extensions


@dataclass
class ClusterConfig:
    nworkers: int = 1
    nservers: int = 0
    start_port: int = 6723
    nprocs_per_group: int = 1
    nthreads_per_procs: int = 1
    nthreads_per_server: int = 1
    workspace: str = ""
    vis_subfolder: str = "vis"
    log_subfolder: str = "log"
    synchronous: bool = False
    largest_message: int = 1048576
    bandwidth: float = 100.0
    # --- TPU-native mesh axes (additive). Sizes multiply to the device
    # count; 0/unset axes are dropped. The legacy fields above map onto
    # these when they are left unset (see singa_tpu.parallel.mesh).
    data_parallel: int = 0       # dp axis ("data")
    tensor_parallel: int = 0     # tp axis ("model")
    pipeline_parallel: int = 0   # pp axis ("pipe")
    sequence_parallel: int = 0   # sp/cp axis ("seq")
    expert_parallel: int = 0     # ep axis ("expert")
    # microbatches in flight per pipelined step (GPipe schedule); only
    # meaningful with pipeline_parallel > 1 and layers carrying
    # locationid stage marks.  0 → 2 * pipeline_parallel.
    pipeline_microbatches: int = 0


# ---------------------------------------------------------------------------
# loaders


def load_model_config(path: str) -> ModelConfig:
    return _build(ModelConfig, textproto.parse_file(path), path)


def load_cluster_config(path: str) -> ClusterConfig:
    return _build(ClusterConfig, textproto.parse_file(path), path)


def model_config_from_text(text: str) -> ModelConfig:
    return _build(ModelConfig, textproto.parse(text), "<string>")


def model_config_from_dict(d: Dict[str, Any]) -> ModelConfig:
    """Build from a nested plain dict (values need not be listified)."""
    return _build(ModelConfig, _listify(d), "<dict>")


def config_to_dict(cfg) -> Dict[str, Any]:
    """Dataclass config → nested {field: value} dict, omitting fields that
    still hold their schema default (the loader re-fills them), so the
    emitted text-proto stays as terse as the reference's hand-written
    configs (examples/mnist/*.conf)."""
    out: Dict[str, Any] = {}
    for f in dataclasses.fields(type(cfg)):
        v = getattr(cfg, f.name)
        if f.default is not dataclasses.MISSING and v == f.default:
            continue
        if f.default_factory is not dataclasses.MISSING and v == f.default_factory():  # noqa: E501
            continue
        if dataclasses.is_dataclass(v):
            out[f.name] = config_to_dict(v)
        elif isinstance(v, list):
            out[f.name] = [config_to_dict(x) if dataclasses.is_dataclass(x)
                           else x for x in v]
        else:
            out[f.name] = v
    return out


def model_config_to_text(cfg: "ModelConfig") -> str:
    """Serialize back to the reference's text-proto surface; round-trips
    through load (`model_config_from_text(model_config_to_text(c)) == c`)."""
    return textproto.dump(config_to_dict(cfg)) + "\n"


def _listify(d: Dict[str, Any]) -> Dict[str, List[Any]]:
    out: Dict[str, List[Any]] = {}
    for k, v in d.items():
        vs = v if isinstance(v, list) else [v]
        out[k] = [_listify(x) if isinstance(x, dict) else x for x in vs]
    return out
