"""singa_tpu: a TPU-native deep learning framework with the capabilities of
early SINGA (jwmneu/singa), built on JAX/XLA/Pallas.

Layer-DAG models are declared with the reference's text-proto config surface
(NetProto/LayerProto/UpdaterProto) and compile to a single jitted train step;
parallelism (DP/TP/PP/SP/EP) is expressed as jax.sharding over a device Mesh.
"""
__version__ = "0.1.0"

from .config import (  # noqa: F401
    ModelConfig, NetConfig, LayerConfig, ParamConfig, UpdaterConfig,
    ClusterConfig, load_model_config, load_cluster_config,
)
