"""Sequence/context parallelism: ring attention + Ulysses.

New first-class capability (SURVEY.md §5: the reference has no sequence
axis; its closest mechanism is generic layer partitioning).  Both
strategies shard the sequence axis of (B, H, S, D) attention inputs over
the mesh's "seq" axis — and keep the batch dim on "data" and the head
dim on "model", so they compose with data/tensor parallelism on the same
mesh instead of gathering the global batch onto every device:

- **Ring attention** (blockwise attention + KV rotation): each device
  keeps its Q chunk and rotates KV chunks around the ring with
  `jax.lax.ppermute` (XLA collective-permute over ICI), merging partial
  attention results in log-sum-exp space.  Memory per device is O(S/n).

- **Ulysses**: two `all_to_all`s re-shard seq→heads, run dense local
  attention on H/(sp·tp) heads at full sequence length, then shard back.
  Cheaper comm volume for moderate S; needs H/tp divisible by sp.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
try:
    from jax import shard_map
    #: True when running on the pre-0.4.35 experimental shard_map.
    #: The legacy tracer's check_rep/rewrite machinery is known to
    #: drift ring-attention numerics slightly (PR 10); parity tests
    #: consult this flag to xfail rather than assert-fail there.
    LEGACY_SHARD_MAP = False
except ImportError:  # pre-0.4.35 jax: experimental namespace, and the
    # replication-check kwarg is still called check_rep there
    from jax.experimental.shard_map import shard_map as _shard_map
    LEGACY_SHARD_MAP = True

    def shard_map(f, **kw):
        # every call site here passes check_vma=False; map it to
        # check_rep=False (the old default of True turned the
        # replication CHECK into a rewrite pass that perturbed the
        # ring collectives' numerics — the PR 10 drift)
        kw["check_rep"] = bool(kw.pop("check_vma", False))
        return _shard_map(f, **kw)

from ..ops.attention import (NEG_INF, attention_reference,
                             chunk_attention_blockwise, flash_chunk,
                             flash_chunk_legal, merge_attention)


def _spec(mesh: Mesh, seq_axis: str, heads: int):
    """(B, H, S, D): batch on data, heads on model (when divisible), seq
    on the sequence axis."""
    head_axis = "model" if heads % mesh.shape["model"] == 0 else None
    return P("data", head_axis, seq_axis, None)


def packed_attention_sharded(q, k, v, mesh: Mesh, num_heads: int,
                             num_kv_heads: int, causal: bool,
                             block_q: int, block_k: int) -> jnp.ndarray:
    """The packed flash kernels (in-kernel GQA, zero transposes) as a
    shard_map local step over the mesh: batch on "data", heads on
    "model".  q: (B, S, H·D), k/v: (B, S, Hkv·D) — the projections'
    native layout, globally sharded exactly as TP partition_dim=1
    leaves them, so no resharding happens at the shard_map boundary.

    Each device runs the same kernel the single-chip path runs, on its
    (B/dp, S, (H/tp)·D) slice.  GQA group slices stay aligned because
    the caller guarantees heads % tp == 0 AND kv_heads % tp == 0:
    shard i holds q heads [i·H/tp, (i+1)·H/tp) and exactly their kv
    group heads [i·Hkv/tp, (i+1)·Hkv/tp).  This closes the round-4 gap
    where `ctx.mesh is None` fenced the packed layout (and its +28% GQA
    win at S=4096) out of every multi-device run."""
    from ..ops.attention import flash_attention_packed
    tp = mesh.shape.get("model", 1)
    dp = mesh.shape.get("data", 1)
    assert num_heads % max(tp, 1) == 0 and num_kv_heads % max(tp, 1) == 0
    h_local = num_heads // max(tp, 1)
    hkv_local = num_kv_heads // max(tp, 1)
    spec = P("data" if dp > 1 else None, None, "model" if tp > 1 else None)

    def local(q, k, v):
        return flash_attention_packed(q, k, v, h_local, causal, block_q,
                                      block_k, None, hkv_local)

    return shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)


def ring_attention(q, k, v, mesh: Mesh, axis: str = "seq",
                   causal: bool = True,
                   use_flash: bool | None = None) -> jnp.ndarray:
    """q: (B, H, S, D); k/v: (B, Hkv, S, D) with Hkv <= H (GQA) and S
    sharded over `axis`.  Returns attention output with q's sharding.

    KV rotates UNEXPANDED (round 5): every ppermute moves Hkv-head
    chunks — for the 8-head/2-kv dryrun case that is 4x less ICI
    traffic and 4x less rotating KV memory than expanding first; the
    group expansion happens inside the local step, on the local chunk
    only.  (Bandwidth frugality is the reference's core comm design,
    param_manager.cc:85-93.)

    Local step: the Pallas flash kernels when the chunk shapes tile
    (`use_flash` None = auto).  Under a causal mask every ring rotation
    is one of exactly three cases — diagonal (kv_off == q_off: the
    standard causal kernel), fully visible (kv strictly earlier:
    non-causal kernel), fully masked (kv strictly later: contributes
    nothing) — so the offset-aware mask the XLA fallback needs never
    enters the kernel; a lax.cond picks visible-vs-masked per device.
    The rotation loop is Python-unrolled (nseq is static), making the
    per-rotation case static too."""
    from ..ops.attention import expand_kv_heads
    nseq = mesh.shape[axis]
    if nseq == 1:
        return attention_reference(q, expand_kv_heads(k, q.shape[1]),
                                   expand_kv_heads(v, q.shape[1]), causal)
    b, h, s_global, d = q.shape
    hkv = k.shape[1]
    # heads ride "model" only when BOTH q and kv head counts divide it —
    # a mismatched split would misalign the local GQA groups
    tp = mesh.shape["model"]
    head_axis = "model" if h % tp == 0 and hkv % tp == 0 else None
    spec = P("data", head_axis, axis, None)
    chunk = s_global // nseq
    if use_flash is None:
        use_flash = flash_chunk_legal(chunk, chunk, d)

    # per-chunk tuned block geometry (bk=1024 wins for chunks >= 1024,
    # same table as the single-device and Ulysses paths)
    from ..ops.attention import flash_blocks
    fbq, fbk = flash_blocks(chunk)

    def local_flash(q, k, v):
        idx = jax.lax.axis_index(axis)
        perm = [(i, (i + 1) % nseq) for i in range(nseq)]
        h_local = q.shape[1]
        out = jnp.zeros(q.shape, jnp.float32)
        lse = jnp.full(q.shape[:3] + (1,), NEG_INF, jnp.float32)
        k_cur, v_cur = k, v
        for s in range(nseq):
            # group expansion happens on the LOCAL chunk only (the
            # rotating carry stays at Hkv width), and for the
            # conditional rotations INSIDE the visible branch so
            # fully-masked hops do no attention-side work at all
            def vis(args, causal_=False):
                qq, kk, vv = args
                return flash_chunk(qq, expand_kv_heads(kk, h_local),
                                   expand_kv_heads(vv, h_local),
                                   causal_, block_q=fbq, block_k=fbk)

            if not causal:
                o_new, l_new = vis((q, k_cur, v_cur))
            elif s == 0:
                # diagonal: kv_off == q_off on every device
                o_new, l_new = vis((q, k_cur, v_cur), True)
            else:
                # kv chunk s hops back: visible iff it wrapped no ring
                # boundary (idx >= s); otherwise it is entirely in the
                # future and contributes nothing
                o_new, l_new = jax.lax.cond(
                    idx >= s,
                    vis,
                    lambda args: (
                        jnp.zeros(args[0].shape, jnp.float32),
                        jnp.full(args[0].shape[:3] + (1,), NEG_INF,
                                 jnp.float32)),
                    (q, k_cur, v_cur))
            out, lse = merge_attention(out, lse, o_new, l_new)
            if s < nseq - 1:
                k_cur = jax.lax.ppermute(k_cur, axis, perm)
                v_cur = jax.lax.ppermute(v_cur, axis, perm)
        return out.astype(q.dtype)

    def local(q, k, v):
        idx = jax.lax.axis_index(axis)
        chunk = q.shape[2]
        q_off = idx * chunk
        h_local = q.shape[1]

        def step(carry, s):
            k_cur, v_cur, out, lse = carry
            src = jax.lax.rem(idx - s + nseq, nseq)  # owner of current kv
            # chunked-flash local step: the per-rotation score matrix
            # stays O(chunk·block) even for long local KV chunks
            o_new, lse_new = chunk_attention_blockwise(
                q, expand_kv_heads(k_cur, h_local),
                expand_kv_heads(v_cur, h_local), causal, q_off,
                src * chunk)
            out, lse = merge_attention(out, lse, o_new, lse_new)
            # rotate kv to the next device (ring over ICI), Hkv-wide
            perm = [(i, (i + 1) % nseq) for i in range(nseq)]
            k_nxt = jax.lax.ppermute(k_cur, axis, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis, perm)
            return (k_nxt, v_nxt, out, lse), None

        out0 = jnp.zeros(q.shape, jnp.float32)
        lse0 = jnp.full(q.shape[:3] + (1,), NEG_INF, jnp.float32)
        (k, v, out, lse), _ = jax.lax.scan(
            step, (k, v, out0, lse0), jnp.arange(nseq))
        return out.astype(q.dtype)

    return shard_map(local_flash if use_flash else local, mesh=mesh,
                     in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)


def ulysses_attention(q, k, v, mesh: Mesh, axis: str = "seq",
                      causal: bool = True,
                      attn_fn=None) -> jnp.ndarray:
    """Ulysses SP: all-to-all seq→heads, local full-sequence attention,
    all-to-all back.  q: (B, H, S, D); k/v: (B, Hkv, S, D), Hkv <= H
    (GQA), S sharded over `axis`.

    When Hkv splits the same way H does (over "model" and the seq
    axis), k/v travel the all-to-alls at Hkv width — group expansion
    happens on the post-a2a local chunk, so comm volume scales with
    Hkv, not H (round 5, same frugality as the ring path).  Otherwise
    k/v are pre-expanded (the pre-round-5 layout).

    The local step defaults to the Pallas flash kernel (the post-a2a
    chunk is FULL sequence length with no position offsets — plain
    causal attention, exactly the kernel's contract) whenever the
    global S and D tile; dense reference otherwise or when attn_fn is
    given."""
    from ..ops.attention import expand_kv_heads
    nseq = mesh.shape[axis]
    s_global, d = q.shape[2], q.shape[3]
    h = q.shape[1]
    hkv = k.shape[1]
    if attn_fn is None:
        if flash_chunk_legal(s_global, s_global, d):
            from ..ops.attention import flash_attention, flash_blocks
            bq, bk = flash_blocks(s_global)
            attn_fn = lambda q, k, v, c: flash_attention(  # noqa: E731
                q, k, v, c, bq, bk)
        else:
            attn_fn = attention_reference
    if nseq == 1:
        return attn_fn(q, expand_kv_heads(k, h), expand_kv_heads(v, h),
                       causal)
    tp = mesh.shape["model"]
    h_local = h // tp if h % tp == 0 and tp > 1 else h
    if h_local % nseq:
        raise ValueError(
            f"Ulysses needs heads ({h}"
            f"{f'/tp={tp}' if tp > 1 and h % tp == 0 else ''}) "
            f"% seq axis ({nseq}) == 0")
    # kv rides at Hkv width iff it splits exactly like q's heads do:
    # same model-axis divisibility (so both shard or neither does) and
    # the local kv head count splits over the seq axis — then the
    # contiguous a2a blocks keep q-head groups aligned with their kv
    # slice and the local expansion is exact
    head_on_model = h % tp == 0
    hkv_local = hkv // tp if head_on_model and hkv % tp == 0 else hkv
    kv_native = (hkv != h
                 and (hkv % tp == 0) == head_on_model
                 and hkv_local % nseq == 0)
    if hkv != h and not kv_native:
        k = expand_kv_heads(k, h)
        v = expand_kv_heads(v, h)

    spec = _spec(mesh, axis, h)
    kv_spec = (P("data", "model" if head_on_model else None, axis, None)
               if kv_native else spec)

    def local(q, k, v):
        def to_heads(x):   # (B, H, S/n, D) -> (B, H/n, S, D)
            return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                      tiled=True)

        def to_seq(x):     # (B, H/n, S, D) -> (B, H, S/n, D)
            return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                      tiled=True)

        qh = to_heads(q)
        kh, vh = to_heads(k), to_heads(v)
        if kh.shape[1] != qh.shape[1]:
            kh = expand_kv_heads(kh, qh.shape[1])
            vh = expand_kv_heads(vh, qh.shape[1])
        out = attn_fn(qh, kh, vh, causal)
        return to_seq(out)

    return shard_map(local, mesh=mesh, in_specs=(spec, kv_spec, kv_spec),
                     out_specs=spec, check_vma=False)(q, k, v)
