"""Sequence/context parallelism: ring attention + Ulysses.

New first-class capability (SURVEY.md §5: the reference has no sequence
axis; its closest mechanism is generic layer partitioning).  Both
strategies shard the sequence axis of (B, H, S, D) attention inputs over
the mesh's "seq" axis — and keep the batch dim on "data" and the head
dim on "model", so they compose with data/tensor parallelism on the same
mesh instead of gathering the global batch onto every device:

- **Ring attention** (blockwise attention + KV rotation): each device
  keeps its Q chunk and rotates KV chunks around the ring with
  `jax.lax.ppermute` (XLA collective-permute over ICI), merging partial
  attention results in log-sum-exp space.  Memory per device is O(S/n).

- **Ulysses**: two `all_to_all`s re-shard seq→heads, run dense local
  attention on H/(sp·tp) heads at full sequence length, then shard back.
  Cheaper comm volume for moderate S; needs H/tp divisible by sp.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from ..ops.attention import (NEG_INF, attention_reference,
                             chunk_attention_blockwise, flash_chunk,
                             flash_chunk_legal, merge_attention)


def _spec(mesh: Mesh, seq_axis: str, heads: int):
    """(B, H, S, D): batch on data, heads on model (when divisible), seq
    on the sequence axis."""
    head_axis = "model" if heads % mesh.shape["model"] == 0 else None
    return P("data", head_axis, seq_axis, None)


def ring_attention(q, k, v, mesh: Mesh, axis: str = "seq",
                   causal: bool = True,
                   use_flash: bool | None = None) -> jnp.ndarray:
    """q/k/v: (B, H, S, D) with S sharded over `axis`.  Returns attention
    output with the same sharding.

    Local step: the Pallas flash kernels when the chunk shapes tile
    (`use_flash` None = auto).  Under a causal mask every ring rotation
    is one of exactly three cases — diagonal (kv_off == q_off: the
    standard causal kernel), fully visible (kv strictly earlier:
    non-causal kernel), fully masked (kv strictly later: contributes
    nothing) — so the offset-aware mask the XLA fallback needs never
    enters the kernel; a lax.cond picks visible-vs-masked per device.
    The rotation loop is Python-unrolled (nseq is static), making the
    per-rotation case static too."""
    nseq = mesh.shape[axis]
    if nseq == 1:
        return attention_reference(q, k, v, causal)
    spec = _spec(mesh, axis, q.shape[1])
    b, h, s_global, d = q.shape
    chunk = s_global // nseq
    if use_flash is None:
        use_flash = flash_chunk_legal(chunk, chunk, d)

    # per-chunk tuned block geometry (bk=1024 wins for chunks >= 1024,
    # same table as the single-device and Ulysses paths)
    from ..ops.attention import flash_blocks
    fbq, fbk = flash_blocks(chunk)

    def local_flash(q, k, v):
        idx = jax.lax.axis_index(axis)
        perm = [(i, (i + 1) % nseq) for i in range(nseq)]
        out = jnp.zeros(q.shape, jnp.float32)
        lse = jnp.full(q.shape[:3] + (1,), NEG_INF, jnp.float32)
        k_cur, v_cur = k, v
        for s in range(nseq):
            if not causal:
                o_new, l_new = flash_chunk(q, k_cur, v_cur, False,
                                           block_q=fbq, block_k=fbk)
            elif s == 0:
                # diagonal: kv_off == q_off on every device
                o_new, l_new = flash_chunk(q, k_cur, v_cur, True,
                                           block_q=fbq, block_k=fbk)
            else:
                # kv chunk s hops back: visible iff it wrapped no ring
                # boundary (idx >= s); otherwise it is entirely in the
                # future and contributes nothing
                o_new, l_new = jax.lax.cond(
                    idx >= s,
                    lambda args: flash_chunk(*args, False,
                                             block_q=fbq, block_k=fbk),
                    lambda args: (
                        jnp.zeros(args[0].shape, jnp.float32),
                        jnp.full(args[0].shape[:3] + (1,), NEG_INF,
                                 jnp.float32)),
                    (q, k_cur, v_cur))
            out, lse = merge_attention(out, lse, o_new, l_new)
            if s < nseq - 1:
                k_cur = jax.lax.ppermute(k_cur, axis, perm)
                v_cur = jax.lax.ppermute(v_cur, axis, perm)
        return out.astype(q.dtype)

    def local(q, k, v):
        idx = jax.lax.axis_index(axis)
        chunk = q.shape[2]
        q_off = idx * chunk

        def step(carry, s):
            k_cur, v_cur, out, lse = carry
            src = jax.lax.rem(idx - s + nseq, nseq)  # owner of current kv
            # chunked-flash local step: the per-rotation score matrix
            # stays O(chunk·block) even for long local KV chunks
            o_new, lse_new = chunk_attention_blockwise(
                q, k_cur, v_cur, causal, q_off, src * chunk)
            out, lse = merge_attention(out, lse, o_new, lse_new)
            # rotate kv to the next device (ring over ICI)
            perm = [(i, (i + 1) % nseq) for i in range(nseq)]
            k_nxt = jax.lax.ppermute(k_cur, axis, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis, perm)
            return (k_nxt, v_nxt, out, lse), None

        out0 = jnp.zeros(q.shape, jnp.float32)
        lse0 = jnp.full(q.shape[:3] + (1,), NEG_INF, jnp.float32)
        (k, v, out, lse), _ = jax.lax.scan(
            step, (k, v, out0, lse0), jnp.arange(nseq))
        return out.astype(q.dtype)

    return shard_map(local_flash if use_flash else local, mesh=mesh,
                     in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)


def ulysses_attention(q, k, v, mesh: Mesh, axis: str = "seq",
                      causal: bool = True,
                      attn_fn=None) -> jnp.ndarray:
    """Ulysses SP: all-to-all seq→heads, local full-sequence attention,
    all-to-all back.  q/k/v: (B, H, S, D), S sharded over `axis`.

    The local step defaults to the Pallas flash kernel (the post-a2a
    chunk is FULL sequence length with no position offsets — plain
    causal attention, exactly the kernel's contract) whenever the
    global S and D tile; dense reference otherwise or when attn_fn is
    given."""
    nseq = mesh.shape[axis]
    s_global, d = q.shape[2], q.shape[3]
    if attn_fn is None:
        if flash_chunk_legal(s_global, s_global, d):
            from ..ops.attention import flash_attention, flash_blocks
            bq, bk = flash_blocks(s_global)
            attn_fn = lambda q, k, v, c: flash_attention(  # noqa: E731
                q, k, v, c, bq, bk)
        else:
            attn_fn = attention_reference
    if nseq == 1:
        return attn_fn(q, k, v, causal)
    h = q.shape[1]
    tp = mesh.shape["model"]
    h_local = h // tp if h % tp == 0 and tp > 1 else h
    if h_local % nseq:
        raise ValueError(
            f"Ulysses needs heads ({h}"
            f"{f'/tp={tp}' if tp > 1 and h % tp == 0 else ''}) "
            f"% seq axis ({nseq}) == 0")

    spec = _spec(mesh, axis, h)

    def local(q, k, v):
        def to_heads(x):   # (B, H, S/n, D) -> (B, H/n, S, D)
            return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                      tiled=True)

        def to_seq(x):     # (B, H/n, S, D) -> (B, H, S/n, D)
            return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                      tiled=True)

        out = attn_fn(to_heads(q), to_heads(k), to_heads(v), causal)
        return to_seq(out)

    return shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)
