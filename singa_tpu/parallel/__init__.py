"""Parallelism: device mesh, shardings, SP/PP strategies, elastic tier.

Sequence/pipeline strategies import lazily — they pull in Pallas and are
only needed when a model actually uses them.
"""

from .bootstrap import (coordinator_address, distributed_init,
                        parse_hostfile)
from .mesh import AXES, make_mesh, mesh_from_cluster
from .partition import (param_shardings, batch_shardings, chunk_shardings,
                        pad_params, place_chunk, seq_batch_shardings,
                        shard_params, shard_opt_state, shard_batch,
                        replicated)

_LAZY = {
    "ring_attention": ("sequence", "ring_attention"),
    "ulysses_attention": ("sequence", "ulysses_attention"),
    "pipeline_apply": ("pipeline", "pipeline_apply"),
    "stack_stage_params": ("pipeline", "stack_stage_params"),
    "ElasticController": ("elastic", "ElasticController"),
    "elastic_update": ("elastic", "elastic_update"),
    "randomsync_update": ("elastic", "randomsync_update"),
    "sync_sample_ratio": ("elastic", "sync_sample_ratio"),
}


def __getattr__(name):
    if name in _LAZY:
        import importlib
        module, attr = _LAZY[name]
        return getattr(importlib.import_module(f".{module}", __name__), attr)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_LAZY))
