from .mesh import AXES, make_mesh, mesh_from_cluster
from .partition import (param_shardings, batch_shardings, shard_params,
                        shard_opt_state, shard_batch, replicated)
