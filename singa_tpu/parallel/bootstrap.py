"""Multi-host bootstrap — TPU-native successor of the hostfile launch.

Reference: the singa binary is launched once per process with
`-procsID=$i -hostfile=<file>` (examples/mnist/run.sh:20-37); each
process reads the hostfile to learn every peer's address and derives
its role and ports from its id (cluster.cc:10-26, cluster.h:80-95).
Bootstrap is static — no discovery, no elasticity.

The TPU-native equivalent keeps the exact same launch surface
(-procsID, -hostfile) but hands coordination to `jax.distributed`:
the first hostfile line is the coordinator, `start_port` (the same
ClusterProto field that anchored the reference's ZMQ port scheme)
becomes the coordinator port, and every process calls
`jax.distributed.initialize` over DCN.  After that, `jax.devices()`
spans all hosts and a single Mesh covers the whole slice — the
worker/server role fork (main.cc:49-55) is gone because gradient
aggregation is a compiled psum, not a server plane.
"""

from __future__ import annotations

import os
from typing import List, Optional

DEFAULT_PORT = 6723  # ClusterProto.start_port default (cluster.proto:7)


def parse_hostfile(path: str) -> List[str]:
    """One host per line, '#' comments and blank lines ignored
    (reference hostfile format, examples/mnist/hostfile).

    A duplicate host is rejected — two processes binding the same
    coordinates would produce a membership list whose failures only
    surface later as rendezvous hangs or double-routed traffic — and
    a file with no hosts at all (empty / comments only) is an error
    instead of a silently empty membership."""
    hosts: List[str] = []
    seen = set()
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            host = line.split("#", 1)[0].strip()
            if not host:
                continue
            if host in seen:
                raise ValueError(
                    f"hostfile {path}: duplicate host {host!r} at "
                    f"line {lineno} — every member must be unique")
            seen.add(host)
            hosts.append(host)
    if not hosts:
        raise ValueError(
            f"hostfile {path}: no hosts (file is empty or comments "
            f"only); expected one host[:port] per line")
    return hosts


def coordinator_address(hosts: List[str], port: int = DEFAULT_PORT) -> str:
    """Coordinator = first hostfile entry (the reference pins server
    processes to the tail of the id range instead; with no server plane
    the head host simply hosts the rendezvous)."""
    if not hosts:
        raise ValueError("empty hostfile")
    head = hosts[0]
    if ":" in head:  # host:port spelling wins over start_port
        return head
    return f"{head}:{port}"


def distributed_init(procs_id: int = 0,
                     hostfile: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     port: int = DEFAULT_PORT) -> bool:
    """Initialize jax.distributed from the reference launch coordinates.

    Returns True if multi-process init ran, False for the single-process
    fast path (hostfile absent / one host) — mirroring how a 1-line
    hostfile run of the reference degenerates to a single process.

    Environment overrides (JAX's own convention) win when set:
    JAX_COORDINATOR_ADDRESS, JAX_NUM_PROCESSES, JAX_PROCESS_ID.
    """
    env_num = os.environ.get("JAX_NUM_PROCESSES")
    env_pid = os.environ.get("JAX_PROCESS_ID")
    if env_num is not None:
        num_processes = int(env_num)
    if env_pid is not None:
        procs_id = int(env_pid)
    if hostfile is None and num_processes is None:
        return False
    if hostfile is not None:
        hosts = parse_hostfile(hostfile)
        if num_processes is None:
            num_processes = len(hosts)
        coord = os.environ.get("JAX_COORDINATOR_ADDRESS") or \
            coordinator_address(hosts, port)
    else:
        coord = os.environ.get("JAX_COORDINATOR_ADDRESS")
        if coord is None:
            raise ValueError(
                "num_processes given without hostfile; set "
                "JAX_COORDINATOR_ADDRESS or pass a hostfile")
    if not 0 <= procs_id < num_processes:
        raise ValueError(
            f"procsID {procs_id} out of range for {num_processes} processes")
    if num_processes == 1:
        return False
    import jax
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=num_processes,
                               process_id=procs_id)
    return True
