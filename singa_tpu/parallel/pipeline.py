"""Pipeline parallelism with microbatching over the "pipe" mesh axis.

The reference's closest mechanism is cross-process activation exchange
through BridgeSrc/BridgeDst layers over ZMQ PUSH/PULL (SURVEY.md §2.2-4)
— point-to-point dataflow with no microbatch schedule.  This module is
the first-class successor: a GPipe-style schedule where every device
runs one stage and activations hop stage→stage via
`jax.lax.ppermute` (XLA collective-permute over ICI), with n_micro
microbatches in flight to fill the pipeline bubble.

Constraints (SPMD): every stage must map activations of one shared
shape/dtype to the same shape/dtype (true for transformer blocks).  The
backward pass is autodiff through the scan — GPipe semantics (all
forward, then all backward), with activation memory O(n_micro) per
stage; combine with jax.checkpoint on stage_fn for O(1).

The reference's `locationid` layer field (model.proto:128) maps onto
stage ids here: net configs partition into stages by locationid.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
try:
    from jax import shard_map
    LEGACY_SHARD_MAP = False   # see parallel/sequence.py
except ImportError:  # pre-0.4.35 jax: experimental namespace, and the
    # replication-check kwarg is still called check_rep there
    from jax.experimental.shard_map import shard_map as _shard_map
    LEGACY_SHARD_MAP = True

    def shard_map(f, **kw):
        # call sites pass check_vma=False; keep the legacy check_rep
        # rewrite OFF too (sequence.py has the numerics rationale)
        kw["check_rep"] = bool(kw.pop("check_vma", False))
        return _shard_map(f, **kw)


def stack_stage_params(per_stage_params: Sequence[Any]) -> Any:
    """Stack a list of per-stage param pytrees along a new leading stage
    dim (leaves must match shapes across stages)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)


def pipeline_apply(mesh: Mesh, stage_fn: Callable[..., jnp.ndarray],
                   stage_params: Any, x: jnp.ndarray,
                   axis: str = "pipe",
                   batch_axis: str | None = None,
                   rng: jax.Array | None = None,
                   virtual: int = 1) -> jnp.ndarray:
    """Run microbatches through the pipeline.

    stage_params: pytree with leaves (n_stages, ...) — sharded over
    `axis` so each device keeps only its stage's slice.
    x: (n_micro, micro_batch, ...) microbatched input.  With
    `batch_axis` set (e.g. "data"), the micro_batch dim (dim 1) shards
    over that axis so dp groups pipeline DIFFERENT slices of the batch
    instead of replicating the work.
    With `rng` set, stage_fn is called as stage_fn(params, mb, key)
    where key = fold_in(fold_in(rng, stage), microbatch) — every
    (stage, microbatch) cell draws independent randomness, so
    rng-bearing layers (dropout) work inside stages; without it the
    two-arg form is called.
    `virtual` > 1 selects the CIRCULAR (interleaved) schedule — the
    1F1B-family form that is natural in SPMD/XLA: n_stages = P·virtual
    virtual stages, P = pipe axis size, each device holding `virtual`
    round-robin slices (device d runs stages d, d+P, d+2P, …) and
    microbatches looping the ring `virtual` times.  Bubble shrinks from
    (P·v−1)/(m+P·v−1) ticks to (P−1)/(m·v+P−1) — ~v× smaller — at the
    same per-tick work; no waiting stash is needed because every
    (microbatch, virtual stage) output feeds the next tick directly.
    Requires n_micro % P == 0 (microbatches travel in rounds of P).
    Returns (n_micro, micro_batch, ...) outputs of the final stage,
    sharded the same way.
    """
    nstages = mesh.shape[axis]
    x_spec = P(None, batch_axis) if batch_axis else P()
    if nstages == 1:
        # degenerate mesh: run every stacked stage sequentially on the
        # one device, with the same per-(stage, microbatch) key fold
        n_total = jax.tree_util.tree_leaves(stage_params)[0].shape[0]

        def all_stages(mb, m_idx):
            h = mb
            for s in range(n_total):
                ps = jax.tree_util.tree_map(lambda p, s=s: p[s],
                                            stage_params)
                if rng is None:
                    h = stage_fn(ps, h)
                else:
                    h = stage_fn(ps, h, jax.random.fold_in(
                        jax.random.fold_in(rng, s), m_idx))
            return h

        return jax.vmap(all_stages)(x, jnp.arange(x.shape[0]))

    n_micro = x.shape[0]
    p_spec = jax.tree_util.tree_map(lambda _: P(axis), stage_params)

    if virtual > 1:
        if n_micro % nstages:
            raise ValueError(
                f"circular schedule needs n_micro ({n_micro}) % pipe "
                f"axis ({nstages}) == 0 (microbatches travel in rounds)")
        return _schedule_circular(mesh, stage_fn, stage_params, x, axis,
                                  x_spec, p_spec, rng, nstages, virtual,
                                  n_micro)

    if n_micro < nstages:
        raise ValueError(f"n_micro ({n_micro}) must be >= pipeline stages "
                         f"({nstages}) to fill the pipeline")

    def call(stage, params, inp, key):
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        if key is None:
            return stage_fn(params, inp)
        return stage_fn(params, inp, key)

    return _schedule(mesh, call, stage_params, x, axis, x_spec, p_spec,
                     rng, nstages, n_micro)


def _schedule(mesh, call, stage_params, x, axis, x_spec, p_spec, rng,
              nstages, n_micro):
    """The GPipe fill-drain schedule shared by the uniform (stacked
    SPMD stages) and heterogeneous (lax.switch branches) pipelines.
    `call(stage, params, inp, key)` runs one stage tick."""

    def local(params, xm):
        stage = jax.lax.axis_index(axis)
        total = n_micro + nstages - 1
        fwd_perm = [(i, i + 1) for i in range(nstages - 1)]
        stage_rng = (None if rng is None
                     else jax.random.fold_in(rng, stage))

        def tick(carry, t):
            state, outputs = carry
            # this stage processes microbatch m = t - stage at tick t
            # (clipped during fill/drain, where the result is discarded)
            m_idx = jnp.clip(t - stage, 0, n_micro - 1)
            x_t = jax.lax.dynamic_index_in_dim(
                xm, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False)
            inp = jnp.where(stage == 0, x_t.astype(state.dtype), state)
            key = (None if stage_rng is None
                   else jax.random.fold_in(stage_rng, m_idx))
            out = call(stage, params, inp, key)
            oidx = jnp.clip(t - (nstages - 1), 0, n_micro - 1)
            updated = jax.lax.dynamic_update_index_in_dim(
                outputs, out, oidx, 0)
            collect = jnp.logical_and(stage == nstages - 1,
                                      t >= nstages - 1)
            outputs = jnp.where(collect, updated, outputs)
            state = jax.lax.ppermute(out, axis, fwd_perm)
            return (state, outputs), None

        state0 = jnp.zeros(xm.shape[1:], xm.dtype)
        out0 = jnp.zeros_like(xm)
        (_, outputs), _ = jax.lax.scan(tick, (state0, out0),
                                       jnp.arange(total))
        # broadcast final-stage outputs to all stages
        mask = (stage == nstages - 1).astype(outputs.dtype)
        return jax.lax.psum(outputs * mask, axis)

    return shard_map(local, mesh=mesh, in_specs=(p_spec, x_spec),
                     out_specs=x_spec, check_vma=False)(stage_params, x)


def _schedule_circular(mesh, stage_fn, stage_params, x, axis, x_spec,
                       p_spec, rng, P_, v, n_micro):
    """Interleaved/circular fill-drain schedule (the 1F1B-family form).

    Device d holds virtual stages {d, d+P, …, d+(v−1)·P} (round-robin),
    microbatches hop the ring with wraparound and loop it v times.
    Work mapping: device d at tick t runs work item j = t − d (idle
    outside [0, v·n_micro)), decomposed j = round·(v·P) + w·P + m_in →
    microbatch m = round·P + m_in at virtual stage σ = w·P + d.  The
    mapping is conflict-free by construction (unique (w, m) per (d, t))
    and every output feeds the next tick's consumer directly, so no
    waiting stash exists.  Total ticks v·n_micro + P − 1: the bubble is
    (P−1) ticks instead of GPipe's (v·P−1) for the same v·P stages.

    `stage_params` leaves are (v·P, …) in virtual-stage order σ; they
    are permuted here so contiguous sharding over `axis` lands stage
    σ = w·P + d at device d row w.  Autodiff through the tick scan
    yields the reverse circular schedule (ppermute transposes to the
    reverse ring)."""
    S = v * P_

    def reorder(p):
        idx = jnp.asarray([(pos % v) * P_ + pos // v
                           for pos in range(S)])
        return p[idx]

    stage_params = jax.tree_util.tree_map(reorder, stage_params)

    def local(params, xm):
        d = jax.lax.axis_index(axis)
        total = v * n_micro + P_ - 1
        perm = [(i, (i + 1) % P_) for i in range(P_)]

        def tick(carry, t):
            state, outputs = carry
            j = t - d                       # this device's work index
            valid = jnp.logical_and(j >= 0, j < v * n_micro)
            jc = jnp.clip(j, 0, v * n_micro - 1)
            rnd, rem = jnp.divmod(jc, v * P_)
            w, m_in = jnp.divmod(rem, P_)
            m = rnd * P_ + m_in             # microbatch index
            sigma = w * P_ + d              # virtual stage id
            pw = jax.tree_util.tree_map(
                lambda p: jax.lax.dynamic_index_in_dim(
                    p, w, 0, keepdims=False), params)
            x_t = jax.lax.dynamic_index_in_dim(xm, m, 0, keepdims=False)
            # stage 0 of the ring at wrap 0 consumes fresh input;
            # everything else consumes the hopped state
            fresh = jnp.logical_and(d == 0, w == 0)
            inp = jnp.where(fresh, x_t.astype(state.dtype), state)
            if rng is None:
                out = stage_fn(pw, inp)
            else:
                key = jax.random.fold_in(jax.random.fold_in(rng, sigma), m)
                out = stage_fn(pw, inp, key)
            collect = jnp.logical_and(
                valid, jnp.logical_and(d == P_ - 1, w == v - 1))
            updated = jax.lax.dynamic_update_index_in_dim(
                outputs, out, m, 0)
            outputs = jnp.where(collect, updated, outputs)
            state = jax.lax.ppermute(out, axis, perm)
            return (state, outputs), None

        state0 = jnp.zeros(xm.shape[1:], xm.dtype)
        out0 = jnp.zeros_like(xm)
        (_, outputs), _ = jax.lax.scan(tick, (state0, out0),
                                       jnp.arange(total))
        mask = (d == P_ - 1).astype(outputs.dtype)
        return jax.lax.psum(outputs * mask, axis)

    return shard_map(local, mesh=mesh, in_specs=(p_spec, x_spec),
                     out_specs=x_spec, check_vma=False)(stage_params, x)


def pipeline_apply_hetero(mesh, branch_fn, params, x,
                          axis: str = "pipe",
                          batch_axis: str | None = None,
                          rng: jax.Array | None = None) -> jnp.ndarray:
    """GPipe schedule for NON-uniform stages: every boundary tensor is
    flattened and zero-padded to one (micro_batch, max_flat) buffer so
    the ppermute hop has a single SPMD shape, and each device runs its
    own structure via `branch_fn(stage, params, flat_mb, key)`
    (lax.switch inside).  `params` is the full resolved param dict,
    REPLICATED on every device (heterogeneous stages cannot stack) —
    the memory tradeoff that buys arbitrary per-stage structure, the
    reference's bridge-layer generality (neuralnet.cc:198-323).
    """
    x_spec = P(None, batch_axis) if batch_axis else P()
    p_spec = jax.tree_util.tree_map(lambda _: P(), params)
    nstages = mesh.shape[axis]
    n_micro = x.shape[0]
    # nstages == 1 is unreachable from HeteroPipelineNet (the trainer
    # only pipelines a pipe axis > 1) and the schedule handles it
    # degenerately anyway (empty ppermute), so no fast path exists.
    if n_micro < nstages:
        raise ValueError(f"n_micro ({n_micro}) must be >= pipeline "
                         f"stages ({nstages}) to fill the pipeline")
    return _schedule(mesh, branch_fn, params, x, axis, x_spec, p_spec,
                     rng, nstages, n_micro)
