"""Pipeline parallelism with microbatching over the "pipe" mesh axis.

The reference's closest mechanism is cross-process activation exchange
through BridgeSrc/BridgeDst layers over ZMQ PUSH/PULL (SURVEY.md §2.2-4)
— point-to-point dataflow with no microbatch schedule.  This module is
the first-class successor: a GPipe-style schedule where every device
runs one stage and activations hop stage→stage via
`jax.lax.ppermute` (XLA collective-permute over ICI), with n_micro
microbatches in flight to fill the pipeline bubble.

Constraints (SPMD): every stage must map activations of one shared
shape/dtype to the same shape/dtype (true for transformer blocks).  The
backward pass is autodiff through the scan — GPipe semantics (all
forward, then all backward), with activation memory O(n_micro) per
stage; combine with jax.checkpoint on stage_fn for O(1).

The reference's `locationid` layer field (model.proto:128) maps onto
stage ids here: net configs partition into stages by locationid.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map


def stack_stage_params(per_stage_params: Sequence[Any]) -> Any:
    """Stack a list of per-stage param pytrees along a new leading stage
    dim (leaves must match shapes across stages)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)


def pipeline_apply(mesh: Mesh, stage_fn: Callable[..., jnp.ndarray],
                   stage_params: Any, x: jnp.ndarray,
                   axis: str = "pipe",
                   batch_axis: str | None = None,
                   rng: jax.Array | None = None) -> jnp.ndarray:
    """Run microbatches through the pipeline.

    stage_params: pytree with leaves (n_stages, ...) — sharded over
    `axis` so each device keeps only its stage's slice.
    x: (n_micro, micro_batch, ...) microbatched input.  With
    `batch_axis` set (e.g. "data"), the micro_batch dim (dim 1) shards
    over that axis so dp groups pipeline DIFFERENT slices of the batch
    instead of replicating the work.
    With `rng` set, stage_fn is called as stage_fn(params, mb, key)
    where key = fold_in(fold_in(rng, stage), microbatch) — every
    (stage, microbatch) cell draws independent randomness, so
    rng-bearing layers (dropout) work inside stages; without it the
    two-arg form is called.
    Returns (n_micro, micro_batch, ...) outputs of the final stage,
    sharded the same way.
    """
    nstages = mesh.shape[axis]
    x_spec = P(None, batch_axis) if batch_axis else P()
    if nstages == 1:
        params0 = jax.tree_util.tree_map(lambda p: p[0], stage_params)
        if rng is None:
            return jax.vmap(lambda mb: stage_fn(params0, mb))(x)
        keys = jax.vmap(
            lambda m: jax.random.fold_in(jax.random.fold_in(rng, 0), m)
        )(jnp.arange(x.shape[0]))
        return jax.vmap(lambda mb, k: stage_fn(params0, mb, k))(x, keys)

    n_micro = x.shape[0]
    if n_micro < nstages:
        raise ValueError(f"n_micro ({n_micro}) must be >= pipeline stages "
                         f"({nstages}) to fill the pipeline")

    p_spec = jax.tree_util.tree_map(lambda _: P(axis), stage_params)

    def call(stage, params, inp, key):
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        if key is None:
            return stage_fn(params, inp)
        return stage_fn(params, inp, key)

    return _schedule(mesh, call, stage_params, x, axis, x_spec, p_spec,
                     rng, nstages, n_micro)


def _schedule(mesh, call, stage_params, x, axis, x_spec, p_spec, rng,
              nstages, n_micro):
    """The GPipe fill-drain schedule shared by the uniform (stacked
    SPMD stages) and heterogeneous (lax.switch branches) pipelines.
    `call(stage, params, inp, key)` runs one stage tick."""

    def local(params, xm):
        stage = jax.lax.axis_index(axis)
        total = n_micro + nstages - 1
        fwd_perm = [(i, i + 1) for i in range(nstages - 1)]
        stage_rng = (None if rng is None
                     else jax.random.fold_in(rng, stage))

        def tick(carry, t):
            state, outputs = carry
            # this stage processes microbatch m = t - stage at tick t
            # (clipped during fill/drain, where the result is discarded)
            m_idx = jnp.clip(t - stage, 0, n_micro - 1)
            x_t = jax.lax.dynamic_index_in_dim(
                xm, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False)
            inp = jnp.where(stage == 0, x_t.astype(state.dtype), state)
            key = (None if stage_rng is None
                   else jax.random.fold_in(stage_rng, m_idx))
            out = call(stage, params, inp, key)
            oidx = jnp.clip(t - (nstages - 1), 0, n_micro - 1)
            updated = jax.lax.dynamic_update_index_in_dim(
                outputs, out, oidx, 0)
            collect = jnp.logical_and(stage == nstages - 1,
                                      t >= nstages - 1)
            outputs = jnp.where(collect, updated, outputs)
            state = jax.lax.ppermute(out, axis, fwd_perm)
            return (state, outputs), None

        state0 = jnp.zeros(xm.shape[1:], xm.dtype)
        out0 = jnp.zeros_like(xm)
        (_, outputs), _ = jax.lax.scan(tick, (state0, out0),
                                       jnp.arange(total))
        # broadcast final-stage outputs to all stages
        mask = (stage == nstages - 1).astype(outputs.dtype)
        return jax.lax.psum(outputs * mask, axis)

    return shard_map(local, mesh=mesh, in_specs=(p_spec, x_spec),
                     out_specs=x_spec, check_vma=False)(stage_params, x)


def pipeline_apply_hetero(mesh, branch_fn, params, x,
                          axis: str = "pipe",
                          batch_axis: str | None = None,
                          rng: jax.Array | None = None) -> jnp.ndarray:
    """GPipe schedule for NON-uniform stages: every boundary tensor is
    flattened and zero-padded to one (micro_batch, max_flat) buffer so
    the ppermute hop has a single SPMD shape, and each device runs its
    own structure via `branch_fn(stage, params, flat_mb, key)`
    (lax.switch inside).  `params` is the full resolved param dict,
    REPLICATED on every device (heterogeneous stages cannot stack) —
    the memory tradeoff that buys arbitrary per-stage structure, the
    reference's bridge-layer generality (neuralnet.cc:198-323).
    """
    x_spec = P(None, batch_axis) if batch_axis else P()
    p_spec = jax.tree_util.tree_map(lambda _: P(), params)
    nstages = mesh.shape[axis]
    n_micro = x.shape[0]
    # nstages == 1 is unreachable from HeteroPipelineNet (the trainer
    # only pipelines a pipe axis > 1) and the schedule handles it
    # degenerately anyway (empty ppermute), so no fast path exists.
    if n_micro < nstages:
        raise ValueError(f"n_micro ({n_micro}) must be >= pipeline "
                         f"stages ({nstages}) to fill the pipeline")
    return _schedule(mesh, branch_fn, params, x, axis, x_spec, p_spec,
                     rng, nstages, n_micro)
