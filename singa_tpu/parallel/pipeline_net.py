"""Config-driven pipeline parallelism: LayerProto.locationid → stages.

Reference: the Worker moves activations between layer `locationid`s via
BridgeSrc/BridgeDst over ZMQ (model.proto:128,
src/worker/worker.cc:139-155,240-302) — each location runs its slice of
the net and activations hop point-to-point.  TPU-native successor: the
net's layers partition into pipeline stages by locationid, stage
parameters stack along a leading stage axis sharded over the mesh's
"pipe" axis, and microbatched activations hop stage→stage through
`pipeline_apply`'s ppermute schedule (parallel/pipeline.py).

Stage assignment contract (validated, fail-loud):
  * locationid == 0 layers topologically BEFORE the first staged layer
    form the `pre` group (data/parsers/embedding — replicated compute,
    like the reference running its input layers on every worker's
    location 0);
  * locationid 1..S mark the S pipeline stages.  SPMD requires the
    stages be structurally identical (same layer types and param
    shapes, in order) — true for transformer blocks, the model family
    pipeline parallelism exists for.  Each stage must consume exactly
    one cross-stage tensor and produce one.
  * locationid == 0 layers topologically AFTER the staged region form
    the `post` group (head + loss).

The whole thing stays inside the Trainer's flat param dict: stacking
happens inside the jitted loss (its transpose, unstacking, is the
gradient path), so the updater, checkpointing, and cadence machinery
are untouched.  `remat=True` wraps each stage in jax.checkpoint —
GPipe with per-stage rematerialization, bounding activation memory at
O(n_micro) boundary tensors instead of O(n_micro · per-stage
activations).

Rng-bearing layers inside stages (dropout) are supported: the schedule
folds an independent key per (stage, microbatch) cell from the step
rng (pipeline_apply's `rng`), so dropout masks differ across
microbatches exactly as they would across the equivalent unpipelined
batch rows.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.layers import Context
from ..core.net import NeuralNet
from .pipeline import pipeline_apply


class PipelineError(ValueError):
    pass


class NonUniformStages(PipelineError):
    """Stages exist but are not SPMD-stackable (different structure or
    wiring) — the trainer falls back to HeteroPipelineNet."""


def stage_assignment(net: NeuralNet) -> Tuple[List[str], List[List[str]],
                                              List[str]]:
    """(pre, stages, post) layer-name groups from locationid, in the
    net's topological order."""
    topo = net.topo
    loc = {name: net.layers[name].cfg.locationid for name in topo}
    staged = [n for n in topo if loc[n] > 0]
    if not staged:
        raise PipelineError("no layer has locationid > 0")
    ids = sorted({loc[n] for n in staged})
    if ids != list(range(1, len(ids) + 1)):
        raise PipelineError(f"locationids must be contiguous 1..S, got {ids}")
    first = topo.index(staged[0])
    last = max(topo.index(n) for n in staged)
    pre = [n for n in topo[:first] if loc[n] == 0]
    mid0 = [n for n in topo[first:last + 1] if loc[n] == 0]
    if mid0:
        raise PipelineError(
            f"layers {mid0} sit between pipeline stages but have "
            f"locationid 0 — assign them to a stage")
    post = [n for n in topo[last + 1:]]
    stages = [[n for n in topo if loc[n] == s] for s in ids]
    return pre, stages, post


def _stage_param_names(net: NeuralNet, stage: List[str]) -> List[str]:
    names = []
    for lname in stage:
        for spec in net.layers[lname].param_specs:
            names.append(spec.name)
    return names


def _validate_uniform(net: NeuralNet, stages: List[List[str]]) -> None:
    t0 = [net.layers[n].cfg.type for n in stages[0]]
    s0 = [net.param_specs[p].shape for p in _stage_param_names(net,
                                                              stages[0])]
    for i, st in enumerate(stages[1:], 2):
        ti = [net.layers[n].cfg.type for n in st]
        si = [net.param_specs[p].shape
              for p in _stage_param_names(net, st)]
        if ti != t0 or si != s0:
            raise NonUniformStages(
                f"stage {i} is not structurally identical to stage 1: "
                f"types {ti} vs {t0}, param shapes {si} vs {s0}")


def _external_input(net: NeuralNet, stage: List[str]) -> str:
    """The single srclayer reference crossing into this stage."""
    inside = set(stage)
    ext = []
    for lname in stage:
        for src in net.layers[lname].cfg.srclayers:
            if src not in inside:
                ext.append(src)
    uniq = sorted(set(ext))
    if len(uniq) != 1:
        raise PipelineError(
            f"stage {stage} must consume exactly one external tensor, "
            f"found {uniq}")
    return uniq[0]


# ---------------------------------------------------------------------------
# scaffolding shared by the uniform (PipelineNet) and heterogeneous
# (HeteroPipelineNet) forms — ONE definition of the mesh checks, the
# pre/post group application, the dp/batch_axis heuristic, and the
# stage-rng fold, so the two pipelines cannot drift apart.


def _check_mesh(pnet, mesh, axis):
    """Returns the interleave factor v = n_stages / pipe size.  v == 1
    is the plain GPipe schedule (one stage per device); v > 1 — only
    for the uniform PipelineNet — selects the circular/interleaved
    schedule (device d runs stages d, d+P, …), which cuts the bubble
    ~v× (pipeline.py _schedule_circular)."""
    if mesh is None or axis not in mesh.shape:
        raise PipelineError(f"{type(pnet).__name__}.apply needs a mesh "
                            f"with a {axis!r} axis")
    p = mesh.shape[axis]
    if pnet.n_stages % p:
        # a non-multiple would silently drop stages
        raise PipelineError(
            f"{pnet.n_stages} locationid stages need a pipe axis that "
            f"divides them, mesh has {axis}={p}")
    v = pnet.n_stages // p
    if v > 1 and not getattr(pnet, "supports_interleave", False):
        raise PipelineError(
            f"{pnet.n_stages} stages on {axis}={p} needs the "
            f"interleaved schedule, which {type(pnet).__name__} does "
            f"not support — use equal stage/axis counts")
    return v


def _pre_apply(pnet, params, batch, rng, train, mesh, compute_dtype,
               step, outputs, metrics):
    """Run the pre group; returns (train, total_loss, staged_input)."""
    if train is None:
        train = pnet.net.phase == "kTrain"
    total_loss, m, _ = pnet.net.apply(
        params, batch, rng=rng, train=train, mesh=mesh,
        compute_dtype=compute_dtype, layer_subset=pnet.pre,
        outputs=outputs, step=step)
    metrics.update(m)
    x = outputs[pnet.stage_inputs[0]]
    if x.shape[0] % pnet.n_micro:
        raise PipelineError(f"batch {x.shape[0]} not divisible by "
                            f"n_micro {pnet.n_micro}")
    return train, total_loss, x


def _post_apply(pnet, params, batch, rng, train, mesh, compute_dtype,
                step, outputs, metrics, total_loss):
    post_loss, m, _ = pnet.net.apply(
        params, batch, rng=rng, train=train, mesh=mesh,
        compute_dtype=compute_dtype, layer_subset=pnet.post,
        outputs=outputs, step=step)
    metrics.update(m)
    return total_loss + post_loss, metrics, outputs


def _data_batch_axis(mesh, micro_rows):
    """Shard microbatch rows over "data" so dp groups pipeline
    different batch slices; replicated (correct, just wasteful) when
    the rows don't divide."""
    dp = mesh.shape.get("data", 1)
    return "data" if dp > 1 and micro_rows % dp == 0 else None


def _stage_rng(rng, train):
    """Per-(stage, microbatch) key base for rng-bearing stage layers."""
    import jax as _jax
    return (_jax.random.fold_in(rng, 0x9199)
            if rng is not None and train else None)


class HeteroPipelineNet:
    """Pipeline parallelism for NON-uniform stages — the reference's
    actual bridge-layer use case: a conv net whose locationid marks cut
    it into structurally DIFFERENT stages (conv stage, fc stage, ...),
    any legal wiring (neuralnet.cc:198-323 inserts bridges for
    arbitrary layouts).

    Mechanism (see pipeline_apply_hetero): the GPipe ppermute schedule
    needs one SPMD hop shape, so every boundary activation is flattened
    and zero-padded to the widest boundary; each device selects its own
    stage body with lax.switch on the pipe-axis index and
    unflattens/reflattens at its boundary shapes.  Params are
    replicated on every pipe row (heterogeneous shapes cannot stack) —
    a memory tradeoff that is cheap at the conv-net scales this exists
    for.  Constraints kept from the SPMD form: each stage consumes
    exactly ONE tensor from the previous stage (any layer of it, not
    just the last) and exactly one tensor crosses out of the staged
    region into the post group.  Rng-bearing layers are supported the
    same way (per (stage, microbatch) key).
    """

    def __init__(self, net: NeuralNet, n_micro: int):
        self.net = net
        self.n_micro = n_micro
        self.pre, self.stages, self.post = stage_assignment(net)
        self.stage_inputs = [_external_input(net, st)
                             for st in self.stages]
        for s in range(1, len(self.stages)):
            if self.stage_inputs[s] not in self.stages[s - 1]:
                raise PipelineError(
                    f"stage {s + 1} consumes {self.stage_inputs[s]!r}, "
                    f"which is not in stage {s}")
        staged_names = {n for st in self.stages for n in st}
        finals = {src for name in self.post
                  for src in net.layers[name].cfg.srclayers
                  if src in staged_names}
        if len(finals) != 1:
            raise PipelineError(
                f"exactly one staged tensor may cross into the post "
                f"group, found {sorted(finals)}")
        self.final = next(iter(finals))
        if self.final not in self.stages[-1]:
            raise PipelineError(
                f"the post group consumes {self.final!r}, which is not "
                f"in the last stage")
        # boundary layer whose output each stage forwards
        self.forwarded = [self.stage_inputs[s + 1]
                          for s in range(len(self.stages) - 1)]
        self.forwarded.append(self.final)

    @property
    def n_stages(self) -> int:
        return len(self.stages)

    def _mb_shape(self, layer_name: str) -> tuple:
        shape = self.net.layers[layer_name].out_shape
        return (shape[0] // self.n_micro,) + tuple(shape[1:])

    def apply(self, params, batch, rng=None, train: Optional[bool] = None,
              mesh=None, compute_dtype=None, axis: str = "pipe",
              remat: bool = True, step=None):
        import numpy as _np

        from .pipeline import pipeline_apply_hetero

        _check_mesh(self, mesh, axis)
        outputs: Dict[str, Any] = {}
        metrics: Dict[str, jnp.ndarray] = {}
        train, total_loss, x = _pre_apply(
            self, params, batch, rng, train, mesh, compute_dtype, step,
            outputs, metrics)
        b = x.shape[0]
        mb = b // self.n_micro
        in_shapes = [self._mb_shape(n) for n in self.stage_inputs]
        out_shapes = [self._mb_shape(n) for n in self.forwarded]
        # per-microbatch-row flat widths; buffers are (mb, maxflat)
        maxflat = max(int(_np.prod(s[1:]))
                      for s in in_shapes + out_shapes)
        buf_dtype = x.dtype

        full = self.net._resolve_params(params)

        def make_branch(s):
            stage, inp_name = self.stages[s], self.stage_inputs[s]
            ishape, oshape = in_shapes[s], out_shapes[s]

            def branch(prms, flat_in, key):
                # batch-polymorphic: under batch_axis sharding the
                # local microbatch rows are mb / dp
                xin = flat_in[:, :int(_np.prod(ishape[1:]))]
                xin = xin.reshape((flat_in.shape[0],)
                                  + tuple(ishape[1:])).astype(buf_dtype)
                louts = {inp_name: xin}
                for name in stage:
                    layer = self.net.layers[name]
                    srcs = [louts[src] for src in layer.cfg.srclayers]
                    ctx = Context(batch=None, train=train, rng=key,
                                  layer_index=self.net.topo.index(name),
                                  mesh=None, compute_dtype=compute_dtype)
                    louts[name] = layer.apply(prms, srcs, ctx)
                y = louts[self.forwarded[s]]
                if y.dtype != buf_dtype:
                    # the transport buffer carries every boundary in
                    # one dtype; a silent cast at each hop would
                    # diverge from the unpipelined net's numerics
                    raise ValueError(
                        f"hetero-pipeline boundary "
                        f"{self.forwarded[s]!r} produces {y.dtype} but "
                        f"the stage transport buffer is {buf_dtype} "
                        f"(the staged input's dtype) — run with a "
                        f"uniform compute_dtype or cast in the net")
                y = y.reshape(flat_in.shape[0], -1)
                pad = maxflat - y.shape[1]
                y = jnp.pad(y, ((0, 0), (0, pad)))
                return y

            return jax.checkpoint(branch) if remat else branch

        branches = [make_branch(s) for s in range(self.n_stages)]

        def branch_fn(stage, prms, flat_in, key):
            if key is None:
                return jax.lax.switch(
                    stage, [lambda a, s=s: branches[s](prms, a, None)
                            for s in range(self.n_stages)], flat_in)
            return jax.lax.switch(
                stage, [lambda a, k, s=s: branches[s](prms, a, k)
                        for s in range(self.n_stages)], flat_in, key)

        xm = x.reshape(self.n_micro, mb, -1).astype(buf_dtype)
        xm = jnp.pad(xm, ((0, 0), (0, 0), (0, maxflat - xm.shape[2])))
        y = pipeline_apply_hetero(
            mesh, branch_fn, full, xm, axis=axis,
            batch_axis=_data_batch_axis(mesh, mb),
            rng=_stage_rng(rng, train))
        oshape = out_shapes[-1]
        osz = int(_np.prod(oshape[1:]))
        y = y[:, :, :osz].reshape((b,) + tuple(oshape[1:]))
        outputs[self.final] = y
        return _post_apply(self, params, batch, rng, train, mesh,
                           compute_dtype, step, outputs, metrics,
                           total_loss)


class PipelineNet:
    """Pipelined evaluator over a built NeuralNet (see module doc)."""

    supports_interleave = True

    def __init__(self, net: NeuralNet, n_micro: int):
        self.net = net
        self.n_micro = n_micro
        self.pre, self.stages, self.post = stage_assignment(net)
        _validate_uniform(net, self.stages)
        self.stage_inputs = [_external_input(net, st)
                             for st in self.stages]
        # the schedule always forwards the topologically-LAST layer's
        # output of each stage, so anything else consuming a different
        # layer of the previous stage would silently get wrong numerics
        for s in range(1, len(self.stages)):
            if self.stage_inputs[s] != self.stages[s - 1][-1]:
                raise NonUniformStages(
                    f"stage {s + 1} must consume stage {s}'s last layer "
                    f"{self.stages[s - 1][-1]!r}, not "
                    f"{self.stage_inputs[s]!r}")
        last = self.stages[-1][-1]
        staged_names = {n for st in self.stages for n in st}
        for name in self.post:
            for src in net.layers[name].cfg.srclayers:
                if src in staged_names and src != last:
                    raise NonUniformStages(
                        f"post layer {name!r} consumes mid-stage layer "
                        f"{src!r}; only the final stage output "
                        f"{last!r} crosses out of the pipeline")
        self.param_names = [_stage_param_names(net, st)
                            for st in self.stages]

    @property
    def n_stages(self) -> int:
        return len(self.stages)

    def _stack_params(self, params: Dict[str, jnp.ndarray]):
        """{stage-0 param name: (S, ...) stacked leaf}."""
        full = self.net._resolve_params(params)
        out = {}
        for j, name0 in enumerate(self.param_names[0]):
            out[name0] = jnp.stack(
                [full[names[j]] for names in self.param_names])
        return out

    def apply(self, params, batch, rng=None, train: Optional[bool] = None,
              mesh=None, compute_dtype=None, axis: str = "pipe",
              remat: bool = True, step=None):
        """Pipelined forward (+ loss): pre group → microbatched staged
        region over the pipe axis → post group.  Same signature shape
        as NeuralNet.apply; returns (total_loss, metrics, outputs).
        The pre/post groups run through NeuralNet.apply(layer_subset=…)
        so their per-layer semantics (fuse_from, remat, aux losses)
        stay identical to the unpipelined net."""
        virtual = _check_mesh(self, mesh, axis)
        outputs: Dict[str, Any] = {}
        metrics: Dict[str, jnp.ndarray] = {}
        train, total_loss, x = _pre_apply(
            self, params, batch, rng, train, mesh, compute_dtype, step,
            outputs, metrics)
        b = x.shape[0]
        xm = x.reshape((self.n_micro, b // self.n_micro) + x.shape[1:])

        template = self.stages[0]
        tmpl_inp = self.stage_inputs[0]

        def stage_fn(stage_params, mb, key=None):
            louts = {tmpl_inp: mb}
            out = None
            for name in template:
                layer = self.net.layers[name]
                srcs = [louts[src] for src in layer.cfg.srclayers]
                ctx = Context(batch=None, train=train, rng=key,
                              layer_index=self.net.topo.index(name),
                              mesh=None, compute_dtype=compute_dtype)
                out = layer.apply(stage_params, srcs, ctx)
                louts[name] = out
            return out

        if remat:
            stage_fn = jax.checkpoint(stage_fn)

        stacked = self._stack_params(params)
        y = pipeline_apply(
            mesh, stage_fn, stacked, xm, axis=axis,
            batch_axis=_data_batch_axis(mesh, b // self.n_micro),
            rng=_stage_rng(rng, train), virtual=virtual)
        last_out = self.stages[-1][-1]
        outputs[last_out] = y.reshape((b,) + y.shape[2:])
        return _post_apply(self, params, batch, rng, train, mesh,
                           compute_dtype, step, outputs, metrics,
                           total_loss)
