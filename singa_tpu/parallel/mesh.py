"""Device mesh construction — the TPU-native Cluster topology.

Reference: /root/reference/include/utils/cluster.h — process topology as
(nworkers, nservers, nprocs_per_group, nthreads_per_procs) with worker
groups running data-parallel replicas and intra-group executors running
net partitions (§2.2 of SURVEY.md).  On TPU the topology is a
jax.sharding.Mesh with named axes:

  data    — data parallelism (reference: worker groups + kDataPartition)
  model   — tensor parallelism (reference: kLayerPartition)
  pipe    — pipeline stages (reference: locationid/bridge layers)
  seq     — sequence/context parallelism (new; ring/Ulysses attention)
  expert  — expert parallelism (new; MoE)

Legacy ClusterProto fields map onto mesh axes via mesh_from_cluster();
the server-plane fields (nservers, ports, bandwidth…) have no TPU
meaning — gradient aggregation is a compiled psum — and are accepted
and ignored with a note.
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from ..config.schema import ClusterConfig

AXES = ("data", "model", "pipe", "seq", "expert")


def make_mesh(devices: Optional[Sequence] = None, *, data: int = 0,
              model: int = 1, pipe: int = 1, seq: int = 1,
              expert: int = 1) -> Mesh:
    """Build a 5-axis mesh. `data=0` means "absorb remaining devices"."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    fixed = model * pipe * seq * expert
    if data == 0:
        if n % fixed:
            raise ValueError(
                f"{n} devices not divisible by model*pipe*seq*expert={fixed}")
        data = n // fixed
    total = data * fixed
    if total != n:
        raise ValueError(f"mesh {data}x{model}x{pipe}x{seq}x{expert}={total} "
                         f"!= {n} devices")
    arr = np.asarray(devices).reshape(data, model, pipe, seq, expert)
    return Mesh(arr, AXES)


def mesh_from_cluster(cluster: Optional[ClusterConfig],
                      net_partition_type: str = "kNone",
                      devices: Optional[Sequence] = None) -> Mesh:
    """Map ClusterProto topology onto a mesh.

    Explicit TPU-native axis fields win; otherwise the legacy fields are
    interpreted per §2.2: ngroups = nworkers/nprocs_per_group groups of
    group_size = nprocs_per_group*nthreads_per_procs executors each.
    Groups are data-parallel; intra-group executors are data- or
    model-parallel per NetProto.partition_type (cluster.h:49-60,
    neuralnet.cc:45-56).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if cluster is None:
        return make_mesh(devices)
    if any((cluster.data_parallel, cluster.tensor_parallel,
            cluster.pipeline_parallel, cluster.sequence_parallel,
            cluster.expert_parallel)):
        return make_mesh(
            devices,
            data=cluster.data_parallel or 0,
            model=cluster.tensor_parallel or 1,
            pipe=cluster.pipeline_parallel or 1,
            seq=cluster.sequence_parallel or 1,
            expert=cluster.expert_parallel or 1)
    group_size = cluster.nprocs_per_group * cluster.nthreads_per_procs
    ngroups = max(cluster.nworkers // max(cluster.nprocs_per_group, 1), 1)
    # Reference topology (§2.2-2/3, cluster.h:49-60): ngroups
    # data-parallel worker groups × group_size executors per group; the
    # in-group executors split the BATCH under kDataPartition or the
    # NEURON dim under kLayerPartition (neuralnet.cc:45-56).  Faithful
    # mesh mapping:
    #   kLayerPartition → (data=ngroups, model=group_size)
    #   kDataPartition/kNone → one data axis over all devices (groups
    #     and in-group executors both split the batch, so the two
    #     levels collapse into one axis with identical numerics)
    # Anything that cannot map exactly (device count != topology,
    # group_size not dividing n) warns LOUDLY instead of silently
    # reshaping.  NOTE: with an async consistency tier configured
    # (Elastic/RandomSync), ngroups is realized by the replica runtime
    # (parallel/elastic.py), not by this mesh.
    def _warn(msg):
        import sys
        print(f"warning: mesh_from_cluster: {msg}", file=sys.stderr)

    if ngroups * group_size != n:
        _warn(f"cluster topology ngroups={ngroups} x "
              f"group_size={group_size} != {n} devices; axis sizes "
              f"follow the device count")
    if net_partition_type == "kLayerPartition" and group_size > 1:
        tp = group_size if n % group_size == 0 \
            else math.gcd(group_size, n)
        if tp != group_size:
            _warn(f"group_size {group_size} does not divide device "
                  f"count {n}; model axis clipped to gcd {tp}")
        return make_mesh(devices, data=n // tp, model=tp)
    # kDataPartition / kNone: all devices data-parallel
    return make_mesh(devices)
