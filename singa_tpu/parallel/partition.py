"""Partition specs: NetProto/ParamProto partition config → NamedShardings.

This is the TPU-native successor of the reference's net partitioner
(neuralnet.cc:112-323): where the reference rewrites the layer graph with
Slice/Concate/Split/Bridge connector layers, here the same intent is a
set of sharding annotations; GSPMD compiles in the all-gathers /
reduce-scatters / collective-permutes those connector layers hand-coded
over ZMQ.

  kDataPartition  → batch dim sharded over the "data" axis
                    (gradient psum inserted by XLA at the loss reduce)
  kLayerPartition → param partition_dim sharded over "model"
                    (activations follow by propagation)

Per-layer LayerProto.partition_type additionally becomes an activation
sharding constraint inside NeuralNet.apply (net.py _constrain) — the
9 src→dst connector cases of the reference partitioner fall out of
GSPMD propagation between differently-constrained layers.  The
reference's SetupAfterPartition hyperparameter rewriting
(layer.cc:54-61) has no analogue by construction: layers here keep
GLOBAL shapes (XLA's global-view semantics), so hyperparameters never
change under partitioning.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.net import NeuralNet


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def param_shardings(mesh: Mesh, net: NeuralNet,
                    tp_axis: str = "model",
                    pad_uneven: bool = False) -> Dict[str, NamedSharding]:
    """Per-param NamedSharding from ParamProto.partition_dim + the layer
    defaults (weights partition on the neuron dim under kLayerPartition,
    base_layer.h:121-128).

    A param whose partition dim doesn't divide the mesh axis:
      * pad_uneven=False (raw arrays): replicated STORAGE
        (jax.device_put only tiles divisible dims) — its COMPUTE still
        partitions, via the in-step uneven constraint
        NeuralNet._constrain_uneven_params emits (GSPMD tiles with an
        implicit last-shard pad, the reference's
        last-partition-remainder contract, neuralnet.cc:160-162);
      * pad_uneven=True (arrays padded by pad_params): sharded STORAGE
        over the padded dim — use with shard_params/shard_opt_state,
        which pad first.  NeuralNet._resolve_params slices the pad off
        at use, so padded storage is transparent to every consumer."""
    out = {}
    for name, spec in net.param_specs.items():
        axis = spec.mesh_axis or tp_axis
        n = mesh.shape[axis]
        dim = spec.partition_dim
        if n > 1 and dim >= 0 and (spec.shape[dim] % n == 0 or pad_uneven):
            axes: list = [None] * len(spec.shape)
            axes[dim] = axis
            out[name] = NamedSharding(mesh, P(*axes))
        else:
            out[name] = replicated(mesh)
    return out


def pad_params(mesh: Mesh, net: NeuralNet, params: Dict[str, jnp.ndarray],
               tp_axis: str = "model") -> Dict[str, jnp.ndarray]:
    """Zero-pad every uneven partition dim up to the next multiple of
    its mesh axis, so weights AND optimizer state of non-divisible dims
    stop replicating (VERDICT r4 item 6; reference anchor
    base_layer.cc:125-129 last-partition remainder).  Zero pad is
    closed under training: pad grads are exactly zero (slice
    transpose), so momentum/Adam state and weight decay keep the pad at
    zero forever.  NeuralNet._resolve_params slices arrays back to
    their spec shape at use, making the layout transparent to the step
    and decode; checkpoints are saved UNPADDED (Trainer._ckpt_state →
    net.unpad_params) so they stay spec-shaped and mesh-portable."""
    out = dict(params)
    for name, spec in net.param_specs.items():
        if name not in out:
            continue
        axis = spec.mesh_axis or tp_axis
        n = mesh.shape[axis]
        dim = spec.partition_dim
        if (n > 1 and dim >= 0 and spec.shape[dim] % n
                # idempotence: only pad a spec-shaped array — an
                # already-padded one (a second pass through this API)
                # must not grow again
                and out[name].shape[dim] == spec.shape[dim]):
            widths = [(0, 0)] * len(spec.shape)
            widths[dim] = (0, -spec.shape[dim] % n)
            out[name] = jnp.pad(out[name], widths)
    return out


def batch_shardings(mesh: Mesh, batch_tree: Any,
                    data_axis: str = "data") -> Any:
    """Shard every leaf's dim 0 (batch) over the data axis."""
    def leaf(x):
        return NamedSharding(mesh, P(data_axis))
    return jax.tree_util.tree_map(leaf, batch_tree)


def seq_batch_shardings(mesh: Mesh, batch_tree: Any,
                        data_axis: str = "data",
                        seq_axis: str = "seq") -> Any:
    """Token batches (B, S): shard batch over data AND sequence over seq
    — the input layout for ring/Ulysses sequence parallelism."""
    def leaf(x):
        if getattr(x, "ndim", 0) >= 2:
            return NamedSharding(mesh, P(data_axis, seq_axis))
        return NamedSharding(mesh, P(data_axis))
    return jax.tree_util.tree_map(leaf, batch_tree)


def shard_params(mesh: Mesh, net: NeuralNet, params: Dict[str, jnp.ndarray],
                 tp_axis: str = "model") -> Dict[str, jnp.ndarray]:
    """pad_params + device_put: uneven partition dims get padded,
    SHARDED storage instead of replicating."""
    shardings = param_shardings(mesh, net, tp_axis, pad_uneven=True)
    padded = pad_params(mesh, net, params, tp_axis)
    return {k: jax.device_put(v, shardings.get(k, replicated(mesh)))
            for k, v in padded.items()}


def shard_opt_state(mesh: Mesh, net: NeuralNet, opt_state,
                    tp_axis: str = "model"):
    """Optimizer history mirrors the param shardings (the TPU analogue of
    the reference's servers sharding params by id — param history lives
    with its shard), including the padded layout for uneven dims."""
    shardings = param_shardings(mesh, net, tp_axis, pad_uneven=True)

    def put_tree(tree):
        padded = pad_params(mesh, net, tree, tp_axis)
        return {k: jax.device_put(v, shardings.get(k, replicated(mesh)))
                for k, v in padded.items()}
    return {k: put_tree(v) for k, v in opt_state.items()}


def shard_batch(mesh: Mesh, batch, data_axis: str = "data",
                shardings_fn=None):
    """device_put a host batch tree onto the mesh.  `shardings_fn`
    defaults to batch_shardings; pass seq_batch_shardings for
    sequence-parallel token layouts."""
    shardings = (shardings_fn or batch_shardings)(mesh, batch, data_axis)
    return jax.tree_util.tree_map(jax.device_put, batch, shardings)


def chunk_shardings(mesh: Mesh, chunk_tree: Any, data_axis: str = "data",
                    seq_axis: Optional[str] = None) -> Any:
    """Shardings for a STACKED chunk of batches (leading scan axis):
    the step axis stays unsharded — every device runs every scan step —
    while dim 1 (the batch) shards over `data_axis`, exactly the layout
    `train_steps`' in-scan per-step slices expect.  With `seq_axis`,
    token leaves of rank >= 3 additionally shard their sequence dim
    (the stacked form of seq_batch_shardings)."""
    def leaf(x):
        if seq_axis is not None and getattr(x, "ndim", 0) >= 3:
            return NamedSharding(mesh, P(None, data_axis, seq_axis))
        return NamedSharding(mesh, P(None, data_axis))
    return jax.tree_util.tree_map(leaf, chunk_tree)


def place_chunk(mesh: Mesh, chunk: Any, data_axis: str = "data",
                seq_axis: Optional[str] = None) -> Any:
    """device_put a stacked host chunk with batch-dim shardings.  The
    replacement for `jnp.stack`-ing device batches, which silently
    gathered the whole chunk onto the default device under a mesh."""
    shardings = chunk_shardings(mesh, chunk, data_axis, seq_axis)
    return jax.tree_util.tree_map(jax.device_put, chunk, shardings)
