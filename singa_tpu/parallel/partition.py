"""Partition specs: NetProto/ParamProto partition config → NamedShardings.

This is the TPU-native successor of the reference's net partitioner
(neuralnet.cc:112-323): where the reference rewrites the layer graph with
Slice/Concate/Split/Bridge connector layers, here the same intent is a
set of sharding annotations; GSPMD compiles in the all-gathers /
reduce-scatters / collective-permutes those connector layers hand-coded
over ZMQ.

  kDataPartition  → batch dim sharded over the "data" axis
                    (gradient psum inserted by XLA at the loss reduce)
  kLayerPartition → param partition_dim sharded over "model"
                    (activations follow by propagation)

Per-layer LayerProto.partition_type additionally becomes an activation
sharding constraint inside NeuralNet.apply (net.py _constrain) — the
9 src→dst connector cases of the reference partitioner fall out of
GSPMD propagation between differently-constrained layers.  The
reference's SetupAfterPartition hyperparameter rewriting
(layer.cc:54-61) has no analogue by construction: layers here keep
GLOBAL shapes (XLA's global-view semantics), so hyperparameters never
change under partitioning.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.net import NeuralNet


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def param_shardings(mesh: Mesh, net: NeuralNet,
                    tp_axis: str = "model") -> Dict[str, NamedSharding]:
    """Per-param NamedSharding from ParamProto.partition_dim + the layer
    defaults (weights partition on the neuron dim under kLayerPartition,
    base_layer.h:121-128).  A param whose partition dim doesn't divide
    the mesh axis gets replicated STORAGE (jax.device_put only tiles
    divisible dims) — its COMPUTE still partitions, via the in-step
    uneven constraint NeuralNet._constrain_uneven_params emits (GSPMD
    tiles with an implicit last-shard pad, the reference's
    last-partition-remainder contract, neuralnet.cc:160-162)."""
    out = {}
    for name, spec in net.param_specs.items():
        axis = spec.mesh_axis or tp_axis
        n = mesh.shape[axis]
        dim = spec.partition_dim
        if n > 1 and dim >= 0 and spec.shape[dim] % n == 0:
            axes: list = [None] * len(spec.shape)
            axes[dim] = axis
            out[name] = NamedSharding(mesh, P(*axes))
        else:
            out[name] = replicated(mesh)
    return out


def batch_shardings(mesh: Mesh, batch_tree: Any,
                    data_axis: str = "data") -> Any:
    """Shard every leaf's dim 0 (batch) over the data axis."""
    def leaf(x):
        return NamedSharding(mesh, P(data_axis))
    return jax.tree_util.tree_map(leaf, batch_tree)


def seq_batch_shardings(mesh: Mesh, batch_tree: Any,
                        data_axis: str = "data",
                        seq_axis: str = "seq") -> Any:
    """Token batches (B, S): shard batch over data AND sequence over seq
    — the input layout for ring/Ulysses sequence parallelism."""
    def leaf(x):
        if getattr(x, "ndim", 0) >= 2:
            return NamedSharding(mesh, P(data_axis, seq_axis))
        return NamedSharding(mesh, P(data_axis))
    return jax.tree_util.tree_map(leaf, batch_tree)


def shard_params(mesh: Mesh, net: NeuralNet, params: Dict[str, jnp.ndarray],
                 tp_axis: str = "model") -> Dict[str, jnp.ndarray]:
    shardings = param_shardings(mesh, net, tp_axis)
    return {k: jax.device_put(v, shardings[k]) for k, v in params.items()}


def shard_opt_state(mesh: Mesh, net: NeuralNet, opt_state,
                    tp_axis: str = "model"):
    """Optimizer history mirrors the param shardings (the TPU analogue of
    the reference's servers sharding params by id — param history lives
    with its shard)."""
    shardings = param_shardings(mesh, net, tp_axis)

    def put_tree(tree):
        return {k: jax.device_put(v, shardings[k]) for k, v in tree.items()}
    return {k: put_tree(v) for k, v in opt_state.items()}


def shard_batch(mesh: Mesh, batch, data_axis: str = "data",
                shardings_fn=None):
    """device_put a host batch tree onto the mesh.  `shardings_fn`
    defaults to batch_shardings; pass seq_batch_shardings for
    sequence-parallel token layouts."""
    shardings = (shardings_fn or batch_shardings)(mesh, batch, data_axis)
    return jax.tree_util.tree_map(jax.device_put, batch, shardings)
