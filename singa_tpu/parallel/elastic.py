"""Asynchronous multi-replica consistency tier: Elastic averaging +
RandomSync — the reference parameter server's two sync *algorithms*,
preserved as first-class capability.

Reference semantics:
- **Elastic** (EASGD, param.cc:216-256): each replica periodically
  exchanges with a center copy: diff = (replica - center) * alpha;
  center += diff; replica -= diff; alpha = moving_rate / ngroups
  (param_manager.cc:15).  Cadence: UpdaterProto.sync_frequency after
  warmup_steps (model.proto:336-338, worker.cc:44-55).
- **RandomSync** (param.cc:102-213): the replica sends a seeded random
  *sample* of (data - snapshot) deltas; the center adds the deltas and
  returns its old values; the replica overwrites sampled entries with
  the center values and updates its snapshot.  The sample size follows
  the bandwidth model (param_manager.cc:85-93).

On TPU the synchronous psum path inside the compiled step replaces the
PS for intra-slice gradients; this module is the *cross-slice* tier
(slices connected over DCN, where async/compressed sync still pays).
The math is pure pytree ops, so it runs under jit on whatever process
holds the center copy; transport across hosts is jax.distributed /
multi-slice runtime.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from ..config.schema import UpdaterConfig


def elastic_update(replica, center, alpha: float):
    """One EASGD exchange (param.cc:232-256). Returns (replica, center)."""
    def one(r, c):
        diff = (r - c) * alpha
        return r - diff, c + diff
    pairs = jax.tree_util.tree_map(one, replica, center)
    new_r = jax.tree_util.tree_map(lambda p: p[0], pairs,
                                   is_leaf=lambda x: isinstance(x, tuple))
    new_c = jax.tree_util.tree_map(lambda p: p[1], pairs,
                                   is_leaf=lambda x: isinstance(x, tuple))
    return new_r, new_c


def randomsync_update(replica, center, snapshot, sample_ratio: float,
                      rng: jax.Array):
    """One RandomSync exchange (param.cc:102-213).

    A seeded uniform mask selects ~sample_ratio of entries; the center
    absorbs the replica's masked delta vs snapshot, the replica adopts
    the center's resulting values at the mask, and the snapshot records
    them.  Returns (replica, center, snapshot).
    """
    leaves, treedef = jax.tree_util.tree_flatten(replica)
    keys = jax.random.split(rng, len(leaves))
    c_leaves = jax.tree_util.tree_leaves(center)
    s_leaves = jax.tree_util.tree_leaves(snapshot)
    new_r, new_c, new_s = [], [], []
    for r, c, s, k in zip(leaves, c_leaves, s_leaves, keys):
        mask = (jax.random.uniform(k, r.shape) < sample_ratio
                ).astype(r.dtype)
        delta = (r - s) * mask
        c2 = c + delta
        r2 = r * (1 - mask) + c2 * mask
        s2 = s * (1 - mask) + c2 * mask
        new_r.append(r2)
        new_c.append(c2)
        new_s.append(s2)
    un = jax.tree_util.tree_unflatten
    return un(treedef, new_r), un(treedef, new_c), un(treedef, new_s)


def sync_sample_ratio(bandwidth_mb_s: float, nservers: int, nworkers: int,
                      model_size_floats: int, compute_time_s: float) -> float:
    """Bandwidth-adaptive sample ratio (param_manager.cc:85-93):
    the fraction of the model that fits through the pipe per step."""
    if model_size_floats <= 0 or compute_time_s <= 0:
        return 1.0
    throughput = bandwidth_mb_s * 1e6 / 4.0 * nservers   # floats/sec
    demand = model_size_floats * nworkers / compute_time_s
    return float(max(0.0, min(1.0, throughput / demand)))


class ElasticController:
    """Cross-slice consistency driver with the reference's cadence knobs.

    One instance lives on the coordinating process; `maybe_sync` is
    called each step with that slice's params.
    """

    def __init__(self, cfg: UpdaterConfig, ngroups: int = 1):
        self.cfg = cfg
        self.alpha = (cfg.moving_rate / max(ngroups, 1)
                      if cfg.moving_rate else 0.0)
        self.mode = cfg.param_type           # "Elastic" | "RandomSync"
        self.center = None
        self.snapshot = None
        self.sample_ratio = 1.0

    def init(self, params) -> None:
        self.center = jax.tree_util.tree_map(jnp.copy, params)
        if self.mode == "RandomSync":
            self.snapshot = jax.tree_util.tree_map(jnp.copy, params)

    def sync_now(self, step: int) -> bool:
        """warmup_steps then every sync_frequency (worker.cc:44-55)."""
        return (step >= self.cfg.warmup_steps
                and self.cfg.sync_frequency > 0
                and (step - self.cfg.warmup_steps)
                % self.cfg.sync_frequency == 0)

    def maybe_sync(self, step: int, params, rng=None):
        if self.center is None or not self.sync_now(step):
            return params
        if self.mode == "RandomSync":
            rng = rng if rng is not None else jax.random.PRNGKey(step)
            params, self.center, self.snapshot = randomsync_update(
                params, self.center, self.snapshot, self.sample_ratio, rng)
        else:
            params, self.center = elastic_update(params, self.center,
                                                 self.alpha)
        return params
