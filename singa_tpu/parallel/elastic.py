"""Asynchronous multi-replica consistency tier: Elastic averaging +
RandomSync — the reference parameter server's two sync *algorithms*,
preserved as first-class capability.

Reference semantics:
- **Elastic** (EASGD, param.cc:216-256): each replica periodically
  exchanges with a center copy: diff = (replica - center) * alpha;
  center += diff; replica -= diff; alpha = moving_rate / ngroups
  (param_manager.cc:15).  Cadence: UpdaterProto.sync_frequency after
  warmup_steps (model.proto:336-338, worker.cc:44-55).
- **RandomSync** (param.cc:102-213): the replica sends a seeded random
  *sample* of (data - snapshot) deltas; the center adds the deltas and
  returns its old values; the replica overwrites sampled entries with
  the center values and updates its snapshot.  The sample size follows
  the bandwidth model (param_manager.cc:85-93).

On TPU the synchronous psum path inside the compiled step replaces the
PS for intra-slice gradients; this module is the *cross-slice* tier
(slices connected over DCN, where async/compressed sync still pays).
The math is pure pytree ops, so it runs under jit on whatever process
holds the center copy; transport across hosts is jax.distributed /
multi-slice runtime.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from ..config.schema import UpdaterConfig


def elastic_update(replica, center, alpha: float):
    """One EASGD exchange (param.cc:232-256). Returns (replica, center)."""
    def one(r, c):
        diff = (r - c) * alpha
        return r - diff, c + diff
    pairs = jax.tree_util.tree_map(one, replica, center)
    new_r = jax.tree_util.tree_map(lambda p: p[0], pairs,
                                   is_leaf=lambda x: isinstance(x, tuple))
    new_c = jax.tree_util.tree_map(lambda p: p[1], pairs,
                                   is_leaf=lambda x: isinstance(x, tuple))
    return new_r, new_c


def randomsync_update(replica, center, snapshot, sample_ratio: float,
                      rng: jax.Array):
    """One RandomSync exchange (param.cc:102-213).

    A seeded uniform mask selects ~sample_ratio of entries; the center
    absorbs the replica's masked delta vs snapshot, the replica adopts
    the center's resulting values at the mask, and the snapshot records
    them.  Returns (replica, center, snapshot).
    """
    leaves, treedef = jax.tree_util.tree_flatten(replica)
    keys = jax.random.split(rng, len(leaves))
    c_leaves = jax.tree_util.tree_leaves(center)
    s_leaves = jax.tree_util.tree_leaves(snapshot)
    new_r, new_c, new_s = [], [], []
    for r, c, s, k in zip(leaves, c_leaves, s_leaves, keys):
        mask = (jax.random.uniform(k, r.shape) < sample_ratio
                ).astype(r.dtype)
        delta = (r - s) * mask
        c2 = c + delta
        r2 = r * (1 - mask) + c2 * mask
        s2 = s * (1 - mask) + c2 * mask
        new_r.append(r2)
        new_c.append(c2)
        new_s.append(s2)
    un = jax.tree_util.tree_unflatten
    return un(treedef, new_r), un(treedef, new_c), un(treedef, new_s)


def sync_sample_ratio(bandwidth_mb_s: float, nservers: int, nworkers: int,
                      model_size_floats: int, compute_time_s: float) -> float:
    """Bandwidth-adaptive sample ratio (param_manager.cc:85-93):
    the fraction of the model that fits through the pipe per step."""
    if model_size_floats <= 0 or compute_time_s <= 0:
        return 1.0
    throughput = bandwidth_mb_s * 1e6 / 4.0 * nservers   # floats/sec
    demand = model_size_floats * nworkers / compute_time_s
    return float(max(0.0, min(1.0, throughput / demand)))


def async_active(ucfg: UpdaterConfig | None) -> bool:
    """True when UpdaterProto's consistency knobs request the async
    tier: RandomSync explicitly, or Elastic with a nonzero moving_rate
    (the reference's mlp.conf sets moving_rate 0.9, sync_frequency 8;
    moving_rate's default 0 keeps plain-sync configs inert)."""
    return (ucfg is not None and ucfg.sync_frequency > 0
            and (ucfg.param_type == "RandomSync"
                 or (ucfg.param_type == "Elastic"
                     and ucfg.moving_rate > 0)))


class ElasticController:
    """Cross-slice consistency driver with the reference's cadence knobs.

    One instance lives on the coordinating process; `maybe_sync` is
    called each step with that slice's params.
    """

    def __init__(self, cfg: UpdaterConfig, ngroups: int = 1):
        self.cfg = cfg
        self.alpha = (cfg.moving_rate / max(ngroups, 1)
                      if cfg.moving_rate else 0.0)
        self.mode = cfg.param_type           # "Elastic" | "RandomSync"
        self.center = None
        self.snapshot = None
        self.sample_ratio = 1.0

    def init(self, params) -> None:
        self.center = jax.tree_util.tree_map(jnp.copy, params)
        if self.mode == "RandomSync":
            self.snapshot = jax.tree_util.tree_map(jnp.copy, params)

    def sync_now(self, step: int) -> bool:
        """warmup_steps then every sync_frequency (worker.cc:44-55)."""
        return (step >= self.cfg.warmup_steps
                and self.cfg.sync_frequency > 0
                and (step - self.cfg.warmup_steps)
                % self.cfg.sync_frequency == 0)

    def maybe_sync(self, step: int, params, rng=None):
        """Exchange with the center at the cadence.  The center
        initializes lazily from the FIRST post-warmup params — the
        reference worker pushes its trained params to the servers after
        the warmup loop, before any sync (worker.cc:50-55); seeding the
        center from step-0 initialization would make the first exchange
        snap the replica most of the way back toward init."""
        if not self.sync_now(step):
            return params
        if self.center is None:
            self.init(params)
            return params
        if self.mode == "RandomSync":
            if self.snapshot is None:
                # replica joining an existing center (multi-group):
                # its first delta baseline is its own current params
                self.snapshot = jax.tree_util.tree_map(jnp.copy, params)
            rng = rng if rng is not None else jax.random.PRNGKey(step)
            params, self.center, self.snapshot = randomsync_update(
                params, self.center, self.snapshot, self.sample_ratio, rng)
        else:
            params, self.center = elastic_update(params, self.center,
                                                 self.alpha)
        return params


class ReplicaSet:
    """The reference's worker-group topology as a runtime: `ngroups`
    replicas train asynchronously against one shared center copy (the
    parameter server's role, param.cc:102-256).

    Replicas step round-robin on one controller process — the
    single-host simulation of groups that the reference runs as
    separate processes; each holds its own params/opt_state and data
    stream and exchanges with the shared center at the UpdaterProto
    cadence (sync_frequency after warmup_steps, worker.cc:44-55).
    The center is ONE shared copy (the PS role); RandomSync snapshots
    are PER-replica state (param.cc:102-213 keeps them per worker —
    sharing them would erase other replicas' contributions from the
    center).  The center seeds lazily from the first replica to finish
    warmup (worker.cc:50-55).  Cross-host deployment runs one
    ReplicaSet member per slice with transport via jax.distributed.
    """

    def __init__(self, trainer, ngroups: int, seed: int = 0):
        self.trainer = trainer
        self.ngroups = ngroups
        cfg = trainer.cfg.updater
        self.controllers = [ElasticController(cfg, ngroups)
                            for _ in range(ngroups)]
        self.replicas = []
        for g in range(ngroups):
            # every replica starts from the SAME initialization — the
            # reference's group 0 initializes params and the other
            # groups fetch them from the servers (worker.cc Setup), so
            # replicas share a loss basin and their center average is
            # meaningful.  Divergence comes from the data streams.
            p, o = trainer.init(seed=seed)
            self.replicas.append({"params": p, "opt": o})

    def _share_center(self, src: ElasticController) -> None:
        for c in self.controllers:
            c.center = src.center   # snapshots stay per-replica

    def run(self, data_iters, steps: int, seed: int = 0,
            hooks=None):
        """Train every replica for `steps` steps, round-robin (one step
        per replica per round — simulated asynchrony: replicas hit the
        center at interleaved times).  Returns the final center params
        and per-replica metric history."""
        if len(data_iters) != self.ngroups:
            raise ValueError(f"need {self.ngroups} data iterators, got "
                             f"{len(data_iters)}")
        rng = jax.random.PRNGKey(seed ^ 0xA57)
        history = [[] for _ in range(self.ngroups)]
        for step in range(steps):
            for g, rep in enumerate(self.replicas):
                batch = next(data_iters[g])
                step_rng = jax.random.fold_in(
                    jax.random.fold_in(rng, step), g)
                rep["params"], rep["opt"], metrics = \
                    self.trainer.train_step(rep["params"], rep["opt"],
                                            batch, step, step_rng)
                ctl = self.controllers[g]
                rep["params"] = ctl.maybe_sync(step, rep["params"],
                                               rng=step_rng)
                if ctl.center is not None:
                    self._share_center(ctl)
                history[g].append(
                    {k: float(v) for k, v in metrics.items()})
                if hooks:
                    for h in hooks:
                        h(step, g, history[g][-1])
        return self.controllers[0].center, history

    @property
    def center(self):
        return self.controllers[0].center
