"""Asynchronous multi-replica consistency tier: Elastic averaging +
RandomSync — the reference parameter server's two sync *algorithms*,
preserved as first-class capability.

Reference semantics:
- **Elastic** (EASGD, param.cc:216-256): each replica periodically
  exchanges with a center copy: diff = (replica - center) * alpha;
  center += diff; replica -= diff; alpha = moving_rate / ngroups
  (param_manager.cc:15).  Cadence: UpdaterProto.sync_frequency after
  warmup_steps (model.proto:336-338, worker.cc:44-55).
- **RandomSync** (param.cc:102-213): the replica sends a seeded random
  *sample* of (data - snapshot) deltas; the center adds the deltas and
  returns its old values; the replica overwrites sampled entries with
  the center values and updates its snapshot.  The sample size follows
  the bandwidth model (param_manager.cc:85-93).

On TPU the synchronous psum path inside the compiled step replaces the
PS for intra-slice gradients; this module is the *cross-slice* tier
(slices connected over DCN, where async/compressed sync still pays).
The math is pure pytree ops, so it runs under jit on whatever process
holds the center copy; transport across hosts is jax.distributed /
multi-slice runtime.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config.schema import UpdaterConfig
from ..utils.faults import Backoff, Preemption, maybe_fault
from ..utils.health import SPIKE_SCALE, delta_health


class SyncRoundSkipped(RuntimeError):
    """Internal signal: a center exchange failed past its retry budget;
    the caller degrades to 'skip this sync round'."""


def _poisoned_contrib(params, kind):
    """Honor a silent `sync.delta` fault: the replica's contribution is
    numerically poisoned (NaN / scaled) BEFORE validation sees it —
    the deterministic stand-in for a diverged replica or a corrupted
    cross-slice transfer."""
    if kind not in ("nan", "spike"):
        return params
    scale = float("nan") if kind == "nan" else SPIKE_SCALE
    return jax.tree_util.tree_map(lambda x: x * scale, params)


def sync_with_retries(exchange, *, attempts: int = 3,
                      backoff: Backoff | None = None,
                      log=print, step: int | None = None):
    """Run a cross-slice `exchange()` with retries + exponential
    backoff.  Cross-slice links (DCN between slices — the tier this
    module exists for) flake in ways intra-slice ICI does not, and the
    async algorithms tolerate a missed round by construction (EASGD /
    RandomSync replicas drift between exchanges anyway), so a failed
    exchange degrades to SKIPPING the round instead of killing a
    multi-hour run.  Returns exchange()'s value, or raises
    SyncRoundSkipped after the budget; Preemption always propagates
    (the process is going away — retrying is pointless)."""
    backoff = backoff or Backoff(base=0.05, cap=2.0, seed=step or 0)
    last: BaseException | None = None
    for k in range(max(attempts, 1)):
        try:
            maybe_fault("sync.elastic")
            return exchange()
        except Preemption:
            raise
        except Exception as e:  # noqa: BLE001 — transport/runtime faults
            last = e
            log(f"warning: cross-slice sync failed"
                + (f" at step {step}" if step is not None else "")
                + f" (attempt {k + 1}/{attempts}): {e}")
            if k + 1 < attempts:
                backoff.sleep(k)
    raise SyncRoundSkipped(
        f"cross-slice sync abandoned after {attempts} attempts: {last}"
    ) from last


def elastic_update(replica, center, alpha: float):
    """One EASGD exchange (param.cc:232-256). Returns (replica, center)."""
    def one(r, c):
        diff = (r - c) * alpha
        return r - diff, c + diff
    pairs = jax.tree_util.tree_map(one, replica, center)
    new_r = jax.tree_util.tree_map(lambda p: p[0], pairs,
                                   is_leaf=lambda x: isinstance(x, tuple))
    new_c = jax.tree_util.tree_map(lambda p: p[1], pairs,
                                   is_leaf=lambda x: isinstance(x, tuple))
    return new_r, new_c


def randomsync_update(replica, center, snapshot, sample_ratio: float,
                      rng: jax.Array):
    """One RandomSync exchange (param.cc:102-213).

    A seeded uniform mask selects ~sample_ratio of entries; the center
    absorbs the replica's masked delta vs snapshot, the replica adopts
    the center's resulting values at the mask, and the snapshot records
    them.  Returns (replica, center, snapshot).
    """
    leaves, treedef = jax.tree_util.tree_flatten(replica)
    keys = jax.random.split(rng, len(leaves))
    c_leaves = jax.tree_util.tree_leaves(center)
    s_leaves = jax.tree_util.tree_leaves(snapshot)
    new_r, new_c, new_s = [], [], []
    for r, c, s, k in zip(leaves, c_leaves, s_leaves, keys):
        mask = (jax.random.uniform(k, r.shape) < sample_ratio
                ).astype(r.dtype)
        delta = (r - s) * mask
        c2 = c + delta
        r2 = r * (1 - mask) + c2 * mask
        s2 = s * (1 - mask) + c2 * mask
        new_r.append(r2)
        new_c.append(c2)
        new_s.append(s2)
    un = jax.tree_util.tree_unflatten
    return un(treedef, new_r), un(treedef, new_c), un(treedef, new_s)


def sync_sample_ratio(bandwidth_mb_s: float, nservers: int, nworkers: int,
                      model_size_floats: int, compute_time_s: float) -> float:
    """Bandwidth-adaptive sample ratio (param_manager.cc:85-93):
    the fraction of the model that fits through the pipe per step."""
    if model_size_floats <= 0 or compute_time_s <= 0:
        return 1.0
    # MB means 1024*1024 here, matching the reference formula's units
    throughput = bandwidth_mb_s * 1024 * 1024 / 4.0 * nservers  # floats/sec
    demand = model_size_floats * nworkers / compute_time_s
    return float(max(0.0, min(1.0, throughput / demand)))


def sync_now(cfg: UpdaterConfig, step: int) -> bool:
    """warmup_steps then every sync_frequency (worker.cc:44-55) — the
    ONE cadence predicate, shared by the controller, the round-robin
    simulation, and the distributed runtime."""
    return (step >= cfg.warmup_steps
            and cfg.sync_frequency > 0
            and (step - cfg.warmup_steps) % cfg.sync_frequency == 0)


def easgd_alpha(cfg: UpdaterConfig, ngroups: int) -> float:
    """alpha = moving_rate / ngroups (param_manager.cc:15)."""
    return cfg.moving_rate / max(ngroups, 1) if cfg.moving_rate else 0.0


def async_active(ucfg: UpdaterConfig | None) -> bool:
    """True when UpdaterProto's consistency knobs request the async
    tier: RandomSync explicitly, or Elastic with a nonzero moving_rate
    (the reference's mlp.conf sets moving_rate 0.9, sync_frequency 8;
    moving_rate's default 0 keeps plain-sync configs inert)."""
    return (ucfg is not None and ucfg.sync_frequency > 0
            and (ucfg.param_type == "RandomSync"
                 or (ucfg.param_type == "Elastic"
                     and ucfg.moving_rate > 0)))


class ElasticController:
    """Cross-slice consistency driver with the reference's cadence knobs.

    One instance lives on the coordinating process; `maybe_sync` is
    called each step with that slice's params.
    """

    def __init__(self, cfg: UpdaterConfig, ngroups: int = 1,
                 bandwidth_mb_s: float = 0.0, nservers: int = 1,
                 log_fn=print, sync_retries: int = 3,
                 sync_backoff: Backoff | None = None,
                 validate: bool = True, delta_max_norm: float = 0.0,
                 seed: int = 0, group: int = 0):
        """`validate` rejects a non-finite (or, with `delta_max_norm`,
        norm-exploded) replica contribution before it touches the
        center — the poisoned round degrades to a skipped one (counted
        in `poisoned_rounds`), exactly like a failed transport round.
        `seed`/`group` seed the rng fallback so an rng-less
        `maybe_sync` stays on the ReplicaSet trajectory contract."""
        self.cfg = cfg
        self.alpha = easgd_alpha(cfg, ngroups)
        self.mode = cfg.param_type           # "Elastic" | "RandomSync"
        self.center = None
        self.snapshot = None
        self.sample_ratio = 1.0
        self.bandwidth_mb_s = bandwidth_mb_s
        self.nservers = max(nservers, 1)
        self.log = log_fn
        self.sync_retries = max(sync_retries, 1)
        self.sync_backoff = sync_backoff
        self.skipped_rounds = 0
        self.validate = validate
        self.delta_max_norm = delta_max_norm
        self.poisoned_rounds = 0
        self.seed = seed
        self.group = group

    def configure_sync(self, compute_time_s: float,
                       model_size_floats: int, nworkers: int) -> None:
        """Runtime SyncConfig (param_manager.cc:85-93, called with the
        measured warmup step time, worker.cc:42-48): adapt the
        RandomSync sample ratio to the configured pipe.  A zero
        bandwidth (the TPU default — ICI/DCN collectives, not a
        modelled PS pipe) leaves sampling at 1.0."""
        if self.bandwidth_mb_s > 0:
            self.sample_ratio = sync_sample_ratio(
                self.bandwidth_mb_s, self.nservers, nworkers,
                model_size_floats, compute_time_s)

    def init(self, params) -> None:
        self.center = jax.tree_util.tree_map(jnp.copy, params)
        if self.mode == "RandomSync":
            self.snapshot = jax.tree_util.tree_map(jnp.copy, params)

    def sync_now(self, step: int) -> bool:
        return sync_now(self.cfg, step)

    def _fallback_rng(self, step: int):
        """The trajectory-exactness contract between ReplicaSet and
        DistributedReplicaSet derives every exchange rng as
        fold_in(fold_in(PRNGKey(seed ^ 0xA57), step), group) — the old
        `PRNGKey(step)` default silently diverged from it, so a caller
        omitting `rng` broke cross-runtime reproducibility."""
        base = jax.random.PRNGKey(self.seed ^ 0xA57)
        return jax.random.fold_in(jax.random.fold_in(base, step),
                                  self.group)

    def maybe_sync(self, step: int, params, rng=None):
        """Exchange with the center at the cadence.  The center
        initializes lazily from the FIRST post-warmup params — the
        reference worker pushes its trained params to the servers after
        the warmup loop, before any sync (worker.cc:50-55); seeding the
        center from step-0 initialization would make the first exchange
        snap the replica most of the way back toward init.

        With `validate` (default), a poisoned contribution — non-finite,
        or delta norm beyond `delta_max_norm` — never touches the
        center: the round is rejected, `poisoned_rounds` counts it, and
        the replica keeps training on its own params (the same
        degradation as SyncRoundSkipped)."""
        if not self.sync_now(step):
            return params
        if self.center is None:
            if self.validate:
                ok, _ = delta_health(params)
                if not ok:
                    # a non-finite replica must not SEED the center
                    self.poisoned_rounds += 1
                    self.log(f"warning: poisoned params at center init "
                             f"(step {step}): non-finite; round "
                             f"skipped, center not seeded")
                    return params
            self.init(params)
            return params
        contrib = _poisoned_contrib(params, maybe_fault("sync.delta"))
        if self.mode == "RandomSync":
            if self.snapshot is None:
                # replica joining an existing center (multi-group):
                # its first delta baseline is its own current params
                self.snapshot = jax.tree_util.tree_map(jnp.copy, params)
            rng = rng if rng is not None else self._fallback_rng(step)
            ref = self.snapshot

            def exchange():
                return randomsync_update(contrib, self.center,
                                         self.snapshot,
                                         self.sample_ratio, rng)
        else:
            ref = self.center

            def exchange():
                return elastic_update(contrib, self.center, self.alpha)
        if self.validate:
            ok, norm = delta_health(contrib, ref,
                                    max_norm=self.delta_max_norm)
            if not ok:
                self.poisoned_rounds += 1
                self.log(f"warning: poisoned sync delta at step {step} "
                         f"(delta norm {norm:.6g}"
                         + (f" > cap {self.delta_max_norm:.6g}"
                            if math.isfinite(norm) else ": non-finite")
                         + "); rejecting exchange — center untouched")
                return params
        try:
            out = sync_with_retries(exchange, attempts=self.sync_retries,
                                    backoff=self.sync_backoff,
                                    log=self.log, step=step)
        except SyncRoundSkipped as e:
            # the replica keeps training on its own params; the next
            # cadence step exchanges a (larger) delta as usual
            self.skipped_rounds += 1
            self.log(f"warning: skipping sync round at step {step} "
                     f"({e}); replica continues un-synced")
            return params
        if self.mode == "RandomSync":
            params, self.center, self.snapshot = out
        else:
            params, self.center = out
        return params


class ReplicaSet:
    """The reference's worker-group topology as a runtime: `ngroups`
    replicas train asynchronously against one shared center copy (the
    parameter server's role, param.cc:102-256).

    Replicas step round-robin on one controller process — the
    single-host simulation of groups that the reference runs as
    separate processes; each holds its own params/opt_state and data
    stream and exchanges with the shared center at the UpdaterProto
    cadence (sync_frequency after warmup_steps, worker.cc:44-55).
    The center is ONE shared copy (the PS role); RandomSync snapshots
    are PER-replica state (param.cc:102-213 keeps them per worker —
    sharing them would erase other replicas' contributions from the
    center).  The center seeds lazily from the first replica to finish
    warmup (worker.cc:50-55).  Cross-host deployment runs one
    ReplicaSet member per slice with transport via jax.distributed.
    """

    def __init__(self, trainer, ngroups: int, seed: int = 0,
                 bandwidth_mb_s: float = 0.0, nservers: int = 1,
                 quarantine_after: int = 3):
        """`quarantine_after`: consecutive poisoned sync rounds (the
        controller's delta validation rejecting a replica's
        contribution) after which the replica is QUARANTINED — pulled
        out of the round-robin instead of dragging the center with
        divergent deltas round after round."""
        self.trainer = trainer
        self.ngroups = ngroups
        self.quarantine_after = max(quarantine_after, 1)
        cfg = trainer.cfg.updater
        self.controllers = [ElasticController(
            cfg, ngroups, bandwidth_mb_s=bandwidth_mb_s,
            nservers=nservers, log_fn=trainer.log,
            seed=seed, group=g) for g in range(ngroups)]
        self.replicas = []
        for g in range(ngroups):
            # every replica starts from the SAME initialization — the
            # reference's group 0 initializes params and the other
            # groups fetch them from the servers (worker.cc Setup), so
            # replicas share a loss basin and their center average is
            # meaningful.  Divergence comes from the data streams.
            p, o = trainer.init(seed=seed)
            self.replicas.append({"params": p, "opt": o,
                                  "quarantined": False, "strikes": 0})

    def _share_center(self, src: ElasticController) -> None:
        # one LOGICAL center, but fresh containers per controller:
        # leaves are immutable jax arrays (safe to share), while an
        # accidental in-place dict mutation on one controller must not
        # silently corrupt every replica's view.  Snapshots stay
        # per-replica.
        for c in self.controllers:
            c.center = jax.tree_util.tree_map(lambda x: x, src.center)

    def run(self, data_iters, steps: int, seed: int = 0,
            hooks=None):
        """Train every replica for `steps` steps, round-robin (one step
        per replica per round — simulated asynchrony: replicas hit the
        center at interleaved times).  Returns the final center params
        and per-replica metric history."""
        if len(data_iters) != self.ngroups:
            raise ValueError(f"need {self.ngroups} data iterators, got "
                             f"{len(data_iters)}")
        import time as _time

        rng = jax.random.PRNGKey(seed ^ 0xA57)
        history = [[] for _ in range(self.ngroups)]
        warmup = self.trainer.cfg.updater.warmup_steps
        t_warm = None
        for step in range(steps):
            # Warmup timing for the bandwidth model (worker.cc:42-48
            # times the warmup loop, then SyncConfig).  Step 0 is the
            # jit compile — excluded (the reference's C++ has no
            # compile step to distort the measurement with).
            if step == 1 and warmup > 1:
                t_warm = _time.perf_counter()
            if step == warmup and t_warm is not None:
                per_step = ((_time.perf_counter() - t_warm)
                            / ((warmup - 1) * self.ngroups))
                size = sum(int(np.prod(v.shape)) for v in
                           self.replicas[0]["params"].values())
                for c in self.controllers:
                    c.configure_sync(per_step, size, self.ngroups)
            for g, rep in enumerate(self.replicas):
                if rep["quarantined"]:
                    continue
                batch = next(data_iters[g])
                step_rng = jax.random.fold_in(
                    jax.random.fold_in(rng, step), g)
                rep["params"], rep["opt"], metrics = \
                    self.trainer.train_step(rep["params"], rep["opt"],
                                            batch, step, step_rng)
                ctl = self.controllers[g]
                poisoned_before = ctl.poisoned_rounds
                rep["params"] = ctl.maybe_sync(step, rep["params"],
                                               rng=step_rng)
                if ctl.poisoned_rounds > poisoned_before:
                    # this replica's delta was rejected by validation;
                    # repeated offenders are pulled from the rotation
                    # instead of dragging the center every round
                    rep["strikes"] += 1
                    if rep["strikes"] >= self.quarantine_after:
                        rep["quarantined"] = True
                        self.trainer.log(
                            f"warning: quarantining replica {g} at "
                            f"step {step} after {rep['strikes']} "
                            f"consecutive poisoned sync rounds — it no "
                            f"longer trains or exchanges")
                        continue
                elif ctl.sync_now(step):
                    # a completed clean round clears the streak
                    rep["strikes"] = 0
                if ctl.center is not None:
                    self._share_center(ctl)
                history[g].append(
                    {k: float(v) for k, v in metrics.items()})
                if hooks:
                    for h in hooks:
                        h(step, g, history[g][-1])
        return self.controllers[0].center, history

    @property
    def center(self):
        return self.controllers[0].center


class DistributedReplicaSet:
    """The async consistency tier over REAL transport: one replica per
    process (jax.distributed), center exchange as a global-array
    program so the cross-process movement is XLA collectives — the
    role the reference's ZMQ worker<->server delta push/pull played
    (param_manager.cc:100-153, server.cc:45-214).

    Trajectory-exact with the single-process `ReplicaSet` simulation on
    the same seeds: the exchange program all-gathers the replicas
    along a `group` mesh axis and applies the SAME sequential
    center chain the round-robin controller applies (replica 0 first,
    then 1, ...), with the same lazy center init (first post-warmup
    sync seeds the center from replica 0, which skips its own exchange
    that step — worker.cc:50-55 semantics) and the same per-replica
    RandomSync snapshots and fold_in rng scheme.  Every process
    computes the identical replicated center, so there is no
    coordinator process to fail.
    """

    def __init__(self, trainer, seed: int = 0,
                 bandwidth_mb_s: float = 0.0, nservers: int = 1,
                 validate: bool = True, delta_max_norm: float = 0.0):
        self.trainer = trainer
        self.proc = jax.process_index()
        self.ngroups = jax.process_count()
        cfg = trainer.cfg.updater
        self.cfg = cfg
        self.alpha = easgd_alpha(cfg, self.ngroups)
        self.mode = cfg.param_type
        self._center_global = None            # replicated global array
        self.snapshot = None
        self.sample_ratio = 1.0
        self.bandwidth_mb_s = bandwidth_mb_s
        self.nservers = max(nservers, 1)
        self.params, self.opt = trainer.init(seed=seed)
        self._mesh = self._group_mesh()
        self._exchange = None
        self._check = None
        self.sync_retries = 3
        self.skipped_rounds = 0
        self.validate = validate
        self.delta_max_norm = delta_max_norm
        self.poisoned_rounds = 0

    def _group_mesh(self):
        from jax.sharding import Mesh

        import numpy as np
        rows = [[d for d in jax.devices() if d.process_index == p]
                for p in range(self.ngroups)]
        width = min(len(r) for r in rows)
        devs = np.array([r[:width] for r in rows])
        return Mesh(devs, ("group", "local"))

    def _sync_now(self, step: int) -> bool:
        return sync_now(self.cfg, step)

    # -- global-array plumbing --------------------------------------------
    def _stack(self, tree):
        """Local pytree -> global pytree with a leading `group` axis
        sharded one-row-per-process (replicated over local devices)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        def one(leaf):
            leaf = jnp.asarray(leaf)[None]
            shards = [jax.device_put(leaf, d)
                      for d in self._mesh.devices[self.proc]]
            return jax.make_array_from_single_device_arrays(
                (self.ngroups,) + leaf.shape[1:],
                NamedSharding(self._mesh, P("group")), shards)
        return jax.tree_util.tree_map(one, tree)

    def _local(self, tree):
        """This process's row of a group-stacked global pytree."""
        def one(leaf):
            for s in leaf.addressable_shards:
                return jnp.asarray(s.data)[0]
        return jax.tree_util.tree_map(one, tree)

    def _replicated(self, tree):
        def one(leaf):
            for s in leaf.addressable_shards:
                return jnp.asarray(s.data)
        return jax.tree_util.tree_map(one, tree)

    def _build_exchange(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh, G = self._mesh, self.ngroups
        grp = NamedSharding(mesh, P("group"))
        rep = NamedSharding(mesh, P())
        mode, alpha = self.mode, self.alpha

        def unstack(tree):
            return [jax.tree_util.tree_map(lambda x, g=g: x[g], tree)
                    for g in range(G)]

        def restack(trees):
            return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                          *trees)

        if mode == "RandomSync":
            def exchange(stacked_r, center, stacked_s, ratio, base_rng,
                         step, init_center):
                """Sequential center chain, replica 0 first — the
                exact round-robin order of ReplicaSet.run.
                `init_center` (host bool -> two compiled variants)
                marks the lazy-init step: center := replica 0's
                params, replica 0 skips its own exchange, later
                replicas exchange against the fresh center with
                zero-delta snapshots.  `ratio` is traced so
                sample_ratio updates (the bandwidth model) apply
                without recompiling."""
                rs, ss = unstack(stacked_r), unstack(stacked_s)
                c = (rs[0] if init_center else center)
                if init_center:
                    ss = [jax.tree_util.tree_map(jnp.copy, r)
                          for r in rs]
                for g in range(1 if init_center else 0, G):
                    rng_g = jax.random.fold_in(
                        jax.random.fold_in(base_rng, step), g)
                    rs[g], c, ss[g] = randomsync_update(
                        rs[g], c, ss[g], ratio, rng_g)
                return restack(rs), c, restack(ss)

            return jax.jit(
                exchange, static_argnums=(6,),
                in_shardings=(grp, rep, grp, rep, rep, rep),
                out_shardings=(grp, rep, grp))

        def exchange(stacked_r, center, init_center):
            """Elastic variant: no snapshots, no rng — the model-sized
            snapshot round-trip would be dead weight here."""
            rs = unstack(stacked_r)
            c = (rs[0] if init_center else center)
            for g in range(1 if init_center else 0, G):
                rs[g], c = elastic_update(rs[g], c, alpha)
            return restack(rs), c

        return jax.jit(exchange, static_argnums=(2,),
                       in_shardings=(grp, rep), out_shardings=(grp, rep))

    def _build_check(self):
        """Per-replica delta validation as a replicated-output program:
        every process computes the SAME (G,) ok/norm vectors from the
        group-stacked global array, so the skip-a-poisoned-round
        decision is symmetric across processes — no collective
        deadlock (the same constraint the retry path documents)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self._mesh
        grp = NamedSharding(mesh, P("group"))
        rep = NamedSharding(mesh, P())

        def check(stacked, ref, max_norm):
            s_l = jax.tree_util.tree_leaves(stacked)
            r_l = jax.tree_util.tree_leaves(ref)
            G = s_l[0].shape[0]
            sq = jnp.zeros((G,), jnp.float32)
            finite = jnp.ones((G,), bool)
            for s, r in zip(s_l, r_l):
                d = (s - r[None]).astype(jnp.float32)
                axes = tuple(range(1, d.ndim))
                sq = sq + jnp.sum(jnp.square(d), axis=axes)
                finite = jnp.logical_and(
                    finite, jnp.all(jnp.isfinite(d), axis=axes))
            norm = jnp.sqrt(sq)
            ok = jnp.logical_and(finite, jnp.isfinite(norm))
            ok = jnp.logical_and(
                ok, jnp.where(max_norm > 0, norm <= max_norm, True))
            return ok, norm

        return jax.jit(check, in_shardings=(grp, rep, rep),
                       out_shardings=(rep, rep))

    def _sync(self, step: int, base_rng) -> bool:
        """One center exchange.  Returns False when the round was
        REJECTED by delta validation (a poisoned contribution — the
        counted degradation, center untouched), True otherwise.

        Commit discipline: all outputs (params / snapshot / center) are
        computed and localized FIRST, then assigned in one straight-line
        block — a failure mid-exchange (flaky DCN collective, injected
        fault) can no longer leave `self.snapshot` updated while
        `self.params` / `self._center_global` are stale, which made a
        `sync_with_retries` re-entry exchange a fresh snapshot against
        stale params (torn-state bug, ISSUE 3)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        if self._exchange is None:
            self._exchange = self._build_exchange()
        rep = NamedSharding(self._mesh, P())
        init = self._center_global is None
        contrib = _poisoned_contrib(self.params,
                                    maybe_fault("sync.delta"))
        stacked_r = self._stack(contrib)
        # replicated operands must be identical on every process
        # (device_put to a cross-process sharding verifies this); the
        # init-step center placeholder is zeros — the exchange program
        # ignores it when init_center is set
        put_rep = lambda t: jax.tree_util.tree_map(  # noqa: E731
            lambda x: jax.device_put(jnp.asarray(x), rep), t)
        center = (self._center_global if not init
                  else put_rep(jax.tree_util.tree_map(
                      jnp.zeros_like, self.params)))
        if self.validate:
            if self._check is None:
                self._check = self._build_check()
            # vs zeros on the init round a "delta" is the raw params,
            # so only the finiteness leg applies there
            cap = 0.0 if init else self.delta_max_norm
            ok, norms = self._check(
                stacked_r, center,
                put_rep(jnp.asarray(cap, jnp.float32)))
            ok = np.asarray(self._replicated(ok))
            if not bool(ok.all()):
                bad = [int(g) for g in np.nonzero(~ok)[0]]
                norms = np.asarray(self._replicated(norms))
                self.poisoned_rounds += 1
                print(f"warning: poisoned sync delta at step {step} "
                      f"from replica(s) {bad} (delta norms "
                      f"{[float(norms[g]) for g in bad]}); rejecting "
                      f"exchange — center untouched", flush=True)
                return False
        if self.mode == "RandomSync":
            snap = (self.snapshot if self.snapshot is not None
                    else self.params)
            out_r, c, out_s = self._exchange(
                stacked_r, center, self._stack(snap),
                put_rep(jnp.asarray(self.sample_ratio, jnp.float32)),
                put_rep(base_rng),
                put_rep(jnp.asarray(step, jnp.uint32)), init)
            new_snapshot = self._local(out_s)
        else:
            out_r, c = self._exchange(stacked_r, center, init)
            new_snapshot = self.snapshot
        new_params = self._local(out_r)
        # -- atomic commit: nothing above may have mutated self state --
        self.params = new_params
        self.snapshot = new_snapshot
        self._center_global = c
        return True

    def run(self, data_iter, steps: int, seed: int = 0, hooks=None):
        """Train this process's replica for `steps` steps with center
        exchanges at the UpdaterProto cadence.  Returns (center,
        history) — history is THIS replica's metric list."""
        import time as _time

        rng = jax.random.PRNGKey(seed ^ 0xA57)
        g = self.proc
        history = []
        warmup = self.cfg.warmup_steps
        t_warm = None
        for step in range(steps):
            # Warmup timing -> SyncConfig (worker.cc:42-48), as in the
            # simulation; every process must agree on ONE ratio (the
            # exchange takes it as a replicated operand), so the
            # per-process measurements are averaged across processes.
            if step == 1 and warmup > 1:
                t_warm = _time.perf_counter()
            if (step == warmup and t_warm is not None
                    and self.bandwidth_mb_s > 0):
                per_step = (_time.perf_counter() - t_warm) / (warmup - 1)
                if self.ngroups > 1:
                    from jax.experimental import multihost_utils
                    per_step = float(np.mean(
                        multihost_utils.process_allgather(
                            np.asarray(per_step, np.float32))))
                size = sum(int(np.prod(v.shape))
                           for v in self.params.values())
                self.sample_ratio = sync_sample_ratio(
                    self.bandwidth_mb_s, self.nservers, self.ngroups,
                    size, per_step)
            batch = next(data_iter)
            step_rng = jax.random.fold_in(
                jax.random.fold_in(rng, step), g)
            self.params, self.opt, metrics = self.trainer.train_step(
                self.params, self.opt, batch, step, step_rng)
            if self._sync_now(step):
                # every process must make the same skip/retry decision
                # or the collective exchange deadlocks; a failed DCN
                # collective raises on ALL participants, and the seeded
                # backoff keys on `step`, so the decision is symmetric
                try:
                    sync_with_retries(lambda: self._sync(step, rng),
                                      attempts=self.sync_retries,
                                      step=step)
                except SyncRoundSkipped as e:
                    self.skipped_rounds += 1
                    print(f"warning: skipping sync round at step "
                          f"{step} ({e}); replica continues un-synced")
            history.append({k: float(v) for k, v in metrics.items()})
            if hooks:
                for h in hooks:
                    h(step, g, history[-1])
        return self.center, history

    @property
    def center(self):
        """This process's copy of the (replicated) center params."""
        return (None if self._center_global is None
                else self._replicated(self._center_global))
