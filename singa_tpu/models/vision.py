"""Vision model zoo as NetProto-style configs built programmatically.

The reference ships MNIST MLP + LeNet configs (examples/mnist/{mlp,conv}
.conf); its BASELINE configs additionally name AlexNet on CIFAR-10 /
ImageNet.  These builders emit the same declarative LayerConfig graphs
the text configs would, so everything downstream (net builder, sharding,
trainer) is identical whether a model comes from a .conf file or here.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..config.schema import ModelConfig, model_config_from_dict


def _param(name, **kw):
    return {"name": name, **kw}


_UNIFORM = dict(init_method="kUniform", low=-0.05, high=0.05)
_FANIN = dict(init_method="kUniformSqrtFanIn")


def _conv(name, src, nf, kernel, stride=1, pad=0, std=None, bias_value=0.0,
          lr2=2.0):
    winit = (dict(init_method="kGaussain", std=std) if std is not None
             else _FANIN)
    return {
        "name": name, "type": "kConvolution", "srclayers": src,
        "convolution_param": {"num_filters": nf, "kernel": kernel,
                              "stride": stride, "pad": pad},
        "param": [
            _param("weight", **winit),
            _param("bias", init_method="kConstant", value=bias_value,
                   learning_rate_multiplier=lr2),
        ],
    }


def _pool(name, src, kernel=2, stride=2, mode="MAX"):
    return {"name": name, "type": "kPooling", "srclayers": src,
            "pooling_param": {"pool": mode, "kernel": kernel,
                              "stride": stride}}


def _ip(name, src, n, std=None, bias_value=0.0, lr2=2.0):
    winit = (dict(init_method="kGaussain", std=std) if std is not None
             else _FANIN)
    return {
        "name": name, "type": "kInnerProduct", "srclayers": src,
        "inner_product_param": {"num_output": n},
        "param": [
            _param("weight", **winit),
            _param("bias", init_method="kConstant", value=bias_value,
                   learning_rate_multiplier=lr2),
        ],
    }


def _relu(name, src):
    return {"name": name, "type": "kReLU", "srclayers": src}


def _lrn(name, src, local_size=5, alpha=1e-4, beta=0.75):
    return {"name": name, "type": "kLRN", "srclayers": src,
            "lrn_param": {"local_size": local_size, "alpha": alpha,
                          "beta": beta}}


def _dropout(name, src, ratio=0.5):
    return {"name": name, "type": "kDropout", "srclayers": src,
            "dropout_param": {"dropout_ratio": ratio}}


def _data_head(batchsize, parser="kRGBImage", rgb_scale=1.0, cropsize=0,
               mirror=True, mnist_norm=(255.0, 0.0)):
    layers: List[Dict] = [
        {"name": "data", "type": "kShardData",
         "data_param": {"batchsize": batchsize}},
        {"name": "label", "type": "kLabel", "srclayers": "data"},
    ]
    if parser == "kRGBImage":
        layers.insert(1, {
            "name": "rgb", "type": "kRGBImage", "srclayers": "data",
            "rgbimage_param": {"scale": rgb_scale, "cropsize": cropsize,
                               "mirror": mirror}})
        head = "rgb"
    else:
        layers.insert(1, {
            "name": "mnist", "type": "kMnistImage", "srclayers": "data",
            "mnist_param": {"norm_a": mnist_norm[0], "norm_b": mnist_norm[1]}})
        head = "mnist"
    return layers, head


def _loss(src, topk=1):
    return {"name": "loss", "type": "kSoftmaxLoss",
            "srclayers": [src, "label"],
            "softmaxloss_param": {"topk": topk}}


def alexnet_cifar10(batchsize: int = 128, train_steps: int = 10000,
                    lr: float = 0.001) -> ModelConfig:
    """Reduced AlexNet for CIFAR-10 (the classic 3-conv caffe variant the
    reference era used for this dataset): conv32-pool-relu-lrn ×2 swap,
    conv64, ip."""
    layers, head = _data_head(batchsize, "kRGBImage", rgb_scale=1 / 255.0)
    layers += [
        _conv("conv1", head, 32, 5, 1, 2, std=1e-4),
        _pool("pool1", "conv1", 3, 2),
        _relu("relu1", "pool1"),
        _lrn("norm1", "relu1", 3, 5e-5),
        _conv("conv2", "norm1", 32, 5, 1, 2, std=1e-2),
        _relu("relu2", "conv2"),
        _pool("pool2", "relu2", 3, 2, "AVE"),
        _lrn("norm2", "pool2", 3, 5e-5),
        _conv("conv3", "norm2", 64, 5, 1, 2, std=1e-2),
        _relu("relu3", "conv3"),
        _pool("pool3", "relu3", 3, 2, "AVE"),
        _ip("ip1", "pool3", 10, std=1e-2),
        _loss("ip1"),
    ]
    return model_config_from_dict({
        "name": "alexnet-cifar10",
        "train_steps": train_steps,
        "display_frequency": 100,
        "updater": {"type": "kSGD", "base_learning_rate": lr,
                    "momentum": 0.9, "weight_decay": 0.004,
                    "learning_rate_change_method": "kFixed"},
        "neuralnet": {"layer": layers},
    })


def alexnet_cifar10_full(batchsize: int = 1024, train_steps: int = 50000,
                         lr: float = 0.01) -> ModelConfig:
    """The actual 5-conv AlexNet stack (conv1-5 + LRN×2 + fc6-8) adapted
    to 32×32 CIFAR-10 input (stride-1 conv1, as in the standard CIFAR
    adaptation).  This — not the 3-conv caffe 'cifar10_quick' above — is
    the 'AlexNet on CIFAR-10' of the BASELINE MFU gate; its 192-384
    channel convs and 4096-wide fcs are MXU-shaped, whereas the quick
    net's 32-channel convs cap out the 128-lane MXU at ~25%."""
    layers, head = _data_head(batchsize, "kRGBImage", rgb_scale=1 / 255.0)
    layers += [
        _conv("conv1", head, 64, 5, 1, 2, std=1e-2),
        _relu("relu1", "conv1"),
        _lrn("norm1", "relu1", 5, 1e-4),
        _pool("pool1", "norm1", 3, 2),
        _conv("conv2", "pool1", 192, 5, 1, 2, std=1e-2, bias_value=1.0),
        _relu("relu2", "conv2"),
        _lrn("norm2", "relu2", 5, 1e-4),
        _pool("pool2", "norm2", 3, 2),
        _conv("conv3", "pool2", 384, 3, 1, 1, std=1e-2),
        _relu("relu3", "conv3"),
        _conv("conv4", "relu3", 256, 3, 1, 1, std=1e-2, bias_value=1.0),
        _relu("relu4", "conv4"),
        _conv("conv5", "relu4", 256, 3, 1, 1, std=1e-2, bias_value=1.0),
        _relu("relu5", "conv5"),
        _pool("pool5", "relu5", 3, 2),
        _ip("fc6", "pool5", 4096, std=5e-3, bias_value=1.0),
        _relu("relu6", "fc6"),
        _dropout("drop6", "relu6"),
        _ip("fc7", "drop6", 4096, std=5e-3, bias_value=1.0),
        _relu("relu7", "fc7"),
        _dropout("drop7", "relu7"),
        _ip("fc8", "drop7", 10, std=1e-2),
        _loss("fc8"),
    ]
    return model_config_from_dict({
        "name": "alexnet-cifar10-full",
        "train_steps": train_steps,
        "display_frequency": 100,
        "updater": {"type": "kSGD", "base_learning_rate": lr,
                    "momentum": 0.9, "weight_decay": 0.0005,
                    "learning_rate_change_method": "kStep", "gamma": 0.1,
                    "learning_rate_change_frequency": 20000},
        "neuralnet": {"layer": layers},
    })


def alexnet_imagenet(batchsize: int = 256, train_steps: int = 450000,
                     nclass: int = 1000) -> ModelConfig:
    """Full AlexNet (ImageNet-1k, single-tower): the reference BASELINE's
    'AlexNet on ImageNet-1k (data-parallel multi-worker)' config."""
    layers, head = _data_head(batchsize, "kRGBImage", cropsize=227)
    layers += [
        _conv("conv1", head, 96, 11, 4, 0, std=1e-2),
        _relu("relu1", "conv1"),
        _lrn("norm1", "relu1", 5, 1e-4),
        _pool("pool1", "norm1", 3, 2),
        _conv("conv2", "pool1", 256, 5, 1, 2, std=1e-2, bias_value=1.0),
        _relu("relu2", "conv2"),
        _lrn("norm2", "relu2", 5, 1e-4),
        _pool("pool2", "norm2", 3, 2),
        _conv("conv3", "pool2", 384, 3, 1, 1, std=1e-2),
        _relu("relu3", "conv3"),
        _conv("conv4", "relu3", 384, 3, 1, 1, std=1e-2, bias_value=1.0),
        _relu("relu4", "conv4"),
        _conv("conv5", "relu4", 256, 3, 1, 1, std=1e-2, bias_value=1.0),
        _relu("relu5", "conv5"),
        _pool("pool5", "relu5", 3, 2),
        _ip("fc6", "pool5", 4096, std=5e-3, bias_value=1.0),
        _relu("relu6", "fc6"),
        _dropout("drop6", "relu6"),
        _ip("fc7", "drop6", 4096, std=5e-3, bias_value=1.0),
        _relu("relu7", "fc7"),
        _dropout("drop7", "relu7"),
        _ip("fc8", "drop7", nclass, std=1e-2),
        _loss("fc8", topk=1),
    ]
    return model_config_from_dict({
        "name": "alexnet-imagenet",
        "train_steps": train_steps,
        "display_frequency": 20,
        "updater": {"type": "kSGD", "base_learning_rate": 0.01,
                    "momentum": 0.9, "weight_decay": 0.0005,
                    "learning_rate_change_method": "kStep", "gamma": 0.1,
                    "learning_rate_change_frequency": 100000},
        "neuralnet": {"layer": layers},
    })


def lenet_mnist(batchsize: int = 64, train_steps: int = 10000) -> ModelConfig:
    """The conv.conf LeNet, programmatic (same hyperparams)."""
    layers, head = _data_head(batchsize, "kMnistImage")
    layers += [
        _conv("conv1", head, 20, 5),
        _pool("pool1", "conv1", 2, 2),
        _conv("conv2", "pool1", 50, 5),
        _pool("pool2", "conv2", 2, 2),
        _ip("ip1", "pool2", 500),
        _relu("relu1", "ip1"),
        _ip("ip2", "relu1", 10),
        _loss("ip2"),
    ]
    return model_config_from_dict({
        "name": "lenet-mnist",
        "train_steps": train_steps,
        # test cadence mirrors the reference conv.conf:3-4
        "test_steps": 100, "test_frequency": 500,
        "display_frequency": 100,
        "updater": {"type": "kSGD", "base_learning_rate": 0.01,
                    "momentum": 0.9, "weight_decay": 0.0005,
                    "learning_rate_change_method": "kInverse",
                    "gamma": 0.0001, "pow": 0.75},
        "neuralnet": {"layer": layers},
    })


def mlp_mnist(batchsize: int = 1000, train_steps: int = 60000,
              widths=(2500, 2000, 1500, 1000, 500)) -> ModelConfig:
    """The mlp.conf deep MLP, programmatic."""
    layers, head = _data_head(batchsize, "kMnistImage",
                              mnist_norm=(127.5, 1.0))
    src = head
    for i, w in enumerate(widths, 1):
        layers.append({
            "name": f"fc{i}", "type": "kInnerProduct", "srclayers": src,
            "inner_product_param": {"num_output": w},
            "param": [_param("weight", **_UNIFORM),
                      _param("bias", **_UNIFORM)]})
        layers.append({"name": f"tanh{i}", "type": "kTanh",
                       "srclayers": f"fc{i}"})
        src = f"tanh{i}"
    layers.append({
        "name": f"fc{len(widths) + 1}", "type": "kInnerProduct",
        "srclayers": src, "inner_product_param": {"num_output": 10},
        "param": [_param("weight", **_UNIFORM), _param("bias", **_UNIFORM)]})
    layers.append(_loss(f"fc{len(widths) + 1}"))
    return model_config_from_dict({
        "name": "deep-big-simple-mlp",
        "train_steps": train_steps,
        # test cadence mirrors the reference mlp.conf:3-4
        "test_steps": 10, "test_frequency": 30,
        "display_frequency": 30,
        # the reference's mlp.conf runs the Elastic-averaging consistency
        # tier (mlp.conf:12-16): sync with the center every 8 steps
        # after 60 warmup steps — live through Trainer.run/ReplicaSet
        "updater": {"type": "kSGD", "base_learning_rate": 0.001,
                    "learning_rate_change_method": "kStep", "gamma": 0.997,
                    "learning_rate_change_frequency": 60,
                    "param_type": "Elastic", "sync_frequency": 8,
                    "moving_rate": 0.9, "warmup_steps": 60},
        "neuralnet": {"layer": layers},
    })
