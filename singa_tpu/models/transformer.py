"""Decoder-only transformer LM — the modern flagship model family.

Declared in the same NetProto-style config IR as the reference's conv
nets (SURVEY.md §5: expose SP/CP "the same way the reference exposes
partitioning — as declarative config").  Pre-norm blocks:

    x += attn(rmsnorm(x));  x += ffn_or_moe(rmsnorm(x))

`seq_parallel` threads attention through ring/Ulysses over the mesh's
"seq" axis; `moe_every > 0` replaces every Nth FFN with a top-k MoE
whose experts shard over "expert"; projection weights carry
partition_dim for tensor parallelism over "model".
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..config.schema import ModelConfig, model_config_from_dict
from ..core import seq_layers  # noqa: F401  (registers the layer types)


def transformer_lm(vocab_size: int = 32000,
                   num_layers: int = 4,
                   embed_dim: int = 512,
                   num_heads: int = 8,
                   head_dim: int = 64,
                   num_kv_heads: int = 0,
                   ffn_hidden: int = 0,
                   seq_len: int = 1024,
                   batchsize: int = 8,
                   seq_parallel: str = "none",
                   moe_every: int = 0,
                   num_experts: int = 8,
                   experts_per_token: int = 2,
                   train_steps: int = 1000,
                   learning_rate: float = 3e-4,
                   precision: str = "float32",
                   tie_embeddings: bool = True,
                   fused_head: bool = True,
                   pipeline_stages: int = 0,
                   dropout: float = 0.0) -> ModelConfig:
    """`fused_head` emits the kLMHeadLoss layer (chunked projection+xent,
    no (B,S,V) logits tensor) instead of kLMHead → kSoftmaxLoss; the two
    forms are numerically identical.

    `pipeline_stages = S > 0` marks each block's layers with
    LayerProto.locationid 1..S (num_layers must divide evenly) — the
    reference's per-layer location field (model.proto:128) — which the
    Trainer maps onto the mesh's "pipe" axis via
    parallel.pipeline_net.PipelineNet.  Embedding and head keep
    locationid 0 (pre/post groups)."""
    ffn_hidden = ffn_hidden or int(embed_dim * 8 / 3 // 64 * 64) or 256
    layers: List[Dict] = [
        {"name": "data", "type": "kSequenceData",
         "seqdata_param": {"batchsize": batchsize, "seq_len": seq_len,
                           "vocab_size": vocab_size}},
        {"name": "labels", "type": "kSeqLabel", "srclayers": "data"},
        {"name": "embed", "type": "kEmbed", "srclayers": "data",
         "embed_param": {"vocab_size": vocab_size, "embed_dim": embed_dim}},
    ]
    if pipeline_stages:
        if num_layers % pipeline_stages:
            raise ValueError(f"num_layers {num_layers} not divisible by "
                             f"pipeline_stages {pipeline_stages}")
        per_stage = num_layers // pipeline_stages

    src = "embed"
    for i in range(num_layers):
        stage_mark = ({"locationid": i // per_stage + 1}
                      if pipeline_stages else {})
        attn_in = f"ln{i}a"
        layers.append({"name": attn_in, "type": "kRMSNorm",
                       "srclayers": src, **stage_mark})
        layers.append({
            "name": f"attn{i}", "type": "kAttention", "srclayers": attn_in,
            "attention_param": {
                "num_heads": num_heads, "head_dim": head_dim,
                "causal": True, "seq_parallel": seq_parallel,
                "num_kv_heads": num_kv_heads}, **stage_mark})
        layers.append({"name": f"res{i}a", "type": "kResidualAdd",
                       "srclayers": [src, f"attn{i}"], **stage_mark})
        ffn_in = f"ln{i}b"
        layers.append({"name": ffn_in, "type": "kRMSNorm",
                       "srclayers": f"res{i}a", **stage_mark})
        use_moe = moe_every > 0 and (i + 1) % moe_every == 0
        if use_moe:
            layers.append({
                "name": f"moe{i}", "type": "kMoE", "srclayers": ffn_in,
                "moe_param": {"num_experts": num_experts,
                              "experts_per_token": experts_per_token,
                              "expert_hidden": ffn_hidden}, **stage_mark})
            block_out = f"moe{i}"
        else:
            layers.append({
                "name": f"ffn{i}", "type": "kFeedForward",
                "srclayers": ffn_in,
                "ffn_param": {"hidden_dim": ffn_hidden}, **stage_mark})
            block_out = f"ffn{i}"
        layers.append({"name": f"res{i}b", "type": "kResidualAdd",
                       "srclayers": [f"res{i}a", block_out], **stage_mark})
        src = f"res{i}b"
        if dropout > 0:
            # block-output dropout (kDropout inside the stage mark — a
            # pipeline stage with rng-bearing layers is first-class)
            layers.append({"name": f"drop{i}", "type": "kDropout",
                           "srclayers": src,
                           "dropout_param": {"dropout_ratio": dropout},
                           **stage_mark})
            src = f"drop{i}"

    layers.append({"name": "ln_f", "type": "kRMSNorm", "srclayers": src})
    if fused_head:
        head = {"name": "loss", "type": "kLMHeadLoss",
                "srclayers": ["ln_f", "labels"],
                "embed_param": {"vocab_size": vocab_size,
                                "embed_dim": embed_dim},
                "softmaxloss_param": {"topk": 1}}
        if tie_embeddings:
            head["share_param"] = ["embed/embedding"]
            head["param"] = [{"name": "w"}]
        layers.append(head)
    else:
        head = {"name": "lm_head", "type": "kLMHead", "srclayers": "ln_f",
                "embed_param": {"vocab_size": vocab_size,
                                "embed_dim": embed_dim}}
        if tie_embeddings:
            head["share_param"] = ["embed/embedding"]
            head["param"] = [{"name": "w"}]
        layers.append(head)
        layers.append({"name": "loss", "type": "kSoftmaxLoss",
                       "srclayers": ["lm_head", "labels"],
                       "softmaxloss_param": {"topk": 1}})

    return model_config_from_dict({
        "name": f"transformer-lm-{num_layers}L{embed_dim}E",
        "train_steps": train_steps,
        "display_frequency": 50,
        "precision": precision,
        "updater": {"type": "kAdam", "base_learning_rate": learning_rate,
                    "weight_decay": 0.0,
                    "learning_rate_change_method": "kFixed"},
        "neuralnet": {"layer": layers},
    })


def synthetic_token_batches(batchsize: int, seq_len: int, vocab_size: int,
                            seed: int = 0, data_layer: str = "data",
                            table_seed: int = 1234):
    """Learnable synthetic LM data: Markov chains with a fixed random
    transition table — a model that learns beats the unigram entropy
    floor.  The table comes from `table_seed`, NOT `seed`, so train and
    test streams (different seeds) sample the same "language"."""
    import numpy as np
    rng = np.random.default_rng(seed)
    # sparse-ish transition: each (prev) maps to 4 likely next tokens
    nexts = np.random.default_rng(table_seed).integers(
        0, vocab_size, (vocab_size, 4))
    while True:
        toks = np.empty((batchsize, seq_len + 1), np.int32)
        toks[:, 0] = rng.integers(0, vocab_size, batchsize)
        choices = rng.integers(0, 4, (batchsize, seq_len))
        noise = rng.random((batchsize, seq_len)) < 0.1
        rand_tok = rng.integers(0, vocab_size, (batchsize, seq_len))
        for t in range(seq_len):
            nxt = nexts[toks[:, t], choices[:, t]]
            toks[:, t + 1] = np.where(noise[:, t], rand_tok[:, t], nxt)
        yield {data_layer: {"input": toks[:, :-1], "target": toks[:, 1:]}}
