"""RBM / autoencoder pretraining — the kContrastiveDivergence path.

The reference declares GradCalcAlg::kContrastiveDivergence
(model.proto:40-44) and its BASELINE configs name "RBM / autoencoder
pretraining (layer-wise greedy)", but the 2015 code never implemented a
CD worker.  Here it is, TPU-native: the CD-k Gibbs chain is a
`lax.scan` inside one jitted step (binary units, sigmoid activations),
so pretraining runs entirely on device.

Greedy stacking follows the classic recipe (Hinton & Salakhutdinov
2006): train RBM_i on the hidden probabilities of RBM_{i-1}, then unroll
into a deep autoencoder (decoder = tied transposed weights) whose
fine-tuning uses the ordinary net/trainer path.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


def init_rbm(rng: jax.Array, nvis: int, nhid: int,
             std: float = 0.01) -> Dict[str, jnp.ndarray]:
    wkey, = jax.random.split(rng, 1)
    return {
        "W": std * jax.random.normal(wkey, (nvis, nhid), jnp.float32),
        "bv": jnp.zeros((nvis,), jnp.float32),
        "bh": jnp.zeros((nhid,), jnp.float32),
    }


def _h_prob(params, v):
    return jax.nn.sigmoid(v @ params["W"] + params["bh"])


def _v_prob(params, h):
    return jax.nn.sigmoid(h @ params["W"].T + params["bv"])


def free_energy(params, v):
    """F(v) = -v·bv - Σ softplus(vW + bh)."""
    return (-v @ params["bv"]
            - jnp.sum(jax.nn.softplus(v @ params["W"] + params["bh"]),
                      axis=-1))


def cd_grads(params, v0, rng, k: int = 1,
             persistent: Optional[jnp.ndarray] = None,
             ) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray, jnp.ndarray]:
    """CD-k gradients.  Returns (grads, reconstruction_error, chain_end).

    grads follow the *descent* convention (apply with params -= lr*grad)
    so they plug into the Updater family directly.  `persistent` (a
    traced array, PCD) supplies the Gibbs chain start; None starts from
    the data batch.
    """
    start = persistent if persistent is not None else v0
    return _cd_grads(params, v0, rng, start, k)


@functools.partial(jax.jit, static_argnames=("k",))
def _cd_grads(params, v0, rng, start, k: int):
    b = v0.shape[0]
    h0_prob = _h_prob(params, v0)

    def gibbs(carry, key):
        v, _ = carry
        kh, kv = jax.random.split(key)
        h_prob = _h_prob(params, v)
        h = jax.random.bernoulli(kh, h_prob).astype(jnp.float32)
        v_prob = _v_prob(params, h)
        v_new = jax.random.bernoulli(kv, v_prob).astype(jnp.float32)
        return (v_new, v_prob), None

    keys = jax.random.split(rng, k)
    (vk, vk_prob), _ = jax.lax.scan(gibbs, (start, start), keys)
    hk_prob = _h_prob(params, vk_prob)

    # <v0 h0> - <vk hk>, sign-flipped to descent convention
    gW = -(v0.T @ h0_prob - vk_prob.T @ hk_prob) / b
    gbv = -jnp.mean(v0 - vk_prob, axis=0)
    gbh = -jnp.mean(h0_prob - hk_prob, axis=0)
    recon = jnp.mean(jnp.square(v0 - _v_prob(params, h0_prob)))
    return {"W": gW, "bv": gbv, "bh": gbh}, recon, vk


def pretrain_rbm(rng: jax.Array, data_iter, nvis: int, nhid: int,
                 steps: int = 1000, lr: float = 0.1, k: int = 1,
                 momentum: float = 0.5,
                 log_every: int = 0, log_fn=print) -> Dict[str, jnp.ndarray]:
    """Train one RBM with CD-k + momentum SGD on binary-ish data in [0,1]."""
    params = init_rbm(rng, nvis, nhid)
    vel = jax.tree_util.tree_map(jnp.zeros_like, params)

    @jax.jit
    def sgd(params, vel, grads):
        vel = jax.tree_util.tree_map(
            lambda m, g: momentum * m + lr * g, vel, grads)
        params = jax.tree_util.tree_map(lambda p, m: p - m, params, vel)
        return params, vel

    for step in range(steps):
        v0 = next(data_iter)
        grads, recon, _ = cd_grads(params, v0,
                                   jax.random.fold_in(rng, step), k=k)
        params, vel = sgd(params, vel, grads)
        if log_every and step % log_every == 0:
            log_fn(f"rbm step-{step}: recon {float(recon):.5f}")
    return params


def greedy_pretrain(rng: jax.Array, data_factory, widths: Sequence[int],
                    nvis: int, steps_per_layer: int = 1000, lr: float = 0.1,
                    k: int = 1, log_fn=print) -> List[Dict[str, jnp.ndarray]]:
    """Stack RBMs greedily: each trained on the previous layer's hidden
    probabilities."""
    rbms: List[Dict[str, jnp.ndarray]] = []
    sizes = [nvis] + list(widths)

    def lifted_iter():
        it = data_factory()
        while True:
            v = next(it)
            for p in rbms:
                v = _h_prob(p, v)
            yield v

    for i, (nv, nh) in enumerate(zip(sizes[:-1], sizes[1:])):
        log_fn(f"pretraining RBM {i}: {nv} -> {nh}")
        rbms.append(pretrain_rbm(jax.random.fold_in(rng, i), lifted_iter(),
                                 nv, nh, steps_per_layer, lr, k))
    return rbms


def unroll_autoencoder(rbms: List[Dict[str, jnp.ndarray]]
                       ) -> Dict[str, jnp.ndarray]:
    """Unroll stacked RBMs into deep-autoencoder params: encoder layers
    enc_i/{weight,bias} and tied decoder layers dec_i/{weight,bias}
    (decoder weight = encoder transpose, per Hinton's unrolling)."""
    params = {}
    n = len(rbms)
    for i, p in enumerate(rbms):
        params[f"enc{i}/weight"] = p["W"]
        params[f"enc{i}/bias"] = p["bh"]
        params[f"dec{n - 1 - i}/weight"] = p["W"].T
        params[f"dec{n - 1 - i}/bias"] = p["bv"]
    return params


# ---------------------------------------------------------------------------
# config surface: the kRBM layer + kContrastiveDivergence trainer hook


def register_rbm_layer() -> None:
    """Idempotent registration of the kRBM layer type (called lazily by
    core.layers.create_layer, mirroring the seq_layers family)."""
    from ..core.layers import (LAYER_REGISTRY, Layer, LayerError,
                               register_layer)
    if "kRBM" in LAYER_REGISTRY:
        return

    from ..core.seq_layers import _declare_with_default

    @register_layer("kRBM")
    class RBMLayer(Layer):
        """Restricted Boltzmann machine layer (RBMProto: num_hidden,
        cd_k, persistent).  Forward = hidden-unit probabilities
        sigmoid(vW + bh) — the deterministic pass used for greedy
        stacking and downstream layers; training runs the CD-k chain
        through Trainer's kContrastiveDivergence path
        (ModelProto.alg, model.proto:40-44), not backprop."""

        is_rbm = True

        def setup(self, src_shapes):
            p = self.cfg.rbm_param
            if p is None or not p.num_hidden:
                raise LayerError(f"{self.name}: rbm_param.num_hidden "
                                 "required")
            s = tuple(src_shapes[0])
            self.nvis = 1
            for d in s[1:]:
                self.nvis *= d
            self.nhid = p.num_hidden
            self.cd_k = max(p.cd_k, 1)
            self.persistent = p.persistent
            self.out_shape = (s[0], self.nhid)
            self.w_key = _declare_with_default(
                self, 0, "weight", (self.nvis, self.nhid), 0.01)
            self.bv_key = _declare_with_default(
                self, 1, "vbias", (self.nvis,), 0.0)
            self.bh_key = _declare_with_default(
                self, 2, "hbias", (self.nhid,), 0.0)

        def cd_view(self, params):
            """{W, bv, bh} view for cd_grads."""
            return {"W": params[self.w_key], "bv": params[self.bv_key],
                    "bh": params[self.bh_key]}

        def named_grads(self, cd):
            return {self.w_key: cd["W"], self.bv_key: cd["bv"],
                    self.bh_key: cd["bh"]}

        def apply(self, params, srcs, ctx):
            v = srcs[0].reshape(srcs[0].shape[0], -1)
            return _h_prob(self.cd_view(params), v)


def rbm_mnist(widths: Sequence[int] = (250, 100), batchsize: int = 64,
              train_steps: int = 2000, lr: float = 0.1, cd_k: int = 1):
    """Config for greedy RBM pretraining on MNIST-shaped data — the
    BASELINE's 'RBM / autoencoder pretraining (layer-wise greedy)'
    entry as a declarative net (alg: kContrastiveDivergence)."""
    from ..config.schema import model_config_from_dict
    layers = [
        {"name": "data", "type": "kShardData",
         "data_param": {"batchsize": batchsize}},
        {"name": "mnist", "type": "kMnistImage", "srclayers": "data",
         "mnist_param": {"norm_a": 255.0}},
    ]
    src = "mnist"
    for i, w in enumerate(widths):
        layers.append({"name": f"rbm{i}", "type": "kRBM",
                       "srclayers": src,
                       "rbm_param": {"num_hidden": w, "cd_k": cd_k}})
        src = f"rbm{i}"
    return model_config_from_dict({
        "name": "rbm-mnist", "train_steps": train_steps,
        "display_frequency": 100,
        "alg": "kContrastiveDivergence",
        "updater": {"type": "kSGD", "base_learning_rate": lr,
                    "momentum": 0.5,
                    "learning_rate_change_method": "kFixed"},
        "neuralnet": {"layer": layers}})


def autoencoder_apply(params: Dict[str, jnp.ndarray], v: jnp.ndarray,
                      nlayers: int) -> jnp.ndarray:
    """Forward through the unrolled autoencoder (sigmoid units).  The
    returned reconstruction is differentiable — fine-tune with jax.grad
    on e.g. mean-square or cross-entropy reconstruction loss."""
    h = v
    for i in range(nlayers):
        h = jax.nn.sigmoid(h @ params[f"enc{i}/weight"]
                           + params[f"enc{i}/bias"])
    for i in range(nlayers):
        h = jax.nn.sigmoid(h @ params[f"dec{i}/weight"]
                           + params[f"dec{i}/bias"])
    return h
