"""Autoregressive inference for the transformer LM family: KV-cache
prefill + single-token decode, compiled as two XLA programs.

The reference framework is train/test only (worker.cc Test loop runs
Forward over labelled batches; there is no sampling path) — generation
is a capability the sequence-model family adds.  TPU-first design:

- static shapes everywhere: the cache is allocated at `max_len` up
  front and written with `lax.dynamic_update_slice`; the decode loop is
  one `lax.scan` over the new-token axis, so the whole generation is a
  single compiled program (one dispatch), not a per-token Python loop.
- attention over the cache is a masked dense read of the full cache —
  at decode the query is one token, so the (1, max_len) score row is
  tiny; masking `kpos > pos` makes the static shape exact.
- the same `NeuralNet` (core/net.py) drives decode: position-wise
  layers (embed, rmsnorm, ffn, moe, residual) run their normal
  `apply`; only kAttention (cache read/write + absolute-position RoPE)
  and the heads (emit logits instead of loss) are special-cased.

Works with both head forms emitted by models.transformer.transformer_lm
(kLMHead -> kSoftmaxLoss, and the fused kLMHeadLoss whose loss layer is
re-used here only for its projection weight).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.layers import Context
from ..core.net import NeuralNet

CacheEntry = Dict[str, jnp.ndarray]   # {"k","v"}: (B, Hkv, max_len, D)
Cache = Dict[str, CacheEntry]         # attention-layer name -> entry


def init_cache(net: NeuralNet, batchsize: int, max_len: int,
               dtype=jnp.float32) -> Cache:
    """Zeroed KV cache for every kAttention layer in the net."""
    cache: Cache = {}
    for name in net.topo:
        layer = net.layers[name]
        if layer.cfg.type != "kAttention":
            continue
        shape = (batchsize, layer.kv_heads, max_len, layer.head_dim)
        cache[name] = {"k": jnp.zeros(shape, dtype),
                      "v": jnp.zeros(shape, dtype)}
    return cache


def _attn_cached(layer, params, x, entry: CacheEntry, pos,
                 kmask: Optional[jnp.ndarray] = None
                 ) -> Tuple[jnp.ndarray, CacheEntry]:
    """Attention for a (B, T, E) chunk whose first token sits at absolute
    position `pos` (traced scalar), against the running KV cache.

    `kmask` (B, max_len) bool, optional: per-sequence validity of key
    positions, ANDed with the causal mask.  The serving tier LEFT-pads
    variable-length prompts to a bucket length and masks the pad keys —
    with RoPE's relative rotations, left-padding keeps every attended
    (query, key) distance identical to the unpadded sequence, so a
    padded batched decode matches the unpadded one.

    GQA reads the cache at Hkv width: q is grouped to (B, Hkv, G, T, D)
    and contracted against the (B, Hkv, max_len, D) cache directly — no
    expand_kv_heads copy, so the per-step HBM cache read (the decode
    bottleneck once weights are amortized over batch) scales with Hkv,
    not H."""
    assert layer.causal, f"{layer.name}: decode requires causal attention"
    b, t, e = x.shape
    q, k, v = layer.qkv(params, x, pos + jnp.arange(t), _CTX)

    k_cache = jax.lax.dynamic_update_slice(
        entry["k"], k.astype(entry["k"].dtype), (0, 0, pos, 0))
    v_cache = jax.lax.dynamic_update_slice(
        entry["v"], v.astype(entry["v"].dtype), (0, 0, pos, 0))

    groups = layer.heads // layer.kv_heads
    kk = k_cache.astype(q.dtype)
    vv = v_cache.astype(q.dtype)
    qpos = pos + jnp.arange(t)[:, None]            # (T, 1) absolute
    kpos = jnp.arange(kk.shape[2])[None, :]        # (1, max_len)
    allowed = (kpos <= qpos)[None]                 # (1, T, max_len)
    if kmask is not None:
        allowed = allowed & kmask[:, None, :]      # (B, T, max_len)
    if groups == 1:
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, kk,
                            preferred_element_type=jnp.float32)
        scores = scores / jnp.sqrt(jnp.float32(layer.head_dim))
        scores = jnp.where(allowed[:, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs.astype(vv.dtype), vv)
    else:
        qg = q.reshape(b, layer.kv_heads, groups, t, layer.head_dim)
        scores = jnp.einsum("bhgqd,bhkd->bhgqk", qg, kk,
                            preferred_element_type=jnp.float32)
        scores = scores / jnp.sqrt(jnp.float32(layer.head_dim))
        scores = jnp.where(allowed[:, None, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bhgqk,bhkd->bhgqd", probs.astype(vv.dtype), vv)
        out = out.reshape(b, layer.heads, t, layer.head_dim)
    out = out.transpose(0, 2, 1, 3).reshape(b, t, -1)
    out = layer._proj(params, layer.wo, out.astype(x.dtype), _CTX)
    return out, {"k": k_cache, "v": v_cache}


_CTX = Context(batch={}, train=False, rng=None, layer_index=0, mesh=None,
               compute_dtype=None)


def forward_cached(net: NeuralNet, params, tokens: jnp.ndarray,
                   cache: Cache, pos,
                   kmask: Optional[jnp.ndarray] = None
                   ) -> Tuple[jnp.ndarray, Cache]:
    """Run the LM over a (B, T) token chunk at absolute offset `pos`.
    Returns (logits (B, T, V) float32, updated cache).  `kmask`
    (B, max_len) bool marks per-sequence attendable key positions
    (see `_attn_cached` — the serving tier's left-pad mask); None
    keeps the pure causal mask."""
    full = net._resolve_params(params)
    outputs: Dict[str, Any] = {}
    new_cache: Cache = dict(cache)
    logits = None
    for idx, name in enumerate(net.topo):
        layer = net.layers[name]
        ltype = layer.cfg.type
        srcs = [net._src_out(outputs, s, name) for s in layer.cfg.srclayers]
        if ltype == "kSequenceData":
            outputs[name] = {"input": tokens, "target": tokens}
        elif ltype == "kSeqLabel":
            outputs[name] = tokens
        elif ltype == "kAttention":
            out, new_cache[name] = _attn_cached(
                layer, full, srcs[0], cache[name], pos, kmask=kmask)
            outputs[name] = out
        elif ltype == "kLMHead":
            outputs[name] = layer.apply(full, srcs, _CTX)
            logits = outputs[name]
        elif ltype == "kLMHeadLoss":
            # reuse the fused loss layer's projection to emit logits
            logits = layer.project_logits(full, srcs[0])
            outputs[name] = logits
        elif ltype == "kSoftmaxLoss":
            outputs[name] = None     # no loss at decode
        else:
            ctx = Context(batch={}, train=False, rng=None, layer_index=idx,
                          mesh=None, compute_dtype=None)
            outputs[name] = layer.apply(full, srcs, ctx)
    if logits is None:
        raise ValueError("net has no kLMHead/kLMHeadLoss layer")
    return logits.astype(jnp.float32), new_cache


def _attn_paged(layer, params, x, entry: CacheEntry, tables,
                ntoks) -> Tuple[jnp.ndarray, CacheEntry]:
    """Single-token decode attention over a block/paged KV pool.

    `x` is (1, S, E): the serving tier's S decode slots ride the SEQ
    axis of a batch-1 chunk, so every position-wise layer (embed,
    rmsnorm, ffn, lmhead) and `layer.qkv`'s per-position RoPE treat a
    slot exactly like a sequence position — `ntoks` (S,) int32 is both
    the per-slot absolute position vector RoPE rotates by and the
    per-slot key-visibility horizon.  The slots never attend each
    other: attention below is per-slot against that slot's own blocks.

    `entry` holds the layer's {"k","v"} pools, each (num_blocks, Hkv,
    block_len, D); `tables` (S, T) int32 maps slot s's logical block t
    to a pool index (block 0 = null: inactive slots and table tails
    point there; its contents are never visible through the mask).
    Token position p of slot s lives at pool[tables[s, p // bl], :,
    p % bl] — flat gathered position p equals absolute position p, so
    the score row matches `_attn_cached`'s contiguous row entry for
    entry, and with masked lanes contributing exact zeros after
    softmax the paged read is bit-identical to the contiguous one
    (the parity tests pin this).

    Write-before-read: the new K/V is scattered at position ntoks[s]
    first, then the gather reads `kpos <= ntoks[s]` — the same
    self-inclusive causal horizon as `_attn_cached` at T=1."""
    assert layer.causal, f"{layer.name}: decode requires causal attention"
    _, s, _ = x.shape
    bl = entry["k"].shape[2]
    q, k, v = layer.qkv(params, x, ntoks, _CTX)    # (1,H,S,D)/(1,Hkv,S,D)

    bidx = tables[jnp.arange(s), ntoks // bl]      # (S,) pool block
    off = ntoks % bl                               # (S,) offset in block
    k_new = k[0].transpose(1, 0, 2)                # (S, Hkv, D)
    v_new = v[0].transpose(1, 0, 2)
    # advanced indices (S,) around the ":" land the (S, Hkv, D) update
    # at [block, :, offset]; inactive slots write the null block
    k_pool = entry["k"].at[bidx, :, off].set(k_new.astype(entry["k"].dtype))
    v_pool = entry["v"].at[bidx, :, off].set(v_new.astype(entry["v"].dtype))

    t = tables.shape[1]
    kk = k_pool[tables]                            # (S, T, Hkv, bl, D)
    vv = v_pool[tables]
    kk = kk.transpose(0, 2, 1, 3, 4).reshape(
        s, layer.kv_heads, t * bl, layer.head_dim).astype(q.dtype)
    vv = vv.transpose(0, 2, 1, 3, 4).reshape(
        s, layer.kv_heads, t * bl, layer.head_dim).astype(q.dtype)

    qs = q[0].transpose(1, 0, 2)[:, :, None, :]    # (S, H, 1, D)
    kpos = jnp.arange(t * bl)[None, :]             # (1, T*bl)
    allowed = kpos <= ntoks[:, None]               # (S, T*bl)
    groups = layer.heads // layer.kv_heads
    if groups == 1:
        scores = jnp.einsum("bhqd,bhkd->bhqk", qs, kk,
                            preferred_element_type=jnp.float32)
        scores = scores / jnp.sqrt(jnp.float32(layer.head_dim))
        scores = jnp.where(allowed[:, None, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs.astype(vv.dtype), vv)
    else:
        qg = qs.reshape(s, layer.kv_heads, groups, 1, layer.head_dim)
        scores = jnp.einsum("bhgqd,bhkd->bhgqk", qg, kk,
                            preferred_element_type=jnp.float32)
        scores = scores / jnp.sqrt(jnp.float32(layer.head_dim))
        scores = jnp.where(allowed[:, None, None, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bhgqk,bhkd->bhgqd", probs.astype(vv.dtype), vv)
        out = out.reshape(s, layer.heads, 1, layer.head_dim)
    out = out[:, :, 0, :].reshape(1, s, -1)        # back to (1, S, H*D)
    out = layer._proj(params, layer.wo, out.astype(x.dtype), _CTX)
    return out, {"k": k_pool, "v": v_pool}


def forward_paged(net: NeuralNet, params, tokens: jnp.ndarray,
                  pools: Cache, tables, ntoks
                  ) -> Tuple[jnp.ndarray, Cache]:
    """One decode step for S slots against the paged KV pool.
    `tokens` (1, S) int32 — slot s's last sampled token on the seq
    axis; `tables` (S, T) int32 block tables; `ntoks` (S,) int32
    tokens already written per slot (= the incoming token's absolute
    position).  Returns (logits (1, S, V) float32, updated pools)."""
    full = net._resolve_params(params)
    outputs: Dict[str, Any] = {}
    new_pools: Cache = dict(pools)
    logits = None
    for idx, name in enumerate(net.topo):
        layer = net.layers[name]
        ltype = layer.cfg.type
        srcs = [net._src_out(outputs, s, name) for s in layer.cfg.srclayers]
        if ltype == "kSequenceData":
            outputs[name] = {"input": tokens, "target": tokens}
        elif ltype == "kSeqLabel":
            outputs[name] = tokens
        elif ltype == "kAttention":
            out, new_pools[name] = _attn_paged(
                layer, full, srcs[0], pools[name], tables, ntoks)
            outputs[name] = out
        elif ltype == "kLMHead":
            outputs[name] = layer.apply(full, srcs, _CTX)
            logits = outputs[name]
        elif ltype == "kLMHeadLoss":
            logits = layer.project_logits(full, srcs[0])
            outputs[name] = logits
        elif ltype == "kSoftmaxLoss":
            outputs[name] = None
        else:
            ctx = Context(batch={}, train=False, rng=None, layer_index=idx,
                          mesh=None, compute_dtype=None)
            outputs[name] = layer.apply(full, srcs, ctx)
    if logits is None:
        raise ValueError("net has no kLMHead/kLMHeadLoss layer")
    return logits.astype(jnp.float32), new_pools


def scatter_prefill(pools: Cache, cache: Cache, table_row) -> Cache:
    """Scatter a batch-1 contiguous prefill cache ((1, Hkv, P, D) per
    layer, P a block_len multiple) into the paged pools at the blocks
    named by `table_row` (P // block_len,) int32.  Table entries
    beyond the slot's real reservation are 0: garbage from pad
    positions lands in the null block, where no mask ever looks."""
    out: Cache = {}
    for name, entry in cache.items():
        bl = pools[name]["k"].shape[2]
        hkv, p, d = entry["k"].shape[1:]
        nb = p // bl
        kb = entry["k"][0].transpose(1, 0, 2).reshape(
            nb, bl, hkv, d).transpose(0, 2, 1, 3)   # (nb, Hkv, bl, D)
        vb = entry["v"][0].transpose(1, 0, 2).reshape(
            nb, bl, hkv, d).transpose(0, 2, 1, 3)
        out[name] = {
            "k": pools[name]["k"].at[table_row].set(
                kb.astype(pools[name]["k"].dtype)),
            "v": pools[name]["v"].at[table_row].set(
                vb.astype(pools[name]["v"].dtype))}
    return out


def _sample(logits: jnp.ndarray, key, temperature: float,
            top_k: int, top_p: float) -> jnp.ndarray:
    """logits: (B, V) -> (B,) int32.  temperature 0 = greedy."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0 and top_k < logits.shape[-1]:
        kth = jax.lax.top_k(logits, top_k)[0][:, -1:]
        logits = jnp.where(logits < kth, -1e30, logits)
    if 0.0 < top_p < 1.0:
        # nucleus: keep the smallest prefix of descending-prob tokens
        # whose mass reaches top_p.  A token is kept iff the mass
        # STRICTLY BEFORE it is < top_p (the top-1 token is always
        # kept); static shapes — one sort + cumsum over V
        desc = -jnp.sort(-logits, axis=-1)
        probs = jax.nn.softmax(desc, axis=-1)
        before = jnp.cumsum(probs, axis=-1) - probs
        kth = jnp.min(jnp.where(before < top_p, desc, jnp.inf),
                      axis=-1, keepdims=True)
        logits = jnp.where(logits < kth, -1e30, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


@partial(jax.jit, static_argnums=(0, 3, 5, 6, 7, 8, 9))
def _generate_jit(net, params, prompt, max_new_tokens, key,
                  temperature, top_k, eos_id, max_len, top_p):
    b, p = prompt.shape
    if max_len is None:
        max_len = p + max_new_tokens
    elif max_len < p + max_new_tokens:
        # clamping up silently would recompile a different cache
        # geometry — the exact drift max_len exists to prevent
        raise ValueError(f"max_len={max_len} < prompt({p}) + "
                         f"max_new_tokens({max_new_tokens})")
    dtype = jax.tree_util.tree_leaves(params)[0].dtype
    cache = init_cache(net, b, max_len, dtype)

    logits, cache = forward_cached(net, params, prompt, cache, 0)
    keys = jax.random.split(key, max_new_tokens)
    tok0 = _sample(logits[:, -1], keys[0], temperature, top_k,
                   top_p)
    done0 = (jnp.zeros((b,), jnp.bool_) if eos_id is None
             else tok0 == eos_id)

    def step(carry, k):
        tok, cache, pos, done = carry
        logits, cache = forward_cached(net, params, tok[:, None], cache, pos)
        nxt = _sample(logits[:, -1], k, temperature, top_k, top_p)
        if eos_id is not None:
            nxt = jnp.where(done, eos_id, nxt)
            done = done | (nxt == eos_id)
        return (nxt, cache, pos + 1, done), nxt

    (_, _, _, _), rest = jax.lax.scan(
        step, (tok0, cache, jnp.int32(p), done0), keys[1:])
    return jnp.concatenate([tok0[:, None], rest.T], axis=1)


@partial(jax.jit, static_argnums=(0, 3, 4, 5, 6, 7))
def _beam_jit(net, params, prompt, max_new_tokens, num_beams,
              length_penalty, eos_id, max_len):
    b, p = prompt.shape
    w = num_beams
    if max_len is None:
        max_len = p + max_new_tokens
    elif max_len < p + max_new_tokens:
        raise ValueError(f"max_len={max_len} < prompt({p}) + "
                         f"max_new_tokens({max_new_tokens})")
    dtype = jax.tree_util.tree_leaves(params)[0].dtype
    cache = init_cache(net, b, max_len, dtype)

    logits, cache = forward_cached(net, params, prompt, cache, 0)
    lp0 = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32), axis=-1)
    vocab = lp0.shape[-1]
    if eos_id is not None and not 0 <= eos_id < vocab:
        # out of range, the frozen-vector .at[eos_id].set() below would
        # silently drop and beam freezing would never engage
        raise ValueError(f"eos_id={eos_id} out of range for vocab size "
                         f"{vocab}")
    # only min(W, V) distinct beams exist after one token; pad the rest
    # with -inf scores so they never outrank a real candidate
    k0 = min(w, vocab)
    scores, tok = jax.lax.top_k(lp0, k0)              # (B, k0) each
    if k0 < w:
        scores = jnp.concatenate(
            [scores, jnp.full((b, w - k0), -1e30, scores.dtype)], axis=1)
        tok = jnp.concatenate(
            [tok, jnp.tile(tok[:, :1], (1, w - k0))], axis=1)
    tok = tok.astype(jnp.int32)
    # beam-expand the cache: beam index varies fastest, so flat row
    # b*W + j is batch b's beam j — matching the take() reorder below
    cache = jax.tree_util.tree_map(
        lambda a: jnp.repeat(a, w, axis=0), cache)
    seqs = jnp.zeros((b, w, max_new_tokens), jnp.int32)
    seqs = jnp.where(jnp.arange(max_new_tokens) == 0, tok[:, :, None],
                     seqs)
    done = (tok == eos_id) if eos_id is not None \
        else jnp.zeros((b, w), jnp.bool_)
    lengths = jnp.ones((b, w), jnp.int32)

    def step(carry, t):
        seqs, scores, cache, done, lengths, last = carry
        logits, cache = forward_cached(
            net, params, last.reshape(b * w, 1), cache, p + t - 1)
        lp = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32),
                                axis=-1).reshape(b, w, vocab)
        if eos_id is not None:
            # a finished beam only continues with eos at zero cost, so
            # its score freezes and it cannot spawn siblings
            frozen = jnp.full((vocab,), -1e30,
                              jnp.float32).at[eos_id].set(0.0)
            lp = jnp.where(done[:, :, None], frozen, lp)
        cand = (scores[:, :, None] + lp).reshape(b, w * vocab)
        scores, idx = jax.lax.top_k(cand, w)
        beam = idx // vocab
        tokv = (idx % vocab).astype(jnp.int32)
        seqs = jnp.take_along_axis(seqs, beam[:, :, None], axis=1)
        seqs = jnp.where(jnp.arange(max_new_tokens) == t,
                         tokv[:, :, None], seqs)
        done = jnp.take_along_axis(done, beam, axis=1)
        lengths = jnp.take_along_axis(lengths, beam, axis=1)
        lengths = jnp.where(done, lengths, t + 1)
        if eos_id is not None:
            done = done | (tokv == eos_id)
        flat = (jnp.arange(b)[:, None] * w + beam).reshape(-1)
        cache = jax.tree_util.tree_map(
            lambda a: jnp.take(a, flat, axis=0), cache)
        return (seqs, scores, cache, done, lengths, tokv), None

    if max_new_tokens > 1:
        (seqs, scores, cache, done, lengths, _), _ = jax.lax.scan(
            step, (seqs, scores, cache, done, lengths, tok),
            jnp.arange(1, max_new_tokens))
    if length_penalty:
        ranked = scores / (lengths.astype(jnp.float32) ** length_penalty)
    else:
        ranked = scores
    best = jnp.argmax(ranked, axis=1)
    return (jnp.take_along_axis(seqs, best[:, None, None],
                                axis=1)[:, 0],
            jnp.take_along_axis(scores, best[:, None], axis=1)[:, 0])


def beam_search(net: NeuralNet, params, prompt, max_new_tokens: int,
                num_beams: int = 4, length_penalty: float = 0.0,
                eos_id: Optional[int] = None,
                max_len: Optional[int] = None
                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Beam-search decode: returns (tokens (B, max_new_tokens) int32,
    log-prob scores (B,) float32) for the best beam per sequence.  One
    compiled program: prefill at batch B, then a lax.scan decode loop
    at batch B·num_beams with per-step beam reordering of the KV cache
    (static shapes throughout — the top-k over W·V candidates and the
    cache `take` are ordinary XLA ops).  After `eos_id` a beam is
    frozen: it keeps emitting eos at zero added cost and its score
    stops moving.  `length_penalty` alpha divides final scores by
    length**alpha for ranking (0 = rank by raw log-prob).  `max_len`
    over-allocates the KV cache exactly as in generate() — pin it to
    keep one compiled cache geometry across runs of different
    lengths."""
    prompt = jnp.asarray(prompt, jnp.int32)
    if int(num_beams) < 1:
        # num_beams=0 would reach jax.lax.top_k(lp0, 0) and die with a
        # cryptic XLA error deep in the trace
        raise ValueError(f"num_beams must be >= 1, got {num_beams}")
    if int(max_new_tokens) <= 0:
        b = prompt.shape[0]
        return (jnp.zeros((b, 0), jnp.int32), jnp.zeros((b,), jnp.float32))
    return _beam_jit(net, params, prompt, int(max_new_tokens),
                     int(num_beams), float(length_penalty), eos_id,
                     None if max_len is None else int(max_len))


def generate(net: NeuralNet, params, prompt,
             max_new_tokens: int, key: Optional[jax.Array] = None,
             temperature: float = 0.0, top_k: int = 0,
             eos_id: Optional[int] = None,
             max_len: Optional[int] = None,
             top_p: float = 0.0) -> jnp.ndarray:
    """Sample `max_new_tokens` continuations of `prompt` ((B, P) int32).
    Returns the (B, max_new_tokens) generated tokens.  One compiled
    program: prefill + a lax.scan decode loop with per-token sampling
    (greedy when temperature == 0; top-k truncation when top_k > 0;
    nucleus truncation when 0 < top_p < 1 — both filters compose,
    top-k first).  After `eos_id` is produced, a sequence keeps
    emitting `eos_id`.  `max_len` over-allocates the KV cache beyond
    prompt+new (the tail is mask-ignored) — lets callers fix the cache
    geometry across runs of different lengths (bench.py isolates
    prefill this way)."""
    if key is None:
        key = jax.random.PRNGKey(0)
    prompt = jnp.asarray(prompt, jnp.int32)
    if int(max_new_tokens) <= 0:
        return jnp.zeros((prompt.shape[0], 0), jnp.int32)
    return _generate_jit(net, params, prompt, int(max_new_tokens), key,
                         float(temperature), int(top_k), eos_id,
                         None if max_len is None else int(max_len),
                         float(top_p))
