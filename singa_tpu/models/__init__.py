from .vision import (alexnet_cifar10, alexnet_cifar10_full, alexnet_imagenet,
                     lenet_mnist, mlp_mnist)
from .transformer import synthetic_token_batches, transformer_lm
from .generate import beam_search, generate, forward_cached, init_cache
from . import rbm
