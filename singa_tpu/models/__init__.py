from .vision import (alexnet_cifar10, alexnet_cifar10_full, alexnet_imagenet,
                     lenet_mnist, mlp_mnist)
from . import rbm
