"""Shard store, record codec, and pipeline tests (reference parity:
shard.cc format, model.proto Record wire format, prefetch semantics)."""

import os
import struct

import numpy as np
import pytest

from singa_tpu.data import (Record, SingleLabelImageRecord, Datum, Shard,
                            prefetch, shard_batches, synthetic_image_batches)


def make_record(label, side=4, seed=0):
    rng = np.random.default_rng(seed)
    img = rng.integers(0, 256, (side, side), dtype=np.uint8)
    return Record(image=SingleLabelImageRecord(
        shape=[side, side], label=label, pixel=img.tobytes())), img


def test_shard_roundtrip(tmp_path):
    with Shard(str(tmp_path), Shard.KCREATE) as sh:
        for i in range(5):
            rec, _ = make_record(i, seed=i)
            assert sh.insert(f"key{i}", rec.encode())
        # duplicate key rejected (shard.cc:49-52)
        rec, _ = make_record(9)
        assert not sh.insert("key0", rec.encode())

    with Shard(str(tmp_path), Shard.KREAD) as sh:
        assert sh.count() == 5
        items = list(sh)
        assert [k for k, _ in items] == [f"key{i}".encode() for i in range(5)]
        decoded = Record.decode(items[3][1])
        assert decoded.image.label == 3
        assert decoded.image.shape == [4, 4]


def test_shard_binary_layout(tmp_path):
    """Byte-for-byte the reference layout: [u64 klen][key][u64 vlen][val]."""
    with Shard(str(tmp_path), Shard.KCREATE) as sh:
        sh.insert("ab", b"xyz")
    raw = open(os.path.join(str(tmp_path), "shard.dat"), "rb").read()
    assert raw == struct.pack("<Q", 2) + b"ab" + struct.pack("<Q", 3) + b"xyz"


def test_shard_append_truncates_torn_tail(tmp_path):
    with Shard(str(tmp_path), Shard.KCREATE) as sh:
        sh.insert("k1", b"value1")
    # simulate a crashed writer: half a tuple at the tail
    with open(os.path.join(str(tmp_path), "shard.dat"), "ab") as f:
        f.write(struct.pack("<Q", 2) + b"k2" + struct.pack("<Q", 100) + b"par")
    with Shard(str(tmp_path), Shard.KAPPEND) as sh:
        assert not sh.insert("k1", b"dup")   # dedup survives reopen
        assert sh.insert("k3", b"value3")
    with Shard(str(tmp_path), Shard.KREAD) as sh:
        assert [(k, v) for k, v in sh] == [(b"k1", b"value1"),
                                           (b"k3", b"value3")]


def test_record_codec_against_protobuf_library():
    """Cross-check our hand-rolled wire codec against google.protobuf's
    generic wire parsing (field numbers + values)."""
    rec, img = make_record(7, side=3)
    buf = rec.encode()
    # decode with the protobuf library's low-level reader
    from google.protobuf.internal import decoder

    # walk top-level: expect field 2 (image submessage)
    pos = 0
    tag, pos = decoder._DecodeVarint(buf, pos)
    assert tag >> 3 == 2 and tag & 7 == 2
    ln, pos = decoder._DecodeVarint(buf, pos)
    sub = buf[pos:pos + ln]
    dec = SingleLabelImageRecord.decode(sub)
    assert dec.label == 7
    np.testing.assert_array_equal(dec.pixels_array(), img)


def test_datum_roundtrip():
    d = Datum(channels=3, height=2, width=2, data=b"\x01" * 12, label=5,
              float_data=[0.5, -1.5])
    d2 = Datum.decode(d.encode())
    assert (d2.channels, d2.height, d2.width, d2.label) == (3, 2, 2, 5)
    assert d2.data == b"\x01" * 12
    np.testing.assert_allclose(d2.float_data, [0.5, -1.5])


def test_shard_batches_and_prefetch(tmp_path):
    with Shard(str(tmp_path), Shard.KCREATE) as sh:
        for i in range(10):
            rec, _ = make_record(i % 3, side=4, seed=i)
            sh.insert(f"r{i:03d}", rec.encode())
    it = prefetch(shard_batches(str(tmp_path), batchsize=4, loop=False))
    batches = list(it)
    assert len(batches) == 3  # 4+4+2
    assert batches[0]["data"]["pixel"].shape == (4, 4, 4)
    assert batches[0]["data"]["label"].dtype == np.int32
    assert batches[2]["data"]["pixel"].shape == (2, 4, 4)


def test_synthetic_learnable_batches():
    it = synthetic_image_batches(8, seed=0)
    b = next(it)
    assert b["data"]["pixel"].shape == (8, 28, 28)
    assert b["data"]["pixel"].dtype == np.uint8
    assert b["data"]["label"].shape == (8,)


def test_native_shard_interop(tmp_path):
    """The C++ shard store and the Python one are byte-interoperable
    (both follow the reference format, shard.cc)."""
    native = pytest.importorskip("singa_tpu.data.native")
    if not native.available():
        pytest.skip("native library not built")
    # write with C++, read with Python
    with native.NativeShardWriter(str(tmp_path)) as w:
        assert w.insert("a", b"alpha")
        assert w.insert("b", b"beta")
        assert not w.insert("a", b"dup")
    with Shard(str(tmp_path), Shard.KREAD) as sh:
        assert [(k, v) for k, v in sh] == [(b"a", b"alpha"), (b"b", b"beta")]
    # append with C++ (dedup must survive), read with C++
    with native.NativeShardWriter(str(tmp_path), append=True) as w:
        assert not w.insert("b", b"dup")
        assert w.insert("c", b"gamma")
    with native.NativeShardReader(str(tmp_path)) as r:
        assert r.count() == 3
        assert [k for k, _ in r] == [b"a", b"b", b"c"]


def test_native_shard_torn_tail(tmp_path):
    native = pytest.importorskip("singa_tpu.data.native")
    if not native.available():
        pytest.skip("native library not built")
    with native.NativeShardWriter(str(tmp_path)) as w:
        w.insert("k1", b"v1")
    with open(os.path.join(str(tmp_path), "shard.dat"), "ab") as f:
        f.write(struct.pack("<Q", 2) + b"k2")   # torn record
    with native.NativeShardWriter(str(tmp_path), append=True) as w:
        assert w.insert("k3", b"v3")
    with native.NativeShardReader(str(tmp_path)) as r:
        assert [k for k, _ in r] == [b"k1", b"k3"]


def test_loader_tool_mnist_and_split(tmp_path):
    """tools/data_loader parity: idx -> shard -> split."""
    import struct as st
    from singa_tpu.tools import loader
    # synthesize tiny idx files
    n, r, c = 10, 4, 4
    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 256, (n, r, c), dtype=np.uint8)
    labels = rng.integers(0, 10, n).astype(np.uint8)
    ip = tmp_path / "img.idx"
    lp = tmp_path / "lab.idx"
    ip.write_bytes(st.pack(">IIII", 2051, n, r, c) + imgs.tobytes())
    lp.write_bytes(st.pack(">II", 2049, n) + labels.tobytes())

    out = tmp_path / "shard"
    wrote = loader.create_shard(loader.read_mnist_idx(str(ip), str(lp)),
                                str(out))
    assert wrote == n
    # restartable: re-running appends nothing (key dedup)
    wrote2 = loader.create_shard(loader.read_mnist_idx(str(ip), str(lp)),
                                 str(out))
    assert wrote2 == 0

    with Shard(str(out), Shard.KREAD) as sh:
        recs = [Record.decode(v) for _, v in sh]
    assert len(recs) == n
    np.testing.assert_array_equal(recs[3].image.pixels_array(), imgs[3])
    assert recs[3].image.label == labels[3]

    counts = loader.split_shard(str(out), str(tmp_path / "part"), 3)
    assert sum(counts) == n and counts == [4, 3, 3]


def test_loader_tool_cifar(tmp_path):
    from singa_tpu.tools import loader
    rng = np.random.default_rng(1)
    rows = b"".join(
        bytes([rng.integers(0, 10)]) + rng.integers(0, 256, 3072,
                                                    dtype=np.uint8).tobytes()
        for _ in range(5))
    binp = tmp_path / "data_batch.bin"
    binp.write_bytes(rows)
    out = tmp_path / "shard"
    wrote = loader.create_shard(loader.read_cifar10_bins([str(binp)]),
                                str(out))
    assert wrote == 5
    with Shard(str(out), Shard.KREAD) as sh:
        rec = Record.decode(next(iter(sh))[1])
    assert rec.image.shape == [3, 32, 32]


def test_token_streams_share_language_across_seeds():
    # train (seed) and test (seed+1) must sample the SAME transition
    # table, else eval can never reflect learning
    from singa_tpu.models.transformer import synthetic_token_batches
    table = np.random.default_rng(1234).integers(0, 64, (64, 4))
    for seed in (0, 1):
        b = next(synthetic_token_batches(8, 128, 64, seed=seed))["data"]
        inp, tgt = b["input"], b["target"]
        hits = np.mean([tgt[i, t] in table[inp[i, t]]
                        for i in range(8) for t in range(128)])
        assert hits > 0.8, f"seed {seed}: only {hits:.2f} follow the table"


def test_loader_tool_imagefolder_and_mean(tmp_path):
    """ImageNet-style folder -> shard (cv2 resize, CHW uint8) + per-pixel
    mean record (the mean.binaryproto role)."""
    cv2 = pytest.importorskip("cv2")
    from singa_tpu.tools import loader
    img_dir = tmp_path / "imgs"
    os.makedirs(img_dir)
    rng = np.random.default_rng(0)
    lines = []
    for i in range(4):
        img = rng.integers(0, 256, (40 + i, 30, 3)).astype(np.uint8)
        cv2.imwrite(str(img_dir / f"im{i}.png"), img)
        lines.append(f"im{i}.png {i % 2}")
    lst = tmp_path / "list.txt"
    lst.write_text("\n".join(lines) + "\n")

    out = tmp_path / "shard"
    n = loader.create_shard(
        loader.read_image_folder(str(img_dir), str(lst), size=16), str(out))
    assert n == 4
    with Shard(str(out), Shard.KREAD) as sh:
        recs = [Record.decode(v).image for _, v in sh]
    assert all(tuple(r.shape) == (3, 16, 16) for r in recs)
    assert [r.label for r in recs] == [0, 1, 0, 1]

    mean_path = tmp_path / "mean.rec"
    mean = loader.compute_mean(str(out), str(mean_path))
    assert mean.shape == (3, 16, 16)
    stored = Record.decode(mean_path.read_bytes()).image
    np.testing.assert_allclose(stored.pixels_array(), mean, rtol=1e-6)
    expect = np.mean([r.pixels_array().astype(np.float64) for r in recs],
                     axis=0)
    np.testing.assert_allclose(mean, expect, atol=1e-4)


def test_native_record_batch_decode_matches_python(tmp_path):
    """C++ record_batch_decode == Python codec on the same shard, and
    shard_batches uses it transparently."""
    native = pytest.importorskip("singa_tpu.data.native")
    if not native.available():
        pytest.skip("native library not built")
    from singa_tpu.data.pipeline import shard_batches

    rng = np.random.default_rng(9)
    folder = tmp_path / "s"
    os.makedirs(folder)
    recs = []
    with Shard(str(folder), Shard.KCREATE) as sh:
        for i in range(7):
            img = rng.integers(0, 256, (3, 5, 4)).astype(np.uint8)
            rec = Record(image=SingleLabelImageRecord(
                shape=[3, 5, 4], label=i % 3, pixel=img.tobytes()))
            sh.insert(f"k{i}", rec.encode())
            recs.append((img, i % 3))

    vals = [Record(image=SingleLabelImageRecord(
        shape=[3, 5, 4], label=lb, pixel=im.tobytes())).encode()
        for im, lb in recs]
    out = native.decode_image_batch(vals)
    assert out is not None
    pixels, labels = out
    assert pixels.shape == (7, 3, 5, 4) and pixels.dtype == np.uint8
    np.testing.assert_array_equal(labels, [r[1] for r in recs])
    for i, (im, _) in enumerate(recs):
        np.testing.assert_array_equal(pixels[i], im)

    # malformed record -> graceful None (fallback path)
    assert native.decode_image_batch([b"\xff\xff\xff"]) is None

    batches = list(shard_batches(str(folder), 3, loop=False))
    assert [b["data"]["pixel"].shape[0] for b in batches] == [3, 3, 1]
    np.testing.assert_array_equal(batches[0]["data"]["pixel"][0], recs[0][0])


def test_native_decode_rejects_mixed_shapes():
    """Same pixel count but different dims must NOT be silently
    reinterpreted under record 0's shape (native == Python semantics)."""
    native = pytest.importorskip("singa_tpu.data.native")
    if not native.available():
        pytest.skip("native library not built")
    px = bytes(range(60))
    a = Record(image=SingleLabelImageRecord(
        shape=[3, 5, 4], label=0, pixel=px)).encode()
    b = Record(image=SingleLabelImageRecord(
        shape=[60], label=1, pixel=px)).encode()
    assert native.decode_image_batch([a, a]) is not None
    assert native.decode_image_batch([a, b]) is None   # falls back


def test_native_decode_skips_unknown_fixed_fields():
    """Records carrying unknown fixed32/fixed64 fields still decode on
    the native path (find_image must skip wire types 1 and 5)."""
    native = pytest.importorskip("singa_tpu.data.native")
    if not native.available():
        pytest.skip("native library not built")
    import struct
    px = bytes(range(12))
    body = Record(image=SingleLabelImageRecord(
        shape=[3, 4], label=2, pixel=px)).encode()
    # prepend unknown field 15 (fixed64) and field 14 (fixed32)
    extra = bytes([(15 << 3) | 1]) + struct.pack("<Q", 7)
    extra += bytes([(14 << 3) | 5]) + struct.pack("<I", 9)
    out = native.decode_image_batch([extra + body])
    assert out is not None
    pixels, labels = out
    assert pixels.shape == (1, 3, 4) and labels[0] == 2


def test_prefetcher_propagates_iterator_errors():
    """A corrupt record must surface as an error on the consumer thread,
    not masquerade as a clean end of data."""
    from singa_tpu.data.pipeline import prefetch

    def bad_iter():
        yield 1
        raise ValueError("corrupt Record buffer")

    it = prefetch(bad_iter())
    assert next(it) == 1
    with pytest.raises(ValueError, match="corrupt Record buffer"):
        next(it)


def test_corrupt_record_raises():
    from singa_tpu.data.records import record_has_image

    good = Record(type=1).encode()
    assert record_has_image(good) is False
    with pytest.raises(ValueError, match="corrupt"):
        record_has_image(b"\x12\xff")  # length-delimited field, torn tail


def test_pipeline_skips_imageless_records(tmp_path):
    """Type-only records (no image submessage) never shrink a batch."""
    from singa_tpu.data.pipeline import shard_batches

    rng = np.random.default_rng(3)
    folder = tmp_path / "s"
    os.makedirs(folder)
    with Shard(str(folder), Shard.KCREATE) as sh:
        n = 0
        for i in range(9):
            if i % 3 == 1:
                sh.insert(f"t{i}", Record(type=1).encode())  # image-less
            else:
                img = rng.integers(0, 256, (2, 2)).astype(np.uint8)
                sh.insert(f"k{i}", Record(image=SingleLabelImageRecord(
                    shape=[2, 2], label=i, pixel=img.tobytes())).encode())
                n += 1
    batches = list(shard_batches(str(folder), 2, loop=False))
    sizes = [b["data"]["pixel"].shape[0] for b in batches]
    assert sum(sizes) == n and all(s == 2 for s in sizes[:-1])


def test_partition_shard_group_semantics(tmp_path):
    """script/load_data.py partition() parity: workers in one group see
    that group's contiguous slice — the whole slice when replicated,
    disjoint sub-slices otherwise; the tail is never dropped."""
    from singa_tpu.data.records import Record, SingleLabelImageRecord
    from singa_tpu.data.shard import Shard
    from singa_tpu.tools.loader import partition_shard

    src = tmp_path / "src"
    src.mkdir()
    with Shard(str(src), Shard.KCREATE) as sh:
        for i in range(23):   # deliberately not divisible by 2 or 4
            rec = Record(image=SingleLabelImageRecord(
                shape=[2, 2], label=i, pixel=bytes([i] * 4)))
            sh.insert(f"k{i:03d}", rec.encode())

    def labels(folder):
        with Shard(folder, Shard.KREAD) as sh:
            return [Record.decode(v).image.label for _, v in sh]

    # 4 workers, groups of 2, split inside the group
    counts = partition_shard(str(src), str(tmp_path / "split"), 4, 2)
    got = [labels(str(tmp_path / "split" / f"proc{i}")) for i in range(4)]
    assert counts == [len(g) for g in got]
    # group 0 = records [0, 11), group 1 = [11, 23); disjoint per worker
    assert got[0] + got[1] == list(range(11))
    assert got[2] + got[3] == list(range(11, 23))
    assert sum(counts) == 23   # nothing dropped

    # replicate: every group member sees the full group slice
    partition_shard(str(src), str(tmp_path / "rep"), 4, 2,
                    replicate=True)
    r = [labels(str(tmp_path / "rep" / f"proc{i}")) for i in range(4)]
    assert r[0] == r[1] == list(range(11))
    assert r[2] == r[3] == list(range(11, 23))

    with pytest.raises(ValueError):
        partition_shard(str(src), str(tmp_path / "bad"), 4, 3)


def test_place_shards_dry_run_emits_rsync_plan(tmp_path):
    """scripts/place_shards.sh (the load_data.py/node.sh ops-glue
    successor) in its dry-run default: one rsync line per hostfile
    process, ports stripped from the ssh target, proc{i} suffix kept
    remotely, comments/blanks skipped, missing partitions warned."""
    import os
    import subprocess
    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "place_shards.sh")
    for i in range(2):
        (tmp_path / f"proc{i}").mkdir()
    hostfile = tmp_path / "hostfile"
    hostfile.write_text(
        "# comment\n\n10.0.0.1:5555\n10.0.0.2\n10.0.0.3\n")
    r = subprocess.run(
        ["bash", script, str(tmp_path), str(hostfile), "/data/shards"],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    lines = r.stdout.strip().splitlines()
    assert lines == [
        f"rsync -az --mkpath {tmp_path}/proc0/ 10.0.0.1:/data/shards/proc0/",
        f"rsync -az --mkpath {tmp_path}/proc1/ 10.0.0.2:/data/shards/proc1/",
    ]
    assert "proc2 missing" in r.stderr         # 3rd host, no partition
