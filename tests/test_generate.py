"""KV-cache autoregressive generation (models/generate.py).

Correctness anchor: the cached prefill+decode path must produce the
same logits as the full (uncached) forward over the same tokens —
teacher-forcing parity — for both head forms (kLMHead->kSoftmaxLoss and
the fused kLMHeadLoss)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from singa_tpu.core.net import build_net
from singa_tpu.models.generate import forward_cached, generate, init_cache
from singa_tpu.models.transformer import transformer_lm

VOCAB, SEQ, B = 64, 16, 2
SHAPES = {"data": {"input": (SEQ,), "target": (SEQ,)}}


def _net_and_params(fused_head, seed=0, **kw):
    cfg = transformer_lm(vocab_size=VOCAB, num_layers=2, embed_dim=32,
                         num_heads=4, head_dim=8, seq_len=SEQ, batchsize=B,
                         fused_head=fused_head, **kw)
    net = build_net(cfg, "kTest", SHAPES)
    params = net.init_params(jax.random.PRNGKey(seed))
    return net, params


def _full_logits(net, params, toks):
    """Uncached reference logits via the net's ordinary apply."""
    batch = {"data": {"input": toks, "target": toks}}
    if any(l.cfg.type == "kLMHead" for l in net.layers.values()):
        _, _, outputs = net.apply(params, batch, train=False)
        (name,) = [n for n, l in net.layers.items()
                   if l.cfg.type == "kLMHead"]
        return outputs[name].astype(jnp.float32)
    # fused head: replay its projection on the final hidden state
    _, _, outputs = net.apply(params, batch, train=False)
    (name,) = [n for n, l in net.layers.items()
               if l.cfg.type == "kLMHeadLoss"]
    layer = net.layers[name]
    hidden = outputs[layer.cfg.srclayers[0]]
    return layer.project_logits(net._resolve_params(params), hidden)


@pytest.mark.parametrize("fused_head", [False, True])
def test_prefill_matches_full_forward(fused_head):
    net, params = _net_and_params(fused_head)
    toks = jnp.asarray(
        np.random.default_rng(0).integers(0, VOCAB, (B, SEQ)), jnp.int32)
    cache = init_cache(net, B, SEQ)
    logits, _ = forward_cached(net, params, toks, cache, 0)
    np.testing.assert_allclose(logits, _full_logits(net, params, toks),
                               rtol=2e-4, atol=2e-4)


def test_stepwise_decode_matches_prefill():
    """Feeding tokens one at a time through the cache must equal the
    one-shot prefill (positions, RoPE offsets, masking all line up)."""
    net, params = _net_and_params(fused_head=True)
    toks = jnp.asarray(
        np.random.default_rng(1).integers(0, VOCAB, (B, SEQ)), jnp.int32)
    cache = init_cache(net, B, SEQ)
    ref, _ = forward_cached(net, params, toks, cache, 0)

    cache = init_cache(net, B, SEQ)
    step_logits = []
    for t in range(SEQ):
        lg, cache = forward_cached(net, params, toks[:, t:t + 1], cache,
                                   jnp.int32(t))
        step_logits.append(lg[:, 0])
    got = jnp.stack(step_logits, axis=1)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_generate_greedy_deterministic():
    net, params = _net_and_params(fused_head=True)
    prompt = jnp.asarray(
        np.random.default_rng(2).integers(0, VOCAB, (B, 4)), jnp.int32)
    out1 = generate(net, params, prompt, 8)
    out2 = generate(net, params, prompt, 8)
    assert out1.shape == (B, 8)
    assert out1.dtype == jnp.int32
    np.testing.assert_array_equal(out1, out2)
    assert int(out1.min()) >= 0 and int(out1.max()) < VOCAB


def test_generate_greedy_matches_full_argmax():
    """Greedy decode must pick argmax of the full-forward logits at each
    position (run the uncached forward on the growing sequence)."""
    net, params = _net_and_params(fused_head=False)
    prompt_len, nnew = 4, 4
    prompt = jnp.asarray(
        np.random.default_rng(3).integers(0, VOCAB, (B, prompt_len)),
        jnp.int32)
    got = generate(net, params, prompt, nnew)

    seq = prompt
    for _ in range(nnew):
        logits = _full_logits(net, params, seq)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(got, seq[:, prompt_len:])


def test_generate_sampling_topk_and_eos():
    net, params = _net_and_params(fused_head=True)
    prompt = jnp.asarray(
        np.random.default_rng(4).integers(0, VOCAB, (B, 4)), jnp.int32)
    out = generate(net, params, prompt, 12, key=jax.random.PRNGKey(7),
                   temperature=0.8, top_k=8)
    assert out.shape == (B, 12)
    # eos propagation: once eos appears every later token is eos
    eos = int(out[0, 3])  # pick an id that actually occurs
    out2 = generate(net, params, prompt, 12, key=jax.random.PRNGKey(7),
                    temperature=0.8, top_k=8, eos_id=eos)
    arr = np.asarray(out2)
    for row in arr:
        hits = np.where(row == eos)[0]
        if hits.size:
            assert (row[hits[0]:] == eos).all()


def test_generate_zero_tokens_returns_empty():
    net, params = _net_and_params(fused_head=True)
    prompt = jnp.zeros((B, 4), jnp.int32)
    out = generate(net, params, prompt, 0)
    assert out.shape == (B, 0) and out.dtype == jnp.int32


def test_generate_with_moe_and_gqa():
    """Decode path covers MoE blocks and grouped-query attention."""
    net, params = _net_and_params(fused_head=True, moe_every=2,
                                  num_experts=4, experts_per_token=2,
                                  num_kv_heads=2)
    toks = jnp.asarray(
        np.random.default_rng(5).integers(0, VOCAB, (B, SEQ)), jnp.int32)
    cache = init_cache(net, B, SEQ)
    logits, _ = forward_cached(net, params, toks, cache, 0)
    np.testing.assert_allclose(logits, _full_logits(net, params, toks),
                               rtol=2e-4, atol=2e-4)
    out = generate(net, params, toks[:, :4], 6)
    assert out.shape == (B, 6)


def test_generate_max_len_overallocation_equivalent():
    """An over-allocated KV cache (max_len > prompt+new) must not change
    the tokens: the tail slots are mask-ignored.  bench.py relies on
    this to time the prefill probe at the full run's cache geometry."""
    net, params = _net_and_params(False)
    toks = jnp.asarray(
        np.random.default_rng(5).integers(0, VOCAB, (B, 6)), jnp.int32)
    base = generate(net, params, toks, 8)
    over = generate(net, params, toks, 8, max_len=32)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(over))


def test_generate_max_len_too_small_raises():
    """max_len below prompt+new must fail loudly — silently clamping up
    would recompile a different cache geometry, the drift the pin
    exists to prevent."""
    net, params = _net_and_params(False)
    toks = jnp.zeros((B, 6), jnp.int32)
    with pytest.raises(ValueError, match="max_len"):
        generate(net, params, toks, 8, max_len=10)


def test_sample_top_p_truncates_to_nucleus():
    """Nucleus sampling keeps the smallest descending-prob prefix whose
    mass reaches top_p (top-1 always kept) and masks the rest."""
    from singa_tpu.models.generate import _sample
    logits = jnp.log(jnp.asarray([[0.6, 0.25, 0.1, 0.05]]))
    # top_p=0.5: nucleus is {0} -> deterministic despite temperature 1
    for i in range(5):
        assert int(_sample(logits, jax.random.PRNGKey(i), 1.0, 0, 0.5)[0]) == 0
    # top_p=0.7: before-mass [0, .6, .85, .95] -> nucleus {0, 1}
    toks = {int(_sample(logits, jax.random.PRNGKey(i), 1.0, 0, 0.7)[0])
            for i in range(40)}
    assert toks == {0, 1}
    # top_p=0 disables the filter: every token reachable
    toks = {int(_sample(logits, jax.random.PRNGKey(i), 1.0, 0, 0.0)[0])
            for i in range(120)}
    assert toks == {0, 1, 2, 3}


def test_generate_top_p_smoke():
    net, params = _net_and_params(False)
    toks = jnp.zeros((B, 4), jnp.int32)
    out = generate(net, params, toks, 6, key=jax.random.PRNGKey(1),
                   temperature=0.8, top_p=0.9)
    assert out.shape == (B, 6)
    assert (np.asarray(out) >= 0).all() and (np.asarray(out) < VOCAB).all()


def test_beam_search_finds_global_optimum_small_vocab():
    """Oracle: with num_beams >= V**(T-1) beam search is exhaustive, so
    its winner must equal the argmax-log-prob continuation over ALL
    V**T candidates (scored by teacher-forced forward)."""
    import itertools

    from singa_tpu.models.generate import beam_search
    cfg = transformer_lm(vocab_size=4, num_layers=2, embed_dim=32,
                         num_heads=4, head_dim=8, seq_len=SEQ, batchsize=1)
    net = build_net(cfg, "kTest", SHAPES)
    params = net.init_params(jax.random.PRNGKey(3))
    prompt = jnp.asarray([[1, 2]], jnp.int32)
    T = 3
    toks, score = beam_search(net, params, prompt, T, num_beams=16)

    best_seq, best_lp = None, -np.inf
    for cand in itertools.product(range(4), repeat=T):
        full = jnp.concatenate(
            [prompt, jnp.asarray([cand], jnp.int32)], axis=1)
        cache = init_cache(net, 1, full.shape[1])
        logits, _ = forward_cached(net, params, full, cache, 0)
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        total = sum(float(lp[0, prompt.shape[1] - 1 + i, cand[i]])
                    for i in range(T))
        if total > best_lp:
            best_lp, best_seq = total, cand
    assert tuple(np.asarray(toks)[0]) == best_seq
    assert float(score[0]) == pytest.approx(best_lp, abs=1e-3)


def test_beam_search_width_one_is_greedy():
    from singa_tpu.models.generate import beam_search
    net, params = _net_and_params(False)
    prompt = jnp.asarray(
        np.random.default_rng(7).integers(0, VOCAB, (B, 5)), jnp.int32)
    greedy = generate(net, params, prompt, 6)
    beams, _ = beam_search(net, params, prompt, 6, num_beams=1)
    np.testing.assert_array_equal(np.asarray(beams), np.asarray(greedy))


def test_beam_search_eos_freezes_beam():
    from singa_tpu.models.generate import beam_search
    net, params = _net_and_params(False)
    prompt = jnp.zeros((1, 4), jnp.int32)
    eos = int(np.asarray(generate(net, params, prompt, 1))[0, 0])
    toks, _ = beam_search(net, params, prompt, 6, num_beams=2,
                          eos_id=eos)
    row = np.asarray(toks)[0]
    # np.argmax(row == eos) returns 0 on an all-False row, so assert the
    # winner actually emitted eos first — a non-eos winning beam should
    # fail HERE with a clear message, not downstream for the wrong reason
    assert eos in row, (
        f"winning beam never emitted eos={eos} (row={row.tolist()}): the "
        f"greedy next token should make eos the top continuation")
    # once eos appears every later slot is eos (the frozen-beam contract)
    hit = np.argmax(row == eos)
    assert row[hit] == eos and (row[hit:] == eos).all()


def test_beam_search_length_penalty_matches_bruteforce():
    """alpha=1.0 ranking (score/length) against brute force over all
    V**T continuations, with eos-frozen lengths: the winner under the
    penalized objective must match."""
    import itertools

    from singa_tpu.models.generate import beam_search
    cfg = transformer_lm(vocab_size=4, num_layers=2, embed_dim=32,
                         num_heads=4, head_dim=8, seq_len=SEQ, batchsize=1)
    net = build_net(cfg, "kTest", SHAPES)
    params = net.init_params(jax.random.PRNGKey(9))
    prompt = jnp.asarray([[3, 0]], jnp.int32)
    T, EOS = 3, 1
    toks, _ = beam_search(net, params, prompt, T, num_beams=16,
                          length_penalty=1.0, eos_id=EOS,
                          max_len=prompt.shape[1] + T + 2)  # over-alloc ok
    best_seq, best = None, -np.inf
    for cand in itertools.product(range(4), repeat=T):
        # canonical frozen form: after eos, only eos continuations exist
        if EOS in cand:
            cut = cand.index(EOS)
            if any(c != EOS for c in cand[cut:]):
                continue
            length = cut + 1
        else:
            length = T
        full = jnp.concatenate(
            [prompt, jnp.asarray([cand], jnp.int32)], axis=1)
        cache = init_cache(net, 1, full.shape[1])
        logits, _ = forward_cached(net, params, full, cache, 0)
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        total = sum(float(lp[0, prompt.shape[1] - 1 + i, cand[i]])
                    for i in range(length))   # frozen tail adds zero
        score = total / length
        if score > best:
            best, best_seq = score, cand
    assert tuple(np.asarray(toks)[0]) == best_seq


def test_decode_with_tp_sharded_params_matches_unsharded():
    """Distributed inference by sharding alone: the SAME compiled
    decode/beam programs run with TP-sharded (partition_dim) params on
    a data x model mesh — GSPMD propagates the shardings through the
    cache loop — and must produce identical tokens."""
    from singa_tpu.models.generate import beam_search
    from singa_tpu.parallel.mesh import make_mesh
    from singa_tpu.parallel.partition import param_shardings

    net, params = _net_and_params(False)
    prompt = jnp.asarray(
        np.random.default_rng(11).integers(0, VOCAB, (B, 5)), jnp.int32)
    base = np.asarray(generate(net, params, prompt, 6))
    bb, bs = beam_search(net, params, prompt, 6, num_beams=4)

    mesh = make_mesh(jax.devices(), data=2, model=4)
    sh = param_shardings(mesh, net)
    # guard against vacuity: the config must actually partition params
    assert any(not s.is_fully_replicated for s in sh.values())
    sp = {k: jax.device_put(v, sh[k]) for k, v in params.items()}
    np.testing.assert_array_equal(np.asarray(generate(net, sp, prompt, 6)),
                                  base)
    tb, ts = beam_search(net, sp, prompt, 6, num_beams=4)
    np.testing.assert_array_equal(np.asarray(tb), np.asarray(bb))
    np.testing.assert_allclose(np.asarray(ts), np.asarray(bs),
                               rtol=1e-4, atol=1e-4)

    # and DP: prompts sharded over the data axis compose with the
    # TP-sharded params in the same programs
    from jax.sharding import NamedSharding, PartitionSpec as P
    dprompt = jax.device_put(prompt, NamedSharding(mesh, P("data")))
    np.testing.assert_array_equal(
        np.asarray(generate(net, sp, dprompt, 6)), base)
