"""Updater/LR-schedule/init golden tests vs NumPy oracles implementing
the reference math (updater.cc, param.cc:61-99)."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from singa_tpu.config.schema import ParamConfig, UpdaterConfig
from singa_tpu.core.init import init_param
from singa_tpu.core.updater import Multipliers, Updater, learning_rate


def _lr(method, step, **kw):
    cfg = UpdaterConfig(type="kSGD", learning_rate_change_method=method, **kw)
    return float(learning_rate(cfg, step))


def test_lr_schedules_reference_formulas():
    # kFixed
    assert _lr("kFixed", 7, base_learning_rate=0.1) == pytest.approx(0.1)
    # kLinear: (1-r)*base + r*final, r = step/freq
    assert _lr("kLinear", 5, base_learning_rate=1.0, final_learning_rate=0.0,
               learning_rate_change_frequency=10) == pytest.approx(0.5)
    # kExponential: base / 2^(step/freq)
    assert _lr("kExponential", 10, base_learning_rate=0.4,
               final_learning_rate=0.2,
               learning_rate_change_frequency=5) == pytest.approx(0.1)
    # kInverse_t: base / (1 + step/final)
    assert _lr("kInverse_t", 4, base_learning_rate=0.4,
               final_learning_rate=0.2) == pytest.approx(0.4 / 21.0)
    # kInverse: base * (1+gamma*step)^-pow    (conv.conf uses this)
    assert _lr("kInverse", 100, base_learning_rate=0.01, gamma=0.0001,
               pow=0.75) == pytest.approx(0.01 * (1.01) ** -0.75)
    # kStep: base * gamma^(step // freq) — integer division (updater.cc:41-45)
    assert _lr("kStep", 119, base_learning_rate=0.001, gamma=0.997,
               learning_rate_change_frequency=60) == pytest.approx(
                   0.001 * 0.997 ** 1)
    assert _lr("kStep", 120, base_learning_rate=0.001, gamma=0.997,
               learning_rate_change_frequency=60) == pytest.approx(
                   0.001 * 0.997 ** 2)


def _run_updater(utype, steps=3, **kw):
    cfg = UpdaterConfig(type=utype, base_learning_rate=kw.pop("lr", 0.1), **kw)
    up = Updater(cfg)
    params = {"w": jnp.array([1.0, -2.0, 3.0])}
    grads = {"w": jnp.array([0.5, 0.25, -1.0])}
    state = up.init(params)
    out = []
    for step in range(steps):
        params, state = up.update(step, grads, params, state)
        out.append(np.asarray(params["w"]).copy())
    return out, cfg


def test_sgd_momentum_reference():
    out, cfg = _run_updater("kSGD", momentum=0.9, weight_decay=0.01, lr=0.1)
    p = np.array([1.0, -2.0, 3.0])
    g0 = np.array([0.5, 0.25, -1.0])
    h = np.zeros(3)
    for step in range(3):
        g = g0 + p * 0.01
        h = h * 0.9 + 0.1 * g
        p = p - h
        np.testing.assert_allclose(out[step], p, rtol=1e-6)


def test_nesterov_reference():
    out, _ = _run_updater("kNesterov", momentum=0.9, lr=0.1)
    p = np.array([1.0, -2.0, 3.0])
    g = np.array([0.5, 0.25, -1.0])
    h = np.zeros(3)
    for step in range(3):
        h_old = h.copy()
        h = h * 0.9 + 0.1 * g
        p = p - (h * 1.9 - h_old * 0.9)
        np.testing.assert_allclose(out[step], p, rtol=1e-6)


def test_adagrad_reference_wd_after_history():
    """wd is folded into grad AFTER history accumulates the raw square
    (updater.cc:121-127)."""
    out, _ = _run_updater("kAdaGrad", weight_decay=0.1, lr=0.1)
    p = np.array([1.0, -2.0, 3.0])
    g0 = np.array([0.5, 0.25, -1.0])
    h = np.zeros(3)
    for step in range(3):
        h = h + g0 ** 2
        g = g0 + p * 0.1
        p = p - 0.1 * g / np.sqrt(h + 1e-7)
        np.testing.assert_allclose(out[step], p, rtol=1e-5)


def test_rmsprop_reference():
    out, _ = _run_updater("kRMSProp", rho=0.9, lr=0.1)
    p = np.array([1.0, -2.0, 3.0])
    g = np.array([0.5, 0.25, -1.0])
    h = np.zeros(3)
    for step in range(3):
        h = h * 0.9 + 0.1 * g ** 2
        p = p - 0.1 * g / np.sqrt(h + 1e-7)
        np.testing.assert_allclose(out[step], p, rtol=1e-5)


def test_adadelta_reference():
    out, _ = _run_updater("kAdaDelta", rho=0.9, lr=0.0)
    p = np.array([1.0, -2.0, 3.0])
    g = np.array([0.5, 0.25, -1.0])
    h = np.zeros(3)
    u = np.zeros(3)
    for step in range(3):
        h = h * 0.9 + 0.1 * g ** 2
        tmp = g * np.sqrt(u + 1e-7) / np.sqrt(h + 1e-7)
        u = 0.9 * u + 0.1 * tmp ** 2
        p = p - tmp
        np.testing.assert_allclose(out[step], p, rtol=1e-5)


def test_lr_multiplier_applied():
    """conv.conf biases use learning_rate_multiplier: 2.0."""
    cfg = UpdaterConfig(type="kSGD", base_learning_rate=0.1)
    up = Updater(cfg)
    params = {"w": jnp.array([1.0]), "b": jnp.array([1.0])}
    grads = {"w": jnp.array([1.0]), "b": jnp.array([1.0])}
    mults = {"w": Multipliers(1.0, 1.0), "b": Multipliers(2.0, 1.0)}
    state = up.init(params)
    params, _ = up.update(0, grads, params, state, multipliers=mults)
    assert float(params["w"][0]) == pytest.approx(0.9)
    assert float(params["b"][0]) == pytest.approx(0.8)


def test_update_is_jittable():
    cfg = UpdaterConfig(type="kRMSProp", base_learning_rate=0.1)
    up = Updater(cfg)
    params = {"w": jnp.ones((4, 4))}
    state = up.init(params)

    @jax.jit
    def step_fn(step, params, state):
        grads = {"w": jnp.ones((4, 4)) * 0.1}
        return up.update(step, grads, params, state)

    p1, s1 = step_fn(0, params, state)
    p2, s2 = step_fn(1, p1, s1)
    assert not np.allclose(np.asarray(p2["w"]), np.asarray(p1["w"]))


# ---------------------------------------------------------------------------
# init methods (param.cc:61-99)


def test_init_constant():
    x = init_param(jax.random.PRNGKey(0),
                   ParamConfig(init_method="kConstant", value=0.25), (3, 2))
    np.testing.assert_allclose(np.asarray(x), 0.25)


def test_init_uniform_range_and_value_scale():
    cfg = ParamConfig(init_method="kUniform", low=-0.05, high=0.05, value=2.0)
    x = np.asarray(init_param(jax.random.PRNGKey(1), cfg, (2000,)))
    assert x.min() >= -0.1 and x.max() <= 0.1
    assert x.max() > 0.08  # scale actually applied


def test_init_uniform_sqrt_fanin():
    """kUniformSqrtFanIn: U(low,high) * value / sqrt(fan_in/3)
    (param.cc:74-78); conv.conf uses defaults low=-1, high=1, value=1."""
    cfg = ParamConfig(init_method="kUniformSqrtFanIn")
    fan_in = 75  # e.g. conv1: 1*5*5*3
    x = np.asarray(init_param(jax.random.PRNGKey(2), cfg, (500,), fan_in))
    bound = 1.0 / math.sqrt(fan_in / 3.0)
    assert abs(x).max() <= bound + 1e-6
    assert abs(x).max() > bound * 0.98


def test_init_uniform_sqrt_fanin_out():
    cfg = ParamConfig(init_method="kUniformSqrtFanInOut", low=-1, high=1)
    x = np.asarray(init_param(jax.random.PRNGKey(3), cfg, (30, 70)))
    bound = 1.0 / math.sqrt(100)
    assert abs(x).max() <= bound + 1e-6


def test_init_gaussian_variants():
    cfg = ParamConfig(init_method="kGaussain", mean=1.0, std=0.1)
    x = np.asarray(init_param(jax.random.PRNGKey(4), cfg, (5000,)))
    assert abs(x.mean() - 1.0) < 0.01
    cfg2 = ParamConfig(init_method="kGaussainSqrtFanIn", std=1.0)
    y = np.asarray(init_param(jax.random.PRNGKey(5), cfg2, (100, 50)))
    assert abs(y.std() - 0.1) < 0.01  # scaled by 1/sqrt(shape[0]=100)


def test_default_multipliers_hoisted_to_construction():
    """The default Multipliers pytree and the treedef must be derived
    once (init / first structure seen), not rebuilt on every update
    call — the update runs inside the scan body, so per-call tree
    construction was paid on every trace (ISSUE 2 satellite)."""
    cfg = UpdaterConfig(type="kSGD", base_learning_rate=0.1,
                        learning_rate_change_method="kFixed")
    up = Updater(cfg)
    params = {"w": jnp.ones((4,)), "b": jnp.zeros((2,))}
    state = up.init(params)
    treedef = jax.tree_util.tree_structure(params)
    assert treedef in up._default_mults          # pre-built at init
    cached = up._default_mults[treedef]
    grads = {"w": jnp.full((4,), 0.5), "b": jnp.full((2,), 0.25)}
    p1, s1 = up.update(0, grads, params, state)
    assert up._default_mults[treedef] is cached  # reused, not rebuilt
    # and the defaulted path matches an explicit all-ones multiplier tree
    mults = {"w": Multipliers(), "b": Multipliers()}
    p2, _ = up.update(0, grads, params, state, multipliers=mults)
    for k in params:
        np.testing.assert_array_equal(np.asarray(p1[k]), np.asarray(p2[k]))
    # a DIFFERENT structure (the CD path updates param subsets) still works
    sub_p = {"w": params["w"]}
    sub_s = {"history": {"w": state["history"]["w"]}}
    p3, _ = up.update(0, {"w": grads["w"]}, sub_p, sub_s)
    np.testing.assert_array_equal(np.asarray(p3["w"]), np.asarray(p1["w"]))
    assert len(up._default_mults) == 2
