"""Numeric-health sentinel tests (ISSUE 3): device-side probes fused
into the train step, host-side OK/SPIKE/NONFINITE/DIVERGED
classification, checkpoint verdict quarantine (`skip_unhealthy`
walk-back), Supervisor divergence rescue (rollback past the unhealthy
window, blame-batch skip, one-shot LR backoff), and poisoned-sync
rejection in the elastic tier.

The acceptance property: inject `nan` at `step.grad` after a good
checkpoint and the Supervisor restores the last *numerically good*
snapshot, applies the rescue policy, and the trajectory from the
rollback point is bit-identical to an uninterrupted run making the same
skip/LR decisions."""

import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from singa_tpu.config.schema import UpdaterConfig, model_config_from_dict
from singa_tpu.core.supervisor import Supervisor, TrainingAborted
from singa_tpu.core.trainer import Trainer
from singa_tpu.data.synthetic import synthetic_image_batches
from singa_tpu.utils import checkpoint as ckpt_mod
from singa_tpu.utils.faults import Backoff, FaultSchedule, inject
from singa_tpu.utils.health import (DIVERGED, NONFINITE, OK, SPIKE,
                                    HealthMonitor, HealthSpec,
                                    NumericDivergence, delta_health)

pytestmark = [pytest.mark.faults, pytest.mark.health]

SHAPES = {"data": {"pixel": (28, 28), "label": ()}}
_NO_WAIT = Backoff(base=0.0, cap=0.0, jitter=0.0)


def _mlp_cfg(train_steps=20, ckpt_freq=4):
    return model_config_from_dict({
        "name": "health-mlp", "train_steps": train_steps,
        "checkpoint_frequency": ckpt_freq,
        "updater": {"type": "kSGD", "base_learning_rate": 0.01,
                    "learning_rate_change_method": "kFixed"},
        "neuralnet": {"layer": [
            {"name": "data", "type": "kShardData",
             "data_param": {"batchsize": 8}},
            {"name": "mnist", "type": "kMnistImage", "srclayers": "data",
             "mnist_param": {"norm_a": 255.0}},
            {"name": "label", "type": "kLabel", "srclayers": "data"},
            {"name": "ip1", "type": "kInnerProduct", "srclayers": "mnist",
             "inner_product_param": {"num_output": 16},
             "param": [{"name": "w1",
                        "init_method": "kUniformSqrtFanIn"},
                       {"name": "b1"}]},
            {"name": "ip2", "type": "kInnerProduct", "srclayers": "ip1",
             "inner_product_param": {"num_output": 10},
             "param": [{"name": "w2",
                        "init_method": "kUniformSqrtFanIn"},
                       {"name": "b2"}]},
            {"name": "loss", "type": "kSoftmaxLoss",
             "srclayers": ["ip2", "label"]}]}})


def _data_factory():
    return synthetic_image_batches(8, seed=3, stream_seed=104)


def _baseline(train_steps=20):
    tr = Trainer(_mlp_cfg(train_steps, ckpt_freq=0), SHAPES,
                 log_fn=lambda s: None, donate=False)
    p, o = tr.init(seed=0)
    return tr.run(p, o, _data_factory(), seed=0)[0]


# -- HealthSpec grammar ------------------------------------------------------
def test_health_spec_parse_grammar():
    s = HealthSpec.parse("grad_norm_max=1e4, spike_mad=8; patience=2,"
                         "blame_batches=3,lr_backoff=0.5")
    assert s.grad_norm_max == 1e4 and s.spike_mad == 8.0
    assert s.patience == 2 and s.blame_batches == 3
    assert s.lr_backoff == 0.5
    assert HealthSpec.parse(None) == HealthSpec()
    with pytest.raises(ValueError, match="bad health spec entry"):
        HealthSpec.parse("nope=1")
    with pytest.raises(ValueError, match="bad health spec value"):
        HealthSpec.parse("window=abc")


# -- monitor classification --------------------------------------------------
def test_monitor_classifies_nonfinite_spike_diverged():
    logs = []
    mon = HealthMonitor(HealthSpec(grad_norm_max=100.0, warmup=4,
                                   spike_mad=6, patience=2),
                        log_fn=logs.append)
    m = lambda loss, gn: {"loss": loss, "health/grad_norm": gn,  # noqa: E731
                          "health/param_norm": 1.0,
                          "health/update_ratio": 0.01}
    for s in range(6):   # warm the window with steady values
        assert mon.observe(s, m(1.0, 2.0)).status == OK
    assert mon.observe(6, m(float("nan"), 2.0)).status == NONFINITE
    assert mon.observe(7, m(1.0, 200.0)).status == DIVERGED  # hard cap
    v = mon.observe(8, m(1.0, 50.0))                         # MAD spike
    assert v.status == SPIKE and v.metric == "grad_norm"
    # second consecutive spike escalates (patience=2)
    assert mon.observe(9, m(1.0, 50.0)).status == DIVERGED
    assert any("SPIKE" in l for l in logs)
    # spikes never entered the rolling window
    assert max(mon._windows["grad_norm"]) == 2.0


def test_monitor_verdict_brackets_snapshots():
    mon = HealthMonitor(HealthSpec(warmup=2, spike_mad=4, patience=10),
                        log_fn=lambda s: None)
    m = lambda gn: {"loss": 1.0, "health/grad_norm": gn}  # noqa: E731
    for s in range(4):
        mon.observe(s, m(1.0))
    assert mon.snapshot_health()["verdict"] == OK and mon.ok_to_save()
    mon.observe(4, m(100.0))   # SPIKE taints the window
    assert mon.snapshot_health()["verdict"] == SPIKE
    assert mon.ok_to_save()    # suspect still saves (marked)
    mon.mark_snapshot()
    assert mon.snapshot_health()["verdict"] == OK
    mon.observe(5, m(float("inf")))
    assert not mon.ok_to_save()  # fatal refuses the save


# -- device-side probes ------------------------------------------------------
def test_probes_ride_metrics_and_leave_trajectory_bitwise():
    p_ref = _baseline(train_steps=6)
    seen = {}
    mon = HealthMonitor(HealthSpec(), log_fn=lambda s: None)
    tr = Trainer(_mlp_cfg(6, ckpt_freq=0), SHAPES,
                 log_fn=lambda s: None, donate=False, health=mon)
    p, o = tr.init(seed=0)
    p_h, _, _ = tr.run(p, o, _data_factory(), seed=0,
                       hooks=[lambda s, m: seen.setdefault(s, m)])
    for k in p_ref:   # probes are read-only: params bit-identical
        np.testing.assert_array_equal(np.asarray(p_ref[k]),
                                      np.asarray(p_h[k]), err_msg=k)
    for key in ("health/grad_norm", "health/param_norm",
                "health/update_ratio"):
        assert key in seen[0] and np.isfinite(float(seen[0][key]))
    assert mon.counts[OK] == 6


def test_nan_at_step_grad_raises_structured_divergence():
    mon = HealthMonitor(HealthSpec(), log_fn=lambda s: None)
    tr = Trainer(_mlp_cfg(8, ckpt_freq=0), SHAPES,
                 log_fn=lambda s: None, donate=False, health=mon)
    p, o = tr.init(seed=0)
    with inject(FaultSchedule.parse("step.grad@3:nan")):
        with pytest.raises(NumericDivergence) as ei:
            tr.run(p, o, _data_factory(), seed=0)
    e = ei.value
    assert (e.step, e.status, e.metric) == (3, NONFINITE, "grad_norm")


# -- checkpoint quarantine ---------------------------------------------------
def test_skip_unhealthy_restore_walks_past_bad_verdict(tmp_path,
                                                       monkeypatch):
    monkeypatch.setattr(ckpt_mod, "_HAVE_ORBAX", False)
    mgr = ckpt_mod.CheckpointManager(str(tmp_path), log_fn=lambda s: None)
    state = lambda v: ({"w": np.full(3, v)}, {"history": {"w": np.zeros(3)}})  # noqa: E731
    mgr.save(4, *state(4.0), health={"verdict": "ok"})
    mgr.save(8, *state(8.0), health={"verdict": "spike",
                                     "grad_norm": 1e5})
    mgr.save(12, *state(12.0), health={"verdict": "diverged"})
    # default restore: latest readable wins regardless of verdict
    _, _, step = mgr.restore()
    assert step == 12
    logs = []
    mgr.log = logs.append
    params, _, step = mgr.restore(skip_unhealthy=True)
    assert step == 4
    np.testing.assert_allclose(params["w"], 4.0)
    assert sum("health verdict" in l for l in logs) == 2
    assert mgr.health_verdict(8) == "spike"
    assert mgr.health_verdict(4) == "ok"


def test_trainer_refuses_checkpoint_of_fatal_window(tmp_path,
                                                    monkeypatch):
    monkeypatch.setattr(ckpt_mod, "_HAVE_ORBAX", False)
    logs = []
    mon = HealthMonitor(HealthSpec(), log_fn=lambda s: None)
    tr = Trainer(_mlp_cfg(4, ckpt_freq=2), SHAPES, log_fn=logs.append,
                 donate=False, health=mon)
    mon.observe(0, {"loss": float("nan")})   # poison the window
    ckpt = ckpt_mod.CheckpointManager(str(tmp_path),
                                      log_fn=lambda s: None)
    p, o = tr.init(seed=0)
    assert tr._save_checkpoint(ckpt, 2, p, o) is False
    assert ckpt.latest_step() is None
    assert any("refusing checkpoint" in l for l in logs)


# -- Supervisor divergence rescue (the acceptance property) ------------------
def test_supervisor_rescue_rolls_back_past_unhealthy_checkpoint(
        tmp_path, monkeypatch):
    """spike at step 9 taints the step-12 snapshot (saved with verdict
    "spike"); nan at step 13 is fatal.  The rescue must walk back PAST
    the tainted snapshot to step 8, replay (the one-shot faults do not
    re-fire), and land bit-identical to an uninterrupted run."""
    monkeypatch.setattr(ckpt_mod, "_HAVE_ORBAX", False)
    p_ref = _baseline()

    spec = HealthSpec(grad_norm_max=0.0, update_ratio_max=0.0,
                      spike_mad=8, patience=10)
    logs = []
    mon = HealthMonitor(spec, log_fn=logs.append)
    tr = Trainer(_mlp_cfg(), SHAPES, log_fn=logs.append, donate=False,
                 health=mon)
    sup = Supervisor(tr, str(tmp_path), max_restarts=0,
                     backoff=_NO_WAIT, log=logs.append)
    sched = FaultSchedule.parse("step.grad@9:spike,step.grad@13:nan")
    with inject(sched):
        p_sup, _, _ = sup.run(_data_factory, seed=0)
    assert [f.kind for f in sup.failures] == ["divergence"]
    assert sorted(f.site for f in sched.fired) == ["step.grad"] * 2
    assert any("verdict 'spike'; skipping" in l for l in logs), logs
    assert any("resumed from step 8" in l for l in logs), logs
    for k in p_ref:
        assert np.all(np.isfinite(np.asarray(p_sup[k]))), k
        np.testing.assert_array_equal(np.asarray(p_sup[k]),
                                      np.asarray(p_ref[k]), err_msg=k)


def test_supervisor_rescue_on_chunked_scan_loop(tmp_path, monkeypatch):
    monkeypatch.setattr(ckpt_mod, "_HAVE_ORBAX", False)
    p_ref = _baseline()
    mon = HealthMonitor(HealthSpec(), log_fn=lambda s: None)
    tr = Trainer(_mlp_cfg(), SHAPES, log_fn=lambda s: None,
                 donate=False, health=mon)
    sup = Supervisor(tr, str(tmp_path), max_restarts=0,
                     backoff=_NO_WAIT, log=lambda s: None)
    with inject(FaultSchedule.parse("step.grad@13:nan")):
        p_sup, _, _ = sup.run(_data_factory, seed=0, scan_chunk=5)
    assert [f.kind for f in sup.failures] == ["divergence"]
    for k in p_ref:
        np.testing.assert_array_equal(np.asarray(p_sup[k]),
                                      np.asarray(p_ref[k]), err_msg=k)


def test_supervisor_divergence_budget_is_separate(tmp_path,
                                                  monkeypatch):
    monkeypatch.setattr(ckpt_mod, "_HAVE_ORBAX", False)
    mon = HealthMonitor(HealthSpec(), log_fn=lambda s: None)
    tr = Trainer(_mlp_cfg(8, ckpt_freq=2), SHAPES,
                 log_fn=lambda s: None, donate=False, health=mon)
    sup = Supervisor(tr, str(tmp_path), max_restarts=5,
                     max_divergences=1, backoff=_NO_WAIT,
                     log=lambda s: None)
    # two separate nan injections (visit 3 = step 3 of attempt 1;
    # after the step-2 restore, visit 6 = step 4 of attempt 2): the
    # second blows the divergence budget even though the error budget
    # (5) has plenty left
    sched = FaultSchedule.parse("step.grad@3:nan,step.grad@6:nan")
    with inject(sched), pytest.raises(TrainingAborted) as ei:
        sup.run(_data_factory, seed=0)
    assert "numeric divergences exceed" in str(ei.value)
    assert [f.kind for f in ei.value.failures] == ["divergence"] * 2


def test_supervisor_blame_batches_and_lr_backoff_deterministic(
        tmp_path, monkeypatch):
    """The rescue policy's trajectory is reproducible: an uninterrupted
    run that makes the SAME decisions (skip the blamed batches from the
    rollback point, train with the backed-off LR) lands bit-identical."""
    monkeypatch.setattr(ckpt_mod, "_HAVE_ORBAX", False)
    mon = HealthMonitor(HealthSpec(), log_fn=lambda s: None)
    tr = Trainer(_mlp_cfg(), SHAPES, log_fn=lambda s: None,
                 donate=False, health=mon)
    logs = []
    sup = Supervisor(tr, str(tmp_path), max_restarts=0,
                     backoff=_NO_WAIT, blame_batches=2, lr_backoff=0.5,
                     log=logs.append)
    with inject(FaultSchedule.parse("step.grad@13:nan")):
        p_sup, _, _ = sup.run(_data_factory, seed=0)
    assert tr.updater.lr_scale == 0.5
    assert any("blaming batches [13, 15)" in l for l in logs), logs

    # manual baseline: plain run to the rollback point (step 12), then
    # continue with lr*0.5 and stream indices 13,14 dropped
    tr_a = Trainer(_mlp_cfg(12, ckpt_freq=0), SHAPES,
                   log_fn=lambda s: None, donate=False)
    p, o = tr_a.init(seed=0)
    p12, o12, _ = tr_a.run(p, o, _data_factory(), seed=0)
    tr_b = Trainer(_mlp_cfg(20, ckpt_freq=0), SHAPES,
                   log_fn=lambda s: None, donate=False)
    tr_b.updater.lr_scale = 0.5
    tr_b._build_steps(False)

    def skipping():
        for i, b in enumerate(_data_factory()):
            if i not in (13, 14):
                yield b
    it = skipping()
    for _ in range(12):
        next(it)
    p_base, _, _ = tr_b.run(p12, o12, it, seed=0, start_step=12)
    for k in p_base:
        np.testing.assert_array_equal(np.asarray(p_sup[k]),
                                      np.asarray(p_base[k]), err_msg=k)


# -- poisoned-sync rejection -------------------------------------------------
def _elastic_ctl(**kw):
    from singa_tpu.parallel.elastic import ElasticController
    cfg = UpdaterConfig(type="kSGD", base_learning_rate=0.1,
                        param_type="Elastic", moving_rate=0.5,
                        sync_frequency=1, warmup_steps=0)
    return ElasticController(cfg, log_fn=lambda s: None,
                             sync_backoff=_NO_WAIT, **kw)


def test_poisoned_sync_delta_rejected_center_untouched():
    logs = []
    ctl = _elastic_ctl()
    ctl.log = logs.append
    params = ctl.maybe_sync(0, {"w": jnp.full((4,), 2.0)})  # center init
    center_before = np.asarray(ctl.center["w"]).copy()
    with inject(FaultSchedule.parse("sync.delta@0:nan")):
        out = ctl.maybe_sync(1, {"w": jnp.full((4,), 5.0)})
    assert ctl.poisoned_rounds == 1
    # degraded like SyncRoundSkipped: replica keeps its params, the
    # center never saw the NaNs
    np.testing.assert_allclose(np.asarray(out["w"]), 5.0)
    np.testing.assert_allclose(np.asarray(ctl.center["w"]),
                               center_before)
    assert any("poisoned" in l for l in logs)


def test_sync_delta_norm_cap_rejects_finite_explosion():
    ctl = _elastic_ctl(delta_max_norm=1.0)
    ctl.maybe_sync(0, {"w": jnp.zeros(4)})
    out = ctl.maybe_sync(1, {"w": jnp.full((4,), 100.0)})  # |Δ| = 200
    assert ctl.poisoned_rounds == 1
    np.testing.assert_allclose(np.asarray(ctl.center["w"]), 0.0)
    np.testing.assert_allclose(np.asarray(out["w"]), 100.0)


def test_nonfinite_params_never_seed_the_center():
    ctl = _elastic_ctl()
    out = ctl.maybe_sync(0, {"w": jnp.full((4,), float("nan"))})
    assert ctl.center is None and ctl.poisoned_rounds == 1
    assert np.all(np.isnan(np.asarray(out["w"])))


def test_spike_kind_poisons_but_validation_off_lets_it_through():
    """The hazard the validation exists for: with validate=False a
    poisoned delta corrupts the center."""
    ctl = _elastic_ctl(validate=False)
    ctl.maybe_sync(0, {"w": jnp.zeros(4)})
    with inject(FaultSchedule.parse("sync.delta@0:nan")):
        ctl.maybe_sync(1, {"w": jnp.full((4,), 5.0)})
    assert np.all(np.isnan(np.asarray(ctl.center["w"])))
    assert ctl.poisoned_rounds == 0


def test_rng_fallback_matches_replicaset_fold_in_scheme():
    cfg = UpdaterConfig(type="kSGD", base_learning_rate=0.1,
                        param_type="RandomSync", sync_frequency=1,
                        warmup_steps=0)
    from singa_tpu.parallel.elastic import ElasticController
    mk = lambda: ElasticController(cfg, log_fn=lambda s: None,  # noqa: E731
                                   seed=7, group=1)
    c1, c2 = mk(), mk()
    for c in (c1, c2):
        c.init({"w": jnp.zeros(100, jnp.float32)})
        c.snapshot = {"w": jnp.zeros(100, jnp.float32)}
        c.sample_ratio = 0.5
    p = {"w": jnp.arange(100, dtype=jnp.float32)}
    base = jax.random.PRNGKey(7 ^ 0xA57)
    explicit = jax.random.fold_in(jax.random.fold_in(base, 3), 1)
    o1 = c1.maybe_sync(3, p)                 # fallback derivation
    o2 = c2.maybe_sync(3, p, rng=explicit)   # the contract's rng
    np.testing.assert_array_equal(np.asarray(o1["w"]),
                                  np.asarray(o2["w"]))


def test_replica_set_quarantines_repeat_offender():
    import sys
    sys.path.insert(0, "tests")
    from test_elastic import _mlp_cfg as elastic_cfg

    from singa_tpu.parallel.elastic import ReplicaSet
    cfg = elastic_cfg(moving_rate=0.9, sync_frequency=1, warmup=0,
                      steps=0)
    logs = []
    tr = Trainer(cfg, SHAPES, log_fn=logs.append, donate=False)
    rs = ReplicaSet(tr, ngroups=2, seed=0, quarantine_after=3)
    iters = [synthetic_image_batches(32, seed=11, stream_seed=60 + g)
             for g in range(2)]
    # round-robin visits: step 0 -> g0 seeds the center (no visit),
    # g1 visit 0; then g0/g1 alternate — visits 0,2,4 are replica 1
    sched = FaultSchedule.parse(
        "sync.delta@0:nan,sync.delta@2:nan,sync.delta@4:nan")
    with inject(sched):
        center, hist = rs.run(iters, steps=6, seed=0)
    assert rs.replicas[1]["quarantined"] and rs.controllers[1].poisoned_rounds == 3
    assert not rs.replicas[0]["quarantined"]
    assert len(hist[1]) < len(hist[0])      # it stopped training
    for v in center.values():               # center stayed clean
        assert np.all(np.isfinite(np.asarray(v)))
    assert any("quarantining replica 1" in l for l in logs)


def test_distributed_sync_commits_atomically_and_rejects_poison():
    """Single-process DistributedReplicaSet: (a) a failure mid-exchange
    leaves params/snapshot/center ALL unchanged (the torn-state fix —
    previously a crash between the three assignments left the snapshot
    ahead of the params); (b) a poisoned contribution is rejected with
    `poisoned_rounds` counted and no state change."""
    import sys
    sys.path.insert(0, "tests")
    from test_elastic import _mlp_cfg as elastic_cfg

    from singa_tpu.parallel.elastic import DistributedReplicaSet
    cfg = elastic_cfg(moving_rate=0.0, sync_frequency=1, warmup=0,
                      steps=0, param_type="RandomSync")
    tr = Trainer(cfg, SHAPES, log_fn=lambda s: None, donate=False)
    drs = DistributedReplicaSet(tr, seed=0)
    rng = jax.random.PRNGKey(0)
    assert drs._sync(0, rng) and drs._sync(1, rng)

    def snap():
        return ({k: np.asarray(v).copy() for k, v in drs.params.items()},
                {k: np.asarray(v).copy()
                 for k, v in drs.snapshot.items()},
                {k: np.asarray(v).copy()
                 for k, v in drs._replicated(drs._center_global).items()})

    before = snap()
    exchange = drs._exchange
    drs._exchange = lambda *a, **k: (_ for _ in ()).throw(
        RuntimeError("mid-sync failure"))
    with pytest.raises(RuntimeError, match="mid-sync"):
        drs._sync(2, rng)
    after = snap()
    for b, a in zip(before, after):
        for k in b:
            np.testing.assert_array_equal(a[k], b[k], err_msg=k)

    drs._exchange = exchange
    with inject(FaultSchedule.parse("sync.delta@0:nan")):
        assert drs._sync(3, rng) is False
    assert drs.poisoned_rounds == 1
    after = snap()
    for b, a in zip(before, after):
        for k in b:
            np.testing.assert_array_equal(a[k], b[k], err_msg=k)


# -- delta_health helper -----------------------------------------------------
def test_delta_health_finite_and_norm():
    ok, norm = delta_health({"w": jnp.ones(4)}, {"w": jnp.zeros(4)})
    assert ok and norm == pytest.approx(2.0)
    ok, norm = delta_health({"w": jnp.array([1.0, float("nan")])})
    assert not ok
    ok, _ = delta_health({"w": jnp.full(4, 10.0)}, max_norm=1.0)
    assert not ok
