"""Closed-loop train-and-serve pipeline (singa_tpu/core/pipeline.py):
the trainer publishes checkpoints into a workspace the serving fleet
promotes out of, concurrently.

Correctness anchors:
  * a checkpoint poll racing a LIVE writer (mid-rename, half-written
    MANIFEST.json) reads as "no change" — counted `torn_polls`, never
    an exception, never a reload of a torn step;
  * a DIVERGED step is never served by more than the canary: the
    manifest-verdict gate rolls it back, and on a cold start the
    canary is restored to fresh-init params (step -1), not left on
    the bad step;
  * cold start → first publish promotes WITHOUT an engine restart —
    the rollout must not pre-capture the fingerprint at start()
    (a save landing between engine load and rollout start would be
    invisible forever: the fleet-pinned-at--1 race);
  * under continuous client load with a trainer restart mid-run,
    every blessed checkpoint reaches traffic within bounded lag and
    no response ever comes from below the promoted step.

Cost control: rollout/controller logic is exercised through stub
handles and fake fleets (ticks driven explicitly); exactly one test
runs the real closed loop (tiny LM, 2 real engines, supervised
trainer with an injected preemption)."""

import os
import threading
import time

import numpy as np
import pytest

from singa_tpu.core.pipeline import PipelineController, PipelineSpec
from singa_tpu.serve import RolloutController, RolloutSpec, Router, RouterSpec
from singa_tpu.utils.checkpoint import CheckpointManager
from singa_tpu.utils.faults import FaultSchedule, inject

from test_fleet import StubHandle, _net_and_params, _save

pytestmark = pytest.mark.pipeline

VOCAB, SEQ = 64, 16
SHAPES = {"data": {"input": (SEQ,), "target": (SEQ,)}}


def _params():
    return {"w": np.arange(16, dtype=np.float32).reshape(4, 4)}


# -- spec grammar ------------------------------------------------------------

def test_pipeline_spec_parse_grammar():
    s = PipelineSpec.parse("lag_alarm_s=5.5,join_s=120;seed=3")
    assert s.lag_alarm_s == 5.5 and s.join_s == 120.0 and s.seed == 3
    assert PipelineSpec.parse(None) == PipelineSpec()
    assert PipelineSpec.parse("") == PipelineSpec()
    with pytest.raises(ValueError, match="unknown key"):
        PipelineSpec.parse("bogus=1")
    with pytest.raises(ValueError):
        PipelineSpec.parse("lag_alarm_s=0")


# -- torn-poll hardening (satellite: fingerprint vs a live writer) -----------

def test_fingerprint_torn_manifest_reads_as_no_change(tmp_path):
    """A half-written MANIFEST.json (non-atomic writer, cross-fs
    rename) must read as 'no change': the previous fingerprint comes
    back, `torn_polls` counts it, nothing raises."""
    mgr = CheckpointManager(str(tmp_path), log_fn=lambda s: None)
    mgr.save(1, _params(), {"t": np.zeros(())},
             health={"verdict": "ok"})
    good = mgr.fingerprint()
    assert mgr.torn_polls == 0
    man = os.path.join(str(tmp_path), "checkpoints", "MANIFEST.json")
    with open(man) as f:
        full = f.read()
    with open(man, "w") as f:
        f.write(full[: len(full) // 2])     # torn mid-write
    torn = mgr.fingerprint()
    assert torn == good                     # the cached last-good fp
    assert mgr.torn_polls == 1
    with open(man, "w") as f:               # writer finishes
        f.write(full)
    healed = mgr.fingerprint()
    assert healed[0] == good[0] and mgr.torn_polls == 1


def test_fingerprint_never_raises_against_live_writer(tmp_path):
    """Regression (the satellite's racing test): a real save loop in
    one thread, a fingerprint/latest/verdict poll loop in another —
    the reader must never see an exception and every completed
    observation must be of a fully-written step."""
    writer = CheckpointManager(str(tmp_path), log_fn=lambda s: None)
    reader = CheckpointManager(str(tmp_path), log_fn=lambda s: None)
    errors, seen = [], []
    stop = threading.Event()

    def poll():
        try:
            while not stop.is_set():
                steps, _ = reader.fingerprint()
                if steps:
                    step = max(steps)
                    # a visible step must read as classified-or-gone
                    # (GC may delete it between the two reads), never
                    # as an exception or a half-written verdict
                    assert reader.health_verdict(step) in (None, "ok")
                    seen.append(step)
        except Exception as e:  # noqa: BLE001 — the regression
            errors.append(e)

    t = threading.Thread(target=poll, daemon=True)
    t.start()
    try:
        for step in range(1, 13):
            writer.save(step, _params(), {"t": np.zeros(())},
                        health={"verdict": "ok"})
    finally:
        stop.set()
        t.join(10.0)
    assert not errors, errors
    assert seen and max(seen) >= 1
    # monotonic observation: polls never time-travel backwards past a
    # step they already saw (max_to_keep GC deletes OLD steps only)
    assert all(b >= a for a, b in zip(seen, seen[1:])), seen[:50]


def test_engine_poll_reload_skips_torn_manifest(tmp_path):
    """InferenceEngine.poll_reload against a torn manifest: 'unchanged'
    + a counted stats torn_poll — never an exception, never a reload
    of the torn state; the next clean poll reloads normally."""
    from singa_tpu.serve import InferenceEngine, ServeSpec

    net, params = _net_and_params()
    mgr = CheckpointManager(str(tmp_path), log_fn=lambda s: None)
    _save(mgr, 1, params)
    eng = InferenceEngine(net, ServeSpec(), workspace=str(tmp_path),
                          log_fn=lambda s: None)
    eng.load()
    assert eng.params_step == 1
    _save(mgr, 2, params)                   # a newer step lands...
    man = os.path.join(str(tmp_path), "checkpoints", "MANIFEST.json")
    with open(man) as f:
        full = f.read()                     # the completed 2-step manifest
    with open(man, "w") as f:
        f.write(full[: len(full) // 2])     # ...but the poll sees torn
    assert eng.poll_reload() == "unchanged"
    assert eng.params_step == 1
    assert eng.stats.torn_polls == 1
    with open(man, "w") as f:               # the writer's rename lands
        f.write(full)
    assert eng.poll_reload() == "reloaded"
    assert eng.params_step == 2 and eng.stats.torn_polls == 1


# -- cold-start races (satellite: the fleet-pinned-at--1 class) --------------

def _cold_rollout(tmp_path, n=2, **spec_kw):
    spec_kw.setdefault("poll_s", 0.05)
    spec_kw.setdefault("window_s", 0.2)
    spec_kw.setdefault("min_requests", 1)
    stubs = [StubHandle(f"e{i}", step=-1) for i in range(n)]
    router = Router(stubs, spec=RouterSpec(), log_fn=lambda s: None)
    router.probe_all()
    ctrl = RolloutController(router, str(tmp_path),
                             spec=RolloutSpec(**spec_kw),
                             log_fn=lambda s: None)
    return ctrl, stubs


def test_cold_start_first_publish_promotes_without_restart(tmp_path):
    """A checkpoint that lands BEFORE rollout.start() must still be
    noticed (start() must not pre-capture the fingerprint) and the
    first blessed step must promote from a -1 cold start with no
    engine restart."""
    ctrl, stubs = _cold_rollout(tmp_path)
    _, params = _net_and_params()
    mgr = CheckpointManager(str(tmp_path), log_fn=lambda s: None)
    _save(mgr, 1, params)                   # lands before start()
    ctrl.start(-1)
    ctrl.stop()                             # keep ticks hand-driven
    ctrl.tick()                             # OBSERVE: sees step 1
    assert ctrl.state == "CANARY" and ctrl.target_step == 1
    canary = next(s for s in stubs if s.name == ctrl.canary)
    canary.served += 3                      # canary traffic
    ctrl._deadline = time.monotonic() - 1.0
    ctrl.tick()                             # evaluate -> promote
    assert ctrl.state == "OBSERVE" and ctrl.pinned_step == 1
    assert ctrl.promotions == 1 and ctrl.rollbacks == 0
    assert all(s.step == 1 for s in stubs)


def test_cold_start_rejected_first_checkpoint_restores_fresh_init(
        tmp_path):
    """DIVERGED-never-ships, cold-start edition: the FIRST checkpoint
    carries a bad manifest verdict — the canary must be rolled back to
    fresh-init params (reload(step=-1)), and no second engine may ever
    touch the bad step."""
    ctrl, stubs = _cold_rollout(tmp_path)
    _, params = _net_and_params()
    mgr = CheckpointManager(str(tmp_path), log_fn=lambda s: None)
    _save(mgr, 2, params, verdict="DIVERGED")
    ctrl.pinned_step, ctrl._fp = -1, None   # start() without the thread
    ctrl.tick()
    assert ctrl.state == "CANARY"
    canary = next(s for s in stubs if s.name == ctrl.canary)
    others = [s for s in stubs if s is not canary]
    assert canary.step == 2                 # exactly one engine on it
    assert all(s.step == -1 for s in others)
    canary.served += 3
    ctrl._deadline = time.monotonic() - 1.0
    ctrl.tick()                             # evaluate -> ROLLBACK
    assert ctrl.rollbacks == 1 and ctrl.promotions == 0
    assert canary.step == -1                # back on fresh-init params
    assert canary.reloads[-1] == -1
    for s in others:
        assert 2 not in s.reloads           # the bad step never spread
    # the rejected fingerprint is remembered: no canary ping-pong
    ctrl.tick()
    assert ctrl.state == "OBSERVE" and ctrl.canaries == 1


def test_engine_reload_to_fresh_init(tmp_path):
    """The engine half of the cold-start rollback: reload(step=-1)
    restores the constructor's fresh-init params."""
    from singa_tpu.serve import InferenceEngine, ServeSpec

    net, params = _net_and_params()
    mgr = CheckpointManager(str(tmp_path), log_fn=lambda s: None)
    _save(mgr, 3, params)
    eng = InferenceEngine(net, ServeSpec(), workspace=str(tmp_path),
                          params=params, log_fn=lambda s: None)
    eng.load()
    assert eng.params_step == 3
    assert eng.reload_to(-1) == "reloaded"
    assert eng.params_step == -1 and eng.params is not None
    assert eng.reload_to(-1) == "unchanged"


def test_unfinalized_step_dir_is_invisible_and_resavable(tmp_path):
    """A writer SIGKILLed mid-orbax-save leaves a step directory with
    no metadata marker.  Readers must not list it — a canary must
    never target a half-written step — and a resumed trainer's re-save
    of that SAME step must actually land instead of being silently
    swallowed by orbax's step-already-exists skip (which would record
    a blessed verdict for a snapshot that does not exist)."""
    mgr = CheckpointManager(str(tmp_path), log_fn=lambda s: None)
    if mgr._mgr is None:
        pytest.skip("orbax-layout behavior")
    params = _params()
    _save(mgr, 1, params)
    os.makedirs(os.path.join(str(tmp_path), "checkpoints", "2"))
    reader = CheckpointManager(str(tmp_path), log_fn=lambda s: None)
    assert reader.available_steps() == [1]       # the wreck is invisible
    steps, _ = reader.fingerprint()
    assert steps == (1,)
    _save(mgr, 2, params)                        # replay over the wreck
    assert reader.available_steps() == [1, 2]
    restored = reader.restore(step=2)
    assert restored is not None and restored[2] == 2


def test_reload_to_current_step_recovers_without_disk(tmp_path):
    """Restoring a refused canary to a pinned step the checkpoint GC
    has since deleted must succeed from memory ("unchanged") and clear
    the stale-healthz flag — otherwise the engine reports degraded
    forever, the router drops it, and with every engine burned the
    fleet sheds all traffic."""
    import shutil

    from singa_tpu.serve import InferenceEngine, ServeSpec

    net, params = _net_and_params()
    mgr = CheckpointManager(str(tmp_path), log_fn=lambda s: None)
    _save(mgr, 3, params)
    eng = InferenceEngine(net, ServeSpec(), workspace=str(tmp_path),
                          params=params, log_fn=lambda s: None)
    eng.load()
    assert eng.params_step == 3
    # GC the snapshot out from under the engine, then hit it with a
    # canary reload nothing on disk can satisfy: refused + stale
    shutil.rmtree(os.path.join(str(tmp_path), "checkpoints", "3"),
                  ignore_errors=True)
    assert eng.reload_to(99) == "refused"
    assert eng.health()["status"] == "degraded"
    # the rollout's restore-to-pinned: the step it already serves
    assert eng.reload_to(3) == "unchanged"
    assert eng.params_step == 3
    assert eng.health()["status"] == "ok"


def test_canary_rollback_to_gcd_pinned_step_uses_memory(tmp_path):
    """A long-pinned fleet outlives its own snapshot: with the trainer
    saving every few seconds and max_to_keep=3, the pinned step is
    GC'd off disk while the fleet still serves it.  Rolling a canary
    back to the pinned step must then come from the engine's in-memory
    previous params — a refusal here marks the canary stale/unhealthy
    and (with every engine burned in turn) the fleet sheds all
    traffic."""
    import shutil

    from singa_tpu.serve import InferenceEngine, ServeSpec

    net, params = _net_and_params()
    mgr = CheckpointManager(str(tmp_path), log_fn=lambda s: None)
    _save(mgr, 3, params)
    eng = InferenceEngine(net, ServeSpec(), workspace=str(tmp_path),
                          params=params, log_fn=lambda s: None)
    eng.load()
    assert eng.params_step == 3          # the fleet's pinned step
    _save(mgr, 5, params)
    assert eng.reload_to(5) == "reloaded"  # canary to the new step
    # GC deletes the pinned snapshot while the canary window runs
    shutil.rmtree(os.path.join(str(tmp_path), "checkpoints", "3"),
                  ignore_errors=True)
    assert eng.reload_to(3) == "reloaded"  # rollback, from memory
    assert eng.params_step == 3
    assert eng.health()["status"] == "ok"


# -- PipelineController over fakes -------------------------------------------

class _FakeTrainer:
    on_checkpoint = None


class _FakeSupervisor:
    def __init__(self):
        self.trainer = _FakeTrainer()
        self.failures = []


class _FakeRollout:
    def __init__(self):
        self.pinned_step = -1


class _FakeRouter:
    def names(self):
        return ["e0"]


class _FakeFleet:
    def __init__(self):
        self.rollout = _FakeRollout()
        self.router = _FakeRouter()

    def snapshot(self):
        return {"rollout": {"pinned_step": self.rollout.pinned_step}}


def _controller(tmp_path, **spec_kw):
    sup, fleet = _FakeSupervisor(), _FakeFleet()
    ctl = PipelineController(sup, fleet, str(tmp_path),
                             spec=PipelineSpec(**spec_kw),
                             log_fn=lambda s: None)
    return ctl, sup, fleet


def test_controller_requires_a_rollout(tmp_path):
    fleet = _FakeFleet()
    fleet.rollout = None
    with pytest.raises(ValueError, match="rollout"):
        PipelineController(_FakeSupervisor(), fleet, str(tmp_path))


def test_publish_blessing_and_lag_gauge(tmp_path):
    """Only ok/None verdicts bless a step; the lag pair tracks blessed
    minus served and drains (recording the promote latency) when the
    rollout catches up."""
    ctl, sup, fleet = _controller(tmp_path)
    hook = sup.trainer.on_checkpoint
    assert hook is not None                 # controller wired it
    hook(4, "ok")
    hook(8, None)
    hook(12, "spike")                       # published, NOT blessed
    assert ctl.published == 3 and ctl.unblessed == 1
    lag = ctl.lag()
    assert lag["blessed_step"] == 8 and lag["served_step"] == -1
    assert lag["lag_steps"] == 9 and lag["lag_s"] >= 0.0
    fleet.rollout.pinned_step = 8           # the fleet catches up
    lag = ctl.lag()
    assert lag["lag_steps"] == 0 and lag["lag_s"] == 0.0
    assert len(ctl.promote_lags_s) == 2     # steps 4 and 8 drained
    snap = ctl.snapshot()
    assert snap["published"] == 3 and snap["blessed_step"] == 8
    assert snap["train"]["done"] is False   # never started


def test_publish_fault_degrades_to_counter(tmp_path):
    """An injected pipeline.publish fault must not lose the blessing
    (the rollout polls the fingerprint itself) and must never raise
    back into the trainer."""
    ctl, sup, _ = _controller(tmp_path)
    sched = FaultSchedule.parse("pipeline.publish@1:error", seed=0)
    with inject(sched):
        sup.trainer.on_checkpoint(5, "ok")
        sup.trainer.on_checkpoint(10, "ok")
    assert [f.site for f in sched.fired] == ["pipeline.publish"]
    assert ctl.publish_faults == 1
    assert ctl.published == 2 and ctl.last_blessed_step == 10


def test_lag_alarm_fires_once_per_blessed_step(tmp_path):
    logs = []
    sup, fleet = _FakeSupervisor(), _FakeFleet()
    ctl = PipelineController(sup, fleet, str(tmp_path),
                             spec=PipelineSpec(lag_alarm_s=0.01),
                             log_fn=logs.append)
    sup.trainer.on_checkpoint(3, "ok")
    time.sleep(0.05)
    ctl.lag()
    ctl.lag()                               # same blessed step: no spam
    alarms = [m for m in logs if "lag alarm" in m]
    assert len(alarms) == 1 and "step 3" in alarms[0]


def test_controller_metrics_registry(tmp_path):
    from singa_tpu.obs.metrics import MetricsRegistry

    ctl, sup, fleet = _controller(tmp_path)
    reg = MetricsRegistry()
    ctl.register_into(reg)
    sup.trainer.on_checkpoint(6, "ok")
    fleet.rollout.pinned_step = 6
    text = reg.render_prometheus()
    assert "singa_pipeline_blessed_step 6" in text
    assert "singa_pipeline_served_step 6" in text
    assert "singa_pipeline_lag_steps 0" in text
    assert "singa_pipeline_published_total 1" in text


# -- the one real closed loop ------------------------------------------------

def test_pipeline_blessed_reaches_traffic_with_trainer_restart(tmp_path):
    """The real loop, end to end on CPU: supervised tiny-LM trainer
    (with an injected mid-run preemption) + a 2-engine fleet, under
    continuous client load.  Every blessed checkpoint must reach
    traffic (lag drains to zero), no response may come from below the
    promoted step, and no client request may fail."""
    import jax

    from singa_tpu.core.supervisor import Supervisor
    from singa_tpu.core.trainer import Trainer
    from singa_tpu.models.transformer import (synthetic_token_batches,
                                              transformer_lm)
    from singa_tpu.serve import EngineFleet, ServeSpec
    from singa_tpu.utils.health import HealthMonitor, HealthSpec

    cfg = transformer_lm(vocab_size=VOCAB, num_layers=2, embed_dim=32,
                         num_heads=4, head_dim=8, seq_len=SEQ,
                         batchsize=4, train_steps=18)
    cfg.checkpoint_frequency = 6
    mon = HealthMonitor(HealthSpec(), log_fn=lambda s: None)
    tr = Trainer(cfg, SHAPES, log_fn=lambda s: None, donate=False,
                 health=mon)
    sup = Supervisor(tr, str(tmp_path), max_restarts=3,
                     log=lambda s: None)
    net = tr.test_net or tr.train_net
    fleet = EngineFleet.local(
        net, ServeSpec.parse("buckets=2x6,max_new_tokens=4,"
                             "batch_window_s=0.002"),
        2, workspace=str(tmp_path),
        params=net.init_params(jax.random.PRNGKey(0)),
        rollout_spec=RolloutSpec(poll_s=0.1, window_s=0.25,
                                 min_requests=1),
        log_fn=lambda s: None)
    ctl = PipelineController(sup, fleet, str(tmp_path),
                             spec=PipelineSpec(lag_alarm_s=60),
                             log_fn=lambda s: None)

    sched = FaultSchedule.parse("step.train@10:preempt", seed=0)
    prompt = np.arange(1, 6, dtype=np.int32)
    failures, responses = 0, []
    with inject(sched):
        ctl.start(lambda: synthetic_token_batches(4, SEQ, VOCAB,
                                                  seed=5), seed=0)
        try:
            deadline = time.monotonic() + 240.0
            while time.monotonic() < deadline:
                done = not ctl.train_running()
                lag = ctl.lag()
                pinned_before = fleet.rollout.pinned_step
                try:
                    out = ctl.generate(prompt)
                    responses.append((pinned_before, out["step"]))
                except Exception:  # noqa: BLE001 — counted, asserted 0
                    failures += 1
                if done and lag["lag_steps"] == 0 and \
                        lag["blessed_step"] >= 0:
                    break
            assert ctl.wait(timeout=30.0), "training never finished"
        finally:
            ctl.stop()

    assert ctl.train_error is None, ctl.train_error
    # the preemption fired and the supervisor absorbed it mid-pipeline
    assert [f.kind for f in sup.failures] == ["preemption"]
    assert failures == 0, f"{failures} client-visible failures"
    # every blessed checkpoint reached traffic: loop fully drained
    lag = ctl.lag()
    assert lag["blessed_step"] == 18
    assert lag["served_step"] == 18 and lag["lag_steps"] == 0
    assert fleet.rollout.promotions >= 1
    assert fleet.rollout.rollbacks == 0
    # no response ever came from below the promoted step (cold-start
    # fresh-init responses are step -1 == the pinned step then)
    for pinned_before, step in responses:
        assert step >= pinned_before, (pinned_before, step)
    # ...and only blessed steps (or fresh-init) were ever served
    served_steps = {s for _, s in responses}
    assert served_steps <= {-1, 6, 12, 18}, served_steps
    # bounded lag: blessed-to-served, as observed at poll time
    assert ctl.promote_lags_s and max(ctl.promote_lags_s) < 120.0
