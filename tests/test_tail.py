"""Tail-tolerant serving (singa_tpu/serve/qos.py + router hedging +
priority brownout): end-to-end deadlines, hedged dispatch under a
global retry budget, and priority-aware admission.

Correctness anchors:
  * a deadline is ONE absolute budget — dead-on-arrival requests are
    counted `expired_on_arrival` and never reach an engine, a retry
    never outlives the client's deadline, and an engine-reported
    DeadlineExpired is TERMINAL (no strike, no retry-elsewhere);
  * the hedge fires after the windowed-p95-derived delay, the first
    result wins, the loser is cancelled (`cancelled`, never `failed`),
    and every hedge token comes from the global `RetryBudget` —
    exhaustion degrades to single-shot, never to shed;
  * brownout sheds lowest class first with an honest per-class
    Retry-After that escalates over consecutive sheds and resets after
    a healthy dispatch (the regression this file pins).

Cost control: router paths run on scriptable stubs; the two real-cb
tests share one module-scoped tiny engine.  The full three-leg gate
(stalled straggler, brownout overload, DOA) is `bench.py
--tail-smoke`."""

import threading
import time

import jax
import numpy as np
import pytest

from singa_tpu.core.net import build_net
from singa_tpu.models.transformer import transformer_lm
from singa_tpu.serve import (Cancelled, DeadlineExpired,
                             InferenceEngine, InferenceServer,
                             Overloaded, Router, RouterSpec, ServeSpec,
                             qos)
from singa_tpu.serve.router import RouterStats
from singa_tpu.serve.stats import ServeStats
from singa_tpu.serve.traffic import Phase, TrafficGen, steady
from singa_tpu.utils.faults import FaultSchedule, inject

pytestmark = pytest.mark.tail

VOCAB, SEQ = 64, 16
SHAPES = {"data": {"input": (SEQ,), "target": (SEQ,)}}


# -- qos primitives ----------------------------------------------------------

def test_check_priority_normalizes_and_rejects():
    assert qos.check_priority(None) == "interactive"
    assert qos.check_priority(" Batch ") == "batch"
    assert qos.check_priority("BEST_EFFORT") == "best_effort"
    with pytest.raises(ValueError, match="unknown priority"):
        qos.check_priority("urgent")


def test_resolve_deadline_precedence():
    now = time.monotonic()
    # explicit deadline wins over any timeout
    assert qos.resolve_deadline(5.0, now + 1.0, 30.0) == now + 1.0
    # timeout-derived otherwise; default when timeout is None
    d = qos.resolve_deadline(2.0, None, 30.0)
    assert 1.5 < qos.remaining_s(d) <= 2.0
    d = qos.resolve_deadline(None, None, 30.0)
    assert 29.0 < qos.remaining_s(d) <= 30.0
    # a non-positive timeout means no deadline at all
    assert qos.resolve_deadline(0.0, None, 30.0) is None
    assert qos.remaining_s(None) is None


def test_deadline_header_roundtrip_reanchors():
    d = time.monotonic() + 1.0
    hdr = qos.deadline_to_header(d)
    assert hdr is not None and 0 < int(hdr) <= 1000
    back = qos.deadline_from_header(hdr)
    assert 0 < qos.remaining_s(back) <= 1.0
    # a DEAD deadline propagates as dead (0ms), never as no-deadline
    assert qos.deadline_to_header(time.monotonic() - 5.0) == "0"
    assert qos.remaining_s(qos.deadline_from_header("0")) <= 0
    assert qos.deadline_to_header(None) is None
    assert qos.deadline_from_header(None) is None
    assert qos.deadline_from_header("") is None


def test_retry_budget_caps_amplification():
    b = qos.RetryBudget(ratio=0.25, burst=2.0)
    assert b.spend() and b.spend()        # burst drains
    assert not b.spend()                  # then denied
    for _ in range(4):                    # 4 primaries earn 1 token
        b.earn()
    assert b.spend() and not b.spend()
    b.refund()                            # never-dispatched spend
    assert b.spend()
    for _ in range(1000):                 # earning caps at burst
        b.earn()
    assert b.tokens() == pytest.approx(2.0)


def test_class_backoffs_escalate_per_class_and_reset():
    cb = qos.ClassBackoffs(base=0.05, cap=2.0, seed=0)
    d_int = cb.shed_delay("interactive")
    d_be1 = cb.shed_delay("best_effort")
    # lower classes are told to stay away longer (factor 4x)
    assert d_be1 > d_int
    d_be2 = cb.shed_delay("best_effort")
    assert d_be2 > d_be1                  # ITS streak escalates...
    assert cb.streak("interactive") == 1  # ...without touching others
    cb.reset("best_effort")
    assert cb.streak("best_effort") == 0
    assert cb.shed_delay("best_effort") <= d_be2  # streak restarted


# -- scriptable router stubs -------------------------------------------------

class TailStub:
    """Engine-handle double with a QoS-aware `request`: scriptable
    latency and failure, records the kwargs each dispatch carried."""

    def __init__(self, name, delay_s=0.0, exc=None):
        self.name = name
        self.delay_s = delay_s
        self.exc = exc
        self.step = 1
        self.queue_depth = 0
        self.served = 0
        self.calls = []

    def probe(self):
        return {"ok": True, "status": "ok", "step": self.step,
                "queue_depth": self.queue_depth}

    def stats_snapshot(self):
        return {"completed": self.served}

    def request(self, mode, tokens, timeout=None, deadline=None,
                priority="interactive", cancel_event=None):
        self.calls.append({"deadline": deadline, "priority": priority,
                           "cancel_event": cancel_event})
        if self.delay_s:
            time.sleep(self.delay_s)
        if self.exc is not None:
            raise self.exc
        self.served += 1
        return {"tokens": [1, 2], "step": self.step}


def _router(stubs, **spec_kw):
    spec_kw.setdefault("request_timeout_s", 5.0)
    spec_kw.setdefault("hedge_max_s", 0.05)
    r = Router(stubs, spec=RouterSpec(**spec_kw),
               log_fn=lambda s: None)
    r.probe_all()
    return r


# -- deadlines through the router --------------------------------------------

def test_router_dead_on_arrival_never_reaches_an_engine():
    stubs = [TailStub("e0"), TailStub("e1")]
    r = _router(stubs)
    with pytest.raises(DeadlineExpired, match="dead on arrival"):
        r.route("generate", [1, 2], deadline=time.monotonic() - 0.1)
    assert r.stats.expired_on_arrival == 1
    assert r.stats.routed == 0            # never counted as traffic
    assert all(not s.calls for s in stubs)


def test_engine_deadline_is_terminal_not_a_strike():
    # satellite: an engine-reported DeadlineExpired must count
    # deadline_terminal — NOT failed, NOT a strike toward quarantine,
    # and never a retry on a sibling (that only blows the budget more)
    stubs = [TailStub("e0", exc=DeadlineExpired("expired in queue")),
             TailStub("e1")]
    r = _router(stubs, hedge="off", quarantine_after=1)
    with pytest.raises(DeadlineExpired):
        r.route("generate", [1, 2])
    assert r.stats.deadline_terminal == 1
    assert r.stats.failed == 0 and r.stats.retried == 0
    m = {m["name"]: m for m in r.members()}["e0"]
    assert m["strikes"] == 0 and not m["quarantined"]
    assert not stubs[1].calls             # no retry elsewhere


def test_retry_never_outlives_the_client_deadline():
    stubs = [TailStub("e0", delay_s=0.08, exc=RuntimeError("boom")),
             TailStub("e1", delay_s=0.08, exc=RuntimeError("boom"))]
    r = _router(stubs, hedge="off", quarantine_after=10)
    with pytest.raises(DeadlineExpired, match="deadline exhausted"):
        r.route("generate", [1, 2],
                deadline=time.monotonic() + 0.04)
    # the first attempt ate the budget; the retry was refused
    assert r.stats.deadline_terminal == 1
    assert len(stubs[0].calls) + len(stubs[1].calls) == 1


def test_deadline_and_priority_propagate_to_the_handle():
    stubs = [TailStub("e0")]
    r = _router(stubs)
    d = time.monotonic() + 3.0
    r.route("generate", [1, 2], deadline=d, priority="batch")
    call = stubs[0].calls[0]
    assert call["deadline"] == d and call["priority"] == "batch"


# -- hedged dispatch ---------------------------------------------------------

def test_hedge_beats_a_straggler_and_cancels_the_loser():
    slow = TailStub("e0", delay_s=0.6)
    fast = TailStub("e1")
    r = _router([slow, fast], hedge_min_s=0.01, hedge_max_s=0.05)
    t0 = time.monotonic()
    out = r.route("generate", [1, 2])
    dt = time.monotonic() - t0
    assert out["engine"] == "e1"          # the hedge won
    assert dt < 0.5                       # without waiting out e0
    assert r.stats.hedges == 1 and r.stats.hedge_wins == 1
    assert r.stats.completed == 1 and r.stats.failed == 0
    # the loser's cancel_event was set so it can stop mid-decode
    deadline = time.monotonic() + 2.0
    while not slow.calls[0]["cancel_event"].is_set():
        assert time.monotonic() < deadline
        time.sleep(0.005)


def test_hedge_budget_exhaustion_degrades_to_single_shot():
    slow = TailStub("e0", delay_s=0.15)
    fast = TailStub("e1")
    r = _router([slow, fast], hedge_min_s=0.01, hedge_max_s=0.03)
    r.retry_budget = qos.RetryBudget(ratio=0.0, burst=0.0)
    out = r.route("generate", [1, 2])
    assert out["engine"] == "e0"          # served, slowly, by the
    assert r.stats.hedges == 0            # primary: never shed because
    assert r.stats.budget_denied >= 1     # the budget ran dry
    assert r.stats.completed == 1 and r.stats.shed == 0


def test_serve_hedge_fault_abandons_only_the_hedge():
    slow = TailStub("e0", delay_s=0.15)
    fast = TailStub("e1")
    r = _router([slow, fast], hedge_min_s=0.01, hedge_max_s=0.03)
    with inject(FaultSchedule.parse("serve.hedge@0:error")):
        out = r.route("generate", [1, 2])
    assert out["engine"] == "e0"          # primary untouched
    assert r.stats.hedges == 0 and not fast.calls
    # the spent token was refunded: no dispatch ever happened
    assert r.retry_budget.tokens() == pytest.approx(
        r.retry_budget.burst)


def test_hedge_delay_tracks_windowed_p95():
    r = _router([TailStub("e0"), TailStub("e1")],
                hedge_min_s=0.05, hedge_max_s=1.0)
    assert r._hedge_delay() == pytest.approx(1.0)   # no history yet
    for _ in range(20):
        r.stats.observe_latency(0.2)
    r._hedge_cache_t = 0.0                # expire the 0.5s cache
    assert r._hedge_delay() == pytest.approx(0.2, abs=0.01)
    for _ in range(400):                  # p95 now in the fast mass
        r.stats.observe_latency(0.001)
    r._hedge_cache_t = 0.0
    assert r._hedge_delay() == pytest.approx(0.05)  # clamped at min


# -- priority brownout -------------------------------------------------------

def _pressurize(r, rate=1.0):
    """Pin the router's cached capacity-shed pressure reading."""
    r._pressure = rate
    r._pressure_t = time.monotonic() + 60.0   # cache never refreshes


def test_brownout_sheds_lowest_class_first():
    r = _router([TailStub("e0"), TailStub("e1")],
                brownout_shed_rate=0.1)
    _pressurize(r, 0.15)                  # over thr, under 3x thr
    with pytest.raises(Overloaded, match="brownout"):
        r.route("generate", [1, 2], priority="best_effort")
    r.route("generate", [1, 2], priority="batch")       # still admits
    r.route("generate", [1, 2], priority="interactive")
    _pressurize(r, 0.5)                   # over 3x thr: batch too
    with pytest.raises(Overloaded):
        r.route("generate", [1, 2], priority="batch")
    r.route("generate", [1, 2], priority="interactive")  # always
    assert r.stats.shed_best_effort == 1
    assert r.stats.shed_batch == 1 and r.stats.shed_interactive == 0
    assert r.stats.brownout_sheds == 2
    assert r.stats.completed == 3


def test_brownout_sheds_do_not_feed_the_pressure_signal():
    rs = RouterStats(window_s=30.0)
    for _ in range(10):
        rs.count("routed")
    rs.observe_shed("interactive", brownout=False)      # capacity
    rs.observe_shed("best_effort", brownout=True, n=5)  # brownout
    w = rs.windowed(5.0)
    assert w["shed_rate"] == pytest.approx(6 / 10)
    # only the capacity shed engages brownout — its own sheds feeding
    # back would latch it on forever
    assert w["capacity_shed_rate"] == pytest.approx(1 / 10)


def test_shed_retry_after_escalates_then_resets_after_dispatch():
    # the regression this PR pins: consecutive router sheds escalate
    # the honest Retry-After, and ONE healthy dispatch resets it
    r = _router([TailStub("e0")], brownout_shed_rate=0.1)
    _pressurize(r, 1.0)
    delays = []
    for _ in range(3):
        with pytest.raises(Overloaded) as ei:
            r.route("generate", [1, 2], priority="best_effort")
        delays.append(ei.value.retry_after)
    assert delays[0] < delays[1] < delays[2]  # escalating streak
    assert r._shed_backoffs.streak("best_effort") == 3
    _pressurize(r, 0.0)                   # pressure clears
    r.route("generate", [1, 2], priority="best_effort")
    assert r._shed_backoffs.streak("best_effort") == 0
    _pressurize(r, 1.0)
    with pytest.raises(Overloaded) as ei:
        r.route("generate", [1, 2], priority="best_effort")
    assert ei.value.retry_after <= delays[1]  # back near base


# -- stats: p99 + per-class views (satellite) --------------------------------

def test_router_stats_p99_and_class_views():
    rs = RouterStats(window_s=30.0)
    for ms in range(1, 101):
        rs.observe_latency(ms / 1e3,
                           "interactive" if ms <= 90 else "batch")
    w = rs.windowed(30.0)
    assert w["p99_latency_ms"] == pytest.approx(100.0, abs=0.01)
    assert w["p95_by_class"]["interactive"] < \
        w["p95_by_class"]["batch"]
    assert w["completed_by_class"] == {"interactive": 90, "batch": 10,
                                       "best_effort": 0}
    snap = rs.snapshot()
    assert snap["p99_latency_ms"] == pytest.approx(100.0, abs=0.01)
    assert snap["p99_latency_recent_ms"] == pytest.approx(100.0,
                                                          abs=0.01)


def test_serve_stats_p99_nearest_rank():
    ss = ServeStats()
    for ms in range(1, 101):
        ss.observe_latency(ms / 1e3)
    assert ss.snapshot()["p99_latency_ms"] == pytest.approx(100.0,
                                                            abs=0.01)
    assert ss.windowed(30.0)["p99_latency_ms"] == pytest.approx(
        100.0, abs=0.01)


# -- traffic harness priority mixes ------------------------------------------

def test_traffic_priority_mix_reports_per_class():
    seen = []

    def req(tokens, priority="interactive"):
        seen.append(priority)
        if priority == "best_effort":
            raise Overloaded("browned out", retry_after=0.01)

    gen = TrafficGen(req, seed=11, log_fn=lambda s: None)
    rep = gen.run([steady("mix", duration_s=0.4, rate_rps=60.0,
                          priorities=("interactive", "best_effort"),
                          priority_weights=(1.0, 1.0))],
                  drain_timeout_s=5.0)
    by = rep["totals"]["by_class"]
    assert set(seen) == {"interactive", "best_effort"}
    assert by["interactive"]["completed"] >= 1
    assert by["best_effort"]["shed"] >= 1
    assert by["best_effort"]["completed"] == 0
    with pytest.raises(ValueError, match="unknown priority"):
        Phase(name="bad", duration_s=1.0, rate_rps=1.0,
              priorities=("vip",))


def test_traffic_default_phase_keeps_bare_request_fn():
    # back-compat: a plain `lambda tokens:` target must keep working
    gen = TrafficGen(lambda tokens: None, seed=1,
                     log_fn=lambda s: None)
    rep = gen.run([steady("plain", duration_s=0.2, rate_rps=30.0)],
                  drain_timeout_s=5.0)
    assert rep["totals"]["failed"] == 0
    assert rep["totals"]["completed"] == rep["totals"]["offered"]


# -- real continuous-batching engine (shared; expensive) ---------------------

@pytest.fixture(scope="module")
def tail_served():
    cfg = transformer_lm(vocab_size=VOCAB, num_layers=2, embed_dim=32,
                         num_heads=4, head_dim=8, seq_len=SEQ,
                         batchsize=2)
    net = build_net(cfg, "kTest", SHAPES)
    params = net.init_params(jax.random.PRNGKey(0))
    spec = ServeSpec(buckets=((2, SEQ),), max_new_tokens=16,
                     temperature=0.0, request_timeout_s=30.0,
                     cb="on", cb_slots=2, cb_block_len=4)
    engine = InferenceEngine(net, spec, params=params,
                             log_fn=lambda s: None)
    server = InferenceServer(engine, http=False, log_fn=lambda s: None)
    server.start()
    yield engine, server
    server.stop()


def test_dead_on_arrival_burns_zero_engine_steps(tail_served):
    engine, server = tail_served
    prompt = np.arange(1, 5, dtype=np.int32)
    server.generate(prompt)               # warm: the engine works
    steps_before = engine.stats.cb_steps
    doa_before = engine.stats.expired_on_arrival
    with pytest.raises(DeadlineExpired, match="dead on arrival"):
        server.generate(prompt, deadline=time.monotonic() - 0.5)
    assert engine.stats.expired_on_arrival == doa_before + 1
    assert engine.stats.cb_steps == steps_before  # no prefill, no step
    server.generate(prompt)               # the engine is unharmed


def test_cancelled_request_is_dropped_not_failed(tail_served):
    engine, server = tail_served
    prompt = np.arange(1, 5, dtype=np.int32)
    ev = threading.Event()
    ev.set()                              # cancelled before admission
    ticket = server.scheduler.submit(prompt, timeout=5.0,
                                     cancel_event=ev)
    with pytest.raises(Cancelled):
        for _ in ticket.events():
            pass
    assert engine.stats.cancelled >= 1
    server.generate(prompt)               # slot bookkeeping intact
