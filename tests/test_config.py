"""Config-surface tests: the reference's example configs must load unchanged.

Reference semantics: /root/reference/src/proto/model.proto (field names,
defaults), examples/mnist/{mlp,conv}.conf (real-world inputs).
"""
import os

import pytest

from singa_tpu.config import (
    ConfigError, load_model_config, load_cluster_config,
    model_config_from_text,
)
from singa_tpu.config import textproto

REF = "/root/reference/examples/mnist"


def test_tokenizer_basics():
    d = textproto.parse('a: 1\nb: "hi"\nc: true\nd: kStep\ne: -0.5  # comment')
    assert d == {"a": [1], "b": ["hi"], "c": [True], "d": ["kStep"],
                 "e": [-0.5]}


def test_nested_and_repeated():
    d = textproto.parse("""
      layer { name: "x" srclayers: "a" srclayers: "b" }
      layer { name: "y" }
    """)
    assert len(d["layer"]) == 2
    assert d["layer"][0]["srclayers"] == ["a", "b"]


def test_colon_optional_before_brace():
    d = textproto.parse('m: { v: 2 }')
    assert d["m"][0]["v"] == [2]


def test_comment_inside_message():
    d = textproto.parse('m {\n# hello\nv: 3\n}')
    assert d["m"][0]["v"] == [3]


@pytest.mark.skipif(not os.path.exists(f"{REF}/mlp.conf"),
                    reason="reference not mounted")
def test_load_reference_mlp_conf():
    cfg = load_model_config(f"{REF}/mlp.conf")
    assert cfg.name == "deep-big-simple-mlp"
    assert cfg.train_steps == 60000
    assert cfg.updater.type == "kSGD"
    assert cfg.updater.learning_rate_change_method == "kStep"
    assert cfg.updater.base_learning_rate == pytest.approx(0.001)
    assert cfg.updater.param_type == "Elastic"
    layers = cfg.neuralnet.layer
    names = [l.name for l in layers]
    # two data layers (train/test variants) + mnist/label + 6 fc + 5 tanh + loss
    assert names.count("data") == 2
    assert "fc6" in names and "loss" in names
    fc1 = next(l for l in layers if l.name == "fc1")
    assert fc1.inner_product_param.num_output == 2500
    assert fc1.param[0].init_method == "kUniform"
    assert fc1.param[0].low == pytest.approx(-0.05)
    loss = next(l for l in layers if l.name == "loss")
    assert loss.srclayers == ["fc6", "label"]
    assert loss.softmaxloss_param.topk == 1
    data_train = layers[0]
    assert data_train.exclude == ["kTest"]
    assert data_train.data_param.batchsize == 1000
    assert data_train.data_param.random_skip == 10000


@pytest.mark.skipif(not os.path.exists(f"{REF}/conv.conf"),
                    reason="reference not mounted")
def test_load_reference_conv_conf():
    cfg = load_model_config(f"{REF}/conv.conf")
    assert cfg.updater.momentum == pytest.approx(0.9)
    assert cfg.updater.weight_decay == pytest.approx(0.0005)
    assert cfg.updater.learning_rate_change_method == "kInverse"
    conv1 = next(l for l in cfg.neuralnet.layer if l.name == "conv1")
    assert conv1.convolution_param.num_filters == 20
    assert conv1.convolution_param.kernel == 5
    assert conv1.param[0].init_method == "kUniformSqrtFanIn"
    assert conv1.param[1].learning_rate_multiplier == pytest.approx(2.0)
    pool1 = next(l for l in cfg.neuralnet.layer if l.name == "pool1")
    assert pool1.pooling_param.pool == "MAX"
    assert pool1.pooling_param.stride == 2
    mnist = next(l for l in cfg.neuralnet.layer if l.name == "mnist")
    assert mnist.mnist_param.norm_a == 255


@pytest.mark.skipif(not os.path.exists(f"{REF}/cluster.conf"),
                    reason="reference not mounted")
def test_load_reference_cluster_conf():
    cfg = load_cluster_config(f"{REF}/cluster.conf")
    assert cfg.nworkers >= 1


def test_defaults_match_reference_proto():
    cfg = model_config_from_text("name: 'm' updater { type: kSGD "
                                 "base_learning_rate: 0.1 }")
    u = cfg.updater
    assert u.hogwild is True
    assert u.delta == pytest.approx(1e-7)
    assert u.rho == pytest.approx(0.9)
    assert u.sync_frequency == 1
    assert u.warmup_steps == 10
    assert u.param_type == "Elastic"
    assert cfg.prefetch is True
    assert cfg.alg == "kBackPropagation"


def test_unknown_field_rejected():
    with pytest.raises(ConfigError):
        model_config_from_text("bogus_field: 3")


def test_bad_enum_rejected():
    with pytest.raises(ConfigError):
        model_config_from_text("updater { type: kBogus }")


def test_textproto_dump_escapes_control_chars():
    """dump() output with newlines/tabs/control chars in string values
    must re-parse (protobuf text-format escaping; ADVICE r1)."""
    from singa_tpu.config.textproto import dump, parse
    msg = {"name": ['weird "x"\npath\twith\rctrl\x01'], "n": [3]}
    text = dump(msg)
    back = parse(text)
    assert back["name"] == msg["name"]
    assert back["n"] == [3]
