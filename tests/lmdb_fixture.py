"""Test-only LMDB environment writer.

No liblmdb exists in this environment, so tests synthesize a real
on-disk LMDB 0.9 environment from the format spec (see
singa_tpu/data/lmdb_reader.py for the layout facts): meta pages 0/1,
leaf/branch B-tree pages, and overflow chains for values that don't
fit in a page.  The writer is deliberately a separate from-spec
encoder, not the reader inverted, so round-trip tests exercise the
format contract rather than one module's private conventions.
"""

from __future__ import annotations

import os
import struct
from typing import Dict, List, Sequence, Tuple

P_BRANCH, P_LEAF, P_OVERFLOW, P_META = 0x01, 0x02, 0x04, 0x08
F_BIGDATA = 0x01
INVALID = 0xFFFFFFFFFFFFFFFF


def _even(n: int) -> int:
    return n + (n & 1)


def _page_header(pgno: int, flags: int, lower: int, upper: int) -> bytes:
    return struct.pack("<QHHHH", pgno, 0, flags, lower, upper)


def _overflow_header(pgno: int, npages: int) -> bytes:
    return struct.pack("<QHHI", pgno, 0, P_OVERFLOW, npages)


def _db(depth, branch, leaf, overflow, entries, root) -> bytes:
    return struct.pack("<IHHQQQQQ", 0, 0, depth, branch, leaf, overflow,
                       entries, root)


def _meta_page(ps: int, pgno: int, txnid: int, db: bytes,
               last_pg: int) -> bytes:
    body = struct.pack("<IIQQ", 0xBEEFC0DE, 1, 0, 1048576)
    body += _db(0, 0, 0, 0, 0, INVALID)          # free DB
    body += db                                   # main DB
    body += struct.pack("<QQ", last_pg, txnid)
    page = _page_header(pgno, P_META, 0, 0) + body
    return page.ljust(ps, b"\x00")


def write_lmdb(path: str, items: Sequence[Tuple[bytes, bytes]],
               page_size: int = 4096) -> str:
    """Write `items` as <path>/data.mdb; returns the file path."""
    os.makedirs(path, exist_ok=True)
    items = sorted(items)
    ps = page_size
    max_inline = ps // 2 - 32        # bigger values go to overflow

    pages: Dict[int, bytes] = {}
    next_pg = 2                      # 0/1 are the meta pages
    n_overflow = 0

    def alloc() -> int:
        nonlocal next_pg
        pg = next_pg
        next_pg += 1
        return pg

    # ---- build leaves ----------------------------------------------------
    leaves: List[Tuple[int, bytes, List[Tuple[bytes, bytes, int]]]] = []
    pending: List[Tuple[bytes, bytes, int]] = []   # (key, val, ovf_pgno)

    def node_size(key: bytes, val: bytes, ovf: int) -> int:
        return _even(8 + len(key) + (8 if ovf else len(val)))

    def fits(nodes) -> bool:
        lower = 16 + 2 * len(nodes)
        used = sum(node_size(*n) for n in nodes)
        return lower + used <= ps

    def flush_leaf():
        nonlocal pending
        if pending:
            leaves.append((alloc(), pending[0][0], pending))
            pending = []

    for key, val in items:
        ovf = 0
        if 8 + len(key) + len(val) > max_inline:
            # overflow chain for the value
            npages = (16 + len(val) + ps - 1) // ps
            ovf = alloc()
            raw = _overflow_header(ovf, npages) + val
            for i in range(npages):
                pg = ovf if i == 0 else alloc()
                pages[pg] = raw[i * ps:(i + 1) * ps].ljust(ps, b"\x00")
            n_overflow += npages
        if not fits(pending + [(key, val, ovf)]):
            flush_leaf()
        pending.append((key, val, ovf))
    flush_leaf()

    for pgno, _, nodes in leaves:
        ptrs: List[int] = []
        upper = ps
        blob = bytearray(ps)
        for key, val, ovf in nodes:
            sz = node_size(key, val, ovf)
            upper -= sz
            ptrs.append(upper)
            if ovf:
                node = struct.pack("<HHHH", len(val) & 0xFFFF,
                                   len(val) >> 16, F_BIGDATA, len(key))
                node += key + struct.pack("<Q", ovf)
            else:
                node = struct.pack("<HHHH", len(val) & 0xFFFF,
                                   len(val) >> 16, 0, len(key))
                node += key + val
            blob[upper:upper + len(node)] = node
        lower = 16 + 2 * len(ptrs)
        blob[:16] = _page_header(pgno, P_LEAF, lower, upper)
        blob[16:lower] = struct.pack(f"<{len(ptrs)}H", *ptrs)
        pages[pgno] = bytes(blob)

    # ---- root ------------------------------------------------------------
    n_branch = 0
    if not leaves:
        root, depth = INVALID, 0
    elif len(leaves) == 1:
        root, depth = leaves[0][0], 1
    else:
        root, depth, n_branch = alloc(), 2, 1
        ptrs, upper = [], ps
        blob = bytearray(ps)
        for i, (pgno, first_key, _) in enumerate(leaves):
            key = b"" if i == 0 else first_key
            sz = _even(8 + len(key))
            upper -= sz
            ptrs.append(upper)
            node = struct.pack("<HHHH", pgno & 0xFFFF,
                               (pgno >> 16) & 0xFFFF, pgno >> 32,
                               len(key)) + key
            blob[upper:upper + len(node)] = node
        lower = 16 + 2 * len(ptrs)
        blob[:16] = _page_header(root, P_BRANCH, lower, upper)
        blob[16:lower] = struct.pack(f"<{len(ptrs)}H", *ptrs)
        pages[root] = bytes(blob)
        if lower > upper:
            raise ValueError("fixture writer: too many leaves for a "
                             "single branch page")

    # ---- metas + assembly ------------------------------------------------
    last_pg = max(pages) if pages else 1
    db = _db(depth, n_branch, len(leaves), n_overflow, len(items), root)
    out = bytearray()
    out += _meta_page(ps, 0, 0, _db(0, 0, 0, 0, 0, INVALID), 1)
    out += _meta_page(ps, 1, 1, db, last_pg)
    for pg in range(2, last_pg + 1):
        out += pages.get(pg, b"\x00" * ps)
    fp = os.path.join(path, "data.mdb")
    with open(fp, "wb") as f:
        f.write(bytes(out))
    return fp
