"""Mid-stream failover (serve/session.py + the Router's durable
stream path): a decode stream survives the death of the engine
serving it by re-admitting (prompt ‖ emitted prefix) on a
same-fingerprint sibling and splicing the legs by absolute sequence
number.

Correctness anchors:
  * exactly-once: across a mid-stream kill every index reaches the
    client once — no duplicates, no gaps — and the spliced terminal
    carries the FULL journaled token list;
  * honesty under impossibility: no same-fingerprint sibling ->
    `finish="failover_stale"` with the journaled prefix (never a
    cross-checkpoint splice); resume off / faulted / legacy handle ->
    the pre-failover terminal error (never a hang, never a replay
    from index 0);
  * the idle watchdog converts a SILENT stall into the same failover
    a transport break gets, and a drain-timeout kick fails a live —
    even already-resumed — stream over instead of truncating it;
  * the scheduler treats an inadmissible `resume_from` (past
    max_new, past EOS, past the provided prefix, negative) as a fast
    400: counted `rejected`, zero engine steps;
  * `qos.transport_budget` clamps the per-hop socket slack to the
    remaining end-to-end deadline (the flat `+30s` leak).

Cost control: the failover choreography runs on scriptable stub
handles (the test_autoscale.py mold — no compiled programs); the one
compiled engine is module-scoped and only backs the scheduler-level
resume admission tests.  The full kill-mid-stream/fault/watchdog run
over real engines lives in `bench.py --failover-smoke`."""

import threading
import time

import jax
import numpy as np
import pytest

from singa_tpu.core.net import build_net
from singa_tpu.models.transformer import transformer_lm
from singa_tpu.serve import (InferenceEngine, InferenceServer,
                             Router, RouterSpec, ServeSpec, qos)
from singa_tpu.utils.faults import FaultSchedule, inject

pytestmark = pytest.mark.failover


# -- satellite: transport budget clamps to the deadline ----------------------

def test_transport_budget_clamps_slack_to_deadline():
    """A 2s client deadline must bound the socket budget: the old
    flat `+ 30.0` held the connection (and the engine slot behind it)
    half a minute after the client gave up."""
    now = time.monotonic()
    b = qos.transport_budget(now + 2.0, None, 30.0)
    assert b < 4.2, f"slack leaked past the deadline: {b}"
    assert b > 2.0                       # still covers the remaining
    # nearly-dead request: floor at 0.1s base + 0.1s slack, never <= 0
    b = qos.transport_budget(now - 5.0, None, 30.0)
    assert 0.15 <= b <= 0.25
    # a deadline far beyond the slack keeps the full 30s slack
    b = qos.transport_budget(now + 300.0, None, 30.0)
    assert 325.0 < b < 335.0
    # no deadline: the old generous behavior stands
    assert qos.transport_budget(None, 5.0, 30.0) == pytest.approx(35.0)
    assert qos.transport_budget(None, None, 7.0) == pytest.approx(37.0)


# -- scriptable stream stubs (no compiled programs) --------------------------

def _tok(step, j):
    """The determinism stand-in: token at absolute index j depends
    only on (fingerprint step, j) — any same-step sibling re-derives
    the identical continuation, exactly like greedy decode."""
    return (int(step) * 7 + j * 3) % 101


class StreamStubHandle:
    """Engine-handle double whose `request_stream` speaks the indexed
    protocol and can be scripted to die, stall silently, or block at
    an absolute token index (each trigger fires once)."""

    def __init__(self, name, step=1):
        self.name = name
        self.step = step
        self.die_at = None       # raise before emitting this index
        self.stall_at = None     # block silently before this index
        self.calls = []          # (resume_from, len(tokens)) per admit

    def probe(self):
        return {"ok": True, "status": "ok", "step": self.step,
                "queue_depth": 0}

    def stats_snapshot(self):
        return {"completed": 0, "failed": 0, "expired": 0,
                "p95_latency_ms": None}

    def request(self, mode, tokens, timeout=None):
        return {"tokens": [1], "step": self.step}

    def request_stream(self, tokens, timeout=None, max_new=None,
                       deadline=None, priority="interactive",
                       cancel_event=None, resume_from=0):
        self.calls.append((int(resume_from), len(tokens)))

        def gen():
            for j in range(int(resume_from), int(max_new)):
                if self.die_at == j:
                    self.die_at = None
                    raise RuntimeError(f"{self.name} exploded at {j}")
                if self.stall_at == j:
                    self.stall_at = None
                    if cancel_event is not None:
                        cancel_event.wait(10.0)
                    return           # ends without a terminal event
                yield {"token": _tok(self.step, j), "i": j}
            yield {"done": True, "finish": "length", "step": self.step,
                   "tokens": [_tok(self.step, j) for j in
                              range(int(resume_from), int(max_new))]}
        return gen()


class LegacyStreamStubHandle(StreamStubHandle):
    """A pre-failover handle: no `resume_from` parameter, no `i`
    field — what every engine looked like before this PR."""

    def request_stream(self, tokens, timeout=None, max_new=None,
                       deadline=None, priority="interactive",
                       cancel_event=None):
        self.calls.append((0, len(tokens)))

        def gen():
            for j in range(int(max_new)):
                if self.die_at == j:
                    self.die_at = None
                    raise RuntimeError(f"{self.name} exploded at {j}")
                yield {"token": _tok(self.step, j)}
            yield {"done": True, "finish": "length", "step": self.step,
                   "tokens": [_tok(self.step, j)
                              for j in range(int(max_new))]}
        return gen()


def _router(handles, **spec_kw):
    spec_kw.setdefault("probe_period_s", 60.0)
    spec_kw.setdefault("quarantine_after", 10)
    spec_kw.setdefault("request_timeout_s", 10.0)
    spec_kw.setdefault("hedge", "off")
    r = Router(handles, spec=RouterSpec(**spec_kw),
               log_fn=lambda s: None)
    r.probe_all()
    return r


def _consume(stream, on_event=None):
    """Drain a stream into (token events, terminal event)."""
    toks, done = [], None
    for ev in stream:
        if ev.get("done"):
            done = ev
            break
        toks.append(ev)
        if on_event is not None:
            on_event(ev)
    return toks, done


# -- the tentpole: exactly-once failover on stubs ----------------------------

def test_stream_failover_exactly_once():
    e0, e1 = StreamStubHandle("e0"), StreamStubHandle("e1")
    e0.die_at = 3                       # dies owing index 3
    r = _router([e0, e1])
    toks, done = _consume(r.route_stream([5, 6], max_new=8))
    # every index exactly once, every token the deterministic one —
    # and each event carries BOTH keys, so a pre-PR client that only
    # reads `token` sees an unchanged stream
    assert [ev["i"] for ev in toks] == list(range(8))
    assert [ev["token"] for ev in toks] == [_tok(1, j) for j in range(8)]
    assert all("token" in ev and "i" in ev for ev in toks)
    # the spliced terminal: full journal, honest provenance
    assert done["tokens"] == [_tok(1, j) for j in range(8)]
    assert done["spliced"] is True and done["resumes"] == 1
    assert done["engine"] == "e1" and done["finish"] == "length"
    # the resume re-admitted (prompt ‖ 3-token prefix) from index 3
    assert e1.calls == [(3, 5)]
    snap = r.sessions.snapshot()
    assert snap["failovers"] == 1 and snap["resumed"] == 1
    assert snap["spliced"] == 1 and snap["done"] == 1
    assert snap["dup_tokens"] == 0 and snap["gap_events"] == 0
    assert r.snapshot()["streams"]["opened"] == 1


def test_failover_stale_fingerprint_is_honest():
    """No same-step sibling left: the stream ends with the journaled
    prefix and `finish="failover_stale"` — never a splice across
    checkpoints, never an exception-shaped lie."""
    e0, e1 = StreamStubHandle("e0", step=1), StreamStubHandle("e1", step=2)
    e0.die_at = 2
    r = _router([e0, e1])
    toks, done = _consume(r.route_stream([5], max_new=8))
    assert [ev["i"] for ev in toks] == [0, 1]
    assert done["finish"] == "failover_stale"
    assert done["tokens"] == [_tok(1, 0), _tok(1, 1)]
    assert done["resumes"] == 1 and "error" in done
    snap = r.sessions.snapshot()
    assert snap["failover_stale"] == 1 and snap["resumed"] == 0
    assert e1.calls == []               # the stale sibling never touched


def test_resume_fault_degrades_to_terminal_error():
    """An injected `serve.resume` fault abandons the resume and the
    client sees the PRE-failover terminal error — degraded, not hung,
    not duplicated."""
    e0, e1 = StreamStubHandle("e0"), StreamStubHandle("e1")
    e0.die_at = 2
    r = _router([e0, e1])
    stream = r.route_stream([5], max_new=8)
    got = []
    with inject(FaultSchedule.parse("serve.resume@0:error")):
        with pytest.raises(RuntimeError, match="e0 exploded at 2"):
            for ev in stream:
                got.append(ev)
    assert [ev["i"] for ev in got] == [0, 1]   # prefix delivered once
    snap = r.sessions.snapshot()
    assert snap["resume_faults"] == 1 and snap["resumed"] == 0
    assert snap["failed"] == 1
    assert e1.calls == []


def test_resume_off_restores_pre_pr_behavior():
    e0, e1 = StreamStubHandle("e0"), StreamStubHandle("e1")
    e0.die_at = 2
    r = _router([e0, e1], resume="off")
    with pytest.raises(RuntimeError, match="e0 exploded at 2"):
        list(r.route_stream([5], max_new=8))
    snap = r.sessions.snapshot()
    assert snap["failovers"] == 1 and snap["resumed"] == 0
    assert e1.calls == []


def test_idle_watchdog_resumes_silent_stall():
    """A stall emits no bytes and no error — only the per-stream idle
    watchdog can tell the client is starving.  It must trigger the
    same exactly-once failover a transport break gets."""
    e0, e1 = StreamStubHandle("e0"), StreamStubHandle("e1")
    e0.stall_at = 2
    r = _router([e0, e1], stream_idle_s=0.2)
    toks, done = _consume(r.route_stream([5], max_new=8))
    assert [ev["i"] for ev in toks] == list(range(8))
    assert [ev["token"] for ev in toks] == [_tok(1, j) for j in range(8)]
    assert done["spliced"] is True
    snap = r.sessions.snapshot()
    assert snap["idle_timeouts"] >= 1 and snap["resumed"] == 1
    assert e1.calls == [(2, 3)]


# -- satellite: drain-timeout kicks a RESUMED stream onwards -----------------

def test_drain_kick_fails_over_a_resumed_stream():
    """Scale-down during an already-failed-over stream: the victim of
    `remove_engine(drain=True)` holds a RESUMED leg; the drain-timeout
    kick must fail it over AGAIN and the client still gets every
    token exactly once."""
    e0 = StreamStubHandle("e0")
    e1 = StreamStubHandle("e1")
    e2 = StreamStubHandle("e2")
    e0.die_at = 2                       # first hop: e0 -> e1
    e1.stall_at = 5                     # e1 blocks so the kick lands
                                        # while its leg is live
    r = _router([e0, e1, e2])
    kicked_at = []

    def on_event(ev):
        if ev["i"] == 3 and not kicked_at:
            kicked_at.append(ev["i"])
            assert not r.remove_engine("e1", drain=True,
                                       timeout_s=0.05)
    toks, done = _consume(r.route_stream([5], max_new=8),
                          on_event=on_event)
    assert [ev["i"] for ev in toks] == list(range(8))
    assert [ev["token"] for ev in toks] == [_tok(1, j) for j in range(8)]
    assert done["spliced"] is True and done["resumes"] == 2
    assert done["tokens"] == [_tok(1, j) for j in range(8)]
    snap = r.sessions.snapshot()
    assert snap["kicked"] == 1 and snap["resumed"] == 2
    assert snap["failovers"] == 2 and snap["done"] == 1
    assert "e1" not in r.names()        # the retire itself completed
    assert e2.calls == [(5, 6)]         # second hop resumed at index 5


# -- satellite: protocol compatibility with pre-PR engines -------------------

def test_legacy_handle_fresh_stream_still_works():
    """A handle that predates the `i` field serves a fresh stream
    unchanged: indices are inferred sequentially, the terminal is not
    marked spliced."""
    r = _router([LegacyStreamStubHandle("e0")])
    toks, done = _consume(r.route_stream([5], max_new=6))
    assert [ev["token"] for ev in toks] == [_tok(1, j) for j in range(6)]
    assert done["tokens"] == [_tok(1, j) for j in range(6)]
    assert "spliced" not in done
    snap = r.sessions.snapshot()
    assert snap["done"] == 1 and snap["failovers"] == 0


def test_legacy_handle_death_degrades_not_replays():
    """A sibling whose `request_stream` would silently DROP
    `resume_from` must not be spliced to — it would replay from index
    0 and duplicate the prefix.  The stream degrades to the original
    terminal error instead."""
    e0 = LegacyStreamStubHandle("e0")
    e1 = LegacyStreamStubHandle("e1")
    e0.die_at = 2
    r = _router([e0, e1])
    with pytest.raises(RuntimeError, match="e0 exploded at 2"):
        list(r.route_stream([5], max_new=8))
    snap = r.sessions.snapshot()
    assert snap["resume_denied"] >= 1 and snap["resumed"] == 0
    assert len(e1.calls) == 0           # never even admitted


# -- scheduler-level resume admission (one compiled engine) ------------------

VOCAB, SEQ, EOS = 64, 16, 63
SHAPES = {"data": {"input": (SEQ,), "target": (SEQ,)}}


@pytest.fixture(scope="module")
def fo_served():
    cfg = transformer_lm(vocab_size=VOCAB, num_layers=2, embed_dim=32,
                         num_heads=4, head_dim=8, seq_len=SEQ,
                         batchsize=2)
    net = build_net(cfg, "kTest", SHAPES)
    params = net.init_params(jax.random.PRNGKey(0))
    spec = ServeSpec(buckets=((2, SEQ),), max_new_tokens=32,
                     temperature=0.0, request_timeout_s=30.0,
                     cb="on", cb_slots=4, cb_block_len=4, eos_id=EOS)
    engine = InferenceEngine(net, spec, params=params,
                             log_fn=lambda s: None)
    server = InferenceServer(engine, http=False, log_fn=lambda s: None)
    server.start()
    yield engine, server
    server.stop()


def test_inadmissible_resume_is_fast_400(fo_served):
    """Every inadmissible `resume_from` is refused before any queue
    or engine work: counted `rejected`, zero scheduler steps."""
    engine, server = fo_served
    prompt = [3, 1, 4, 1]
    rejected0 = engine.stats.rejected
    steps0 = engine.stats.cb_steps
    with pytest.raises(ValueError, match="past max_new"):
        server.generate_stream(prompt, resume_from=64)
    with pytest.raises(ValueError, match=">= 0"):
        server.generate_stream(prompt, resume_from=-1)
    with pytest.raises(ValueError, match="exceeds"):
        server.generate_stream(prompt, resume_from=10)
    with pytest.raises(ValueError, match="eos"):
        # the provided prefix already contains EOS: the original
        # stream finished, there is nothing to resume
        server.generate_stream(prompt + [EOS], resume_from=1)
    assert engine.stats.rejected == rejected0 + 4
    assert engine.stats.cb_steps == steps0, \
        "an inadmissible resume reached the engine"


def test_resume_readmission_bit_identical(fo_served):
    """The determinism contract the whole failover rests on, on a
    REAL compiled scheduler: re-admitting (prompt ‖ prefix) with
    `resume_from=k` re-derives exactly the suffix the uninterrupted
    stream produced, numbered from absolute index k."""
    engine, server = fo_served
    prompt = [3, 1, 4, 1]
    ref = server.generate_stream(prompt).wait(60.0)["tokens"]
    assert len(ref) >= 2
    # resume before any EOS in the reference (an EOS-bearing prefix
    # is inadmissible by design)
    limit = ref.index(EOS) if EOS in ref else len(ref)
    k = max(1, min(limit - 1, (SEQ - len(prompt)) // 2, 4))
    resumed0 = engine.stats.resumed
    ticket = server.generate_stream(prompt + ref[:k], resume_from=k)
    assert ticket.first_index == k
    events = []
    for kind, payload in ticket.events():
        if kind == "tok":
            events.append(payload)
    out = ticket.wait(60.0)
    assert out["tokens"] == ref[k:], \
        f"resume at {k} diverged: {out['tokens']} vs {ref[k:]}"
    assert events == ref[k:]
    assert engine.stats.resumed == resumed0 + 1
