"""Per-IMAGE crop/mirror randomness in RGBImageLayer.

Reference layer.cc:587-616 draws hoff/woff and the mirror coin inside
the per-record parse loop — every image in a batch gets its own crop
offset and flip.  These tests pin that (VERDICT r2 item 2): two images
in one batch receive different crops/flips under a fixed seed, offsets
stay in the reference's rand()%(shape-cropsize) range, and eval is a
deterministic center crop with no mirror.
"""

import jax
import jax.numpy as jnp
import numpy as np

from singa_tpu.config.schema import model_config_from_dict
from singa_tpu.core.net import build_net

B, H, W, CS = 16, 8, 8, 4


def _cfg(cropsize=0, mirror=False):
    layers = [
        {"name": "data", "type": "kShardData",
         "data_param": {"batchsize": B}},
        {"name": "rgb", "type": "kRGBImage", "srclayers": "data",
         "rgbimage_param": {"scale": 1.0, "cropsize": cropsize,
                            "mirror": mirror}},
        {"name": "label", "type": "kLabel", "srclayers": "data"},
        {"name": "ip", "type": "kInnerProduct", "srclayers": "rgb",
         "inner_product_param": {"num_output": 4},
         "param": [{"name": "weight"}, {"name": "bias"}]},
        {"name": "loss", "type": "kSoftmaxLoss",
         "srclayers": ["ip", "label"]},
    ]
    return model_config_from_dict({
        "name": "augtest", "train_steps": 1,
        "updater": {"type": "kSGD", "base_learning_rate": 0.1,
                    "learning_rate_change_method": "kFixed"},
        "neuralnet": {"layer": layers}})


SHAPES = {"data": {"pixel": (3, H, W), "label": ()}}


def _ramp_batch():
    """pixel[b, c, h, w] = h*100 + w: the top-left value of a crop
    reveals its (hoff, woff)."""
    ramp = (np.arange(H)[:, None] * 100.0
            + np.arange(W)[None, :]).astype(np.float32)
    pixel = np.broadcast_to(ramp, (B, 3, H, W)).copy()
    return {"data": {"pixel": jnp.asarray(pixel),
                     "label": jnp.zeros((B,), jnp.int32)}}


def _rgb_out(cfg, train, seed=0):
    net = build_net(cfg, "kTrain", SHAPES)
    params = net.init_params(jax.random.PRNGKey(0))
    _, _, outs = net.apply(params, _ramp_batch(),
                           rng=jax.random.PRNGKey(seed), train=train)
    return np.asarray(outs["rgb"], np.float32)


def test_per_image_crop_offsets_differ():
    out = _rgb_out(_cfg(cropsize=CS), train=True)
    assert out.shape == (B, CS, CS, 3)
    corners = out[:, 0, 0, 0]                 # hoff*100 + woff per image
    hoff, woff = corners // 100, corners % 100
    # reference range: rand() % (shape - cropsize) — exclusive of max
    assert hoff.min() >= 0 and hoff.max() <= H - CS - 1
    assert woff.min() >= 0 and woff.max() <= W - CS - 1
    # per-image randomness: 16 images, 16 equally likely offsets
    assert len({(int(h), int(w)) for h, w in zip(hoff, woff)}) > 1
    # each crop is a contiguous window of the ramp
    for b in range(B):
        expect = (np.arange(CS)[:, None] * 100.0 + np.arange(CS)
                  + corners[b])
        np.testing.assert_array_equal(out[b, :, :, 0], expect)


def test_per_image_mirror_differs():
    out = _rgb_out(_cfg(mirror=True), train=True)
    ramp = (np.arange(H)[:, None] * 100.0
            + np.arange(W)[None, :]).astype(np.float32)
    is_flip = [bool(np.array_equal(out[b, :, :, 0], ramp[:, ::-1]))
               for b in range(B)]
    is_id = [bool(np.array_equal(out[b, :, :, 0], ramp))
             for b in range(B)]
    assert all(f or i for f, i in zip(is_flip, is_id))
    assert any(is_flip) and any(is_id)        # per-image coin, seeded


def test_eval_center_crop_no_mirror():
    out = _rgb_out(_cfg(cropsize=CS, mirror=True), train=False)
    oh, ow = (H - CS) // 2, (W - CS) // 2
    expect = (np.arange(CS)[:, None] * 100.0 + np.arange(CS)
              + oh * 100 + ow)
    for b in range(B):
        np.testing.assert_array_equal(out[b, :, :, 0], expect)


def test_crop_and_mirror_compose():
    out = _rgb_out(_cfg(cropsize=CS, mirror=True), train=True)
    # every row of every crop must be a contiguous ascending or
    # descending run of the ramp (crop then flip)
    for b in range(B):
        row = out[b, 0, :, 0]
        diffs = np.diff(row)
        assert np.all(diffs == 1) or np.all(diffs == -1)


def test_seed_determinism():
    a = _rgb_out(_cfg(cropsize=CS, mirror=True), train=True, seed=3)
    b = _rgb_out(_cfg(cropsize=CS, mirror=True), train=True, seed=3)
    np.testing.assert_array_equal(a, b)
    c = _rgb_out(_cfg(cropsize=CS, mirror=True), train=True, seed=4)
    assert not np.array_equal(a, c)
