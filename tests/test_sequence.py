"""Sequence/modern-parallelism tests: flash attention, ring, Ulysses,
pipeline, MoE, and the transformer family on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from singa_tpu.core.trainer import Trainer
from singa_tpu.models.transformer import (synthetic_token_batches,
                                          transformer_lm)
from singa_tpu.ops.attention import attention_reference, flash_attention, rope
from singa_tpu.ops.moe import moe_ffn
from singa_tpu.parallel import (make_mesh, param_shardings, pipeline_apply,
                                ring_attention, seq_batch_shardings,
                                stack_stage_params, ulysses_attention)
from singa_tpu.parallel.sequence import LEGACY_SHARD_MAP

RNG = np.random.default_rng(0)
SEQ_SHAPES = {"data": {"input": (128,), "target": (128,)}}

# Ring-attention PARITY (not structure) is asserted only on modern jax:
# the pre-0.4.35 experimental shard_map's check_rep rewrite perturbs
# the ring collectives' numerics slightly (the drift noted in PR 10).
# strict=False because the tightened shim (check_rep defaulted off)
# may well restore parity on some legacy versions — an xpass is fine.
ring_parity = pytest.mark.xfail(
    LEGACY_SHARD_MAP,
    reason="pre-0.4.35 jax: experimental shard_map's check_rep "
           "rewrite drifts ring-attention numerics (PR 10 known "
           "issue); parity is asserted on modern jax only",
    strict=False)


def _qkv(b=2, h=8, s=256, d=32):
    return tuple(jnp.asarray(RNG.standard_normal((b, h, s, d))
                             .astype(np.float32)) for _ in range(3))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_reference(causal):
    q, k, v = _qkv()
    out = flash_attention(q, k, v, causal, 128, 128, True)
    ref = attention_reference(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_flash_attention_grads():
    q, k, v = _qkv(1, 2, 128, 16)
    g = jax.grad(lambda q, k, v: flash_attention(
        q, k, v, True, 128, 128, True).sum(), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q, k, v: attention_reference(
        q, k, v, True).sum(), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


@ring_parity
@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(causal):
    q, k, v = _qkv()
    mesh = make_mesh(seq=8)
    out = ring_attention(q, k, v, mesh, "seq", causal)
    ref = attention_reference(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


@ring_parity
def test_ring_attention_grad():
    q, k, v = _qkv(1, 4, 128, 16)
    mesh = make_mesh(seq=8)
    g1 = jax.grad(lambda q: ring_attention(q, k, v, mesh, "seq", True).sum())(q)
    g2 = jax.grad(lambda q: attention_reference(q, k, v, True).sum())(q)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_reference(causal):
    q, k, v = _qkv()
    mesh = make_mesh(seq=8)
    out = ulysses_attention(q, k, v, mesh, "seq", causal)
    ref = attention_reference(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_rope_rotation_preserves_norm():
    x = jnp.asarray(RNG.standard_normal((1, 2, 16, 32)).astype(np.float32))
    y = rope(x, jnp.arange(16))
    np.testing.assert_allclose(np.linalg.norm(np.asarray(x), axis=-1),
                               np.linalg.norm(np.asarray(y), axis=-1),
                               rtol=1e-5)
    # position 0 is identity
    np.testing.assert_allclose(np.asarray(y[:, :, 0]), np.asarray(x[:, :, 0]),
                               rtol=1e-6)


def test_pipeline_matches_sequential():
    mesh = make_mesh(pipe=4)
    nstages, nmicro, mb, d = 4, 8, 4, 16
    per_stage = [{"w": jnp.asarray(
        RNG.standard_normal((d, d)).astype(np.float32)) * 0.3}
        for _ in range(nstages)]
    stacked = stack_stage_params(per_stage)
    x = jnp.asarray(RNG.standard_normal((nmicro, mb, d)).astype(np.float32))

    def stage_fn(p, h):
        return jax.nn.relu(h @ p["w"])

    out = pipeline_apply(mesh, stage_fn, stacked, x)
    ref = x
    for p in per_stage:
        ref = jax.vmap(lambda h, p=p: stage_fn(p, h))(ref)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_rejects_underfilled():
    mesh = make_mesh(pipe=4)
    stacked = stack_stage_params(
        [{"w": jnp.eye(4)} for _ in range(4)])
    x = jnp.zeros((2, 2, 4))
    with pytest.raises(ValueError, match="n_micro"):
        pipeline_apply(mesh, lambda p, h: h, stacked, x)


def test_moe_routes_and_balances():
    e, f, n_exp = 16, 32, 4
    x = jnp.asarray(RNG.standard_normal((2, 8, e)).astype(np.float32))
    params = {
        "router": jnp.asarray(RNG.standard_normal((e, n_exp))
                              .astype(np.float32)),
        "w1": jnp.asarray(RNG.standard_normal((n_exp, e, f))
                          .astype(np.float32)) * 0.1,
        "b1": jnp.zeros((n_exp, f)),
        "w2": jnp.asarray(RNG.standard_normal((n_exp, f, e))
                          .astype(np.float32)) * 0.1,
        "b2": jnp.zeros((n_exp, e)),
    }
    out, aux = moe_ffn(x, params, k=2, capacity_factor=2.0)
    assert out.shape == x.shape
    assert float(aux) > 0
    # with generous capacity every token is processed: output nonzero
    assert float(jnp.mean(jnp.abs(out))) > 1e-3
    # differentiable end to end
    g = jax.grad(lambda p: moe_ffn(x, p, 2, 2.0)[0].sum())(params)
    assert np.isfinite(float(jnp.sum(jnp.abs(g["router"]))))


def test_transformer_trains_and_beats_unigram():
    vocab = 32
    cfg = transformer_lm(vocab_size=vocab, num_layers=2, embed_dim=64,
                         num_heads=4, head_dim=16, seq_len=64, batchsize=8,
                         train_steps=5)
    shapes = {"data": {"input": (64,), "target": (64,)}}
    trainer = Trainer(cfg, shapes, donate=False)
    params, opt = trainer.init(0)
    it = synthetic_token_batches(8, 64, vocab, seed=0)
    losses = []
    p, o = params, opt
    for s in range(60):
        p, o, m = trainer.train_step(p, o, next(it), s, jax.random.PRNGKey(s))
        losses.append(float(m["loss"]))
    # unigram floor is log(vocab); Markov structure is learnable below it
    assert losses[-1] < np.log(vocab) - 0.1, losses[::10]


@ring_parity
def test_transformer_sharded_step_matches_local():
    """dp×tp×sp mesh with ring attention + MoE == single-device numerics."""
    mesh = make_mesh(data=2, model=2, seq=2)
    common = dict(vocab_size=64, num_layers=2, embed_dim=64, num_heads=4,
                  head_dim=16, seq_len=128, batchsize=8, train_steps=3,
                  moe_every=2, num_experts=4)
    cfg_ring = transformer_lm(seq_parallel="ring", **common)
    cfg_local = transformer_lm(seq_parallel="none", **common)
    tr_ring = Trainer(cfg_ring, SEQ_SHAPES, donate=False, mesh=mesh)
    tr_local = Trainer(cfg_local, SEQ_SHAPES, donate=False)
    params, opt = tr_ring.init(0)
    batch = next(synthetic_token_batches(8, 128, 64))
    rng = jax.random.PRNGKey(0)

    p1, o1, m1 = tr_local.train_step(params, opt, batch, 0, rng)

    p_sh = param_shardings(mesh, tr_ring.train_net)
    sp = {k: jax.device_put(v, p_sh[k]) for k, v in params.items()}
    so = {k: {n: jax.device_put(v, p_sh[n]) for n, v in t.items()}
          for k, t in opt.items()}
    sb = jax.tree_util.tree_map(jax.device_put, batch,
                                seq_batch_shardings(mesh, batch))
    p2, o2, m2 = tr_ring.train_step(sp, so, sb, 0, rng)
    assert float(m1["loss"]) == pytest.approx(float(m2["loss"]), rel=1e-4)
    # Adam's step-0 update is ~lr*sign(g), so reduction-order noise in the
    # sharded grads shows up at ~1e-4 relative — tolerance reflects that.
    np.testing.assert_allclose(np.asarray(p1["attn0/wq"]),
                               np.asarray(p2["attn0/wq"]),
                               rtol=2e-3, atol=1e-5)


def test_expert_parallel_sharding_matches_local():
    """EP on an expert=4 mesh: experts genuinely shard AND the sharded
    step reproduces single-device numerics (not just finite loss)."""
    mesh = make_mesh(data=2, expert=4)
    cfg = transformer_lm(vocab_size=32, num_layers=2, embed_dim=32,
                         num_heads=2, head_dim=16, seq_len=64, batchsize=8,
                         moe_every=1, num_experts=4)
    shapes = {"data": {"input": (64,), "target": (64,)}}
    tr = Trainer(cfg, shapes, donate=False, mesh=mesh)
    tr_local = Trainer(cfg, shapes, donate=False)
    shardings = param_shardings(mesh, tr.train_net)
    from jax.sharding import PartitionSpec as P
    assert shardings["moe0/w1"].spec == P("expert", None, None)
    assert shardings["moe0/b2"].spec == P("expert", None)
    params, opt = tr.init(0)
    batch = next(synthetic_token_batches(8, 64, 32))
    rng = jax.random.PRNGKey(0)
    p1, o1, m1 = tr_local.train_step(params, opt, batch, 0, rng)
    sp = {k: jax.device_put(v, shardings[k]) for k, v in params.items()}
    so = {k: {n: jax.device_put(v, shardings[n]) for n, v in t.items()}
          for k, t in opt.items()}
    sb = jax.tree_util.tree_map(jax.device_put, batch,
                                seq_batch_shardings(mesh, batch))
    p2, o2, m2 = tr.train_step(sp, so, sb, 0, rng)
    assert float(m1["loss"]) == pytest.approx(float(m2["loss"]), rel=1e-4)
    np.testing.assert_allclose(np.asarray(p1["moe0/w1"]),
                               np.asarray(p2["moe0/w1"]),
                               rtol=2e-3, atol=1e-5)
    np.testing.assert_allclose(np.asarray(p1["embed/embedding"]),
                               np.asarray(p2["embed/embedding"]),
                               rtol=2e-3, atol=1e-5)


def test_bfloat16_precision_policy():
    cfg = transformer_lm(vocab_size=32, num_layers=1, embed_dim=32,
                         num_heads=2, head_dim=16, seq_len=64, batchsize=4,
                         precision="bfloat16")
    tr = Trainer(cfg, {"data": {"input": (64,), "target": (64,)}},
                 donate=False)
    params, opt = tr.init(0)
    assert params["attn0/wq"].dtype == jnp.float32  # master weights fp32
    batch = next(synthetic_token_batches(4, 64, 32))
    p, o, m = tr.train_step(params, opt, batch, 0, jax.random.PRNGKey(0))
    assert np.isfinite(float(m["loss"]))


def test_tied_lm_head_with_vocab_equal_embed():
    """Regression: tie orientation must come from config, not shape
    heuristics — ambiguous when vocab_size == embed_dim."""
    vocab = 64
    cfg = transformer_lm(vocab_size=vocab, num_layers=1, embed_dim=vocab,
                         num_heads=4, head_dim=16, seq_len=32, batchsize=4,
                         tie_embeddings=True, fused_head=False)
    tr = Trainer(cfg, {"data": {"input": (32,), "target": (32,)}},
                 donate=False)
    params, opt = tr.init(0)
    assert "lm_head/w" not in params          # aliased to embed/embedding
    net = tr.train_net
    batch = next(synthetic_token_batches(4, 32, vocab))
    _, _, outputs = net.apply(params, batch, rng=jax.random.PRNGKey(0))
    # logits must equal h @ embedding.T (the tied orientation)
    h = np.asarray(outputs["ln_f"])
    emb = np.asarray(params["embed/embedding"])
    np.testing.assert_allclose(np.asarray(outputs["lm_head"]),
                               h @ emb.T, rtol=1e-4, atol=1e-4)


def test_moe_capacity_drops_overflow():
    """With capacity_factor small, overflow tokens are dropped (output 0
    contribution) rather than corrupting other experts' slots."""
    e, f, n_exp = 8, 16, 2
    # router forces ALL tokens to expert 0
    params = {
        "router": jnp.asarray(
            np.stack([np.ones(e) * 5, -np.ones(e) * 5], 1)
            .astype(np.float32)),
        "w1": jnp.ones((n_exp, e, f), jnp.float32) * 0.1,
        "b1": jnp.zeros((n_exp, f)),
        "w2": jnp.ones((n_exp, f, e), jnp.float32) * 0.1,
        "b2": jnp.zeros((n_exp, e)),
    }
    x = jnp.ones((1, 8, e))
    out_full, _ = moe_ffn(x, params, k=1, capacity_factor=2.0)
    out_tight, _ = moe_ffn(x, params, k=1, capacity_factor=0.25)
    # tight capacity: only 1 of 8 tokens served (cap = 0.25*8/2 = 1)
    served_full = int(jnp.sum(jnp.any(jnp.abs(out_full) > 1e-6, -1)))
    served_tight = int(jnp.sum(jnp.any(jnp.abs(out_tight) > 1e-6, -1)))
    assert served_full == 8
    assert served_tight == 1


def test_chunked_lm_xent_matches_naive():
    """Fused chunked head+xent == materialized logits path, including
    gradients (the backward recomputes chunks under jax.checkpoint)."""
    import jax
    from singa_tpu.ops.loss import chunked_lm_xent, softmax_loss_metrics
    rng = np.random.default_rng(0)
    n, e, v = 24, 16, 50
    h = jnp.asarray(rng.standard_normal((n, e)).astype(np.float32))
    w = jnp.asarray(rng.standard_normal((e, v)).astype(np.float32)) * 0.1
    labels = jnp.asarray(rng.integers(0, v, (n,)))

    loss_f, prec_f = chunked_lm_xent(h, w, labels, chunk_size=7, topk=2)
    loss_n, prec_n = softmax_loss_metrics(h @ w, labels, topk=2)
    np.testing.assert_allclose(float(loss_f), float(loss_n), rtol=1e-6)
    np.testing.assert_allclose(float(prec_f), float(prec_n), rtol=1e-6)

    gf = jax.grad(lambda h_, w_: chunked_lm_xent(h_, w_, labels, 7)[0],
                  argnums=(0, 1))(h, w)
    gn = jax.grad(lambda h_, w_: softmax_loss_metrics(h_ @ w_, labels)[0],
                  argnums=(0, 1))(h, w)
    np.testing.assert_allclose(np.asarray(gf[0]), np.asarray(gn[0]),
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(gf[1]), np.asarray(gn[1]),
                               atol=1e-5)


def test_fused_head_model_matches_unfused():
    """transformer_lm(fused_head=True) trains identically to the
    kLMHead+kSoftmaxLoss form (tied embeddings -> same param pytree)."""
    import jax
    from singa_tpu.core.trainer import Trainer
    from singa_tpu.models.transformer import (synthetic_token_batches,
                                              transformer_lm)
    kw = dict(vocab_size=64, num_layers=2, embed_dim=32, num_heads=4,
              head_dim=8, seq_len=32, batchsize=4)
    shapes = {"data": {"input": (32,), "target": (32,)}}
    batch = next(synthetic_token_batches(4, 32, 64))
    out = {}
    for fused in (True, False):
        cfg = transformer_lm(fused_head=fused, **kw)
        tr = Trainer(cfg, shapes, donate=False, log_fn=lambda s: None)
        params, opt = tr.init(0)
        p, o, m = tr.train_step(params, opt, batch, 0, jax.random.PRNGKey(0))
        out[fused] = (set(params), p, m)
    assert out[True][0] == out[False][0]          # same param keys (tied)
    np.testing.assert_allclose(float(out[True][2]["loss"]),
                               float(out[False][2]["loss"]), rtol=1e-5)
    np.testing.assert_allclose(
        float(out[True][2]["precision"]),
        float(out[False][2]["precision"]), rtol=1e-5)
    for k in out[True][1]:
        np.testing.assert_allclose(np.asarray(out[True][1][k]),
                                   np.asarray(out[False][1][k]), atol=2e-5)


def test_flash_backward_kernels_multiblock():
    """The hand-written dq/dkv Pallas backward (interpret mode) across
    MULTIPLE q/kv blocks — exercises the per-block accumulation and the
    causal block-skip guard — against autodiff of the dense reference."""
    q, k, v = _qkv(2, 2, 256, 32)
    cot = jnp.asarray(RNG.standard_normal(q.shape).astype(np.float32))
    for causal in (False, True):
        _, vjp_f = jax.vjp(lambda *a: flash_attention(
            *a, causal, 128, 128, True), q, k, v)
        _, vjp_r = jax.vjp(lambda *a: attention_reference(
            *a, causal), q, k, v)
        for a, b in zip(vjp_f(cot), vjp_r(cot)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-4)


def test_chunk_attention_blockwise_matches_dense_chunk():
    """The ring local step's chunked-flash form vs the dense
    chunk_attention: same (out, lse) and same gradients, including
    cross-chunk causal offsets."""
    from singa_tpu.ops.attention import (chunk_attention,
                                         chunk_attention_blockwise)

    q, k, v = _qkv(1, 2, 256, 16)
    cot = jnp.asarray(RNG.standard_normal(q.shape).astype(np.float32))
    for (q_off, kv_off) in ((0, 0), (256, 0), (0, 256)):
        (o_d, l_d), vjp_d = jax.vjp(
            lambda *a: chunk_attention(*a, True, q_off, kv_off), q, k, v)
        (o_b, l_b), vjp_b = jax.vjp(
            lambda *a: chunk_attention_blockwise(*a, True, q_off, kv_off,
                                                 block_k=64), q, k, v)
        np.testing.assert_allclose(np.asarray(o_b), np.asarray(o_d),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(l_b), np.asarray(l_d),
                                   rtol=1e-4, atol=1e-4)
        for a, b in zip(vjp_b((cot, jnp.zeros_like(l_b))),
                        vjp_d((cot, jnp.zeros_like(l_d)))):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-4)


def test_attention_layer_packed_path_matches_strided():
    """The zero-transpose packed flash path (AttentionLayer fast path)
    against the strided (B,H,S,D) path, forward AND parameter
    gradients, on the same weights."""
    from singa_tpu.core.net import build_net
    from singa_tpu.models.transformer import (synthetic_token_batches,
                                              transformer_lm)

    cfg = transformer_lm(vocab_size=64, num_layers=1, embed_dim=64,
                         num_heads=4, head_dim=16, seq_len=128,
                         batchsize=2)
    net = build_net(cfg, "kTrain",
                    {"data": {"input": (128,), "target": (128,)}})
    params = net.init_params(jax.random.PRNGKey(0))
    batch = next(synthetic_token_batches(2, 128, 64))
    attn = [l for l in net.layers.values()
            if l.cfg.type == "kAttention"][0]
    assert attn._packed_eligible(2, 128, type("C", (), {"mesh": None})())

    def loss_fn(p):
        loss, _, _ = net.apply(p, batch, rng=jax.random.PRNGKey(1),
                               train=False)
        return loss
    l1, g1 = jax.value_and_grad(loss_fn)(params)
    # force the strided path on the same net/params
    attn._packed_eligible = lambda b, s, ctx: False
    l2, g2 = jax.value_and_grad(loss_fn)(params)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    for k in g1:
        np.testing.assert_allclose(np.asarray(g1[k]), np.asarray(g2[k]),
                                   rtol=1e-3, atol=1e-5, err_msg=k)


def test_packed_flash_gqa_matches_expanded_reference():
    """Native GQA in the packed kernels (q heads read their group's kv
    slice in-kernel): forward and all three input grads vs the dense
    reference on expanded kv heads."""
    from singa_tpu.ops.attention import (expand_kv_heads,
                                         flash_attention_packed)

    b, h, hkv, s, d = 2, 8, 2, 256, 16
    q = jnp.asarray(RNG.standard_normal((b, s, h * d)).astype(np.float32))
    k = jnp.asarray(RNG.standard_normal((b, s, hkv * d)).astype(np.float32))
    v = jnp.asarray(RNG.standard_normal((b, s, hkv * d)).astype(np.float32))
    cot = jnp.asarray(RNG.standard_normal(q.shape).astype(np.float32))

    def ref(q, k, v, causal):
        qs = q.reshape(b, s, h, d).transpose(0, 2, 1, 3)
        ks = expand_kv_heads(
            k.reshape(b, s, hkv, d).transpose(0, 2, 1, 3), h)
        vs = expand_kv_heads(
            v.reshape(b, s, hkv, d).transpose(0, 2, 1, 3), h)
        o = attention_reference(qs, ks, vs, causal)
        return o.transpose(0, 2, 1, 3).reshape(b, s, h * d)

    for causal in (False, True):
        out_p, vjp_p = jax.vjp(
            lambda *a: flash_attention_packed(
                *a, h, causal, 128, 128, True, hkv), q, k, v)
        out_r, vjp_r = jax.vjp(lambda *a: ref(*a, causal), q, k, v)
        np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_r),
                                   rtol=1e-3, atol=1e-4)
        for a, r in zip(vjp_p(cot), vjp_r(cot)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                       rtol=1e-3, atol=1e-4)


def test_attention_layer_gqa_packed_matches_strided():
    """A GQA config now takes the packed path single-device; it must
    reproduce the strided expand_kv_heads path exactly."""
    from singa_tpu.core.net import build_net
    from singa_tpu.models.transformer import (synthetic_token_batches,
                                              transformer_lm)

    cfg = transformer_lm(vocab_size=64, num_layers=1, embed_dim=64,
                         num_heads=4, head_dim=16, num_kv_heads=2,
                         seq_len=128, batchsize=2)
    net = build_net(cfg, "kTrain",
                    {"data": {"input": (128,), "target": (128,)}})
    params = net.init_params(jax.random.PRNGKey(0))
    batch = next(synthetic_token_batches(2, 128, 64))
    attn = [l for l in net.layers.values()
            if l.cfg.type == "kAttention"][0]
    assert attn.kv_heads == 2
    assert attn._packed_eligible(2, 128, type("C", (), {"mesh": None})())

    def loss_fn(p):
        loss, _, _ = net.apply(p, batch, rng=jax.random.PRNGKey(1),
                               train=False)
        return loss
    l1, g1 = jax.value_and_grad(loss_fn)(params)
    attn._packed_eligible = lambda b, s, ctx: False
    l2, g2 = jax.value_and_grad(loss_fn)(params)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    for k in g1:
        np.testing.assert_allclose(np.asarray(g1[k]), np.asarray(g2[k]),
                                   rtol=1e-3, atol=1e-5, err_msg=k)


@ring_parity
@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_and_blockwise_paths_agree(causal):
    """Both ring local-step implementations — the Pallas flash unrolled
    rotation (use_flash=True) and the XLA blockwise scan fallback — must
    match the dense reference and each other, gradients included."""
    q, k, v = _qkv(1, 4, 256, 16)
    mesh = make_mesh(seq=8)
    of = ring_attention(q, k, v, mesh, "seq", causal, use_flash=True)
    ob = ring_attention(q, k, v, mesh, "seq", causal, use_flash=False)
    ref = attention_reference(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(of), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(of), np.asarray(ob),
                               rtol=1e-4, atol=1e-5)
    gf = jax.grad(lambda k: ring_attention(
        q, k, v, mesh, "seq", causal, use_flash=True).sum())(k)
    gr = jax.grad(lambda k: attention_reference(
        q, k, v, causal).sum())(k)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                               rtol=1e-4, atol=1e-5)


def _count_packed_traces(monkeypatch):
    """Count traces of the packed forward during jit tracing — proof the
    packed kernel path (not the strided fallback) is the one compiled."""
    from singa_tpu.ops import attention as att
    calls = {"n": 0}
    orig = att._packed_forward

    def counting(*a, **kw):
        calls["n"] += 1
        return orig(*a, **kw)

    monkeypatch.setattr(att, "_packed_forward", counting)
    return calls


@pytest.mark.parametrize("mesh_axes", [dict(data=8), dict(data=4, model=2),
                                       dict(model=2, expert=4)])
def test_packed_path_runs_under_mesh_and_matches_local(monkeypatch,
                                                       mesh_axes):
    """Round-5 un-fencing: DP, DP×TP and TP×EP meshes run the PACKED
    flash path (asserted via a trace counter on the packed forward) and
    reproduce the unsharded step's numerics — loss and updated params."""
    mesh = make_mesh(**mesh_axes)
    cfg = transformer_lm(vocab_size=64, num_layers=2, embed_dim=64,
                         num_heads=4, head_dim=16, num_kv_heads=2,
                         seq_len=128, batchsize=8,
                         moe_every=2, num_experts=4)
    tr = Trainer(cfg, SEQ_SHAPES, donate=False, mesh=mesh)
    tr_local = Trainer(cfg, SEQ_SHAPES, donate=False)
    params, opt = tr.init(0)
    batch = next(synthetic_token_batches(8, 128, 64))
    rng = jax.random.PRNGKey(0)
    p1, o1, m1 = tr_local.train_step(params, opt, batch, 0, rng)

    calls = _count_packed_traces(monkeypatch)
    p_sh = param_shardings(mesh, tr.train_net)
    sp = {k: jax.device_put(v, p_sh[k]) for k, v in params.items()}
    so = {k: {n: jax.device_put(v, p_sh[n]) for n, v in t.items()}
          for k, t in opt.items()}
    sb = jax.tree_util.tree_map(jax.device_put, batch,
                                seq_batch_shardings(mesh, batch))
    p2, o2, m2 = tr.train_step(sp, so, sb, 0, rng)
    assert calls["n"] > 0, "mesh step did not trace the packed kernels"
    assert float(m1["loss"]) == pytest.approx(float(m2["loss"]), rel=1e-4)
    for k in ("attn0/wq", "attn0/wk", "embed/embedding"):
        np.testing.assert_allclose(np.asarray(p1[k]), np.asarray(p2[k]),
                                   rtol=2e-3, atol=1e-5, err_msg=k)


def test_packed_mesh_eligibility_gates():
    """Indivisible head/batch splits and sharded seq/pipe axes fall back
    to the strided path instead of mis-sharding the kernel."""
    from singa_tpu.core.net import build_net

    cfg = transformer_lm(vocab_size=64, num_layers=1, embed_dim=96,
                         num_heads=6, head_dim=16, num_kv_heads=2,
                         seq_len=128, batchsize=2)
    net = build_net(cfg, "kTrain", SEQ_SHAPES)
    attn = [l for l in net.layers.values()
            if l.cfg.type == "kAttention"][0]

    def ctx(mesh):
        return type("C", (), {"mesh": mesh})()

    assert attn._packed_eligible(8, 128, ctx(None))
    assert attn._packed_eligible(8, 128, ctx(make_mesh(data=4, model=2)))
    # kv_heads=2 does not divide model=4
    assert not attn._packed_eligible(8, 128, ctx(make_mesh(data=2,
                                                           model=4)))
    # batch 2 does not divide data=8
    assert not attn._packed_eligible(2, 128, ctx(make_mesh(data=8)))
    # sharded sequence axis is the ring/Ulysses regime, not this one
    assert not attn._packed_eligible(8, 128, ctx(make_mesh(data=4,
                                                           seq=2)))


def test_packed_sharded_helper_matches_reference():
    """packed_attention_sharded == dense reference on expanded KV, for a
    GQA geometry sharded batch-and-heads over data×model."""
    from singa_tpu.ops.attention import expand_kv_heads
    from singa_tpu.parallel.sequence import packed_attention_sharded

    b, h, hkv, s, d = 4, 8, 4, 128, 16
    mesh = make_mesh(data=2, model=4)
    q = jnp.asarray(RNG.standard_normal((b, s, h * d)).astype(np.float32))
    k = jnp.asarray(RNG.standard_normal((b, s, hkv * d)).astype(np.float32))
    v = jnp.asarray(RNG.standard_normal((b, s, hkv * d)).astype(np.float32))

    def ref(causal):
        qs = q.reshape(b, s, h, d).transpose(0, 2, 1, 3)
        ks = expand_kv_heads(k.reshape(b, s, hkv, d).transpose(0, 2, 1, 3), h)
        vs = expand_kv_heads(v.reshape(b, s, hkv, d).transpose(0, 2, 1, 3), h)
        o = attention_reference(qs, ks, vs, causal)
        return o.transpose(0, 2, 1, 3).reshape(b, s, h * d)

    for causal in (False, True):
        out = packed_attention_sharded(q, k, v, mesh, h, hkv, causal,
                                       128, 128)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref(causal)),
                                   rtol=1e-3, atol=1e-4)


def _gqa_qkv(b=2, h=8, hkv=2, s=256, d=16):
    q = jnp.asarray(RNG.standard_normal((b, h, s, d)).astype(np.float32))
    k = jnp.asarray(RNG.standard_normal((b, hkv, s, d)).astype(np.float32))
    v = jnp.asarray(RNG.standard_normal((b, hkv, s, d)).astype(np.float32))
    return q, k, v


def _gqa_ref(q, k, v, causal):
    from singa_tpu.ops.attention import expand_kv_heads
    return attention_reference(q, expand_kv_heads(k, q.shape[1]),
                               expand_kv_heads(v, q.shape[1]), causal)


@ring_parity
@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_gqa_unexpanded_kv(causal):
    """Ring accepts (B, Hkv, S, D) k/v directly: forward parity vs the
    dense reference on expanded heads, plus q AND k gradients (the k
    grad flows through ppermute rotations at Hkv width)."""
    q, k, v = _gqa_qkv()
    mesh = make_mesh(seq=8)
    out = ring_attention(q, k, v, mesh, "seq", causal)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_gqa_ref(q, k, v, causal)),
                               rtol=1e-4, atol=1e-5)
    g1 = jax.grad(lambda q, k: ring_attention(
        q, k, v, mesh, "seq", causal).sum(), argnums=(0, 1))(q, k)
    g2 = jax.grad(lambda q, k: _gqa_ref(q, k, v, causal).sum(),
                  argnums=(0, 1))(q, k)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("seq_size,native", [(2, True), (8, False)])
def test_ulysses_gqa_kv_width(seq_size, native):
    """Ulysses with GQA: hkv_local % nseq == 0 rides the a2a at Hkv
    width (native); otherwise pre-expands.  Both must match the dense
    reference."""
    q, k, v = _gqa_qkv(b=8)
    axes = dict(seq=seq_size)
    axes["data"] = 8 // seq_size
    mesh = make_mesh(**axes)
    out = ulysses_attention(q, k, v, mesh, "seq", True)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_gqa_ref(q, k, v, True)),
                               rtol=1e-4, atol=1e-5)
    # the native case's k/v all-to-alls move Hkv-width arrays
    import re
    txt = jax.jit(lambda q, k, v: ulysses_attention(
        q, k, v, mesh, "seq", True)).lower(q, k, v).compile().as_text()
    a2a = re.findall(r"(?:f32|bf16)\[([0-9,]+)\][^\n]*all-to-all", txt)
    assert a2a, "no all-to-all in the lowered Ulysses step"
    hkv_elems = (8 // axes["data"]) * 2 * (256 // seq_size) * 16
    smallest = min(int(np.prod([int(x) for x in dims.split(",")]))
                   for dims in a2a)
    if native:
        assert smallest <= hkv_elems, (smallest, hkv_elems)
    # non-native: no width claim — XLA may sink the expand broadcast
    # past the a2a on its own; parity above is the contract there


def test_ring_ppermute_rotates_hkv_width():
    """The compiled ring step's collective-permutes move Hkv-head
    chunks, not H-head ones — the round-5 4x ICI saving, asserted in
    lowered HLO so a future re-expansion regression fails loudly."""
    b, h, hkv, s, d = 2, 8, 2, 256, 16
    q, k, v = _gqa_qkv(b, h, hkv, s, d)
    mesh = make_mesh(seq=8)
    chunk = s // 8
    txt = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, mesh, "seq", True)).lower(q, k, v).compile().as_text()
    import re
    perms = re.findall(r"(f32|bf16)\[([0-9,]+)\][^\n]*collective-permute",
                       txt)
    assert perms, "no collective-permute in the lowered ring step"
    shapes = {tuple(int(x) for x in dims.split(",")) for _, dims in perms}
    for shape in shapes:
        assert np.prod(shape) <= b * hkv * chunk * d, (
            f"collective-permute moves {shape}, larger than the "
            f"Hkv-width chunk ({b},{hkv},{chunk},{d})")


def test_gqa_dense_fallback_expands_kv():
    """Non-flash-legal GQA shapes (head_dim % 8 != 0) hit the dense
    fallback, which must expand kv heads — regression for the round-5
    refactor that moved expansion out of the shared path."""
    from singa_tpu.core.net import build_net

    cfg = transformer_lm(vocab_size=32, num_layers=1, embed_dim=48,
                         num_heads=4, head_dim=12, num_kv_heads=2,
                         seq_len=120, batchsize=2)
    shapes = {"data": {"input": (120,), "target": (120,)}}
    net = build_net(cfg, "kTrain", shapes)
    params = net.init_params(jax.random.PRNGKey(0))
    batch = next(synthetic_token_batches(2, 120, 32))
    loss, _, _ = net.apply(params, batch, rng=jax.random.PRNGKey(1),
                           train=False)
    assert np.isfinite(float(loss))
