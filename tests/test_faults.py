"""Fault-tolerant runtime tests: deterministic fault injection
(utils.faults), the Supervisor restore-and-replay state machine
(core.supervisor), hardened Prefetcher/Shard/elastic failure paths.

The acceptance property (ISSUE 1): a seeded schedule that preempts
training at step k and tears one checkpoint is FULLY recovered by the
Supervisor — resume from the last *valid* snapshot, replay data to the
right offset, and land on step-N params identical to an uninterrupted
run."""

import threading
import time

import numpy as np
import pytest

from singa_tpu.config.schema import UpdaterConfig, model_config_from_dict
from singa_tpu.core.supervisor import Supervisor, TrainingAborted
from singa_tpu.core.trainer import Trainer
from singa_tpu.data.pipeline import (PipelineStats, PrefetchError,
                                     Prefetcher, shard_batches)
from singa_tpu.data.shard import Shard, ShardError
from singa_tpu.data.synthetic import synthetic_image_batches
from singa_tpu.utils import checkpoint as ckpt_mod
from singa_tpu.utils.faults import (Backoff, FaultError, FaultSchedule,
                                    FaultSpec, Preemption, inject,
                                    maybe_fault)

pytestmark = pytest.mark.faults

SHAPES = {"data": {"pixel": (28, 28), "label": ()}}


def _mlp_cfg(train_steps=12, ckpt_freq=4):
    return model_config_from_dict({
        "name": "faults-mlp", "train_steps": train_steps,
        "checkpoint_frequency": ckpt_freq,
        "updater": {"type": "kSGD", "base_learning_rate": 0.01,
                    "learning_rate_change_method": "kFixed"},
        "neuralnet": {"layer": [
            {"name": "data", "type": "kShardData",
             "data_param": {"batchsize": 8}},
            {"name": "mnist", "type": "kMnistImage", "srclayers": "data",
             "mnist_param": {"norm_a": 255.0}},
            {"name": "label", "type": "kLabel", "srclayers": "data"},
            {"name": "ip1", "type": "kInnerProduct", "srclayers": "mnist",
             "inner_product_param": {"num_output": 16},
             "param": [{"name": "w1",
                        "init_method": "kUniformSqrtFanIn"},
                       {"name": "b1"}]},
            {"name": "ip2", "type": "kInnerProduct", "srclayers": "ip1",
             "inner_product_param": {"num_output": 10},
             "param": [{"name": "w2",
                        "init_method": "kUniformSqrtFanIn"},
                       {"name": "b2"}]},
            {"name": "loss", "type": "kSoftmaxLoss",
             "srclayers": ["ip2", "label"]}]}})


def _data_factory():
    # deterministic batch sequence: a fresh generator replays the same
    # stream, which is what lets restore-at-step-s + skip-s reproduce
    # the uninterrupted trajectory exactly
    return synthetic_image_batches(8, seed=3, stream_seed=104)


_NO_WAIT = Backoff(base=0.0, cap=0.0, jitter=0.0)


# -- FaultSchedule ---------------------------------------------------------
def test_fault_schedule_parse_fires_once_at_visit():
    sch = FaultSchedule.parse("step.train@2:preempt, ckpt.save@0")
    with inject(sch):
        assert maybe_fault("step.train") is None      # visit 0
        assert maybe_fault("step.train") is None      # visit 1
        with pytest.raises(Preemption):
            maybe_fault("step.train")                 # visit 2 fires
        assert maybe_fault("step.train") is None      # one-shot
        with pytest.raises(FaultError):
            maybe_fault("ckpt.save")                  # default kind
    assert maybe_fault("step.train") is None          # inactive outside
    assert sch.visits("step.train") == 4
    assert [f.kind for f in sch.fired] == ["preempt", "error"]


def test_fault_schedule_rejects_unknown_site_and_kind():
    with pytest.raises(ValueError, match="unknown fault site"):
        FaultSchedule.parse("data.nope@1")
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultSpec(site="step.train", at=0, kind="explode")


def test_fault_schedule_seeded_rates_deterministic():
    fires = []
    for _ in range(2):
        sch = FaultSchedule(rates={"data.prefetch": 0.5}, seed=42)
        hits = []
        for i in range(20):
            try:
                sch.visit("data.prefetch")
            except FaultError:
                hits.append(i)
        fires.append(hits)
    assert fires[0] == fires[1] and 0 < len(fires[0]) < 20


# -- Supervisor acceptance -------------------------------------------------
def test_supervisor_recovers_preemption_and_torn_checkpoint(
        tmp_path, monkeypatch):
    """Preempt at step 10 with the step-8 snapshot torn on disk: the
    Supervisor must restore the step-4 snapshot (the last VALID one),
    fast-forward the data stream, and finish with params identical to
    an uninterrupted run."""
    monkeypatch.setattr(ckpt_mod, "_HAVE_ORBAX", False)

    tr0 = Trainer(_mlp_cfg(), SHAPES, log_fn=lambda s: None, donate=False)
    p, o = tr0.init(seed=0)
    p_ref, _, _ = tr0.run(p, o, _data_factory(), seed=0)

    logs = []
    tr1 = Trainer(_mlp_cfg(), SHAPES, log_fn=logs.append, donate=False)
    # cadence saves: step 4 = visit 0, step 8 = visit 1 (torn);
    # step.train visit 10 = the loop iteration that would run step 10
    sched = FaultSchedule([FaultSpec("ckpt.save", 1, "torn"),
                           FaultSpec("step.train", 10, "preempt")])
    sup = Supervisor(tr1, str(tmp_path), max_restarts=2,
                     backoff=_NO_WAIT, log=logs.append)
    with inject(sched):
        p_sup, _, _ = sup.run(_data_factory, seed=0)

    for k in p_ref:
        assert np.all(np.isfinite(np.asarray(p_ref[k]))), k
        np.testing.assert_allclose(np.asarray(p_sup[k]),
                                   np.asarray(p_ref[k]),
                                   rtol=0, atol=0, err_msg=k)
    assert [f.kind for f in sup.failures] == ["preemption"]
    assert any("resumed from step 4" in l for l in logs), logs
    assert any("corrupt or partial" in l for l in logs), logs
    # both fault specs actually fired
    assert sorted(f.site for f in sched.fired) == \
        ["ckpt.save", "step.train"]


def test_supervisor_transient_error_backs_off_and_recovers(tmp_path):
    """A one-shot step failure (flaky data read): restore + replay with
    backoff still reaches the uninterrupted trajectory — including on
    the orbax checkpoint path when available."""
    tr0 = Trainer(_mlp_cfg(train_steps=6, ckpt_freq=2), SHAPES,
                  log_fn=lambda s: None, donate=False)
    p, o = tr0.init(seed=0)
    p_ref, _, _ = tr0.run(p, o, _data_factory(), seed=0)

    tr1 = Trainer(_mlp_cfg(train_steps=6, ckpt_freq=2), SHAPES,
                  log_fn=lambda s: None, donate=False)
    sched = FaultSchedule([FaultSpec("step.train", 3, "error")])
    sup = Supervisor(tr1, str(tmp_path), max_restarts=2,
                     backoff=Backoff(base=0.01, cap=0.02, seed=1),
                     log=lambda s: None)
    t0 = time.monotonic()
    with inject(sched):
        p_sup, _, _ = sup.run(_data_factory, seed=0)
    assert time.monotonic() - t0 >= 0.01        # backoff actually slept
    for k in p_ref:
        assert np.all(np.isfinite(np.asarray(p_ref[k]))), k
        np.testing.assert_allclose(np.asarray(p_sup[k]),
                                   np.asarray(p_ref[k]),
                                   rtol=0, atol=0, err_msg=k)
    assert [f.kind for f in sup.failures] == ["error"]


def test_supervisor_budget_exhausted_raises_structured(tmp_path):
    tr = Trainer(_mlp_cfg(train_steps=4, ckpt_freq=2), SHAPES,
                 log_fn=lambda s: None, donate=False)
    sup = Supervisor(tr, str(tmp_path), max_restarts=2,
                     backoff=_NO_WAIT, log=lambda s: None)
    # every loop iteration fails: the budget must stop the crash loop
    sched = FaultSchedule(rates={"step.train": 1.0}, seed=0)
    with inject(sched), pytest.raises(TrainingAborted) as ei:
        sup.run(_data_factory, seed=0)
    aborted = ei.value
    assert len(aborted.failures) == 3           # first try + 2 restarts
    assert all(f.kind == "error" for f in aborted.failures)
    assert "restart budget" in str(aborted)
    assert "attempt 1" in str(aborted)          # log is in the message


def test_supervisor_without_workspace_replays_from_zero():
    tr0 = Trainer(_mlp_cfg(train_steps=4, ckpt_freq=0), SHAPES,
                  log_fn=lambda s: None, donate=False)
    p, o = tr0.init(seed=0)
    p_ref, _, _ = tr0.run(p, o, _data_factory(), seed=0)

    logs = []
    tr1 = Trainer(_mlp_cfg(train_steps=4, ckpt_freq=0), SHAPES,
                  log_fn=logs.append, donate=False)
    sup = Supervisor(tr1, workspace=None, max_restarts=1,
                     backoff=_NO_WAIT, log=logs.append)
    with inject(FaultSchedule([FaultSpec("step.train", 2, "error")])):
        p_sup, _, _ = sup.run(_data_factory, seed=0)
    for k in p_ref:
        assert np.all(np.isfinite(np.asarray(p_ref[k]))), k
        np.testing.assert_allclose(np.asarray(p_sup[k]),
                                   np.asarray(p_ref[k]),
                                   rtol=0, atol=0, err_msg=k)
    assert any("no workspace" in l for l in logs)


# -- Prefetcher hardening --------------------------------------------------
def test_prefetcher_dead_producer_raises_not_hangs():
    class DeadProducer(Prefetcher):
        def _run(self):   # dies without sentinel or error
            return

    it = DeadProducer(iter([1, 2]), poll_timeout=0.05)
    it._thread.join(timeout=2.0)
    with pytest.raises(PrefetchError, match="died"):
        next(it)


def test_prefetcher_stall_timeout_bounds_the_wait():
    release = threading.Event()

    def slow():
        yield 1
        release.wait(10.0)
        yield 2

    it = Prefetcher(slow(), poll_timeout=0.05, stall_timeout=0.3)
    assert next(it) == 1
    with pytest.raises(PrefetchError, match="stalled"):
        next(it)
    release.set()
    it.close()


def test_prefetcher_quarantines_injected_corrupt_records():
    sched = FaultSchedule([FaultSpec("data.decode", 1, "corrupt")])
    with inject(sched):
        it = Prefetcher(iter(range(5)), poll_timeout=0.05)
        got = list(it)
    # order preserved, nothing dropped, the bad record counted
    assert got == [0, 1, 2, 3, 4]
    assert it.stats.quarantined == 1


def test_prefetcher_close_unblocks_full_queue():
    it = Prefetcher(iter(range(1000)), depth=1, poll_timeout=0.05)
    assert next(it) == 0
    it.close()
    assert not it._thread.is_alive()


def test_shard_batches_quarantines_corrupt_record(tmp_path):
    from test_data import make_record
    with Shard(str(tmp_path), Shard.KCREATE) as sh:
        for i in range(8):
            rec, _ = make_record(i % 3, side=4, seed=i)
            sh.insert(f"r{i:03d}", rec.encode())
        # a record whose bytes fail the protobuf tag-walk
        sh.insert("rbad", b"\x12\xff")
    stats = PipelineStats()
    batches = list(shard_batches(str(tmp_path), batchsize=4, loop=False,
                                 stats=stats))
    assert sum(b["data"]["pixel"].shape[0] for b in batches) == 8
    assert stats.quarantined == 1
    assert stats.passes == 1


# -- Shard close semantics -------------------------------------------------
def test_shard_exit_flushes_when_body_raises(tmp_path):
    from test_data import make_record
    rec, _ = make_record(1, side=4, seed=0)
    with pytest.raises(RuntimeError, match="boom"):
        with Shard(str(tmp_path), Shard.KCREATE) as sh:
            sh.insert("k0", rec.encode())
            raise RuntimeError("boom")
    assert sh.closed
    rd = Shard(str(tmp_path), Shard.KREAD)
    assert rd.count() == 1     # the insert survived the crashed body
    rd.close()


def test_shard_insert_after_close_raises(tmp_path):
    sh = Shard(str(tmp_path), Shard.KCREATE)
    sh.insert("k", b"\x01")
    sh.close()
    sh.close()                 # idempotent
    with pytest.raises(ShardError, match="closed"):
        sh.insert("k2", b"\x02")


# -- elastic sync retry/skip -----------------------------------------------
def _elastic_ctl(**kw):
    from singa_tpu.parallel.elastic import ElasticController
    cfg = UpdaterConfig(type="kSGD", base_learning_rate=0.1,
                        param_type="Elastic", moving_rate=0.5,
                        sync_frequency=1, warmup_steps=0)
    return ElasticController(cfg, log_fn=lambda s: None,
                             sync_backoff=_NO_WAIT, **kw)


def test_elastic_sync_retries_transient_failure():
    import jax.numpy as jnp
    ctl = _elastic_ctl()
    params = {"w": jnp.full((4,), 2.0)}
    params = ctl.maybe_sync(0, params)          # lazy center init
    # visit 0 fails, the in-round retry (visit 1) succeeds
    with inject(FaultSchedule([FaultSpec("sync.elastic", 0, "error")])):
        out = ctl.maybe_sync(1, params)
    assert ctl.skipped_rounds == 0
    # the exchange actually happened: replica moved toward the center
    np.testing.assert_allclose(np.asarray(out["w"]), 2.0)
    assert ctl.center is not None


def test_elastic_sync_skips_round_after_budget():
    import jax.numpy as jnp
    ctl = _elastic_ctl(sync_retries=2)
    params = {"w": jnp.full((4,), 2.0)}
    params = ctl.maybe_sync(0, params)
    center_before = np.asarray(ctl.center["w"]).copy()
    with inject(FaultSchedule(rates={"sync.elastic": 1.0}, seed=0)):
        out = ctl.maybe_sync(1, {"w": jnp.full((4,), 5.0)})
    assert ctl.skipped_rounds == 1
    # degraded, not dead: params and center both unchanged
    np.testing.assert_allclose(np.asarray(out["w"]), 5.0)
    np.testing.assert_allclose(np.asarray(ctl.center["w"]), center_before)


def test_user_hook_exception_is_isolated_not_a_training_error(tmp_path):
    """A raising user hook must not look like a step failure: before
    the fix it escaped Trainer.run, was recorded as a training "error",
    and burned a Supervisor restart (plus a pointless restore+replay)."""
    logs = []
    tr = Trainer(_mlp_cfg(train_steps=6, ckpt_freq=2), SHAPES,
                 log_fn=logs.append, donate=False)
    sup = Supervisor(tr, str(tmp_path), max_restarts=0,
                     backoff=_NO_WAIT, log=logs.append)
    seen = []

    def bad_hook(step, metrics):
        if step == 2:
            raise RuntimeError("observer bug")
        seen.append(step)

    p, _, _ = sup.run(_data_factory, seed=0, hooks=[bad_hook])
    # no restart burned, every other step's hook still fired, loud log
    assert sup.failures == []
    assert seen == [0, 1, 3, 4, 5]
    assert any("user hook" in l and "observer bug" in l for l in logs)
    for k in p:
        assert np.all(np.isfinite(np.asarray(p[k]))), k


def test_trainer_restores_signal_handlers_after_mid_loop_failure(
        tmp_path):
    """An exception escaping the run loop must not leave the trainer's
    SIGTERM/SIGINT hooks installed (the Supervisor would miss real
    preemption signals on the next attempt)."""
    import signal
    tr = Trainer(_mlp_cfg(train_steps=6, ckpt_freq=2), SHAPES,
                 log_fn=lambda s: None, donate=False)
    p, o = tr.init(seed=0)
    before = signal.getsignal(signal.SIGTERM)
    with inject(FaultSchedule([FaultSpec("step.train", 1, "error")])):
        with pytest.raises(FaultError):
            tr.run(p, o, _data_factory(), seed=0,
                   workspace=str(tmp_path))
    assert signal.getsignal(signal.SIGTERM) is before
