"""Overlapped host/device feed pipeline (singa_tpu.data.feed): staging
buffers, the DeviceFeeder stage, sharded chunk placement, and the
acceptance property of ISSUE 2 — the overlapped loop's trajectory is
BIT-identical to the synchronous loop's, including a run killed
mid-chunk and resumed via the Supervisor."""

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

from singa_tpu.config.schema import model_config_from_dict
from singa_tpu.core.supervisor import Supervisor
from singa_tpu.core.trainer import Trainer
from singa_tpu.data.feed import (ChunkStager, DeviceFeeder, FeedError,
                                 staging_buffer)
from singa_tpu.data.synthetic import synthetic_image_batches
from singa_tpu.utils.faults import (Backoff, FaultError, FaultSchedule,
                                    FaultSpec, inject)

SHAPES = {"data": {"pixel": (28, 28), "label": ()}}
_NO_WAIT = Backoff(base=0.0, cap=0.0, jitter=0.0)


def _mlp_cfg(train_steps=12, ckpt_freq=0, display_freq=0):
    return model_config_from_dict({
        "name": "feed-mlp", "train_steps": train_steps,
        "checkpoint_frequency": ckpt_freq,
        "display_frequency": display_freq,
        "updater": {"type": "kSGD", "base_learning_rate": 0.01,
                    "learning_rate_change_method": "kFixed"},
        "neuralnet": {"layer": [
            {"name": "data", "type": "kShardData",
             "data_param": {"batchsize": 8}},
            {"name": "mnist", "type": "kMnistImage", "srclayers": "data",
             "mnist_param": {"norm_a": 255.0}},
            {"name": "label", "type": "kLabel", "srclayers": "data"},
            {"name": "ip1", "type": "kInnerProduct", "srclayers": "mnist",
             "inner_product_param": {"num_output": 16},
             "param": [{"name": "w1",
                        "init_method": "kUniformSqrtFanIn"},
                       {"name": "b1"}]},
            {"name": "ip2", "type": "kInnerProduct", "srclayers": "ip1",
             "inner_product_param": {"num_output": 10},
             "param": [{"name": "w2",
                        "init_method": "kUniformSqrtFanIn"},
                       {"name": "b2"}]},
            {"name": "loss", "type": "kSoftmaxLoss",
             "srclayers": ["ip2", "label"]}]}})


def _data_factory():
    return synthetic_image_batches(8, seed=3, stream_seed=104)


def _run(cfg, scan_chunk, feeder, seed=0, workspace=None):
    losses = {}
    tr = Trainer(cfg, SHAPES, log_fn=lambda s: None, donate=False)
    p, o = tr.init(seed=seed)
    p, o, _ = tr.run(p, o, _data_factory(), seed=seed,
                     scan_chunk=scan_chunk, feeder=feeder,
                     workspace=workspace,
                     hooks=[lambda s, m: losses.__setitem__(
                         s, float(m["loss"]))])
    return p, losses, tr


# -- staging buffers -------------------------------------------------------
def test_staging_buffer_defeats_zero_copy_aliasing():
    """XLA's CPU client zero-copy ALIASES 64-byte-aligned host buffers
    on device_put (alignment is allocator luck) — staging buffers must
    deliberately miss that alignment while staying element-aligned, so
    a reused buffer can never corrupt a previously placed chunk."""
    for shape, dt in (((4, 8, 28, 28), np.uint8), ((4, 16), np.float32),
                      ((3, 7), np.int32)):
        buf = staging_buffer(shape, dt)
        assert buf.shape == shape and buf.dtype == dt
        assert buf.ctypes.data % 64 != 0
        assert buf.ctypes.data % np.dtype(dt).itemsize == 0
        buf[:] = 0   # writable
        placed = jax.device_put(buf)
        placed.block_until_ready()
        buf[:] = 1   # overwrite AFTER placement, like chunk reuse
        assert not np.asarray(placed).any()   # the copy is untouched


def test_chunk_stager_reuses_buffers_and_matches_stack():
    st = ChunkStager(capacity=4)
    a = [{"x": np.full((8,), i, np.float32),
          "y": np.full((8, 2), -i, np.int32)} for i in range(4)]
    b = [{"x": np.full((8,), 100 + i, np.float32),
          "y": np.full((8, 2), i, np.int32)} for i in range(4)]
    pa = st.stage(a)
    addrs = [x.ctypes.data for x in st._sets[0]]
    pb = st.stage(b)
    assert [x.ctypes.data for x in st._sets[0]] == addrs   # no realloc
    np.testing.assert_array_equal(np.asarray(pa["x"]),
                                  np.stack([f["x"] for f in a]))
    np.testing.assert_array_equal(np.asarray(pb["y"]),
                                  np.stack([f["y"] for f in b]))
    # shorter chunk reuses a view of the same buffers
    pc = st.stage(a[:2])
    assert np.asarray(pc["x"]).shape == (2, 8)
    # dtype canonicalization matches jnp.asarray (f64 -> f32 w/o x64)
    pd = st.stage([{"x": np.zeros((4,), np.float64)}] * 2)
    assert np.asarray(pd["x"]).dtype == np.float32


def test_chunk_stager_rotation_never_corrupts_inflight_chunks():
    """With rotating buffer sets (the feeder's mode) a placed chunk is
    handed over BEFORE its transfer is awaited — later stage calls must
    never overwrite the bytes backing an earlier chunk."""
    st = ChunkStager(capacity=2, rotate=3)
    placed = [st.stage([{"x": np.full((4,), 10 * c + r, np.float32)}
                        for r in range(2)]) for c in range(9)]
    for c, p in enumerate(placed):   # all 9 survive 3 full rotations
        np.testing.assert_array_equal(
            np.asarray(p["x"]),
            np.stack([np.full((4,), 10 * c + r, np.float32)
                      for r in range(2)]))


def test_chunk_stager_rejects_empty_chunk():
    with pytest.raises(ValueError, match="empty chunk"):
        ChunkStager().stage([])


# -- DeviceFeeder ----------------------------------------------------------
def test_feeder_delivers_planned_chunks_in_order():
    src = ({"x": np.full((4,), i, np.float32)} for i in range(10))
    fd = DeviceFeeder(src, [(0, 3), (3, 3), (6, 2)], depth=2, capacity=3)
    got = [fd.get() for _ in range(3)]
    assert [(c.start, c.length) for c in got] == [(0, 3), (3, 3), (6, 2)]
    np.testing.assert_array_equal(np.asarray(got[2].batches["x"]),
                                  [[6.0] * 4, [7.0] * 4])
    with pytest.raises(StopIteration):   # plan exhausted, clean end
        fd.get()
    assert fd.chunks_staged == 3
    # the feeder consumed EXACTLY the planned batches (8 of 10): the
    # Supervisor's one-batch-per-step fast-forward contract
    assert next(src)["x"][0] == 8.0
    fd.close()
    fd.close()   # idempotent


def test_feeder_propagates_producer_error():
    def bad():
        yield {"x": np.zeros((2,), np.float32)}
        yield {"x": np.zeros((2,), np.float32)}
        raise RuntimeError("boom mid-pull")
    fd = DeviceFeeder(bad(), [(0, 2), (2, 2)], poll_timeout=0.05)
    fd.get()
    with pytest.raises(RuntimeError, match="boom mid-pull"):
        fd.get()
    fd.close()


def test_feeder_dead_producer_raises_not_hangs():
    class Dead(DeviceFeeder):
        def _run(self):   # dies without sentinel or error
            return
    fd = Dead(iter([]), [(0, 1)], poll_timeout=0.05)
    fd._thread.join(timeout=2.0)
    with pytest.raises(FeedError, match="died"):
        fd.get()


def test_feed_stage_fault_site_fires_on_producer_thread():
    sched = FaultSchedule([FaultSpec("feed.stage", 1, "error")])
    src = ({"x": np.zeros((2,), np.float32)} for _ in range(8))
    with inject(sched):
        fd = DeviceFeeder(src, [(0, 2), (2, 2)], poll_timeout=0.05)
        fd.get()                       # chunk 0 stages clean
        with pytest.raises(FaultError, match="feed.stage"):
            fd.get()                   # chunk 1's staging was injected
    fd.close()
    assert [f.site for f in sched.fired] == ["feed.stage"]


# -- sharded chunk placement ----------------------------------------------
def test_place_chunk_shards_batch_dim_not_scan_dim():
    from singa_tpu.parallel import chunk_shardings, make_mesh, place_chunk
    mesh = make_mesh(jax.devices())   # conftest: 8 CPU devices -> data=8
    chunk = {"pixel": np.zeros((4, 16, 28, 28), np.uint8),
             "label": np.zeros((4, 16), np.int32)}
    placed = place_chunk(mesh, chunk)
    assert placed["pixel"].sharding.spec == P(None, "data")
    assert placed["label"].sharding.spec == P(None, "data")
    # token layouts additionally shard the sequence dim
    sh = chunk_shardings(mesh, {"input": np.zeros((4, 8, 32))},
                         seq_axis="seq")
    assert sh["input"].spec == P(None, "data", "seq")


def test_trainer_chunk_place_routes_fallback_through_mesh(monkeypatch):
    """Satellite: the feeder-OFF chunked path must land stacked chunks
    with the batch-dim sharding too (the old jnp.stack put them on the
    default device)."""
    from singa_tpu.parallel import make_mesh
    mesh = make_mesh(jax.devices())
    tr = Trainer(_mlp_cfg(train_steps=2), SHAPES, log_fn=lambda s: None,
                 donate=False, mesh=mesh)
    placed = tr._chunk_place({"pixel": np.zeros((2, 8, 28, 28), np.uint8)})
    assert placed["pixel"].sharding.spec == P(None, "data")


# -- acceptance: bit-identical trajectories -------------------------------
def test_overlapped_loop_bit_identical_to_synchronous():
    """Feeder ON vs OFF at the same scan_chunk: identical compiled
    programs fed through different host paths — params AND the whole
    per-step metric trajectory must match bit for bit.  Both also agree
    with the per-step loop to float tolerance (different programs)."""
    cfg = _mlp_cfg(train_steps=12, display_freq=4)
    p_sync, l_sync, _ = _run(_mlp_cfg(12, display_freq=4), 4, False)
    p_feed, l_feed, tr = _run(_mlp_cfg(12, display_freq=4), 4, True)
    assert sorted(l_feed) == list(range(12))
    for s in range(12):
        assert l_sync[s] == l_feed[s], s          # bit-identical metrics
    for k in p_sync:
        np.testing.assert_array_equal(np.asarray(p_feed[k]),
                                      np.asarray(p_sync[k]), err_msg=k)
    # the timer now reports the split phases
    assert {"wait", "stage", "train"} <= set(tr.timer.times)
    p_step, l_step, _ = _run(cfg, 0, None)
    for s in range(12):
        np.testing.assert_allclose(l_feed[s], l_step[s], rtol=1e-5)
    for k in p_step:
        np.testing.assert_allclose(np.asarray(p_feed[k]),
                                   np.asarray(p_step[k]), atol=2e-5,
                                   err_msg=k)


@pytest.mark.faults
@pytest.mark.parametrize("spec", [
    FaultSpec("step.train", 2, "preempt"),   # killed mid-run, at a chunk
    FaultSpec("feed.stage", 1, "error"),     # staging thread failure
], ids=["preempt-mid-chunk", "feed-stage-error"])
def test_overlapped_run_killed_and_resumed_bit_identical(tmp_path, spec):
    """A run killed mid-chunk (or whose staging thread fails) and
    resumed via the Supervisor must land on the exact uninterrupted
    trajectory — the feeder's chunk plan restarts at the restored step
    and the fast-forwarded iterator replays the same batches."""
    p_ref, l_ref, _ = _run(_mlp_cfg(12, ckpt_freq=4), 4, False)

    losses = {}
    tr = Trainer(_mlp_cfg(12, ckpt_freq=4), SHAPES,
                 log_fn=lambda s: None, donate=False)
    sup = Supervisor(tr, str(tmp_path), max_restarts=2,
                     backoff=_NO_WAIT, log=lambda s: None)
    sched = FaultSchedule([spec])
    with inject(sched):
        p_sup, _, _ = sup.run(_data_factory, seed=0, scan_chunk=4,
                              feeder=True,
                              hooks=[lambda s, m: losses.__setitem__(
                                  s, float(m["loss"]))])
    assert [f.site for f in sched.fired] == [spec.site]
    assert len(sup.failures) == 1
    for k in p_ref:
        np.testing.assert_array_equal(np.asarray(p_sup[k]),
                                      np.asarray(p_ref[k]), err_msg=k)
    # every step's metrics reached the hooks exactly once-or-replayed,
    # with the uninterrupted values
    for s in range(12):
        assert losses[s] == l_ref[s], s


def test_evaluate_feeder_matches_inline_staging():
    cfg = _mlp_cfg(train_steps=2)
    cfg.test_steps = 7
    tr = Trainer(cfg, SHAPES, log_fn=lambda s: None, donate=False)
    p, _ = tr.init(seed=0)
    a = tr.evaluate(p, _data_factory(), 7, tr.test_step, scan_chunk=3,
                    feeder=True)
    b = tr.evaluate(p, _data_factory(), 7, tr.test_step, scan_chunk=3,
                    feeder=False)
    assert a.keys() == b.keys()
    for k in a:
        assert a[k] == b[k], k     # same chunks, same program: exact
    c = tr.evaluate(p, _data_factory(), 7, tr.test_step, scan_chunk=1)
    for k in a:
        np.testing.assert_allclose(a[k], c[k], rtol=1e-5)


def test_display_logs_identical_across_feed_paths():
    """The deferred metric ring must emit the same step-N display lines
    in the same order as the synchronous loop."""
    def logs_with(feeder):
        logs = []
        tr = Trainer(_mlp_cfg(12, display_freq=3), SHAPES,
                     log_fn=logs.append, donate=False)
        p, o = tr.init(seed=0)
        tr.run(p, o, _data_factory(), seed=0, scan_chunk=4,
               feeder=feeder)
        return [l.split(":")[0] for l in logs if l.startswith("step-")]
    assert logs_with(True) == logs_with(False)
