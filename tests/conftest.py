import os

# Tests run on a virtual 8-device CPU platform: sharding/collective tests
# need a mesh, and unit numerics want CPU float32 (the real hardware here
# is a single TPU chip behind the experimental `axon` platform, whose
# interpreter-startup hook pins jax_platforms="axon,cpu" via jax.config —
# env vars alone cannot override it, so we update the config directly).
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
from jax.extend.backend import clear_backends  # noqa: E402

clear_backends()  # no-op when nothing initialized yet

assert jax.default_backend() == "cpu", jax.default_backend()
assert len(jax.devices()) == 8, jax.devices()
