import os

# Force a virtual 8-device CPU platform for all tests: sharding/collective
# tests need a mesh, and unit numerics don't need the real TPU (which is a
# single chip behind a tunnel in this environment anyway).
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
