"""Mesh + sharding tests on the virtual 8-device CPU mesh.

Validates the TPU-native successors of the reference's partitioner
(§2.2 of SURVEY.md): DP batch sharding with XLA-inserted gradient psum,
TP weight sharding per ParamProto.partition_dim.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from singa_tpu.config import load_model_config
from singa_tpu.config.schema import ClusterConfig
from singa_tpu.core.trainer import Trainer
from singa_tpu.parallel import (batch_shardings, make_mesh,
                                mesh_from_cluster, param_shardings)

MNIST_SHAPES = {"data": {"pixel": (28, 28), "label": ()}}


def _batch(bs, seed=0):
    rng = np.random.default_rng(seed)
    return {"data": {
        "pixel": rng.integers(0, 256, (bs, 28, 28)).astype(np.uint8),
        "label": rng.integers(0, 10, (bs,)).astype(np.int32)}}


def test_make_mesh_axes():
    mesh = make_mesh(model=2)
    assert dict(mesh.shape) == {"data": 4, "model": 2, "pipe": 1,
                                "seq": 1, "expert": 1}
    with pytest.raises(ValueError):
        make_mesh(model=3)  # 8 not divisible


def test_mesh_from_cluster_legacy_mapping():
    cluster = ClusterConfig(nworkers=4, nprocs_per_group=2,
                            nthreads_per_procs=2)
    # ngroups=2 x group_size=4 == 8 devices: exact topology mapping
    mesh = mesh_from_cluster(cluster, "kLayerPartition")
    assert mesh.shape["model"] == 4   # group_size → neuron split
    assert mesh.shape["data"] == 2    # ngroups → group dp
    mesh2 = mesh_from_cluster(cluster, "kDataPartition")
    assert mesh2.shape["data"] == 8   # both levels split the batch


def test_mesh_from_cluster_mismatch_warns(capsys):
    """§2.2-2/3 group structure that cannot map exactly onto the
    device count must warn loudly, not silently reshape (VERDICT r2
    weak 5)."""
    # topology 1x3 over 8 devices: group_size 3 does not divide 8
    cluster = ClusterConfig(nworkers=1, nprocs_per_group=1,
                            nthreads_per_procs=3)
    mesh = mesh_from_cluster(cluster, "kLayerPartition")
    err = capsys.readouterr().err
    assert "does not divide" in err and "!= 8 devices" in err
    assert mesh.shape["model"] == 1   # gcd(3, 8)
    # matching topology stays silent
    ok = ClusterConfig(nworkers=2, nprocs_per_group=1,
                       nthreads_per_procs=4)
    mesh_from_cluster(ok, "kLayerPartition")
    assert "warning" not in capsys.readouterr().err


def test_mesh_from_cluster_explicit_axes():
    cluster = ClusterConfig(data_parallel=2, tensor_parallel=2,
                            pipeline_parallel=2)
    mesh = mesh_from_cluster(cluster)
    assert (mesh.shape["data"], mesh.shape["model"], mesh.shape["pipe"]) \
        == (2, 2, 2)


def test_dp_sharded_step_matches_single_device():
    """The sharded train step must produce the same numbers as the
    unsharded one — GSPMD inserts the gradient psum (the reference's
    in-process allreduce, param_manager.cc:166-187)."""
    cfg = load_model_config("/root/reference/examples/mnist/conv.conf")
    cfg.train_steps = 3
    for layer in cfg.neuralnet.layer:
        if layer.data_param:
            layer.data_param.batchsize = 16
    trainer = Trainer(cfg, MNIST_SHAPES, donate=False)
    params, opt = trainer.init(seed=0)
    batch = _batch(16)
    rng = jax.random.PRNGKey(0)

    # single-device result
    p1, o1, m1 = trainer.train_step(params, opt, batch, 0, rng)

    # dp=8 sharded result
    mesh = make_mesh()
    b_sh = batch_shardings(mesh, batch)
    sharded_batch = jax.tree_util.tree_map(jax.device_put, batch, b_sh)
    p_sh = param_shardings(mesh, trainer.train_net)
    sp = {k: jax.device_put(v, p_sh[k]) for k, v in params.items()}
    so = {k: {n: jax.device_put(v, p_sh[n]) for n, v in t.items()}
          for k, t in opt.items()}
    p2, o2, m2 = trainer.train_step(sp, so, sharded_batch, 0, rng)

    assert float(m1["loss"]) == pytest.approx(float(m2["loss"]), rel=1e-5)
    np.testing.assert_allclose(np.asarray(p1["conv1/weight"]),
                               np.asarray(p2["conv1/weight"]),
                               rtol=1e-5, atol=1e-6)


def test_tp_weight_sharding_from_partition_dim():
    cfg = load_model_config("/root/reference/examples/mnist/conv.conf")
    trainer = Trainer(cfg, MNIST_SHAPES, donate=False)
    mesh = make_mesh(model=2)
    shardings = param_shardings(mesh, trainer.train_net)
    # ip1 weight partition_dim=1 (neuron dim) → sharded over "model"
    assert shardings["ip1/weight"].spec == P(None, "model")
    # conv weight dim0 = num_filters=20 divisible by 2 → sharded
    assert shardings["conv1/weight"].spec == P("model", None)
    # odd dims stay replicated: conv bias (20,)%2==0 so sharded too
    assert shardings["conv2/bias"].spec == P("model")


def test_tp_sharded_step_matches_single_device():
    cfg = load_model_config("/root/reference/examples/mnist/conv.conf")
    for layer in cfg.neuralnet.layer:
        if layer.data_param:
            layer.data_param.batchsize = 8
    trainer = Trainer(cfg, MNIST_SHAPES, donate=False)
    params, opt = trainer.init(seed=1)
    batch = _batch(8, seed=1)
    rng = jax.random.PRNGKey(1)
    p1, o1, m1 = trainer.train_step(params, opt, batch, 0, rng)

    mesh = make_mesh(model=2)   # dp=4 × tp=2
    p_sh = param_shardings(mesh, trainer.train_net)
    b_sh = batch_shardings(mesh, batch)
    sp = {k: jax.device_put(v, p_sh[k]) for k, v in params.items()}
    so = {k: {n: jax.device_put(v, p_sh[n]) for n, v in t.items()}
          for k, t in opt.items()}
    sb = jax.tree_util.tree_map(jax.device_put, batch, b_sh)
    p2, o2, m2 = trainer.train_step(sp, so, sb, 0, rng)
    assert float(m1["loss"]) == pytest.approx(float(m2["loss"]), rel=1e-5)
    np.testing.assert_allclose(np.asarray(p1["ip1/weight"]),
                               np.asarray(p2["ip1/weight"]),
                               rtol=1e-4, atol=1e-5)


# --- multi-host bootstrap (parallel/bootstrap.py) -------------------------

def test_parse_hostfile_and_coordinator(tmp_path):
    from singa_tpu.parallel import coordinator_address, parse_hostfile
    hf = tmp_path / "hostfile"
    hf.write_text("# cluster\nhost-a\n\nhost-b  # trailing\nhost-c:9999\n")
    hosts = parse_hostfile(str(hf))
    assert hosts == ["host-a", "host-b", "host-c:9999"]
    assert coordinator_address(hosts, port=7001) == "host-a:7001"
    # explicit host:port head wins over the port argument
    assert coordinator_address(["h:5"], port=7001) == "h:5"


def test_distributed_init_single_process_fast_path(tmp_path):
    from singa_tpu.parallel import distributed_init
    hf = tmp_path / "hostfile"
    hf.write_text("localhost\n")
    # one host → no multi-process init (and no jax.distributed side effect)
    assert distributed_init(0, str(hf)) is False
    assert distributed_init(0, None) is False


def test_distributed_init_validates_procs_id(tmp_path):
    from singa_tpu.parallel import distributed_init
    hf = tmp_path / "hostfile"
    hf.write_text("host-a\nhost-b\n")
    with pytest.raises(ValueError):
        distributed_init(5, str(hf))


def test_distributed_init_out_of_range_even_single_host(tmp_path):
    from singa_tpu.parallel import distributed_init
    hf = tmp_path / "hostfile"
    hf.write_text("localhost\n")
    with pytest.raises(ValueError):
        distributed_init(3, str(hf))  # stale/truncated hostfile: fail fast


def test_distributed_init_env_overrides(tmp_path, monkeypatch):
    from singa_tpu.parallel import distributed_init
    hf = tmp_path / "hostfile"
    hf.write_text("host-a\nhost-b\n")
    monkeypatch.setenv("JAX_NUM_PROCESSES", "1")
    monkeypatch.setenv("JAX_PROCESS_ID", "0")
    # env says single process → fast path, even with a 2-host file
    assert distributed_init(1, str(hf)) is False


def test_distributed_init_two_process_e2e(tmp_path):
    """End-to-end jax.distributed over two REAL processes on localhost
    (round-1 review: the bootstrap was tested only to the parsing
    layer).  Each process runs distributed_init from the same
    reference-style hostfile, builds a global mesh spanning both
    processes' virtual CPU devices, and shard_maps a psum whose result
    proves cross-process reduction happened (process 0's shard alone
    cannot produce the global sum)."""
    import socket
    import subprocess
    import sys
    import textwrap

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    hostfile = tmp_path / "hostfile"
    hostfile.write_text(f"127.0.0.1:{port}\n127.0.0.1\n")

    child = tmp_path / "child.py"
    child.write_text(textwrap.dedent("""
        import sys
        import functools
        import numpy as np
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        from jax.experimental.shard_map import shard_map

        from singa_tpu.parallel.bootstrap import distributed_init

        pid = int(sys.argv[1])
        assert distributed_init(procs_id=pid, hostfile=sys.argv[2])
        assert jax.process_count() == 2, jax.process_count()
        assert jax.local_device_count() == 2
        devs = np.array(jax.devices())          # 4 global devices
        mesh = Mesh(devs, ("data",))
        sharding = NamedSharding(mesh, P("data"))
        # global value [0, 1, 2, 3]: each process materializes only its
        # addressable shards
        x = jax.make_array_from_callback(
            (4,), sharding,
            lambda idx: np.arange(4, dtype=np.float32)[idx])

        @functools.partial(shard_map, mesh=mesh, in_specs=P("data"),
                           out_specs=P())
        def allsum(v):
            return jax.lax.psum(jnp.sum(v, keepdims=True), "data")

        out = jax.jit(allsum, out_shardings=NamedSharding(mesh, P()))(x)
        total = float(np.asarray(out)[0])
        assert total == 6.0, total
        print(f"proc{pid} global_sum={total}", flush=True)
    """))

    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2",
               PYTHONPATH=os.path.dirname(os.path.dirname(
                   os.path.abspath(__file__))))
    for var in ("JAX_NUM_PROCESSES", "JAX_PROCESS_ID",
                "JAX_COORDINATOR_ADDRESS"):
        env.pop(var, None)
    procs = [subprocess.Popen(
        [sys.executable, str(child), str(i), str(hostfile)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc{i} failed:\n{out}"
        assert f"proc{i} global_sum=6.0" in out, out


def test_multihost_sharded_checkpoint_save_restore(tmp_path):
    """Multi-host sharded checkpointing (the scale story the reference's
    split_threshold, model.proto:62-65, gestured at): two jax.distributed
    processes save params sharded over a global 2x2 mesh through
    CheckpointManager and restore them with the SAME shardings — each
    process only ever materializes its addressable shards."""
    import socket
    import subprocess
    import sys
    import textwrap

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    hostfile = tmp_path / "hostfile"
    hostfile.write_text(f"127.0.0.1:{port}\n127.0.0.1\n")
    workspace = tmp_path / "ws"

    child = tmp_path / "child.py"
    child.write_text(textwrap.dedent("""
        import sys
        import numpy as np
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from singa_tpu.parallel.bootstrap import distributed_init
        from singa_tpu.utils.checkpoint import CheckpointManager

        pid = int(sys.argv[1])
        assert distributed_init(procs_id=pid, hostfile=sys.argv[2])
        assert jax.process_count() == 2
        devs = np.array(jax.devices()).reshape(2, 2)
        mesh = Mesh(devs, ("data", "model"))

        def make(shape, spec, seed):
            vals = np.arange(np.prod(shape), dtype=np.float32
                             ).reshape(shape) + seed
            return jax.make_array_from_callback(
                shape, NamedSharding(mesh, spec), lambda idx: vals[idx])

        params = {"w": make((8, 4), P("data", "model"), 1),
                  "b": make((4,), P("model"), 2)}
        opt = {"momentum": {"w": make((8, 4), P("data", "model"), 3),
                            "b": make((4,), P("model"), 4)}}
        mgr = CheckpointManager(sys.argv[3])
        mgr.save(5, params, opt)

        template = {"params": params, "opt_state": opt}
        rp, ro, step = mgr.restore(template=template)
        assert step == 5
        for k in params:
            assert rp[k].sharding == params[k].sharding, (k, rp[k].sharding)
            got = np.concatenate(
                [np.asarray(s.data).ravel()
                 for s in sorted(rp[k].addressable_shards,
                                 key=lambda s: s.index)])
            want = np.concatenate(
                [np.asarray(s.data).ravel()
                 for s in sorted(params[k].addressable_shards,
                                 key=lambda s: s.index)])
            np.testing.assert_array_equal(got, want)
        assert ro["momentum"]["w"].sharding == opt["momentum"]["w"].sharding

        # unpad-at-save on a multi-process mesh: the REAL
        # net.unpad_params over a padded param that is not fully
        # addressable from this process — the slice is a collective
        # SPMD computation every process runs; it must work, not
        # raise, so padded-storage checkpointing composes with
        # multi-host training
        from singa_tpu.config.schema import model_config_from_dict
        from singa_tpu.core.net import build_net
        netcfg = model_config_from_dict({
            "name": "mh", "neuralnet": {"layer": [
                {"name": "data", "type": "kShardData",
                 "data_param": {"batchsize": 4}},
                {"name": "img", "type": "kMnistImage",
                 "srclayers": "data"},
                {"name": "label", "type": "kLabel", "srclayers": "data"},
                {"name": "ip", "type": "kInnerProduct",
                 "srclayers": "img", "partition_type": "kLayerPartition",
                 "inner_product_param": {"num_output": 5},
                 "param": [{"name": "w"}, {"name": "b"}]},
                {"name": "loss", "type": "kSoftmaxLoss",
                 "srclayers": ["ip", "label"]},
            ]}})
        net = build_net(netcfg, "kTrain",
                        {"data": {"pixel": (8,), "label": ()}})
        wname = [n for n, s in net.param_specs.items()
                 if s.shape == (8, 5)][0]
        # stored padded 5 -> 6 (model=2), sharded across both processes
        unpadded = net.unpad_params(
            {wname: make((8, 6), P("data", "model"), 5)})
        jax.block_until_ready(unpadded[wname])
        assert unpadded[wname].shape == (8, 5)
        print(f"proc{pid} sharded_ckpt_ok step={step}", flush=True)
    """))

    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2",
               PYTHONPATH=os.path.dirname(os.path.dirname(
                   os.path.abspath(__file__))))
    for var in ("JAX_NUM_PROCESSES", "JAX_PROCESS_ID",
                "JAX_COORDINATOR_ADDRESS"):
        env.pop(var, None)
    procs = [subprocess.Popen(
        [sys.executable, str(child), str(i), str(hostfile), str(workspace)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc{i} failed:\n{out}"
        assert f"proc{i} sharded_ckpt_ok step=5" in out, out
