"""End-to-end real-data run (VERDICT r2 item 5): idx files on disk →
loader tool → shard.dat → NATIVE batch decoder → prefetch → conv.conf
training, with falling loss.  Proves the "zero CPU compute in the
inner loop" data story on actual files, not synthetic arrays.
Reference bar: tools/data_loader/data_loader.cc:97-148 (idx → shard)
+ layer.cc:646-673 (ShardData batching).
"""

import os
import struct

import jax
import numpy as np
import pytest

from singa_tpu.config import load_model_config
from singa_tpu.core.trainer import Trainer
from singa_tpu.data import native, prefetch, resolve_data_source
from singa_tpu.tools import loader

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_idx(tmp_path, n=512, seed=0):
    """Learnable MNIST-style idx pair: 10 class templates + noise."""
    rng = np.random.default_rng(seed)
    templates = rng.integers(0, 256, (10, 28, 28)).astype(np.float32)
    labels = rng.integers(0, 10, n).astype(np.uint8)
    imgs = np.clip(templates[labels]
                   + rng.normal(0, 16.0, (n, 28, 28)), 0, 255
                   ).astype(np.uint8)
    ip = tmp_path / "train-images-idx3-ubyte"
    lp = tmp_path / "train-labels-idx1-ubyte"
    with open(ip, "wb") as f:
        f.write(struct.pack(">IIII", 2051, n, 28, 28))
        f.write(imgs.tobytes())
    with open(lp, "wb") as f:
        f.write(struct.pack(">II", 2049, n))
        f.write(labels.tobytes())
    return str(ip), str(lp)


def test_idx_to_shard_to_native_training(tmp_path, monkeypatch):
    images, labels_f = _write_idx(tmp_path)
    out = tmp_path / "mnist_train_shard"

    # 1. the loader tool (the reference's `loader` binary role)
    rc = loader.main(["create", "mnist", images, labels_f, str(out)])
    assert rc == 0
    assert (out / "shard.dat").exists()

    # 2. the native C++ decoder must be live and actually used; on a
    # host without the compiled library this test has no subject —
    # skip rather than fail (CI guarantees the build via `make -C
    # native`, where the hard check belongs)
    if native.load_library() is None:
        pytest.skip("native/libsinga_native.so not built on this host")
    calls = {"n": 0}
    real = native.decode_image_batch

    def spy(vals):
        r = real(vals)
        if r is not None:
            calls["n"] += 1
        return r
    monkeypatch.setattr(native, "decode_image_batch", spy)

    # 3. the reference's own conv.conf, pointed at the shard
    cfg = load_model_config(
        os.path.join(REPO, "examples/mnist/conv.conf"))
    cfg.train_steps = 80
    cfg.display_frequency = 0
    cfg.test_frequency = 0
    for layer in cfg.neuralnet.layer:
        if layer.data_param:
            layer.data_param.batchsize = 64
            layer.data_param.path = str(out)

    train_iter, _ = resolve_data_source(cfg, 64)
    tr = Trainer(cfg, {"data": {"pixel": (28, 28), "label": ()}},
                 log_fn=lambda s: None, donate=False)
    params, opt = tr.init(seed=0)
    losses = []
    tr.run(params, opt, train_iter,
           hooks=[lambda step, m: losses.append(float(m["loss"]))])

    assert len(losses) == 80
    first, last = np.mean(losses[:5]), np.mean(losses[-5:])
    assert last < first * 0.7, (first, last)
    assert calls["n"] > 0, "native batch decoder was never used"
