"""Golden numeric tests for singa_tpu.ops vs NumPy oracles implementing
the reference math (mshadow expressions, layer.cc compute paths)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from singa_tpu import ops

RNG = np.random.default_rng(0)


def np_conv2d(x, w, b, kernel, stride, pad):
    """Direct-loop conv oracle over the reference weight layout
    (num_filters, C*k*k), layer.cc:63-83."""
    n, c, h, w_ = x.shape
    nf = w.shape[0]
    oh = (h + 2 * pad - kernel) // stride + 1
    ow = (w_ + 2 * pad - kernel) // stride + 1
    xp = np.pad(x, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    wk = w.reshape(nf, c, kernel, kernel)
    out = np.zeros((n, nf, oh, ow), np.float32)
    for ni in range(n):
        for f in range(nf):
            for i in range(oh):
                for j in range(ow):
                    patch = xp[ni, :, i * stride:i * stride + kernel,
                               j * stride:j * stride + kernel]
                    out[ni, f, i, j] = np.sum(patch * wk[f]) + b[f]
    return out


@pytest.mark.parametrize("pad,stride", [(0, 1), (2, 2), (1, 3)])
def test_conv2d_golden(pad, stride):
    x = RNG.standard_normal((2, 3, 9, 9)).astype(np.float32)
    w = RNG.standard_normal((4, 3 * 3 * 3)).astype(np.float32)
    b = RNG.standard_normal((4,)).astype(np.float32)
    got = ops.conv2d(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b),
                     kernel=3, stride=stride, pad=pad)
    want = np_conv2d(x, w, b, 3, stride, pad)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)


def test_im2col_matches_conv():
    """weight @ im2col(x) == conv2d(x) — the reference's own identity
    (layer.cc:75-82)."""
    x = RNG.standard_normal((1, 2, 6, 6)).astype(np.float32)
    w = RNG.standard_normal((3, 2 * 3 * 3)).astype(np.float32)
    col = ops.im2col(jnp.asarray(x[0]), kernel=3, stride=1)
    via_col = (jnp.asarray(w) @ col).reshape(1, 3, 4, 4)
    direct = ops.conv2d(jnp.asarray(x), jnp.asarray(w), None, kernel=3, stride=1)
    np.testing.assert_allclose(np.asarray(via_col), np.asarray(direct),
                               rtol=1e-4, atol=1e-4)


def np_pool(x, kernel, stride, mode):
    n, c, h, w = x.shape
    oh = int(np.ceil((h - kernel) / stride)) + 1
    ow = int(np.ceil((w - kernel) / stride)) + 1
    out = np.zeros((n, c, oh, ow), np.float32)
    for i in range(oh):
        for j in range(ow):
            hs, ws = i * stride, j * stride
            win = x[:, :, hs:min(hs + kernel, h), ws:min(ws + kernel, w)]
            if mode == "max":
                out[:, :, i, j] = win.max(axis=(2, 3))
            else:
                # reference AVE divides by k*k always (layer.cc:513-515)
                out[:, :, i, j] = win.sum(axis=(2, 3)) / (kernel * kernel)
    return out


@pytest.mark.parametrize("h,k,s", [(6, 2, 2), (7, 3, 2), (5, 2, 3)])
def test_pool_golden(h, k, s):
    x = RNG.standard_normal((2, 3, h, h)).astype(np.float32)
    got_max = ops.max_pool2d(jnp.asarray(x), k, s)
    got_avg = ops.avg_pool2d(jnp.asarray(x), k, s)
    np.testing.assert_allclose(np.asarray(got_max), np_pool(x, k, s, "max"),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got_avg), np_pool(x, k, s, "avg"),
                               rtol=1e-5, atol=1e-5)


def test_maxpool_grad_routes_to_argmax():
    """unpool<red::maximum> semantics: grad flows only to the max cell."""
    x = jnp.array([[[[1., 2.], [3., 4.]]]])
    g = jax.grad(lambda t: ops.max_pool2d(t, 2, 2).sum())(x)
    np.testing.assert_allclose(np.asarray(g),
                               [[[[0., 0.], [0., 1.]]]])


def np_lrn(x, lsize, alpha, beta, knorm):
    n, c, h, w = x.shape
    half = lsize // 2
    sq = x * x
    norm = np.zeros_like(x)
    for ci in range(c):
        lo, hi = max(0, ci - half), min(c, ci + half + 1)
        norm[:, ci] = sq[:, lo:hi].sum(axis=1)
    norm = norm * (alpha / lsize) + knorm
    return x * norm ** (-beta)


def test_lrn_golden():
    x = RNG.standard_normal((2, 8, 4, 4)).astype(np.float32)
    got = ops.lrn(jnp.asarray(x), 5, 1e-4, 0.75, 1.0)
    np.testing.assert_allclose(np.asarray(got), np_lrn(x, 5, 1e-4, 0.75, 1.0),
                               rtol=1e-5, atol=1e-6)


def test_lrn_grad_matches_reference_formula():
    """layer.cc:366-377: gsrc = g*norm^-b - 2*b*salpha*chpool(g*x*norm^(-b-1))*x"""
    lsize, alpha, beta, knorm = 5, 1e-2, 0.75, 1.0
    x = RNG.standard_normal((1, 7, 3, 3)).astype(np.float32)
    gout = RNG.standard_normal(x.shape).astype(np.float32)
    _, vjp = jax.vjp(lambda t: ops.lrn(t, lsize, alpha, beta, knorm),
                     jnp.asarray(x))
    got = np.asarray(vjp(jnp.asarray(gout))[0])

    salpha = alpha / lsize
    half = lsize // 2
    sq = x * x
    norm = np.zeros_like(x)
    for ci in range(x.shape[1]):
        lo, hi = max(0, ci - half), min(x.shape[1], ci + half + 1)
        norm[:, ci] = sq[:, lo:hi].sum(axis=1)
    norm = norm * salpha + knorm
    inner = gout * x * norm ** (-beta - 1.0)
    ch = np.zeros_like(x)
    for ci in range(x.shape[1]):
        lo, hi = max(0, ci - half), min(x.shape[1], ci + half + 1)
        ch[:, ci] = inner[:, lo:hi].sum(axis=1)
    want = gout * norm ** (-beta) - 2.0 * beta * salpha * ch * x
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_fused_relu_lrn_matches_relu_then_lrn():
    """relu_lrn(relu=True) == lrn(relu(x)) in fwd AND bwd — the fused
    conv→relu→lrn path NeuralNet._fuse_relu_lrn selects (custom_vjp
    with in-vjp relu and x>0 gradient masking, ops/lrn.py)."""
    lsize, alpha, beta, knorm = 5, 1e-2, 0.75, 1.0
    x = jnp.asarray(RNG.standard_normal((2, 4, 3, 16)).astype(np.float32))
    g = jnp.asarray(RNG.standard_normal(x.shape).astype(np.float32))

    def fused(t):
        return ops.relu_lrn(t, lsize, alpha, beta, knorm, relu=True,
                            layout="NHWC")

    def unfused(t):
        # autodiff oracle: separate relu, then the NCHW reduce_window
        # LRN (no custom_vjp on either piece)
        a = jnp.maximum(t, 0.0)
        return ops.lrn(jnp.transpose(a, (0, 3, 1, 2)), lsize, alpha,
                       beta, knorm, layout="NCHW").transpose(0, 2, 3, 1)

    y1, vjp1 = jax.vjp(fused, x)
    y2, vjp2 = jax.vjp(unfused, x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(vjp1(g)[0]),
                               np.asarray(vjp2(g)[0]),
                               rtol=1e-4, atol=1e-5)


def test_stanh_constants():
    x = jnp.array([0.5, -1.0, 2.0])
    np.testing.assert_allclose(
        np.asarray(ops.stanh(x)),
        1.7159047 * np.tanh(0.66666667 * np.asarray(x)), rtol=1e-6)
    # grad-from-output identity: stanh'(x) = B*A - (B/A) * y^2
    g = jax.grad(lambda t: ops.stanh(t).sum())(x)
    y = np.asarray(ops.stanh(x))
    want = 0.66666667 * 1.7159047 - 0.66666667 / 1.7159047 * y * y
    np.testing.assert_allclose(np.asarray(g), want, rtol=1e-5)


def test_nhwc_ops_match_nchw_oracles():
    """The NHWC code paths (the production layout for every vision net)
    must agree numerically with the NCHW golden-oracle paths: conv's
    HWIO weight transpose, pool's window tuples, and LRN's banded-matmul
    channel window."""
    x = RNG.standard_normal((2, 5, 7, 7)).astype(np.float32)  # NCHW
    xh = jnp.asarray(np.moveaxis(x, 1, -1))                   # NHWC
    xc = jnp.asarray(x)

    w = RNG.standard_normal((6, 5 * 3 * 3)).astype(np.float32)
    b = RNG.standard_normal((6,)).astype(np.float32)
    conv_c = ops.conv2d(xc, jnp.asarray(w), jnp.asarray(b), kernel=3,
                        stride=2, pad=1)
    conv_h = ops.conv2d(xh, jnp.asarray(w), jnp.asarray(b), kernel=3,
                        stride=2, pad=1, layout="NHWC")
    np.testing.assert_allclose(np.moveaxis(np.asarray(conv_h), -1, 1),
                               np.asarray(conv_c), rtol=1e-5, atol=1e-5)

    for f in (ops.max_pool2d, ops.avg_pool2d):
        pc = f(xc, 3, 2)
        ph = f(xh, 3, 2, layout="NHWC")
        np.testing.assert_allclose(np.moveaxis(np.asarray(ph), -1, 1),
                                   np.asarray(pc), rtol=1e-6)

    lc = ops.lrn(xc, 3, 5e-5, 0.75, 1.0)
    lh = ops.lrn(xh, 3, 5e-5, 0.75, 1.0, layout="NHWC")
    np.testing.assert_allclose(np.moveaxis(np.asarray(lh), -1, 1),
                               np.asarray(lc), rtol=1e-5, atol=1e-6)
    # gradients too (banded matmul backward vs reduce_window backward)
    gc = jax.grad(lambda t: (ops.lrn(t, 3, 5e-5, 0.75, 1.0) ** 2).sum())(xc)
    gh = jax.grad(lambda t: (ops.lrn(t, 3, 5e-5, 0.75, 1.0,
                                     layout="NHWC") ** 2).sum())(xh)
    np.testing.assert_allclose(np.moveaxis(np.asarray(gh), -1, 1),
                               np.asarray(gc), rtol=1e-4, atol=1e-5)


def test_binary_op_structs():
    """square/threshold/power/sqrtop vs cxxnet_op.h:71-113 oracles."""
    a = jnp.array([0.25, 4.0, 0.5, 2.0])
    b = jnp.array([0.5, 0.5, 3.0, 2.0])
    np.testing.assert_allclose(np.asarray(ops.square(a)),
                               np.asarray(a) ** 2, rtol=1e-6)
    np.testing.assert_array_equal(
        np.asarray(ops.threshold(a, b)),
        (np.asarray(a) < np.asarray(b)).astype(np.float32))
    np.testing.assert_allclose(np.asarray(ops.power(a, b)),
                               np.asarray(a) ** np.asarray(b), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(ops.sqrtop(a, b)),
                               np.sqrt(np.asarray(a) + np.asarray(b)),
                               rtol=1e-6)


def test_relu_and_leaky():
    x = jnp.array([-2.0, 0.0, 3.0])
    np.testing.assert_allclose(np.asarray(ops.relu(x)), [0, 0, 3])
    np.testing.assert_allclose(np.asarray(ops.relu(x, 0.1)),
                               [-0.2, 0, 3], rtol=1e-6)


def test_softmax_loss_golden():
    logits = RNG.standard_normal((8, 10)).astype(np.float32)
    labels = RNG.integers(0, 10, 8)
    loss, prec = ops.softmax_loss_metrics(
        jnp.asarray(logits), jnp.asarray(labels), topk=3, scale=1.0)
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    want_loss = -np.mean(np.log(p[np.arange(8), labels]))
    top3 = np.argsort(-logits, axis=-1)[:, :3]
    want_prec = np.mean([labels[i] in top3[i] for i in range(8)])
    np.testing.assert_allclose(float(loss), want_loss, rtol=1e-5)
    np.testing.assert_allclose(float(prec), want_prec, rtol=1e-6)


def test_softmax_loss_grad_is_prob_minus_onehot():
    """layer.cc:756-765: gsrc = (prob - onehot) * scale / batch."""
    logits = RNG.standard_normal((4, 5)).astype(np.float32)
    labels = np.array([1, 0, 4, 2])
    scale = 2.0
    g = jax.grad(lambda t: ops.softmax_cross_entropy(
        t, jnp.asarray(labels), scale))(jnp.asarray(logits))
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    onehot = np.eye(5, dtype=np.float32)[labels]
    np.testing.assert_allclose(np.asarray(g), (p - onehot) * scale / 4,
                               rtol=1e-5, atol=1e-6)


def test_dropout_mask_and_scale():
    x = jnp.ones((1000,))
    y = ops.dropout(x, 0.4, jax.random.PRNGKey(0), train=True)
    kept = np.asarray(y) > 0
    assert abs(kept.mean() - 0.6) < 0.06
    np.testing.assert_allclose(np.asarray(y)[kept], 1.0 / 0.6, rtol=1e-6)
    y_eval = ops.dropout(x, 0.4, jax.random.PRNGKey(0), train=False)
    np.testing.assert_allclose(np.asarray(y_eval), np.asarray(x))


def test_linear_golden():
    x = RNG.standard_normal((3, 4, 2)).astype(np.float32)  # flattened to (3,8)
    w = RNG.standard_normal((8, 5)).astype(np.float32)
    b = RNG.standard_normal((5,)).astype(np.float32)
    got = ops.linear(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b))
    want = x.reshape(3, 8) @ w + b
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)


def test_elastic_deform_identity_and_transforms():
    """ops/augment: zero strengths = identity; rotation/scale/elastic move
    pixels as expected; deterministic under a fixed key."""
    import jax
    from singa_tpu.ops.augment import elastic_deform
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((3, 17, 17)).astype(np.float32))
    key = jax.random.PRNGKey(0)

    out = elastic_deform(x, key)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), atol=1e-5)

    # the rotation center is a fixed point of a pure rotation
    delta = jnp.zeros((1, 17, 17)).at[0, 8, 8].set(1.0)
    rot = elastic_deform(delta, key, beta=45.0)
    assert float(rot[0, 8, 8]) > 0.99

    # elastic displacement changes the image but is deterministic
    e1 = elastic_deform(x, key, kernel=5, sigma=2.0, alpha=3.0)
    e2 = elastic_deform(x, key, kernel=5, sigma=2.0, alpha=3.0)
    e3 = elastic_deform(x, jax.random.PRNGKey(1), kernel=5, sigma=2.0,
                        alpha=3.0)
    np.testing.assert_allclose(np.asarray(e1), np.asarray(e2))
    assert float(jnp.max(jnp.abs(e1 - x))) > 1e-3
    assert float(jnp.max(jnp.abs(e1 - e3))) > 1e-3


def test_mnist_layer_applies_distortion_only_in_train():
    """kMnistImage runs the declared-but-unimplemented reference
    distortion surface (MnistProto) on-device in the train phase only."""
    import jax
    from singa_tpu.config import model_config_from_text
    from singa_tpu.core import build_net
    text = """
    neuralnet {
      layer { name: "data" type: "kShardData" data_param { batchsize: 4 } }
      layer { name: "mnist" type: "kMnistImage" srclayers: "data"
              mnist_param { kernel: 5 sigma: 2.0 alpha: 4.0 beta: 10.0
                            norm_a: 255.0 } }
      layer { name: "lab" type: "kLabel" srclayers: "data" }
      layer { name: "fc" type: "kInnerProduct" srclayers: "mnist"
              inner_product_param { num_output: 10 }
              param { name: "weight" init_method: kUniform }
              param { name: "bias" init_method: kConstant value: 0 } }
      layer { name: "loss" type: "kSoftmaxLoss" srclayers: "fc"
              srclayers: "lab" }
    }
    """
    cfg = model_config_from_text(text)
    net = build_net(cfg, "kTrain", {"data": {"pixel": (28, 28),
                                             "label": ()}})
    params = net.init_params(jax.random.PRNGKey(0))
    rng = np.random.default_rng(1)
    batch = {"data": {
        "pixel": jnp.asarray(rng.integers(0, 256, (4, 28, 28))
                             .astype(np.uint8)),
        "label": jnp.asarray(rng.integers(0, 10, (4,)))}}
    _, _, out_train = net.apply(params, batch, rng=jax.random.PRNGKey(3),
                                train=True)
    _, _, out_eval = net.apply(params, batch, train=False)
    plain = np.asarray(batch["data"]["pixel"], np.float32) / 255.0
    np.testing.assert_allclose(np.asarray(out_eval["mnist"]), plain,
                               atol=1e-6)
    assert float(jnp.max(jnp.abs(out_train["mnist"] - plain))) > 1e-4


def test_lrn_pallas_interpret_matches_band_path():
    """The Pallas batch-in-lanes LRN kernels (ops/lrn_pallas.py) against
    the production jnp band-matmul custom_vjp, in interpreter mode on
    the CPU test platform.  (On chip the kernels measured slower than
    XLA's fused band-dot emitter and are not selected — see
    ops/lrn.py:_impl_for — but they remain the independent oracle for
    the closed-form backward and the benchmark baseline for
    tools/ablate.py.)"""
    from singa_tpu.ops.lrn import _lrn_nhwc
    from singa_tpu.ops.lrn_pallas import eligible

    x = jnp.asarray(RNG.standard_normal((128, 4, 4, 8)).astype(np.float32))
    g = jnp.asarray(RNG.standard_normal((128, 4, 4, 8)).astype(np.float32))
    assert eligible(x)
    for relu in (False, True):
        args = (3, 5e-3, 0.75, 1.0, relu)
        y_j, vjp_j = jax.vjp(lambda t: _lrn_nhwc(t, *args, "jnp"), x)
        y_p, vjp_p = jax.vjp(lambda t: _lrn_nhwc(t, *args, "interpret"), x)
        np.testing.assert_allclose(y_p, y_j, atol=1e-5)
        np.testing.assert_allclose(vjp_p(g)[0], vjp_j(g)[0], atol=1e-5)
    # non-lane-multiple batch is not eligible
    assert not eligible(jnp.zeros((100, 4, 4, 8)))


def test_maxpool_equality_mask_vjp_ties_match_reference():
    """_max_pool_nhwc routes gradient to EVERY tied max (mshadow
    unpool<red::maximum> semantics, tensor_expr_ext.h:148-163): with a
    constant input, every window position compares equal to the max and
    receives the window's full cotangent — unlike select-and-scatter,
    which picks a single winner."""
    from singa_tpu.ops.pool import _max_pool_nhwc

    x = jnp.ones((1, 4, 4, 1), np.float32)
    y, vjp = jax.vjp(lambda t: _max_pool_nhwc(t, 2, 2), x)
    (dx,) = vjp(jnp.ones_like(y))
    # 2x2 stride-2 windows: every input position ties -> grad 1 each
    np.testing.assert_allclose(dx, np.ones((1, 4, 4, 1)))
    # and on untied data it matches autodiff of the NCHW path
    xr = jnp.asarray(RNG.standard_normal((2, 8, 8, 3)).astype(np.float32))
    cot = jnp.asarray(RNG.standard_normal((2, 4, 4, 3)).astype(np.float32))
    _, vjp_em = jax.vjp(lambda t: _max_pool_nhwc(t, 3, 2), xr)
    _, vjp_ad = jax.vjp(
        lambda t: ops.max_pool2d(t.transpose(0, 3, 1, 2), 3, 2,
                                 "NCHW").transpose(0, 2, 3, 1), xr)
    np.testing.assert_allclose(vjp_em(cot)[0], vjp_ad(cot)[0], atol=1e-6)
