"""Serving fleet (singa_tpu/serve/router.py + fleet.py): health-driven
dispatch, quarantine/readmission, router shedding, and the canary
rollout state machine (OBSERVE -> CANARY -> PROMOTE/ROLLBACK).

Correctness anchors:
  * killing an engine never surfaces as a client failure while a
    healthy sibling exists — requests retry elsewhere, the dead engine
    is quarantined and readmitted on recovery;
  * a bad checkpoint fingerprint can touch at most ONE engine: a
    DIVERGED manifest verdict, a dead canary, or an injected
    `serve.reload` fault all end in rollback with the fleet pinned.

Cost control: router and rollout logic is exercised through stub
handles (no compiled programs, no threads — probe rounds and rollout
ticks are driven explicitly); exactly one test builds a real 2-engine
fleet over the tiny test LM with a single (2, 6) bucket."""

import tempfile
import time

import jax
import numpy as np
import pytest

from singa_tpu.core.net import build_net
from singa_tpu.models.transformer import transformer_lm
from singa_tpu.parallel.bootstrap import parse_hostfile
from singa_tpu.serve import (EngineFleet, EngineUnavailable,
                             InferenceEngine, InferenceServer,
                             Overloaded, RolloutController, RolloutSpec,
                             Router, RouterSpec, ServeSpec)
from singa_tpu.utils.checkpoint import CheckpointManager
from singa_tpu.utils.faults import FaultSchedule, inject

pytestmark = pytest.mark.fleet

VOCAB, SEQ = 64, 16
SHAPES = {"data": {"input": (SEQ,), "target": (SEQ,)}}


def _net_and_params(seed=0):
    cfg = transformer_lm(vocab_size=VOCAB, num_layers=2, embed_dim=32,
                         num_heads=4, head_dim=8, seq_len=SEQ,
                         batchsize=2)
    net = build_net(cfg, "kTest", SHAPES)
    return net, net.init_params(jax.random.PRNGKey(seed))


def _save(mgr, step, params, verdict="ok"):
    mgr.save(step, params, {"t": np.zeros(())},
             health={"verdict": verdict})


# -- spec grammars -----------------------------------------------------------

def test_router_spec_parse_grammar():
    s = RouterSpec.parse("probe_period_s=0.1,quarantine_after=3;"
                         "readmit_base_s=0.5,max_attempts=2")
    assert s.probe_period_s == 0.1 and s.quarantine_after == 3
    assert s.readmit_base_s == 0.5 and s.max_attempts == 2
    assert RouterSpec.parse(None) == RouterSpec()
    with pytest.raises(ValueError, match="unknown key"):
        RouterSpec.parse("bogus=1")
    with pytest.raises(ValueError):
        RouterSpec.parse("quarantine_after=0")


def test_rollout_spec_parse_grammar():
    s = RolloutSpec.parse("window_s=2.5,min_requests=10;p95_ratio=4")
    assert s.window_s == 2.5 and s.min_requests == 10
    assert s.p95_ratio == 4.0
    assert RolloutSpec.parse("") == RolloutSpec()
    with pytest.raises(ValueError, match="unknown key"):
        RolloutSpec.parse("nope=2")
    with pytest.raises(ValueError):
        RolloutSpec.parse("window_s=0")


# -- hostfile membership hardening -------------------------------------------

def test_parse_hostfile_rejects_duplicates(tmp_path):
    p = tmp_path / "hosts"
    p.write_text("10.0.0.1:8000\n10.0.0.2:8000\n10.0.0.1:8000\n")
    with pytest.raises(ValueError, match="duplicate host"):
        parse_hostfile(str(p))


def test_parse_hostfile_rejects_empty_membership(tmp_path):
    p = tmp_path / "hosts"
    p.write_text("# fleet members\n\n   \n# none yet\n")
    with pytest.raises(ValueError, match="no hosts"):
        parse_hostfile(str(p))
    p2 = tmp_path / "hosts2"
    p2.write_text("")
    with pytest.raises(ValueError, match="no hosts"):
        parse_hostfile(str(p2))


# -- stub engine handle ------------------------------------------------------

class StubHandle:
    """Engine handle test double: scriptable health, load, failure,
    and reload behavior; no threads, no compiled programs."""

    def __init__(self, name, step=1, queue_depth=0):
        self.name = name
        self.step = step
        self.queue_depth = queue_depth
        self.fail_probe = False
        self.fail_request = False
        self.overloaded = False
        self.reload_error = False
        self.reload_refuse = False
        self.served = 0
        self.reloads = []
        self.failed = 0

    def probe(self):
        if self.fail_probe:
            raise EngineUnavailable(f"{self.name} is down")
        return {"ok": True, "status": "ok", "step": self.step,
                "queue_depth": self.queue_depth}

    def stats_snapshot(self):
        return {"completed": self.served, "failed": self.failed,
                "expired": 0, "p95_latency_ms": None}

    def request(self, mode, tokens, timeout=None):
        if self.fail_request:
            self.failed += 1
            raise EngineUnavailable(f"{self.name} crashed")
        if self.overloaded:
            raise Overloaded(f"{self.name} full", retry_after=0.01)
        self.served += 1
        return {"tokens": [1, 2], "step": self.step}

    def reload(self, step=None):
        self.reloads.append(step)
        if self.reload_error:
            raise EngineUnavailable(f"{self.name} is down")
        if self.reload_refuse:
            return {"outcome": "refused", "step": self.step}
        if step is not None and step != self.step:
            self.step = step
            return {"outcome": "reloaded", "step": step}
        return {"outcome": "unchanged", "step": self.step}


def _router(n=3, **spec_kw):
    spec_kw.setdefault("quarantine_after", 2)
    spec_kw.setdefault("readmit_base_s", 0.01)
    spec_kw.setdefault("readmit_cap_s", 0.02)
    stubs = [StubHandle(f"e{i}") for i in range(n)]
    r = Router(stubs, spec=RouterSpec(**spec_kw), log_fn=lambda s: None)
    r.probe_all()          # first verdicts, no probe thread
    return r, stubs


# -- router dispatch ---------------------------------------------------------

def test_route_picks_least_loaded():
    r, stubs = _router(3)
    stubs[0].queue_depth, stubs[2].queue_depth = 5, 3
    r.probe_all()
    out = r.route("generate", [1, 2])
    assert out["engine"] == "e1"
    assert stubs[1].served == 1


def test_route_retries_on_other_engine_and_strikes():
    r, stubs = _router(2, quarantine_after=1)
    stubs[0].queue_depth = 0
    stubs[1].queue_depth = 9          # e0 is preferred...
    r.probe_all()
    stubs[0].fail_request = True      # ...but crashed
    out = r.route("generate", [1, 2])
    assert out["engine"] == "e1"      # client never saw the failure
    assert r.stats.retried == 1 and r.stats.completed == 1
    # the failure was charged to e0 like a failed probe: quarantined
    m = {m["name"]: m for m in r.members()}
    assert m["e0"]["quarantined"] and not m["e1"]["quarantined"]


def test_quarantine_and_readmission_cycle():
    r, stubs = _router(2, quarantine_after=2)
    stubs[0].fail_probe = True
    r.probe_all()                     # strike 1
    assert not r.members()[0]["quarantined"]
    r.probe_all()                     # strike 2 -> quarantined
    m = {m["name"]: m for m in r.members()}
    assert m["e0"]["quarantined"] and r.stats.quarantines == 1
    assert r.healthy_names() == ["e1"]
    # benched: probes skip it until the Backoff delay passes
    stubs[0].fail_probe = False
    time.sleep(0.03)                  # > readmit_cap_s
    r.probe_all()                     # readmission probe passes
    m = {m["name"]: m for m in r.members()}
    assert not m["e0"]["quarantined"] and r.stats.readmissions == 1
    assert sorted(r.healthy_names()) == ["e0", "e1"]


def test_all_engines_down_sheds_with_escalating_retry_after():
    r, stubs = _router(2, quarantine_after=1)
    for s in stubs:
        s.fail_probe = True
    r.probe_all()
    delays = []
    for _ in range(3):
        with pytest.raises(Overloaded) as ei:
            r.route("generate", [1])
        delays.append(ei.value.retry_after)
        assert r.stats.shed == len(delays)
    # consecutive sheds escalate the hint (seeded-jitter Backoff is
    # monotone across doublings at these magnitudes)
    assert delays[0] < delays[2]


def test_fleet_dispatch_fault_is_retried_not_surfaced():
    r, stubs = _router(2, quarantine_after=1)
    with inject(FaultSchedule.parse("fleet.dispatch@0:error")):
        out = r.route("generate", [1, 2])
    # the faulted attempt was charged to one engine; the request still
    # completed on the other
    assert out["engine"] in ("e0", "e1")
    assert r.stats.retried == 1 and r.stats.completed == 1
    assert sum(m["quarantined"] for m in r.members()) == 1


def test_overload_is_load_not_failure():
    r, stubs = _router(2, quarantine_after=1)
    stubs[0].queue_depth = 0
    stubs[1].queue_depth = 9
    r.probe_all()
    stubs[0].overloaded = True
    out = r.route("generate", [1])
    assert out["engine"] == "e1"
    # no strike for shedding under load: e0 stays dispatchable
    assert not any(m["quarantined"] for m in r.members())


# -- rollout state machine (stub handles, ticks driven by hand) --------------

def _controller(ws, n=3, **ro_kw):
    ro_kw.setdefault("window_s", 0.01)
    r, stubs = _router(n, quarantine_after=1)
    ctrl = RolloutController(r, ws, spec=RolloutSpec(**ro_kw),
                             log_fn=lambda s: None)
    # arm without the controller thread: ticks are driven by the test
    ctrl.pinned_step = 1
    ctrl._fp = ctrl.mgr.fingerprint()
    return ctrl, r, stubs


def test_healthy_rollout_canaries_one_then_promotes():
    _, params = None, {"w": np.ones((2,), np.float32)}
    with tempfile.TemporaryDirectory() as ws:
        mgr = CheckpointManager(ws, log_fn=lambda s: None)
        _save(mgr, 1, params)
        ctrl, r, stubs = _controller(ws)
        ctrl.tick()
        assert ctrl.state == "OBSERVE"        # nothing new
        _save(mgr, 2, params)
        ctrl.tick()
        assert ctrl.state == "CANARY" and ctrl.canaries == 1
        # exactly ONE engine carries the new step during the window
        assert sum(1 for s in stubs if s.step == 2) == 1
        time.sleep(0.02)                      # window_s elapsed
        ctrl.tick()
        assert ctrl.state == "OBSERVE" and ctrl.promotions == 1
        assert ctrl.pinned_step == 2
        assert all(s.step == 2 for s in stubs)


def test_unhealthy_rollout_rolls_back_and_is_not_retried():
    params = {"w": np.ones((2,), np.float32)}
    with tempfile.TemporaryDirectory() as ws:
        mgr = CheckpointManager(ws, log_fn=lambda s: None)
        _save(mgr, 1, params)
        ctrl, r, stubs = _controller(ws)
        _save(mgr, 2, params, verdict="diverged")
        ctrl.tick()
        assert ctrl.state == "CANARY"
        assert sum(1 for s in stubs if s.step == 2) == 1
        time.sleep(0.02)
        ctrl.tick()
        assert ctrl.rollbacks == 1 and ctrl.promotions == 0
        assert ctrl.pinned_step == 1
        # the canary was restored: nobody serves the bad step
        assert all(s.step == 1 for s in stubs)
        # the rejected fingerprint is remembered, not re-canaried
        for _ in range(3):
            ctrl.tick()
        assert ctrl.canaries == 1
        # a NEW save (new fingerprint) is eligible again
        _save(mgr, 3, params)
        ctrl.tick()
        assert ctrl.state == "CANARY" and ctrl.canaries == 2


def test_canary_dies_mid_canary_rolls_back_without_deadlock():
    params = {"w": np.ones((2,), np.float32)}
    with tempfile.TemporaryDirectory() as ws:
        mgr = CheckpointManager(ws, log_fn=lambda s: None)
        _save(mgr, 1, params)
        ctrl, r, stubs = _controller(ws, window_s=60.0)  # long window
        _save(mgr, 2, params)
        ctrl.tick()
        assert ctrl.state == "CANARY"
        victim = next(s for s in stubs if s.step == 2)
        victim.fail_probe = True
        victim.reload_error = True    # even the rollback reload fails
        r.probe_all()                 # quarantine_after=1 -> benched
        ctrl.tick()                   # detects the dead canary
        assert ctrl.state == "OBSERVE" and ctrl.rollbacks == 1
        assert ctrl.pinned_step == 1 and ctrl.canary is None
        # the fleet keeps serving on the survivors
        out = r.route("generate", [1])
        assert out["engine"] != victim.name


def test_newer_fingerprint_mid_canary_restarts_on_newest():
    params = {"w": np.ones((2,), np.float32)}
    with tempfile.TemporaryDirectory() as ws:
        mgr = CheckpointManager(ws, log_fn=lambda s: None)
        _save(mgr, 1, params)
        ctrl, r, stubs = _controller(ws, window_s=60.0)
        _save(mgr, 2, params)
        ctrl.tick()
        assert ctrl.state == "CANARY" and ctrl.target_step == 2
        _save(mgr, 3, params)         # newer checkpoint lands mid-canary
        ctrl.tick()
        assert ctrl.canary_restarts == 1
        assert ctrl.state == "CANARY" and ctrl.target_step == 3
        # still at most one engine off the pinned step
        assert sum(1 for s in stubs if s.step != 1) == 1
        time.sleep(0.02)
        # the abandoned step 2 was never promoted anywhere
        assert all(s.step in (1, 3) for s in stubs)


def test_rollout_fault_mid_canary_aborts_safely():
    params = {"w": np.ones((2,), np.float32)}
    with tempfile.TemporaryDirectory() as ws:
        mgr = CheckpointManager(ws, log_fn=lambda s: None)
        _save(mgr, 1, params)
        ctrl, r, stubs = _controller(ws, window_s=60.0)
        _save(mgr, 2, params)
        ctrl.tick()
        assert ctrl.state == "CANARY"
        with inject(FaultSchedule.parse("fleet.rollout@0:error")):
            ctrl.tick()               # faulted tick -> rollback, never die
        assert ctrl.state == "OBSERVE" and ctrl.rollbacks == 1
        assert ctrl.pinned_step == 1 and all(s.step == 1 for s in stubs)


def test_torn_target_is_a_counted_refusal():
    params = {"w": np.ones((2,), np.float32)}
    with tempfile.TemporaryDirectory() as ws:
        mgr = CheckpointManager(ws, log_fn=lambda s: None)
        _save(mgr, 1, params)
        ctrl, r, stubs = _controller(ws)
        for s in stubs:
            s.reload_refuse = True    # target never lands anywhere
        _save(mgr, 2, params)
        ctrl.tick()
        assert ctrl.state == "OBSERVE" and ctrl.refusals == 1
        assert ctrl.canaries == 0 and ctrl.pinned_step == 1
        ctrl.tick()                   # rejected fp: no retry loop
        assert ctrl.refusals == 1


# -- honest /healthz ---------------------------------------------------------

def test_engine_health_degrades_on_failure_streak():
    net, params = _net_and_params()
    eng = InferenceEngine(net, ServeSpec(degraded_after=3),
                          params=params, log_fn=lambda s: None)
    assert eng.health()["ok"]
    for _ in range(3):
        eng.stats.observe_batch_failure()
    h = eng.health()
    assert not h["ok"] and "consecutive failed batches" in \
        " ".join(h["reasons"])
    # any successful batch resets the streak
    eng.stats.observe_batch(1, 1)
    assert eng.health()["ok"]


def test_engine_health_degrades_on_stale_params():
    net, params = _net_and_params()
    bad = dict(params)
    k = next(iter(bad))
    bad[k] = np.zeros(np.asarray(bad[k]).shape + (2,), np.float32)
    with tempfile.TemporaryDirectory() as ws:
        mgr = CheckpointManager(ws, max_to_keep=10,
                                log_fn=lambda s: None)
        _save(mgr, 1, params)
        eng = InferenceEngine(net, ServeSpec(), workspace=ws,
                              log_fn=lambda s: None)
        eng.load()
        assert eng.health()["ok"]
        _save(mgr, 2, bad)            # geometry mismatch -> failed swap
        assert eng.poll_reload() == "failed"
        h = eng.health()
        assert not h["ok"] and "stale" in " ".join(h["reasons"])
        # a later good save clears the degradation
        _save(mgr, 3, params)
        assert eng.poll_reload() == "reloaded"
        assert eng.health()["ok"]


def test_healthz_endpoint_returns_503_when_degraded():
    import json as _json
    import urllib.error
    import urllib.request

    net, params = _net_and_params()
    eng = InferenceEngine(net, ServeSpec(degraded_after=2),
                          params=params, log_fn=lambda s: None)
    srv = InferenceServer(eng, port=0, warmup_modes=(),
                          log_fn=lambda s: None)
    srv.start()
    try:
        host, port = srv.address
        url = f"http://{host}:{port}/healthz"
        with urllib.request.urlopen(url, timeout=10) as r:
            assert r.status == 200 and _json.loads(r.read())["ok"]
        eng.stats.observe_batch_failure()
        eng.stats.observe_batch_failure()
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(url, timeout=10)
        assert ei.value.code == 503
        body = _json.loads(ei.value.read())
        assert body["status"] == "degraded" and body["reasons"]
    finally:
        srv.stop()


def test_pinned_engine_never_self_reloads():
    net, params = _net_and_params()
    p2 = jax.tree_util.tree_map(lambda a: a * 2.0, params)
    with tempfile.TemporaryDirectory() as ws:
        mgr = CheckpointManager(ws, max_to_keep=10,
                                log_fn=lambda s: None)
        _save(mgr, 1, params)
        eng = InferenceEngine(net, ServeSpec(), workspace=ws,
                              log_fn=lambda s: None, pinned=True)
        assert eng.load() == 1
        _save(mgr, 2, p2)
        assert eng.poll_reload() == "pinned"    # the poll is a no-op
        assert eng.params_step == 1
        # only the explicit command channel moves a pinned engine
        assert eng.reload_to(2) == "reloaded"
        assert eng.params_step == 2


# -- real-engine integration (one compiled fleet, one bucket) ----------------

def test_reload_fault_on_canary_keeps_fleet_pinned_and_serving():
    """ISSUE 7 rollout edge: an injected `serve.reload` fault on the
    canary's reload must leave the whole fleet on the old fingerprint
    with ZERO failed user requests — the canary mechanism absorbs the
    fault instead of spreading it."""
    net, params = _net_and_params()
    spec = ServeSpec(buckets=((2, 6),), max_new_tokens=2,
                     batch_window_s=0.005, request_timeout_s=20.0)
    with tempfile.TemporaryDirectory() as ws:
        mgr = CheckpointManager(ws, max_to_keep=10,
                                log_fn=lambda s: None)
        _save(mgr, 1, params)
        fleet = EngineFleet.local(
            net, spec, 2, workspace=ws, params=params,
            router_spec=RouterSpec(probe_period_s=0.05,
                                   quarantine_after=1,
                                   readmit_base_s=0.05),
            rollout_spec=RolloutSpec(poll_s=0.05, window_s=0.1),
            log_fn=lambda s: None)
        # pinned fleet members never poll-reload, so the FIRST
        # serve.reload visit in this process is the canary's reload_to
        with inject(FaultSchedule.parse("serve.reload@0:error")):
            fleet.start()
            try:
                assert fleet.rollout.pinned_step == 1
                prompt = np.arange(1, 5, dtype=np.int32)
                assert fleet.generate(prompt)["step"] == 1
                _save(mgr, 2, params)
                deadline = time.time() + 15
                while time.time() < deadline and \
                        fleet.rollout.refusals == 0 and \
                        fleet.rollout.rollbacks == 0:
                    fleet.generate(prompt)
                    time.sleep(0.02)
                ro = fleet.rollout.snapshot()
                assert ro["refusals"] + ro["rollbacks"] == 1
                assert ro["promotions"] == 0 and ro["pinned_step"] == 1
                # the fleet never left the old fingerprint...
                steps = [fleet.router.handle_for(n).engine.params_step
                         for n in fleet.router.names()]
                assert steps == [1, 1]
                # ...and not one user request failed along the way
                assert fleet.router.stats.failed == 0
                assert fleet.generate(prompt)["step"] == 1
            finally:
                fleet.stop()
