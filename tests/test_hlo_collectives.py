"""HLO collective-regression tests (VERDICT r4 item 4): lower the
sharded steps on the 8-device CPU mesh and assert the expected
collective set — ppermute counts per ring layer, all-to-alls for
Ulysses, psum for DP grads, and critically NO all-gather of a full
parameter or full-sequence activation.  This is the only multi-chip
perf guard available without hardware; the round-3 hybrid remat
regression and the round-5 loss-reshape full-S gather would both have
been caught here mechanically.

The reference's connector insertion was exact by construction
(neuralnet.cc:229-290); these tests pin the GSPMD-compiled equivalent.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from singa_tpu.core.trainer import Trainer
from singa_tpu.models.transformer import (synthetic_token_batches,
                                          transformer_lm)
from singa_tpu.parallel import (make_mesh, param_shardings, ring_attention,
                                seq_batch_shardings, ulysses_attention)

RNG = np.random.default_rng(0)


def collective_defs(txt: str, op: str):
    """(dtype, dims) of every `op` definition site in compiled HLO."""
    return re.findall(rf"(\S+)\[([0-9,]*)\][^\n]* {op}\(", txt)


def _sharded_step_text(mesh, cfg, bs, seq, vocab=64):
    shapes = {"data": {"input": (seq,), "target": (seq,)}}
    tr = Trainer(cfg, shapes, donate=False, mesh=mesh)
    p, o = tr.init(0)
    psh = param_shardings(mesh, tr.train_net)
    sp = {k: jax.device_put(v, psh[k]) for k, v in p.items()}
    so = {k: {n: jax.device_put(v, psh[n]) for n, v in t.items()}
          for k, t in o.items()}
    b = next(synthetic_token_batches(bs, seq, vocab))
    sb = jax.tree_util.tree_map(jax.device_put, b,
                                seq_batch_shardings(mesh, b))
    txt = tr.train_step.lower(
        sp, so, sb, 0, jax.random.PRNGKey(0)).compile().as_text()
    return tr.train_net, txt


def _qkv(b=2, h=4, s=256, d=16):
    return tuple(jnp.asarray(RNG.standard_normal((b, h, s, d))
                             .astype(np.float32)) for _ in range(3))


def test_ring_ppermute_counts():
    """One ring layer over nseq=4: k and v each hop nseq-1 times in the
    forward — 2*(nseq-1) collective-permutes — and the backward mirrors
    them exactly (4*(nseq-1) total under grad)."""
    nseq = 4
    mesh = make_mesh(seq=nseq, data=2)
    q, k, v = _qkv()
    fwd = jax.jit(lambda q, k, v: ring_attention(
        q, k, v, mesh, "seq", True)).lower(q, k, v).compile().as_text()
    assert len(collective_defs(fwd, "collective-permute")) \
        == 2 * (nseq - 1)
    assert not collective_defs(fwd, "all-gather")

    grad = jax.jit(jax.grad(
        lambda q, k, v: ring_attention(q, k, v, mesh, "seq", True).sum(),
        argnums=(0, 1, 2))).lower(q, k, v).compile().as_text()
    assert len(collective_defs(grad, "collective-permute")) \
        == 4 * (nseq - 1)
    assert not collective_defs(grad, "all-gather")


def test_ulysses_all_to_all_present_no_gather():
    """Ulysses moves data exclusively through all-to-alls (q, k, v in +
    out back): they must appear, and nothing may fall back to an
    all-gather of the full sequence."""
    mesh = make_mesh(seq=4, data=2)
    q, k, v = _qkv()
    txt = jax.jit(lambda q, k, v: ulysses_attention(
        q, k, v, mesh, "seq", True)).lower(q, k, v).compile().as_text()
    assert len(collective_defs(txt, "all-to-all")) >= 4
    assert not collective_defs(txt, "all-gather")


def test_dp_step_psums_grads_only():
    """Pure DP: gradient all-reduces and nothing else — no gathers, no
    permutes (a gather here would mean a param or activation silently
    replicating through comm)."""
    mesh = make_mesh(data=8)
    cfg = transformer_lm(vocab_size=64, num_layers=1, embed_dim=64,
                         num_heads=4, head_dim=16, seq_len=128,
                         batchsize=8)
    _, txt = _sharded_step_text(mesh, cfg, 8, 128)
    assert collective_defs(txt, "all-reduce")
    assert not collective_defs(txt, "all-gather")
    assert not collective_defs(txt, "collective-permute")


def test_tp_step_never_gathers_full_params():
    """dp×tp: activation boundary gathers are the Megatron contract,
    but NO all-gather may produce a full parameter (that would mean the
    sharded weight reassembles every step)."""
    mesh = make_mesh(data=4, model=2)
    cfg = transformer_lm(vocab_size=64, num_layers=1, embed_dim=64,
                         num_heads=4, head_dim=16, seq_len=128,
                         batchsize=8)
    net, txt = _sharded_step_text(mesh, cfg, 8, 128)
    param_shapes = {tuple(s.shape) for s in net.param_specs.values()}
    for dtype, dims in collective_defs(txt, "all-gather"):
        shape = tuple(int(x) for x in dims.split(",") if x)
        assert shape not in param_shapes, (
            f"all-gather reassembles full param shape {shape}")


def test_ring_sp_step_has_no_full_sequence_gather():
    """The SP train step must keep EVERY tensor sequence-sharded: zero
    all-gathers in the lowered step.  Regression guard for the round-5
    find that the loss's (B,S,E)→(B·S,E) reshape gathered the full
    sequence per data shard before _shard_tokens pinned the merged
    token dim to ("data","seq")."""
    nseq = 4
    mesh = make_mesh(data=2, seq=nseq)
    cfg = transformer_lm(vocab_size=64, num_layers=1, embed_dim=64,
                         num_heads=4, head_dim=16, seq_len=128,
                         batchsize=8, seq_parallel="ring")
    _, txt = _sharded_step_text(mesh, cfg, 8, 128)
    assert not collective_defs(txt, "all-gather"), [
        f"{t}[{d}]" for t, d in collective_defs(txt, "all-gather")]
    # fwd + bwd ppermutes for one ring layer
    assert len(collective_defs(txt, "collective-permute")) \
        == 4 * (nseq - 1)
    assert collective_defs(txt, "all-reduce")
