"""Perf smoke (ISSUE 2 satellite): the feed-pipeline A/B bench leg
under the `perf` marker.  Marked `slow` too — it trains real (small)
LeNet chunks three times — so tier-1 (`-m "not slow"`) skips it; run
with `pytest -m perf` or scripts/perf_smoke.sh."""

import json
import os
import subprocess
import sys

import pytest

pytestmark = [pytest.mark.perf, pytest.mark.slow]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_feed_smoke_records_host_wait_drop(tmp_path):
    out = tmp_path / "BENCH_pr2.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--feed-smoke", "--out", str(out)],
        check=True, env=env, cwd=REPO, timeout=1800)
    r = json.loads(out.read_text())
    assert r["metric"] == "lenet_feed_pipeline"
    for leg in ("feeder_on", "feeder_off"):
        assert r[leg]["steps_per_sec"] > 0
        assert 0.0 <= r[leg]["host_wait_fraction"] < 1.0
    # the acceptance property: overlap removes host data-wait from the
    # critical path
    assert r["value"] > 0, r
