"""Fleet-wide distributed tracing (ISSUE 14): cross-process trace
propagation (`X-Trace-Id`/`X-Parent-Span`), merged fleet export +
per-request critical-path attribution, tail-based sampling, and the
post-mortem flight recorder.

Correctness anchors:
  * one trace id end to end — a hedged unary request and a
    killed/failed-over stream each carry a SINGLE trace id across
    every leg (primary, hedge, resume), and a merged buffer has zero
    orphan spans;
  * the wire pair degrades, never 400s — a malformed parent span id
    parses to 0 (root of a remote track), a missing trace id to None;
  * bounded buffers — the span ring evicts (counted), the JSONL event
    log rotates (counted) and its flush accounting stays CUMULATIVE
    across rotations;
  * tail sampling keeps only interesting requests (slow / failed /
    shed / hedged / resumed) and physically discards the rest's
    buffered spans;
  * the flight recorder dumps on its trigger table — rollback,
    quarantine, failover, shed storm, divergence, faulted flush —
    rate-limited per trigger, WITHOUT any trace exporter configured.

Cost control: everything below the two-process test runs on
scriptable stubs and hand-built buffers (no compiled programs).  The
one real worker subprocess test is `@pytest.mark.slow` — the
tier-1 bar runs it only in the nightly/chaos lane, alongside
`bench.py --trace-smoke` and `scripts/obs_smoke.sh`."""

import glob
import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

from singa_tpu import obs
from singa_tpu.obs import collect
from singa_tpu.obs.flightrec import FlightRecorder
from singa_tpu.obs.log import EventLog
from singa_tpu.obs.metrics import MetricsRegistry
from singa_tpu.obs.trace import Tracer
from singa_tpu.serve import Router, RouterSpec, qos
from singa_tpu.serve.router import (HttpEngineHandle, RequestLog,
                                    RouterStats)
from singa_tpu.serve.stats import ServeStats
from singa_tpu.utils.faults import FaultSchedule, inject

pytestmark = pytest.mark.obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_leaked_session():
    obs.disable()
    yield
    obs.disable()


# -- the wire pair: serialize / parse ----------------------------------------

def test_trace_headers_roundtrip():
    assert qos.trace_to_headers(None) == {}
    assert qos.trace_to_headers(("", 0)) == {}
    h = qos.trace_to_headers(("abc123", 42))
    assert h == {qos.TRACE_HEADER: "abc123",
                 qos.PARENT_SPAN_HEADER: "42"}
    assert qos.trace_from_headers("abc123", "42") == ("abc123", 42)
    # a trace id without a parent span: root of a remote track
    h = qos.trace_to_headers(("abc123", 0))
    assert h == {qos.TRACE_HEADER: "abc123"}
    assert qos.trace_from_headers("abc123", None) == ("abc123", 0)


def test_trace_headers_degrade_never_reject():
    """A malformed span id parses to 0 and a missing trace id to None
    — telemetry that rides along on a request must never 400 it."""
    assert qos.trace_from_headers("abc123", "not-a-number") == \
        ("abc123", 0)
    assert qos.trace_from_headers(None, "42") is None
    assert qos.trace_from_headers("   ", "42") is None


def test_explicit_anchor_joins_remote_trace():
    """The receive side of a hop: `span(..., trace=..., parent=...)`
    lands the local span in the SENDER's trace under its span."""
    with obs.session(obs.ObsSpec()) as o:
        with obs.span("frontend") as fsp:
            ctx = obs.trace_context()
            assert ctx == (fsp.trace, fsp.span_id)
            wire = qos.trace_to_headers(ctx)
        # "other process": parse the pair back and re-anchor
        rx = qos.trace_from_headers(wire.get(qos.TRACE_HEADER),
                                    wire.get(qos.PARENT_SPAN_HEADER))
        with obs.span("worker", trace=rx[0], parent=rx[1]) as wsp:
            assert wsp.trace == fsp.trace
            assert wsp.parent_id == fsp.span_id
        evs = {e["name"]: e for e in o.tracer.events()}
    assert evs["worker"]["args"]["trace"] == \
        evs["frontend"]["args"]["trace"]
    assert evs["worker"]["args"]["parent_id"] == \
        evs["frontend"]["args"]["span_id"]


# -- bounded span buffer (satellite: ring mode) ------------------------------

def test_trace_ring_keeps_most_recent_and_counts_evictions():
    t = Tracer(ring=4, process="w0")
    t0 = time.perf_counter()
    for i in range(10):
        t.add_span(f"s{i}", t0, 0.001)
    evs = t.events()
    assert [e["name"] for e in evs] == ["s6", "s7", "s8", "s9"]
    assert t.evicted == 6 and t.dropped == 0
    d = t.trace_dict()
    assert d["process"] == "w0" and "wall_origin_s" in d


def test_discard_trace_counts_sampled_out():
    t = Tracer()
    t0 = time.perf_counter()
    t.add_span("keep", t0, 0.001, trace="t-keep")
    t.add_span("drop1", t0, 0.001, trace="t-drop")
    t.add_span("drop2", t0, 0.001, trace="t-drop")
    assert t.discard_trace("t-drop") == 2
    assert [e["name"] for e in t.events()] == ["keep"]
    assert t.sampled_out == 2
    assert t.discard_trace("") == 0


# -- tail-based sampling policy ----------------------------------------------

def test_tail_sampler_policy_matrix():
    s = obs.TailSampler(obs.ObsSpec(sample="tail", sample_slow_ms=50))
    assert not s.keep(0.010)                  # fast + boring: dropped
    assert s.keep(0.100)                      # slow against the bar
    assert s.keep(0.001, failed=True)
    assert s.keep(0.001, shed=True)
    assert s.keep(0.001, hedged=True)
    assert s.keep(0.001, resumed=True)
    snap = s.snapshot()
    assert snap == {"policy": "tail", "kept": 5, "sampled_out": 1}
    # no explicit bar: the caller's windowed p95 decides
    s = obs.TailSampler(obs.ObsSpec(sample="tail"))
    assert s.keep(0.200, p95_s=0.1)
    assert not s.keep(0.050, p95_s=0.1)
    assert not s.keep(0.050, p95_s=None)      # no signal: count+drop
    # sample=all keeps everything, sampler is pure bookkeeping
    s = obs.TailSampler(obs.ObsSpec(sample="all"))
    assert s.keep(0.0001) and s.snapshot()["sampled_out"] == 0


def test_sample_trace_discards_buffered_spans():
    spec = obs.ObsSpec(sample="tail", sample_slow_ms=1000)
    with obs.session(spec) as o:
        with obs.span("boring") as sp:
            tid = sp.trace
        assert len(o.tracer.events()) == 1
        assert obs.sample_trace(tid, 0.001) is False
        assert o.tracer.events() == []        # physically discarded
        assert o.tracer.sampled_out == 1
        # an interesting request at the same latency is kept
        with obs.span("hedged") as sp:
            tid2 = sp.trace
        assert obs.sample_trace(tid2, 0.001, hedged=True) is True
        assert [e["name"] for e in o.tracer.events()] == ["hedged"]


def test_obs_spec_grammar_new_keys():
    s = obs.ObsSpec.parse("sample=tail,sample_slow_ms=250,"
                          "trace_ring=128,max_events_mb=1.5,"
                          "process=w0,flightrec=/tmp/fr,"
                          "flightrec_ring=64")
    assert s.sample == "tail" and s.sample_slow_ms == 250.0
    assert s.trace_ring == 128 and s.max_events_mb == 1.5
    assert s.process == "w0" and s.flightrec == "/tmp/fr"
    assert s.flightrec_ring == 64
    with pytest.raises(ValueError):
        obs.ObsSpec.parse("sample=sometimes")


# -- merged export: dedup, re-anchor, orphans, critical path -----------------

def _buf(process, pid, wall_origin_s, spans):
    evs = [{"ph": "M", "pid": pid, "tid": 0, "name": "process_name",
            "args": {"name": process}}]
    for name, sid, parent, ts, dur, extra in spans:
        args = {"span_id": sid, "trace": "t1"}
        if parent:
            args["parent_id"] = parent
        args.update(extra)
        evs.append({"ph": "X", "cat": "obs", "name": name,
                    "pid": pid, "tid": 1, "ts": ts, "dur": dur,
                    "args": args})
    return {"traceEvents": evs, "displayTimeUnit": "ms",
            "process": process, "pid": pid,
            "wall_origin_s": wall_origin_s}


def test_merge_dedupes_and_reanchors_onto_earliest_origin():
    router = _buf("router", 1, 100.0,
                  [("router.dispatch", 1, 0, 0.0, 1000.0, {})])
    worker = _buf("worker-0", 2, 100.0005,
                  [("serve.request", 2, 1, 0.0, 400.0,
                    {"engine": "e0"})])
    # the worker buffer pulled twice (overlapping /trace windows):
    # dedup on (pid, span_id) keeps one copy
    m = collect.merge([router, worker, worker])
    spans = [e for e in m["traceEvents"] if e["ph"] == "X"]
    assert len(spans) == 2
    assert collect.trace_ids(m) == ["t1"]
    # worker ts re-anchored by the 500us origin skew
    by_name = {e["name"]: e for e in spans}
    assert by_name["serve.request"]["ts"] == pytest.approx(500.0)
    assert by_name["router.dispatch"]["ts"] == pytest.approx(0.0)
    # metadata first, both process names survive
    assert m["traceEvents"][0]["ph"] == "M"
    assert m["processes"] == {1: "router", 2: "worker-0"}
    # every parent resolves across the process boundary
    assert collect.orphans(m, "t1") == []


def test_merge_orphans_and_critical_path():
    router = _buf("router", 1, 100.0,
                  [("router.dispatch", 1, 0, 0.0, 1000.0, {}),
                   ("lost", 3, 999, 10.0, 5.0, {})])
    worker = _buf("worker-0", 2, 100.0,
                  [("serve.request", 2, 1, 100.0, 400.0,
                    {"engine": "e0"})])
    m = collect.merge([router, worker])
    orphans = collect.orphans(m, "t1")
    assert [e["name"] for e in orphans] == ["lost"]
    # self time = duration minus child overlap: the dispatch span
    # mostly WAITED on the worker, so the worker leads the path
    rows = collect.critical_path(m, "t1")
    self_us = {r["name"]: r["self_us"] for r in rows}
    # the orphan's missing parent discounts nothing: 1000 - 400
    assert self_us["router.dispatch"] == pytest.approx(600.0)
    assert self_us["serve.request"] == pytest.approx(400.0)
    assert rows[0]["name"] == "router.dispatch"
    assert rows[0]["process"] == "router"
    assert {r["name"] for r in rows} == \
        {"router.dispatch", "serve.request", "lost"}


# -- event-log rotation: counters stay cumulative (satellite a) --------------

def test_eventlog_rotation_never_resets_counters(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    log = EventLog(path, max_bytes=256)
    for i in range(30):
        assert log.emit("tick", i=i, pad="x" * 40)
    assert log.rotations >= 1
    assert os.path.exists(path + ".1")
    assert log.written == 30 and log.dropped == 0
    log.close()
    # the live file holds only the post-rotation suffix, yet the
    # counter covered every generation
    with open(path) as f:
        live = [json.loads(ln) for ln in f if ln.strip()]
    assert 0 < len(live) < 30


def test_flush_accounting_survives_rotation(tmp_path):
    """The obs.flush record's `events_written` must keep adding up no
    matter how many times the JSONL file rolled underneath it."""
    path = str(tmp_path / "ev.jsonl")
    spec = obs.ObsSpec(events=path, max_events_mb=0.0002)  # ~200 B
    with obs.session(spec):
        for i in range(12):
            obs.emit_event("tick", i=i, pad="y" * 40)
    recs = []
    for p in (path, path + ".1"):
        if os.path.exists(p):
            with open(p) as f:
                recs += [json.loads(ln) for ln in f if ln.strip()]
    flush = [r for r in recs if r["kind"] == "obs.flush"]
    assert len(flush) == 1
    assert flush[0]["events_written"] >= 12
    assert flush[0]["events_rotations"] >= 1
    assert flush[0]["events_dropped"] == 0


# -- per-request lifecycle records (GET /debug/requests) ---------------------

def test_request_log_bounds_and_slowest():
    rl = RequestLog(keep=4, slowest=2)
    for i in range(10):
        rl.record(corr=f"req-{i}", latency_ms=float(i))
    snap = rl.snapshot()
    assert snap["recorded"] == 10
    assert [r["corr"] for r in snap["recent"]] == \
        ["req-6", "req-7", "req-8", "req-9"]
    assert [r["latency_ms"] for r in snap["slowest"]] == [9.0, 8.0]
    assert all("ts" in r for r in snap["recent"])


# -- real Prometheus histograms (satellite b) --------------------------------

def test_router_stats_histograms_render():
    reg = MetricsRegistry()
    rs = RouterStats()
    rs.register_into(reg)
    rs.observe_latency(0.05)
    rs.observe_stage("admit", 0.01)
    rs.observe_stage("decode", 0.04)
    text = reg.render_prometheus()
    for name in ("singa_fleet_request_latency_seconds",
                 "singa_request_stage_seconds_admit",
                 "singa_request_stage_seconds_decode"):
        assert f"{name}_bucket{{le=" in text, name
        assert f"{name}_sum" in text and f"{name}_count" in text
    # no registry attached: observe_stage is a no-op, not a crash
    RouterStats().observe_stage("admit", 0.01)


def test_serve_stats_histograms_render():
    reg = MetricsRegistry()
    ss = ServeStats()
    ss.register_into(reg)
    ss.observe_latency(0.02)
    ss.observe_request(queue_wait_s=0.005, service_s=0.015,
                       ntokens=8)
    text = reg.render_prometheus()
    for name in ("singa_serve_request_latency_seconds",
                 "singa_serve_queue_wait_seconds",
                 "singa_serve_service_seconds"):
        assert f"{name}_bucket{{le=" in text, name
        assert f"{name}_sum" in text and f"{name}_count" in text
    # unregistered stats keep working without histograms
    ServeStats().observe_latency(0.01)


# -- scriptable stream stubs (the test_failover.py mold) ---------------------

def _tok(step, j):
    return (int(step) * 7 + j * 3) % 101


class StreamStubHandle:
    """Engine-handle double speaking the indexed stream protocol,
    scriptable to die at an absolute token index (fires once)."""

    def __init__(self, name, step=1):
        self.name = name
        self.step = step
        self.die_at = None
        self.calls = []

    def probe(self):
        return {"ok": True, "status": "ok", "step": self.step,
                "queue_depth": 0}

    def stats_snapshot(self):
        return {"completed": 0, "failed": 0, "expired": 0,
                "p95_latency_ms": None}

    def request(self, mode, tokens, timeout=None):
        return {"tokens": [1], "step": self.step}

    def request_stream(self, tokens, timeout=None, max_new=None,
                       deadline=None, priority="interactive",
                       cancel_event=None, resume_from=0):
        self.calls.append((int(resume_from), len(tokens)))

        def gen():
            for j in range(int(resume_from), int(max_new)):
                if self.die_at == j:
                    self.die_at = None
                    raise RuntimeError(f"{self.name} exploded at {j}")
                yield {"token": _tok(self.step, j), "i": j}
            yield {"done": True, "finish": "length",
                   "step": self.step,
                   "tokens": [_tok(self.step, j) for j in
                              range(int(resume_from), int(max_new))]}
        return gen()


class SlowUnaryStubHandle(StreamStubHandle):
    """Unary requests take `delay` seconds — long enough for the
    router's forced 10ms hedge delay to fire a second leg."""

    def __init__(self, name, step=1, delay=0.15):
        super().__init__(name, step=step)
        self.delay = delay

    def request(self, mode, tokens, timeout=None):
        time.sleep(self.delay)
        return {"tokens": [1], "step": self.step}


def _router(handles, **spec_kw):
    spec_kw.setdefault("probe_period_s", 60.0)
    spec_kw.setdefault("quarantine_after", 10)
    spec_kw.setdefault("request_timeout_s", 10.0)
    spec_kw.setdefault("hedge", "off")
    r = Router(handles, spec=RouterSpec(**spec_kw),
               log_fn=lambda s: None)
    r.probe_all()
    return r


def _consume(stream):
    toks, done = [], None
    for ev in stream:
        if ev.get("done"):
            done = ev
            break
        toks.append(ev)
    return toks, done


# -- satellite c: one trace id across primary + hedge + resumed legs ---------

def test_one_trace_id_spans_failover_legs():
    """A mid-stream engine death must not fork the trace: the resume
    leg (and the post-hoc stage spans) anchor under the originating
    `router.stream` span, same trace id, same corr."""
    e0, e1 = StreamStubHandle("e0"), StreamStubHandle("e1")
    e0.die_at = 3
    r = _router([e0, e1])
    try:
        with obs.session(obs.ObsSpec()):
            toks, done = _consume(r.route_stream([5, 6], max_new=8))
            evs = [e for e in obs.trace_dump()["traceEvents"]
                   if e["ph"] == "X"]
            merged = collect.merge([obs.trace_dump()])
        assert done["spliced"] is True and done["resumes"] == 1
        assert len(toks) == 8
        by_name = {}
        for e in evs:
            by_name.setdefault(e["name"], []).append(e)
        for needed in ("router.stream", "router.attempt",
                       "router.resume", "stream.first_token",
                       "stream.decode"):
            assert needed in by_name, (needed, sorted(by_name))
        root = by_name["router.stream"][0]
        tid = root["args"]["trace"]
        corr = root["args"]["corr"]
        # every leg — dispatch attempt, failover resume, post-hoc
        # stage spans — carries the ONE trace id and originating corr
        legs = (by_name["router.attempt"] + by_name["router.resume"]
                + by_name["stream.first_token"]
                + by_name["stream.decode"])
        assert {e["args"]["trace"] for e in legs} == {tid}
        assert {e["args"].get("corr") for e in legs} == {corr}
        # the resume leg is anchored under the stream root and names
        # both engines of the splice
        rsp = by_name["router.resume"][0]["args"]
        assert rsp["parent_id"] == root["args"]["span_id"]
        assert rsp["from_engine"] == "e0" and rsp["engine"] == "e1"
        assert collect.orphans(merged, tid) == []
        # the lifecycle record indexes the same trace
        row = r.requests.snapshot()["recent"][-1]
        assert row["trace"] == tid and row["corr"] == corr
        assert row["outcome"] == "spliced" and row["resumes"] == 1
    finally:
        r.stop()


def test_one_trace_id_spans_hedge_legs():
    """Both legs of a hedged unary request carry the originating
    corr/trace — the regression was each hedge run() thread minting a
    fresh root, making hedges invisible in any trace."""
    e0 = SlowUnaryStubHandle("e0")
    e1 = SlowUnaryStubHandle("e1")
    r = _router([e0, e1], hedge="on",
                hedge_min_s=0.01, hedge_max_s=0.01)
    try:
        with obs.session(obs.ObsSpec()):
            out = r.route("generate", [5, 6])
            # the losing leg closes its span AFTER the winner returns
            # (its thread is still in the stub's sleep): wait for it
            stop = time.monotonic() + 5.0
            while time.monotonic() < stop:
                evs = [e for e in obs.trace_dump()["traceEvents"]
                       if e["ph"] == "X"]
                if sum(1 for e in evs
                       if e["name"] == "router.attempt") >= 2:
                    break
                time.sleep(0.01)
        assert out["engine"] in ("e0", "e1")
        disp = [e for e in evs if e["name"] == "router.dispatch"]
        attempts = [e for e in evs if e["name"] == "router.attempt"]
        assert len(disp) == 1 and len(attempts) >= 2
        tid = disp[0]["args"]["trace"]
        corr = disp[0]["args"]["corr"]
        assert {e["args"]["trace"] for e in attempts} == {tid}
        assert {e["args"]["corr"] for e in attempts} == {corr}
        hedge_flags = {e["args"]["hedge"] for e in attempts}
        assert hedge_flags == {True, False}
        assert all(e["args"]["parent_id"] ==
                   disp[0]["args"]["span_id"] for e in attempts)
        row = r.requests.snapshot()["recent"][-1]
        assert row["hedged"] is True and row["trace"] == tid
    finally:
        r.stop()


def test_stage_partition_sums_to_latency():
    """admit/first_token/decode share one clock and its boundary
    stamps, so the recorded stages sum to the recorded latency."""
    r = _router([StreamStubHandle("e0")])
    try:
        with obs.session(obs.ObsSpec()):
            _consume(r.route_stream([5], max_new=4))
        row = r.requests.snapshot()["recent"][-1]
        assert set(row["stages_ms"]) == \
            {"admit", "first_token", "decode"}
        assert sum(row["stages_ms"].values()) == \
            pytest.approx(row["latency_ms"], abs=0.005)
    finally:
        r.stop()


# -- flight recorder ---------------------------------------------------------

def test_flightrec_trigger_table(tmp_path):
    fr = FlightRecorder(str(tmp_path), ring=32, cooldown_s=0.05)
    path = fr.observe("fleet.rollback", {"target": 7})
    assert path and "flightrec-rollback-" in os.path.basename(path)
    with open(path) as f:
        dump = json.load(f)
    assert dump["trigger"] == "rollback"
    assert dump["events"][-1]["kind"] == "fleet.rollback"
    assert dump["events"][-1]["target"] == 7
    # rate limit: a second rollback inside the cooldown is absorbed
    assert fr.observe("fleet.rollback", {}) is None
    time.sleep(0.06)
    assert fr.observe("fleet.rollback", {}) is not None
    # the rest of the trigger table
    p = fr.observe("fleet.quarantine", {"engine": "e0"})
    assert p and "flightrec-quarantine-" in os.path.basename(p)
    p = fr.observe("stream.resume", {"sid": "s1"})
    assert p and "flightrec-failover-" in os.path.basename(p)
    p = fr.observe("health.verdict", {"verdict": "DIVERGED"})
    assert p and "flightrec-divergence-" in os.path.basename(p)
    assert fr.observe("health.verdict", {"verdict": "HEALTHY"}) is None
    assert fr.dumps == 5 and fr.dump_failures == 0


def test_flightrec_shed_storm(tmp_path):
    fr = FlightRecorder(str(tmp_path), cooldown_s=0.0)
    paths = [fr.observe("serve.shed", {"priority": "best_effort"})
             for _ in range(16)]
    # one shed is load; the 16th inside the window is an incident
    assert all(p is None for p in paths[:15])
    assert paths[15] and "shed_storm" in os.path.basename(paths[15])
    assert fr.sheds_seen == 16


def test_flightrec_dump_carries_tracer_tail(tmp_path):
    t = Tracer(process="w0")
    t.add_span("serve.request", time.perf_counter(), 0.001,
               corr="req-1")
    fr = FlightRecorder(str(tmp_path))
    path = fr.trigger("quarantine", tracer=t, engine="e0", strikes=3)
    with open(path) as f:
        dump = json.load(f)
    assert dump["process"] == "w0"
    assert dump["context"] == {"engine": "e0", "strikes": 3}
    assert [s["name"] for s in dump["spans"]] == ["serve.request"]


def test_flightrec_fires_without_trace_export(tmp_path):
    """The 3am story: nobody configured trace/events exporters, only
    `flightrec=...` — a failover event must still leave a dump."""
    fr_dir = str(tmp_path / "fr")
    with obs.session(obs.ObsSpec(flightrec=fr_dir)):
        obs.emit_event("stream.resume", sid="s1", from_engine="e0",
                       engine="e1", at=3)
    dumps = glob.glob(os.path.join(fr_dir, "flightrec-failover-*.json"))
    assert len(dumps) == 1
    with open(dumps[0]) as f:
        dump = json.load(f)
    assert any(ev["kind"] == "stream.resume" for ev in dump["events"])


def test_obs_flush_fault_triggers_flightrec(tmp_path):
    """A faulted telemetry teardown is itself a trigger — the one
    loss the recorder exists to survive."""
    fr_dir = str(tmp_path / "fr")
    sched = FaultSchedule.parse("obs.flush@0")
    with obs.session(obs.ObsSpec(flightrec=fr_dir)):
        with obs.span("work"):
            pass
        with inject(sched):
            obs.disable()                      # flush under fault
    assert [f.site for f in sched.fired] == ["obs.flush"]
    dumps = glob.glob(os.path.join(fr_dir, "flightrec-*.json"))
    assert len(dumps) == 1
    with open(dumps[0]) as f:
        dump = json.load(f)
    assert dump["trigger"] == "obs.flush_fault"
    assert [s["name"] for s in dump["spans"]] == ["work"]


# -- satellite d: real two-process propagation -------------------------------

@pytest.mark.slow
def test_worker_spans_carry_router_trace_two_process(tmp_path):
    """Spawn a real pinned worker subprocess with `--obs on`, route
    one request through a local Router under a router-side session,
    pull the worker's `/trace` ring, and prove the merged file holds
    ONE trace spanning both pids with zero orphans."""
    port = 18517
    url = f"http://127.0.0.1:{port}"
    spec = ("buckets=2x128,max_new_tokens=8,batch_window_s=0.005,"
            "cb=on,cb_slots=2,cb_block_len=16")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "singa_tpu.main", "serve",
         "-model_conf", "examples/transformer/lm.conf", "--pinned",
         "--port", str(port), "--serve_spec", spec,
         "--workspace", str(tmp_path), "--obs", "on",
         "--obs_spec", "trace_ring=4096,process=worker-0"],
        cwd=REPO, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    r = None
    try:
        deadline = time.monotonic() + 300.0
        while True:
            if proc.poll() is not None:
                pytest.fail("worker exited before serving /healthz")
            try:
                with urllib.request.urlopen(url + "/healthz",
                                            timeout=2.0) as resp:
                    if resp.status == 200:
                        break
            except Exception:
                pass
            if time.monotonic() > deadline:
                pytest.fail("worker never became healthy")
            time.sleep(0.5)
        with obs.session(obs.ObsSpec(process="router",
                                     trace_ring=65536)):
            r = Router([HttpEngineHandle("w0", url)],
                       spec=RouterSpec(probe_period_s=60.0,
                                       quarantine_after=5,
                                       request_timeout_s=120.0,
                                       hedge="off"),
                       log_fn=lambda s: None)
            r.probe_all()
            out = r.route("generate", [5, 7, 9, 11], timeout=120.0)
            assert out["tokens"]
            row = r.requests.snapshot()["recent"][-1]
            tid = row["trace"]
            assert tid
            worker_buf = collect.fetch_trace(url)
            merged = collect.merge([obs.trace_dump(), worker_buf])
        spans = collect.spans_of(merged, tid)
        names = {e["name"] for e in spans}
        assert "router.dispatch" in names and "serve.request" in names
        # the trace crossed the process boundary: both pids, both
        # process names, and every remote parent resolves
        assert len({e["pid"] for e in spans}) >= 2
        assert {"router", "worker-0"} <= set(merged["processes"].values())
        assert collect.orphans(merged, tid) == []
    finally:
        if r is not None:
            r.stop()
        proc.kill()
        proc.wait(30)
