"""The scoped-VMEM compiler-option knob (VERDICT r2 item 9).

Policy: ModelProto `scoped_vmem` (auto|on|off), overridden by the
SINGA_TPU_SCOPED_VMEM env var.  `auto` applies the raised budget only
to conv stacks whose widest conv has >= 96 filters — the documented
workaround for the LeNet-scale compile hang.
"""

import pytest

import singa_tpu.ops.attention as attention
from singa_tpu.config.schema import ConfigError, model_config_from_dict
from singa_tpu.core.trainer import Trainer
from singa_tpu.models.vision import alexnet_cifar10_full, lenet_mnist

ALEX_SHAPES = {"data": {"pixel": (3, 32, 32), "label": ()}}
LENET_SHAPES = {"data": {"pixel": (28, 28), "label": ()}}


def _opts(cfg, shapes, monkeypatch, env=None):
    monkeypatch.setattr(attention, "_on_tpu", lambda: True)
    if env is not None:
        monkeypatch.setenv("SINGA_TPU_SCOPED_VMEM", env)
    else:
        monkeypatch.delenv("SINGA_TPU_SCOPED_VMEM", raising=False)
    t = Trainer(cfg, shapes, log_fn=lambda s: None)
    return t._compiler_options()


def test_auto_picks_option_for_alexnet(monkeypatch):
    opts = _opts(alexnet_cifar10_full(batchsize=8), ALEX_SHAPES,
                 monkeypatch)
    assert opts == Trainer.TPU_CONV_COMPILER_OPTIONS


def test_auto_skips_lenet(monkeypatch):
    assert _opts(lenet_mnist(batchsize=8), LENET_SHAPES,
                 monkeypatch) is None


def test_field_off_disables(monkeypatch):
    cfg = alexnet_cifar10_full(batchsize=8)
    cfg.scoped_vmem = "off"
    assert _opts(cfg, ALEX_SHAPES, monkeypatch) is None


def test_field_on_forces_for_lenet(monkeypatch):
    cfg = lenet_mnist(batchsize=8)
    cfg.scoped_vmem = "on"
    assert _opts(cfg, LENET_SHAPES,
                 monkeypatch) == Trainer.TPU_CONV_COMPILER_OPTIONS


def test_env_overrides_field(monkeypatch):
    cfg = alexnet_cifar10_full(batchsize=8)
    cfg.scoped_vmem = "on"
    assert _opts(cfg, ALEX_SHAPES, monkeypatch, env="off") is None


def test_bad_env_fails_loud(monkeypatch):
    with pytest.raises(ValueError, match="SINGA_TPU_SCOPED_VMEM"):
        _opts(lenet_mnist(batchsize=8), LENET_SHAPES, monkeypatch,
              env="sometimes")


def test_bad_field_fails_loud():
    with pytest.raises(ConfigError, match="scoped_vmem"):
        model_config_from_dict({"name": "x", "scoped_vmem": "maybe"})


def test_textproto_field_parses():
    cfg = model_config_from_dict({"name": "x", "scoped_vmem": "on"})
    assert cfg.scoped_vmem == "on"


def test_attention_family_gets_modest_budget(monkeypatch):
    from singa_tpu.models.transformer import transformer_lm
    cfg = transformer_lm(vocab_size=64, num_layers=1, embed_dim=32,
                         num_heads=2, head_dim=16, seq_len=32,
                         batchsize=4)
    shapes = {"data": {"input": (32,), "target": (32,)}}
    assert _opts(cfg, shapes,
                 monkeypatch) == Trainer.TPU_ATTN_COMPILER_OPTIONS
    # "on" must force the FAMILY budget, never the conv-sized one
    # (which starves the flash kernels)
    cfg2 = transformer_lm(vocab_size=64, num_layers=1, embed_dim=32,
                          num_heads=2, head_dim=16, seq_len=32,
                          batchsize=4)
    cfg2.scoped_vmem = "on"
    assert _opts(cfg2, shapes,
                 monkeypatch) == Trainer.TPU_ATTN_COMPILER_OPTIONS
