"""Fused Pallas LM-head forward (ops/head_loss.py) vs the chunked XLA
path and the dense softmax_loss_metrics oracle — loss, top-1
precision, argmax tie semantics, and gradients."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from singa_tpu.ops.head_loss import fused_lm_xent
from singa_tpu.ops.loss import chunked_lm_xent, softmax_loss_metrics

N, E, V = 64, 128, 512
BN, BV = 16, 128


def _data(seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    h = jnp.asarray(rng.standard_normal((N, E)), dtype)
    w_vE = jnp.asarray(rng.standard_normal((V, E)) * 0.05, dtype)
    labels = jnp.asarray(rng.integers(0, V, (N,)), jnp.int32)
    return h, w_vE, labels


def _fused(h, w, labels, scale=1.0):
    return fused_lm_xent(h, w, labels, scale, 4096, BN, BV, True)


def test_fused_matches_dense_oracle():
    h, w, labels = _data()
    loss_f, prec_f = _fused(h, w, labels)
    logits = (h @ w.T).astype(jnp.float32)
    loss_d, prec_d = softmax_loss_metrics(logits, labels)
    np.testing.assert_allclose(float(loss_f), float(loss_d), rtol=1e-5)
    np.testing.assert_allclose(float(prec_f), float(prec_d), rtol=1e-6)


def test_fused_matches_chunked():
    h, w, labels = _data(1)
    loss_f, prec_f = _fused(h, w, labels, scale=2.0)
    loss_c, prec_c = chunked_lm_xent(h, w, labels, chunk_size=16,
                                     scale=2.0, w_is_vE=True)
    np.testing.assert_allclose(float(loss_f), float(loss_c), rtol=1e-5)
    np.testing.assert_allclose(float(prec_f), float(prec_c), rtol=1e-6)


def test_argmax_tie_lowest_index_wins():
    h = jnp.zeros((N, E), jnp.float32)      # all logits identical (0)
    _, w, _ = _data(2)
    w = jnp.zeros_like(w)
    labels = jnp.zeros((N,), jnp.int32)     # label 0 == argmax 0
    _, prec = _fused(h, w, labels)
    assert float(prec) == 1.0               # every row ties; idx 0 wins
    labels2 = jnp.ones((N,), jnp.int32)
    _, prec2 = _fused(h, w, labels2)
    assert float(prec2) == 0.0


def test_gradients_match_chunked():
    h, w, labels = _data(3)

    def f_fused(hh, ww):
        loss, _ = _fused(hh, ww, labels, scale=1.5)
        return loss

    def f_chunk(hh, ww):
        loss, _ = chunked_lm_xent(hh, ww, labels, chunk_size=16,
                                  scale=1.5, w_is_vE=True)
        return loss

    (lf, (dh_f, dw_f)) = jax.value_and_grad(f_fused, (0, 1))(h, w)
    (lc, (dh_c, dw_c)) = jax.value_and_grad(f_chunk, (0, 1))(h, w)
    np.testing.assert_allclose(float(lf), float(lc), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(dh_f), np.asarray(dh_c),
                               rtol=2e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(dw_f), np.asarray(dw_c),
                               rtol=2e-4, atol=1e-6)


def test_label_logit_exact():
    """The online pass must pick the label's exact f32 logit, not an
    approximation — loss for a one-hot-certain row is ~0."""
    rng = np.random.default_rng(4)
    h = jnp.asarray(rng.standard_normal((N, E)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((V, E)) * 0.05, jnp.float32)
    logits = h @ w.T
    labels = jnp.argmax(logits, axis=1).astype(jnp.int32)
    loss_f, prec_f = _fused(h, w, labels)
    loss_d, _ = softmax_loss_metrics(logits.astype(jnp.float32), labels)
    assert float(prec_f) == 1.0
    np.testing.assert_allclose(float(loss_f), float(loss_d), rtol=1e-5)


def test_layer_gating(monkeypatch):
    """The LMHeadLoss layer selects the fused kernel exactly when the
    head is tied, top-1, kernel-legal, and on a real TPU."""
    import singa_tpu.ops.attention as attention
    from singa_tpu.core.net import build_net
    from singa_tpu.models.transformer import transformer_lm

    cfg = transformer_lm(vocab_size=2048, num_layers=1, embed_dim=128,
                         num_heads=2, head_dim=64, seq_len=128,
                         batchsize=4)
    net = build_net(cfg, "kTrain", {"data": {"input": (128,),
                                             "target": (128,)}})
    layer = net.layers["loss"]
    h2 = jnp.zeros((4 * 128, 128), jnp.bfloat16)      # N=512, E=128
    w = jnp.zeros((2048, 128), jnp.bfloat16)          # (V, E)

    monkeypatch.setattr(attention, "_on_tpu", lambda: True)
    assert layer._use_fused(h2, w, True)
    assert not layer._use_fused(h2, w, False)          # untied (E,V)
    layer.topk = 5
    assert not layer._use_fused(h2, w, True)           # top-k > 1
    layer.topk = 1
    # shape-illegal: N not a multiple of the token block
    assert not layer._use_fused(h2[:100], w, True)
    # off-TPU: always the chunked XLA path
    monkeypatch.setattr(attention, "_on_tpu", lambda: False)
    assert not layer._use_fused(h2, w, True)
