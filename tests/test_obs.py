"""Unified telemetry tests (ISSUE 6): span tracer + Chrome trace
export, correlation-id flow, ObsSpec grammar, metrics registry +
Prometheus exposition, /metrics-vs-/stats consistency on a live
server, obs.emit fault degradation (dropped telemetry, work
unaffected), the ServeStats.gauge typo regression, and the windowed
QPS / uptime satellites.

Cost control: the one compiled-engine test module-scopes a 1-layer
single-bucket LM server; everything else is pure-host."""

import json
import threading
import urllib.request

import numpy as np
import pytest

from singa_tpu import obs
from singa_tpu.config.schema import model_config_from_dict
from singa_tpu.core.trainer import Trainer
from singa_tpu.data.synthetic import synthetic_image_batches
from singa_tpu.obs.log import EventLog
from singa_tpu.obs.metrics import (MetricsRegistry, Sample,
                                   parse_prometheus)
from singa_tpu.obs.trace import NULL_SPAN
from singa_tpu.serve.stats import ServeStats
from singa_tpu.utils.faults import FaultSchedule, inject

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _no_leaked_session():
    obs.disable()
    yield
    obs.disable()


# -- tracer / spans ----------------------------------------------------------

def test_span_is_null_when_off():
    assert obs.active() is None
    assert obs.span("anything", corr="x") is NULL_SPAN
    with obs.span("anything") as sp:
        sp.set(k=1)                      # no-op, no error
    assert obs.current_corr() is None
    obs.emit_event("nothing", a=1)       # no-op, no error


def test_trace_export_nested_parented_corr(tmp_path):
    trace_path = tmp_path / "trace.json"
    spec = obs.ObsSpec(trace=str(trace_path))
    with obs.session(spec):
        with obs.span("outer", corr="attempt-1", step=4) as outer:
            assert obs.current_corr() == "attempt-1"
            with obs.span("inner") as inner:
                # same-thread spans inherit parent + corr
                assert inner.parent_id == outer.span_id
                assert inner.corr == "attempt-1"
            with obs.span("override", corr="req-9") as ov:
                assert ov.corr == "req-9"
    # session exit exported the trace
    d = json.loads(trace_path.read_text())
    assert d["displayTimeUnit"] == "ms"
    evs = {e["name"]: e for e in d["traceEvents"] if e["ph"] == "X"}
    assert set(evs) == {"outer", "inner", "override"}
    for e in evs.values():
        assert isinstance(e["ts"], (int, float))
        assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
        assert e["cat"] == "obs"
    assert evs["inner"]["args"]["parent_id"] == \
        evs["outer"]["args"]["span_id"]
    assert evs["inner"]["args"]["corr"] == "attempt-1"
    assert evs["override"]["args"]["corr"] == "req-9"
    assert evs["outer"]["args"]["step"] == 4
    assert "parent_id" not in evs["outer"]["args"]
    # thread-name metadata rides along for Perfetto track naming
    assert any(e["ph"] == "M" and e["name"] == "thread_name"
               for e in d["traceEvents"])


def test_cross_thread_corr_handoff():
    with obs.session(obs.ObsSpec()) as o:
        with obs.span("consumer", corr="attempt-3"):
            corr = obs.current_corr()    # explicit hand-off value

            def producer():
                # thread-local stacks do NOT cross threads: without the
                # explicit corr the producer span would be rootless
                assert obs.current_corr() is None
                with obs.span("producer", corr=corr):
                    pass

            t = threading.Thread(target=producer)
            t.start()
            t.join()
        evs = {e["name"]: e for e in o.tracer.events()}
        assert evs["producer"]["args"]["corr"] == "attempt-3"
        assert "parent_id" not in evs["producer"]["args"]


def test_span_records_error_and_propagates():
    with obs.session(obs.ObsSpec()) as o:
        with pytest.raises(RuntimeError, match="boom"):
            with obs.span("failing"):
                raise RuntimeError("boom")
        (ev,) = o.tracer.events()
        assert ev["args"]["error"] == "RuntimeError"


# -- ObsSpec grammar ---------------------------------------------------------

def test_obsspec_parse_grammar():
    spec = obs.ObsSpec.parse("trace=/tmp/t.json;events=/tmp/e.jsonl,"
                             "metrics_period_s=2.5,max_spans=100")
    assert spec.trace == "/tmp/t.json"
    assert spec.events == "/tmp/e.jsonl"
    assert spec.metrics_period_s == 2.5 and spec.max_spans == 100
    assert obs.ObsSpec.parse(None) == obs.ObsSpec()
    assert obs.ObsSpec.parse("") == obs.ObsSpec()
    with pytest.raises(ValueError, match="bad obs spec entry"):
        obs.ObsSpec.parse("bogus=1")
    with pytest.raises(ValueError, match="bad obs spec"):
        obs.ObsSpec.parse("max_spans")           # no '='
    with pytest.raises(ValueError, match="bad obs spec value"):
        obs.ObsSpec.parse("max_spans=lots")


# -- metrics registry --------------------------------------------------------

def test_registry_prometheus_render_roundtrip():
    reg = MetricsRegistry()
    c = reg.counter("singa_test_steps_total", "steps")
    c.inc()
    c.inc(2)
    assert reg.counter("singa_test_steps_total") is c  # idempotent
    reg.gauge("singa_test_depth").set(7)
    h = reg.histogram("singa_test_latency_seconds",
                      buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    reg.register_collector(lambda: [
        Sample("singa_test_collected", "gauge", "from a surface", 3.5)])
    text = reg.render_prometheus()
    assert "# TYPE singa_test_steps_total counter" in text
    assert "# TYPE singa_test_latency_seconds histogram" in text
    parsed = parse_prometheus(text)
    assert parsed["singa_test_steps_total"] == 3
    assert parsed["singa_test_depth"] == 7
    assert parsed["singa_test_collected"] == 3.5
    # cumulative le-buckets + sum/count
    assert parsed['singa_test_latency_seconds_bucket{le="0.1"}'] == 1
    assert parsed['singa_test_latency_seconds_bucket{le="1"}'] == 2
    assert parsed['singa_test_latency_seconds_bucket{le="+Inf"}'] == 3
    assert parsed["singa_test_latency_seconds_count"] == 3
    assert abs(parsed["singa_test_latency_seconds_sum"] - 5.55) < 1e-9
    # flat snapshot mirrors the same data
    snap = reg.snapshot()
    assert snap["singa_test_steps_total"] == 3
    assert snap["singa_test_latency_seconds_count"] == 3
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("singa_test_steps_total")
    with pytest.raises(ValueError, match="bad metric name"):
        parse_prometheus("this is not prometheus\n")
    with pytest.raises(ValueError, match="bad exposition line"):
        parse_prometheus("lonely_name\n")


def test_registry_broken_collector_is_skipped():
    reg = MetricsRegistry()
    reg.counter("singa_ok_total").inc()
    reg.register_collector(lambda: 1 / 0)
    parsed = parse_prometheus(reg.render_prometheus())
    assert parsed["singa_ok_total"] == 1
    assert reg.collector_errors >= 1


# -- live server: /metrics vs /stats ----------------------------------------

@pytest.fixture(scope="module")
def http_server():
    import jax

    from singa_tpu.core.net import build_net
    from singa_tpu.models.transformer import transformer_lm
    from singa_tpu.serve import (InferenceEngine, InferenceServer,
                                 ServeSpec)
    cfg = transformer_lm(vocab_size=64, num_layers=1, embed_dim=32,
                         num_heads=4, head_dim=8, seq_len=16,
                         batchsize=2)
    net = build_net(cfg, "kTest",
                    {"data": {"input": (16,), "target": (16,)}})
    params = net.init_params(jax.random.PRNGKey(0))
    spec = ServeSpec(buckets=((2, 6),), max_new_tokens=3,
                     batch_window_s=0.005, request_timeout_s=20.0)
    engine = InferenceEngine(net, spec, params=params,
                             log_fn=lambda s: None)
    server = InferenceServer(engine, port=0, http=True,
                             log_fn=lambda s: None)
    server.start()
    yield server
    server.stop()


def _get(server, path):
    host, port = server.address
    with urllib.request.urlopen(f"http://{host}:{port}{path}",
                                timeout=30) as r:
        return r.headers.get("Content-Type", ""), r.read().decode()


def test_metrics_endpoint_agrees_with_stats(http_server):
    server = http_server
    for plen in (2, 5, 3):
        server.generate(np.arange(1, 1 + plen, dtype=np.int32))
    ctype, text = _get(server, "/metrics")
    assert ctype.startswith("text/plain")
    parsed = parse_prometheus(text)          # valid exposition format
    _, stats_body = _get(server, "/stats")
    stats = json.loads(stats_body)
    for k in ("submitted", "completed", "failed", "shed", "batches",
              "compiles", "reloads"):
        assert parsed[f"singa_serve_{k}_total"] == stats[k], k
    assert parsed["singa_serve_queue_depth"] == stats["queue_depth"]
    assert parsed["singa_serve_uptime_s"] >= 0
    assert parsed["singa_serve_p95_latency_ms"] == \
        stats["p95_latency_ms"]


def test_obs_emit_fault_request_still_served(http_server):
    server = http_server
    sched = FaultSchedule.parse("obs.emit@0")
    with obs.session(obs.ObsSpec()) as o:
        with inject(sched):
            out = server.generate(np.array([5, 6], np.int32))
    assert len(out["tokens"]) == 3           # request completed
    assert [f.site for f in sched.fired] == ["obs.emit"]
    assert o.tracer.dropped >= 1             # telemetry degraded


# -- obs.emit fault on the training side -------------------------------------

def _tiny_mlp_cfg(train_steps=4):
    return model_config_from_dict({
        "name": "obs-mlp", "train_steps": train_steps,
        "updater": {"type": "kSGD", "base_learning_rate": 0.01,
                    "learning_rate_change_method": "kFixed"},
        "neuralnet": {"layer": [
            {"name": "data", "type": "kShardData",
             "data_param": {"batchsize": 8}},
            {"name": "mnist", "type": "kMnistImage",
             "srclayers": "data", "mnist_param": {"norm_a": 255.0}},
            {"name": "label", "type": "kLabel", "srclayers": "data"},
            {"name": "ip", "type": "kInnerProduct", "srclayers": "mnist",
             "inner_product_param": {"num_output": 10},
             "param": [{"name": "w", "init_method": "kUniformSqrtFanIn"},
                       {"name": "b"}]},
            {"name": "loss", "type": "kSoftmaxLoss",
             "srclayers": ["ip", "label"]}]}})


def test_obs_emit_fault_training_step_completes(tmp_path):
    shapes = {"data": {"pixel": (28, 28), "label": ()}}
    tr = Trainer(_tiny_mlp_cfg(), shapes, log_fn=lambda s: None,
                 donate=False)
    p, o = tr.init(seed=0)
    spec = obs.ObsSpec(trace=str(tmp_path / "t.json"),
                       events=str(tmp_path / "e.jsonl"))
    sched = FaultSchedule.parse("obs.emit@0,obs.emit@1")
    with obs.session(spec) as sess:
        with inject(sched):
            p, o, hist = tr.run(p, o, synthetic_image_batches(
                8, seed=3, stream_seed=104), seed=0)
        dropped = sess.tracer.dropped + \
            (sess.events.dropped if sess.events else 0)
    assert len(sched.fired) == 2             # both faults consumed
    assert dropped >= 1                      # into drop counters...
    for k in p:                              # ...not into the step
        assert np.all(np.isfinite(np.asarray(p[k]))), k


# -- event log + logger ------------------------------------------------------

def test_event_log_writes_jsonl(tmp_path):
    path = tmp_path / "events.jsonl"
    ev = EventLog(str(path))
    assert ev.emit("supervisor.restart", attempt=1, fail_kind="preempt")
    assert ev.emit("health.verdict", step=3, status="SPIKE")
    ev.close()
    assert not ev.emit("late")               # closed -> dropped
    recs = [json.loads(l) for l in path.read_text().splitlines()]
    assert [r["kind"] for r in recs] == ["supervisor.restart",
                                         "health.verdict"]
    assert recs[0]["attempt"] == 1 and "ts" in recs[0]
    assert ev.written == 2 and ev.dropped == 1


def test_logger_prefix_levels_and_event_mirror(tmp_path):
    lines = []
    log = obs.get_logger("trainer", sink=lines.append)
    log("step-5: loss : 0.3")
    log("warning: something soft failed")
    assert lines == ["[trainer] step-5: loss : 0.3",
                     "[trainer] warning: something soft failed"]
    # warning+ records mirror into the ACTIVE session's event log,
    # resolved per call — the logger predates the session
    spec = obs.ObsSpec(events=str(tmp_path / "e.jsonl"))
    with obs.session(spec):
        log("warning: now mirrored")
        log("plain info, not mirrored")
    recs = [json.loads(l) for l in
            (tmp_path / "e.jsonl").read_text().splitlines()]
    logged = [r for r in recs if r["kind"] == "log"]
    assert len(logged) == 1
    assert logged[0]["component"] == "trainer"
    assert logged[0]["level"] == "warning"
    assert "now mirrored" in logged[0]["msg"]


# -- ServeStats satellites ---------------------------------------------------

def test_gauge_typo_raises_attribute_error():
    st = ServeStats()
    st.gauge("queue_depth", 5)
    assert st.queue_depth == 5
    with pytest.raises(AttributeError):
        st.gauge("queue_dpeth", 5)           # the regression: silent
    assert not hasattr(st, "queue_dpeth")    # attribute creation


def test_qps_recent_and_uptime():
    st = ServeStats(qps_window_s=10.0)
    assert st.qps_recent() == 0.0            # idle from birth
    for _ in range(5):
        st.observe_latency(0.01)
    assert st.qps_recent() > 0.0
    assert st.uptime_s() >= 0.0
    snap = st.snapshot()
    assert snap["completed"] == 5
    assert snap["qps_recent"] > 0.0
    assert snap["uptime_s"] >= 0.0
    # lifetime qps also positive here; the two only diverge when
    # traffic stops (qps decays, qps_recent zeroes out of the window)
    assert snap["qps"] > 0.0
