"""Elastic/RandomSync cross-slice tier tests (reference algorithm parity:
param.cc:102-256, param_manager.cc:85-93, worker.cc:44-55)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from singa_tpu.config.schema import UpdaterConfig
from singa_tpu.parallel.elastic import (ElasticController, elastic_update,
                                        randomsync_update, sync_sample_ratio)


def test_elastic_update_reference_formula():
    replica = {"w": jnp.array([2.0, 0.0])}
    center = {"w": jnp.array([0.0, 1.0])}
    r2, c2 = elastic_update(replica, center, alpha=0.5)
    # diff = (r - c) * 0.5 = [1.0, -0.5]
    np.testing.assert_allclose(np.asarray(r2["w"]), [1.0, 0.5])
    np.testing.assert_allclose(np.asarray(c2["w"]), [1.0, 0.5])


def test_elastic_pulls_replicas_to_consensus():
    rng = np.random.default_rng(0)
    replicas = [{"w": jnp.asarray(rng.standard_normal(8).astype(np.float32))}
                for _ in range(4)]
    center = {"w": jnp.zeros(8, jnp.float32)}
    for _ in range(50):
        for i in range(4):
            replicas[i], center = elastic_update(replicas[i], center, 0.3)
    spread = np.ptp(np.stack([np.asarray(r["w"]) for r in replicas]), axis=0)
    assert spread.max() < 0.05


def test_randomsync_exchanges_masked_entries():
    replica = {"w": jnp.arange(1000, dtype=jnp.float32)}
    center = {"w": jnp.zeros(1000, jnp.float32)}
    snapshot = {"w": jnp.zeros(1000, jnp.float32)}
    r2, c2, s2 = randomsync_update(replica, center, snapshot, 0.3,
                                   jax.random.PRNGKey(0))
    moved = np.asarray(c2["w"]) != 0
    frac = moved[1:].mean()   # index 0 has value 0 either way
    assert 0.2 < frac < 0.4
    # center absorbed replica deltas at the mask
    np.testing.assert_allclose(np.asarray(c2["w"])[moved],
                               np.arange(1000)[moved])
    # replica and snapshot adopted the center's values at the mask
    np.testing.assert_allclose(np.asarray(r2["w"])[moved],
                               np.asarray(c2["w"])[moved])
    np.testing.assert_allclose(np.asarray(s2["w"])[moved],
                               np.asarray(c2["w"])[moved])
    # unmasked entries untouched
    np.testing.assert_allclose(np.asarray(r2["w"])[~moved],
                               np.arange(1000)[~moved])


def test_sync_sample_ratio_formula():
    # throughput = 100MB/s (MB = 1024*1024, the reference's units)
    # / 4 bytes * 1 server = 26,214,400 floats/s;
    # demand = 1e6 floats * 50 workers / 1s = 5e7 -> ratio 0.524288
    assert sync_sample_ratio(100, 1, 50, 1_000_000, 1.0) == pytest.approx(
        100 * 1024 * 1024 / 4 / 5e7)
    assert sync_sample_ratio(1e9, 1, 1, 1000, 1.0) == 1.0
    assert sync_sample_ratio(100, 1, 1, 0, 1.0) == 1.0


def test_controller_cadence_matches_reference():
    cfg = UpdaterConfig(type="kSGD", base_learning_rate=0.1,
                        param_type="Elastic", moving_rate=0.9,
                        sync_frequency=8, warmup_steps=60)
    ctl = ElasticController(cfg, ngroups=3)
    assert ctl.alpha == pytest.approx(0.3)
    fires = [s for s in range(100) if ctl.sync_now(s)]
    assert fires == [60, 68, 76, 84, 92]


def test_controller_end_to_end_two_slices():
    """Two simulated slices training the same quadratic stay closer with
    elastic averaging than without."""
    cfg = UpdaterConfig(type="kSGD", base_learning_rate=0.1,
                        param_type="Elastic", moving_rate=0.6,
                        sync_frequency=2, warmup_steps=0)
    target = jnp.asarray(np.linspace(-1, 1, 8).astype(np.float32))

    def train(with_sync):
        ctl = ElasticController(cfg, ngroups=2)
        rng = np.random.default_rng(0)
        slices = [{"w": jnp.asarray(rng.standard_normal(8)
                                    .astype(np.float32))} for _ in range(2)]
        ctl.init(slices[0])
        for step in range(30):
            for i, p in enumerate(slices):
                g = 2 * (p["w"] - target) + jnp.asarray(
                    rng.normal(0, 0.1, 8).astype(np.float32))
                p = {"w": p["w"] - 0.05 * g}
                slices[i] = ctl.maybe_sync(step, p) if with_sync else p
        return slices

    synced = train(True)
    unsynced = train(False)
    d_synced = float(jnp.max(jnp.abs(synced[0]["w"] - synced[1]["w"])))
    d_unsynced = float(jnp.max(jnp.abs(unsynced[0]["w"] - unsynced[1]["w"])))
    assert d_synced < d_unsynced
    # and both still converge toward the target
    assert float(jnp.mean(jnp.abs(synced[0]["w"] - target))) < 0.2


# ---------------------------------------------------------------------------
# runtime integration (VERDICT r1 item 4): the knobs in a config drive
# training behavior through Trainer.run and the multi-replica ReplicaSet


def _mlp_cfg(moving_rate=0.0, sync_frequency=4, warmup=2, steps=12,
             param_type="Elastic"):
    from singa_tpu.config.schema import model_config_from_dict
    layers = [
        {"name": "data", "type": "kShardData",
         "data_param": {"batchsize": 32}},
        {"name": "mnist", "type": "kMnistImage", "srclayers": "data",
         "mnist_param": {"norm_a": 255.0}},
        {"name": "label", "type": "kLabel", "srclayers": "data"},
        {"name": "fc1", "type": "kInnerProduct", "srclayers": "mnist",
         "inner_product_param": {"num_output": 32},
         "param": [{"name": "weight", "init_method": "kUniformSqrtFanIn"},
                   {"name": "bias"}]},
        {"name": "relu", "type": "kReLU", "srclayers": "fc1"},
        {"name": "fc2", "type": "kInnerProduct", "srclayers": "relu",
         "inner_product_param": {"num_output": 10},
         "param": [{"name": "weight", "init_method": "kUniformSqrtFanIn"},
                   {"name": "bias"}]},
        {"name": "loss", "type": "kSoftmaxLoss",
         "srclayers": ["fc2", "label"]},
    ]
    return model_config_from_dict({
        "name": "tiny-mlp", "train_steps": steps,
        "updater": {"type": "kSGD", "base_learning_rate": 0.1,
                    "momentum": 0.9,
                    "learning_rate_change_method": "kFixed",
                    "sync_frequency": sync_frequency,
                    "warmup_steps": warmup,
                    "moving_rate": moving_rate,
                    "param_type": param_type},
        "neuralnet": {"layer": layers}})


def _run_trainer(cfg, seed=0, scan_chunk=0):
    from singa_tpu.core.trainer import Trainer
    from singa_tpu.data.synthetic import synthetic_image_batches

    tr = Trainer(cfg, {"data": {"pixel": (28, 28), "label": ()}},
                 log_fn=lambda s: None, donate=False)
    params, opt = tr.init(seed=seed)
    it = synthetic_image_batches(32, seed=11, stream_seed=50)
    params, opt, _ = tr.run(params, opt, it, seed=seed,
                            scan_chunk=scan_chunk)
    return tr, params


def test_conf_knobs_drive_elastic_in_trainer_run():
    """moving_rate/sync_frequency in the updater block change training:
    the controller engages, holds a center, and the resulting params
    differ from a plain-SGD run with identical data and seeds."""
    cfg_plain = _mlp_cfg(moving_rate=0.0)
    cfg_el = _mlp_cfg(moving_rate=0.9)
    tr_p, p_plain = _run_trainer(cfg_plain)
    tr_e, p_el = _run_trainer(cfg_el)
    assert tr_p.elastic is None
    assert tr_e.elastic is not None and tr_e.elastic.center is not None
    diffs = [float(np.max(np.abs(np.asarray(p_el[k]) -
                                 np.asarray(p_plain[k])))) for k in p_el]
    assert max(diffs) > 1e-6, "elastic knobs had no effect"


def test_elastic_scan_chunks_cut_at_sync_steps():
    """The fused-scan path must produce the same params as per-step
    dispatch when syncs fire mid-run (chunks cut at sync boundaries)."""
    cfg = _mlp_cfg(moving_rate=0.9, sync_frequency=3, warmup=2, steps=10)
    _, p1 = _run_trainer(cfg, scan_chunk=0)
    _, p8 = _run_trainer(cfg, scan_chunk=8)
    for k in p1:
        np.testing.assert_allclose(np.asarray(p1[k]), np.asarray(p8[k]),
                                   rtol=1e-5, atol=1e-6, err_msg=k)


@pytest.mark.parametrize("param_type", ["Elastic", "RandomSync"])
def test_two_replica_groups_converge(param_type):
    """2-replica ReplicaSet (EASGD / RandomSync) on distinct data
    streams: both replicas' losses fall and the center tracks them —
    the async consistency tier trains, not just averages."""
    from singa_tpu.core.trainer import Trainer
    from singa_tpu.data.synthetic import synthetic_image_batches
    from singa_tpu.parallel.elastic import ReplicaSet, async_active

    cfg = _mlp_cfg(moving_rate=0.9, sync_frequency=2, warmup=2, steps=0,
                   param_type=param_type)
    if param_type == "RandomSync":
        # full-sample RandomSync overwrites params wholesale at each
        # exchange, which invalidates SGD momentum history (measured:
        # diverges at momentum 0.9, converges 2.3 -> 0.03 without) —
        # the reference pairs RandomSync with AdaGrad-style updaters
        cfg.updater.momentum = 0.0
    assert async_active(cfg.updater)
    tr = Trainer(cfg, {"data": {"pixel": (28, 28), "label": ()}},
                 log_fn=lambda s: None, donate=False)
    rs = ReplicaSet(tr, ngroups=2, seed=0)
    iters = [synthetic_image_batches(32, seed=11, stream_seed=60 + g)
             for g in range(2)]
    center, hist = rs.run(iters, steps=40, seed=0)

    # plain single-replica SGD baseline, same budget per replica
    cfg_p = _mlp_cfg(moving_rate=0.0, steps=0)
    tr_p = Trainer(cfg_p, {"data": {"pixel": (28, 28), "label": ()}},
                   log_fn=lambda s: None, donate=False)
    pp, po = tr_p.init(seed=0)
    it = synthetic_image_batches(32, seed=11, stream_seed=60)
    losses_p = []
    for s in range(40):
        pp, po, m = tr_p.train_step(pp, po, next(it), s,
                                    jax.random.PRNGKey(s))
        losses_p.append(float(m["loss"]))

    for g in range(2):
        first = np.mean([h["loss"] for h in hist[g][:5]])
        last = np.mean([h["loss"] for h in hist[g][-5:]])
        assert last < first * 0.5, (param_type, g, first, last)
    # replica quality in the same ballpark as plain SGD
    last_async = np.mean([h["loss"] for h in hist[0][-5:]])
    last_plain = np.mean(losses_p[-5:])
    assert last_async < max(2.0 * last_plain, last_plain + 0.5)
    # center is a consensus: close to the replicas it averages
    for g in range(2):
        d = [float(np.mean(np.abs(np.asarray(rs.replicas[g]["params"][k])
                                  - np.asarray(center[k]))))
             for k in center]
        assert max(d) < 0.5


# ---------------------------------------------------------------------------
# VERDICT r2 item 3: the async tier over REAL transport — two localhost
# processes under jax.distributed, one replica each, center exchange as
# a global-array collective program (DistributedReplicaSet).


@pytest.mark.parametrize("param_type,moving_rate,nprocs",
                         [("Elastic", 0.9, 2), ("RandomSync", 0.0, 2),
                          ("Elastic", 0.9, 3),
                          ("RandomSync", 0.0, 3)])
def test_distributed_replica_set_multiprocess_e2e(tmp_path, param_type,
                                                 moving_rate, nprocs):
    """Every replica's losses decrease AND the distributed center
    matches the single-process ReplicaSet trajectory on the same
    seeds (trajectory-exact sequential exchange).  The 3-process case
    exercises the G>2 sequential center chain."""
    import json
    import socket
    import subprocess
    import sys
    import textwrap

    from singa_tpu.core.trainer import Trainer
    from singa_tpu.data.synthetic import synthetic_image_batches
    from singa_tpu.parallel.elastic import ReplicaSet

    steps = 12

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    hostfile = tmp_path / "hostfile"
    hostfile.write_text(f"127.0.0.1:{port}\n"
                        + "127.0.0.1\n" * (nprocs - 1))

    child = tmp_path / "child.py"
    child.write_text(textwrap.dedent(f"""
        import json, sys
        import numpy as np
        from singa_tpu.parallel.bootstrap import distributed_init

        pid = int(sys.argv[1])
        assert distributed_init(procs_id=pid, hostfile=sys.argv[2])
        import jax
        from singa_tpu.core.trainer import Trainer
        from singa_tpu.config.schema import model_config_from_dict
        from singa_tpu.data.synthetic import synthetic_image_batches
        from singa_tpu.parallel.elastic import DistributedReplicaSet

        sys.path.insert(0, {str(os.path.dirname(os.path.abspath(__file__)))!r})
        from test_elastic import _mlp_cfg

        cfg = _mlp_cfg(moving_rate={moving_rate}, sync_frequency=4,
                       warmup=2, steps={steps},
                       param_type={param_type!r})
        tr = Trainer(cfg, {{"data": {{"pixel": (28, 28), "label": ()}}}},
                     log_fn=lambda s: None, donate=False)
        drs = DistributedReplicaSet(tr, seed=0)
        it = synthetic_image_batches(32, seed=11, stream_seed=60 + pid)
        center, hist = drs.run(it, steps={steps}, seed=0)
        np.savez(sys.argv[3] + f"/center_{{pid}}.npz",
                 **{{k: np.asarray(v) for k, v in center.items()}})
        print("HIST" + str(pid) + json.dumps(
            [h["loss"] for h in hist]), flush=True)
    """))

    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2",
               PYTHONPATH=os.path.dirname(os.path.dirname(
                   os.path.abspath(__file__))))
    for var in ("JAX_NUM_PROCESSES", "JAX_PROCESS_ID",
                "JAX_COORDINATOR_ADDRESS"):
        env.pop(var, None)
    procs = [subprocess.Popen(
        [sys.executable, str(child), str(i), str(hostfile),
         str(tmp_path)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for i in range(nprocs)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc{i} failed:\n{out}"

    hists = {}
    for i, out in enumerate(outs):
        for line in out.splitlines():
            if line.startswith(f"HIST{i}"):
                hists[i] = json.loads(line[len(f"HIST{i}"):])
    assert set(hists) == set(range(nprocs)), outs

    # every replica learns
    for g in range(nprocs):
        assert np.mean(hists[g][-3:]) < np.mean(hists[g][:3]), hists[g]

    # single-process simulation on the same seeds
    cfg = _mlp_cfg(moving_rate=moving_rate, sync_frequency=4, warmup=2,
                   steps=steps, param_type=param_type)
    tr = Trainer(cfg, {"data": {"pixel": (28, 28), "label": ()}},
                 log_fn=lambda s: None, donate=False)
    rs = ReplicaSet(tr, ngroups=nprocs, seed=0)
    iters = [synthetic_image_batches(32, seed=11, stream_seed=60 + g)
             for g in range(nprocs)]
    center_sim, hist_sim = rs.run(iters, steps=steps, seed=0)

    # per-replica loss trajectories match the simulation
    for g in range(nprocs):
        np.testing.assert_allclose(
            hists[g], [h["loss"] for h in hist_sim[g]],
            rtol=2e-4, atol=2e-5)

    # the centers match across processes and vs the simulation
    centers = [np.load(tmp_path / f"center_{g}.npz")
               for g in range(nprocs)]
    for k in center_sim:
        for c in centers[1:]:
            np.testing.assert_allclose(centers[0][k], c[k],
                                       rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(
            centers[0][k], np.asarray(center_sim[k]), rtol=1e-4,
            atol=1e-5)


def test_configure_sync_sets_sample_ratio_deterministically():
    """Runtime SyncConfig (param_manager.cc:85-93): crafted numbers give
    an exact ratio, and a zero bandwidth (the TPU default pipe — ICI
    collectives, not a modelled PS link) leaves sampling at 1.0."""
    cfg = UpdaterConfig(type="kSGD", base_learning_rate=0.1,
                        param_type="RandomSync", sync_frequency=1,
                        warmup_steps=2)
    ctl = ElasticController(cfg, ngroups=1, bandwidth_mb_s=0.3)
    # throughput = 0.3 MB/s (MB = 1024*1024) / 4 B = 78,643.2 floats/s;
    # demand = 250e3 floats / 1s
    ctl.configure_sync(1.0, 250_000, 1)
    assert ctl.sample_ratio == pytest.approx(0.3 * 1024 * 1024 / 1e6)
    off = ElasticController(cfg, ngroups=1, bandwidth_mb_s=0.0)
    off.configure_sync(1.0, 250_000, 1)
    assert off.sample_ratio == 1.0


def test_configured_bandwidth_makes_the_exchange_sample():
    """With a configured ratio < 1 the RandomSync exchange provably
    SAMPLES: roughly that fraction of entries move, the rest stay."""
    cfg = UpdaterConfig(type="kSGD", base_learning_rate=0.1,
                        param_type="RandomSync", sync_frequency=1,
                        warmup_steps=0)
    ctl = ElasticController(cfg, ngroups=2, bandwidth_mb_s=0.3)
    ctl.configure_sync(1.0, 250_000, 1)     # -> ratio 0.3
    base = {"w": jnp.zeros(20_000, jnp.float32)}
    ctl.init(base)
    replica = {"w": jnp.ones(20_000, jnp.float32)}
    # zero delta vs snapshot: the replica simply ADOPTS center values
    # at the sampled mask, so the changed fraction IS the sample ratio
    ctl.snapshot = {"w": jnp.ones(20_000, jnp.float32)}
    out = ctl.maybe_sync(0, replica, rng=jax.random.PRNGKey(3))
    changed = float((np.asarray(out["w"]) != 1.0).mean())
    assert 0.25 < changed < 0.35, changed


def test_replica_set_run_invokes_syncconfig_after_warmup():
    """ReplicaSet.run must measure warmup step time and call SyncConfig
    on every controller (worker.cc:42-48): a vanishing bandwidth yields
    a near-zero sample ratio; the default (bandwidth off) stays 1.0."""
    from singa_tpu.core.trainer import Trainer
    from singa_tpu.data.synthetic import synthetic_image_batches
    from singa_tpu.parallel.elastic import ReplicaSet

    cfg = _mlp_cfg(moving_rate=0.0, sync_frequency=2, warmup=3, steps=0,
                   param_type="RandomSync")
    cfg.updater.momentum = 0.0
    tr = Trainer(cfg, {"data": {"pixel": (28, 28), "label": ()}},
                 log_fn=lambda s: None, donate=False)
    rs = ReplicaSet(tr, ngroups=2, seed=0, bandwidth_mb_s=1e-9)
    iters = [synthetic_image_batches(32, seed=11, stream_seed=70 + g)
             for g in range(2)]
    rs.run(iters, steps=6, seed=0)
    assert all(c.sample_ratio < 0.01 for c in rs.controllers), \
        [c.sample_ratio for c in rs.controllers]

    rs_off = ReplicaSet(tr, ngroups=2, seed=0)
    iters = [synthetic_image_batches(32, seed=11, stream_seed=80 + g)
             for g in range(2)]
    rs_off.run(iters, steps=6, seed=0)
    assert all(c.sample_ratio == 1.0 for c in rs_off.controllers)
