"""Elastic/RandomSync cross-slice tier tests (reference algorithm parity:
param.cc:102-256, param_manager.cc:85-93, worker.cc:44-55)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from singa_tpu.config.schema import UpdaterConfig
from singa_tpu.parallel.elastic import (ElasticController, elastic_update,
                                        randomsync_update, sync_sample_ratio)


def test_elastic_update_reference_formula():
    replica = {"w": jnp.array([2.0, 0.0])}
    center = {"w": jnp.array([0.0, 1.0])}
    r2, c2 = elastic_update(replica, center, alpha=0.5)
    # diff = (r - c) * 0.5 = [1.0, -0.5]
    np.testing.assert_allclose(np.asarray(r2["w"]), [1.0, 0.5])
    np.testing.assert_allclose(np.asarray(c2["w"]), [1.0, 0.5])


def test_elastic_pulls_replicas_to_consensus():
    rng = np.random.default_rng(0)
    replicas = [{"w": jnp.asarray(rng.standard_normal(8).astype(np.float32))}
                for _ in range(4)]
    center = {"w": jnp.zeros(8, jnp.float32)}
    for _ in range(50):
        for i in range(4):
            replicas[i], center = elastic_update(replicas[i], center, 0.3)
    spread = np.ptp(np.stack([np.asarray(r["w"]) for r in replicas]), axis=0)
    assert spread.max() < 0.05


def test_randomsync_exchanges_masked_entries():
    replica = {"w": jnp.arange(1000, dtype=jnp.float32)}
    center = {"w": jnp.zeros(1000, jnp.float32)}
    snapshot = {"w": jnp.zeros(1000, jnp.float32)}
    r2, c2, s2 = randomsync_update(replica, center, snapshot, 0.3,
                                   jax.random.PRNGKey(0))
    moved = np.asarray(c2["w"]) != 0
    frac = moved[1:].mean()   # index 0 has value 0 either way
    assert 0.2 < frac < 0.4
    # center absorbed replica deltas at the mask
    np.testing.assert_allclose(np.asarray(c2["w"])[moved],
                               np.arange(1000)[moved])
    # replica and snapshot adopted the center's values at the mask
    np.testing.assert_allclose(np.asarray(r2["w"])[moved],
                               np.asarray(c2["w"])[moved])
    np.testing.assert_allclose(np.asarray(s2["w"])[moved],
                               np.asarray(c2["w"])[moved])
    # unmasked entries untouched
    np.testing.assert_allclose(np.asarray(r2["w"])[~moved],
                               np.arange(1000)[~moved])


def test_sync_sample_ratio_formula():
    # throughput = 100MB/s /4 *1 server = 25e6 floats/s;
    # demand = 1e6 floats * 50 workers / 1s = 5e7 -> ratio 0.5
    assert sync_sample_ratio(100, 1, 50, 1_000_000, 1.0) == pytest.approx(0.5)
    assert sync_sample_ratio(1e9, 1, 1, 1000, 1.0) == 1.0
    assert sync_sample_ratio(100, 1, 1, 0, 1.0) == 1.0


def test_controller_cadence_matches_reference():
    cfg = UpdaterConfig(type="kSGD", base_learning_rate=0.1,
                        param_type="Elastic", moving_rate=0.9,
                        sync_frequency=8, warmup_steps=60)
    ctl = ElasticController(cfg, ngroups=3)
    assert ctl.alpha == pytest.approx(0.3)
    fires = [s for s in range(100) if ctl.sync_now(s)]
    assert fires == [60, 68, 76, 84, 92]


def test_controller_end_to_end_two_slices():
    """Two simulated slices training the same quadratic stay closer with
    elastic averaging than without."""
    cfg = UpdaterConfig(type="kSGD", base_learning_rate=0.1,
                        param_type="Elastic", moving_rate=0.6,
                        sync_frequency=2, warmup_steps=0)
    target = jnp.asarray(np.linspace(-1, 1, 8).astype(np.float32))

    def train(with_sync):
        ctl = ElasticController(cfg, ngroups=2)
        rng = np.random.default_rng(0)
        slices = [{"w": jnp.asarray(rng.standard_normal(8)
                                    .astype(np.float32))} for _ in range(2)]
        ctl.init(slices[0])
        for step in range(30):
            for i, p in enumerate(slices):
                g = 2 * (p["w"] - target) + jnp.asarray(
                    rng.normal(0, 0.1, 8).astype(np.float32))
                p = {"w": p["w"] - 0.05 * g}
                slices[i] = ctl.maybe_sync(step, p) if with_sync else p
        return slices

    synced = train(True)
    unsynced = train(False)
    d_synced = float(jnp.max(jnp.abs(synced[0]["w"] - synced[1]["w"])))
    d_unsynced = float(jnp.max(jnp.abs(unsynced[0]["w"] - unsynced[1]["w"])))
    assert d_synced < d_unsynced
    # and both still converge toward the target
    assert float(jnp.mean(jnp.abs(synced[0]["w"] - target))) < 0.2
