"""SLO-driven autoscaler + open-loop traffic harness
(singa_tpu/serve/autoscale.py + traffic.py) and the elastic-membership
paths they lean on (Router.add_engine/remove_engine, canary abort).

Correctness anchors:
  * drain semantics — a draining member stops admitting under the same
    lock that admits, in-flight work finishes before retirement, and a
    deliberately retired engine leaves its strike record behind;
  * removing the CANARY mid-rollout ABORTS the canary (back to
    OBSERVE, checkpoint unjudged, re-canaries on a survivor) — it
    never counts as a rollback and never condemns the fingerprint;
  * the control law scales up on any pressure signal, scales down only
    after a consecutive-quiet-tick streak, and a faulted `scale.decide`
    tick takes NO membership action;
  * the traffic generator is open-loop: arrivals never wait on
    completions.

Cost control: everything here runs on stub handles and fabricated
signals — no compiled programs; the one real-fleet traffic run lives
in `bench.py --traffic-smoke`."""

import tempfile
import threading
import time

import numpy as np
import pytest

from singa_tpu.serve import (Overloaded, RolloutController, RolloutSpec,
                             Router, RouterSpec)
from singa_tpu.serve.autoscale import AutoScaler, AutoScaleSpec
from singa_tpu.serve.router import RouterStats
from singa_tpu.serve.stats import ServeStats
from singa_tpu.serve.traffic import (Phase, TrafficGen, diurnal,
                                     flash_crowd, ramp, steady)
from singa_tpu.utils.checkpoint import CheckpointManager
from singa_tpu.utils.faults import FaultSchedule, inject

pytestmark = pytest.mark.traffic


class StubHandle:
    """Scriptable engine-handle double (the test_fleet.py mold): no
    threads, no compiled programs."""

    def __init__(self, name, step=1, queue_depth=0):
        self.name = name
        self.step = step
        self.queue_depth = queue_depth
        self.fail_probe = False
        self.occupancy = None
        self.served = 0
        self.reloads = []

    def probe(self):
        if self.fail_probe:
            from singa_tpu.serve import EngineUnavailable
            raise EngineUnavailable(f"{self.name} is down")
        return {"ok": True, "status": "ok", "step": self.step,
                "queue_depth": self.queue_depth}

    def stats_snapshot(self):
        snap = {"completed": self.served, "failed": 0, "expired": 0,
                "p95_latency_ms": None}
        if self.occupancy is not None:
            snap["cb_slot_occupancy"] = self.occupancy
        return snap

    def request(self, mode, tokens, timeout=None):
        self.served += 1
        return {"tokens": [1, 2], "step": self.step}

    def reload(self, step=None):
        self.reloads.append(step)
        if step is not None and step != self.step:
            self.step = step
            return {"outcome": "reloaded", "step": step}
        return {"outcome": "unchanged", "step": self.step}


def _router(n=2, **spec_kw):
    spec_kw.setdefault("quarantine_after", 2)
    spec_kw.setdefault("readmit_base_s", 0.01)
    spec_kw.setdefault("readmit_cap_s", 0.02)
    stubs = [StubHandle(f"e{i}") for i in range(n)]
    r = Router(stubs, spec=RouterSpec(**spec_kw), log_fn=lambda s: None)
    r.probe_all()
    return r, stubs


class StubFleet:
    """Fleet double over a real Router: `grow`/`retire` go through the
    real membership paths, so the AutoScaler under test exercises the
    same add/drain semantics as a local fleet."""

    def __init__(self, n=1):
        self.router, self.stubs = _router(n)
        self.rollout = None
        self.grow_error = None
        self._next = n

    def grow(self):
        if self.grow_error is not None:
            raise RuntimeError(self.grow_error)
        h = StubHandle(f"e{self._next}")
        self._next += 1
        self.stubs.append(h)
        self.router.add_engine(h)
        return h.name

    def retire(self, name, drain=True, timeout_s=30.0):
        return self.router.remove_engine(name, drain=drain,
                                         timeout_s=timeout_s)


def _scaler(n=1, **spec_kw):
    spec_kw.setdefault("cooldown_s", 0.0)
    spec_kw.setdefault("window_s", 5.0)
    spec_kw.setdefault("tick_s", 0.01)
    spec_kw.setdefault("quiet_ticks", 2)
    spec_kw.setdefault("max_engines", 3)
    fleet = StubFleet(n)
    sc = AutoScaler(fleet, spec=AutoScaleSpec(**spec_kw),
                    log_fn=lambda s: None)
    return sc, fleet


def _join_action(sc, timeout=5.0):
    t = sc._action_thread
    if t is not None:
        t.join(timeout)
    deadline = time.monotonic() + timeout
    while sc._busy and time.monotonic() < deadline:
        time.sleep(0.002)
    assert not sc._busy


# -- spec grammar ------------------------------------------------------------

def test_autoscale_spec_parse_grammar():
    s = AutoScaleSpec.parse("slo_p95_ms=150,max_engines=8;"
                            "cooldown_s=1.5,quiet_ticks=5")
    assert s.slo_p95_ms == 150.0 and s.max_engines == 8
    assert s.cooldown_s == 1.5 and s.quiet_ticks == 5
    assert AutoScaleSpec.parse(None) == AutoScaleSpec()
    assert AutoScaleSpec.parse("") == AutoScaleSpec()
    with pytest.raises(ValueError, match="unknown key"):
        AutoScaleSpec.parse("bogus=1")
    with pytest.raises(ValueError):
        AutoScaleSpec.parse("min_engines=0")
    with pytest.raises(ValueError):
        AutoScaleSpec.parse("min_engines=3,max_engines=2")
    with pytest.raises(ValueError):
        AutoScaleSpec.parse("down_margin=1")


# -- windowed stats (satellite: recent-rate views) ---------------------------

def test_router_stats_windowed_rates():
    rs = RouterStats(window_s=5.0)
    for _ in range(8):
        rs.count("routed")
    rs.count("shed", 2)
    for ms in (10, 20, 30, 40):
        rs.observe_latency(ms / 1000.0)
    w = rs.windowed(5.0)
    assert w["routed"] == 8 and w["shed"] == 2 and w["completed"] == 4
    assert w["shed_rate"] == pytest.approx(2 / 8, abs=1e-3)
    assert w["p50_latency_ms"] == pytest.approx(30.0, abs=0.01)
    assert w["p95_latency_ms"] == pytest.approx(40.0, abs=0.01)
    assert w["qps"] > 0
    snap = rs.snapshot()
    assert snap["shed_rate_recent"] == pytest.approx(2 / 8, abs=1e-3)
    assert snap["p95_latency_recent_ms"] == pytest.approx(40.0,
                                                          abs=0.01)


def test_router_stats_window_excludes_old_samples():
    rs = RouterStats(window_s=1.0)
    now = time.monotonic()
    rs._t0 = now - 100.0          # fake uptime so the cap won't bite
    rs._routed_t.append((now - 50.0, "default"))            # ancient
    rs._done_t.append((now - 50.0, 9.9, "interactive", "default"))
    rs.count("routed")
    rs.observe_latency(0.005)
    w = rs.windowed(1.0)
    assert w["routed"] == 1 and w["completed"] == 1
    assert w["p95_latency_ms"] == pytest.approx(5.0, abs=0.01)


def test_serve_stats_windowed_rates():
    ss = ServeStats()
    ss.count("shed")
    ss.observe_latency(0.02)
    ss.observe_latency(0.04)
    w = ss.windowed(10.0)
    assert w["shed"] == 1 and w["completed"] == 2
    assert w["shed_rate"] == pytest.approx(1 / 3, abs=1e-3)
    assert w["p95_latency_ms"] == pytest.approx(40.0, abs=0.01)
    snap = ss.snapshot()
    assert snap["shed_rate_recent"] == pytest.approx(1 / 3, abs=1e-3)
    assert snap["p95_latency_recent_ms"] == pytest.approx(40.0,
                                                          abs=0.01)


# -- elastic membership: add_engine / remove_engine --------------------------

def test_add_engine_joins_and_serves():
    r, stubs = _router(1)
    r.add_engine(StubHandle("e9", queue_depth=0))
    assert sorted(r.names()) == ["e0", "e9"]
    assert r.stats.joins == 1
    stubs[0].queue_depth = 9
    r.probe_all()
    out = r.route("generate", [1, 2])
    assert out["engine"] == "e9"          # new member eats traffic
    with pytest.raises(ValueError, match="duplicate engine name"):
        r.add_engine(StubHandle("e9"))


def test_remove_engine_drains_in_flight_before_retiring():
    r, stubs = _router(2)
    name = r._pick(set())                 # hold one in-flight slot
    assert name is not None
    done = {}

    def retire():
        done["drained"] = r.remove_engine(name, drain=True,
                                          timeout_s=5.0)

    t = threading.Thread(target=retire)
    t.start()
    deadline = time.monotonic() + 2.0
    while time.monotonic() < deadline:   # admissions stop immediately
        m = {m["name"]: m for m in r.members()}
        if name in m and m[name]["draining"]:
            break
        time.sleep(0.002)
    assert r._pick(set()) != name        # draining excluded from _pick
    assert name in r.names()             # but not yet retired
    r._release(name)                     # in-flight work completes
    t.join(5.0)
    assert done["drained"] is True
    assert name not in r.names()
    assert r.stats.retires == 1


def test_remove_engine_drain_timeout_still_retires():
    r, stubs = _router(2)
    name = r._pick(set())                # never released
    drained = r.remove_engine(name, drain=True, timeout_s=0.05)
    assert drained is False              # timed out...
    assert name not in r.names()         # ...but retirement completes


def test_retire_forgets_strikes():
    r, stubs = _router(2, quarantine_after=1)
    stubs[0].fail_probe = True
    r.probe_all()
    assert {m["name"]: m for m in r.members()}["e0"]["quarantined"]
    assert r.remove_engine("e0", drain=True, timeout_s=1.0)
    # deliberate retirement: the strike record leaves with the member
    stubs[0].fail_probe = False
    r.add_engine(stubs[0])
    m = {m["name"]: m for m in r.members()}["e0"]
    assert m["strikes"] == 0 and not m["quarantined"] and m["healthy"]


# -- canary removed mid-rollout: abort, not rollback -------------------------

def _controller(ws, n=3, **ro_kw):
    ro_kw.setdefault("window_s", 0.01)
    r, stubs = _router(n, quarantine_after=1)
    ctrl = RolloutController(r, ws, spec=RolloutSpec(**ro_kw),
                             log_fn=lambda s: None)
    ctrl.pinned_step = 1
    ctrl._fp = ctrl.mgr.fingerprint()
    return ctrl, r, stubs


def test_canary_removed_mid_canary_aborts_without_rollback():
    params = {"w": np.ones((2,), np.float32)}
    with tempfile.TemporaryDirectory() as ws:
        mgr = CheckpointManager(ws, log_fn=lambda s: None)
        mgr.save(1, params, {"t": np.zeros(())},
                 health={"verdict": "ok"})
        ctrl, r, stubs = _controller(ws)
        mgr.save(2, params, {"t": np.zeros(())},
                 health={"verdict": "ok"})
        ctrl.tick()
        assert ctrl.state == "CANARY"
        victim = ctrl.canary
        assert r.remove_engine(victim, drain=True, timeout_s=1.0)
        ctrl.tick()
        # abort: back to OBSERVE, no rollback counted, checkpoint NOT
        # condemned
        assert ctrl.state == "OBSERVE"
        assert ctrl.canary_aborts == 1 and ctrl.rollbacks == 0
        assert ctrl._rejected_fp is None
        assert ctrl.pinned_step == 1
        # the unjudged step re-canaries on a survivor
        ctrl.tick()
        assert ctrl.state == "CANARY" and ctrl.canaries == 2
        assert ctrl.canary != victim and ctrl.canary in r.names()


def test_non_canary_removal_leaves_rollout_untouched():
    params = {"w": np.ones((2,), np.float32)}
    with tempfile.TemporaryDirectory() as ws:
        mgr = CheckpointManager(ws, log_fn=lambda s: None)
        mgr.save(1, params, {"t": np.zeros(())},
                 health={"verdict": "ok"})
        ctrl, r, stubs = _controller(ws)
        mgr.save(2, params, {"t": np.zeros(())},
                 health={"verdict": "ok"})
        ctrl.tick()
        assert ctrl.state == "CANARY"
        bystander = next(n for n in r.names() if n != ctrl.canary)
        assert r.remove_engine(bystander, drain=True, timeout_s=1.0)
        time.sleep(0.02)                  # window_s elapsed
        ctrl.tick()
        assert ctrl.promotions == 1 and ctrl.pinned_step == 2
        assert ctrl.canary_aborts == 0 and ctrl.rollbacks == 0


# -- control law on fabricated signals ---------------------------------------

def _sig(**kw):
    base = {"n": 1, "healthy": 1, "queue_depth": 0, "shed_rate": 0.0,
            "qps": 0.0, "p95_ms": None, "occupancy": None,
            "lag_steps": 0}
    base.update(kw)
    return base


def test_decide_up_on_each_pressure_signal():
    sc, _ = _scaler(1)
    assert sc.decide(_sig(shed_rate=0.5))["dir"] == "up"
    assert sc.decide(_sig(p95_ms=10_000.0))["dir"] == "up"
    assert sc.decide(_sig(queue_depth=99))["dir"] == "up"
    assert sc.decide(_sig(occupancy=0.99))["dir"] == "up"
    # pressure at max_engines holds instead
    assert sc.decide(_sig(n=3, shed_rate=0.5))["dir"] == "hold"


def test_decide_down_needs_consecutive_quiet_streak():
    sc, _ = _scaler(2, quiet_ticks=3, min_engines=1)
    quiet = _sig(n=2)
    assert sc.decide(quiet)["dir"] == "hold"     # streak 1
    assert sc.decide(quiet)["dir"] == "hold"     # streak 2
    assert sc.decide(_sig(n=2, shed_rate=0.5))["dir"] == "up"  # reset
    assert sc.decide(quiet)["dir"] == "hold"     # streak restarts
    assert sc.decide(quiet)["dir"] == "hold"
    assert sc.decide(quiet)["dir"] == "down"     # streak 3
    # quiet at min_engines never goes below the floor
    sc2, _ = _scaler(1, quiet_ticks=1, min_engines=1)
    assert sc2.decide(_sig(n=1))["dir"] == "hold"
    # pipeline lag is NOT quiet: a busy fleet is not a shrinkable one
    sc3, _ = _scaler(2, quiet_ticks=1)
    assert sc3.decide(_sig(n=2, lag_steps=3))["dir"] == "hold"


def test_tick_scales_up_on_shed_pressure():
    sc, fleet = _scaler(1)
    fleet.router.stats.count("routed", 10)
    fleet.router.stats.count("shed", 5)
    assert sc.tick() == "up"
    assert sc.scale_ups == 1
    assert len(fleet.router.names()) == 2
    # the joined member is live in dispatch
    assert sorted(fleet.router.healthy_names()) == ["e0", "e1"]


def test_tick_cooldown_vetoes_backtoback_actions():
    sc, fleet = _scaler(1, cooldown_s=30.0)
    fleet.router.stats.count("routed", 10)
    fleet.router.stats.count("shed", 5)
    assert sc.tick() == "up"
    fleet.router.stats.count("shed", 5)          # still under pressure
    assert sc.tick() == "hold"                   # cooldown veto
    assert sc.holds == 1 and len(fleet.router.names()) == 2
    assert "cooldown" in sc.last_why


def test_tick_scales_down_after_quiet_and_drains():
    sc, fleet = _scaler(2, quiet_ticks=2, min_engines=1)
    assert sc.tick() == "hold"                   # quiet streak 1
    assert sc.tick() == "down"                   # streak 2: retire one
    _join_action(sc)
    assert sc.scale_downs == 1 and sc.drained_clean == 1
    assert len(fleet.router.names()) == 1
    # at the floor now: quiet ticks keep holding
    assert sc.tick() == "hold"
    assert len(fleet.router.names()) == 1


def test_scale_down_never_picks_the_canary():
    sc, fleet = _scaler(2, quiet_ticks=1, min_engines=1)

    class _Rollout:
        canary = "e0"
    fleet.rollout = _Rollout()
    assert sc.tick() == "down"
    _join_action(sc)
    assert fleet.router.names() == ["e0"]        # bystander retired


def test_grow_failure_aborts_without_membership_change():
    sc, fleet = _scaler(1)
    fleet.grow_error = "no spawn config"
    fleet.router.stats.count("routed", 10)
    fleet.router.stats.count("shed", 5)
    assert sc.tick() == "abort"
    assert sc.grow_failures == 1 and sc.aborts == 1
    assert len(fleet.router.names()) == 1


def test_scale_decide_fault_skips_decision():
    sc, fleet = _scaler(2, quiet_ticks=1, min_engines=1)
    with inject(FaultSchedule.parse("scale.decide@0:error")):
        assert sc.tick() == "abort"              # faulted: no action
    assert sc.decide_faults == 1 and sc.aborts == 1
    assert len(fleet.router.names()) == 2        # nothing retired
    assert sc.scale_downs == 0 and sc.scale_ups == 0
    assert sc.tick() == "down"                   # next tick recovers
    _join_action(sc)
    assert len(fleet.router.names()) == 1


def test_autoscaler_snapshot_and_metrics():
    from singa_tpu.obs.metrics import MetricsRegistry
    sc, fleet = _scaler(1)
    sc.tick()
    snap = sc.snapshot()
    assert snap["ticks"] == 1 and snap["engines"] == 1
    reg = MetricsRegistry()
    sc.register_into(reg)
    text = reg.render_prometheus()
    assert "singa_autoscale_ticks_total" in text
    assert "singa_autoscale_engines" in text


# -- open-loop traffic harness -----------------------------------------------

def test_phase_validation_and_builders():
    with pytest.raises(ValueError):
        Phase(name="bad", duration_s=0, rate_rps=1.0)
    with pytest.raises(ValueError):
        Phase(name="bad", duration_s=1.0, rate_rps=-1.0)
    p = ramp("r", 2.0, 1.0, 5.0)
    assert p.rate_at(0.0) == pytest.approx(1.0)
    assert p.rate_at(1.0) == pytest.approx(5.0)
    fc = flash_crowd("f", 1.0, 2.0, k=5.0)
    assert fc.rate_rps == pytest.approx(10.0)
    day = diurnal(base_rps=1.0, peak_rps=4.0, rise_s=1.0,
                  plateau_s=1.0, fall_s=1.0)
    assert [p.name for p in day] == ["diurnal-rise", "diurnal-plateau",
                                     "diurnal-fall"]
    assert day[1].rate_rps == pytest.approx(4.0)


def test_traffic_is_open_loop_arrivals_do_not_wait():
    def slow_request(tokens):
        time.sleep(0.3)                  # far slower than the gap

    gen = TrafficGen(slow_request, seed=7, log_fn=lambda s: None)
    rep = gen.run([steady("burst", duration_s=0.4, rate_rps=40.0)],
                  drain_timeout_s=5.0)
    tot = rep["totals"]
    # closed-loop would manage ~1 arrival in 0.4s; open-loop offers
    # ~16 (Poisson) regardless of completion latency
    assert tot["offered"] >= 6
    assert tot["completed"] == tot["offered"]
    assert tot["failed"] == 0 and tot["dropped_harness"] == 0


def test_traffic_accounts_shed_and_failures():
    calls = {"n": 0}

    def flaky(tokens):
        calls["n"] += 1
        if calls["n"] % 3 == 1:
            raise Overloaded("full", retry_after=0.01)
        if calls["n"] % 3 == 2:
            raise ValueError("boom")

    gen = TrafficGen(flaky, seed=3, log_fn=lambda s: None)
    rep = gen.run([steady("p", duration_s=0.3, rate_rps=30.0)],
                  drain_timeout_s=5.0)
    tot = rep["totals"]
    assert tot["offered"] == (tot["completed"] + tot["shed"]
                              + tot["failed"])
    assert tot["shed"] >= 1 and tot["failed"] >= 1
    assert tot["shed_rate"] > 0
    assert any("ValueError" in e for e in tot["errors"])
    row = rep["phases"][0]
    for key in ("offered", "completed", "shed", "failed",
                "dropped_harness", "qps_offered", "p95_ms"):
        assert key in row


def test_traffic_max_outstanding_counts_drops():
    release = threading.Event()

    def stuck(tokens):
        release.wait(5.0)

    gen = TrafficGen(stuck, seed=1, max_outstanding=2,
                     log_fn=lambda s: None)
    try:
        rep = gen.run([steady("p", duration_s=0.3, rate_rps=50.0)],
                      drain_timeout_s=0.1)
    finally:
        release.set()
    tot = rep["totals"]
    assert tot["dropped_harness"] >= 1   # counted, never silent
    # only spawned arrivals count as offered; the cap held
    assert tot["offered"] <= 2


def test_traffic_streams_with_slow_reader():
    events = {"n": 0}

    def req(tokens):
        pass

    def stream(tokens, max_new=4):
        for i in range(int(max_new)):
            events["n"] += 1
            yield {"token": i}
        yield {"done": True}

    gen = TrafficGen(req, stream_fn=stream, seed=5,
                     log_fn=lambda s: None)
    rep = gen.run([steady("s", duration_s=0.25, rate_rps=20.0,
                          stream_p=1.0, slow_reader_s=0.001,
                          max_new=(3,))],
                  drain_timeout_s=5.0)
    tot = rep["totals"]
    assert tot["completed"] == tot["offered"] and tot["failed"] == 0
    assert events["n"] == 3 * tot["completed"]
