"""Inference serving tier (singa_tpu/serve/): bucket selection and
padding, deadline expiry + shedding, hot-reload atomicity under
`serve.reload` faults, unhealthy-checkpoint reload refusal.

Correctness anchor: a request served through a padded bucket must
decode the EXACT tokens `generate()` produces unpadded — left-padding
plus the per-key kmask preserves every RoPE-relative (query, key)
distance, so the serving tier adds batching without changing the
model's output.

Cost control: compiled-program tests share one module-scoped engine
over the tiny 2-layer test LM; reload/refusal tests verify params
values and steps directly (no compiled programs needed)."""

import tempfile
import threading
import time

import jax
import numpy as np
import pytest

from singa_tpu.core.net import build_net
from singa_tpu.models.generate import generate
from singa_tpu.models.transformer import transformer_lm
from singa_tpu.serve import (DeadlineExpired, InferenceEngine,
                             InferenceServer, MicroBatcher, Overloaded,
                             ServeSpec, ServeStats)
from singa_tpu.utils.checkpoint import CheckpointManager
from singa_tpu.utils.faults import FaultError, FaultSchedule, inject

pytestmark = pytest.mark.serve

VOCAB, SEQ = 64, 16
SHAPES = {"data": {"input": (SEQ,), "target": (SEQ,)}}


def _net_and_params(seed=0):
    cfg = transformer_lm(vocab_size=VOCAB, num_layers=2, embed_dim=32,
                         num_heads=4, head_dim=8, seq_len=SEQ,
                         batchsize=2)
    net = build_net(cfg, "kTest", SHAPES)
    return net, net.init_params(jax.random.PRNGKey(seed))


# -- ServeSpec ---------------------------------------------------------------

def test_spec_parse_grammar():
    spec = ServeSpec.parse("buckets=1x8/4x16,max_new_tokens=4,"
                           "eos_id=2;temperature=0.5,queue_capacity=9")
    assert spec.buckets == ((1, 8), (4, 16))
    assert spec.max_new_tokens == 4 and spec.eos_id == 2
    assert spec.temperature == 0.5 and spec.queue_capacity == 9
    assert ServeSpec.parse("eos_id=none").eos_id is None
    with pytest.raises(ValueError, match="unknown key"):
        ServeSpec.parse("bogus=1")
    with pytest.raises(ValueError):
        ServeSpec.parse("max_new_tokens=0")


def test_spec_bucket_selection_smallest_admissible():
    spec = ServeSpec(buckets=((1, 8), (4, 8), (2, 16), (8, 32)))
    # smallest batch that fits, shortest prompt padding
    assert spec.bucket_for(1, 5) == (1, 8)
    assert spec.bucket_for(3, 8) == (4, 8)
    assert spec.bucket_for(2, 9) == (2, 16)
    # overflow: no bucket holds 6 at plen<=8 -> widest admissible
    assert spec.bucket_for(6, 8) == (8, 32)
    assert spec.bucket_for(9, 30) == (8, 32)
    with pytest.raises(ValueError, match="exceeds every bucket"):
        spec.bucket_for(1, 33)


# -- shared compiled engine (expensive: built once) --------------------------

@pytest.fixture(scope="module")
def served():
    net, params = _net_and_params()
    spec = ServeSpec(buckets=((2, 6), (4, 12)), max_new_tokens=5,
                     batch_window_s=0.01, request_timeout_s=20.0)
    engine = InferenceEngine(net, spec, params=params,
                             log_fn=lambda s: None)
    server = InferenceServer(engine, http=False, log_fn=lambda s: None)
    server.start()
    yield net, params, engine, server
    server.stop()


def test_padded_bucket_matches_unpadded_generate(served):
    net, params, engine, server = served
    rng = np.random.default_rng(0)
    for plen in (1, 4, 9, 12):
        prompt = rng.integers(1, VOCAB, plen).astype(np.int32)
        ref = np.asarray(generate(net, params, prompt[None], 5))[0]
        out = server.generate(prompt)
        assert out["tokens"] == ref.tolist(), \
            f"plen={plen}: padded {out['tokens']} != {ref.tolist()}"


def test_concurrent_mixed_lengths_zero_recompiles(served):
    net, params, engine, server = served
    warm = engine.stats.compiles
    rng = np.random.default_rng(1)
    prompts = [rng.integers(1, VOCAB, rng.integers(1, 13)).astype(
        np.int32) for _ in range(16)]
    errs, outs = [], []

    def client(p):
        try:
            outs.append(server.generate(p))
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=client, args=(p,))
               for p in prompts]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs and len(outs) == 16
    assert engine.stats.compiles == warm, "recompiled after warmup"
    occ = engine.stats.occupancy()
    assert occ is not None and 0 < occ <= 1.0


def test_predict_mode_logprobs(served):
    net, params, engine, server = served
    out = server.predict(np.array([3, 1, 4], np.int32))
    lp = np.asarray(out["logprobs"])
    assert lp.shape == (VOCAB,)
    assert abs(float(np.exp(lp).sum()) - 1.0) < 1e-4


def test_http_frontend_roundtrip(served):
    import json
    import urllib.request

    net, params, engine, _ = served
    srv = InferenceServer(engine, port=0, log_fn=lambda s: None)
    srv.start()
    try:
        host, port = srv.address
        req = urllib.request.Request(
            f"http://{host}:{port}/generate",
            data=json.dumps({"tokens": [5, 9, 3]}).encode())
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())
        assert len(out["tokens"]) == 5
        with urllib.request.urlopen(
                f"http://{host}:{port}/stats", timeout=10) as r:
            snap = json.loads(r.read())
        assert snap["completed"] >= 1 and "p50_latency_ms" in snap
    finally:
        srv.stop()


# -- admission control / deadlines (no compiled programs needed) -------------

class _StallEngine:
    """Engine stand-in whose run_batch blocks on an event — lets the
    queue fill / deadlines pass deterministically."""

    def __init__(self, spec):
        self.spec = spec
        self.stats = ServeStats()
        self.params = {"w": np.zeros(1)}
        self.params_step = 0
        self.release = threading.Event()
        self.calls = []

    def run_batch(self, mode, tokens, plens, params=None):
        self.calls.append((mode, tokens.shape, tuple(plens.tolist())))
        self.release.wait(20.0)
        if mode == "predict":
            return np.zeros((tokens.shape[0], VOCAB), np.float32)
        return np.zeros((tokens.shape[0], self.spec.max_new_tokens),
                        np.int32)


def test_queue_full_sheds_with_backoff_hint():
    spec = ServeSpec(buckets=((1, 8),), queue_capacity=2,
                     batch_window_s=0.01)
    eng = _StallEngine(spec)
    mb = MicroBatcher(eng, log_fn=lambda s: None)
    mb.start()
    try:
        first = mb.submit([1, 2])
        for _ in range(200):          # wait until it's IN FLIGHT (off
            if eng.calls:             # the queue, stalled in run_batch)
                break
            time.sleep(0.01)
        assert eng.calls, "dispatch loop never picked up the request"
        tickets = [first] + [mb.submit([1, 2]) for _ in range(2)]
        delays = []
        for _ in range(3):
            with pytest.raises(Overloaded) as ei:
                mb.submit([1, 2])
            delays.append(ei.value.retry_after)
        assert eng.stats.shed == 3
        # consecutive sheds escalate the Backoff hint
        assert delays[0] < delays[-1]
        eng.release.set()
        for t in tickets:
            t.wait(20.0)
        assert eng.stats.completed == 3
    finally:
        eng.release.set()
        mb.stop()


def test_admit_fault_sheds_request():
    spec = ServeSpec(buckets=((1, 8),))
    eng = _StallEngine(spec)
    eng.release.set()
    mb = MicroBatcher(eng, log_fn=lambda s: None)
    mb.start()
    try:
        with inject(FaultSchedule.parse("serve.admit@0:error")):
            with pytest.raises(Overloaded, match="admission fault"):
                mb.submit([1, 2])
        assert eng.stats.shed == 1 and eng.stats.submitted == 0
        mb.submit([1, 2]).wait(20.0)   # next request admitted fine
    finally:
        mb.stop()


def test_deadline_expires_in_queue():
    spec = ServeSpec(buckets=((1, 8),), batch_window_s=0.0)
    eng = _StallEngine(spec)
    mb = MicroBatcher(eng, log_fn=lambda s: None)
    mb.start()
    try:
        blocker = mb.submit([1, 2], timeout=30.0)   # occupies dispatch
        time.sleep(0.05)
        doomed = mb.submit([3, 4], timeout=0.05)    # expires queued
        time.sleep(0.2)
        eng.release.set()
        blocker.wait(20.0)
        with pytest.raises(DeadlineExpired):
            doomed.wait(20.0)
        assert eng.stats.expired == 1
    finally:
        eng.release.set()
        mb.stop()


def test_batch_fault_fails_batch_but_server_stays_up():
    spec = ServeSpec(buckets=((1, 8),))
    eng = _StallEngine(spec)
    eng.release.set()
    mb = MicroBatcher(eng, log_fn=lambda s: None)
    mb.start()
    try:
        with inject(FaultSchedule.parse("serve.batch@0:error")):
            t1 = mb.submit([1, 2])
            with pytest.raises(FaultError):
                t1.wait(20.0)
            assert eng.stats.failed == 1
            # the dispatch loop survives: the next batch serves
            mb.submit([1, 2]).wait(20.0)
        assert eng.stats.completed == 1
    finally:
        mb.stop()


def test_unservable_prompt_rejected():
    spec = ServeSpec(buckets=((2, 8),))
    eng = _StallEngine(spec)
    mb = MicroBatcher(eng, log_fn=lambda s: None)
    with pytest.raises(ValueError, match="exceeds the largest bucket"):
        mb.submit(np.arange(9))
    with pytest.raises(ValueError, match="empty"):
        mb.submit([])


# -- hot reload (real CheckpointManager, no compiled programs) ---------------

def _save(mgr, step, params, verdict="ok"):
    mgr.save(step, params, {"t": np.zeros(())},
             health={"verdict": verdict})


def test_engine_loads_latest_healthy_checkpoint():
    net, params = _net_and_params()
    p2 = jax.tree_util.tree_map(lambda a: a * 2.0, params)
    with tempfile.TemporaryDirectory() as ws:
        mgr = CheckpointManager(ws, max_to_keep=10,
                                log_fn=lambda s: None)
        _save(mgr, 1, params)
        _save(mgr, 2, p2)
        _save(mgr, 3, params, verdict="diverged")   # latest is bad
        eng = InferenceEngine(net, ServeSpec(), workspace=ws,
                              log_fn=lambda s: None)
        assert eng.load() == 2     # walked back past the unhealthy one
        k = next(iter(eng.params))
        np.testing.assert_array_equal(np.asarray(eng.params[k]),
                                      np.asarray(p2[k]))


def test_reload_swaps_refuses_and_degrades():
    net, params = _net_and_params()
    p2 = jax.tree_util.tree_map(lambda a: a * 1.5, params)
    p3 = jax.tree_util.tree_map(lambda a: a + 1.0, params)
    with tempfile.TemporaryDirectory() as ws:
        mgr = CheckpointManager(ws, max_to_keep=10,
                                log_fn=lambda s: None)
        _save(mgr, 1, params)
        eng = InferenceEngine(net, ServeSpec(), workspace=ws,
                              log_fn=lambda s: None)
        assert eng.load() == 1
        assert eng.poll_reload() == "unchanged"

        # new healthy snapshot -> swap
        _save(mgr, 2, p2)
        assert eng.poll_reload() == "reloaded"
        assert eng.params_step == 2 and eng.stats.reloads == 1

        # new UNHEALTHY snapshot -> refused, old params keep serving,
        # and the refusal is not re-attempted every poll
        _save(mgr, 3, p3, verdict="nonfinite")
        assert eng.poll_reload() == "refused"
        assert eng.params_step == 2
        assert eng.stats.reloads_refused == 1
        assert eng.poll_reload() == "unchanged"

        # injected reload fault -> degrade (counted), params unmoved...
        _save(mgr, 4, p3)
        with inject(FaultSchedule.parse("serve.reload@0:error")):
            assert eng.poll_reload() == "failed"
        assert eng.params_step == 2
        assert eng.stats.reload_failures == 1
        # ...and the very next clean poll retries and lands
        assert eng.poll_reload() == "reloaded"
        assert eng.params_step == 4
        k = next(iter(eng.params))
        np.testing.assert_array_equal(np.asarray(eng.params[k]),
                                      np.asarray(p3[k]))


def test_reload_atomicity_inflight_batch_keeps_old_params():
    """The dispatcher reads engine.params once per batch: a swap that
    lands mid-batch must not change what that batch computes with."""
    net, params = _net_and_params()
    p2 = jax.tree_util.tree_map(lambda a: a * 3.0, params)
    with tempfile.TemporaryDirectory() as ws:
        mgr = CheckpointManager(ws, max_to_keep=10,
                                log_fn=lambda s: None)
        _save(mgr, 1, params)
        eng = InferenceEngine(net, ServeSpec(), workspace=ws,
                              log_fn=lambda s: None)
        eng.load()
        captured = eng.params          # the batch's one read
        k = next(iter(captured))
        before = np.asarray(captured[k]).copy()
        _save(mgr, 2, p2)
        assert eng.poll_reload() == "reloaded"      # swap mid-flight
        # the captured tree is untouched; only the live pointer moved
        np.testing.assert_array_equal(np.asarray(captured[k]), before)
        np.testing.assert_array_equal(np.asarray(eng.params[k]),
                                      np.asarray(p2[k]))


def test_reload_rejects_mismatched_geometry():
    """A checkpoint whose params disagree in shape with the serving
    model must degrade (old params keep serving), not swap garbage in
    front of compiled programs."""
    net, params = _net_and_params()
    bad = dict(params)
    k = next(iter(bad))
    bad[k] = np.zeros(np.asarray(bad[k]).shape + (2,), np.float32)
    with tempfile.TemporaryDirectory() as ws:
        mgr = CheckpointManager(ws, max_to_keep=10,
                                log_fn=lambda s: None)
        _save(mgr, 1, params)
        eng = InferenceEngine(net, ServeSpec(), workspace=ws,
                              log_fn=lambda s: None)
        eng.load()
        _save(mgr, 2, bad)
        assert eng.poll_reload() == "failed"
        assert eng.params_step == 1
        assert eng.stats.reload_failures == 1


def test_stats_snapshot_fields():
    st = ServeStats()
    st.count("submitted", 3)
    st.observe_batch(3, 4)
    for ms in (1.0, 2.0, 100.0):
        st.observe_latency(ms / 1e3)
    snap = st.snapshot()
    assert snap["completed"] == 3
    assert snap["batch_occupancy"] == 0.75
    assert snap["p50_latency_ms"] == 2.0
    assert snap["p95_latency_ms"] == 100.0
    assert snap["qps"] > 0
