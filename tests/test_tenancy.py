"""Multi-tenant QoS isolation (singa_tpu/serve/tenancy.py plus the
admission paths that enforce it): tenant registry grammar and label
folding, retry-budget floors under cross-tenant drain, per-(tenant,
class) Retry-After streaks, quota enforcement at the continuous
scheduler, model-aware routing with honest fast 404s, bounded
`singa_tenant_*` label cardinality under a tenant-id fuzzer, the
autoscaler's quota-weighted shed signal, traffic-harness tenant
mixes, and the flight recorder's per-tenant shed-storm trigger.

Correctness anchors:
  * one tenant's retry storm can drain the SHARED budget bucket but
    never another tenant's guaranteed floor;
  * a hostile tenant-id fuzzer cannot grow /metrics: unconfigured ids
    fold into `other` and nothing is dropped on fold (the accounting
    identity: per-tenant sums equal the totals);
  * an unserved model family is an honest fast 404 (UnknownModel) —
    never a strike, never a shed charged to capacity.

Cost control: everything below runs on stub handles or pure
datastructures except ONE module-scoped cb engine (the test_cb.py
mold) used to pin tenant queue-quota shedding at the real scheduler."""

import os
import time

import jax
import numpy as np
import pytest

from singa_tpu.core.net import build_net
from singa_tpu.models.transformer import transformer_lm
from singa_tpu.obs.flightrec import FlightRecorder
from singa_tpu.obs.metrics import MetricsRegistry, parse_prometheus
from singa_tpu.serve import (EngineUnavailable, InferenceEngine,
                             InferenceServer, Overloaded, Router,
                             RouterSpec, ServeSpec, TenantBudget,
                             TenantRegistry, TenantSpec, UnknownModel)
from singa_tpu.serve import qos
from singa_tpu.serve.autoscale import AutoScaler, AutoScaleSpec
from singa_tpu.serve.qos import ClassBackoffs, RetryBudget
from singa_tpu.serve.tenancy import TenantCounts
from singa_tpu.serve.traffic import Phase, TrafficGen, steady

pytestmark = pytest.mark.tenancy

VOCAB, SEQ = 64, 16
SHAPES = {"data": {"input": (SEQ,), "target": (SEQ,)}}


def _net_and_params(seed=0):
    cfg = transformer_lm(vocab_size=VOCAB, num_layers=2, embed_dim=32,
                         num_heads=4, head_dim=8, seq_len=SEQ,
                         batchsize=2)
    net = build_net(cfg, "kTest", SHAPES)
    return net, net.init_params(jax.random.PRNGKey(seed))


# -- spec grammar and label folding ------------------------------------------

def test_tenant_registry_parse_grammar():
    reg = TenantRegistry.parse(
        "a,queue_frac=0.25,budget_floor=4;b,queue_frac=0.5")
    assert reg.names() == ("a", "b", "default", "other")
    assert reg.spec_for("a").queue_frac == 0.25
    assert reg.spec_for("a").budget_floor == 4.0
    assert reg.spec_for("b").queue_frac == 0.5
    # default/other exist unconfigured: no floor, no quota
    assert reg.spec_for("default").budget_floor == 0.0
    assert reg.spec_for(None).queue_frac == 1.0
    assert TenantRegistry.parse(None).names() == ("default", "other")
    with pytest.raises(ValueError, match="bad tenant spec entry"):
        TenantRegistry.parse("a,bogus=1")
    with pytest.raises(ValueError, match="bad tenant spec value"):
        TenantRegistry.parse("a,queue_frac=wide")
    with pytest.raises(ValueError, match="bad tenant name"):
        TenantRegistry.parse("Team A,queue_frac=0.5")
    with pytest.raises(ValueError, match="queue_frac"):
        TenantRegistry.parse("a,queue_frac=0")
    with pytest.raises(ValueError, match="duplicate"):
        TenantRegistry(
            [TenantSpec(name="a"), TenantSpec(name="a")])


def test_check_tenant_degrades_never_rejects():
    # missing/blank -> the legacy default tenant; garbage is
    # sanitized, not 400'd — tenancy is isolation, not auth
    assert qos.check_tenant(None) == "default"
    assert qos.check_tenant("   ") == "default"
    assert qos.check_tenant("  Team-A!! ") == "team-a__"
    assert qos.check_tenant("a" * 200) == "a" * 64
    assert qos.check_tenant("ünïcode") == "_n_code"


def test_label_folding_bounds_unconfigured_ids():
    reg = TenantRegistry.parse("a,queue_frac=0.5")
    assert reg.label("a") == "a"
    assert reg.label(None) == "default"
    assert reg.label("никто") == "other"
    assert reg.label("fuzz-9000") == "other"
    # `other` may be configured explicitly to clamp what the
    # unconfigured collectively get
    clamped = TenantRegistry.parse("other,queue_frac=0.125")
    assert clamped.spec_for("fuzz-9000").queue_frac == 0.125


def test_quota_arithmetic_floors_at_one():
    reg = TenantRegistry.parse(
        "a,queue_frac=0.25,slot_frac=0.5,kv_frac=0.01")
    assert reg.queue_quota("a", 8) == 2
    assert reg.slot_quota("a", 2) == 1
    # a quota can never starve a tenant of its last unit
    assert reg.kv_quota("a", 10) == 1
    assert reg.queue_quota("default", 8) == 8
    assert reg.share("a") == 0.25 and reg.share("default") == 1.0


def test_brownout_fracs_inherit_and_override():
    reg = TenantRegistry.parse("a,brownout_batch_frac=0.125")
    # 0.0 = inherit the engine's fraction; > 0 = tenant override
    assert reg.brownout_fracs("a", 0.5, 0.75) == (0.5, 0.125)
    assert reg.brownout_fracs("default", 0.5, 0.75) == (0.5, 0.75)


# -- retry-budget floors -----------------------------------------------------

def test_budget_floor_survives_other_tenants_drain():
    shared = RetryBudget(ratio=0.1, burst=8.0)
    reg = TenantRegistry.parse("a,budget_floor=4;b,budget_floor=3")
    with pytest.raises(RuntimeError, match="bind_budgets"):
        reg.budget("a")
    reg.bind_budgets(shared)
    ba, bb = reg.budget("a"), reg.budget("b")
    # tenant A drains its own floor AND the whole shared bucket dry
    drained = 0
    while ba.spend() and drained < 10_000:
        drained += 1
    assert drained == 12                   # 4 floor + 8 shared burst
    assert not ba.spend()
    # B's guaranteed floor is untouched by A's storm
    for _ in range(3):
        assert bb.spend()
    assert not bb.spend()                  # floor dry, shared dry
    # an unconfigured tenant has no floor: pure shared behavior
    assert not reg.budget("fuzz").spend()


def test_budget_earn_tops_floor_then_overflows_shared():
    shared = RetryBudget(ratio=0.5, burst=4.0)
    b = TenantBudget(shared, floor=2.0)
    while shared.spend():                  # shared dry, floor full
        pass
    assert b.tokens() == 2.0
    assert b.spend() and b.spend() and not b.spend()
    b.earn(2)                              # 2 * ratio = 1.0 -> floor
    assert b.tokens() == pytest.approx(1.0)
    assert shared.tokens() == pytest.approx(0.0)
    b.earn(4)                              # 1.0 tops the floor, then
    assert b.tokens() == pytest.approx(2.0)  # 1.0 overflows shared
    assert shared.tokens() == pytest.approx(1.0)


def test_budget_refund_refills_floor_first():
    shared = RetryBudget(ratio=0.5, burst=4.0)
    b = TenantBudget(shared, floor=2.0)
    assert b.spend(2.0) and b.tokens() == 0.0
    before = shared.tokens()
    b.refund(3.0)                          # 2 to the floor, 1 shared
    assert b.tokens() == pytest.approx(2.0)
    assert shared.tokens() == pytest.approx(min(before + 1.0, 4.0))


# -- per-(tenant, class) Retry-After streaks ---------------------------------

def test_streaks_scoped_per_tenant_and_class():
    cb = ClassBackoffs(base=0.05, cap=2.0, seed=0)
    # the pre-tenancy regression: ANY successful dispatch used to
    # reset the escalation streak for everyone, so a busy tenant's
    # completions masked another tenant's congestion
    delays = [cb.shed_delay("interactive", tenant="a")
              for _ in range(4)]
    assert cb.streak("interactive", tenant="a") == 4
    # strictly escalating: base*2^k dominates the +-25% jitter
    assert delays[2] > delays[0] and delays[3] > delays[1]
    # another tenant's success, and this tenant's OTHER class, leave
    # the streak alone
    cb.reset("interactive", tenant="b")
    cb.reset("batch", tenant="a")
    assert cb.streak("interactive", tenant="a") == 4
    # only (a, interactive)'s own admission ends its streak
    cb.reset("interactive", tenant="a")
    assert cb.streak("interactive", tenant="a") == 0
    d0 = cb.shed_delay("interactive", tenant="a")
    assert d0 < delays[3]


def test_streak_tenant_cap_folds_to_other():
    cb = ClassBackoffs(base=0.05, cap=2.0, max_tenants=2)
    cb.shed_delay("interactive", tenant="a")     # 2nd tenant (default
    cb.shed_delay("interactive", tenant="t-3")   # preseeded) -> other
    cb.shed_delay("interactive", tenant="t-4")   # -> other too
    assert cb.streak("interactive", tenant="t-9000") == 2


# -- bounded label cardinality (the tenant-id fuzzer) ------------------------

def test_tenant_counts_fuzz_bounded_and_nothing_dropped():
    tc = TenantCounts(("shed",), max_tenants=64)
    for i in range(10_000):
        tc.count("shed", f"fuzz-{i}")
    labels = tc.tenants()
    assert len(labels) <= 64
    assert "other" in labels
    # the accounting identity: folding drops NOTHING — every count
    # lands under some label, overflow under `other`
    assert tc.totals()["shed"] == 10_000
    assert sum(tc.get("shed", t) for t in labels) == 10_000
    assert tc.get("shed", "other") >= 10_000 - 64
    with pytest.raises(ValueError, match="unknown tenant counter"):
        tc.count("bogus", "a")


def test_tenant_metrics_series_bounded_and_parse_roundtrip():
    tc = TenantCounts(("routed", "shed"), max_tenants=64)
    reg = MetricsRegistry()
    tc.register_into(reg)
    for i in range(10_000):
        tc.count("shed", f"fuzz-{i}")
        tc.observe_latency(0.01, f"fuzz-{i}")
    text = reg.render_prometheus()
    parsed = parse_prometheus(text)        # raises on a garbled line
    shed = {k: v for k, v in parsed.items()
            if k.startswith("singa_tenant_shed_total")}
    # bounded series: at most max_tenants labels ever hit /metrics
    assert 0 < len(shed) <= 64
    assert len([k for k in parsed
                if k.startswith("singa_tenant_")]) <= 64 * 3
    # /metrics agrees with the counters: the fuzz total survives the
    # render -> parse roundtrip intact
    assert sum(shed.values()) == 10_000
    assert parsed['singa_tenant_shed_total{tenant="other"}'] \
        >= 10_000 - 64


# -- model-aware routing (stub handles, the test_fleet.py mold) --------------

class StubHandle:
    def __init__(self, name, family="default", step=1):
        self.name = name
        self.family = family
        self.step = step
        self.fail_probe = False
        self.overloaded = False
        self.served = 0

    def probe(self):
        if self.fail_probe:
            raise EngineUnavailable(f"{self.name} is down")
        return {"ok": True, "status": "ok", "step": self.step,
                "queue_depth": 0, "family": self.family}

    def stats_snapshot(self):
        return {"completed": self.served, "failed": 0, "expired": 0,
                "p95_latency_ms": None}

    def request(self, mode, tokens, timeout=None):
        if self.overloaded:
            raise Overloaded(f"{self.name} full", retry_after=0.01)
        self.served += 1
        return {"tokens": [1, 2], "step": self.step}

    def reload(self, step=None):
        return {"outcome": "unchanged", "step": self.step}


def _router(stubs, tenancy=None, **spec_kw):
    spec_kw.setdefault("quarantine_after", 2)
    spec_kw.setdefault("readmit_base_s", 0.01)
    spec_kw.setdefault("readmit_cap_s", 0.02)
    r = Router(stubs, spec=RouterSpec(**spec_kw), tenancy=tenancy,
               log_fn=lambda s: None)
    r.probe_all()
    return r


def test_unknown_model_is_fast_404_never_a_strike():
    stubs = [StubHandle("e0"), StubHandle("e1")]
    r = _router(stubs)
    with pytest.raises(UnknownModel, match="llama"):
        r.route("generate", [1, 2], model="llama")
    assert r.stats.unknown_model == 1
    # honest 404, not a failure: nobody was struck, nothing was shed
    assert all(m["strikes"] == 0 for m in r.members())
    assert r.stats.shed == 0 and r.stats.failed == 0
    # UnknownModel is a ValueError for duck-typed callers (HTTP 404
    # branch is checked before the generic 400)
    assert isinstance(UnknownModel("x"), ValueError)


def test_family_scoped_dispatch_and_canary():
    stubs = [StubHandle("e0", family="llama"),
             StubHandle("e1", family="gemma"),
             StubHandle("e2", family="llama")]
    r = _router(stubs)
    assert r.families() == ["gemma", "llama"]
    for _ in range(4):
        out = r.route("generate", [1, 2], model="gemma")
        assert out["engine"] == "e1"
    assert stubs[1].served == 4 and stubs[0].served == 0
    # family name is case/space-normalized like ServeSpec.family
    out = r.route("generate", [1, 2], model="  LLaMA ")
    assert out["engine"] in ("e0", "e2")
    assert r.engine_family("e1") == "gemma"
    assert r.pick_canary(family="gemma") == "e1"


def test_quarantined_family_sheds_honestly_not_404():
    stubs = [StubHandle("e0", family="llama"),
             StubHandle("e1", family="gemma")]
    r = _router(stubs, quarantine_after=1)
    stubs[1].fail_probe = True
    r.probe_all()                          # gemma is struck out...
    assert any(m["quarantined"] for m in r.members())
    # ...but still SERVED: mid-quarantine is overload, not absence —
    # a 404 would tell clients to drop a family that is coming back
    with pytest.raises(Overloaded):
        r.route("generate", [1, 2], model="gemma")
    assert r.stats.unknown_model == 0


def test_router_tenant_accounting_and_sheds():
    reg = TenantRegistry.parse("a,queue_frac=0.25")
    stubs = [StubHandle("e0")]
    r = _router(stubs, tenancy=reg)
    r.route("generate", [1, 2], tenant="a")
    r.route("generate", [1, 2], tenant="fuzz-77")   # folds to other
    stubs[0].overloaded = True
    with pytest.raises(Overloaded) as ei:
        r.route("generate", [1, 2], tenant="a")
    assert ei.value.retry_after > 0
    snap = r.stats.snapshot()["by_tenant"]
    assert snap["a"]["routed"] == 2 and snap["a"]["completed"] == 1
    assert snap["a"]["shed"] == 1
    assert snap["other"]["completed"] == 1
    win = r.stats.windowed(60.0)
    assert win["shed_by_tenant"]["a"] == 1


# -- autoscaler: quota-weighted shed signal ----------------------------------

class _SignalFleet:
    def __init__(self, tenancy=None):
        self.router = _router([StubHandle("e0")], tenancy=tenancy)
        self.rollout = None


def test_autoscale_shed_signal_weighted_by_tenant_share():
    reg = TenantRegistry.parse("a,queue_frac=0.25")
    fleet = _SignalFleet(tenancy=reg)
    sc = AutoScaler(fleet, spec=AutoScaleSpec(window_s=60.0),
                    log_fn=lambda s: None)
    st = fleet.router.stats
    # 2 interactive sheds charged to quota-limited tenant a (share
    # 0.25), 2 to default (share 1.0): a tenant overflowing its OWN
    # entitlement is containment working, not a capacity signal
    for _ in range(2):
        st.observe_shed("interactive", tenant="a")
        st.observe_shed("interactive", tenant="default")
    sig = sc.signals()
    assert sig["tenant_shed_factor"] == pytest.approx(0.625)
    # shed_rate carries the discount: 4 interactive sheds * 0.625,
    # over max(routed, 1) = 1 routed
    assert sig["shed_rate"] == pytest.approx(2.5)


def test_autoscale_shed_signal_legacy_without_tenancy():
    fleet = _SignalFleet()                 # default registry: share 1
    sc = AutoScaler(fleet, spec=AutoScaleSpec(window_s=60.0),
                    log_fn=lambda s: None)
    fleet.router.stats.observe_shed("interactive")
    sig = sc.signals()
    assert sig["tenant_shed_factor"] == 1.0


# -- traffic harness: per-phase tenant mixes ---------------------------------

def test_phase_tenant_mix_validation():
    p = steady("s", 1.0, 2.0, tenants=("a", "b"),
               tenant_weights=(3.0, 1.0))
    assert p.tenants == ("a", "b")
    with pytest.raises(ValueError, match="tenant_weights"):
        Phase(name="x", duration_s=1.0, rate_rps=1.0,
              tenants=("a", "b"), tenant_weights=(1.0,))
    with pytest.raises(ValueError, match="tenants"):
        Phase(name="x", duration_s=1.0, rate_rps=1.0, tenants=())


def test_traffic_attributes_per_tenant_and_omits_default_kwarg():
    seen = []

    def fn(toks, **kw):
        seen.append(kw.get("tenant"))
        return {"tokens": [1]}

    gen = TrafficGen(fn, vocab=8, seed=0, log_fn=lambda s: None)
    rep = gen.run([steady("mix", 1.2, 30.0, prompt_lens=(2,),
                          tenants=("a", "default"),
                          tenant_weights=(1.0, 1.0))],
                  drain_timeout_s=10.0)
    by = rep["phases"][0]["by_tenant"]
    assert set(by) <= {"a", "default"} and "a" in by
    assert by["a"]["completed"] == by["a"]["offered"]
    # legacy clients stay legacy: the default tenant is sent as NO
    # kwarg at all (request_fn signatures from PR 11 keep working)
    assert None in seen and "a" in seen and "default" not in seen
    tot = rep["totals"]["by_tenant"]
    assert sum(r["offered"] for r in tot.values()) == \
        rep["totals"]["offered"]


# -- flight recorder: per-tenant shed storm ----------------------------------

def test_flightrec_tenant_shed_storm_fires_on_diluted_burst(tmp_path):
    fr = FlightRecorder(str(tmp_path), cooldown_s=0.0)
    # a slow background of other-tenant sheds dilutes the global
    # window below its threshold...
    t0 = time.monotonic() - 60.0
    for i in range(4):
        fr._shed_ts.append((t0 + i, "b"))
    # ...while tenant a absorbs a rapid burst: ITS storm, not b's
    paths = [fr.observe("serve.shed", {"tenant": "a"})
             for _ in range(12)]
    assert all(p is None for p in paths[:11])
    assert paths[11] and "tenant_shed_storm" in \
        os.path.basename(paths[11])


def test_flightrec_single_tenant_burst_stays_global_storm(tmp_path):
    # no dilution -> the plain shed_storm fires at 16, exactly the
    # pre-tenancy contract (test_trace.py pins the same behavior)
    fr = FlightRecorder(str(tmp_path), cooldown_s=0.0)
    paths = [fr.observe("serve.shed", {"tenant": "a"})
             for _ in range(16)]
    assert all(p is None for p in paths[:15])
    assert paths[15] and "shed_storm" in os.path.basename(paths[15])
    assert "tenant_shed_storm" not in os.path.basename(paths[15])


# -- tenant queue quota at the real scheduler --------------------------------

@pytest.fixture(scope="module")
def cb_tenant_engine():
    net, params = _net_and_params()
    # the test_cb.py cb_small geometry: one worst-case request holds
    # 33 of 39 pool blocks, so admission wedges deterministically
    spec = ServeSpec(buckets=((2, SEQ),), max_new_tokens=128,
                     temperature=0.0, queue_capacity=4,
                     request_timeout_s=60.0,
                     cb="on", cb_slots=2, cb_block_len=4, cb_blocks=40)
    reg = TenantRegistry.parse("a,queue_frac=0.25;b,queue_frac=0.5")
    engine = InferenceEngine(net, spec, params=params,
                             log_fn=lambda s: None)
    server = InferenceServer(engine, http=False, tenancy=reg,
                             log_fn=lambda s: None)
    server.start()
    yield server, engine
    server.stop()


def test_scheduler_enforces_tenant_queue_quota(cb_tenant_engine):
    server, engine = cb_tenant_engine
    sched = server.scheduler
    # a worst-case hog pins the pool: everything behind it queues
    hog = server.generate_stream(np.array([6, 7, 8], np.int32),
                                 tenant="a")
    next(hog.tokens(timeout=30.0))
    # tenant a's queue quota is max(int(0.25 * 4), 1) = 1: one queued
    # request fits, the second is shed as A'S overflow...
    q1 = server.generate_stream(np.array([2, 2, 2], np.int32),
                                tenant="a")
    with pytest.raises(Overloaded, match="tenant a queue quota"):
        server.generate(np.array([3, 3, 3], np.int32), tenant="a")
    # ...while tenant b still queues into the SAME engine: a's
    # overflow is a's problem, not the fleet's
    q2 = server.generate_stream(np.array([4, 4, 4], np.int32),
                                tenant="b")
    assert sched.stats.tenants.get("shed", "a") >= 1
    assert sched.stats.tenants.get("shed", "b") == 0
    for t in (hog, q1, q2):
        assert len(t.wait(180.0)["tokens"]) == 128
    assert sched.stats.tenants.get("completed", "a") >= 2
    assert sched.stats.tenants.get("completed", "b") >= 1
