"""Continuous batching (serve/kvcache.py, serve/scheduler.py): paged
KV cache bookkeeping, paged-vs-contiguous greedy parity, slot
join/retire under mid-batch EOS, deadline expiry (queued and
mid-stream), block-pool exhaustion -> admission shed, hot-reload
mid-stream, zero recompiles after warmup, and the head-of-line p95
gate against the static bucket path.

Correctness anchor: a request decoded through the paged cache must
produce the EXACT greedy tokens `generate()` produces on a contiguous
cache — token position p of slot s lives at
pool[table[s, p // block_len], :, p % block_len], the gather
reassembles it in absolute-position order, and masked scores underflow
to exact zeros, so paging changes memory layout and nothing else.

Cost control: compiled-program tests share two module-scoped engines
(one cb, one static for the p95 gate) over the tiny 2-layer test LM;
the deadline/exhaustion engine self-calibrates its timeout from a
measured full run instead of guessing CPU step latency."""

import tempfile
import threading
import time

import jax
import numpy as np
import pytest

from singa_tpu.core.net import build_net
from singa_tpu.models.generate import generate
from singa_tpu.models.transformer import transformer_lm
from singa_tpu.serve import (DeadlineExpired, InferenceEngine,
                             InferenceServer, Overloaded,
                             PagedKVCache, ServeSpec)
from singa_tpu.serve.kvcache import NULL_BLOCK
from singa_tpu.utils.checkpoint import CheckpointManager

pytestmark = pytest.mark.serve

VOCAB, SEQ = 64, 16
SHAPES = {"data": {"input": (SEQ,), "target": (SEQ,)}}


def _net_and_params(seed=0):
    cfg = transformer_lm(vocab_size=VOCAB, num_layers=2, embed_dim=32,
                         num_heads=4, head_dim=8, seq_len=SEQ,
                         batchsize=2)
    net = build_net(cfg, "kTest", SHAPES)
    return net, net.init_params(jax.random.PRNGKey(seed))


# -- spec grammar ------------------------------------------------------------

def test_spec_parse_cb_grammar():
    spec = ServeSpec.parse("buckets=4x16,max_new_tokens=8,cb=on,"
                           "cb_slots=4,cb_block_len=4")
    assert spec.cb_on and spec.cb_slots == 4 and spec.cb_block_len == 4
    assert spec.cb_prefill_len == 16          # already a block multiple
    assert spec.cb_blocks_per_slot == 6       # ceil((16 + 8) / 4)
    assert spec.cb_pool_blocks == 25          # 4 * 6 + null block
    assert not ServeSpec.parse("buckets=4x16").cb_on  # default off
    # a prompt cap below the bucket keeps its own prefill geometry
    capped = ServeSpec.parse("buckets=4x16,max_new_tokens=8,cb=on,"
                             "cb_block_len=4,cb_prompt_cap=6")
    assert capped.cb_max_prompt_len == 6
    assert capped.cb_prefill_len == 8         # 6 rounded up to blocks
    with pytest.raises(ValueError):
        ServeSpec.parse("cb=maybe")
    with pytest.raises(ValueError):
        ServeSpec.parse("cb=on,cb_slots=0")
    with pytest.raises(ValueError):
        ServeSpec.parse("cb_block_len=0")


# -- paged cache bookkeeping (no compiled programs) --------------------------

def test_kvcache_alloc_free_refcounts():
    net, _ = _net_and_params()
    kv = PagedKVCache(net, num_slots=2, max_blocks_per_slot=3,
                      num_blocks=7, block_len=4, dtype=np.float32)
    assert kv.usable_blocks == 6 and kv.free_blocks == 6
    assert kv.blocks_for(1) == 1 and kv.blocks_for(4) == 1
    assert kv.blocks_for(5) == 2
    row = kv.alloc(0, 2)
    assert row.shape == (3,) and NULL_BLOCK not in row[:2]
    assert row[2] == NULL_BLOCK               # tail beyond reservation
    assert kv.free_blocks == 4 and kv.blocks_in_use == 2
    row2 = kv.alloc(1, 3)
    assert kv.free_blocks == 1
    assert not kv.can_admit(2) and kv.can_admit(1)
    kv.free(0)
    assert kv.free_blocks == 3
    # freed blocks are reusable; the null block never enters the pool
    row3 = kv.alloc(0, 3)
    assert NULL_BLOCK not in row3
    assert set(map(int, row3)) & set(map(int, row))
    assert not set(map(int, row3)) & set(map(int, row2[:3]))
    kv.free_all()
    assert kv.free_blocks == 6 and kv.blocks_in_use == 0
    with pytest.raises(ValueError):
        PagedKVCache(net, num_slots=1, max_blocks_per_slot=1,
                     num_blocks=1, block_len=4, dtype=np.float32)


# -- shared cb engine (expensive: built once) --------------------------------

@pytest.fixture(scope="module")
def cb_served():
    net, params = _net_and_params()
    spec = ServeSpec(buckets=((2, SEQ),), max_new_tokens=32,
                     temperature=0.0, request_timeout_s=30.0,
                     cb="on", cb_slots=4, cb_block_len=4)
    engine = InferenceEngine(net, spec, params=params,
                             log_fn=lambda s: None)
    server = InferenceServer(engine, http=False, log_fn=lambda s: None)
    server.start()
    yield net, params, engine, server
    server.stop()


def test_paged_matches_contiguous_greedy(cb_served):
    """The acceptance anchor: every prompt length, admitted
    concurrently so they share decode steps, decodes bit-identically
    to the contiguous-cache generate()."""
    net, params, engine, server = cb_served
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, VOCAB, plen).astype(np.int32)
               for plen in (1, 5, 9, SEQ)]
    refs = [np.asarray(generate(net, params, p[None], 32))[0].tolist()
            for p in prompts]
    tickets = [server.generate_stream(p) for p in prompts]
    outs = [t.wait(60.0) for t in tickets]
    for p, ref, out in zip(prompts, refs, outs):
        assert out["tokens"] == ref, \
            f"plen={p.size}: paged {out['tokens']} != {ref}"
        assert out["finish"] == "length"


def test_short_joins_and_finishes_while_long_decodes(cb_served):
    """The continuous-batching point: a short request admitted while
    a long generation is mid-decode completes first — no head-of-line
    blocking."""
    net, params, engine, server = cb_served
    long_t = server.generate_stream(np.array([3, 1, 4], np.int32))
    # wait until the long request is actually decoding
    first = next(long_t.tokens(timeout=30.0))
    assert isinstance(first, int)
    short = server.generate(np.array([7, 7], np.int32), max_new=2)
    assert len(short["tokens"]) == 2 and short["finish"] == "length"
    assert not long_t.done(), \
        "short finished only after the long generation — head-of-line"
    out = long_t.wait(60.0)
    assert len(out["tokens"]) == 32 and out["finish"] == "length"


def test_zero_recompiles_after_warmup_mixed_load(cb_served):
    net, params, engine, server = cb_served
    warm = engine.stats.compiles
    assert warm >= 2                  # one prefill + one decode program
    rng = np.random.default_rng(1)
    prompts = [rng.integers(1, VOCAB, rng.integers(1, SEQ + 1)).astype(
        np.int32) for _ in range(12)]
    errs, outs = [], []

    def client(p, mn):
        try:
            outs.append(server.generate(p, max_new=mn))
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=client,
                                args=(p, int(rng.integers(1, 33))))
               for p in prompts]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs and len(outs) == 12
    assert engine.stats.compiles == warm, "recompiled after warmup"


def test_stats_split_histograms_and_prometheus(cb_served):
    from singa_tpu.obs.metrics import MetricsRegistry

    net, params, engine, server = cb_served
    server.generate(np.array([5, 9], np.int32), max_new=3)
    snap = server.snapshot()
    assert snap["generated_tokens"] > 0
    for k in ("p50_queue_wait_ms", "p95_queue_wait_ms",
              "p50_service_ms", "p95_service_ms", "p50_tokens_per_s"):
        assert snap[k] is not None and snap[k] >= 0, k
    assert 0 < snap["cb_slot_occupancy"] <= 1.0
    assert 0 < snap["cb_block_utilization"] <= 1.0
    assert snap["cb"]["slots"] == 4
    reg = MetricsRegistry()
    engine.stats.register_into(reg)
    text = reg.render_prometheus()
    for name in ("singa_serve_generated_tokens_total",
                 "singa_serve_cb_steps_total",
                 "singa_serve_p95_queue_wait_ms",
                 "singa_serve_p95_service_ms",
                 "singa_serve_cb_slot_occupancy",
                 "singa_serve_cb_block_utilization"):
        assert name in text, name


def test_overlong_prompt_fast_reject_both_paths(cb_served):
    net, params, engine, server = cb_served
    before = engine.stats.rejected
    too_long = np.arange(SEQ + 1, dtype=np.int32) % VOCAB + 1
    with pytest.raises(ValueError, match="not servable"):
        server.scheduler.submit(too_long)
    with pytest.raises(ValueError, match="not servable"):
        server.batcher.submit(too_long, mode="generate")
    with pytest.raises(ValueError, match="empty prompt"):
        server.scheduler.submit(np.zeros((0,), np.int32))
    assert engine.stats.rejected == before + 3


# -- EOS retire + slot reuse -------------------------------------------------

def test_eos_retires_slot_mid_batch_and_slot_is_reused():
    net, params = _net_and_params()
    probe = np.array([3, 1, 4], np.int32)
    ref = np.asarray(generate(net, params, probe[None], 8))[0].tolist()
    eos = ref[3]        # greedy hits this mid-decode -> EOS retire
    expected = ref[:ref.index(eos) + 1]   # first occurrence may be <4
    spec = ServeSpec(buckets=((2, SEQ),), max_new_tokens=8,
                     temperature=0.0, eos_id=eos,
                     request_timeout_s=30.0,
                     cb="on", cb_slots=2, cb_block_len=4)
    engine = InferenceEngine(net, spec, params=params,
                             log_fn=lambda s: None)
    server = InferenceServer(engine, http=False, log_fn=lambda s: None)
    server.start()
    try:
        other = np.array([9, 2, 5, 11], np.int32)
        oref = np.asarray(generate(net, params, other[None], 8,
                                   eos_id=eos))[0].tolist()
        if eos in oref:
            oref = oref[:oref.index(eos) + 1]
        t1 = server.generate_stream(probe)
        t2 = server.generate_stream(other)
        out1, out2 = t1.wait(30.0), t2.wait(30.0)
        assert out1["finish"] == "eos"
        assert out1["tokens"] == expected and out1["tokens"][-1] == eos
        assert out2["tokens"] == oref
        # the freed slot admits the next request (retire released it)
        out3 = server.generate(probe)
        assert out3["tokens"] == expected and out3["finish"] == "eos"
    finally:
        server.stop()


# -- deadlines + pool exhaustion (one small engine, self-calibrated) ---------

@pytest.fixture(scope="module")
def cb_small():
    net, params = _net_and_params()
    # pool of 40 blocks: one worst-case request (36 blocks) fits, two
    # cannot coexist -> exhaustion is reachable with two requests
    spec = ServeSpec(buckets=((2, SEQ),), max_new_tokens=128,
                     temperature=0.0, queue_capacity=2,
                     request_timeout_s=30.0,
                     cb="on", cb_slots=2, cb_block_len=4, cb_blocks=40)
    engine = InferenceEngine(net, spec, params=params,
                             log_fn=lambda s: None)
    server = InferenceServer(engine, http=False, log_fn=lambda s: None)
    server.start()
    # calibrate: one full worst-case generation, wall-clock
    t0 = time.monotonic()
    out = server.generate(np.array([1, 2, 3], np.int32))
    full_s = time.monotonic() - t0
    assert len(out["tokens"]) == 128
    yield net, params, engine, server, full_s
    server.stop()


def test_deadline_mid_stream_retires_with_partial_result(cb_small):
    net, params, engine, server, full_s = cb_small
    # a deadline a third of the measured full run: at least the
    # prefill token lands, the 128-token decode cannot finish
    budget = max(full_s / 3.0, 0.02)
    out = server.generate(np.array([4, 5], np.int32), timeout=budget)
    assert out["finish"] == "deadline"
    assert 1 <= len(out["tokens"]) < 128


def test_deadline_expires_in_queue_when_pool_is_held(cb_small):
    net, params, engine, server, full_s = cb_small
    hog = server.generate_stream(np.array([6, 7, 8], np.int32))
    next(hog.tokens(timeout=30.0))    # hog now holds 33 of 39 blocks
    # worst-case reservation (33 blocks) cannot be admitted while the
    # hog runs; a tiny deadline expires it in the queue
    with pytest.raises(DeadlineExpired):
        server.generate(np.array([9, 9, 9], np.int32), timeout=0.05)
    assert engine.stats.expired >= 1
    out = hog.wait(60.0)              # the hog itself is unharmed
    assert len(out["tokens"]) == 128


def test_pool_exhaustion_sheds_at_admission_no_deadlock(cb_small):
    net, params, engine, server, full_s = cb_small
    before_shed = engine.stats.shed
    hog = server.generate_stream(np.array([1, 1, 1], np.int32))
    next(hog.tokens(timeout=30.0))
    # a small reservation still fits alongside the hog (6 free blocks)
    small = server.generate(np.array([5], np.int32), max_new=2)
    assert len(small["tokens"]) == 2
    # two more worst-case requests fill the pending queue (capacity 2)
    q1 = server.generate_stream(np.array([2, 2, 2], np.int32))
    q2 = server.generate_stream(np.array([3, 3, 3], np.int32))
    # the third is shed with a retry hint -- not queued, not deadlocked
    with pytest.raises(Overloaded) as ei:
        server.generate_stream(np.array([4, 4, 4], np.int32))
    assert ei.value.retry_after > 0
    assert engine.stats.shed == before_shed + 1
    # everything admitted completes: FIFO drain, no deadlock
    for t in (hog, q1, q2):
        assert len(t.wait(120.0)["tokens"]) == 128


# -- hot reload mid-stream ---------------------------------------------------

def test_hot_reload_mid_stream_no_tear():
    net, params = _net_and_params()
    p2 = jax.tree_util.tree_map(lambda a: a * 2.0, params)
    with tempfile.TemporaryDirectory() as ws:
        mgr = CheckpointManager(ws, max_to_keep=10,
                                log_fn=lambda s: None)
        mgr.save(1, params, {"t": np.zeros(())},
                 health={"verdict": "ok"})
        # reload_poll_s far out: the test drives poll_reload itself
        spec = ServeSpec(buckets=((2, SEQ),), max_new_tokens=256,
                         temperature=0.0, request_timeout_s=60.0,
                         reload_poll_s=60.0,
                         cb="on", cb_slots=2, cb_block_len=4)
        engine = InferenceEngine(net, spec, workspace=ws,
                                 log_fn=lambda s: None)
        assert engine.load() == 1
        server = InferenceServer(engine, http=False,
                                 log_fn=lambda s: None)
        server.start()
        try:
            t = server.generate_stream(np.array([3, 1, 4], np.int32))
            next(t.tokens(timeout=30.0))
            mgr.save(2, p2, {"t": np.zeros(())},
                     health={"verdict": "ok"})
            assert engine.poll_reload() == "reloaded"
            assert engine.params_step == 2
            assert not t.done(), "stream ended before the reload " \
                "landed; mid-stream swap was not exercised"
            out = t.wait(120.0)
            # no tear: the stream finished cleanly on the new params
            # (each step is internally consistent; the result's step
            # is the one serving at retire time)
            assert len(out["tokens"]) == 256
            assert out["finish"] == "length" and out["step"] == 2
            assert all(0 <= tok < VOCAB for tok in out["tokens"])
        finally:
            server.stop()


# -- the head-of-line gate: cb p95 vs static p95 -----------------------------

def test_cb_p95_beats_static_under_mixed_load():
    """23 shorts + 1 long through both paths: the static bucket
    decodes every batch to full max_new_tokens, so shorts queue behind
    longs; cb retires shorts as they finish.  The acceptance gate is
    cb p95 <= 0.5x static p95 (the bench asserts the same over real
    HTTP).  Both engines use a 256-token decode horizon — the regime
    where the static path's pay-for-max pathology is the device time,
    not per-call overhead."""
    net, params = _net_and_params()
    st_spec = ServeSpec(buckets=((2, SEQ),), max_new_tokens=256,
                        temperature=0.0, batch_window_s=0.005,
                        request_timeout_s=60.0)
    cb_spec = ServeSpec(buckets=((2, SEQ),), max_new_tokens=256,
                        temperature=0.0, request_timeout_s=60.0,
                        cb="on", cb_slots=8, cb_block_len=4)
    st_engine = InferenceEngine(net, st_spec, params=params,
                                log_fn=lambda s: None)
    st_server = InferenceServer(st_engine, http=False,
                                log_fn=lambda s: None)
    st_server.start()
    cb_engine = InferenceEngine(net, cb_spec, params=params,
                                log_fn=lambda s: None)
    cb_server = InferenceServer(cb_engine, http=False,
                                log_fn=lambda s: None)
    cb_server.start()
    try:
        rng = np.random.default_rng(7)
        prompts = [rng.integers(1, VOCAB, 3).astype(np.int32)
                   for _ in range(24)]
        max_news = [2] * 23 + [256]   # p95 rank 22 lands on a short

        def run(server):
            lats = [None] * len(prompts)

            def client(i):
                t0 = time.monotonic()
                out = server.generate(prompts[i],
                                      max_new=max_news[i])
                lats[i] = time.monotonic() - t0
                assert len(out["tokens"]) == max_news[i]

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(len(prompts))]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert all(v is not None for v in lats)
            return float(np.sort(lats)[int(0.95 * len(lats))])

        static_p95 = run(st_server)
        cb_p95 = run(cb_server)
        assert cb_p95 <= 0.5 * static_p95, \
            (f"continuous batching did not beat the static path: "
             f"cb p95 {cb_p95 * 1e3:.1f}ms vs static p95 "
             f"{static_p95 * 1e3:.1f}ms")
    finally:
        st_server.stop()
        cb_server.stop()
