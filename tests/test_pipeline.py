"""Config-wired pipeline parallelism (locationid → "pipe" axis).

Reference: model.proto:128 locationid; worker.cc:139-155,240-302 moves
activations between locations via bridge layers.  Here a config-built
transformer with locationid stage marks must train identically to the
same net unpipelined (VERDICT r1 item 3)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from singa_tpu.core.trainer import Trainer
from singa_tpu.models.transformer import (synthetic_token_batches,
                                          transformer_lm)
from singa_tpu.parallel.mesh import make_mesh
from singa_tpu.parallel.pipeline_net import (PipelineError, PipelineNet,
                                             stage_assignment)
from singa_tpu.core.net import build_net

CFG = dict(vocab_size=64, num_layers=4, embed_dim=32, num_heads=2,
           head_dim=16, ffn_hidden=64, seq_len=32, batchsize=16,
           train_steps=10)
SHAPES = {"data": {"input": (32,), "target": (32,)}}


def _batch():
    return next(synthetic_token_batches(16, 32, 64, seed=5))


def test_stage_assignment_from_locationid():
    cfg = transformer_lm(pipeline_stages=4, **CFG)
    net = build_net(cfg, "kTrain", SHAPES)
    pre, stages, post = stage_assignment(net)
    assert "embed" in pre and "data" in pre
    assert len(stages) == 4
    assert all(len(s) == 6 for s in stages)  # ln,attn,res,ln,ffn,res
    assert post[-1] == "loss" and "ln_f" in post


def test_pipeline_net_matches_unpipelined():
    """One full train step (fwd+bwd+update) through the locationid
    pipeline over pipe=4 equals the unpipelined net, params and loss."""
    mesh = make_mesh(jax.devices(), data=2, pipe=4, model=1)
    cfg_p = transformer_lm(pipeline_stages=4, **CFG)
    cfg_r = transformer_lm(**CFG)
    batch = _batch()

    tr_p = Trainer(cfg_p, SHAPES, log_fn=lambda s: None, donate=False,
                   mesh=mesh)
    assert tr_p._pipeline_nets, "pipeline path not selected"
    tr_r = Trainer(cfg_r, SHAPES, log_fn=lambda s: None, donate=False)

    params, opt = tr_r.init(seed=0)
    rng = jax.random.PRNGKey(2)
    p1, o1, m1 = tr_p.train_step(dict(params), {k: dict(v) for k, v in
                                                opt.items()}, batch, 0, rng)
    p2, o2, m2 = tr_r.train_step(params, opt, batch, 0, rng)
    assert np.allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    for k in p2:
        np.testing.assert_allclose(np.asarray(p1[k]), np.asarray(p2[k]),
                                   rtol=2e-4, atol=2e-5, err_msg=k)


def test_pipeline_eval_matches_and_flat_mesh_inert():
    mesh = make_mesh(jax.devices(), data=2, pipe=4, model=1)
    cfg_p = transformer_lm(pipeline_stages=4, **CFG)
    # eval nets are built only when the test cadence is configured
    # (worker.cc:16-27 semantics — see Trainer._maybe_net)
    cfg_p.test_steps = 1
    cfg_p.test_frequency = 100
    batch = _batch()
    tr_p = Trainer(cfg_p, SHAPES, log_fn=lambda s: None, donate=False,
                   mesh=mesh)
    # locationid marks are inert without a pipe axis (reference: a
    # location-annotated net still runs on one worker)
    tr_flat = Trainer(cfg_p, SHAPES, log_fn=lambda s: None, donate=False)
    assert not tr_flat._pipeline_nets
    params, _ = tr_flat.init(seed=1)
    m1 = tr_p.test_step(params, batch)
    m2 = tr_flat.test_step(params, batch)
    assert np.allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)


def test_pipeline_validation_fails_loud():
    cfg = transformer_lm(pipeline_stages=2, **CFG)
    # corrupt: give a mid-region layer locationid 0
    for l in cfg.neuralnet.layer:
        if l.name == "ffn1":
            l.locationid = 0
    net = build_net(cfg, "kTrain", SHAPES)
    with pytest.raises(PipelineError, match="locationid 0"):
        PipelineNet(net, 4)


def test_pipeline_microbatch_divisibility():
    cfg = transformer_lm(pipeline_stages=4, **CFG)
    net = build_net(cfg, "kTrain", SHAPES)
    with pytest.raises(PipelineError, match="divisible"):
        mesh = make_mesh(jax.devices(), data=1, pipe=4, model=1, seq=2)
        pn = PipelineNet(net, 3)   # 16 % 3 != 0
        pn.apply(net.init_params(jax.random.PRNGKey(0)), _batch(),
                 mesh=mesh)


def test_dropout_inside_pipeline_stage():
    """VERDICT r2 item 7a: rng-bearing layers in stages.  Dropout
    inside each locationid stage trains without error, draws
    independent masks per (stage, microbatch) — deterministic under a
    fixed rng, different under another — and is inert at eval, where
    the pipelined net must match the unpipelined one exactly."""
    mesh = make_mesh(jax.devices(), data=2, pipe=4, model=1)
    cfg_p = transformer_lm(pipeline_stages=4, dropout=0.3, **CFG)
    cfg_r = transformer_lm(dropout=0.3, **CFG)
    batch = _batch()

    tr_p = Trainer(cfg_p, SHAPES, log_fn=lambda s: None, donate=False,
                   mesh=mesh)
    assert tr_p._pipeline_nets, "pipeline path not selected"
    tr_r = Trainer(cfg_r, SHAPES, log_fn=lambda s: None, donate=False)

    params, opt = tr_r.init(seed=0)
    r1, r2 = jax.random.PRNGKey(3), jax.random.PRNGKey(4)
    _, _, m1 = tr_p.train_step(dict(params),
                               {k: dict(v) for k, v in opt.items()},
                               batch, 0, r1)
    _, _, m1b = tr_p.train_step(dict(params),
                                {k: dict(v) for k, v in opt.items()},
                                batch, 0, r1)
    _, _, m2 = tr_p.train_step(dict(params),
                               {k: dict(v) for k, v in opt.items()},
                               batch, 0, r2)
    l1, l1b, l2 = (float(m1["loss"]), float(m1b["loss"]),
                   float(m2["loss"]))
    assert np.isfinite(l1)
    assert l1 == l1b                      # same rng → same masks
    assert abs(l1 - l2) > 1e-6            # different rng → different

    # eval: dropout inert, pipeline == unpipelined
    lp, _, _ = tr_p._net_apply(tr_p.train_net)(
        params, batch, train=False, mesh=tr_p.mesh,
        compute_dtype=tr_p.compute_dtype)
    lr, _, _ = tr_r.train_net.apply(
        params, batch, train=False,
        compute_dtype=tr_r.compute_dtype)
    np.testing.assert_allclose(float(lp), float(lr), rtol=1e-5)
