"""Config-wired pipeline parallelism (locationid → "pipe" axis).

Reference: model.proto:128 locationid; worker.cc:139-155,240-302 moves
activations between locations via bridge layers.  Here a config-built
transformer with locationid stage marks must train identically to the
same net unpipelined (VERDICT r1 item 3)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from singa_tpu.core.trainer import Trainer
from singa_tpu.models.transformer import (synthetic_token_batches,
                                          transformer_lm)
from singa_tpu.parallel.mesh import make_mesh
from singa_tpu.parallel.pipeline_net import (PipelineError, PipelineNet,
                                             stage_assignment)
from singa_tpu.core.net import build_net

CFG = dict(vocab_size=64, num_layers=4, embed_dim=32, num_heads=2,
           head_dim=16, ffn_hidden=64, seq_len=32, batchsize=16,
           train_steps=10)
SHAPES = {"data": {"input": (32,), "target": (32,)}}


def _batch():
    return next(synthetic_token_batches(16, 32, 64, seed=5))


def test_stage_assignment_from_locationid():
    cfg = transformer_lm(pipeline_stages=4, **CFG)
    net = build_net(cfg, "kTrain", SHAPES)
    pre, stages, post = stage_assignment(net)
    assert "embed" in pre and "data" in pre
    assert len(stages) == 4
    assert all(len(s) == 6 for s in stages)  # ln,attn,res,ln,ffn,res
    assert post[-1] == "loss" and "ln_f" in post


def test_pipeline_net_matches_unpipelined():
    """One full train step (fwd+bwd+update) through the locationid
    pipeline over pipe=4 equals the unpipelined net, params and loss."""
    mesh = make_mesh(jax.devices(), data=2, pipe=4, model=1)
    cfg_p = transformer_lm(pipeline_stages=4, **CFG)
    cfg_r = transformer_lm(**CFG)
    batch = _batch()

    tr_p = Trainer(cfg_p, SHAPES, log_fn=lambda s: None, donate=False,
                   mesh=mesh)
    assert tr_p._pipeline_nets, "pipeline path not selected"
    tr_r = Trainer(cfg_r, SHAPES, log_fn=lambda s: None, donate=False)

    params, opt = tr_r.init(seed=0)
    rng = jax.random.PRNGKey(2)
    p1, o1, m1 = tr_p.train_step(dict(params), {k: dict(v) for k, v in
                                                opt.items()}, batch, 0, rng)
    p2, o2, m2 = tr_r.train_step(params, opt, batch, 0, rng)
    assert np.allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    for k in p2:
        np.testing.assert_allclose(np.asarray(p1[k]), np.asarray(p2[k]),
                                   rtol=2e-4, atol=2e-5, err_msg=k)


def test_pipeline_eval_matches_and_flat_mesh_inert():
    mesh = make_mesh(jax.devices(), data=2, pipe=4, model=1)
    cfg_p = transformer_lm(pipeline_stages=4, **CFG)
    # eval nets are built only when the test cadence is configured
    # (worker.cc:16-27 semantics — see Trainer._maybe_net)
    cfg_p.test_steps = 1
    cfg_p.test_frequency = 100
    batch = _batch()
    tr_p = Trainer(cfg_p, SHAPES, log_fn=lambda s: None, donate=False,
                   mesh=mesh)
    # locationid marks are inert without a pipe axis (reference: a
    # location-annotated net still runs on one worker)
    tr_flat = Trainer(cfg_p, SHAPES, log_fn=lambda s: None, donate=False)
    assert not tr_flat._pipeline_nets
    params, _ = tr_flat.init(seed=1)
    m1 = tr_p.test_step(params, batch)
    m2 = tr_flat.test_step(params, batch)
    assert np.allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)


def test_pipeline_validation_fails_loud():
    cfg = transformer_lm(pipeline_stages=2, **CFG)
    # corrupt: give a mid-region layer locationid 0
    for l in cfg.neuralnet.layer:
        if l.name == "ffn1":
            l.locationid = 0
    net = build_net(cfg, "kTrain", SHAPES)
    with pytest.raises(PipelineError, match="locationid 0"):
        PipelineNet(net, 4)


def test_pipeline_microbatch_divisibility():
    cfg = transformer_lm(pipeline_stages=4, **CFG)
    net = build_net(cfg, "kTrain", SHAPES)
    with pytest.raises(PipelineError, match="divisible"):
        mesh = make_mesh(jax.devices(), data=1, pipe=4, model=1, seq=2)
        pn = PipelineNet(net, 3)   # 16 % 3 != 0
        pn.apply(net.init_params(jax.random.PRNGKey(0)), _batch(),
                 mesh=mesh)


def test_dropout_inside_pipeline_stage():
    """VERDICT r2 item 7a: rng-bearing layers in stages.  Dropout
    inside each locationid stage trains without error, draws
    independent masks per (stage, microbatch) — deterministic under a
    fixed rng, different under another — and is inert at eval, where
    the pipelined net must match the unpipelined one exactly."""
    mesh = make_mesh(jax.devices(), data=2, pipe=4, model=1)
    cfg_p = transformer_lm(pipeline_stages=4, dropout=0.3, **CFG)
    cfg_r = transformer_lm(dropout=0.3, **CFG)
    batch = _batch()

    tr_p = Trainer(cfg_p, SHAPES, log_fn=lambda s: None, donate=False,
                   mesh=mesh)
    assert tr_p._pipeline_nets, "pipeline path not selected"
    tr_r = Trainer(cfg_r, SHAPES, log_fn=lambda s: None, donate=False)

    params, opt = tr_r.init(seed=0)
    r1, r2 = jax.random.PRNGKey(3), jax.random.PRNGKey(4)
    _, _, m1 = tr_p.train_step(dict(params),
                               {k: dict(v) for k, v in opt.items()},
                               batch, 0, r1)
    _, _, m1b = tr_p.train_step(dict(params),
                                {k: dict(v) for k, v in opt.items()},
                                batch, 0, r1)
    _, _, m2 = tr_p.train_step(dict(params),
                               {k: dict(v) for k, v in opt.items()},
                               batch, 0, r2)
    l1, l1b, l2 = (float(m1["loss"]), float(m1b["loss"]),
                   float(m2["loss"]))
    assert np.isfinite(l1)
    assert l1 == l1b                      # same rng → same masks
    assert abs(l1 - l2) > 1e-6            # different rng → different

    # eval: dropout inert, pipeline == unpipelined
    lp, _, _ = tr_p._net_apply(tr_p.train_net)(
        params, batch, train=False, mesh=tr_p.mesh,
        compute_dtype=tr_p.compute_dtype)
    lr, _, _ = tr_r.train_net.apply(
        params, batch, train=False,
        compute_dtype=tr_r.compute_dtype)
    np.testing.assert_allclose(float(lp), float(lr), rtol=1e-5)


def _lenet_staged_cfg(staged=True):
    """A conv net whose locationid marks cut it into structurally
    DIFFERENT stages — the reference's actual bridge use case
    (neuralnet.cc:198-323): stage 1 = conv+pool, stage 2 = fc+relu."""
    from singa_tpu.config.schema import model_config_from_dict
    mark = (lambda s: {"locationid": s}) if staged else (lambda s: {})
    layers = [
        {"name": "data", "type": "kShardData",
         "data_param": {"batchsize": 16}},
        {"name": "mnist", "type": "kMnistImage", "srclayers": "data"},
        {"name": "label", "type": "kLabel", "srclayers": "data"},
        {"name": "conv1", "type": "kConvolution", "srclayers": "mnist",
         "convolution_param": {"num_filters": 8, "kernel": 5},
         "param": [{"name": "cw"}, {"name": "cb"}], **mark(1)},
        {"name": "pool1", "type": "kPooling", "srclayers": "conv1",
         "pooling_param": {"pool": "MAX", "kernel": 2, "stride": 2},
         **mark(1)},
        {"name": "ip1", "type": "kInnerProduct", "srclayers": "pool1",
         "inner_product_param": {"num_output": 32},
         "param": [{"name": "w1"}, {"name": "b1"}], **mark(2)},
        {"name": "relu1", "type": "kReLU", "srclayers": "ip1",
         **mark(2)},
        {"name": "ip2", "type": "kInnerProduct", "srclayers": "relu1",
         "inner_product_param": {"num_output": 10},
         "param": [{"name": "w2"}, {"name": "b2"}]},
        {"name": "loss", "type": "kSoftmaxLoss",
         "srclayers": ["ip2", "label"]},
    ]
    return model_config_from_dict({
        "name": "lenet-staged", "train_steps": 4,
        "updater": {"type": "kSGD", "base_learning_rate": 0.05,
                    "learning_rate_change_method": "kFixed"},
        "neuralnet": {"layer": layers}})


def test_hetero_pipeline_conv_net_matches_unpipelined():
    """VERDICT r2 missing 5: a conv net with heterogeneous locationid
    stages pipelines (HeteroPipelineNet) and one full train step
    matches the unpipelined net."""
    from singa_tpu.parallel.pipeline_net import HeteroPipelineNet

    mesh = make_mesh(jax.devices()[:4], data=2, pipe=2)
    shapes = {"data": {"pixel": (28, 28), "label": ()}}
    rng = np.random.default_rng(7)
    batch = {"data": {
        "pixel": jnp.asarray(rng.integers(0, 256, (16, 28, 28)),
                             jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, (16,)))}}

    tr_p = Trainer(_lenet_staged_cfg(True), shapes,
                   log_fn=lambda s: None, donate=False, mesh=mesh)
    pnet = tr_p._pipeline_nets.get(id(tr_p.train_net))
    assert isinstance(pnet, HeteroPipelineNet), type(pnet)
    assert pnet.n_stages == 2
    tr_r = Trainer(_lenet_staged_cfg(False), shapes,
                   log_fn=lambda s: None, donate=False)

    params, opt = tr_r.init(seed=0)
    key = jax.random.PRNGKey(5)
    p1, _, m1 = tr_p.train_step(dict(params),
                                {k: dict(v) for k, v in opt.items()},
                                batch, 0, key)
    p2, _, m2 = tr_r.train_step(params, opt, batch, 0, key)
    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]),
                               rtol=1e-5)
    for k in p2:
        np.testing.assert_allclose(np.asarray(p1[k]), np.asarray(p2[k]),
                                   rtol=1e-4, atol=1e-6)


def test_hetero_pipeline_three_stages_with_dropout():
    """3 heterogeneous stages incl. an rng-bearing (dropout) stage."""
    from singa_tpu.config.schema import model_config_from_dict
    from singa_tpu.parallel.pipeline_net import HeteroPipelineNet

    layers = [
        {"name": "data", "type": "kShardData",
         "data_param": {"batchsize": 12}},
        {"name": "mnist", "type": "kMnistImage", "srclayers": "data"},
        {"name": "label", "type": "kLabel", "srclayers": "data"},
        {"name": "ip1", "type": "kInnerProduct", "srclayers": "mnist",
         "inner_product_param": {"num_output": 24},
         "param": [{"name": "w1", "init_method": "kUniformSqrtFanIn"},
                   {"name": "b1"}], "locationid": 1},
        {"name": "tanh1", "type": "kTanh", "srclayers": "ip1",
         "locationid": 2},
        {"name": "drop1", "type": "kDropout", "srclayers": "tanh1",
         "dropout_param": {"dropout_ratio": 0.4}, "locationid": 2},
        {"name": "ip2", "type": "kInnerProduct", "srclayers": "drop1",
         "inner_product_param": {"num_output": 10},
         "param": [{"name": "w2", "init_method": "kUniformSqrtFanIn"},
                   {"name": "b2"}], "locationid": 3},
        {"name": "loss", "type": "kSoftmaxLoss",
         "srclayers": ["ip2", "label"]},
    ]
    cfg = model_config_from_dict({
        "name": "hetero3", "train_steps": 2,
        "updater": {"type": "kSGD", "base_learning_rate": 0.05,
                    "learning_rate_change_method": "kFixed"},
        "neuralnet": {"layer": layers}})
    mesh = make_mesh(jax.devices()[:3], pipe=3)
    shapes = {"data": {"pixel": (28, 28), "label": ()}}
    tr = Trainer(cfg, shapes, log_fn=lambda s: None, donate=False,
                 mesh=mesh)
    pnet = tr._pipeline_nets.get(id(tr.train_net))
    assert isinstance(pnet, HeteroPipelineNet) and pnet.n_stages == 3
    params, opt = tr.init(seed=0)
    rng = np.random.default_rng(8)
    batch = {"data": {
        "pixel": jnp.asarray(rng.integers(0, 256, (12, 28, 28)),
                             jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, (12,)))}}
    k1, k2 = jax.random.PRNGKey(1), jax.random.PRNGKey(2)
    _, _, ma = tr.train_step(dict(params),
                             {k: dict(v) for k, v in opt.items()},
                             batch, 0, k1)
    _, _, mb_ = tr.train_step(dict(params),
                              {k: dict(v) for k, v in opt.items()},
                              batch, 0, k2)
    assert np.isfinite(float(ma["loss"]))
    assert float(ma["loss"]) != float(mb_["loss"])  # dropout keyed


def test_circular_schedule_matches_sequential():
    """Interleaved/circular schedule (virtual=2): 8 virtual stages on a
    4-wide pipe axis must reproduce the sequential composition."""
    import numpy as np
    from singa_tpu.parallel import make_mesh, pipeline_apply, \
        stack_stage_params

    rng = np.random.default_rng(3)
    P, v, d = 4, 2, 16
    mesh = make_mesh(pipe=P, data=2)
    per_stage = [{"w": jnp.asarray(
        rng.standard_normal((d, d)).astype(np.float32)) * 0.3}
        for _ in range(P * v)]
    stacked = stack_stage_params(per_stage)
    x = jnp.asarray(rng.standard_normal((8, 4, d)).astype(np.float32))

    def stage_fn(p, h):
        return jax.nn.relu(h @ p["w"])

    out = pipeline_apply(mesh, stage_fn, stacked, x, virtual=v)
    ref = x
    for p in per_stage:
        ref = jax.vmap(lambda h, p=p: stage_fn(p, h))(ref)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)
    # gradients flow through the circular schedule's reverse ring
    g1 = jax.grad(lambda s: pipeline_apply(
        mesh, stage_fn, s, x, virtual=v).sum())(stacked)
    g2 = jax.grad(lambda s: _seq_ref(stage_fn, s, x).sum())(stacked)
    np.testing.assert_allclose(np.asarray(g1["w"]), np.asarray(g2["w"]),
                               rtol=1e-4, atol=1e-5)


def _seq_ref(stage_fn, stacked, x):
    n = jax.tree_util.tree_leaves(stacked)[0].shape[0]
    h = x
    for s in range(n):
        p = jax.tree_util.tree_map(lambda a, s=s: a[s], stacked)
        h = jax.vmap(lambda mb, p=p: stage_fn(p, mb))(h)
    return h


def test_circular_rejects_indivisible_micro():
    import numpy as np
    from singa_tpu.parallel import make_mesh, pipeline_apply, \
        stack_stage_params
    mesh = make_mesh(pipe=4, data=2)
    stacked = stack_stage_params([{"w": jnp.eye(4)} for _ in range(8)])
    x = jnp.zeros((6, 2, 4))      # 6 % 4 != 0
    with pytest.raises(ValueError, match="n_micro"):
        pipeline_apply(mesh, lambda p, h: h, stacked, x, virtual=2)


def test_config_interleaved_pipeline_trains_and_matches():
    """8 locationid stages on a pipe=4 mesh select the circular
    schedule through the Trainer, with numerics matching the
    unpipelined net."""
    import numpy as np
    from singa_tpu.core.trainer import Trainer
    from singa_tpu.models.transformer import (synthetic_token_batches,
                                              transformer_lm)
    from singa_tpu.parallel import make_mesh
    from singa_tpu.parallel.pipeline_net import PipelineNet

    mesh = make_mesh(pipe=4, data=2)
    cfg = transformer_lm(vocab_size=64, num_layers=8, embed_dim=32,
                         num_heads=2, head_dim=16, seq_len=128,
                         batchsize=8, pipeline_stages=8)
    shapes = {"data": {"input": (128,), "target": (128,)}}
    tr = Trainer(cfg, shapes, donate=False, mesh=mesh)
    pnet = tr._pipeline_nets.get(id(tr.train_net))
    assert isinstance(pnet, PipelineNet)
    assert pnet.n_stages == 8 and mesh.shape["pipe"] == 4
    p, o = tr.init(0)
    batch = next(synthetic_token_batches(8, 128, 64))
    p2, o2, m = tr.train_step(p, o, batch, 0, jax.random.PRNGKey(0))
    tr0 = Trainer(transformer_lm(vocab_size=64, num_layers=8,
                                 embed_dim=32, num_heads=2, head_dim=16,
                                 seq_len=128, batchsize=8),
                  shapes, donate=False)
    rp, ro, rm = tr0.train_step(p, o, batch, 0, jax.random.PRNGKey(0))
    assert float(m["loss"]) == pytest.approx(float(rm["loss"]), rel=1e-4)
    np.testing.assert_allclose(np.asarray(p2["attn0/wq"]),
                               np.asarray(rp["attn0/wq"]),
                               rtol=2e-3, atol=1e-5)
