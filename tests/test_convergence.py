"""North-star gate 1 as a test: conv.conf trains to >=99% held-out
accuracy (marked slow — a real multi-hundred-step training run).

Mirrors tools/convergence_run.py on the CPU test platform with a
smaller test split so the suite stays tractable; the committed
CONVERGENCE.json records the on-chip run.
"""

import os

import numpy as np
import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_conv_conf_reaches_99_percent(tmp_path):
    from singa_tpu.tools.convergence_run import run

    final = run(os.path.join(REPO, "examples/mnist/conv.conf"),
                target=0.99, max_steps=2000,
                out=str(tmp_path / "conv.json"), noise_std=96.0,
                chunk=100, test_batches=2, log=lambda s: None)
    assert final["reached"], final
    assert final["mnist_test_accuracy"] >= 0.99


def test_rgb_conv_net_learns_with_per_image_augmentation():
    """A conv net on 3-channel RGB data — through the kRGBImage parser
    with per-image mirror ACTIVE — reaches high held-out accuracy in
    ~100 steps.  Pins the full conv/pool/augmentation training path on
    color input (the caffe AlexNet recipes themselves are on a
    50k-step timescale by design: their tiny gaussian inits and
    bias_value=1.0 drown the data signal early — measured, see
    BASELINE.md — so this sane-init net is the e2e learnability
    check)."""
    import jax

    from singa_tpu.config.schema import model_config_from_dict
    from singa_tpu.core.trainer import Trainer
    from singa_tpu.data.synthetic import synthetic_image_batches

    def conv(name, src, nf):
        return {"name": name, "type": "kConvolution", "srclayers": src,
                "convolution_param": {"num_filters": nf, "kernel": 5,
                                      "pad": 2},
                "param": [{"name": name + "w",
                           "init_method": "kUniformSqrtFanIn"},
                          {"name": name + "b"}]}

    def pool(name, src):
        return {"name": name, "type": "kPooling", "srclayers": src,
                "pooling_param": {"pool": "MAX", "kernel": 2,
                                  "stride": 2}}

    layers = [
        {"name": "data", "type": "kShardData",
         "data_param": {"batchsize": 64}},
        {"name": "rgb", "type": "kRGBImage", "srclayers": "data",
         "rgbimage_param": {"scale": 0.00392, "mirror": True}},
        {"name": "label", "type": "kLabel", "srclayers": "data"},
        conv("conv1", "rgb", 16), pool("pool1", "conv1"),
        {"name": "relu1", "type": "kReLU", "srclayers": "pool1"},
        conv("conv2", "relu1", 32), pool("pool2", "conv2"),
        {"name": "relu2", "type": "kReLU", "srclayers": "pool2"},
        {"name": "ip1", "type": "kInnerProduct", "srclayers": "relu2",
         "inner_product_param": {"num_output": 64},
         "param": [{"name": "w1", "init_method": "kUniformSqrtFanIn"},
                   {"name": "b1"}]},
        {"name": "relu3", "type": "kReLU", "srclayers": "ip1"},
        {"name": "ip2", "type": "kInnerProduct", "srclayers": "relu3",
         "inner_product_param": {"num_output": 10},
         "param": [{"name": "w2", "init_method": "kUniformSqrtFanIn"},
                   {"name": "b2"}]},
        {"name": "loss", "type": "kSoftmaxLoss",
         "srclayers": ["ip2", "label"]},
    ]
    cfg = model_config_from_dict({
        "name": "rgb-conv", "train_steps": 120,
        "updater": {"type": "kSGD", "base_learning_rate": 0.01,
                    "momentum": 0.9, "weight_decay": 0.0005,
                    "learning_rate_change_method": "kFixed"},
        "neuralnet": {"layer": layers}})
    tr = Trainer(cfg, {"data": {"pixel": (3, 32, 32), "label": ()}},
                 log_fn=lambda s: None)
    params, opt = tr.init(seed=0)
    it = synthetic_image_batches(64, image_shape=(3, 32, 32), seed=21,
                                 stream_seed=77, noise_std=48.0)
    test_b = next(synthetic_image_batches(
        512, image_shape=(3, 32, 32), seed=21, stream_seed=991,
        noise_std=48.0))
    for step in range(120):
        params, opt, _ = tr.train_step(params, opt, next(it), step,
                                       jax.random.PRNGKey(step))
    _, mm, _ = tr.train_net.apply(params, test_b, train=False)
    assert float(mm["precision"]) > 0.9, float(mm["precision"])
