"""North-star gate 1 as a test: conv.conf trains to >=99% held-out
accuracy (marked slow — a real multi-hundred-step training run).

Mirrors tools/convergence_run.py on the CPU test platform with a
smaller test split so the suite stays tractable; the committed
CONVERGENCE.json records the on-chip run.
"""

import os

import numpy as np
import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_conv_conf_reaches_99_percent(tmp_path):
    from singa_tpu.tools.convergence_run import run

    final = run(os.path.join(REPO, "examples/mnist/conv.conf"),
                target=0.99, max_steps=2000,
                out=str(tmp_path / "conv.json"), noise_std=96.0,
                chunk=100, test_batches=2, log=lambda s: None)
    assert final["reached"], final
    assert final["mnist_test_accuracy"] >= 0.99
