"""NeuralNet builder tests: reference configs → compiled train steps."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from singa_tpu.config import load_model_config, model_config_from_text
from singa_tpu.core import build_net, Trainer
from singa_tpu.core.graph import Graph, GraphError

MNIST_SHAPES = {"data": {"pixel": (28, 28), "label": ()}}


def _mnist_batch(bs, rng, size=28, nclass=10):
    return {"data": {
        "pixel": jnp.asarray(
            rng.integers(0, 256, (bs, size, size)).astype(np.uint8)),
        "label": jnp.asarray(rng.integers(0, nclass, (bs,))),
    }}


def test_graph_topo_and_cycle():
    g = Graph()
    for n in "abc":
        g.add_node(n)
    g.add_edge("a", "b")
    g.add_edge("b", "c")
    assert g.topo_sort() == ["a", "b", "c"]
    g.add_edge("c", "a")
    with pytest.raises(GraphError):
        g.topo_sort()


def test_build_mlp_from_reference_conf():
    cfg = load_model_config("/root/reference/examples/mnist/mlp.conf")
    net = build_net(cfg, "kTrain", MNIST_SHAPES, batchsize=8)
    # phase filtering: only one data layer remains
    assert [n for n in net.topo if n == "data"] == ["data"]
    # shapes through the stack
    assert net.shapes["mnist"] == (8, 28, 28)
    assert net.shapes["fc1"] == (8, 2500)
    assert net.shapes["fc6"] == (8, 10)
    # 6 fc layers × (weight+bias)
    assert len(net.param_specs) == 12
    assert net.param_specs["fc1/weight"].shape == (784, 2500)

    rng = np.random.default_rng(0)
    params = net.init_params(jax.random.PRNGKey(0))
    loss, metrics, outputs = net.apply(params, _mnist_batch(8, rng))
    assert np.isfinite(float(loss))
    assert 0.0 <= float(metrics["precision"]) <= 1.0
    # uniform(-0.05, 0.05) init → initial loss near log(10)
    assert abs(float(loss) - np.log(10)) < 0.5


def test_build_lenet_from_reference_conf():
    cfg = load_model_config("/root/reference/examples/mnist/conv.conf")
    net = build_net(cfg, "kTrain", MNIST_SHAPES, batchsize=4)
    # NHWC runtime layout (same geometry as the reference's NCHW shapes)
    assert net.shapes["conv1"] == (4, 24, 24, 20)
    assert net.shapes["pool1"] == (4, 12, 12, 20)
    assert net.shapes["conv2"] == (4, 8, 8, 50)
    assert net.shapes["pool2"] == (4, 4, 4, 50)
    assert net.shapes["ip1"] == (4, 500)
    assert net.shapes["ip2"] == (4, 10)
    assert net.param_specs["conv1/weight"].shape == (20, 25)
    assert net.param_specs["conv2/weight"].shape == (50, 20 * 25)

    rng = np.random.default_rng(1)
    params = net.init_params(jax.random.PRNGKey(1))
    loss, metrics, _ = net.apply(params, _mnist_batch(4, rng))
    assert np.isfinite(float(loss))


def test_test_phase_net_shares_params():
    cfg = load_model_config("/root/reference/examples/mnist/mlp.conf")
    train_net = build_net(cfg, "kTrain", MNIST_SHAPES, batchsize=8)
    test_net = build_net(cfg, "kTest", MNIST_SHAPES, batchsize=8)
    # same param specs → same pytree works for both (ShareWeights parity)
    assert set(train_net.param_specs) == set(test_net.param_specs)
    params = train_net.init_params(jax.random.PRNGKey(0))
    rng = np.random.default_rng(2)
    loss, _, _ = test_net.apply(params, _mnist_batch(8, rng), train=False)
    assert np.isfinite(float(loss))


def test_trainer_loss_decreases_on_fixed_batch():
    """End-to-end smoke: jitted train step memorizes one batch."""
    cfg = load_model_config("/root/reference/examples/mnist/conv.conf")
    cfg.train_steps = 30
    cfg.test_frequency = 0
    cfg.display_frequency = 0
    trainer = Trainer(cfg, MNIST_SHAPES)
    params, opt_state = trainer.init(seed=0)
    rng = np.random.default_rng(3)
    batch = _mnist_batch(16, rng)

    losses = []
    for step in range(60):
        params, opt_state, metrics = trainer.train_step(
            params, opt_state, batch, step, jax.random.PRNGKey(step))
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.7, losses[::10]


def test_share_param_aliasing():
    text = """
    neuralnet {
      layer { name: "data" type: "kShardData"
              data_param { batchsize: 4 } }
      layer { name: "img" type: "kMnistImage" srclayers: "data" }
      layer { name: "lab" type: "kLabel" srclayers: "data" }
      layer { name: "fc1" type: "kInnerProduct" srclayers: "img"
              inner_product_param { num_output: 784 }
              param { name: "w" init_method: kUniform low: -0.1 high: 0.1 }
              param { name: "b" init_method: kConstant value: 0 } }
      layer { name: "fc2" type: "kInnerProduct" srclayers: "fc1"
              inner_product_param { num_output: 784 }
              share_param: "fc1/w"
              param { name: "w2" }
              param { name: "b2" init_method: kConstant value: 0 } }
      layer { name: "loss" type: "kSoftmaxLoss"
              srclayers: "fc2" srclayers: "lab" }
    }
    """
    cfg = model_config_from_text(text)
    net = build_net(cfg, "kTrain", MNIST_SHAPES)
    assert "fc2/w2" not in net.param_specs
    assert net.param_aliases == {"fc2/w2": "fc1/w"}
    params = net.init_params(jax.random.PRNGKey(0))
    rng = np.random.default_rng(4)
    loss, _, _ = net.apply(params, _mnist_batch(4, rng))
    assert np.isfinite(float(loss))


def test_connector_layers_concate_slice_split():
    text = """
    neuralnet {
      layer { name: "data" type: "kShardData"
              data_param { batchsize: 6 } }
      layer { name: "img" type: "kMnistImage" srclayers: "data" }
      layer { name: "lab" type: "kLabel" srclayers: "data" }
      layer { name: "split" type: "kSplit" srclayers: "img"
              split_param { num_splits: 2 } }
      layer { name: "fc_a" type: "kInnerProduct" srclayers: "split"
              inner_product_param { num_output: 8 }
              param { name: "weight" init_method: kUniform }
              param { name: "bias" init_method: kConstant value: 0 } }
      layer { name: "fc_b" type: "kInnerProduct" srclayers: "split"
              inner_product_param { num_output: 8 }
              param { name: "weight" init_method: kUniform }
              param { name: "bias" init_method: kConstant value: 0 } }
      layer { name: "cat" type: "kConcate"
              srclayers: "fc_a" srclayers: "fc_b"
              concate_param { concate_dimension: 1 } }
      layer { name: "slice" type: "kSlice" srclayers: "cat"
              slice_param { slice_dimension: 1 slice_num: 2 } }
      layer { name: "out_a" type: "kReLU" srclayers: "slice" }
      layer { name: "out_b" type: "kReLU" srclayers: "slice" }
      layer { name: "cat2" type: "kConcate"
              srclayers: "out_a" srclayers: "out_b"
              concate_param { concate_dimension: 1 } }
      layer { name: "loss" type: "kSoftmaxLoss"
              srclayers: "cat2" srclayers: "lab" }
    }
    """
    cfg = model_config_from_text(text)
    net = build_net(cfg, "kTrain", MNIST_SHAPES)
    assert net.shapes["cat"] == (6, 16)
    assert net.shapes["slice"] == ((6, 8), (6, 8))
    assert net.shapes["cat2"] == (6, 16)
    params = net.init_params(jax.random.PRNGKey(0))
    rng = np.random.default_rng(5)
    loss, _, outputs = net.apply(params, _mnist_batch(6, rng))
    np.testing.assert_allclose(
        np.asarray(outputs["cat2"]),
        np.maximum(np.asarray(outputs["cat"]), 0), rtol=1e-6)


def test_uneven_slice_remainder_to_last():
    """neuralnet.cc:160-162: remainder goes to the last partition."""
    text = """
    neuralnet {
      layer { name: "data" type: "kShardData" data_param { batchsize: 2 } }
      layer { name: "img" type: "kMnistImage" srclayers: "data" }
      layer { name: "lab" type: "kLabel" srclayers: "data" }
      layer { name: "fc" type: "kInnerProduct" srclayers: "img"
              inner_product_param { num_output: 10 }
              param { name: "weight" } param { name: "bias" } }
      layer { name: "slice" type: "kSlice" srclayers: "fc"
              slice_param { slice_dimension: 1 slice_num: 3 } }
      layer { name: "a" type: "kReLU" srclayers: "slice" }
      layer { name: "b" type: "kReLU" srclayers: "slice" }
      layer { name: "c" type: "kReLU" srclayers: "slice" }
      layer { name: "cat" type: "kConcate"
              srclayers: "a" srclayers: "b" srclayers: "c"
              concate_param { concate_dimension: 1 } }
      layer { name: "loss" type: "kSoftmaxLoss"
              srclayers: "cat" srclayers: "lab" }
    }
    """
    cfg = model_config_from_text(text)
    net = build_net(cfg, "kTrain", MNIST_SHAPES)
    assert net.shapes["slice"] == ((2, 3), (2, 3), (2, 4))


def test_fused_relu_lrn_net_matches_unfused():
    """A conv→relu→lrn net produces identical loss and grads whether
    the relu is fused into the LRN custom_vjp (fuse_from, the default
    the builder picks) or the layers run separately."""
    import numpy as np

    from singa_tpu.models.vision import alexnet_cifar10
    from singa_tpu.core.net import build_net

    cfg = alexnet_cifar10(batchsize=4)
    shapes = {"data": {"pixel": (3, 8, 8), "label": ()}}
    rng = np.random.default_rng(3)
    batch = {"data": {
        "pixel": jnp.asarray(rng.standard_normal((4, 3, 8, 8)),
                             jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, (4,)))}}

    fused = build_net(cfg, "kTrain", shapes)
    assert any(getattr(l, "fuse_from", "") for l in fused.layers.values())
    unfused = build_net(cfg, "kTrain", shapes)
    for l in unfused.layers.values():
        if hasattr(l, "fuse_from"):
            l.fuse_from = ""
    params = fused.init_params(jax.random.PRNGKey(0))

    def loss_of(net):
        # rng: the kRGBImage per-image mirror (train-time) draws it;
        # same key both nets → identical flips → comparable grads
        return jax.value_and_grad(
            lambda p: net.apply(p, batch, rng=jax.random.PRNGKey(1),
                                train=True)[0])(params)

    l1, g1 = loss_of(fused)
    l2, g2 = loss_of(unfused)
    assert np.allclose(float(l1), float(l2), rtol=1e-5)
    for k in g1:
        np.testing.assert_allclose(np.asarray(g1[k]), np.asarray(g2[k]),
                                   rtol=1e-4, atol=1e-5)


def test_debug_info_and_json():
    cfg = load_model_config("/root/reference/examples/mnist/conv.conf")
    net = build_net(cfg, "kTrain", MNIST_SHAPES, batchsize=2)
    params = net.init_params(jax.random.PRNGKey(0))
    rng = np.random.default_rng(6)
    _, _, outputs = net.apply(params, _mnist_batch(2, rng))
    info = net.debug_info(params, outputs)
    assert "conv1" in info and "param" in info
    j = net.to_json()
    assert '"nodes"' in j and '"links"' in j


def test_train_steps_scan_matches_per_step_calls():
    """trainer.train_steps (one lax.scan program) must reproduce n
    individual train_step calls exactly — same params, same metrics."""
    cfg = load_model_config("/root/reference/examples/mnist/conv.conf")
    cfg.display_frequency = 0
    trainer = Trainer(cfg, MNIST_SHAPES, donate=False)
    params, opt_state = trainer.init(seed=0)
    rng = np.random.default_rng(7)
    key = jax.random.PRNGKey(9)
    n = 4

    # reused fixed batch
    batch = _mnist_batch(8, rng)
    p_scan, o_scan, metrics = trainer.train_steps(
        params, opt_state, batch, 0, key, n)
    assert metrics["loss"].shape == (n,)
    p_ref, o_ref = params, opt_state
    for step in range(n):
        p_ref, o_ref, m = trainer.train_step(
            p_ref, o_ref, batch, step, jax.random.fold_in(key, step))
        np.testing.assert_allclose(float(metrics["loss"][step]),
                                   float(m["loss"]), rtol=1e-5)
    for k in p_ref:
        np.testing.assert_allclose(np.asarray(p_scan[k]),
                                   np.asarray(p_ref[k]), atol=1e-5)

    # stacked per-step batches (leading axis n) are scanned over
    batches = [_mnist_batch(8, rng) for _ in range(n)]
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *batches)
    p_scan2, _, metrics2 = trainer.train_steps(
        params, opt_state, stacked, 0, key, n, True)
    p_ref2, o_ref2 = params, opt_state
    for step in range(n):
        p_ref2, o_ref2, m = trainer.train_step(
            p_ref2, o_ref2, batches[step], step,
            jax.random.fold_in(key, step))
        np.testing.assert_allclose(float(metrics2["loss"][step]),
                                   float(m["loss"]), rtol=1e-5)


def test_run_scan_chunk_matches_per_step_run():
    """run(scan_chunk=N) must produce the same params, display logs, and
    test history as the per-step loop, with cadence at the same steps."""
    cfg = load_model_config("/root/reference/examples/mnist/conv.conf")
    cfg.train_steps = 11
    cfg.display_frequency = 3
    cfg.test_frequency = 5
    cfg.test_steps = 2
    rng = np.random.default_rng(11)
    train_batches = [_mnist_batch(8, rng) for _ in range(cfg.train_steps)]
    test_batches = [_mnist_batch(8, rng) for _ in range(cfg.test_steps)]

    def run_with(chunk):
        logs = []
        tr = Trainer(cfg, MNIST_SHAPES, log_fn=logs.append, donate=False)
        p, o = tr.init(seed=0)
        p, o, hist = tr.run(p, o, iter(train_batches),
                            test_iter_factory=lambda: iter(test_batches),
                            seed=0, scan_chunk=chunk)
        return p, hist, logs

    p1, hist1, logs1 = run_with(0)
    p4, hist4, logs4 = run_with(4)
    for k in p1:
        np.testing.assert_allclose(np.asarray(p4[k]), np.asarray(p1[k]),
                                   atol=2e-5)
    assert [h["step"] for h in hist1] == [h["step"] for h in hist4]
    for h1, h4 in zip(hist1, hist4):
        assert abs(h1["loss"] - h4["loss"]) < 1e-4
    # same display steps (log lines starting with "step-N:"); DebugInfo
    # lines are excluded — they print at chunk granularity by design
    # (labeled with the chunk's last step, whose params they reflect)
    steps1 = [l.split(":")[0] for l in logs1
              if l.startswith("step-") and " debug" not in l]
    steps4 = [l.split(":")[0] for l in logs4
              if l.startswith("step-") and " debug" not in l]
    assert steps1 == steps4


def test_preemption_signal_checkpoints_and_resumes(tmp_path):
    """SIGTERM mid-run -> snapshot at the current step + clean stop;
    resume() continues from there (the recovery story the reference
    lacks: a killed worker hung the whole job)."""
    import os
    import signal

    cfg = load_model_config("/root/reference/examples/mnist/conv.conf")
    cfg.train_steps = 50
    cfg.test_frequency = 0
    cfg.display_frequency = 0
    cfg.checkpoint_frequency = 1000   # cadence would never fire
    trainer = Trainer(cfg, MNIST_SHAPES, log_fn=lambda s: None,
                      donate=False)
    params, opt_state = trainer.init(seed=0)
    rng = np.random.default_rng(21)
    batches = [_mnist_batch(8, rng) for _ in range(50)]

    def self_sigterm(step, metrics):
        if step == 4:
            os.kill(os.getpid(), signal.SIGTERM)

    p, o, _ = trainer.run(params, opt_state, iter(batches),
                          hooks=[self_sigterm], workspace=str(tmp_path))
    p2, o2, start = trainer.resume(params, opt_state, str(tmp_path))
    assert start == 5                      # stopped after finishing step 4
    for k in p:
        np.testing.assert_allclose(np.asarray(p2[k]), np.asarray(p[k]))
    # handler restored: SIGTERM must not be swallowed anymore
    assert signal.getsignal(signal.SIGTERM) in (
        signal.SIG_DFL, signal.default_int_handler) or callable(
        signal.getsignal(signal.SIGTERM))


def test_checkpoint_layout_version_mismatch_refuses(tmp_path):
    """A checkpoint written under a different parameter layout version
    (or a pre-versioning one) must refuse to restore instead of loading
    permuted weights (ADVICE r1: the NCHW->NHWC vdim reorder)."""
    import os
    import pytest
    from singa_tpu.utils.checkpoint import (CheckpointManager,
                                            LayoutMismatchError)

    mgr = CheckpointManager(str(tmp_path))
    params = {"w": jnp.ones((2, 2))}
    opt = {"history": {"w": jnp.zeros((2, 2))}}
    mgr.save(3, params, opt)
    restored = mgr.restore(template={"params": params, "opt_state": opt})
    assert restored is not None and restored[2] == 3

    # simulate an old checkpoint: version marker absent
    os.remove(os.path.join(mgr.dir, "LAYOUT_VERSION"))
    with pytest.raises(LayoutMismatchError, match="layout version 1"):
        CheckpointManager(str(tmp_path)).restore(
            template={"params": params, "opt_state": opt})


def test_maybe_net_raises_on_broken_eval_phase():
    """A typo'd srclayer in the test phase must FAIL Trainer
    construction, not silently disable evaluation (round-1 review: the
    old bare `except Exception` in _maybe_net swallowed real config
    errors)."""
    from singa_tpu.core.layers import LayerError
    from singa_tpu.core.trainer import Trainer
    from singa_tpu.models.vision import lenet_mnist

    cfg = lenet_mnist(batchsize=4)
    # an extra kTest-only layer pointing at a layer that doesn't exist
    from singa_tpu.config.schema import model_config_from_dict
    d = {"name": "broken", "train_steps": 1, "test_steps": 5,
         "test_frequency": 1,
         "updater": {"type": "kSGD", "base_learning_rate": 0.01},
         "neuralnet": {"layer": [
             {"name": "data", "type": "kShardData",
              "data_param": {"batchsize": 4}},
             {"name": "mnist", "type": "kMnistImage", "srclayers": "data"},
             {"name": "label", "type": "kLabel", "srclayers": "data"},
             {"name": "ip", "type": "kInnerProduct", "srclayers": "mnist",
              "inner_product_param": {"num_output": 10},
              "param": [{"name": "weight", "init_method": "kUniform",
                         "low": -0.1, "high": 0.1},
                        {"name": "bias", "init_method": "kConstant"}]},
             {"name": "bad", "type": "kReLU", "srclayers": "nope",
              "exclude": ["kTrain", "kValidation"]},
             {"name": "loss", "type": "kSoftmaxLoss",
              "srclayers": ["ip", "label"]},
         ]}}
    with pytest.raises(LayerError, match="nope"):
        Trainer(model_config_from_dict(d),
                {"data": {"pixel": (28, 28), "label": ()}},
                log_fn=lambda s: None)
    # sanity: the clean config (with test cadence on) still builds
    cfg.test_steps = 10
    tr = Trainer(cfg, {"data": {"pixel": (28, 28), "label": ()}},
                 log_fn=lambda s: None)
    assert tr.test_step is not None


def test_maybe_net_none_when_phase_has_no_loss():
    """A phase whose filtered layers lack a loss layer is legitimately
    absent — Trainer builds no eval step and raises nothing."""
    from singa_tpu.core.trainer import Trainer
    from singa_tpu.models.vision import lenet_mnist

    cfg = lenet_mnist(batchsize=4)
    cfg.test_steps = 10
    cfg.validation_steps = 10
    for l in cfg.neuralnet.layer:
        if l.type == "kSoftmaxLoss":
            l.exclude = ["kTest", "kValidation"]
    tr = Trainer(cfg, {"data": {"pixel": (28, 28), "label": ()}},
                 log_fn=lambda s: None)
    assert tr.test_step is None and tr.val_step is None
